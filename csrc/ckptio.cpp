// Native checkpoint chunk writer.
//
// Parity: the reference's checkpoint save path runs in C++ (tensor
// serialization in paddle/fluid/framework + async save executors); here
// the TPU framework's checkpoint layout is many independent .npy chunk
// files, and the Python async saver's disk phase is a serial,
// GIL-bound np.save loop. This library writes a BATCH of (header, data)
// pairs to files from a thread pool — each file is open/pwrite/fsync on
// its own thread, so large sharded checkpoints hit the filesystem at
// device-count parallelism instead of one-file-at-a-time Python.
//
// C ABI (consumed via ctypes from paddle_tpu/distributed/checkpoint.py):
//   ptck_write_batch(n, paths[], headers[], header_lens[],
//                    datas[], data_lens[], nthreads, do_fsync)
//     -> 0 on success, else the number of files that failed to write.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

bool write_all(int fd, const uint8_t* buf, int64_t len) {
  int64_t off = 0;
  while (off < len) {
    ssize_t w = write(fd, buf + off, static_cast<size_t>(len - off));
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    off += w;
  }
  return true;
}

bool write_one(const char* path, const uint8_t* header, int64_t header_len,
               const uint8_t* data, int64_t data_len, bool do_fsync) {
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  bool ok = write_all(fd, header, header_len) &&
            (data_len == 0 || write_all(fd, data, data_len));
  if (ok && do_fsync) ok = fsync(fd) == 0;
  close(fd);
  return ok;
}

}  // namespace

extern "C" {

// Returns the number of failed files (0 = all written).
int ptck_write_batch(int n, const char** paths, const uint8_t** headers,
                     const int64_t* header_lens, const uint8_t** datas,
                     const int64_t* data_lens, int nthreads, int do_fsync) {
  if (n <= 0) return 0;
  std::atomic<int> next{0};
  std::atomic<int> failures{0};
  int nt = nthreads > 0 ? nthreads : 4;
  if (nt > n) nt = n;
  std::vector<std::thread> workers;
  workers.reserve(nt);
  for (int w = 0; w < nt; ++w) {
    workers.emplace_back([&] {
      for (;;) {
        int i = next.fetch_add(1);
        if (i >= n) return;
        if (!write_one(paths[i], headers[i], header_lens[i], datas[i],
                       data_lens[i], do_fsync != 0))
          failures.fetch_add(1);
      }
    });
  }
  for (auto& t : workers) t.join();
  return failures.load();
}

}  // extern "C"
