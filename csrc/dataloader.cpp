// Native data-loading core.
//
// Parity: the reference's C++ DataLoader machinery (multiprocess workers,
// pinned-memory H2D pipeline, python/paddle/io/ + paddle/fluid/operators/
// reader/) — the part of the framework where Python in the per-step loop
// actually costs throughput.
//
// TPU-native shape of the problem: pretraining reads fixed-length token
// sequences from large binary shards. This library mmaps token-bin files
// (uint16 or uint32 tokens), draws deterministic per-epoch shuffled
// sequence indices, and materializes batches into caller-provided int32
// buffers from a background prefetch thread pool, so the Python side only
// does a queue pop + jax.device_put.
//
// C ABI (consumed via ctypes from paddle_tpu/io/native.py):
//   ptdl_open(path, token_bytes, seq_len)            -> handle (>=0) | -errno
//   ptdl_num_seqs(handle)                            -> int64
//   ptdl_start_epoch(handle, seed, batch, drop_last, shuffle, nthreads)
//   ptdl_next_batch(handle, out_int32, out_indices)  -> n_filled (0 = end)
//   ptdl_close(handle)

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <memory>
#include <map>
#include <mutex>
#include <numeric>
#include <random>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Batch {
  std::vector<int32_t> tokens;
  std::vector<int64_t> indices;
  int64_t n = 0;
};

struct Dataset {
  int fd = -1;
  const uint8_t* data = nullptr;
  size_t bytes = 0;
  int token_bytes = 2;
  int64_t seq_len = 0;
  int64_t num_seqs = 0;

  // epoch state
  std::vector<int64_t> order;
  std::atomic<int64_t> next_index{0};
  int64_t batch_size = 1;
  bool drop_last = true;
  int64_t epoch_batches = 0;

  // prefetch machinery. Batches are delivered IN INDEX ORDER: workers
  // complete out of order under load, so a plain FIFO queue makes the
  // epoch's batch sequence scheduling-dependent (breaks same-seed
  // determinism); the ready-map + next_deliver cursor restores it.
  std::vector<std::thread> workers;
  std::map<int64_t, Batch> ready;
  int64_t next_deliver = 0;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  size_t max_queue = 8;
  std::atomic<bool> stopping{false};

  ~Dataset() { shutdown(); }

  void shutdown() {
    {
      // store+notify under mu: a lock-free store can land between a
      // waiter's predicate check and its block, losing the wakeup
      std::lock_guard<std::mutex> g(mu);
      stopping.store(true);
    }
    cv_push.notify_all();
    cv_pop.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
    {
      std::lock_guard<std::mutex> g(mu);
      ready.clear();
    }
    if (data) {
      munmap(const_cast<uint8_t*>(data), bytes);
      data = nullptr;
    }
    if (fd >= 0) {
      close(fd);
      fd = -1;
    }
  }

  inline int32_t token_at(int64_t flat) const {
    if (token_bytes == 2) {
      uint16_t v;
      std::memcpy(&v, data + flat * 2, 2);
      return static_cast<int32_t>(v);
    }
    int32_t v;
    std::memcpy(&v, data + flat * 4, 4);
    return v;
  }

  void fill(Batch& b, int64_t batch_start) {
    int64_t remaining = static_cast<int64_t>(order.size()) - batch_start;
    int64_t n = remaining < batch_size ? remaining : batch_size;
    b.n = n;
    b.tokens.resize(static_cast<size_t>(n) * seq_len);
    b.indices.resize(n);
    for (int64_t i = 0; i < n; ++i) {
      int64_t seq = order[batch_start + i];
      b.indices[i] = seq;
      int64_t base = seq * seq_len;
      int32_t* out = b.tokens.data() + i * seq_len;
      for (int64_t t = 0; t < seq_len; ++t) out[t] = token_at(base + t);
    }
  }

  void worker_loop() {
    while (!stopping.load()) {
      int64_t bi = next_index.fetch_add(1);
      if (bi >= epoch_batches) return;
      Batch b;
      fill(b, bi * batch_size);
      std::unique_lock<std::mutex> lk(mu);
      // bounded lookahead relative to the delivery cursor — the batch
      // the consumer needs next (bi == next_deliver) is never blocked,
      // so this cannot deadlock
      cv_push.wait(lk, [&] {
        return stopping.load() ||
               bi < next_deliver + static_cast<int64_t>(max_queue);
      });
      if (stopping.load()) return;
      ready.emplace(bi, std::move(b));
      cv_pop.notify_all();
    }
  }
};

std::mutex g_mu;
std::vector<std::unique_ptr<Dataset>> g_handles;

Dataset* get(int h) {
  std::lock_guard<std::mutex> g(g_mu);
  if (h < 0 || h >= static_cast<int>(g_handles.size())) return nullptr;
  return g_handles[h].get();
}

}  // namespace

extern "C" {

int ptdl_open(const char* path, int token_bytes, int64_t seq_len) {
  if (token_bytes != 2 && token_bytes != 4) return -1;
  if (seq_len <= 0) return -1;
  auto ds = std::make_unique<Dataset>();
  ds->fd = open(path, O_RDONLY);
  if (ds->fd < 0) return -2;
  struct stat st;
  if (fstat(ds->fd, &st) != 0) return -3;
  ds->bytes = static_cast<size_t>(st.st_size);
  ds->token_bytes = token_bytes;
  ds->seq_len = seq_len;
  ds->num_seqs = static_cast<int64_t>(ds->bytes) / (token_bytes * seq_len);
  if (ds->num_seqs == 0) return -4;
  void* p = mmap(nullptr, ds->bytes, PROT_READ, MAP_PRIVATE, ds->fd, 0);
  if (p == MAP_FAILED) return -5;
  madvise(p, ds->bytes, MADV_WILLNEED);
  ds->data = static_cast<const uint8_t*>(p);
  std::lock_guard<std::mutex> g(g_mu);
  g_handles.push_back(std::move(ds));
  return static_cast<int>(g_handles.size()) - 1;
}

int64_t ptdl_num_seqs(int h) {
  Dataset* ds = get(h);
  return ds ? ds->num_seqs : -1;
}

int ptdl_start_epoch(int h, int64_t seed, int64_t batch_size, int drop_last,
                     int shuffle, int nthreads) {
  Dataset* ds = get(h);
  if (!ds || batch_size <= 0) return -1;
  // stop any previous epoch's workers (store under mu — see shutdown)
  {
    std::lock_guard<std::mutex> g(ds->mu);
    ds->stopping.store(true);
  }
  ds->cv_push.notify_all();
  ds->cv_pop.notify_all();
  for (auto& t : ds->workers)
    if (t.joinable()) t.join();
  ds->workers.clear();
  {
    std::lock_guard<std::mutex> g(ds->mu);
    ds->ready.clear();
    ds->next_deliver = 0;
  }
  ds->stopping.store(false);

  ds->order.resize(ds->num_seqs);
  std::iota(ds->order.begin(), ds->order.end(), 0);
  if (shuffle) {
    std::mt19937_64 rng(static_cast<uint64_t>(seed));
    std::shuffle(ds->order.begin(), ds->order.end(), rng);
  }
  ds->batch_size = batch_size;
  ds->drop_last = drop_last != 0;
  ds->epoch_batches = ds->drop_last
                          ? ds->num_seqs / batch_size
                          : (ds->num_seqs + batch_size - 1) / batch_size;
  ds->next_index.store(0);
  int n = nthreads > 0 ? nthreads : 2;
  for (int i = 0; i < n; ++i)
    ds->workers.emplace_back([ds] { ds->worker_loop(); });
  return 0;
}

// out must hold batch_size*seq_len int32; out_indices batch_size int64.
// returns rows filled; 0 when the epoch is exhausted; <0 on error.
int64_t ptdl_next_batch(int h, int32_t* out, int64_t* out_indices) {
  Dataset* ds = get(h);
  if (!ds) return -1;
  std::unique_lock<std::mutex> lk(ds->mu);
  Batch b;
  for (;;) {
    if (ds->next_deliver >= ds->epoch_batches) return 0;  // exhausted
    const int64_t want = ds->next_deliver;
    // multi-consumer safe: a second caller waiting on the same index
    // wakes when the cursor moves past it and retries on the new head
    ds->cv_pop.wait(lk, [&] {
      return ds->stopping.load() || ds->ready.count(want) != 0 ||
             ds->next_deliver != want;
    });
    if (ds->stopping.load() && ds->ready.count(want) == 0) return 0;
    if (ds->next_deliver != want) continue;  // lost the race; retry
    auto it = ds->ready.find(want);
    b = std::move(it->second);
    ds->ready.erase(it);
    ds->next_deliver = want + 1;
    break;
  }
  ds->cv_push.notify_all();
  ds->cv_pop.notify_all();
  lk.unlock();
  std::memcpy(out, b.tokens.data(), b.tokens.size() * sizeof(int32_t));
  if (out_indices)
    std::memcpy(out_indices, b.indices.data(),
                b.indices.size() * sizeof(int64_t));
  return b.n;
}

int ptdl_close(int h) {
  std::lock_guard<std::mutex> g(g_mu);
  if (h < 0 || h >= static_cast<int>(g_handles.size()) || !g_handles[h])
    return -1;
  g_handles[h].reset();
  return 0;
}

}  // extern "C"
