"""Secondary benchmark configs from BASELINE.json: ERNIE-MoE, ViT-L,
SD-UNet, Mamba, and decode/TTFT inference.

Each ``run_config(name)`` returns the same one-line JSON dict shape as
the headline llama bench. Sizes scale by platform: real configs on TPU,
smoke configs on CPU (so the suite is runnable anywhere, rc=0 always).

Timing discipline (round 5): every THROUGHPUT number is derived from
profiler DEVICE time (``benchmarks/devtime.py``), never from wall clock
through the remote tunnel — wall clock produced 4 physically-impossible
numbers in round 4 (dispatch was measured, not execution). A hard
plausibility guard refuses any result whose computed FLOP/s exceeds 95%
of chip peak. Exception: ``bench_infer``'s TTFT is a client-observed
LATENCY, which is wall-clock by definition — in this sandbox it
includes the remote tunnel's per-dispatch RTT (~10-90ms), recorded in
the result's ``latency_basis`` note so the numbers aren't mistaken for
on-host serving latency.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.devtime import (
    check_plausible,
    compiled_flops,
    fetch_sync,
    traced_step_ms,
)


def _platform():
    return jax.devices()[0].platform


def _result(metric, value, unit, extra, tpu_diags):
    if tpu_diags:
        extra["tpu_probe"] = tpu_diags
    extra["platform"] = _platform()
    extra["n_chips"] = len(jax.devices())
    if extra.pop("implausible", False):
        # measurement artifact — refuse to report it as a result, but
        # keep the refused value for diagnosis (mirrors the headline)
        extra["refused_value"] = round(float(value), 2)
        return {
            "metric": metric + "_implausible",
            "value": 0.0,
            "unit": "error",
            "vs_baseline": 0.0,
            "extra": extra,
        }
    return {
        "metric": metric,
        "value": round(float(value), 2),
        "unit": unit,
        "vs_baseline": 1.0,
        "extra": extra,
    }


def _train_throughput(model, data, loss_fn=None, unit_count=0):
    """Shared train-step timing harness.

    -> (per-sec rate from DEVICE step time, extra-dict with
    device/wall step ms, XLA-cost-analysis FLOPs, mfu_est, and the
    plausibility verdict)."""
    import paddle_tpu as pt
    from paddle_tpu import distributed as dist, optimizer as opt
    from paddle_tpu.trainer import TrainStep

    mesh = dist.build_mesh(devices=jax.devices()[:1])
    # multi_precision matches the headline (bench.py llama config): bf16
    # params train against fp32 masters — without them, sub-2^-8
    # relative updates round to zero in bf16 and the measured workload
    # is cheaper than the one BASELINE.md documents
    ts = TrainStep(model, opt.AdamW(1e-4, multi_precision=True), mesh,
                   loss_fn=loss_fn)
    tpu = _platform() == "tpu"
    # warmup / compile, with a real completion fetch
    fetch_sync(ts.run(data))
    loss = ts.run(data)
    fetch_sync(loss)

    # cost analysis BEFORE the timed phase, and prime the TrainStep's
    # telemetry FLOPs cache with it — the lazy probe (an AOT
    # lower+compile) must never fire inside a traced timing window
    flops = compiled_flops(ts.lower(data))
    ts._flops_per_step = flops
    ts._flops_probed = True

    # phase 1: short trace to learn the true device step time
    timing = traced_step_ms(lambda: ts.run(data), n_steps=3)
    # phase 2: if the traced window is too short for stable numbers,
    # re-trace with enough steps for ~0.4s of device time
    if tpu and timing.device_step_ms and timing.device_step_ms * 3 < 200:
        n = min(100, max(5, int(400 / timing.device_step_ms)))
        timing = traced_step_ms(lambda: ts.run(data), n_steps=n)
    plaus = check_plausible(flops, timing.step_ms)
    if tpu and timing.device_step_ms is None:
        # no device plane in the trace: wall clock through the tunnel
        # is NOT an acceptable substitute — refuse rather than publish
        plaus = {"implausible": True, "mfu_est": None,
                 "reason": "profiler trace carried no device plane; "
                           "tunnel wall-clock refused as a throughput "
                           "basis"}

    rate = unit_count / (timing.step_ms / 1e3)
    extra = {
        "step_ms": round(timing.step_ms, 3),
        "device_step_ms": (round(timing.device_step_ms, 3)
                           if timing.device_step_ms else None),
        "wall_step_ms": round(timing.wall_step_ms, 3),
        "timed_steps": timing.n_steps,
        "flops_per_step": flops,
        "loss": float(loss),
        **plaus,
    }
    if timing.op_summary is not None and timing.op_summary.rows:
        total = timing.op_summary.total_ms
        extra["device_categories"] = {
            k: round(100.0 * v / total, 1)
            for k, v in timing.op_summary.by_category().items()}
        # top-10 device-time op table — the attribution treatment the
        # Llama headline got, for every config (what exactly is the
        # step spending its device time on?). Per-chip like
        # device_step_ms: row totals sum ALL device planes, so divide
        # by the plane count too (SPMD: each chip runs the same step).
        planes = max(timing.op_summary.n_planes, 1)
        extra["top_ops"] = [
            {"op": (r.name if len(r.name) <= 64 else r.name[:61] + "..."),
             "ms_per_step": round(
                 r.total_ms / timing.n_steps / planes, 3),
             "pct": round(100.0 * r.total_ms / total, 1),
             "count": r.count,
             "category": r.category}
            for r in timing.op_summary.rows[:10]]
    return rate, extra


def _unet_groupnorm_roofline(cfg, batch, bytes_per_elem):
    """Analytic HBM roofline for every GroupNorm site in the UNet.

    Mirrors UNet2DConditionModel's constructor loops to enumerate each
    GroupNorm's (channels, resolution), then prices the fused kernel's
    traffic: forward reads the activation once and writes once, the
    backward reads (x, dy) and writes dx — 5 activation-passes/step.
    GroupNorm is bandwidth-bound (O(1) FLOPs/byte), so this byte count
    over peak HBM bandwidth is its floor device time; comparing the
    measured GroupNorm rows in ``top_ops`` against ``roofline_ms`` says
    whether the kernel is at roofline or leaving bandwidth unused."""
    ch = list(cfg.block_out_channels)
    s = cfg.sample_size
    L = len(ch)

    def res(level):
        return s // (2 ** level)

    sites = []  # (channels, resolution) per GroupNorm call
    skip = [ch[0]]
    cur = ch[0]
    for level, out_c in enumerate(ch):
        for _ in range(cfg.layers_per_block):
            sites.append((cur, res(level)))        # resnet norm1
            sites.append((out_c, res(level)))      # resnet norm2
            if level >= L - 2:
                sites.append((out_c, res(level)))  # cross-attn norm
            cur = out_c
            skip.append(cur)
        if level < L - 1:
            skip.append(cur)
    r_mid = res(L - 1)
    sites += [(cur, r_mid)] * 5  # mid res1 (2) + attn (1) + res2 (2)
    for level, out_c in enumerate(reversed(ch)):
        r = res(L - 1 - level)
        for _ in range(cfg.layers_per_block + 1):
            sites.append((cur + skip.pop(), r))    # resnet norm1
            sites.append((out_c, r))               # resnet norm2
            if level < 2:
                sites.append((out_c, r))           # cross-attn norm
            cur = out_c
    sites.append((cur, s))                         # conv_norm_out
    elems = sum(batch * c * r * r for c, r in sites)
    hbm_bytes = 5 * elems * bytes_per_elem
    from benchmarks.devtime import peak_hbm_bandwidth

    bw = peak_hbm_bandwidth(jax.devices()[0])
    return {
        "sites": len(sites),
        "activation_elems_per_step": elems,
        "hbm_bytes_per_step": hbm_bytes,
        "roofline_ms": round(hbm_bytes / bw * 1e3, 3),
        "peak_hbm_gbps": round(bw / 1e9, 1),
        "assumes": "fused 1r+1w fwd, 2r+1w bwd per site "
                   "(kernels/group_norm.py); unfused multiplies this",
    }


def bench_moe(tpu_diags):
    import os

    import paddle_tpu as pt
    from paddle_tpu.models import ErnieMoEConfig, ErnieMoEForCausalLM

    tpu = _platform() == "tpu"
    # BENCH_MOE_DROPLESS=1 selects no-token-drop routing (grouped
    # matmul / EP all-to-all dispatch) instead of the capacity path
    dropless = os.environ.get("BENCH_MOE_DROPLESS", "0") == "1"
    cfg = (ErnieMoEConfig(
        vocab_size=32000, hidden_size=1024, num_hidden_layers=8,
        num_attention_heads=8, max_position_embeddings=1024,
        num_experts=8, moe_every=2, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, moe_dropless=dropless)
        if tpu else ErnieMoEConfig.tiny(
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            moe_dropless=dropless))
    batch, seq = (4, 1024) if tpu else (2, 128)
    pt.seed(0)
    model = ErnieMoEForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)))
    rate, extra = _train_throughput(
        model, {"input_ids": ids, "labels": ids}, unit_count=batch * seq)
    extra["experts"] = cfg.num_experts
    extra["compute_dtype"] = "float32"
    return _result("ernie_moe_train_tokens_per_sec", rate, "tokens/s",
                   extra, tpu_diags)


def bench_vit(tpu_diags):
    import paddle_tpu as pt
    from paddle_tpu.models import ViT, ViTConfig
    from paddle_tpu.nn import functional as F

    tpu = _platform() == "tpu"
    cfg = ViTConfig.vit_l() if tpu else ViTConfig.tiny()
    batch = 32 if tpu else 4
    pt.seed(0)
    model = ViT(cfg)
    # bf16 compute + fp32 masters on TPU — the AMP-equivalent config the
    # reference trains ViT under (fp32 ran the MXU at half rate; the
    # first device-time capture measured 214.6 img/s / 40.8% MFU fp32)
    dt = jnp.bfloat16 if tpu else jnp.float32
    if tpu:
        model.to(pt.bfloat16)
    imgs = jnp.asarray(np.random.default_rng(0).standard_normal(
        (batch, cfg.num_channels, cfg.image_size, cfg.image_size)), dt)
    labels = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.num_classes, (batch,)))

    def loss_fn(logits, label):
        return F.cross_entropy(logits, label).mean()

    rate, extra = _train_throughput(
        model, {"input": imgs, "label": labels}, loss_fn=loss_fn,
        unit_count=batch)
    extra["compute_dtype"] = "bfloat16" if tpu else "float32"
    from paddle_tpu.nn import layout

    extra["conv_layout"] = (
        "NHWC" if layout.decide(cfg.channels_last) else "NCHW")
    return _result("vit_l_train_images_per_sec", rate, "images/s",
                   extra, tpu_diags)


def bench_unet(tpu_diags):
    import paddle_tpu as pt
    from paddle_tpu.models import UNet2DConditionModel, UNetConfig

    tpu = _platform() == "tpu"
    cfg = (UNetConfig(sample_size=32) if tpu
           else UNetConfig.tiny(sample_size=8))
    batch = 4 if tpu else 1
    pt.seed(0)
    model = UNet2DConditionModel(cfg)
    # bf16 compute + fp32 masters on TPU (reference trains SD under AMP).
    # The fp32 capture spent 40% of device time re-laying f32 conv
    # weights ({1,0,3,2}<->{0,1,3,2} copies every step) and ran the MXU
    # at half rate — 40.8 samples/s / 9.0% MFU.
    dt = jnp.bfloat16 if tpu else jnp.float32
    if tpu:
        model.to(pt.bfloat16)
    size = cfg.sample_size
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (batch, cfg.in_channels, size, size)), dt)
    t = jnp.asarray(np.random.default_rng(1).integers(0, 1000, (batch,)))
    ctx = jnp.asarray(np.random.default_rng(2).standard_normal(
        (batch, 77, cfg.cross_attention_dim)), dt)

    # adapter computing the denoising MSE (proxy for the ppdiffusers
    # training loss) so TrainStep's self-loss path applies
    from paddle_tpu.core.module import Layer

    class _Wrap(Layer):
        def __init__(self):
            super().__init__()
            self.unet = model

        def forward(self, sample, timestep, context, target):
            pred = self.unet(sample, timestep, context)
            # MSE in fp32 regardless of compute dtype
            diff = pred.astype(jnp.float32) - target.astype(jnp.float32)
            return jnp.mean(diff ** 2)

    wrap = _Wrap()
    data = {"sample": x, "timestep": t, "context": ctx, "target": x}
    rate, extra = _train_throughput(wrap, data, unit_count=batch)
    extra["compute_dtype"] = "bfloat16" if tpu else "float32"
    from paddle_tpu.nn import layout

    extra["conv_layout"] = (
        "NHWC" if layout.decide(cfg.channels_last) else "NCHW")
    extra["groupnorm_roofline"] = _unet_groupnorm_roofline(
        cfg, batch, bytes_per_elem=2 if dt == jnp.bfloat16 else 4)
    return _result("sd_unet_train_samples_per_sec", rate, "samples/s",
                   extra, tpu_diags)


def bench_mamba(tpu_diags):
    import paddle_tpu as pt
    from paddle_tpu.models import MambaConfig, MambaForCausalLM

    tpu = _platform() == "tpu"
    cfg = (MambaConfig(
        vocab_size=32000, hidden_size=768, num_hidden_layers=12,
        use_chunked_scan=True)
        if tpu else MambaConfig.tiny(use_chunked_scan=True, scan_chunk=32))
    batch, seq = (4, 1024) if tpu else (2, 64)
    pt.seed(0)
    model = MambaForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)))
    rate, extra = _train_throughput(
        model, {"input_ids": ids, "labels": ids}, unit_count=batch * seq)
    extra["compute_dtype"] = "float32"
    return _result("mamba_train_tokens_per_sec", rate, "tokens/s",
                   extra, tpu_diags)


PROBE_CHUNK = 2  # step_adaptive's short-chunk size; warmup compiles it


def _run_load(eng, prompts, new_tokens, gap, max_chunk, mode="chunked"):
    """One steady-arrival load sweep. A new request lands every ``gap``
    seconds while earlier ones decode; returns TTFT percentiles and the
    served-token throughput over the window. Modes:
    ``chunked`` — fixed-K chunks, admission overlapped behind them;
    ``blocking`` — head-of-line CONTROL: same K-step chunks, but
    admission prefills BLOCK the loop instead of overlapping (isolates
    what the overlapped scheduler buys);
    ``adaptive`` — ``step_adaptive``: short chunks while admission work
    is queued, full chunks in steady decode."""
    if mode not in ("chunked", "blocking", "adaptive"):
        raise ValueError(f"unknown load mode {mode!r}")
    eng._finished.clear()
    eng.metrics_window_reset()  # one telemetry window per sweep
    t_start = time.perf_counter()
    submitted = 0
    next_arrival = t_start
    n_requests = len(prompts)
    while True:
        now = time.perf_counter()
        while submitted < n_requests and now >= next_arrival:
            eng.add_request(prompts[submitted], new_tokens)
            submitted += 1
            next_arrival += gap
            now = time.perf_counter()
        if mode == "blocking" and eng._queue:
            eng._admit()  # blocking whole-prefill admission
        if mode == "adaptive":
            busy = eng.step_adaptive(max_chunk, probe_chunk=PROBE_CHUNK)
        else:
            busy = eng.step_chunk(max_chunk)
        if submitted >= n_requests and not busy and not eng.active.any():
            break
    t_total = time.perf_counter() - t_start

    reqs = [eng._finished[r] for r in sorted(eng._finished)]
    total_toks = sum(len(r.output) for r in reqs)
    out = {
        "gap_ms": round(gap * 1e3, 1),
        "served_tokens_per_sec": round(total_toks / t_total, 1),
        "n_requests": len(reqs),
    }
    # TTFT percentiles + scheduler peaks come from the shared telemetry
    # registry (the same numbers a live /metrics scrape reports), not a
    # bench-private accounting path; raw Request fields remain the
    # fallback when PT_FLAGS_telemetry=off
    snap = eng.metrics_snapshot()
    ttft = snap.get("ttft_ms") or {}
    if ttft.get("p50") is not None:
        out["p50_ttft_ms"] = round(float(ttft["p50"]), 2)
        out["p99_ttft_ms"] = round(float(ttft["p99"]), 2)
        out["peak_queue_depth"] = int(snap["queue_depth"]["peak"])
        out["peak_batch_occupancy"] = round(
            float(snap["batch_occupancy"]["peak"]), 3)
        out["peak_kv_pool_utilization"] = round(
            float(snap["kv_pool"]["peak_utilization"]), 3)
    else:
        ttfts = np.array(
            [r.ttft_ms for r in reqs if r.ttft_ms is not None])
        out["p50_ttft_ms"] = round(float(np.percentile(ttfts, 50)), 2)
        out["p99_ttft_ms"] = round(float(np.percentile(ttfts, 99)), 2)
    return out


def bench_infer(tpu_diags):
    """Serving LOAD CURVE: TTFT p50/p99 at several steady arrival rates
    spanning sub-saturation -> saturation, plus a chunked-prefill on/off
    comparison at the middle rate — BASELINE's inference metric measured
    the way a server sees it (one overload point says nothing about
    scheduling quality; VERDICT r4 weak #3)."""
    import paddle_tpu as pt
    from paddle_tpu.inference.serving import (
        ContinuousBatchingEngine,
        EngineConfig,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    tpu = _platform() == "tpu"
    cfg = (LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_hidden_layers=16, num_attention_heads=8, num_key_value_heads=8,
        max_position_embeddings=2048, use_flash_attention=True,
        dtype="bfloat16")
        if tpu else LlamaConfig(
            vocab_size=512, hidden_size=256, intermediate_size=512,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=4, max_position_embeddings=512,
            use_flash_attention=False))
    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    if tpu:
        model.to(pt.bfloat16)

    prompt_len = 120
    new_tokens = 64 if tpu else 8
    n_requests = 24 if tpu else 6
    max_chunk = 8 if tpu else 4
    ecfg = EngineConfig(
        max_slots=8 if tpu else 2,
        max_len=512 if tpu else 256,
        seq_buckets=(128,),
        cache_dtype=jnp.bfloat16 if tpu else jnp.float32,
    )
    eng = ContinuousBatchingEngine(model, ecfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,))
               for _ in range(n_requests)]

    # warmup: compile the prefill + chunk-decode programs; drop its
    # record (its TTFT is compile time, not serving time). The blocking
    # control reuses these same programs (it only changes admission
    # blocking); the adaptive sweep also uses the probe-sized chunk, so
    # compile that K too — a mid-measurement compile would bill seconds
    # of compile time as TTFT.
    eng.run([prompts[0]], max_new_tokens=2, max_chunk=max_chunk)
    eng.run([prompts[0]], max_new_tokens=2, max_chunk=PROBE_CHUNK)

    # unloaded TTFT: one request into an empty engine (prefill +
    # admission latency with zero queueing)
    unloaded = _run_load(eng, prompts[:1], new_tokens, 1e-3, max_chunk)

    # arrival-rate sweep: FIXED design gaps (a chunk-relative gap would
    # self-scale the offered load with engine speed and make TTFT
    # incomparable across rounds). 300ms ~ sub-saturation for 8 slots,
    # 75ms ~ 2x overload.
    gaps = (0.300, 0.150, 0.075) if tpu else (0.050,)
    curve = [_run_load(eng, prompts, new_tokens, g, max_chunk)
             for g in gaps]

    # overlapped-admission OFF at the middle rate: same decode chunks,
    # but admission prefills block the loop (head-of-line control)
    mid = gaps[len(gaps) // 2]
    unchunked = _run_load(eng, prompts, new_tokens, mid, max_chunk,
                          mode="blocking")
    # adaptive chunk sizing at the same rate (short chunks while the
    # admission queue is non-empty — should match blocking's TTFT while
    # keeping chunked throughput)
    adaptive = _run_load(eng, prompts, new_tokens, mid, max_chunk,
                         mode="adaptive")

    headline = curve[len(gaps) // 2]
    return _result(
        "infer_p50_ttft_ms", headline["p50_ttft_ms"], "ms",
        {"latency_basis": "client wall-clock incl. tunnel dispatch RTT",
         "compute_dtype": "bfloat16" if tpu else "float32",
         "p99_ttft_ms": headline["p99_ttft_ms"],
         "unloaded_ttft_ms": unloaded["p50_ttft_ms"],
         "served_tokens_per_sec": headline["served_tokens_per_sec"],
         "load_curve": curve,
         "chunked_prefill_off": unchunked,
         "adaptive_chunking": adaptive,
         "n_requests": headline["n_requests"], "prompt_len": prompt_len,
         "new_tokens": new_tokens,
         "arrival_gap_ms": headline["gap_ms"],
         "max_chunk": max_chunk,
         "slots": ecfg.max_slots}, tpu_diags)


def _build_7b_int8(cfg, group_size=128, seed=0, weight_dtype="int8"):
    """Construct a weight-only-quantized Llama of ``cfg``'s size WITHOUT
    ever materializing the fp32/bf16 dense tree (28 GB for 7B — beyond
    the 16 GB HBM): the model is meta-initialized (ShapeDtypeStructs),
    every linear is swapped for a WeightOnlyLinear allocated directly at
    int8/int4, the qweights are filled with random values on-device
    (decode throughput is value-independent), and only the small
    non-linear params (embeddings, norms) are materialized densely."""
    import jax.random as jrandom

    from paddle_tpu.core import meta
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.quantization import WeightOnlyLinear
    from paddle_tpu.quantization.qat import replace_layers
    from paddle_tpu.distributed.parallel_layers.mp_layers import (
        ColumnParallelLinear,
        RowParallelLinear,
    )
    from paddle_tpu.nn.layer.common import Linear

    with meta.meta_init():
        model = LlamaForCausalLM(cfg)

    kinds = (Linear, ColumnParallelLinear, RowParallelLinear)
    model = replace_layers(
        model, lambda s: type(s) in kinds,
        lambda s: WeightOnlyLinear(s.in_features, s.out_features,
                                   weight_dtype=weight_dtype,
                                   group_size=group_size))

    key = jrandom.PRNGKey(seed)
    for name, sub in model.named_sublayers():
        if isinstance(sub, WeightOnlyLinear):
            key, k1, k2 = jrandom.split(key, 3)
            q = jrandom.randint(
                k1, sub._buffers["qweight"].shape, -127, 128, jnp.int8)
            # scales sized like a real quantization of N(0, 0.02) weights
            s = 0.02 * (1.0 + 0.1 * jrandom.uniform(
                k2, sub._buffers["scale"].shape)) / 127.0
            sub._buffers["qweight"] = q
            sub._buffers["scale"] = s.astype(jnp.float32)
            sub.bias = None  # llama linears are bias-free
    meta.materialize(model, seed=seed)  # embeddings + norms only now
    if cfg.dtype == "bfloat16":
        import paddle_tpu as pt

        model.to(pt.bfloat16)
    model.eval()
    return model


def _decode_attn_roofline(mcfg, ecfg, steady_len, cache_bytes):
    """Analytic HBM roofline for the decode-attention stage at this
    bench's steady state (mirrors ``_unet_groupnorm_roofline``): every
    layer's RoPE + KV-append + attention priced through the kernelbench
    traffic model, fused vs unfused, at the mid-measurement sequence
    length. Decode attention is bandwidth-bound, so bytes over peak HBM
    bandwidth is its floor device time per step; comparing against the
    measured chunk time says how much of the step the KV stream is."""
    from benchmarks.devtime import peak_hbm_bandwidth
    from benchmarks.kernelbench import decode_hbm_bytes

    lens = [steady_len] * ecfg.max_slots
    kvh = mcfg.num_key_value_heads
    group = mcfg.num_attention_heads // kvh
    kw = (dict(page_size=ecfg.page_size) if ecfg.paged
          else dict(max_len=ecfg.max_len))
    mode = "paged" if ecfg.paged else "contiguous"
    act_bytes = 2 if mcfg.dtype == "bfloat16" else 4
    fused = mcfg.num_hidden_layers * decode_hbm_bytes(
        mode, True, lens, kvh, group, mcfg.head_dim,
        cache_bytes=cache_bytes, act_bytes=act_bytes, **kw)
    unfused = mcfg.num_hidden_layers * decode_hbm_bytes(
        mode, False, lens, kvh, group, mcfg.head_dim,
        cache_bytes=cache_bytes, act_bytes=act_bytes, **kw)
    bw = peak_hbm_bandwidth(jax.devices()[0])
    return {
        "steady_seq_len": steady_len,
        "fused_hbm_bytes_per_step": fused,
        "unfused_hbm_bytes_per_step": unfused,
        "fused_roofline_ms": round(fused / bw * 1e3, 3),
        "unfused_roofline_ms": round(unfused / bw * 1e3, 3),
        "peak_hbm_gbps": round(bw / 1e9, 1),
        "assumes": "per-layer rope+append+attention traffic "
                   "(benchmarks/kernelbench.decode_hbm_bytes); "
                   "PT_FLAGS_fused_decode picks the fused row on TPU",
    }


def _shared_prefix_scenario(model, base_ecfg, tpu):
    """Prefix-cache A/B under shared-system-prompt load: N requests
    share a long block-aligned prefix and differ only in a short tail.
    Requests run SEQUENTIALLY (request k+1 can hit the blocks request k
    published), once with ``PT_FLAGS_prefix_cache=on`` and once off;
    reports TTFT p50/p95 and the token hit rate per arm plus the
    modeled prefill-FLOPs row. The prefill chunk is shrunk to one page
    for the scenario so the suffix-vs-prompt chunk-count difference is
    visible even at the CPU smoke size."""
    from benchmarks.kernelbench import prefill_admission_flops
    from paddle_tpu import flags as F
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    ps = base_ecfg.page_size
    shared_len = (4 if tpu else 2) * ps
    tail_len = 8
    new_tokens = 16 if tpu else 4
    n_requests = 12 if tpu else 4
    rng = np.random.default_rng(7)
    vocab = model.config.vocab_size
    shared = rng.integers(0, vocab, (shared_len,))
    prompts = [np.concatenate([shared, rng.integers(0, vocab, (tail_len,))])
               for _ in range(n_requests)]
    warm = rng.integers(0, vocab, (shared_len + tail_len,))

    ecfg = base_ecfg
    saved = {k: F.flag(k) for k in ("prefix_cache", "prefill_chunk")}
    out = {}
    try:
        for arm in ("on", "off"):
            F.set_flags({"prefix_cache": arm == "on",
                         "prefill_chunk": ps})
            eng = ContinuousBatchingEngine(model, ecfg)
            eng.run([warm], max_new_tokens=2)  # compile, no shared blocks
            # ONE unified snapshot document (prefix/spec/SLO ride
            # along whether telemetry is on or off)
            base = eng.metrics_snapshot()["prefix_cache"]
            ttfts = []
            for p in prompts:
                ttfts.append(eng.run([p], new_tokens)[0].ttft_ms)
            snap = eng.metrics_snapshot()["prefix_cache"]
            hit_toks = snap["hit_tokens"] - base["hit_tokens"]
            prompt_toks = snap["prompt_tokens"] - base["prompt_tokens"]
            out[arm] = {
                "p50_ttft_ms": round(float(np.percentile(ttfts, 50)), 2),
                "p95_ttft_ms": round(float(np.percentile(ttfts, 95)), 2),
                "prefix_hits": snap["hits"] - base["hits"],
                "prefix_hit_rate_tokens": round(
                    hit_toks / prompt_toks if prompt_toks else 0.0, 3),
                "cached_blocks": snap["cached_blocks"],
            }
            eng = None  # drop this arm's KV pool before the next builds
    finally:
        F.set_flags(saved)
    out["n_requests"] = n_requests
    out["shared_prefix_len"] = int(shared_len)
    out["tail_len"] = tail_len
    out["modeled_prefill"] = prefill_admission_flops(
        shared_len + tail_len, shared_len, chunk=ps,
        buckets=tuple(base_ecfg.seq_buckets),
        max_len=base_ecfg.max_len,
        hidden=model.config.hidden_size,
        inter=model.config.intermediate_size,
        n_layers=model.config.num_hidden_layers, vocab=vocab)
    return out


def _spec_ngram_scenario(model, base_ecfg, tpu):
    """Speculative-decoding A/B under repetitive-suffix traffic (the
    regime n-gram self-drafting targets: code, JSON, templated
    answers). Prompts end in repeated template blocks; requests run
    once with ``PT_FLAGS_spec_decode=ngram`` and once ``off`` through
    the same scheduler; reports served tok/s, the acceptance rate the
    drafter actually achieved, and — the quality claim — whether the
    two arms' greedy outputs were identical. A short decode chunk
    keeps draft opportunities frequent (each chunk boundary is one
    propose-verify chance); both arms pay the same sync cadence so the
    ratio isolates what verification buys."""
    from paddle_tpu import flags as F
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.inference.spec_decode import Drafter, NgramDrafter

    class _ForceDrafter(Drafter):
        """Warm-up-only: always proposes (garbage is fine — rejection
        still exercises the verify program), so the [slots, K+1]
        compile deterministically lands in the warm-up, not the timed
        window. The n-gram drafter can't guarantee that: its first
        firing depends on what the model happens to emit."""

        def propose(self, history, k):
            return np.full((k,), int(history[-1]), np.int64)

    vocab = model.config.vocab_size
    rng = np.random.default_rng(11)
    unit = rng.integers(0, vocab, (8,))
    n_requests = 8 if tpu else 3
    reps = 6 if tpu else 3
    prompts = [np.concatenate(
        [rng.integers(0, vocab, (4,))] + [unit] * reps)
        for _ in range(n_requests)]
    # long enough for greedy decode to fall into its attractor loop —
    # the repetitive regime the drafter targets (and the chunked
    # scheduler's preemption gate needs a MAJORITY of slots drafting
    # in the same tick before a verify pass runs)
    new_tokens = 48 if tpu else 32
    max_chunk = 2
    saved = F.flag("spec_decode")
    out = {}
    outputs = {}
    try:
        for arm in ("on", "off"):
            F.set_flags({"spec_decode": "ngram" if arm == "on"
                         else "off"})
            eng = ContinuousBatchingEngine(
                model, base_ecfg,
                drafter=_ForceDrafter() if arm == "on" else None)
            eng.run([prompts[0]], max_new_tokens=base_ecfg.spec_k + 2,
                    max_chunk=max_chunk)
            if arm == "on":
                assert eng.spec_snapshot()["verify_calls"] > 0, \
                    "warm-up never compiled the verify program"
                eng._drafter = NgramDrafter()  # the drafter under test
            eng._finished.clear()
            # reported acceptance/verify stats cover the timed window
            # only, not the warm-up's forced drafts
            eng.spec_stats = {k: 0 for k in eng.spec_stats}
            t0 = time.perf_counter()
            reqs = eng.run(prompts, max_new_tokens=new_tokens,
                           max_chunk=max_chunk)
            dt = time.perf_counter() - t0
            toks = sum(len(r.output) for r in reqs)
            snap = eng.metrics_snapshot()["spec_decode"]
            outputs[arm] = [r.output for r in reqs]
            out[arm] = {
                "tokens_per_sec": round(toks / dt, 1),
                "acceptance_rate": round(snap["acceptance_rate"], 3),
                "proposed": snap["proposed"],
                "accepted": snap["accepted"],
                "verify_calls": snap["verify_calls"],
                "fallback_steps": snap["fallback_steps"],
            }
            eng = None  # drop this arm's KV pool before the next builds
    finally:
        F.set_flags({"spec_decode": saved})
    out["outputs_match"] = outputs["on"] == outputs["off"]
    out["n_requests"] = n_requests
    out["new_tokens"] = new_tokens
    out["max_chunk"] = max_chunk
    out["spec_k"] = base_ecfg.spec_k
    return out


def _goodput_scenario(model, base_ecfg, tpu):
    """Closed-loop goodput-under-SLO sweep (ROADMAP item 5's metric):
    arrival QPS rises across steps, every request carries the
    ``interactive`` SLO class, and each step reports p99 TTFT /
    per-request TPOT plus the GOODPUT fraction (requests finishing
    within target) — the number that ranks schedulers, instead of raw
    tok/s. Percentiles come from the telemetry registry when the flag
    is on; otherwise from the finished requests' own recorded
    timelines (`ttft_ms`/`tpot_ms`), so the sweep runs under the test
    suite's telemetry-off default too. Targets are generous on the CPU
    smoke (dispatch dominates); the TPU row's 200/50 ms is the
    interactive envelope BASELINE.md tracks."""
    from paddle_tpu import flags as F

    # flight data rides the sweep: the time-series store + burn-rate
    # detectors give each QPS step a BURN column (is attainment eating
    # budget at this load?) and cost attribution prices each request
    # in device-ms — the trend-shaped numbers the ledger accumulates.
    # Short windows: the CPU smoke runs only a handful of ticks/step.
    saved_fl = {k: F.flag(k) for k in
                ("timeseries", "timeseries_cadence", "alerts",
                 "cost_attribution")}
    F.set_flags({"timeseries": True, "timeseries_cadence": 2,
                 "alerts": True, "cost_attribution": True})
    try:
        return _goodput_sweep(model, base_ecfg, tpu)
    finally:
        F.set_flags(saved_fl)


def _goodput_sweep(model, base_ecfg, tpu):
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    qps_steps = (2.0, 4.0, 8.0) if tpu else (8.0, 25.0)
    n_requests = 16 if tpu else 4
    new_tokens = 32 if tpu else 4
    prompt_len = 48 if tpu else 10
    max_chunk = 8 if tpu else 4
    ttft_target = 200.0 if tpu else 2000.0
    tpot_target = 50.0 if tpu else 1000.0
    eng = ContinuousBatchingEngine(model, base_ecfg)
    rng = np.random.default_rng(3)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab, (prompt_len,))
               for _ in range(n_requests)]
    # warm-up compiles the prefill + chunk programs outside every
    # timed step (a mid-sweep compile would bill seconds as TTFT)
    eng.run([prompts[0]], max_new_tokens=2, max_chunk=max_chunk)
    rows = []
    for qps in qps_steps:
        gap = 1.0 / qps
        eng._finished.clear()
        eng.metrics_window_reset()
        eng.slo_window_reset()
        eng.alerts_window_reset()  # per-step burn-rate peak
        t_start = time.perf_counter()
        submitted = 0
        next_arrival = t_start
        while True:
            now = time.perf_counter()
            while submitted < n_requests and now >= next_arrival:
                eng.add_request(prompts[submitted], new_tokens,
                                slo="interactive",
                                ttft_target_ms=ttft_target,
                                tpot_target_ms=tpot_target)
                # closed-loop honesty: the TTFT clock starts at the
                # SCHEDULED arrival, not the (step-delayed) add time —
                # arrivals can only land between chunks, and omitting
                # that queueing delay (coordinated omission) would
                # understate p99 exactly at the saturation knee this
                # sweep exists to find
                eng._queue[-1]._submit_t = next_arrival
                submitted += 1
                next_arrival += gap
                now = time.perf_counter()
            busy = eng.step_chunk(max_chunk)
            if submitted >= n_requests and not busy \
                    and not eng.active.any():
                break
            if not busy and not eng.active.any() \
                    and submitted < n_requests:
                # idle between arrivals: sleep to the next one instead
                # of hammering step_chunk at 100% host CPU — the spin
                # would compete with the engine's own dispatch and
                # distort the very p99s this sweep reports
                time.sleep(max(
                    0.0, min(next_arrival - time.perf_counter(), gap)))
        wall = time.perf_counter() - t_start
        reqs = [eng._finished[r] for r in sorted(eng._finished)]
        toks = sum(len(r.output) for r in reqs)
        slo = eng.slo_snapshot()
        row = {
            "qps": qps,
            "n_requests": len(reqs),
            "served_tokens_per_sec": round(toks / wall, 1),
            "goodput": (round(slo["goodput"], 3)
                        if slo["goodput"] is not None else None),
            "goodput_tokens_per_sec": round(
                sum(len(r.output) for r in reqs if r.slo_met) / wall, 1),
            "slo_met": slo["met"],
            "slo_violated": slo["violated"],
        }
        # flight-data columns: peak SLO burn (violation ratio over
        # error budget, min of fast/slow windows — the alert rule's
        # own scalar) and mean attributed device-ms per request at
        # this QPS — trend-shaped numbers the ledger accumulates
        asn = eng.alerts_snapshot()
        if asn.get("enabled"):
            row["burn_rate"] = round(
                asn["rules"]["slo_burn_rate"]["peak"], 3)
        costs = [r.device_ms for r in reqs]
        row["mean_req_device_ms"] = (
            round(float(np.mean(costs)), 3) if costs else None)
        snap = eng.metrics_snapshot()
        ttft = snap.get("ttft_ms") or {}
        if ttft.get("p99") is not None:
            row["p99_ttft_ms"] = round(float(ttft["p99"]), 2)
        else:
            row["p99_ttft_ms"] = round(float(np.percentile(
                [r.ttft_ms for r in reqs], 99)), 2)
        rtpot = snap.get("request_tpot_ms") or {}
        if rtpot.get("p99") is not None:
            row["p99_tpot_ms"] = round(float(rtpot["p99"]), 2)
        else:
            tpots = [r.tpot_ms for r in reqs if r.tpot_ms is not None]
            row["p99_tpot_ms"] = (round(float(np.percentile(tpots, 99)),
                                        2) if tpots else None)
        # trace-derived cross-check: the lifecycle tracer's closing
        # 'active' spans carry each request's token count — they must
        # agree with the scheduler's own view (tracing on only).
        # `checked` counts the spans still in the ring: None (not
        # True) when the ring cycled past them all — a vacuous all()
        # must not report agreement it never verified
        if eng._tracer is not None:
            acts = {e["rid"]: e["args"] for e in eng._tracer.events()
                    if e["kind"] == "request" and e["name"] == "active"}
            checked = [r for r in reqs if r.rid in acts]
            row["trace_spans_checked"] = len(checked)
            row["trace_spans_consistent"] = (
                all(acts[r.rid]["tokens"] == len(r.output)
                    for r in checked) if checked else None)
        rows.append(row)
    cost = eng.cost_snapshot()
    asn = eng.alerts_snapshot()
    return {
        "slo_class": "interactive",
        "ttft_target_ms": ttft_target,
        "tpot_target_ms": tpot_target,
        "n_requests_per_step": n_requests,
        "new_tokens": new_tokens,
        "max_chunk": max_chunk,
        "sweep": rows,
        # compact flight summary for the bench ledger (shed-path
        # included): peak burn across the sweep, p50 attributed
        # request device-ms, total alert firings
        "flight": {
            "burn_rate_peak": max(
                (r["burn_rate"] for r in rows
                 if r.get("burn_rate") is not None), default=None),
            "req_device_ms_p50": (
                round(cost["request_device_ms_p50"], 3)
                if cost.get("request_device_ms_p50") is not None
                else None),
            "alerts_fired": (asn.get("fired_total")
                             if asn.get("enabled") else None),
        },
    }


def _sched_ab_scenario(model, base_ecfg, tpu):
    """Scheduler A/B the goodput sweep exists to rank: the SAME
    saturated mixed-tenant burst (batch hog + interactive tail, 2
    tenants) runs under FIFO admission and under the SLO-fair
    scheduler, reporting per-arm goodput and interactive TTFT — plus a
    tenant-starvation adversary (one tenant floods, the other sends
    occasional interactive) where the number that matters is the
    SMALL tenant's worst TTFT: bounded under SLO-fair, queue-tail
    under FIFO.

    Interactive TTFT targets are CALIBRATED (half the FIFO arm's
    median interactive TTFT) and attainment computed post-hoc from
    each request's recorded ``ttft_ms`` — absolute wall targets would
    encode this host's speed, and the A/B's claim is about ORDERING:
    the same workload, the same engine, only admission policy moves
    (post-hoc also means one engine build per arm, no probe run)."""
    import time as _time

    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.serving_api import SLOFairScheduler, TenantQuota

    n_int = 6 if tpu else 3
    n_batch = 6 if tpu else 3
    batch_tokens = 64 if tpu else 10
    int_tokens = 16 if tpu else 4
    prompt_len = 48 if tpu else 10
    max_chunk = 8 if tpu else 4
    rng = np.random.default_rng(11)
    vocab = model.config.vocab_size
    batch_prompts = [rng.integers(0, vocab, (prompt_len,))
                     for _ in range(n_batch)]
    int_prompts = [rng.integers(0, vocab, (prompt_len,))
                   for _ in range(n_int)]

    def make_sched():
        return SLOFairScheduler(
            tenants={"bulk": TenantQuota(
                weight=1.0,
                max_slots=max(base_ecfg.max_slots - 1, 1)),
                "acme": TenantQuota(weight=2.0)})

    def run_arm(sched):
        eng = ContinuousBatchingEngine(model, base_ecfg)
        if sched is not None:
            eng.set_scheduler(sched)
        # warm-up compiles outside the timed burst
        eng.run([int_prompts[0]], max_new_tokens=2,
                max_chunk=max_chunk)
        eng._finished.clear()
        t0 = _time.perf_counter()
        # saturated burst BY CONSTRUCTION: the batch hog queues first,
        # the interactive tail arrives behind it — FIFO must drain the
        # hog before any interactive prefill runs. Targets are huge
        # (1e9): attainment is computed post-hoc against the
        # calibrated target from the recorded ttft_ms
        for p in batch_prompts:
            eng.add_request(p, batch_tokens, tenant="bulk",
                            slo="batch")
        for p in int_prompts:
            eng.add_request(p, int_tokens, tenant="acme",
                            slo="interactive", ttft_target_ms=1e9)
        while eng.step_chunk(max_chunk) or eng._queue \
                or eng.active.any():
            pass
        wall = _time.perf_counter() - t0
        reqs = list(eng._finished.values())
        ints = [r for r in reqs if r.slo == "interactive"]
        toks = sum(len(r.output) for r in reqs)
        return {
            "interactive_ttfts": [r.ttft_ms for r in ints],
            "served_tokens_per_sec": round(toks / wall, 1),
            "preemptions": eng.sched_stats["preemptions"],
            "all_finished": len(reqs) == n_int + n_batch,
        }

    def attain(arm, ttft_target):
        ttfts = arm.pop("interactive_ttfts")
        met = sum(1 for t in ttfts if t <= ttft_target)
        arm["interactive_goodput"] = round(met / len(ttfts), 3)
        # batch requests (generous class targets) count as met: the
        # overall goodput moves on the interactive tail only
        arm["goodput"] = round(
            (met + n_batch) / (n_int + n_batch), 3)
        arm["interactive_median_ttft_ms"] = round(
            float(np.median(ttfts)), 2)
        arm["interactive_p99_ttft_ms"] = round(
            float(np.percentile(ttfts, 99)), 2)
        return arm

    fifo = run_arm(None)
    fair = run_arm(make_sched())
    # calibrated between the arms' behavior: half the FIFO median
    ttft_target = max(
        float(np.median(fifo["interactive_ttfts"])) / 2, 1.0)
    fifo = attain(fifo, ttft_target)
    fair = attain(fair, ttft_target)

    # tenant-starvation adversary: "hog" floods batch, "small" sends
    # two interactive requests behind the flood — worst small-tenant
    # TTFT is the starvation bound
    def run_adversary(sched):
        eng = ContinuousBatchingEngine(model, base_ecfg)
        if sched is not None:
            eng.set_scheduler(sched)
        eng.run([int_prompts[0]], max_new_tokens=2,
                max_chunk=max_chunk)
        eng._finished.clear()
        for p in batch_prompts * 2:
            eng.add_request(p, batch_tokens, tenant="hog",
                            slo="batch")
        small = [eng.add_request(p, int_tokens, tenant="small",
                                 slo="interactive", ttft_target_ms=1e9)
                 for p in int_prompts[:2]]
        while eng.step_chunk(max_chunk) or eng._queue \
                or eng.active.any():
            pass
        worst = max(eng._finished[r].ttft_ms for r in small)
        return round(float(worst), 2), eng.sched_stats["preemptions"]

    starved_ttft, _ = run_adversary(None)
    adv_sched = SLOFairScheduler(
        tenants={"hog": TenantQuota(
            weight=1.0, max_slots=max(base_ecfg.max_slots - 1, 1)),
            "small": TenantQuota(weight=4.0)},
        ttft_margin_ms=1e9)  # every tracked request counts as urgent
    fair_ttft, adv_preempts = run_adversary(adv_sched)
    return {
        "ttft_target_ms": round(ttft_target, 2),
        "fifo": fifo,
        "slo_fair": fair,
        "starvation": {
            "fifo_worst_small_ttft_ms": starved_ttft,
            "slo_fair_worst_small_ttft_ms": fair_ttft,
            "bound_factor": (round(starved_ttft / fair_ttft, 2)
                             if fair_ttft else None),
            "preemptions": adv_preempts,
        },
    }


def _http_overhead_scenario(model, base_ecfg, tpu):
    """Server-path overhead: the SAME workload through the library
    path (direct ``step_chunk`` drive) and through the HTTP front
    door over a real loopback socket (one concurrent non-streaming
    client per request), reported as tok/s on both paths + overhead
    percent — the satellite row that keeps the wire path honest on
    the compact ledger."""
    import threading as _threading
    import time as _time

    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.serving_api import start_api_server

    n_req = 8 if tpu else 3
    new_tokens = 32 if tpu else 4
    prompt_len = 48 if tpu else 10
    max_chunk = 8 if tpu else 4
    rng = np.random.default_rng(5)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab, (prompt_len,))
               for _ in range(n_req)]

    eng = ContinuousBatchingEngine(model, base_ecfg)
    eng.run([prompts[0]], max_new_tokens=2, max_chunk=max_chunk)
    t0 = _time.perf_counter()
    reqs = eng.run(prompts, max_new_tokens=new_tokens,
                   max_chunk=max_chunk)
    lib_wall = _time.perf_counter() - t0
    lib_toks = sum(len(r.output) for r in reqs)

    eng2 = ContinuousBatchingEngine(model, base_ecfg)
    srv = start_api_server(eng2, scheduler=None, max_chunk=max_chunk)
    try:
        import http.client
        import urllib.parse

        u = urllib.parse.urlparse(srv.url)

        def post(prompt, out):
            conn = http.client.HTTPConnection(u.hostname, u.port,
                                              timeout=120)
            try:
                conn.request(
                    "POST", "/v1/completions",
                    json.dumps({"prompt": [int(t) for t in prompt],
                                "max_tokens": new_tokens}),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = json.loads(resp.read())
                out.append(len(payload["choices"][0]["token_ids"]))
            finally:
                conn.close()

        # warm the server engine's programs outside the timed window
        warm_out = []
        post(prompts[0], warm_out)
        counts = []
        t0 = _time.perf_counter()
        threads = [_threading.Thread(target=post, args=(p, counts))
                   for p in prompts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        http_wall = _time.perf_counter() - t0
        http_toks = sum(counts)
    finally:
        srv.shutdown()
    lib_tps = lib_toks / lib_wall
    http_tps = http_toks / http_wall if http_wall else 0.0
    return {
        "n_requests": n_req,
        "new_tokens": new_tokens,
        "library_tokens_per_sec": round(lib_tps, 1),
        "http_tokens_per_sec": round(http_tps, 1),
        "overhead_pct": (round((lib_tps - http_tps) / lib_tps * 100, 1)
                         if lib_tps else None),
        "all_served": len(counts) == n_req
        and all(c == new_tokens for c in counts),
    }


def _fault_recovery_scenario(model, base_ecfg, tpu):
    """Chaos A/B (recovery-overhead capture): the same greedy workload
    runs clean and under a seeded fault storm (step-dispatch faults +
    NaN-logits storms + latency spikes at the engine's dispatch
    seams). The chaos arm quarantines each faulted step and replays
    the affected requests through the existing chunked-prefill
    program; reported are tokens/s per arm, the recovery/retry
    counts, the wall overhead, and — the quality claim — whether the
    two arms' greedy outputs were bit-identical (deterministic
    replay). The injector is attached AFTER warm-up so a fault never
    lands inside a first-time compile and bills it as recovery
    time."""
    from paddle_tpu.inference.resilience import FaultInjector
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    n_requests = 8 if tpu else 4
    new_tokens = 24 if tpu else 6
    max_chunk = 8 if tpu else 4
    rng = np.random.default_rng(17)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab, (int(rng.integers(8, 24)),))
               for _ in range(n_requests)]
    spec = "step:0.08,nan:0.04,latency:0.05,seed:11,latency_ms:5"
    out = {"fault_spec": spec, "n_requests": n_requests,
           "new_tokens": new_tokens}
    outputs = {}
    for arm in ("clean", "chaos"):
        eng = ContinuousBatchingEngine(model, base_ecfg)
        eng.run([prompts[0]], max_new_tokens=2, max_chunk=max_chunk)
        if arm == "chaos":
            eng._injector = FaultInjector(spec)
        t0 = time.perf_counter()
        reqs = eng.run(prompts, new_tokens, max_chunk=max_chunk)
        wall = time.perf_counter() - t0
        toks = sum(len(r.output) for r in reqs)
        rs = eng.resilience_stats
        outputs[arm] = [r.output for r in reqs]
        out[arm] = {
            "tokens_per_sec": round(toks / wall, 1),
            "wall_s": round(wall, 3),
            "recoveries": rs["recoveries"],
            "retries": rs["retries"],
            "nan_steps": rs["nan_steps"],
            "timeouts": rs["timeouts"],
            "failed": rs["failed"],
        }
        eng = None  # drop this arm's KV pool before the next builds
    out["outputs_match"] = outputs["clean"] == outputs["chaos"]
    clean_w, chaos_w = out["clean"]["wall_s"], out["chaos"]["wall_s"]
    out["recovery_overhead_pct"] = round(
        (chaos_w / clean_w - 1.0) * 100.0, 1) if clean_w else None
    return out


def _replica_failover_scenario(model, base_ecfg, tpu):
    """Replicated-serving chaos A/B (the fleet's recovery-overhead
    capture): the same greedy workload runs through a 2-replica
    ``EngineRouter`` clean and under a seeded replica-kill storm
    (whole-replica crashes + hangs at the router's tick seam). The
    storm arm reclaims each dead replica's in-flight requests from
    the host token ledger and replays them through the survivor's
    existing prefill program; reported are tok/s per arm, the
    failover/reclaim/replay counts, breaker opens, the wall overhead,
    and — the quality claim — whether the two arms' greedy outputs
    were bit-identical (placement- and failover-invariant decoding).
    The injector is attached AFTER warm-up so a crash never lands
    inside a first-time compile and bills it as failover time; retry
    bounds are raised so the A/B measures failover, not retry
    exhaustion."""
    import dataclasses

    from paddle_tpu.inference.resilience import FaultInjector
    from paddle_tpu.inference.router import EngineRouter

    if tpu:
        # two resident KV pools: halve the per-replica footprint so
        # the fleet + int8 weights fit HBM next to each other
        ecfg = dataclasses.replace(base_ecfg, max_slots=4,
                                   max_len=512, max_retries=100)
        n_requests, new_tokens, max_chunk = 8, 24, 8
    else:
        ecfg = dataclasses.replace(base_ecfg, max_retries=100)
        n_requests, new_tokens, max_chunk = 4, 6, 2
    rng = np.random.default_rng(29)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab, (int(rng.integers(8, 24)),))
               for _ in range(n_requests)]
    spec = "replica_crash:0.12,replica_hang:0.06,seed:23"
    out = {"fault_spec": spec, "n_replicas": 2,
           "n_requests": n_requests, "new_tokens": new_tokens}
    outputs = {}
    for arm in ("clean", "storm"):
        router = EngineRouter(model, ecfg, n_replicas=2,
                              breaker_cooldown=3, hang_ticks=2)
        router.run(prompts[:2], max_new_tokens=2, max_chunk=max_chunk)
        if arm == "storm":
            router._injector = FaultInjector(spec)
        t0 = time.perf_counter()
        reqs = router.run(prompts, new_tokens, max_chunk=max_chunk)
        wall = time.perf_counter() - t0
        toks = sum(len(r.output) for r in reqs)
        fs = router.fleet_snapshot()
        outputs[arm] = [r.output for r in reqs]
        out[arm] = {
            "tokens_per_sec": round(toks / wall, 1),
            "wall_s": round(wall, 3),
            "failovers": fs["failovers"],
            "reclaimed": fs["reclaimed"],
            "replayed": fs["replayed"],
            "breaker_opens": fs["breaker_opens"],
            "held": fs["held"],
        }
        router = None  # drop this arm's KV pools before the next builds
    out["outputs_match"] = outputs["clean"] == outputs["storm"]
    out["failovers"] = out["storm"]["failovers"]
    clean_w, storm_w = out["clean"]["wall_s"], out["storm"]["wall_s"]
    out["failover_overhead_pct"] = round(
        (storm_w / clean_w - 1.0) * 100.0, 1) if clean_w else None
    return out


def _audit_scenario():
    """Contract-audit verdict for the ledger: the canonical tiny-arm
    repo program set (ptaudit, analysis/program_audit.py). The
    structural families (AL donation, DQ001 dtype pairs, TX transfer
    bans, DD dead operands) are platform-honest and run everywhere;
    the committed ``.ptaudit-baseline.json`` size/creep pins (SZ,
    DQ002) are CPU-trace canonical — on TPU the fused Pallas kernels
    change the op mix, so the baseline comparison is skipped there
    and ``op_counts_ok`` reads None, never a spurious red. Compact on
    purpose (the ledger line sheds it with the other secondary
    detail): program count, the op-counts-ok bit, the total violation
    count with the first few rule ids named."""
    from paddle_tpu.analysis import program_audit as PA

    on_cpu = _platform() != "tpu"
    t0 = time.perf_counter()
    try:
        rep = PA.audit_repo(use_baseline=on_cpu)
    except Exception as e:  # a broken audit must not sink the bench
        # op_counts_ok None: nothing was COMPARED — the error field
        # and violations:-1 carry the failure, never a spurious red
        return {"programs": 0, "op_counts_ok": None,
                "violations": -1, "error": str(e)[:200]}
    viol = rep["violations"]
    return {
        "programs": len(rep["entries"]),
        "op_counts_ok": (not any(
            v.rule in ("SZ001", "SZ002", "DQ002") for v in viol))
        if on_cpu else None,
        "violations": len(viol),
        "rules": sorted({v.rule for v in viol})[:5],
        "wall_s": round(time.perf_counter() - t0, 2),
    }


def _quant_scenario(base_ecfg, tpu):
    """Quantized-serving A/B: the SAME greedy workload served three
    ways — bf16 weights (baseline), int8 weight streaming, and
    int8 weights × int8 KV pools — through engines the ENGINE itself
    quantizes at init (``EngineConfig.weight_dtype`` /
    ``cache_dtype="int8"``, the production path). Reports tok/s per
    arm, the modeled bytes/token ×-factors from
    ``kernelbench.quant_decode_model`` (what the driver ledger
    predicts ahead of the TPU window), and — the quality claim —
    ``outputs_match`` plus the FIRST-DIVERGENCE token index per arm:
    quantization's greedy delta is measured, never asserted away.

    Builds its own DENSE model (the arms need fp weights to quantize
    from; the main bench model is meta-built at int8 already). On TPU
    it is depth-reduced so the bf16 arm fits HBM next to its KV pool —
    the tok/s ratios isolate byte-width, which is depth-independent."""
    from benchmarks.kernelbench import quant_decode_model
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    import paddle_tpu as pt
    from paddle_tpu.inference.serving import (
        ContinuousBatchingEngine,
        EngineConfig,
    )

    if tpu:
        mcfg = LlamaConfig(
            vocab_size=32000, hidden_size=4096,
            intermediate_size=11008, num_hidden_layers=4,
            num_attention_heads=32, num_key_value_heads=8,
            max_position_embeddings=2048, use_flash_attention=False,
            dtype="bfloat16")
        n_requests, new_tokens, max_chunk = 8, 48, 8
    else:
        # CPU smoke: contract validation (three arms run, divergence is
        # measured), not measurement — smallest config that still
        # exercises GQA + both quant paths keeps the bench suite's
        # tier-1 smoke cheap (compiles dominate at this size)
        mcfg = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=256,
            use_flash_attention=False)
        n_requests, new_tokens, max_chunk = 2, 8, 4
    pt.seed(0)
    model = LlamaForCausalLM(mcfg)
    if mcfg.dtype == "bfloat16":
        model.to(pt.bfloat16)
    model.eval()
    rng = np.random.default_rng(23)
    prompts = [rng.integers(0, mcfg.vocab_size,
                            (int(rng.integers(8, 24)),))
               for _ in range(n_requests)]

    arms = (("bf16", "bf16", base_ecfg.cache_dtype),
            ("int8_w", "int8", base_ecfg.cache_dtype),
            ("int8_w_int8_kv", "int8", "int8"))
    out = {"n_requests": n_requests, "new_tokens": new_tokens,
           "model_layers": mcfg.num_hidden_layers}
    outputs = {}
    for name, wdtype, cdtype in arms:
        ecfg = EngineConfig(
            max_slots=base_ecfg.max_slots, max_len=base_ecfg.max_len,
            seq_buckets=tuple(base_ecfg.seq_buckets),
            paged=base_ecfg.paged, page_size=base_ecfg.page_size,
            cache_dtype=cdtype, weight_dtype=wdtype)
        eng = ContinuousBatchingEngine(model, ecfg)
        eng.run([prompts[0]], max_new_tokens=2,
                max_chunk=max_chunk)  # compile outside the window
        eng._finished.clear()
        t0 = time.perf_counter()
        reqs = eng.run(prompts, new_tokens, max_chunk=max_chunk)
        wall = time.perf_counter() - t0
        toks = sum(len(r.output) for r in reqs)
        outputs[name] = [list(r.output) for r in reqs]
        out[name] = {"tokens_per_sec": round(toks / wall, 1),
                     "wall_s": round(wall, 3)}
        eng = None  # drop this arm's KV pool before the next builds

    def divergence(a, b):
        """First token index (in the concatenated stream order) where
        the arm diverges from the bf16 baseline; None if identical."""
        idx = 0
        for ra, rb in zip(a, b):
            for ta, tb in zip(ra, rb):
                if ta != tb:
                    return idx
                idx += 1
            if len(ra) != len(rb):
                return idx
        return None

    base = outputs["bf16"]
    for name in ("int8_w", "int8_w_int8_kv"):
        d = divergence(base, outputs[name])
        out[name]["outputs_match"] = d is None
        out[name]["first_divergence"] = d
    out["outputs_match"] = out["int8_w"]["outputs_match"] \
        and out["int8_w_int8_kv"]["outputs_match"]
    out["first_divergence"] = out["int8_w_int8_kv"]["first_divergence"]
    # the modeled prediction the ledger carries ahead of the TPU window
    out["modeled_int8_w_x"] = quant_decode_model(
        "int8", "bf16", 0.0)["modeled_speedup"]
    out["modeled_int8_w_int8_kv_x"] = quant_decode_model(
        "int8", "int8", 0.0)["modeled_speedup"]
    out["modeled_compound_x"] = quant_decode_model(
        "int8", "int8", 0.6)["modeled_speedup"]
    return out


def _step_breakdown_scenario(model, base_ecfg, tpu):
    """MEASURED-vs-MODELED per-program step breakdown — the scenario
    that lets every modeled serving claim be laid against real device
    time. Runs the engine with the per-program profiler ON (every
    dispatch sampled), seals the recompile watchdog after warmup, and
    reports one row per compiled program: measured device ms
    (block-until-ready on the program's own outputs) beside the
    kernelbench HBM floor for the decode-family programs (weight
    stream + fused attention traffic over peak HBM bandwidth). Runs on
    ANY backend — the CPU smoke exercises the whole measurement path;
    the TPU capture is where measured-vs-floor becomes a roofline
    claim. Zero post-seal recompiles is part of the row set (the
    runtime watchdog's production complement to the test-only
    compile-count guards)."""
    from benchmarks.devtime import peak_hbm_bandwidth
    from benchmarks.kernelbench import decode_hbm_bytes
    from paddle_tpu import flags as F
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    mcfg = model.config
    prompt_len = 48 if tpu else 10
    new_tokens = 48 if tpu else 8
    n_requests = base_ecfg.max_slots
    max_chunk = 8 if tpu else 4
    rng = np.random.default_rng(17)
    prompts = [rng.integers(0, mcfg.vocab_size, (prompt_len,))
               for _ in range(n_requests)]
    saved = {k: F.flag(k) for k in ("profile_programs",
                                    "profile_sample_every")}
    try:
        F.set_flags({"profile_programs": True,
                     "profile_sample_every": 1})
        eng = ContinuousBatchingEngine(model, base_ecfg)
        cache_bytes = jnp.dtype(eng.cache_dtype).itemsize
        int8_kv = eng.cache_dtype == jnp.int8
        # warmup compiles every program OUTSIDE the measured window,
        # then the watchdog seals: any further specialization is a
        # recompile and lands in the `recompiles` row below
        eng.run([prompts[0]], max_new_tokens=2, max_chunk=max_chunk)
        eng.seal_programs()
        eng.profile_window_reset()
        reqs = eng.run(prompts, max_new_tokens=new_tokens,
                       max_chunk=max_chunk)
        snap = eng.profile_snapshot()
        rec = eng.recompile_snapshot()
        hbm = eng.hbm_snapshot()
    finally:
        F.set_flags(saved)
        eng = None  # drop the KV pool before the main engine builds

    # modeled floors (pure python — ANY backend): one decode iteration
    # re-reads the full weight stream (the engine's REAL resident
    # weight/buffer bytes, quantization included) plus the fused
    # attention-stage traffic at the run's mid-measurement length
    bw = peak_hbm_bandwidth(jax.devices()[0])
    weight_bytes = sum(v for k, v in hbm.items()
                       if k.startswith("weights_"))
    lens = [prompt_len + new_tokens // 2] * base_ecfg.max_slots
    kvh = mcfg.num_key_value_heads
    group = mcfg.num_attention_heads // kvh
    kw = (dict(page_size=base_ecfg.page_size) if base_ecfg.paged
          else dict(max_len=base_ecfg.max_len))
    mode = "paged" if base_ecfg.paged else "contiguous"

    def attn_bytes(n_tok=1):
        # ONE parameterization of the traffic model: decode and the
        # [slots, K+1] verify floors differ only in token width
        return mcfg.num_hidden_layers * decode_hbm_bytes(
            mode, True, lens, kvh, group, mcfg.head_dim,
            cache_bytes=cache_bytes,
            cache_scale_bytes=4 if int8_kv else 0,
            act_bytes=2 if mcfg.dtype == "bfloat16" else 4,
            n_tokens=n_tok, **kw)

    attn = attn_bytes()
    floor_iter_ms = (weight_bytes + attn) / bw * 1e3
    floors = {
        "decode_step": floor_iter_ms,
        "decode_chunk": floor_iter_ms * max_chunk,
        "spec_verify": (weight_bytes
                        + attn_bytes(base_ecfg.spec_k + 1)) / bw * 1e3,
    }
    rows = []
    for program, st in sorted(snap.get("programs", {}).items()):
        row = {
            "program": program,
            "dispatches": st["dispatches"],
            "sampled": st["sampled"],
            "measured_p50_ms": (round(st["device_ms_p50"], 4)
                                if st["device_ms_p50"] is not None
                                else None),
            "measured_mean_ms": (round(st["device_ms_mean"], 4)
                                 if st["device_ms_mean"] is not None
                                 else None),
            "dispatch_mean_ms": (round(st["dispatch_ms_mean"], 4)
                                 if st["dispatch_ms_mean"] is not None
                                 else None),
        }
        if program in floors:
            row["modeled_floor_ms"] = round(floors[program], 4)
            row["floor_basis"] = ("(weights + fused-attn stream "
                                  "bytes) / peak HBM bw")
        row["kernel"] = "step_breakdown"
        print(json.dumps(row), flush=True)
        rows.append(row)
    return {
        "rows": rows,
        "tokens": sum(len(r.output) for r in reqs),
        "recompiles_post_seal": rec.get("recompiles", {}),
        "watchdog_sealed": rec.get("sealed", False),
        "weight_stream_bytes": int(weight_bytes),
        "attn_bytes_per_iter": int(attn),
        "peak_hbm_gbps": round(bw / 1e9, 1),
        "hbm": {k: int(v) for k, v in sorted(hbm.items())},
        "max_chunk": max_chunk,
        "measured_basis": ("block_until_ready on each program's own "
                           "outputs, every dispatch sampled "
                           "(profile_sample_every=1), warmup/compile "
                           "excluded via seal+window-reset"),
    }


def bench_serve7b(tpu_diags):
    """7B-class int8 weight-only decode through the paged continuous-
    batching engine — the first production-scale silicon path (VERDICT
    r4 next-#3; parity: phi weight_only_linear + masked_multihead
    serving). Reports decode tok/s (DEVICE-time basis), TTFT, and HBM
    residency."""
    import os

    from paddle_tpu.inference.serving import (
        ContinuousBatchingEngine,
        EngineConfig,
    )
    from paddle_tpu.models import LlamaConfig

    tpu = _platform() == "tpu"
    if tpu:
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=int(os.environ.get("BENCH_7B_HID", "4096")),
            intermediate_size=int(os.environ.get("BENCH_7B_INTER", "11008")),
            num_hidden_layers=int(os.environ.get("BENCH_7B_LAYERS", "32")),
            num_attention_heads=32, num_key_value_heads=32,
            max_position_embeddings=2048, use_flash_attention=False,
            dtype="bfloat16")
        slots, max_len, prompt_len = 8, 1024, 120
        measure_tokens, max_chunk = 128, 16
        cache_dtype = jnp.bfloat16
    else:
        cfg = LlamaConfig(
            vocab_size=512, hidden_size=256, intermediate_size=512,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=4, max_position_embeddings=256,
            use_flash_attention=False)
        slots, max_len, prompt_len = 2, 128, 12
        measure_tokens, max_chunk = 8, 4
        cache_dtype = jnp.float32

    wdtype = os.environ.get("BENCH_7B_WDTYPE", "int8")
    model = _build_7b_int8(cfg, group_size=128, weight_dtype=wdtype)
    # qweight BYTES on HBM (int4 packs two params/byte: shape is k//2)
    n_linear = sum(int(np.prod(b.shape))
                   for nm, b in model.named_buffers() if "qweight" in nm)
    n_dense = sum(int(np.prod(p.value.shape))
                  for nm, p in model.named_parameters())
    n_params = n_linear * (2 if wdtype == "int4" else 1) + n_dense

    ecfg = EngineConfig(
        max_slots=slots, max_len=max_len, seq_buckets=(128,),
        cache_dtype=cache_dtype, paged=True,
        page_size=64 if tpu else 32)
    # shared-prefix + spec-decode + goodput scenarios run BEFORE the
    # main engine exists: each builds its own engines (one per arm),
    # and two resident KV pools would double-book HBM on the 16 GB
    # target
    shared_prefix = _shared_prefix_scenario(model, ecfg, tpu)
    spec_ngram = _spec_ngram_scenario(model, ecfg, tpu)
    goodput = _goodput_scenario(model, ecfg, tpu)
    sched_ab = _sched_ab_scenario(model, ecfg, tpu)
    http_front_door = _http_overhead_scenario(model, ecfg, tpu)
    fault_recovery = _fault_recovery_scenario(model, ecfg, tpu)
    replica_failover = _replica_failover_scenario(model, ecfg, tpu)
    quant = _quant_scenario(ecfg, tpu)
    step_breakdown = _step_breakdown_scenario(model, ecfg, tpu)
    audit = _audit_scenario()
    eng = ContinuousBatchingEngine(model, ecfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,))
               for _ in range(slots)]

    # warmup / compile all programs
    eng.run([prompts[0]], max_new_tokens=2, max_chunk=max_chunk)

    # unloaded TTFT
    ttft = _run_load(eng, prompts[:1], 4, 1e-3, max_chunk)

    # steady-state decode: all slots resident, chunked decode measured
    # inside a profiler trace — tok/s comes from the DEVICE plane
    from benchmarks.devtime import traced_step_ms

    for p in prompts:
        eng.add_request(p, measure_tokens + 64)
    # admit everything + settle into pure decode
    eng.step_chunk(max_chunk)
    eng.step_chunk(max_chunk)

    n_chunks = max(2, measure_tokens // max_chunk)

    def one_chunk():
        # step_chunk syncs the chunk's tokens to the host itself; return
        # a live cache leaf so traced_step_ms's completion fetch also
        # rides the real output stream
        eng.step_chunk(max_chunk)
        leaf = (eng.layer_caches[0].k_pages if ecfg.paged
                else eng.caches[0][0])
        return leaf[0, 0]

    timing = traced_step_ms(one_chunk, n_steps=n_chunks)
    toks_per_chunk = slots * max_chunk
    decode_tps = toks_per_chunk / (timing.step_ms / 1e3)

    stats = {}
    try:
        stats = jax.devices()[0].memory_stats() or {}
    except Exception:
        pass
    hbm_gb = round(stats.get("bytes_in_use", 0) / 2**30, 2)
    peak_gb = round(stats.get("peak_bytes_in_use", 0) / 2**30, 2)

    extra = {
        "params": n_params,
        "shared_prefix": shared_prefix,
        "spec_ngram": spec_ngram,
        "goodput_under_slo": goodput,
        "sched_ab": sched_ab,
        "http_front_door": http_front_door,
        "fault_recovery": fault_recovery,
        "replica_failover": replica_failover,
        "quant": quant,
        "step_breakdown": step_breakdown,
        "audit": audit,
        "decode_attn_roofline": _decode_attn_roofline(
            cfg, ecfg, prompt_len + measure_tokens // 2,
            2 if cache_dtype == jnp.bfloat16 else 4),
        "qweight_hbm_bytes": n_linear,
        "dense_params": n_dense,
        "weight_dtype": wdtype,
        "compute_dtype": "bfloat16" if tpu else "float32",
        "slots": slots, "max_len": max_len,
        "prompt_len": prompt_len, "max_chunk": max_chunk,
        "paged": True, "page_size": ecfg.page_size,
        "device_chunk_ms": (round(timing.device_step_ms, 3)
                            if timing.device_step_ms else None),
        "wall_chunk_ms": round(timing.wall_step_ms, 3),
        "unloaded_ttft_ms": ttft["p50_ttft_ms"],
        "hbm_gb_in_use": hbm_gb, "hbm_gb_peak": peak_gb,
        "latency_basis": "decode tok/s from profiler device plane; "
                         "TTFT is client wall-clock incl. tunnel RTT",
        "platform": _platform(),
        "n_chips": len(jax.devices()),
    }
    if tpu_diags:
        extra["tpu_probe"] = tpu_diags
    if tpu and timing.device_step_ms is None:
        extra["error"] = ("profiler trace carried no device plane; "
                          "tunnel wall-clock refused as throughput basis")
        return {"metric": f"serve7b_{wdtype}_implausible", "value": 0.0,
                "unit": "error", "vs_baseline": 0.0, "extra": extra}
    # bandwidth plausibility: every decode ITERATION re-reads the int8
    # weights, and one chunk scans max_chunk iterations — the implied
    # streaming rate must stay under HBM bandwidth
    if tpu and timing.device_step_ms:
        from benchmarks.devtime import peak_hbm_bandwidth

        bw = (n_linear * float(max_chunk)) \
            / (timing.device_step_ms / 1e3)  # B/s
        hbm_peak = peak_hbm_bandwidth(jax.devices()[0])
        extra["weight_stream_gbps"] = round(bw / 1e9, 1)
        if bw > 1.25 * hbm_peak:
            extra["error"] = (
                f"implied weight streaming {bw / 1e9:.0f} GB/s exceeds "
                f"HBM bandwidth ({hbm_peak / 1e9:.0f} GB/s) — "
                "measurement artifact, refused")
            return {"metric": f"serve7b_{wdtype}_implausible",
                    "value": 0.0, "unit": "error", "vs_baseline": 0.0,
                    "extra": extra}
    name = (f"serve7b_{wdtype}_decode_tokens_per_sec" if tpu
            else "serve7b_smoke_decode_tokens_per_sec")
    return {"metric": name, "value": round(decode_tps, 1),
            "unit": "tokens/s", "vs_baseline": 1.0, "extra": extra}


_CONFIGS = {
    "moe": bench_moe,
    "vit": bench_vit,
    "unet": bench_unet,
    "mamba": bench_mamba,
    "infer": bench_infer,
    "serve7b": bench_serve7b,
}


def run_config(name, tpu_diags=None):
    if name not in _CONFIGS:
        raise ValueError(f"unknown config {name!r}; one of {list(_CONFIGS)}")
    return _CONFIGS[name](tpu_diags)
