"""Secondary benchmark configs from BASELINE.json: ERNIE-MoE, ViT-L,
SD-UNet, Mamba, and decode/TTFT inference.

Each ``run_config(name)`` returns the same one-line JSON dict shape as
the headline llama bench. Sizes scale by platform: real configs on TPU,
smoke configs on CPU (so the suite is runnable anywhere, rc=0 always).

Timing discipline (round 5): every THROUGHPUT number is derived from
profiler DEVICE time (``benchmarks/devtime.py``), never from wall clock
through the remote tunnel — wall clock produced 4 physically-impossible
numbers in round 4 (dispatch was measured, not execution). A hard
plausibility guard refuses any result whose computed FLOP/s exceeds 95%
of chip peak. Exception: ``bench_infer``'s TTFT is a client-observed
LATENCY, which is wall-clock by definition — in this sandbox it
includes the remote tunnel's per-dispatch RTT (~10-90ms), recorded in
the result's ``latency_basis`` note so the numbers aren't mistaken for
on-host serving latency.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.devtime import (
    check_plausible,
    compiled_flops,
    fetch_sync,
    traced_step_ms,
)


def _platform():
    return jax.devices()[0].platform


def _result(metric, value, unit, extra, tpu_diags):
    if tpu_diags:
        extra["tpu_probe"] = tpu_diags
    extra["platform"] = _platform()
    extra["n_chips"] = len(jax.devices())
    if extra.pop("implausible", False):
        # measurement artifact — refuse to report it as a result, but
        # keep the refused value for diagnosis (mirrors the headline)
        extra["refused_value"] = round(float(value), 2)
        return {
            "metric": metric + "_implausible",
            "value": 0.0,
            "unit": "error",
            "vs_baseline": 0.0,
            "extra": extra,
        }
    return {
        "metric": metric,
        "value": round(float(value), 2),
        "unit": unit,
        "vs_baseline": 1.0,
        "extra": extra,
    }


def _train_throughput(model, data, loss_fn=None, unit_count=0):
    """Shared train-step timing harness.

    -> (per-sec rate from DEVICE step time, extra-dict with
    device/wall step ms, XLA-cost-analysis FLOPs, mfu_est, and the
    plausibility verdict)."""
    import paddle_tpu as pt
    from paddle_tpu import distributed as dist, optimizer as opt
    from paddle_tpu.trainer import TrainStep

    mesh = dist.build_mesh(devices=jax.devices()[:1])
    ts = TrainStep(model, opt.AdamW(1e-4, multi_precision=False), mesh,
                   loss_fn=loss_fn)
    tpu = _platform() == "tpu"
    # warmup / compile, with a real completion fetch
    fetch_sync(ts.run(data))
    loss = ts.run(data)
    fetch_sync(loss)

    # phase 1: short trace to learn the true device step time
    timing = traced_step_ms(lambda: ts.run(data), n_steps=3)
    # phase 2: if the traced window is too short for stable numbers,
    # re-trace with enough steps for ~0.4s of device time
    if tpu and timing.device_step_ms and timing.device_step_ms * 3 < 200:
        n = min(100, max(5, int(400 / timing.device_step_ms)))
        timing = traced_step_ms(lambda: ts.run(data), n_steps=n)

    flops = compiled_flops(ts.lower(data))
    plaus = check_plausible(flops, timing.step_ms)
    if tpu and timing.device_step_ms is None:
        # no device plane in the trace: wall clock through the tunnel
        # is NOT an acceptable substitute — refuse rather than publish
        plaus = {"implausible": True, "mfu_est": None,
                 "reason": "profiler trace carried no device plane; "
                           "tunnel wall-clock refused as a throughput "
                           "basis"}

    rate = unit_count / (timing.step_ms / 1e3)
    extra = {
        "step_ms": round(timing.step_ms, 3),
        "device_step_ms": (round(timing.device_step_ms, 3)
                           if timing.device_step_ms else None),
        "wall_step_ms": round(timing.wall_step_ms, 3),
        "timed_steps": timing.n_steps,
        "flops_per_step": flops,
        "loss": float(loss),
        **plaus,
    }
    if timing.op_summary is not None and timing.op_summary.rows:
        total = timing.op_summary.total_ms
        extra["device_categories"] = {
            k: round(100.0 * v / total, 1)
            for k, v in timing.op_summary.by_category().items()}
    return rate, extra


def bench_moe(tpu_diags):
    import os

    import paddle_tpu as pt
    from paddle_tpu.models import ErnieMoEConfig, ErnieMoEForCausalLM

    tpu = _platform() == "tpu"
    # BENCH_MOE_DROPLESS=1 selects no-token-drop routing (grouped
    # matmul / EP all-to-all dispatch) instead of the capacity path
    dropless = os.environ.get("BENCH_MOE_DROPLESS", "0") == "1"
    cfg = (ErnieMoEConfig(
        vocab_size=32000, hidden_size=1024, num_hidden_layers=8,
        num_attention_heads=8, max_position_embeddings=1024,
        num_experts=8, moe_every=2, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, moe_dropless=dropless)
        if tpu else ErnieMoEConfig.tiny(
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            moe_dropless=dropless))
    batch, seq = (4, 1024) if tpu else (2, 128)
    pt.seed(0)
    model = ErnieMoEForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)))
    rate, extra = _train_throughput(
        model, {"input_ids": ids, "labels": ids}, unit_count=batch * seq)
    extra["experts"] = cfg.num_experts
    return _result("ernie_moe_train_tokens_per_sec", rate, "tokens/s",
                   extra, tpu_diags)


def bench_vit(tpu_diags):
    import paddle_tpu as pt
    from paddle_tpu.models import ViT, ViTConfig
    from paddle_tpu.nn import functional as F

    tpu = _platform() == "tpu"
    cfg = ViTConfig.vit_l() if tpu else ViTConfig.tiny()
    batch = 32 if tpu else 4
    pt.seed(0)
    model = ViT(cfg)
    imgs = jnp.asarray(np.random.default_rng(0).standard_normal(
        (batch, cfg.num_channels, cfg.image_size, cfg.image_size)),
        jnp.float32)
    labels = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.num_classes, (batch,)))

    def loss_fn(logits, label):
        return F.cross_entropy(logits, label).mean()

    rate, extra = _train_throughput(
        model, {"input": imgs, "label": labels}, loss_fn=loss_fn,
        unit_count=batch)
    return _result("vit_l_train_images_per_sec", rate, "images/s",
                   extra, tpu_diags)


def bench_unet(tpu_diags):
    import paddle_tpu as pt
    from paddle_tpu.models import UNet2DConditionModel, UNetConfig

    tpu = _platform() == "tpu"
    cfg = (UNetConfig(sample_size=32) if tpu
           else UNetConfig.tiny(sample_size=8))
    batch = 4 if tpu else 1
    pt.seed(0)
    model = UNet2DConditionModel(cfg)
    size = cfg.sample_size
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (batch, cfg.in_channels, size, size)), jnp.float32)
    t = jnp.asarray(np.random.default_rng(1).integers(0, 1000, (batch,)))
    ctx = jnp.asarray(np.random.default_rng(2).standard_normal(
        (batch, 77, cfg.cross_attention_dim)), jnp.float32)

    # adapter computing the denoising MSE (proxy for the ppdiffusers
    # training loss) so TrainStep's self-loss path applies
    from paddle_tpu.core.module import Layer

    class _Wrap(Layer):
        def __init__(self):
            super().__init__()
            self.unet = model

        def forward(self, sample, timestep, context, target):
            pred = self.unet(sample, timestep, context)
            return jnp.mean((pred - target) ** 2)

    wrap = _Wrap()
    data = {"sample": x, "timestep": t, "context": ctx, "target": x}
    rate, extra = _train_throughput(wrap, data, unit_count=batch)
    return _result("sd_unet_train_samples_per_sec", rate, "samples/s",
                   extra, tpu_diags)


def bench_mamba(tpu_diags):
    import paddle_tpu as pt
    from paddle_tpu.models import MambaConfig, MambaForCausalLM

    tpu = _platform() == "tpu"
    cfg = (MambaConfig(
        vocab_size=32000, hidden_size=768, num_hidden_layers=12,
        use_chunked_scan=True)
        if tpu else MambaConfig.tiny(use_chunked_scan=True, scan_chunk=32))
    batch, seq = (4, 1024) if tpu else (2, 64)
    pt.seed(0)
    model = MambaForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)))
    rate, extra = _train_throughput(
        model, {"input_ids": ids, "labels": ids}, unit_count=batch * seq)
    return _result("mamba_train_tokens_per_sec", rate, "tokens/s",
                   extra, tpu_diags)


def bench_infer(tpu_diags):
    """TTFT under steady arrival load (p50/p99) + decode tokens/sec on
    the flagship Llama — BASELINE's inference metric, measured the way a
    server sees it: requests arrive WHILE other sequences are decoding,
    and admission must not stall in-flight decode (serving.step_chunk's
    overlapped prefill)."""
    import paddle_tpu as pt
    from paddle_tpu.inference.serving import (
        ContinuousBatchingEngine,
        EngineConfig,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    tpu = _platform() == "tpu"
    cfg = (LlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_hidden_layers=16, num_attention_heads=8, num_key_value_heads=8,
        max_position_embeddings=2048, use_flash_attention=True,
        dtype="bfloat16")
        if tpu else LlamaConfig(
            vocab_size=512, hidden_size=256, intermediate_size=512,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=4, max_position_embeddings=512,
            use_flash_attention=False))
    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    if tpu:
        model.to(pt.bfloat16)

    prompt_len = 120
    new_tokens = 64 if tpu else 8
    n_requests = 24 if tpu else 6
    max_chunk = 8 if tpu else 4
    ecfg = EngineConfig(
        max_slots=8 if tpu else 2,
        max_len=512 if tpu else 256,
        seq_buckets=(128,),
        cache_dtype=jnp.bfloat16 if tpu else jnp.float32,
    )
    eng = ContinuousBatchingEngine(model, ecfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,))
               for _ in range(n_requests)]

    # warmup: compile prefill + chunk-decode programs; drop its record
    # (its TTFT is compile time, not serving time)
    eng.run([prompts[0]], max_new_tokens=2, max_chunk=max_chunk)
    eng._finished.clear()

    # steady arrival load: a new request lands every `gap` seconds while
    # earlier ones are still decoding. The calibration chunk (request 0)
    # is INSIDE the measured window so token counts and wall time match.
    # On TPU the gap is a FIXED design constant — a chunk-relative gap
    # self-scales the offered load with engine speed, which made TTFT
    # incomparable across rounds (a faster engine measured "worse").
    t_start = time.perf_counter()
    eng.add_request(prompts[0], new_tokens)
    eng.step_chunk(max_chunk)  # calibration chunk (CPU gap only)
    chunk_s = time.perf_counter() - t_start
    gap = 0.150 if tpu else max(chunk_s / 2, 1e-3)

    submitted = 1
    next_arrival = time.perf_counter() + gap
    while True:
        now = time.perf_counter()
        while submitted < n_requests and now >= next_arrival:
            eng.add_request(prompts[submitted], new_tokens)
            submitted += 1
            next_arrival += gap
            now = time.perf_counter()
        busy = eng.step_chunk(max_chunk)
        if submitted >= n_requests and not busy and not eng.active.any():
            break
    t_total = time.perf_counter() - t_start

    reqs = [eng._finished[r] for r in sorted(eng._finished)]
    ttfts = np.array([r.ttft_ms for r in reqs if r.ttft_ms is not None])
    total_toks = sum(len(r.output) for r in reqs)
    # service throughput over the whole load window (includes prefill
    # and arrival idle gaps — what the server delivers, not raw decode
    # speed; named accordingly)
    served_tps = total_toks / t_total
    # request 0 entered an empty engine: its TTFT is the unloaded
    # (prefill + admission) latency, vs the percentiles' under-load view
    r0 = min(eng._finished)
    unloaded = eng._finished[r0].ttft_ms
    return _result(
        "infer_p50_ttft_ms", float(np.percentile(ttfts, 50)), "ms",
        {"latency_basis": "client wall-clock incl. tunnel dispatch RTT",
         "p99_ttft_ms": round(float(np.percentile(ttfts, 99)), 2),
         "unloaded_ttft_ms": round(unloaded, 2) if unloaded else None,
         "served_tokens_per_sec": round(served_tps, 1),
         "n_requests": len(reqs), "prompt_len": prompt_len,
         "new_tokens": new_tokens, "arrival_gap_ms": round(gap * 1e3, 2),
         "slots": ecfg.max_slots}, tpu_diags)


_CONFIGS = {
    "moe": bench_moe,
    "vit": bench_vit,
    "unet": bench_unet,
    "mamba": bench_mamba,
    "infer": bench_infer,
}


def run_config(name, tpu_diags=None):
    if name not in _CONFIGS:
        raise ValueError(f"unknown config {name!r}; one of {list(_CONFIGS)}")
    return _CONFIGS[name](tpu_diags)
