"""Device-time-true benchmark timing.

Round-4 postmortem (VERDICT r4 "What's weak" #1): wall-clock through the
remote TPU tunnel is untrustworthy in BOTH directions —
``block_until_ready`` can return before execution finishes (measuring
dispatch, which produced 4 physically-impossible throughput numbers:
ViT-L at 9x chip peak), while an actual host value fetch pays an ~85ms
tunnel RTT per roundtrip (under-measuring short steps by 10-40x). The
only honest step time is the XLA profiler's device plane.

This module therefore derives every reported number from:

1. ``traced_step_ms`` — run N steps inside a ``jax.profiler`` trace,
   sync with a real host fetch (``jax.device_get``, which cannot return
   early: the bytes must exist), and read the device-plane op total from
   the xplane/chrome trace (``profiler/xplane.py``). Throughput =
   units / device_step_time.
2. ``compiled_flops`` — XLA's own ``cost_analysis()['flops']`` for the
   exact compiled program (includes remat re-forward FLOPs, attention,
   everything the 6*N*T estimate misses).
3. ``check_plausible`` — a hard guard: computed FLOP/s above 95% of the
   chip's spec-sheet peak is a measurement artifact by definition and
   MUST NOT be reported as a result (the reference's op-benchmark CI
   refuses regressions; ours first refuses impossibilities).

Parity: reference perf-gate tooling (upstream ``tools/`` op-benchmark
CI) + profiler statistics (``paddle/fluid/platform/profiler/``).
"""

from __future__ import annotations

import tempfile
from dataclasses import dataclass
from typing import Callable, Optional

import jax

from paddle_tpu.profiler import xplane

PEAK_BF16_FLOPS = {
    # device_kind -> peak bf16 FLOP/s per chip (public spec sheets)
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}

PEAK_HBM_BYTES = {
    # device_kind -> HBM bandwidth B/s per chip (public spec sheets)
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,    # v5e
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,        # v5p
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,   # v6e / Trillium
    "TPU v6e": 1640e9,
}

# computed-FLOP/s above this fraction of spec-sheet peak is treated as a
# measurement artifact, not a result
MFU_PLAUSIBILITY_CEILING = 0.95


def peak_flops(device=None) -> float:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for k, v in PEAK_BF16_FLOPS.items():
        if kind.startswith(k):
            return v
    return {"tpu": 197e12, "cpu": 1e12}.get(device.platform, 197e12)


def peak_hbm_bandwidth(device=None) -> float:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for k, v in PEAK_HBM_BYTES.items():
        if kind.startswith(k):
            return v
    return 819e9


def fetch_sync(x) -> None:
    """Force REAL completion of ``x``'s computation.

    ``block_until_ready`` can return early through the remote-device
    tunnel; transferring actual bytes to the host cannot — the values do
    not exist until the program ran."""
    jax.device_get(jax.tree_util.tree_leaves(x)[0])


@dataclass
class DeviceTiming:
    device_step_ms: Optional[float]   # None when trace has no device plane
    wall_step_ms: float
    n_steps: int
    op_summary: Optional[xplane.DeviceOpSummary]

    @property
    def step_ms(self) -> float:
        """Honest step time: device-plane time when available (TPU),
        wall time otherwise (CPU wall is not tunneled, hence honest)."""
        return (self.device_step_ms
                if self.device_step_ms else self.wall_step_ms)


def traced_step_ms(run_step: Callable[[], object], n_steps: int = 5,
                   trace_dir: Optional[str] = None) -> DeviceTiming:
    """Execute ``run_step`` n times inside a profiler trace; return the
    per-step device time from the trace's device plane.

    ``run_step`` must return a jax value (used for the completion
    fetch). Call sites should warm up/compile before calling this."""
    import time

    trace_dir = trace_dir or tempfile.mkdtemp(prefix="bench_trace_")
    t0 = time.perf_counter()
    jax.profiler.start_trace(trace_dir)
    try:
        out = None
        for _ in range(n_steps):
            out = run_step()
        fetch_sync(out)
    finally:
        jax.profiler.stop_trace()
    wall_ms = 1e3 * (time.perf_counter() - t0) / n_steps
    ops = xplane.device_op_summary(trace_dir)
    dev_ms = None
    if ops is not None and ops.rows:
        # total_ms sums ALL device planes; per-chip step time divides by
        # the plane count (SPMD: every chip runs the same step)
        dev_ms = ops.total_ms / n_steps / max(ops.n_planes, 1)
    return DeviceTiming(dev_ms, wall_ms, n_steps, ops)


def compiled_flops(lowered_or_jitted, *args, **kw) -> Optional[float]:
    """FLOPs of the compiled program via XLA cost analysis.

    Pass a ``jax.stages.Lowered`` (e.g. from ``TrainStep.lower()``,
    which lowers under the right mesh context), or a jitted callable
    plus its args — retracing cost only (compilation of an identical
    program hits the executable cache on most backends; worst case it
    recompiles once, which a benchmark can afford for an honest FLOPs
    denominator)."""
    try:
        lowered = (lowered_or_jitted if hasattr(lowered_or_jitted,
                                                "compile")
                   and not args and not kw
                   else lowered_or_jitted.lower(*args, **kw))
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = float(ca.get("flops", 0.0))
        return f if f > 0 else None
    except Exception:
        return None


def check_plausible(flops_per_step: Optional[float], step_ms: float,
                    device=None) -> dict:
    """-> {"mfu_est": float|None, "implausible": bool, "reason": str?}.

    A computed FLOP/s above MFU_PLAUSIBILITY_CEILING x peak means the
    timing is broken (dispatch measured instead of execution) — callers
    must refuse to report the number as a result."""
    if not flops_per_step or step_ms <= 0:
        return {"mfu_est": None, "implausible": False}
    peak = peak_flops(device)
    mfu = flops_per_step / (step_ms / 1e3) / peak
    out = {"mfu_est": round(mfu, 4)}
    if mfu > MFU_PLAUSIBILITY_CEILING:
        out["implausible"] = True
        out["reason"] = (
            f"computed {flops_per_step / (step_ms / 1e3) / 1e12:.1f} "
            f"TFLOP/s exceeds {MFU_PLAUSIBILITY_CEILING:.0%} of chip peak "
            f"({peak / 1e12:.0f} TFLOP/s) — measurement artifact, refused")
    else:
        out["implausible"] = False
    return out
