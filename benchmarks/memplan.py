"""AOT scale-proof for the SURVEY §6 north star (7B / 70B).

Parity: the memory-estimation + partitioning pass of the reference's
static auto-parallel engine (upstream:
python/paddle/distributed/auto_parallel/static/engine.py) — answer
"does this config FIT, with these shardings, before buying the pods?".

TPU-native design: build the model under ``core.meta.meta_init`` (zero
parameter bytes), construct the full sharded train step abstractly
(``TrainStep(abstract=True)`` / ``PipelineTrainStep(abstract=True)``),
AOT-lower and compile it on a *virtual* CPU mesh of the target size
(``--xla_force_host_platform_device_count``), and read the per-device
byte plan from ``compiled.memory_analysis()`` plus an analytic
per-parameter shard table. Catches vocab/optimizer replication blowups
that an 876M single-chip run never would.

Configs:
  7b  — Llama-2-7B,  8 devices,  ZeRO-3 x tp2 x sep2, seq 4096
  70b — Llama-3-70B, 128 devices, ZeRO-3(fsdp4) x tp8 x pp4 (1F1B),
        seq 8192
Both must fit v5p HBM (95 GB/chip) with bf16 params + fp32 master +
AdamW moments (~14 B/param total, sharded).

Usage:
  python benchmarks/memplan.py            # both, writes MEMPLAN.md
  python benchmarks/memplan.py 7b|70b     # one config, prints JSON
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys

V5P_HBM_BYTES = 95 * 1024**3

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _force_cpu(n_devices):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    assert len(jax.devices()) >= n_devices, (
        f"{len(jax.devices())} devices < {n_devices}"
    )


def _gb(x):
    return round(x / 1024**3, 3)


def _analytic_table(shardings, shapes_dtypes):
    """Per-device bytes per tensor from NamedSharding.shard_shape —
    the replication detector (a tensor whose per-device bytes equal its
    full bytes while axes were available is a blowup)."""
    import numpy as np

    rows = []
    for name, sh in shardings.items():
        shape, dtype = shapes_dtypes[name]
        full = int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
        per_dev = (int(np.prod(sh.shard_shape(tuple(shape))
                               or (1,))) * np.dtype(dtype).itemsize
                   if len(shape) else full)
        rows.append({"name": name, "shape": list(shape),
                     "dtype": str(np.dtype(dtype).name),
                     "full_mb": round(full / 2**20, 1),
                     "per_device_mb": round(per_dev / 2**20, 1),
                     "spec": str(sh.spec)})
    rows.sort(key=lambda r: -r["per_device_mb"])
    return rows


def plan_7b():
    _force_cpu(8)
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import distributed as dist, optimizer as opt
    from paddle_tpu.core.meta import meta_init
    from paddle_tpu.distributed.strategy import (
        DistributedStrategy,
        HybridConfig,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.trainer import TrainStep

    cfg = LlamaConfig.llama2_7b(
        max_position_embeddings=4096,
        use_flash_attention=False,   # CPU lowering; memory story identical
        use_recompute=True,
    )
    with meta_init():
        model = LlamaForCausalLM(cfg)
    model.to(pt.bfloat16)

    fsdp, tp, sep = 2, 2, 2
    mesh = dist.build_mesh(fsdp=fsdp, tp=tp, sep=sep)
    strategy = DistributedStrategy()
    strategy.hybrid_configs = HybridConfig(
        sharding_degree=fsdp, mp_degree=tp, sep_degree=sep)
    strategy.sharding = True
    strategy.sharding_configs.stage = 3

    optimizer = opt.AdamW(3e-4, weight_decay=0.01, multi_precision=True,
                          grad_clip=opt.ClipGradByGlobalNorm(1.0))
    ts = TrainStep(model, optimizer, mesh, strategy, abstract=True)

    batch, seq = 2, 4096
    ids = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    lowered = ts.lower({"input_ids": ids, "labels": ids})
    compiled = lowered.compile()
    ma = compiled.memory_analysis()

    shapes = {n: (tuple(v.shape), v.dtype) for n, v in ts.params.items()}
    table = _analytic_table(ts.param_shardings, shapes)
    n_params = sum(math.prod(v.shape or (1,)) for v in ts.params.values())
    return _report("7b", mesh, n_params, ma, table,
                   {"fsdp": fsdp, "tp": tp, "sep": sep},
                   batch=batch, seq=seq)


def plan_70b():
    _force_cpu(128)
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import distributed as dist, optimizer as opt
    from paddle_tpu.core.meta import meta_init
    from paddle_tpu.distributed.pipeline import PipelineTrainStep
    from paddle_tpu.distributed.strategy import (
        DistributedStrategy,
        HybridConfig,
    )
    from paddle_tpu.models import LlamaConfig
    from paddle_tpu.models.llama import llama_pipeline_module

    cfg = LlamaConfig.llama3_70b(
        max_position_embeddings=8192,
        use_flash_attention=False,
        use_recompute=True,
    )
    pp, tp, fsdp = 4, 8, 4
    n_micro = 8
    with meta_init():
        module = llama_pipeline_module(cfg, num_stages=pp)
    module.to(pt.bfloat16)

    mesh = dist.build_mesh(fsdp=fsdp, pp=pp, tp=tp)
    strategy = DistributedStrategy()
    strategy.hybrid_configs = HybridConfig(
        sharding_degree=fsdp, mp_degree=tp, pp_degree=pp)
    strategy.sharding = True
    strategy.sharding_configs.stage = 3
    strategy.pipeline = True
    strategy.pipeline_configs.schedule_mode = "1F1B"
    strategy.pipeline_configs.accumulate_steps = n_micro
    strategy.pipeline_configs.vpp_degree = 1
    strategy.recompute = True   # per-layer remat inside each stage chunk

    def loss_fn(logits, labels):
        return pt.nn.functional.cross_entropy(
            logits.reshape(-1, cfg.vocab_size), labels.reshape(-1)).mean()

    ts = PipelineTrainStep(
        module, opt.AdamW(3e-4, multi_precision=True), mesh, strategy,
        loss_fn, abstract=True)

    batch, seq = n_micro, 8192
    ids = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    lowered = ts.lower(ids, ids)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()

    shapes = {n: (tuple(v.shape), v.dtype) for n, v in ts.params.items()}
    table = _analytic_table(ts.param_shardings, shapes)
    n_params = sum(math.prod(v.shape or (1,)) for v in ts.params.values())
    return _report("70b", mesh, n_params, ma, table,
                   {"fsdp": fsdp, "tp": tp, "pp": pp,
                    "schedule": "1F1B", "n_micro": n_micro},
                   batch=batch, seq=seq)


def _report(name, mesh, n_params, ma, table, degrees, batch, seq):
    args_b = getattr(ma, "argument_size_in_bytes", 0)
    temp_b = getattr(ma, "temp_size_in_bytes", 0)
    out_b = getattr(ma, "output_size_in_bytes", 0)
    # donation aliases outputs onto arguments, so args+temp is the
    # resident plan; outputs reported for completeness
    per_dev = args_b + temp_b
    replicated_big = [r for r in table
                      if r["per_device_mb"] == r["full_mb"]
                      and r["full_mb"] > 64]
    return {
        "config": name,
        "n_devices": int(len(mesh.devices.flatten())),
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "degrees": degrees,
        "batch": batch, "seq": seq,
        "params_b": int(n_params),
        "xla_argument_gb_per_device": _gb(args_b),
        "xla_temp_gb_per_device": _gb(temp_b),
        "xla_output_gb_per_device": _gb(out_b),
        "resident_gb_per_device": _gb(per_dev),
        "hbm_budget_gb": _gb(V5P_HBM_BYTES),
        "fits_v5p": bool(per_dev < V5P_HBM_BYTES),
        "replicated_over_64mb": replicated_big,
        "top_tensors": table[:10],
    }


_PLANS = {"7b": (plan_7b, 8), "70b": (plan_70b, 128)}


def run_child(name):
    fn, _ = _PLANS[name]
    print(json.dumps(fn()))


def run_all():
    """Spawn one clean subprocess per config (each needs its own
    --xla_force_host_platform_device_count before backend init)."""
    results = {}
    for name, (_, n_dev) in _PLANS.items():
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), name],
            capture_output=True, text=True, timeout=3600, env=env,
            cwd=REPO,
        )
        lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
        if r.returncode != 0 or not lines:
            results[name] = {"config": name, "error": r.stderr[-2000:]}
        else:
            results[name] = json.loads(lines[-1])
    return results


def write_md(results, path=os.path.join(REPO, "MEMPLAN.md")):
    lines = [
        "# MEMPLAN — AOT scale-proof for the north star",
        "",
        "Generated by `python benchmarks/memplan.py` (see its docstring "
        "for method). The full sharded train step for each config is "
        "built abstractly (`core.meta.meta_init` + "
        "`TrainStep/PipelineTrainStep(abstract=True)`), AOT-compiled on "
        "a virtual CPU mesh of the target size, and the per-device plan "
        "read from `compiled.memory_analysis()`. No parameter memory is "
        "ever allocated; XLA's SPMD partitioner sees exactly the "
        "shardings the real run would use.",
        "",
        "Note: XLA:CPU reports temp (activation) bytes as 0; the "
        "argument column — params + optimizer state + batch, the "
        "dominant resident term under ZeRO-3 + remat — is exact. "
        "Re-running on a TPU backend adds the temp column.",
        "",
        "| config | devices | mesh | params | XLA args/dev | temp/dev | "
        "resident/dev | v5p budget | fits |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for name, r in results.items():
        if "error" in r:
            lines.append(f"| {name} | — | — | — | — | — | — | — | "
                         f"ERROR (see below) |")
            continue
        mesh = "x".join(f"{k}{v}" for k, v in r["degrees"].items()
                        if isinstance(v, int))
        lines.append(
            f"| {name} | {r['n_devices']} | {mesh} | "
            f"{r['params_b'] / 1e9:.2f}B | "
            f"{r['xla_argument_gb_per_device']} GB | "
            f"{r['xla_temp_gb_per_device']} GB | "
            f"{r['resident_gb_per_device']} GB | "
            f"{r['hbm_budget_gb']} GB | "
            f"{'YES' if r['fits_v5p'] else 'NO'} |")
    for name, r in results.items():
        lines += ["", f"## {name}", ""]
        if "error" in r:
            lines += ["```", r["error"], "```"]
            continue
        lines.append(f"batch={r['batch']} seq={r['seq']} "
                     f"degrees={r['degrees']}")
        lines.append("")
        if r["replicated_over_64mb"]:
            lines.append("**Replicated tensors > 64 MB (review!):**")
            for t in r["replicated_over_64mb"]:
                lines.append(f"- `{t['name']}` {t['shape']} "
                             f"{t['full_mb']} MB spec={t['spec']}")
        else:
            lines.append("No parameter > 64 MB is fully replicated.")
        lines += ["", "Top per-device tensors:", "",
                  "| tensor | shape | dtype | full MB | per-dev MB | "
                  "spec |", "|---|---|---|---|---|---|"]
        for t in r["top_tensors"]:
            lines.append(
                f"| `{t['name']}` | {t['shape']} | {t['dtype']} | "
                f"{t['full_mb']} | {t['per_device_mb']} | "
                f"`{t['spec']}` |")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


if __name__ == "__main__":
    if len(sys.argv) > 1:
        run_child(sys.argv[1])
    else:
        res = run_all()
        p = write_md(res)
        print(json.dumps({n: {k: v for k, v in r.items()
                              if k != "top_tensors"}
                          for n, r in res.items()}, indent=1))
        print("wrote", p)
