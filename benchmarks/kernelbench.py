"""Kernel-level roofline micro-bench for the Pallas flash attention.

Measures forward and forward+backward device time at the headline bench
shape and reports each against its FLOP roofline (chip peak), the
number VERDICT r4 item 4 asks to be tracked ("bwd kernel >= 45% of
roofline or a documented analysis").

FLOP accounting (causal): softmax(QK^T)V does 2 matmuls of
2*b*h*sq*sk*d FLOPs each, halved by causal masking. Backward does 5
tile-matmuls in the fused kernel (dv, dp, ds->dq, ds->dk, s recompute)
-> bwd FLOPs = 2.5x fwd. Elementwise VPU work is excluded from the
denominator, so the ratio is a true MXU roofline (VPU-bound kernels
show up as a low ratio, which is the point).

Usage: python benchmarks/kernelbench.py  (needs the real TPU; prints
one JSON line per shape).
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # fail fast WITHOUT importing jax: with the tunnel down, axon
        # plugin registration can hang the interpreter for minutes
        print(json.dumps({"error": "kernel roofline needs the TPU"}))
        return
    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.devtime import peak_flops, traced_step_ms
    from paddle_tpu.kernels.flash_attention import flash_attention

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(json.dumps({"error": "kernel roofline needs the TPU"}))
        return
    peak = peak_flops(dev)

    # headline bench shape + a long-seq point
    shapes = [
        # (batch, seq, heads, head_dim)
        (4, 2048, 24, 128),
        (1, 8192, 24, 128),
    ]
    rng = np.random.default_rng(0)
    for (b, s, h, d) in shapes:
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)

        fwd = jax.jit(functools.partial(flash_attention, causal=True))

        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()

        bwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        # warmup/compile
        jax.device_get(fwd(q, k, v))
        jax.device_get(jax.tree_util.tree_leaves(bwd(q, k, v))[0][0, 0])

        t_fwd = traced_step_ms(lambda: fwd(q, k, v), n_steps=10)
        t_bwd = traced_step_ms(lambda: bwd(q, k, v), n_steps=10)

        fwd_flops = 2 * 2 * b * h * s * s * d * 0.5  # causal
        # fused bwd: 5 tile matmuls vs fwd's 2 (incl. s recompute)
        bwd_flops = fwd_flops * 2.5
        fwd_ms = t_fwd.device_step_ms or t_fwd.step_ms
        tot_ms = t_bwd.device_step_ms or t_bwd.step_ms
        # grad-of-sum runs fwd (for residuals) + bwd kernels
        bwd_ms = max(tot_ms - fwd_ms, 1e-6)
        out = {
            "shape": f"b{b}xs{s}xh{h}xd{d}",
            "fwd_ms": round(fwd_ms, 3),
            "fwd_bwd_ms": round(tot_ms, 3),
            "bwd_ms_est": round(bwd_ms, 3),
            "fwd_roofline": round(fwd_flops / (fwd_ms / 1e3) / peak, 3),
            "bwd_roofline": round(bwd_flops / (bwd_ms / 1e3) / peak, 3),
            "peak_flops": peak,
        }
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
