"""Kernel-level roofline micro-benches: Pallas flash attention (FLOP
roofline) and fused GroupNorm+SiLU (HBM-bytes roofline).

Flash: forward and forward+backward device time at the headline bench
shape, each against the chip's FLOP peak — the number VERDICT r4 item 4
asks to be tracked ("bwd kernel >= 45% of roofline or a documented
analysis").

FLOP accounting (causal): softmax(QK^T)V does 2 matmuls of
2*b*h*sq*sk*d FLOPs each, halved by causal masking. Backward does 5
tile-matmuls in the fused kernel (dv, dp, ds->dq, ds->dk, s recompute)
-> bwd FLOPs = 2.5x fwd. Elementwise VPU work is excluded from the
denominator, so the ratio is a true MXU roofline (VPU-bound kernels
show up as a low ratio, which is the point).

GroupNorm: bandwidth-bound (O(1) FLOPs/byte), so its roofline is HBM
bytes over peak bandwidth — fwd moves 2 activation passes (1 read + 1
write), fwd+bwd 5. Each SD-UNet-representative NHWC shape reports the
fused kernel's achieved fraction of that floor, plus the unfused
XLA-native NCHW GroupNorm at the same shape as the A/B (what the fusion
+ layout policy actually buys).

Usage: python benchmarks/kernelbench.py  (needs the real TPU; prints
one JSON line per shape).
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # fail fast WITHOUT importing jax: with the tunnel down, axon
        # plugin registration can hang the interpreter for minutes
        print(json.dumps({"error": "kernel roofline needs the TPU"}))
        return
    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.devtime import peak_flops, traced_step_ms
    from paddle_tpu.kernels.flash_attention import flash_attention

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(json.dumps({"error": "kernel roofline needs the TPU"}))
        return
    peak = peak_flops(dev)

    # headline bench shape + a long-seq point
    shapes = [
        # (batch, seq, heads, head_dim)
        (4, 2048, 24, 128),
        (1, 8192, 24, 128),
    ]
    rng = np.random.default_rng(0)
    for (b, s, h, d) in shapes:
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)

        fwd = jax.jit(functools.partial(flash_attention, causal=True))

        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()

        bwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        # warmup/compile
        jax.device_get(fwd(q, k, v))
        jax.device_get(jax.tree_util.tree_leaves(bwd(q, k, v))[0][0, 0])

        t_fwd = traced_step_ms(lambda: fwd(q, k, v), n_steps=10)
        t_bwd = traced_step_ms(lambda: bwd(q, k, v), n_steps=10)

        fwd_flops = 2 * 2 * b * h * s * s * d * 0.5  # causal
        # fused bwd: 5 tile matmuls vs fwd's 2 (incl. s recompute)
        bwd_flops = fwd_flops * 2.5
        fwd_ms = t_fwd.device_step_ms or t_fwd.step_ms
        tot_ms = t_bwd.device_step_ms or t_bwd.step_ms
        # grad-of-sum runs fwd (for residuals) + bwd kernels
        bwd_ms = max(tot_ms - fwd_ms, 1e-6)
        out = {
            "kernel": "flash_attention",
            "shape": f"b{b}xs{s}xh{h}xd{d}",
            "fwd_ms": round(fwd_ms, 3),
            "fwd_bwd_ms": round(tot_ms, 3),
            "bwd_ms_est": round(bwd_ms, 3),
            "fwd_roofline": round(fwd_flops / (fwd_ms / 1e3) / peak, 3),
            "bwd_roofline": round(bwd_flops / (bwd_ms / 1e3) / peak, 3),
            "peak_flops": peak,
        }
        print(json.dumps(out), flush=True)

    groupnorm_bench()


def groupnorm_bench():
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.devtime import peak_hbm_bandwidth, traced_step_ms
    from paddle_tpu.kernels import group_norm as gn
    from paddle_tpu.nn import functional as F

    bw = peak_hbm_bandwidth(jax.devices()[0])
    eps = 1e-5
    # SD-UNet block shapes at the bench config (b4, sample 32): the
    # widest level-0 tensor and a deep narrow one
    shapes = [
        # (batch, h, w, channels, groups)
        (4, 32, 32, 320, 32),
        (4, 8, 8, 1280, 32),
    ]
    rng = np.random.default_rng(0)
    for (b, h, w, c, g) in shapes:
        x = jnp.asarray(rng.standard_normal((b, h, w, c)), jnp.bfloat16)
        gamma = jnp.asarray(rng.standard_normal(c), jnp.float32)
        beta = jnp.asarray(rng.standard_normal(c), jnp.float32)
        x_nchw = jnp.transpose(x, (0, 3, 1, 2))

        fused = jax.jit(functools.partial(
            gn.fused_group_norm, num_groups=g, epsilon=eps,
            activation="silu"))

        def fused_loss(x, ga, be):
            return gn.fused_group_norm(
                x, ga, be, g, eps, "silu").astype(jnp.float32).sum()

        def unfused_loss(x, ga, be):
            y = F.group_norm(x, g, ga, be, eps, "NCHW")
            return F.silu(y).astype(jnp.float32).sum()

        fused_bwd = jax.jit(jax.grad(fused_loss, argnums=(0, 1, 2)))
        unfused = jax.jit(
            lambda x, ga, be: F.silu(F.group_norm(x, g, ga, be, eps,
                                                  "NCHW")))
        unfused_bwd = jax.jit(jax.grad(unfused_loss, argnums=(0, 1, 2)))

        for f, args in ((fused, (x, gamma, beta)),
                        (fused_bwd, (x, gamma, beta)),
                        (unfused, (x_nchw, gamma, beta)),
                        (unfused_bwd, (x_nchw, gamma, beta))):
            jax.device_get(jax.tree_util.tree_leaves(f(*args))[0])

        t_f = traced_step_ms(lambda: fused(x, gamma, beta), n_steps=20)
        t_fb = traced_step_ms(lambda: fused_bwd(x, gamma, beta),
                              n_steps=20)
        t_u = traced_step_ms(lambda: unfused(x_nchw, gamma, beta),
                             n_steps=20)
        t_ub = traced_step_ms(lambda: unfused_bwd(x_nchw, gamma, beta),
                              n_steps=20)

        elems = b * h * w * c
        bpe = x.dtype.itemsize
        fwd_bytes = 2 * elems * bpe           # 1 read + 1 write
        fwd_bwd_bytes = 5 * elems * bpe       # + bwd: 2 reads + 1 write
        fwd_ms = t_f.device_step_ms or t_f.step_ms
        tot_ms = t_fb.device_step_ms or t_fb.step_ms
        out = {
            "kernel": "group_norm_silu",
            "shape": f"b{b}x{h}x{w}xc{c}g{g}",
            "fwd_ms": round(fwd_ms, 4),
            "fwd_bwd_ms": round(tot_ms, 4),
            "fwd_hbm_roofline": round(
                (fwd_bytes / (fwd_ms / 1e3)) / bw, 3),
            "fwd_bwd_hbm_roofline": round(
                (fwd_bwd_bytes / (tot_ms / 1e3)) / bw, 3),
            "unfused_nchw_fwd_ms": round(
                t_u.device_step_ms or t_u.step_ms, 4),
            "unfused_nchw_fwd_bwd_ms": round(
                t_ub.device_step_ms or t_ub.step_ms, 4),
            "peak_hbm_gbps": round(bw / 1e9, 1),
        }
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
