"""Kernel-level roofline micro-benches: Pallas flash attention (FLOP
roofline), fused GroupNorm+SiLU (HBM-bytes roofline) and fused decode
attention (HBM-bytes roofline, fused-vs-unfused A/B for both KV-cache
modes).

Flash: forward and forward+backward device time at the headline bench
shape, each against the chip's FLOP peak — the number VERDICT r4 item 4
asks to be tracked ("bwd kernel >= 45% of roofline or a documented
analysis").

FLOP accounting (causal): softmax(QK^T)V does 2 matmuls of
2*b*h*sq*sk*d FLOPs each, halved by causal masking. Backward does 5
tile-matmuls in the fused kernel (dv, dp, ds->dq, ds->dk, s recompute)
-> bwd FLOPs = 2.5x fwd. Elementwise VPU work is excluded from the
denominator, so the ratio is a true MXU roofline (VPU-bound kernels
show up as a low ratio, which is the point).

GroupNorm: bandwidth-bound (O(1) FLOPs/byte), so its roofline is HBM
bytes over peak bandwidth — fwd moves 2 activation passes (1 read + 1
write), fwd+bwd 5. Each SD-UNet-representative NHWC shape reports the
fused kernel's achieved fraction of that floor, plus the unfused
XLA-native NCHW GroupNorm at the same shape as the A/B (what the fusion
+ layout policy actually buys).

Usage: python benchmarks/kernelbench.py  (needs the real TPU; prints
one JSON line per shape).
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def decode_hbm_bytes(mode, fused, seq_lens, kvh, group, d,
                     page_size=None, max_len=None, cache_bytes=2,
                     act_bytes=2, n_tokens=1, cache_scale_bytes=0):
    """Modeled per-layer HBM bytes for one decode step's attention
    stage (RoPE + KV-append + attention over the cached KV) — the
    denominator of the decode roofline and the fused-vs-unfused A/B.

    Counts data crossing HBM↔VMEM (pure python, runs anywhere):
      - both paths read q/k_new/v_new once and write the attention out;
      - both write the new token's K/V row to the cache;
      - cache streaming: paged reads ceil((len+1)/page)·page rows per
        slot (length-pruned, both paths); contiguous FUSED reads
        ceil((len+1)/chunk)·chunk rows, contiguous UNFUSED reads the
        dense slots × max_len view (masked SDPA has no length pruning);
      - UNFUSED additionally materializes rotated q/k to HBM (the RoPE
        pass writes them, the append/attention programs re-read them) —
        the two activation round-trips in-kernel RoPE removes.

    ``n_tokens`` widens the pass to a MULTI-token step per slot — the
    spec-decode verify program's ``[slots, K+1]`` shape: activations,
    appends and the rope rows scale by it, and the cache stream rounds
    ``len + n_tokens`` up to the streaming granularity. The per-layer
    WEIGHT stream (the number spec decode amortizes) is not counted
    here — attention-stage traffic only, same as the n_tokens=1 rows.

    ``cache_bytes`` is the KV byte width (2 bf16, 1 int8);
    ``cache_scale_bytes`` adds the int8 pools' per-row f32 dequant
    scales (4): one scale per cached row per head streams with the K
    and V payloads, and each appended row writes one.
    """
    from paddle_tpu.kernels.decode_attention import contiguous_chunk

    slots = len(seq_lens)
    q_elems = slots * n_tokens * kvh * group * d
    kv_new_elems = slots * n_tokens * kvh * d
    total = (q_elems + 2 * kv_new_elems) * act_bytes   # q, k_new, v_new
    total += q_elems * act_bytes                       # out write
    total += 2 * kv_new_elems * cache_bytes            # append row write
    total += slots * n_tokens * d * 4                  # cos+sin rows
    if mode == "paged":
        gran = page_size
    elif mode == "contiguous":
        gran = contiguous_chunk(max_len) if fused else None
    else:
        raise ValueError(f"unknown cache mode {mode!r}")
    if gran is not None:
        rows = sum(-(-(int(n) + n_tokens) // gran) * gran
                   for n in seq_lens)
    else:
        rows = slots * max_len
    total += 2 * rows * kvh * d * cache_bytes          # K+V stream
    if cache_scale_bytes:
        # int8 pools: per-row scales stream with the payload and one
        # scale row is written per appended row (K and V each)
        total += 2 * rows * kvh * cache_scale_bytes
        total += 2 * kv_new_elems // d * cache_scale_bytes
    if not fused:
        # rope materialization round-trip: write q_rot+k_rot, re-read
        total += 2 * (q_elems + kv_new_elems) * act_bytes
    return total


def prefill_flops(n_tokens, ctx_len, hidden, inter, n_layers, vocab):
    """Modeled MXU FLOPs for a prefill pass computing ``n_tokens``
    rows attending over ``ctx_len`` context (pure python, runs
    anywhere): per-layer qkvo + gated-MLP matmuls per row, QK^T + PV
    attention per row × context, plus the lm head. GQA's smaller kv
    projections and causal halving are ignored — the A/B compares
    admission SCHEMES, and both sides share the constants."""
    lin = 2 * (4 * hidden * hidden + 3 * hidden * inter) * n_tokens
    attn = 2 * 2 * n_tokens * ctx_len * hidden
    head = 2 * n_tokens * hidden * vocab
    return n_layers * (lin + attn) + head


def prefill_admission_flops(prompt_len, prefix_len, chunk, buckets,
                            hidden=4096, inter=11008, n_layers=32,
                            vocab=32000, max_len=None):
    """Modeled prefill cost of one request under the three admission
    schemes — the shared-prefix A/B:

      - ``legacy_flops``: per-bucket prefill pads the prompt up to its
        seq bucket (a 260-token prompt pays a 512-token forward); a
        prompt past the largest bucket pays ``max_len``, the engine's
        ``_bucket`` fallback. When ``max_len`` is omitted the model
        assumes the largest bucket IS max_len (the engine's normalized
        bucket table never exceeds it);
      - ``chunked_flops``: single-program chunked prefill computes the
        prompt rounded up to the chunk;
      - ``chunked_prefix_flops``: prefix-cache hit computes only the
        SUFFIX rounded up to the chunk — cost ∝ suffix length, not
        bucket or prompt length.

    This is the MARGINAL cost of the request's own rows — what an
    admission wave pays per request when its chunks pack with other
    requests'. A lone request in the fixed ``[slots, chunk]`` program
    additionally pays the idle slots' sentinel rows (same trade as the
    engine's fixed-shape decode program), which packing amortizes away.
    """
    import bisect

    bs = sorted(buckets)
    i = bisect.bisect_left(bs, prompt_len)
    bucket = bs[i] if i < len(bs) else (max_len or bs[-1])
    dims = (hidden, inter, n_layers, vocab)
    suffix = max(prompt_len - prefix_len, 1)
    rows_full = -(-prompt_len // chunk) * chunk
    rows_suffix = -(-suffix // chunk) * chunk
    return {
        "prompt_len": prompt_len,
        "prefix_len": prefix_len,
        "bucket": bucket,
        "chunk": chunk,
        "legacy_flops": prefill_flops(bucket, bucket, *dims),
        "chunked_flops": prefill_flops(rows_full, prompt_len, *dims),
        "chunked_prefix_flops": prefill_flops(rows_suffix, prompt_len,
                                              *dims),
    }


def prefill_cost_ab():
    """Print the modeled prefill-admission A/B at serve7b-class shapes
    (pure cost model — runs on any backend): one JSON line per
    (prompt_len, prefix_len) point, mirroring the groupnorm/decode
    rows' format."""
    points = [
        # (prompt_len, prefix_len): cold, warm system prompt, few-shot
        (260, 0), (260, 256), (1500, 0), (1500, 1280), (700, 512),
    ]
    for prompt_len, prefix_len in points:
        row = prefill_admission_flops(
            prompt_len, prefix_len, chunk=256,
            buckets=(128, 256, 512, 1024, 2048))
        row["kernel"] = "prefill_admission_model"
        print(json.dumps(row), flush=True)


def llama7b_weight_stream_bytes(weight_dtype="int8", group_size=128,
                                kvh=8, d=128, hidden=4096, inter=11008,
                                n_layers=32, vocab=32000):
    """Modeled HBM bytes of ONE full weight stream at the serve7b
    shape — the quantity EVERY decode pass re-reads, and what
    weight-only quantization shrinks. Linears (qkvo with GQA-sized kv,
    gated MLP, lm head) carry the chosen byte width plus group-wise
    f32 scales (params/group_size × 4, int8/int4). The embedding is
    NOT in the stream — decode reads one table row per token, not the
    table (it is reported separately for residency accounting). Pure
    python — runs anywhere."""
    linear = n_layers * (2 * hidden * hidden + 2 * hidden * kvh * d
                         + 3 * hidden * inter) + hidden * vocab
    dense = hidden * vocab  # embedding (HBM residency, not stream)
    widths = {"bf16": 2.0, "bfloat16": 2.0, "int8": 1.0, "int4": 0.5}
    if weight_dtype not in widths:
        raise ValueError(f"unknown weight_dtype {weight_dtype!r}")
    payload = linear * widths[weight_dtype]
    scales = (0 if weight_dtype in ("bf16", "bfloat16")
              else linear // group_size * 4)
    return {
        "weight_dtype": weight_dtype,
        "group_size": group_size,
        "linear_params": int(linear),
        "embed_params": int(dense),
        "stream_bytes": int(payload + scales),
        "scale_bytes": int(scales),
    }


def quant_decode_model(weight_dtype="int8", kv_dtype="bf16",
                       accept_rate=0.0, k=4, kvh=8, heads=32, d=128,
                       n_layers=32, group_size=128, seq_len=512,
                       slots=8, page_size=64):
    """THE compound quantized-serving model: bytes/token for a
    (weight dtype × KV dtype × spec-decode acceptance) serving config
    vs the bf16-weights / bf16-KV / no-spec baseline — pure python,
    runs on any backend. Weight and KV byte-widths multiply with spec
    decode's tokens-per-weight-stream, which is why int8-W alone
    models ~1.9× and int8-W × int8-KV × acceptance 0.6 models ~4.6×
    over plain bf16 decode."""
    group = heads // kvh
    lens = [seq_len] * slots
    kv_bytes = {"bf16": 2, "bfloat16": 2, "fp16": 2, "int8": 1,
                "fp32": 4, "float32": 4}[kv_dtype]
    scale_b = 4 if kv_dtype == "int8" else 0
    base_w = llama7b_weight_stream_bytes(
        "bf16", group_size, kvh=kvh, d=d, n_layers=n_layers)
    quant_w = llama7b_weight_stream_bytes(
        weight_dtype, group_size, kvh=kvh, d=d, n_layers=n_layers)
    attn_base = n_layers * decode_hbm_bytes(
        "paged", True, lens, kvh, group, d, page_size=page_size,
        cache_bytes=2)
    n_tok = (k + 1) if accept_rate > 0 else 1
    attn = n_layers * decode_hbm_bytes(
        "paged", True, lens, kvh, group, d, page_size=page_size,
        cache_bytes=kv_bytes, cache_scale_bytes=scale_b,
        n_tokens=n_tok)
    exp_tokens = (1.0 + sum(accept_rate ** j for j in range(1, k + 1))
                  if accept_rate > 0 else 1.0)
    base_bpt = (base_w["stream_bytes"] + attn_base) / slots
    bpt = (quant_w["stream_bytes"] + attn) / slots / exp_tokens
    return {
        "weight_dtype": weight_dtype,
        "kv_dtype": kv_dtype,
        "accept_rate": accept_rate,
        "k": k,
        "kvh": kvh,
        "group_size": group_size,
        "seq_len": seq_len,
        "slots": slots,
        "weight_stream_bytes": quant_w["stream_bytes"],
        "attn_bytes_per_pass": int(attn),
        "tokens_per_weight_stream": round(exp_tokens, 3),
        "bytes_per_token": int(bpt),
        "baseline_bf16_bytes_per_token": int(base_bpt),
        "modeled_speedup": round(base_bpt / bpt, 3),
    }


def quant_cost_ab():
    """Print the modeled quantized-serving rows (pure cost models —
    runs on ANY backend, ahead of the TPU guard): the weight-only
    stream micro A/B at int8/int4 × group 64/128, and the compound
    decode model (weight dtype × KV dtype × spec acceptance) whose
    int8-W and int8-W×0.6-acceptance rows are the driver-ledger
    prediction for the next TPU window."""
    for wd in ("int8", "int4"):
        for g in (64, 128):
            row = llama7b_weight_stream_bytes(wd, group_size=g)
            row["kernel"] = "weight_only_stream_model"
            row["vs_bf16_x"] = round(
                llama7b_weight_stream_bytes("bf16")["stream_bytes"]
                / row["stream_bytes"], 3)
            print(json.dumps(row), flush=True)
    for wd, kv, a in (("int8", "bf16", 0.0), ("int4", "bf16", 0.0),
                      ("int8", "int8", 0.0), ("int8", "int8", 0.6),
                      ("int4", "int8", 0.6)):
        row = quant_decode_model(wd, kv, accept_rate=a)
        row["kernel"] = "quant_decode_model"
        print(json.dumps(row), flush=True)


def spec_decode_model(accept_rate, k, kvh, heads=32, d=128, n_layers=32,
                      weight_bytes=None, seq_len=512, slots=8,
                      page_size=64, cache_bytes=2, weight_byte_width=1,
                      cache_scale_bytes=0):
    """Modeled tokens-per-weight-stream A/B: plain decode vs
    speculative decoding at a given per-draft acceptance rate (pure
    python, runs anywhere).

    Decode throughput is pinned by the per-pass HBM stream: every
    forward pass re-reads ALL model weights plus the attention-stage
    traffic. Plain decode buys 1 token per pass. A verify pass over K
    drafts buys ``1 + Σ_{j=1..K} a^j`` expected tokens (greedy
    acceptance is a PREFIX rule — draft j only counts if every earlier
    draft matched, so independent per-draft acceptance ``a`` compounds
    geometrically) while paying the same weight stream once and a
    modestly wider attention stage (``decode_hbm_bytes`` at
    ``n_tokens = K+1``). The n-gram drafter itself is host-side — zero
    device bytes. ``modeled_speedup`` is the bytes-per-token ratio;
    GQA (kvh) moves it by shrinking the attention share of the stream.
    """
    group = heads // kvh
    lens = [seq_len] * slots
    if weight_bytes is None:
        # serve7b-class weight-only stream: qkvo (GQA-sized kv)
        # + gated MLP per layer + the lm head, ``weight_byte_width``
        # bytes/param (1 = int8, the historical default; 2 = bf16,
        # 0.5 = packed int4)
        hidden, inter, vocab = 4096, 11008, 32000
        weight_bytes = (n_layers * (
            2 * hidden * hidden + 2 * hidden * kvh * d
            + 3 * hidden * inter) + hidden * vocab) * weight_byte_width
    kw = dict(page_size=page_size, cache_bytes=cache_bytes,
              cache_scale_bytes=cache_scale_bytes)
    attn_plain = n_layers * decode_hbm_bytes(
        "paged", True, lens, kvh, group, d, **kw)
    attn_verify = n_layers * decode_hbm_bytes(
        "paged", True, lens, kvh, group, d, n_tokens=k + 1, **kw)
    exp_tokens = 1.0 + sum(accept_rate ** j for j in range(1, k + 1))
    plain_bytes_per_tok = (weight_bytes + attn_plain) / slots
    spec_bytes_per_tok = (weight_bytes + attn_verify) / slots \
        / exp_tokens
    return {
        "accept_rate": accept_rate,
        "k": k,
        "kvh": kvh,
        "seq_len": seq_len,
        "slots": slots,
        "tokens_per_weight_stream": round(exp_tokens, 3),
        "weight_bytes": int(weight_bytes),
        "attn_bytes_plain": int(attn_plain),
        "attn_bytes_verify": int(attn_verify),
        "plain_bytes_per_token": int(plain_bytes_per_tok),
        "spec_bytes_per_token": int(spec_bytes_per_tok),
        "modeled_speedup": round(
            plain_bytes_per_tok / spec_bytes_per_tok, 3),
    }


def spec_decode_cost_ab():
    """Print the modeled spec-decode A/B at the serve7b decode shape
    (pure cost model — runs on any backend): one JSON line per
    (acceptance rate, GQA ratio) point, mirroring the prefill/decode
    rows' format. 0.3 ~ adversarial traffic, 0.6 ~ mixed, 0.9 ~
    repetitive (code/JSON/templated) — the regime the n-gram drafter
    targets."""
    for kvh in (1, 4, 8):
        for a in (0.3, 0.6, 0.9):
            row = spec_decode_model(a, k=4, kvh=kvh)
            row["kernel"] = "spec_decode_model"
            print(json.dumps(row), flush=True)


def decode_bench():
    """Fused single-pass decode attention vs the unfused reference
    (rope → append → attention), both cache modes, at the serve7b-class
    decode shape across GQA ratios — prints one JSON line per config
    with measured ms, modeled HBM bytes and the achieved fraction of
    the HBM roofline."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.devtime import peak_hbm_bandwidth, traced_step_ms
    from paddle_tpu.inference.paged import (
        PagedLayerCache,
        PagedState,
        append_kv,
        paged_attention,
    )
    from paddle_tpu.kernels import decode_attention as da
    from paddle_tpu.kernels.paged_attention import (
        fused_paged_decode_attention,
    )
    from paddle_tpu.kernels.rope import rope_frequencies

    bw = peak_hbm_bandwidth(jax.devices()[0])
    slots, heads, d = 8, 32, 128
    page_size, max_len = 64, 1024
    cdt = jnp.bfloat16
    rng = np.random.default_rng(0)
    lens = np.array([937, 512, 768, 120, 240, 64, 1000, 333], np.int32)
    cos, sin = rope_frequencies(d, max_len + 1)

    for kvh in (1, 4, 8):
        group = heads // kvh
        q = jnp.asarray(
            rng.standard_normal((slots, kvh, group, d)), jnp.bfloat16)
        kn = jnp.asarray(rng.standard_normal((slots, kvh, d)), jnp.bfloat16)
        vn = jnp.asarray(rng.standard_normal((slots, kvh, d)), jnp.bfloat16)
        lens_j = jnp.asarray(lens)

        def measure(label, f, k0, v0, bytes_):
            # one measured A/B row: time f while threading the donated
            # cache buffers through, emit the JSON line, hand the live
            # buffers back for the next variant
            buf = {"k": k0, "v": v0}

            def step():
                out, k2, v2 = f(q, kn, vn, buf["k"], buf["v"])
                buf["k"], buf["v"] = k2, v2
                return out

            jax.device_get(step())
            t = traced_step_ms(step, n_steps=20)
            ms = t.device_step_ms or t.step_ms
            print(json.dumps({
                "kernel": label,
                "shape": f"s{slots}xh{heads}xkvh{kvh}xd{d}",
                "ms": round(ms, 4),
                "modeled_hbm_bytes": bytes_,
                "hbm_roofline": round((bytes_ / (ms / 1e3)) / bw, 3),
                "peak_hbm_gbps": round(bw / 1e9, 1),
            }), flush=True)
            return buf["k"], buf["v"]

        # ---- paged ----
        n_pages = slots * (max_len // page_size) + 1
        kp = jnp.asarray(
            rng.standard_normal((kvh, n_pages, page_size, d)), cdt)
        vp = jnp.asarray(
            rng.standard_normal((kvh, n_pages, page_size, d)), cdt)
        bt = jnp.asarray(
            1 + np.arange(slots * (max_len // page_size)).reshape(
                slots, -1), jnp.int32)

        # caches are DONATED (as the engine's decode does): without
        # donation the aliased in-place append degrades to a full-pool
        # copy per step, which would swamp the traffic being measured
        fused_p = jax.jit(lambda q, kn, vn, kp, vp: (
            fused_paged_decode_attention(
                q, kn, vn, kp, vp, bt, lens_j, lens_j, cos, sin)),
            donate_argnums=(3, 4))

        def unfused_p(q, kn, vn, kp, vp):
            qr, kr = _rope_one(q, kn, lens_j, cos, sin)
            cache = PagedLayerCache(kp, vp)
            state = PagedState(bt, lens_j)
            cache = append_kv(cache, state, kr[:, None], vn[:, None])
            out = paged_attention(
                qr.reshape(slots, 1, heads, d), cache, state)
            return out, cache.k_pages, cache.v_pages
        unfused_p = jax.jit(unfused_p, donate_argnums=(3, 4))

        for name, f, fused in (("fused", fused_p, True),
                               ("unfused", unfused_p, False)):
            kp, vp = measure(
                f"decode_attn_paged_{name}", f, kp, vp,
                decode_hbm_bytes("paged", fused, lens, kvh, group, d,
                                 page_size=page_size, cache_bytes=2,
                                 act_bytes=2))

        # ---- contiguous ----
        ck = jnp.asarray(
            rng.standard_normal((slots, max_len, kvh, d)), cdt)
        cv = jnp.asarray(
            rng.standard_normal((slots, max_len, kvh, d)), cdt)
        fused_c = jax.jit(lambda q, kn, vn, ck, cv: (
            da.fused_contiguous_decode_attention(
                q, kn, vn, ck, cv, lens_j, lens_j, cos, sin)),
            donate_argnums=(3, 4))

        def unfused_c(q, kn, vn, ck, cv):
            # the PRE-FUSION engine path (models/llama.py per-slot
            # branch), not the f32 repeat-materializing parity oracle:
            # rope → row scatter → masked SDPA over the kvh-head cache —
            # the traffic decode_hbm_bytes prices for the unfused side
            from paddle_tpu.nn import functional as F

            qr, kr = _rope_one(q, kn, lens_j, cos, sin)
            ck = ck.at[jnp.arange(slots), lens_j].set(
                kr.astype(ck.dtype))
            cv = cv.at[jnp.arange(slots), lens_j].set(
                vn.astype(cv.dtype))
            mask = (jnp.arange(max_len)[None, :] <=
                    lens_j[:, None])[:, None, None, :]
            out = F.scaled_dot_product_attention(
                qr.reshape(slots, 1, heads, d), ck, cv,
                attn_mask=mask, training=False)
            return out, ck, cv
        unfused_c = jax.jit(unfused_c, donate_argnums=(3, 4))
        for name, f, fused in (("fused", fused_c, True),
                               ("unfused", unfused_c, False)):
            ck, cv = measure(
                f"decode_attn_contig_{name}", f, ck, cv,
                decode_hbm_bytes("contiguous", fused, lens, kvh, group,
                                 d, max_len=max_len, cache_bytes=2,
                                 act_bytes=2))


def _rope_one(q, k_new, positions, cos, sin):
    """Unfused-path rope for the A/B: one token per slot, via the same
    helper the parity oracle uses (kernels/decode_attention)."""
    from paddle_tpu.kernels.decode_attention import _rope_rotate

    slots, kvh, group, d = q.shape
    return (_rope_rotate(q.reshape(slots, kvh * group, d), positions,
                         cos, sin),
            _rope_rotate(k_new, positions, cos, sin))


def main():
    # the modeled prefill + spec-decode + quantized-serving A/Bs are
    # pure Python — emit them on ANY backend, before the TPU-only
    # guards (they are the only output a CPU/GPU host gets from this
    # CLI)
    prefill_cost_ab()
    spec_decode_cost_ab()
    quant_cost_ab()
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # fail fast WITHOUT importing jax: with the tunnel down, axon
        # plugin registration can hang the interpreter for minutes
        print(json.dumps({"error": "kernel roofline needs the TPU"}))
        return
    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.devtime import peak_flops, traced_step_ms
    from paddle_tpu.kernels.flash_attention import flash_attention

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(json.dumps({"error": "kernel roofline needs the TPU"}))
        return
    peak = peak_flops(dev)

    # headline bench shape + a long-seq point
    shapes = [
        # (batch, seq, heads, head_dim)
        (4, 2048, 24, 128),
        (1, 8192, 24, 128),
    ]
    rng = np.random.default_rng(0)
    for (b, s, h, d) in shapes:
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)

        fwd = jax.jit(functools.partial(flash_attention, causal=True))

        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()

        bwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        # warmup/compile
        jax.device_get(fwd(q, k, v))
        jax.device_get(jax.tree_util.tree_leaves(bwd(q, k, v))[0][0, 0])

        t_fwd = traced_step_ms(lambda: fwd(q, k, v), n_steps=10)
        t_bwd = traced_step_ms(lambda: bwd(q, k, v), n_steps=10)

        fwd_flops = 2 * 2 * b * h * s * s * d * 0.5  # causal
        # fused bwd: 5 tile matmuls vs fwd's 2 (incl. s recompute)
        bwd_flops = fwd_flops * 2.5
        fwd_ms = t_fwd.device_step_ms or t_fwd.step_ms
        tot_ms = t_bwd.device_step_ms or t_bwd.step_ms
        # grad-of-sum runs fwd (for residuals) + bwd kernels
        bwd_ms = max(tot_ms - fwd_ms, 1e-6)
        out = {
            "kernel": "flash_attention",
            "shape": f"b{b}xs{s}xh{h}xd{d}",
            "fwd_ms": round(fwd_ms, 3),
            "fwd_bwd_ms": round(tot_ms, 3),
            "bwd_ms_est": round(bwd_ms, 3),
            "fwd_roofline": round(fwd_flops / (fwd_ms / 1e3) / peak, 3),
            "bwd_roofline": round(bwd_flops / (bwd_ms / 1e3) / peak, 3),
            "peak_flops": peak,
        }
        print(json.dumps(out), flush=True)

    groupnorm_bench()
    decode_bench()


def groupnorm_bench():
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.devtime import peak_hbm_bandwidth, traced_step_ms
    from paddle_tpu.kernels import group_norm as gn
    from paddle_tpu.nn import functional as F

    bw = peak_hbm_bandwidth(jax.devices()[0])
    eps = 1e-5
    # SD-UNet block shapes at the bench config (b4, sample 32): the
    # widest level-0 tensor and a deep narrow one
    shapes = [
        # (batch, h, w, channels, groups)
        (4, 32, 32, 320, 32),
        (4, 8, 8, 1280, 32),
    ]
    rng = np.random.default_rng(0)
    for (b, h, w, c, g) in shapes:
        x = jnp.asarray(rng.standard_normal((b, h, w, c)), jnp.bfloat16)
        gamma = jnp.asarray(rng.standard_normal(c), jnp.float32)
        beta = jnp.asarray(rng.standard_normal(c), jnp.float32)
        x_nchw = jnp.transpose(x, (0, 3, 1, 2))

        fused = jax.jit(functools.partial(
            gn.fused_group_norm, num_groups=g, epsilon=eps,
            activation="silu"))

        def fused_loss(x, ga, be):
            return gn.fused_group_norm(
                x, ga, be, g, eps, "silu").astype(jnp.float32).sum()

        def unfused_loss(x, ga, be):
            y = F.group_norm(x, g, ga, be, eps, "NCHW")
            return F.silu(y).astype(jnp.float32).sum()

        fused_bwd = jax.jit(jax.grad(fused_loss, argnums=(0, 1, 2)))
        unfused = jax.jit(
            lambda x, ga, be: F.silu(F.group_norm(x, g, ga, be, eps,
                                                  "NCHW")))
        unfused_bwd = jax.jit(jax.grad(unfused_loss, argnums=(0, 1, 2)))

        for f, args in ((fused, (x, gamma, beta)),
                        (fused_bwd, (x, gamma, beta)),
                        (unfused, (x_nchw, gamma, beta)),
                        (unfused_bwd, (x_nchw, gamma, beta))):
            jax.device_get(jax.tree_util.tree_leaves(f(*args))[0])

        t_f = traced_step_ms(lambda: fused(x, gamma, beta), n_steps=20)
        t_fb = traced_step_ms(lambda: fused_bwd(x, gamma, beta),
                              n_steps=20)
        t_u = traced_step_ms(lambda: unfused(x_nchw, gamma, beta),
                             n_steps=20)
        t_ub = traced_step_ms(lambda: unfused_bwd(x_nchw, gamma, beta),
                              n_steps=20)

        elems = b * h * w * c
        bpe = x.dtype.itemsize
        fwd_bytes = 2 * elems * bpe           # 1 read + 1 write
        fwd_bwd_bytes = 5 * elems * bpe       # + bwd: 2 reads + 1 write
        fwd_ms = t_f.device_step_ms or t_f.step_ms
        tot_ms = t_fb.device_step_ms or t_fb.step_ms
        out = {
            "kernel": "group_norm_silu",
            "shape": f"b{b}x{h}x{w}xc{c}g{g}",
            "fwd_ms": round(fwd_ms, 4),
            "fwd_bwd_ms": round(tot_ms, 4),
            "fwd_hbm_roofline": round(
                (fwd_bytes / (fwd_ms / 1e3)) / bw, 3),
            "fwd_bwd_hbm_roofline": round(
                (fwd_bwd_bytes / (tot_ms / 1e3)) / bw, 3),
            "unfused_nchw_fwd_ms": round(
                t_u.device_step_ms or t_u.step_ms, 4),
            "unfused_nchw_fwd_bwd_ms": round(
                t_ub.device_step_ms or t_ub.step_ms, 4),
            "peak_hbm_gbps": round(bw / 1e9, 1),
        }
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
