"""One-window TPU capture orchestrator.

The tunnel to the chip can be unavailable for hours (see
.claude/skills/verify/SKILL.md), so when a window opens, EVERYTHING
should be captured in one pass: headline at batch 4 and batch 6
(master-only residency decides which fits/wins), every secondary
config, the 7B int8 decode, and the serving load curve — each in its
own subprocess (libtpu is single-process-exclusive; one crash cannot
take the rest down).

Writes BENCH_TPU_CAPTURE.json with full per-config details (the same
dicts the bench children emit, including op summaries and plausibility
verdicts) and seeds BENCH_BASELINE.json via bench's own logic.

Usage:  python benchmarks/capture.py [--skip-secondary]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


CAPTURE_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_TPU_CAPTURE.json")


def main():
    argv = sys.argv[1:]
    env = dict(os.environ)
    ok, diags = bench.probe_tpu()
    if not ok:
        print(json.dumps({"error": "tpu unavailable", "attempts": diags},
                         default=str)[:2000])
        sys.exit(1)

    capture = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()),
               "configs": {}}

    t0 = time.time()
    for tag, batch in (("llama_b4", "4"), ("llama_b6", "6")):
        env_b = {**env, "BENCH_BATCH": batch}
        r = bench._run_one_config("llama", env_b, bench.HEADLINE_TIMEOUT)
        capture["configs"][tag] = r
        v = r.get("value")
        print(f"{tag}: {r.get('metric')} = {v} "
              f"(mfu={r.get('extra', {}).get('mfu_est')}) "
              f"[{time.time() - t0:.0f}s]", flush=True)

    # headline = the better of b4/b6 by TOKENS/S (the metric). Not by
    # mfu_est: cost-analysis FLOPs reward program waste — the first b6
    # capture ran 12% more FLOPs/token (so higher "MFU") while being
    # 9% slower per token. Throughput is the thing being claimed.
    def tps(tag):
        r = capture["configs"][tag]
        if r.get("unit") == "error":
            return -1.0
        return r.get("value") or -1.0

    best = max(("llama_b4", "llama_b6"), key=tps)
    capture["headline"] = best

    if "--skip-secondary" not in argv:
        for name in ("infer", "serve7b", "moe", "vit", "mamba", "unet"):
            tmo = (bench.SERVE7B_TIMEOUT if name == "serve7b"
                   else bench.SECONDARY_TIMEOUT)
            r = bench._run_one_config(name, env, tmo)
            capture["configs"][name] = r
            print(f"{name}: {r.get('metric')} = {r.get('value')} "
                  f"[{time.time() - t0:.0f}s]", flush=True)

    with open(CAPTURE_PATH, "w") as f:
        json.dump(capture, f, indent=1, default=str)

    # seed/refresh per-config baselines through bench's own discipline
    head = capture["configs"][best]
    head.setdefault("extra", {})["secondary"] = {
        k: v for k, v in capture["configs"].items()
        if k not in ("llama_b4", "llama_b6")}
    bench._maybe_write_baseline(head)
    print(f"capture written to {CAPTURE_PATH} "
          f"({time.time() - t0:.0f}s total)")


if __name__ == "__main__":
    main()
