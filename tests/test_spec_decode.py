"""Speculative decoding in the serving engine: n-gram self-drafting +
batched multi-token verify with KV rollback, plus the per-request
sampling params that ride the same PR.

The contract under test:
  - greedy token PARITY: ``PT_FLAGS_spec_decode=ngram`` outputs are
    bit-identical to spec-off in BOTH cache modes (incl. bf16 KV pools
    and prefix-cache on), across ragged lengths and slots that never
    produce a draft — greedy acceptance emits exactly the argmax chain;
  - ROLLBACK: rejected draft rows are logically discarded (seq_lens
    advance only past the accepted prefix; later attention never reads
    the garbage rows);
  - COW-under-verify: the K+1-token write window never mutates a page
    the prefix store still shares;
  - compile count: a mixed spec-on workload adds at most the verify
    program (+ the sampling variant) on top of the spec-off set, and
    spec-off compiles EXACTLY the pre-spec program set;
  - per-request sampling params route through
    ``generation.process_logits_batch`` without perturbing greedy
    neighbors, and sampling slots never draft.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import flags as F
from paddle_tpu.inference.serving import (
    ContinuousBatchingEngine,
    EngineConfig,
)
from paddle_tpu.inference.spec_decode import Drafter, NgramDrafter

# greedy-parity helpers shared with test_quant_serving (satellite of
# PR 9: the quant parity tests reuse the same comparison instead of
# copy-pasting it); serving_flags comes from conftest now
from serving_utils import (
    assert_spec_parity,
    drain as _drain,
    mixed_prompts as _mixed_prompts,
    spec_parity_outputs,
    tiny_ecfg as _ecfg,
    tiny_model as _model,
)

pytestmark = pytest.mark.fast


# ---------------- n-gram drafter ----------------

def test_ngram_drafter_basic_lookup():
    d = NgramDrafter(max_ngram=3, min_ngram=1)
    # history ends in the bigram (7, 8) seen earlier, followed by 9, 10
    h = np.array([1, 7, 8, 9, 10, 5, 7, 8])
    np.testing.assert_array_equal(d.propose(h, 2), [9, 10])
    # k bounds the proposal
    np.testing.assert_array_equal(d.propose(h, 1), [9])
    # no earlier occurrence of any suffix -> empty
    assert d.propose(np.array([1, 2, 3, 4]), 4).size == 0
    # degenerate histories never crash
    assert d.propose(np.array([1]), 4).size == 0
    assert d.propose(np.array([], np.int64), 4).size == 0
    assert d.propose(h, 0).size == 0


def test_ngram_drafter_longest_suffix_and_recency_win():
    d = NgramDrafter(max_ngram=3, min_ngram=1)
    # suffix (2, 3) occurs twice; trigram (9, 2, 3) only once — the
    # longer match decides, not the more recent shorter one
    h = np.array([9, 2, 3, 50, 4, 2, 3, 60, 9, 2, 3])
    np.testing.assert_array_equal(d.propose(h, 1), [50])
    # only unigram matches: the MOST RECENT occurrence's continuation
    h2 = np.array([5, 1, 5, 2, 5])
    np.testing.assert_array_equal(d.propose(h2, 1), [2])
    # proposal may run into the suffix itself (periodic history)
    h3 = np.array([1, 2, 3, 1, 2, 3, 1, 2, 3])
    np.testing.assert_array_equal(d.propose(h3, 3), [1, 2, 3])


def test_ngram_drafter_validates():
    with pytest.raises(ValueError, match="min_ngram"):
        NgramDrafter(max_ngram=1, min_ngram=2)
    with pytest.raises(ValueError, match="min_ngram"):
        NgramDrafter(max_ngram=3, min_ngram=0)


# ---------------- greedy token parity ----------------

@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("cache_dtype", [jnp.float32, jnp.bfloat16])
def test_spec_token_parity(paged, cache_dtype, serving_flags):
    """THE acceptance criterion: spec-on greedy outputs are identical
    to spec-off in both cache modes incl. bf16 pools, with the prefix
    cache on, across ragged lengths and non-drafting slots — and the
    spec arm must actually have accepted drafts (or the test proves
    nothing). The comparison itself lives in serving_utils, shared
    with the quantized-serving parity suite."""
    model, cfg = _model(3)
    rng = np.random.default_rng(5)
    prompts = _mixed_prompts(cfg, rng)
    outs, snaps = spec_parity_outputs(
        model, lambda: _ecfg(paged, cache_dtype=cache_dtype), prompts,
        serving_flags, flags_extra={"prefix_cache": True})
    assert_spec_parity(outs, snaps)


@pytest.mark.parametrize("paged", [False, True])
def test_spec_parity_per_token_step(paged, serving_flags):
    """step() (per-token scheduler) offers a draft opportunity every
    tick — parity must hold there too, at high draft pressure."""
    model, cfg = _model(7)
    rng = np.random.default_rng(2)
    unit = rng.integers(1, cfg.vocab_size, 3)
    prompts = [np.concatenate([unit] * 6),
               rng.integers(1, cfg.vocab_size, 7)]
    outs = {}
    for mode in ("off", "ngram"):
        serving_flags({"spec_decode": mode})
        eng = ContinuousBatchingEngine(model, _ecfg(paged))
        rids = [eng.add_request(p, max_new_tokens=30) for p in prompts]
        _drain(eng)
        outs[mode] = [eng._finished[r].output for r in rids]
        if mode == "ngram":
            assert eng.spec_stats["accepted"] > 0
    assert outs["ngram"] == outs["off"]


def test_spec_auto_mode_parity_and_throttle(serving_flags):
    """auto = ngram drafting + a per-request throttle for undraftable
    traffic. Parity is unconditional; the throttle must stop proposing
    for a request whose drafts never accept."""
    model, cfg = _model(4)
    rng = np.random.default_rng(8)
    unit = rng.integers(1, cfg.vocab_size, 4)
    prompts = [np.concatenate([unit] * 5)]
    serving_flags({"spec_decode": "off"})
    ref = [r.output for r in ContinuousBatchingEngine(
        model, _ecfg(True)).run(prompts, max_new_tokens=24)]

    serving_flags({"spec_decode": "auto"})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    got = [r.output for r in eng.run(prompts, max_new_tokens=24)]
    assert got == ref

    # throttle: a drafter that always proposes garbage stops getting
    # called for the request once its acceptance proves hopeless
    class Garbage(Drafter):
        def __init__(self):
            self.calls = 0

        def propose(self, history, k):
            self.calls += 1
            return np.full((k,), -1, np.int64)  # never a real token

    bad = Garbage()
    eng2 = ContinuousBatchingEngine(model, _ecfg(True), drafter=bad)
    rid = eng2.add_request(prompts[0], max_new_tokens=40)
    _drain(eng2)
    assert eng2._finished[rid].output == ContinuousBatchingEngine(
        model, _ecfg(True)).run(prompts, max_new_tokens=40)[0].output
    assert eng2.spec_stats["accepted"] == 0
    req = eng2._finished[rid]
    # proposals stopped at the throttle threshold, well before the 39
    # decode ticks the request took
    assert 16 <= req._spec_proposed <= 20
    assert eng2.spec_stats["fallback_steps"] > 0


def test_spec_flag_validated():
    model, cfg = _model()
    F.set_flags({"spec_decode": "bogus"})
    try:
        with pytest.raises(ValueError, match="spec_decode"):
            ContinuousBatchingEngine(model, _ecfg(False))
    finally:
        F.set_flags({"spec_decode": "off"})
    with pytest.raises(ValueError, match="spec_k"):
        F.set_flags({"spec_decode": "ngram"})
        try:
            ContinuousBatchingEngine(model, _ecfg(False, spec_k=0))
        finally:
            F.set_flags({"spec_decode": "off"})


# ---------------- rollback ----------------

def test_rollback_rejected_rows_never_read(serving_flags):
    """A verify pass whose drafts are ALL rejected wrote K garbage KV
    rows past the slot's length; the engine advances by exactly one
    token and later attention must never read those rows — pinned by
    bit-parity of the remaining stream against the spec-off oracle."""
    model, cfg = _model(6)
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, cfg.vocab_size, 9)
    serving_flags({"spec_decode": "off"})
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        [prompt], max_new_tokens=12)[0].output

    class WrongDrafter(Drafter):
        """Proposes the WRONG token (off-by-one of the oracle) for the
        first verify, then stops — every draft must be rejected."""

        def __init__(self, oracle):
            self.oracle = oracle
            self.fired = False

        def propose(self, history, k):
            if self.fired:
                return np.zeros((0,), np.int64)
            self.fired = True
            nxt = len(history) - 9  # tokens generated so far
            wrong = [(self.oracle[nxt + j] + 1) % 256 for j in range(k)]
            return np.asarray(wrong, np.int64)

    serving_flags({"spec_decode": "ngram"})
    eng = ContinuousBatchingEngine(model, _ecfg(True),
                                   drafter=WrongDrafter(ref))
    rid = eng.add_request(prompt, max_new_tokens=12)
    eng._admit()
    len0 = int(eng.seq_lens[0])
    assert eng.step()  # the all-rejected verify pass
    assert eng.spec_stats["verify_calls"] == 1
    assert eng.spec_stats["accepted"] == 0
    assert eng.spec_stats["proposed"] == eng.cfg.spec_k
    # rollback: advanced by the bonus token ONLY, not K+1
    assert int(eng.seq_lens[0]) == len0 + 1
    _drain(eng)
    assert eng._finished[rid].output == ref


def test_partial_acceptance_advances_by_accepted_plus_one(serving_flags):
    """Drafts correct for j tokens then wrong: accepted == j exactly
    (greedy acceptance is a prefix rule), seq_lens advances j+1, and
    the stream stays on the oracle."""
    model, cfg = _model(6)
    rng = np.random.default_rng(4)
    prompt = rng.integers(1, cfg.vocab_size, 9)
    serving_flags({"spec_decode": "off"})
    ref = ContinuousBatchingEngine(model, _ecfg(False)).run(
        [prompt], max_new_tokens=12)[0].output

    class HalfRight(Drafter):
        def __init__(self, oracle):
            self.oracle = oracle
            self.fired = False

        def propose(self, history, k):
            if self.fired or k < 3:
                return np.zeros((0,), np.int64)
            self.fired = True
            nxt = len(history) - 9
            d = [self.oracle[nxt], self.oracle[nxt + 1],
                 (self.oracle[nxt + 2] + 1) % 256]
            return np.asarray(d, np.int64)

    serving_flags({"spec_decode": "ngram"})
    eng = ContinuousBatchingEngine(model, _ecfg(False),
                                   drafter=HalfRight(ref))
    rid = eng.add_request(prompt, max_new_tokens=12)
    eng._admit()
    len0 = int(eng.seq_lens[0])
    eng.step()  # verify: 2 accepted, 1 rejected
    assert eng.spec_stats["accepted"] == 2
    assert int(eng.seq_lens[0]) == len0 + 3  # 2 drafts + bonus
    _drain(eng)
    assert eng._finished[rid].output == ref


# ---------------- copy-on-write under verify ----------------

def test_cow_under_verify_never_dirties_shared_page(serving_flags):
    """The verify window (K+1 rows, pad rows included) must trigger the
    decode-time COW guard when it overlaps a shared page — the cached
    prefix entry stays bit-identical through an entire spec-on run."""
    model, cfg = _model(2)
    rng = np.random.default_rng(9)
    unit = rng.integers(1, cfg.vocab_size, 4)
    prompt = np.concatenate([unit] * 4)  # 16 tokens = 2 pages of 8
    serving_flags({"spec_decode": "ngram", "prefix_cache": True})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    r1 = eng.add_request(prompt, max_new_tokens=24)
    _drain(eng)  # per-token steps: every tick is a draft opportunity
    ref = eng._finished[r1].output
    assert eng.spec_stats["accepted"] > 0  # verify actually wrote
    store = eng._prefix
    pages = [p for p, _ns in store._blocks.values()]
    assert len(pages) == 2
    before = [[np.asarray(c.k_pages[:, p]).copy() for p in pages]
              for c in eng.layer_caches]

    # full-cover hit: adopts both shared pages; the verify window's
    # writes start INSIDE the last shared page
    r2 = eng.add_request(prompt, max_new_tokens=24)
    _drain(eng)
    out2 = eng._finished[r2].output
    assert out2 == ref
    assert eng.prefix_stats["cow_copies"] >= 1
    after = [[np.asarray(c.k_pages[:, p]) for p in pages]
             for c in eng.layer_caches]
    for lb, la in zip(before, after):
        for b, a in zip(lb, la):
            np.testing.assert_array_equal(b, a)


def test_cow_guard_covers_full_verify_window(serving_flags):
    """Externally pin the page the verify window writes into (the
    guard test pattern from PR 4, widened to the K-token window): the
    engine must copy it before dispatching verify."""
    model, cfg = _model(4)
    rng = np.random.default_rng(1)
    unit = rng.integers(1, cfg.vocab_size, 2)
    prompt = np.concatenate([unit] * 3)  # repetitive → drafts fire
    serving_flags({"spec_decode": "ngram"})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    rid = eng.add_request(prompt, max_new_tokens=10)
    eng._admit()
    slot = eng._slot_req[0].slot
    page = int(eng.pool.block_tables[slot, 0])
    eng.pool.retain(page)
    snap = np.asarray(eng.layer_caches[0].k_pages[:, page]).copy()
    _drain(eng)
    assert eng.spec_stats["verify_calls"] >= 1
    assert eng.prefix_stats["cow_copies"] >= 1
    np.testing.assert_array_equal(
        snap, np.asarray(eng.layer_caches[0].k_pages[:, page]))
    assert eng._finished[rid].done
    eng.pool.release(page)


# ---------------- compile-count guard ----------------

def test_spec_compile_counts(compile_counter, serving_flags):
    """Spec-off compiles exactly the PR-4 program set; a mixed spec-on
    workload (drafting slots + fallback steps + admissions mid-stream)
    adds AT MOST the verify program on top — and re-running at other
    prompt lengths must not re-specialize anything."""
    model, cfg = _model(6)
    rng = np.random.default_rng(3)
    unit = rng.integers(1, cfg.vocab_size, 4)
    prompts = [np.concatenate([unit] * 5),
               rng.integers(1, cfg.vocab_size, 7),
               rng.integers(1, cfg.vocab_size, 19)]

    serving_flags({"spec_decode": "off"})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    eng.run(prompts, max_new_tokens=12)
    off_set = compile_counter()
    assert off_set == {"prefill_chunk": 1, "decode_chunk": 1}
    assert compile_counter("spec_verify") == 0

    serving_flags({"spec_decode": "ngram"})
    eng2 = ContinuousBatchingEngine(model, _ecfg(True))
    eng2.run(prompts, max_new_tokens=12)
    assert eng2.spec_stats["verify_calls"] > 0
    assert eng2.spec_stats["fallback_steps"] > 0  # mixed workload
    on_set = compile_counter()
    new = {k: on_set[k] - off_set.get(k, 0) for k in on_set
           if on_set[k] - off_set.get(k, 0)}
    # ≤ 2 new programs: the verify pass + the (already-counted-per-
    # engine) fallback chunk this second engine compiled for itself
    assert new.pop("spec_verify") == 1
    assert new == {"prefill_chunk": 1, "decode_chunk": 1}

    # other prompt lengths / a second wave: nothing re-specializes
    eng2.run([rng.integers(1, cfg.vocab_size, 30),
              np.concatenate([unit] * 3)], max_new_tokens=8)
    assert compile_counter("spec_verify") == 1
    assert compile_counter("prefill_chunk") == on_set["prefill_chunk"]
    assert compile_counter("decode_chunk") == on_set["decode_chunk"]


def test_chunk_preemption_gated_on_drafting_share(serving_flags):
    """A lone drafting slot in a wide batch must NOT preempt the
    K-token chunk (every other slot would drop from max_chunk tokens
    per sync to 1); with a majority drafting, verify preempts. The
    per-token step() scheduler preempts unconditionally either way.
    A marker-keyed drafter makes WHO drafts deterministic (the n-gram
    drafter's firing depends on what the model happens to emit)."""
    model, cfg = _model(6)
    rng = np.random.default_rng(12)
    marker = int(rng.integers(1, cfg.vocab_size))
    drafting = np.concatenate(
        [[marker], rng.integers(1, cfg.vocab_size, 8)])
    others = [np.concatenate(
        [[(marker + 1 + i) % cfg.vocab_size or 1],
         rng.integers(1, cfg.vocab_size, 7 + i)]) for i in range(3)]

    class MarkerDrafter(Drafter):
        """Drafts (garbage — rejection is fine, the gate fires on
        PROPOSALS) only for histories starting with the marker."""

        def propose(self, history, k):
            if history.size and int(history[0]) == marker:
                return np.full((min(k, 2),), int(history[-1]), np.int64)
            return np.zeros((0,), np.int64)

    serving_flags({"spec_decode": "ngram"})

    # 1 drafter of 4 active: the chunk is never preempted
    eng = ContinuousBatchingEngine(
        model, _ecfg(True, max_slots=4), drafter=MarkerDrafter())
    for p in [drafting] + others:
        eng.add_request(p, max_new_tokens=12)
    _drain(eng, lambda: eng.step_chunk(4))
    assert eng.spec_stats["verify_calls"] == 0
    assert eng.spec_stats["fallback_steps"] > 0

    # 2 drafters of 2 active: verify preempts the chunk
    eng2 = ContinuousBatchingEngine(
        model, _ecfg(True), drafter=MarkerDrafter())
    eng2.add_request(drafting, max_new_tokens=12)
    eng2.add_request(np.concatenate([[marker], drafting[1:5]]),
                     max_new_tokens=12)
    _drain(eng2, lambda: eng2.step_chunk(4))
    assert eng2.spec_stats["verify_calls"] > 0

    # step(): even the lone drafter preempts (beats a 1-token pass)
    eng3 = ContinuousBatchingEngine(
        model, _ecfg(True, max_slots=4), drafter=MarkerDrafter())
    for p in [drafting] + others:
        eng3.add_request(p, max_new_tokens=12)
    _drain(eng3)
    assert eng3.spec_stats["verify_calls"] > 0


# ---------------- step_adaptive ----------------

def test_step_adaptive_parity_spec_on_and_off(serving_flags):
    """step_adaptive (previously untested): mixed prefill/decode — more
    requests than slots so admission stays queued across chunks — must
    produce exactly step_chunk's tokens, with spec decoding off AND
    on (and the same stream in all four arms)."""
    model, cfg = _model(11)
    rng = np.random.default_rng(6)
    unit = rng.integers(1, cfg.vocab_size, 3)
    prompts = [np.concatenate([unit] * 5),
               rng.integers(1, cfg.vocab_size, 8),
               np.concatenate([unit] * 4),
               rng.integers(1, cfg.vocab_size, 5)]

    outs = {}
    for mode in ("off", "ngram"):
        serving_flags({"spec_decode": mode})
        for sched in ("chunk", "adaptive"):
            eng = ContinuousBatchingEngine(model, _ecfg(True))
            rids = [eng.add_request(p, max_new_tokens=12)
                    for p in prompts]
            if sched == "chunk":
                while eng.step_chunk(4) or eng._queue or \
                        eng.active.any():
                    pass
            else:
                while eng.step_adaptive(max_chunk=4) or \
                        eng.active.any():
                    pass
            outs[(mode, sched)] = [eng._finished[r].output
                                   for r in rids]
            if mode == "ngram":
                assert eng.spec_stats["verify_calls"] > 0
    assert len({tuple(map(tuple, v)) for v in outs.values()}) == 1


# ---------------- per-request sampling params ----------------

def test_per_request_params_validated():
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    p = np.arange(1, 6)
    with pytest.raises(ValueError, match="temperature"):
        eng.add_request(p, 4, temperature=0.0)
    with pytest.raises(ValueError, match="top_k"):
        eng.add_request(p, 4, top_k=-1)
    with pytest.raises(ValueError, match="top_p"):
        eng.add_request(p, 4, top_p=1.5)
    with pytest.raises(ValueError, match="top_p"):
        eng.add_request(p, 4, top_p=0.0)


def test_defaults_equivalent_overrides_keep_plain_arm():
    """Passing overrides that LAND on the engine defaults
    (greedy=True on a greedy engine, top_k=0, top_p=1.0, the engine's
    own temperature) must not flip the compiled programs onto the
    per-slot sampling arm — use_samp stays False and the trace (and
    its per-step vocab sort) is the pre-override one. A real override
    still flips it."""
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    p = np.arange(1, 8)
    eng.add_request(p, 4, greedy=True, top_k=0, top_p=1.0,
                    temperature=eng.cfg.temperature)
    eng._admit()
    use, _ = eng._slot_sampling()
    assert use is False

    eng2 = ContinuousBatchingEngine(model, _ecfg(False))
    eng2.add_request(p, 4, top_k=1)
    eng2._admit()
    use2, _ = eng2._slot_sampling()
    assert use2 is True


def test_per_request_top_k1_matches_greedy():
    """temperature + top_k=1 is sampling with a single survivor — the
    stream must equal the plain greedy reference token for token (the
    in-jit vectorized processor path, deterministically checked)."""
    model, cfg = _model(5)
    prompt = np.arange(1, 8)
    ref = ContinuousBatchingEngine(model, _ecfg(False)).run(
        [prompt], max_new_tokens=8)[0].output

    eng = ContinuousBatchingEngine(model, _ecfg(False))
    rid = eng.add_request(prompt, max_new_tokens=8, temperature=2.0,
                          top_k=1)
    _drain(eng, lambda: eng.step_chunk(4))
    assert eng._finished[rid].output == ref


def test_mixed_greedy_and_sampled_slots_isolated():
    """A sampling neighbor in the same compiled step must not perturb a
    greedy slot's stream (per-slot params are vectors, greedy rows stay
    pure argmax)."""
    model, cfg = _model(9)
    rng = np.random.default_rng(0)
    pa = rng.integers(1, cfg.vocab_size, 6)
    pb = rng.integers(1, cfg.vocab_size, 9)
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        [pa], max_new_tokens=10)[0].output

    eng = ContinuousBatchingEngine(model, _ecfg(True))
    ra = eng.add_request(pa, max_new_tokens=10)  # greedy (engine default)
    rb = eng.add_request(pb, max_new_tokens=10, temperature=5.0,
                         top_p=0.9)
    _drain(eng, lambda: eng.step_chunk(4))
    assert eng._finished[ra].output == ref
    assert len(eng._finished[rb].output) == 10


def test_sampled_request_varies_across_seeds():
    model, cfg = _model(9)
    prompt = np.arange(1, 6)
    firsts = set()
    for seed in range(6):
        eng = ContinuousBatchingEngine(
            model, _ecfg(False, seed=seed))
        rid = eng.add_request(prompt, max_new_tokens=1, temperature=8.0)
        _drain(eng)
        firsts.add(eng._finished[rid].output[0])
    assert len(firsts) > 1


def test_sampling_slots_skip_drafting(serving_flags):
    """Spec decode + sampling compose: the greedy repetitive slot
    drafts, the sampling slot never does (no argmax chain to verify),
    and the greedy slot's stream still matches the oracle."""
    model, cfg = _model(3)
    rng = np.random.default_rng(7)
    unit = rng.integers(1, cfg.vocab_size, 4)
    pa = np.concatenate([unit] * 5)
    pb = rng.integers(1, cfg.vocab_size, 8)
    serving_flags({"spec_decode": "off"})
    refe = ContinuousBatchingEngine(model, _ecfg(True))
    rr = refe.add_request(pa, max_new_tokens=32)
    _drain(refe)
    ref = refe._finished[rr].output

    serving_flags({"spec_decode": "ngram"})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    ra = eng.add_request(pa, max_new_tokens=32)
    rb = eng.add_request(pb, max_new_tokens=32, temperature=3.0)
    _drain(eng)
    assert eng._finished[ra].output == ref
    assert eng.spec_stats["accepted"] > 0
    # every proposal came from the greedy request
    assert eng._finished[rb]._spec_proposed == 0
    assert eng._finished[ra]._spec_proposed == \
        eng.spec_stats["proposed"]


# ---------------- snapshots / telemetry ----------------

def test_spec_snapshot_and_metrics(serving_flags):
    from paddle_tpu import observability
    from paddle_tpu.flags import set_flags as set_pt_flags

    model, cfg = _model(3)
    rng = np.random.default_rng(5)
    unit = rng.integers(1, cfg.vocab_size, 4)
    serving_flags({"spec_decode": "ngram"})
    set_pt_flags({"telemetry": True})
    try:
        eng = ContinuousBatchingEngine(model, _ecfg(True))
        eng.add_request(np.concatenate([unit] * 5), max_new_tokens=32)
        _drain(eng)
        snap = eng.spec_snapshot()
        assert snap["enabled"] and snap["mode"] == "ngram"
        assert snap["proposed"] >= snap["accepted"] > 0
        assert 0 < snap["acceptance_rate"] <= 1
        m = eng.metrics_snapshot()
        assert m["spec_decode"]["verify_calls"] == \
            snap["verify_calls"]
        sd = eng._tel.snapshot()["spec_decode"]
        assert sd["proposed_tokens"] == snap["proposed"]
        assert sd["accepted_tokens"] == snap["accepted"]
        assert sd["acceptance_rate"] == pytest.approx(
            snap["acceptance_rate"])
        text = observability.global_registry().prometheus_text()
        assert "pt_serve_spec_accepted_tokens_total" in text
        assert "pt_serve_spec_acceptance_rate" in text
    finally:
        set_pt_flags({"telemetry": False})
