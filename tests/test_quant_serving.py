"""Quantized serving: int8/int4 weight streaming + int8 KV-cache pools
with in-kernel dequant.

The contract under test:
  - int4 pack/unpack edges raise ACTIONABLE errors, nibble order is
    pinned (low nibble = even row);
  - ``_resolve_cache_dtype`` / ``EngineConfig`` reject combinations
    with no kernel path AT INIT (bad dtypes, int8 KV on the legacy
    bucketed prefill, any quantized mode under a mesh);
  - the engine quantizes a DEEP COPY by default — the caller's model
    stays full-precision and servable;
  - greedy spec-off vs spec-on parity holds under int8 weights and
    int8 KV pools in BOTH cache modes (quantization changes WHICH
    tokens greedy decode emits vs bf16 — measured by the bench quant
    scenario, never asserted here — but within a quant config the
    engine must stay bit-stable across schedulers and spec modes);
  - fused Pallas kernels (interpret mode on CPU) match the lax
    references bit-for-bit on int8 pools at GQA kvh 1/4/8 with ragged
    lengths incl. len-0 and page-boundary slots, and fused-vs-unfused
    engines emit identical tokens;
  - shared-prefix pages CARRY THEIR SCALE ROWS through adopt/COW/
    evict; spec-decode rollback under int8 KV is a pure length
    non-advance; crash-recovery replay under int8 weights+KV is
    deterministic and compiles ZERO new programs;
  - int8-weight serving exercises all compiled serving programs with
    no per-dtype program growth (trace-count guard);
  - the kernelbench quant models report >=1.8x bytes/token for int8-W
    alone and ~4.6x for int8-W x int8-KV x acceptance 0.6 vs bf16
    plain decode, as JSON-serializable rows on any backend.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference.resilience import FaultInjector
from paddle_tpu.inference.serving import (
    ContinuousBatchingEngine,
    EngineConfig,
    _resolve_cache_dtype,
)
from paddle_tpu.inference.spec_decode import Drafter
from paddle_tpu.kernels import decode_attention as da
from paddle_tpu.kernels import quant_matmul as qmm
from paddle_tpu.kernels.paged_attention import fused_paged_decode_attention
from paddle_tpu.kernels.rope import rope_frequencies
from paddle_tpu.quantization import WeightOnlyLinear

from serving_utils import (
    assert_spec_parity,
    drain,
    mixed_prompts,
    spec_parity_outputs,
    tiny_ecfg,
    tiny_model,
)

pytestmark = pytest.mark.fast


# ---------------- int4 edge hardening ----------------

def test_int4_odd_k_actionable_error():
    w = jnp.zeros((129, 8))
    with pytest.raises(ValueError, match="even.*k=129|k=129.*even"):
        qmm.quantize_weight_int4_grouped(w, group_size=129)
    # the message tells the caller what to DO about it
    with pytest.raises(ValueError, match="[Pp]ad"):
        qmm.quantize_weight_int4_grouped(w, group_size=129)


def test_int4_group_mismatch_actionable_error():
    w = jnp.zeros((128, 8))
    with pytest.raises(ValueError, match="group_size=96"):
        qmm.quantize_weight_int4_grouped(w, group_size=96)
    # suggests a group size that actually divides k
    with pytest.raises(ValueError, match="group_size=64"):
        qmm.quantize_weight_int4_grouped(w, group_size=96)
    # int8 grouped path rejects too (its own message)
    with pytest.raises(ValueError, match="group_size"):
        qmm.quantize_weight_int8_grouped(w, group_size=96)


def test_int4_pack_unpack_roundtrip_nibble_order_pinned():
    """Property: pack→unpack is the identity on int4 values, and the
    nibble order is PINNED — row 2i in the LOW nibble of packed row i,
    row 2i+1 in the HIGH nibble (a silent order flip would still
    round-trip, so the order is checked against hand-packed bytes)."""
    rng = np.random.default_rng(3)
    vals = rng.integers(-7, 8, (64, 16)).astype(np.int8)
    lo = vals[0::2].astype(np.int32) & 0xF
    hi = (vals[1::2].astype(np.int32) & 0xF) << 4
    packed = jnp.asarray((lo | hi).astype(np.int8))
    unpacked = np.asarray(qmm._unpack_int4(packed))
    np.testing.assert_array_equal(unpacked, vals)
    # and the quantizer produces exactly that packing for its own q
    w = rng.standard_normal((64, 16)).astype(np.float32)
    pk, s = qmm.quantize_weight_int4_grouped(jnp.asarray(w), 32)
    q = np.asarray(qmm._unpack_int4(pk))
    repack_lo = q[0::2].astype(np.int32) & 0xF
    repack_hi = (q[1::2].astype(np.int32) & 0xF) << 4
    np.testing.assert_array_equal(
        np.asarray(pk), (repack_lo | repack_hi).astype(np.int8))


# ---------------- config validation ----------------

def test_resolve_cache_dtype_error_lists_full_allowed_set():
    with pytest.raises(ValueError) as ei:
        _resolve_cache_dtype("int3")
    msg = str(ei.value)
    for name in ("int8", "bf16", "bfloat16", "float16", "float32"):
        assert name in msg
    # and the new member actually resolves
    assert _resolve_cache_dtype("int8") == jnp.int8


def test_engine_rejects_no_kernel_path_combos_at_init(serving_flags):
    model, cfg = tiny_model()
    with pytest.raises(ValueError, match="weight_dtype"):
        ContinuousBatchingEngine(
            model, tiny_ecfg(True, weight_dtype="fp8"))
    with pytest.raises(ValueError, match="cache_dtype"):
        ContinuousBatchingEngine(
            model, tiny_ecfg(True, cache_dtype="int4"))
    with pytest.raises(ValueError, match="weight_group_size"):
        ContinuousBatchingEngine(
            model, tiny_ecfg(True, weight_dtype="int8",
                             weight_group_size=0))
    # int8 KV has no quantize-on-append path through the legacy
    # bucketed prefill: rejected at init, not at first dispatch
    serving_flags({"prefill_chunk": 0})
    with pytest.raises(ValueError, match="chunked prefill"):
        ContinuousBatchingEngine(
            model, tiny_ecfg(True, cache_dtype="int8"))
    serving_flags({"prefill_chunk": 256})
    # quantized serving is single-chip: any mesh is rejected before
    # params are sharded
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    with pytest.raises(ValueError, match="mesh|tensor-parallel"):
        ContinuousBatchingEngine(
            model, tiny_ecfg(True, weight_dtype="int8"), mesh=mesh)
    with pytest.raises(ValueError, match="mesh|tensor-parallel"):
        ContinuousBatchingEngine(
            model, tiny_ecfg(True, cache_dtype="int8"), mesh=mesh)


def test_weight_dtype_flag_resolution(serving_flags):
    """EngineConfig.weight_dtype='auto' defers to
    PT_FLAGS_serve_weight_dtype; explicit config wins."""
    model, cfg = tiny_model()
    serving_flags({"serve_weight_dtype": "int8"})
    eng = ContinuousBatchingEngine(model, tiny_ecfg(False))
    assert eng.weight_dtype == "int8"
    assert any("qweight" in n for n in eng.buffers)
    serving_flags({"serve_weight_dtype": "bf16"})
    eng2 = ContinuousBatchingEngine(
        model, tiny_ecfg(False, weight_dtype="int4"))
    assert eng2.weight_dtype == "int4"


def test_engine_quantizes_a_copy_by_default():
    model, cfg = tiny_model(1)
    p = np.arange(1, 9)
    ref = ContinuousBatchingEngine(model, tiny_ecfg(False)).run(
        [p], max_new_tokens=6)[0].output
    eng = ContinuousBatchingEngine(
        model, tiny_ecfg(False, weight_dtype="int8"))
    eng.run([p], max_new_tokens=6)
    # the caller's tree still has zero WeightOnlyLinear layers and
    # serves the exact pre-quantization stream
    assert not any(isinstance(m, WeightOnlyLinear)
                   for m in model.sublayers(include_self=True))
    again = ContinuousBatchingEngine(model, tiny_ecfg(False)).run(
        [p], max_new_tokens=6)[0].output
    assert again == ref
    # inplace opt-in mutates (the 7B memory trade)
    model2, _ = tiny_model(1)
    ContinuousBatchingEngine(
        model2, tiny_ecfg(False, weight_dtype="int8",
                          quantize_inplace=True))
    assert any(isinstance(m, WeightOnlyLinear)
               for m in model2.sublayers(include_self=True))


# ---------------- greedy parity under quantization ----------------

@pytest.mark.parametrize("paged", [False, True])
def test_int8_weight_spec_parity(paged, serving_flags):
    """Spec-off vs spec-ngram stays bit-identical when the engine
    serves int8 weights (the shared parity comparison from
    serving_utils, same as the fp suite runs)."""
    model, cfg = tiny_model(3)
    rng = np.random.default_rng(5)
    prompts = mixed_prompts(cfg, rng)
    outs, snaps = spec_parity_outputs(
        model,
        lambda: tiny_ecfg(paged, weight_dtype="int8"),
        prompts, serving_flags, flags_extra={"prefix_cache": True})
    assert_spec_parity(outs, snaps)


@pytest.mark.parametrize("paged", [False, True])
def test_int8_kv_spec_parity(paged, serving_flags):
    """Spec-off vs spec-ngram parity on int8 KV pools x int8 weights
    (the FULL quantized stack — int8 weights over a float cache are
    covered by test_int8_weight_spec_parity) in both cache modes."""
    model, cfg = tiny_model(3)
    rng = np.random.default_rng(5)
    prompts = mixed_prompts(cfg, rng)
    outs, snaps = spec_parity_outputs(
        model,
        lambda: tiny_ecfg(paged, cache_dtype="int8",
                          weight_dtype="int8"),
        prompts, serving_flags, flags_extra={"prefix_cache": True})
    assert_spec_parity(outs, snaps)


@pytest.mark.parametrize("paged", [False, True])
def test_fused_engine_int8_kv_token_parity(paged, serving_flags):
    """PT_FLAGS_fused_decode on (Pallas interpret) vs off (lax
    reference) emits identical tokens on int8 pools — in-kernel
    quantize-on-append and dequant match the XLA paths bit-for-bit."""
    model, cfg = tiny_model(7)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, cfg.vocab_size, 9),
               rng.integers(1, cfg.vocab_size, 5)]
    outs = {}
    for fd in ("off", "on"):
        serving_flags({"fused_decode": fd})
        eng = ContinuousBatchingEngine(
            model, tiny_ecfg(paged, cache_dtype="int8"))
        rids = [eng.add_request(p, 8) for p in prompts]
        drain(eng)
        outs[fd] = [eng._finished[r].output for r in rids]
    assert outs["on"] == outs["off"]


# ---------------- kernel-level parity ----------------

@pytest.mark.parametrize("kvh", [1, 4, 8])
def test_fused_int8_kernels_match_references(kvh):
    """Fused Pallas (interpret) vs lax reference on int8 pools at GQA
    kvh 1/4/8 with ragged lengths incl. a len-0 slot and a
    page/chunk-boundary slot: outputs allclose, written pools AND
    scale rows bit-equal."""
    rng = np.random.default_rng(kvh)
    heads = 4 * kvh
    d = 128
    group = heads // kvh
    slots, page_size, max_len = 4, 16, 128
    n_pages = slots * (max_len // page_size) + 1
    lens = np.array([0, 17, 63, 111], np.int32)
    cos, sin = rope_frequencies(d, max_len + 1)
    q = jnp.asarray(rng.standard_normal((slots, kvh, group, d)),
                    jnp.float32)
    kn = jnp.asarray(rng.standard_normal((slots, kvh, d)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((slots, kvh, d)), jnp.float32)
    lens_j = jnp.asarray(lens)

    # paged
    kp = jnp.asarray(rng.integers(-127, 128,
                                  (kvh, n_pages, page_size, d)), jnp.int8)
    vp = jnp.asarray(rng.integers(-127, 128,
                                  (kvh, n_pages, page_size, d)), jnp.int8)
    ks = jnp.asarray(rng.uniform(1e-3, 2e-2,
                                 (kvh, n_pages, page_size, 1)), jnp.float32)
    vs = jnp.asarray(rng.uniform(1e-3, 2e-2,
                                 (kvh, n_pages, page_size, 1)), jnp.float32)
    bt = jnp.asarray(1 + np.arange(slots * (max_len // page_size))
                     .reshape(slots, -1), jnp.int32)
    of, kpf, vpf, ksf, vsf = fused_paged_decode_attention(
        q, kn, vn, kp, vp, bt, lens_j, lens_j, cos, sin,
        k_scale=ks, v_scale=vs)
    orf, kpr, vpr, ksr, vsr = da.fused_paged_decode_reference(
        q, kn, vn, kp, vp, bt, lens_j, lens_j, cos, sin,
        k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(of), np.asarray(orf),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(kpf), np.asarray(kpr))
    np.testing.assert_array_equal(np.asarray(vpf), np.asarray(vpr))
    # scale rows: last-ulp f32 drift between in-kernel rope and the
    # reference's apply_rope can move an absmax by ~1e-9 — the int8
    # payloads above are bit-equal, which is the bit that matters
    np.testing.assert_allclose(np.asarray(ksf), np.asarray(ksr),
                               rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(np.asarray(vsf), np.asarray(vsr),
                               rtol=1e-5, atol=1e-8)

    # contiguous
    ck = jnp.asarray(rng.integers(-127, 128,
                                  (slots, max_len, kvh, d)), jnp.int8)
    cv = jnp.asarray(rng.integers(-127, 128,
                                  (slots, max_len, kvh, d)), jnp.int8)
    cks = jnp.asarray(rng.uniform(1e-3, 2e-2,
                                  (slots, max_len, kvh)), jnp.float32)
    cvs = jnp.asarray(rng.uniform(1e-3, 2e-2,
                                  (slots, max_len, kvh)), jnp.float32)
    of, ckf, cvf, ksf, vsf = da.fused_contiguous_decode_attention(
        q, kn, vn, ck, cv, lens_j, lens_j, cos, sin,
        k_scale=cks, v_scale=cvs)
    orf, ckr, cvr, ksr, vsr = da.fused_contiguous_decode_reference(
        q, kn, vn, ck, cv, lens_j, lens_j, cos, sin,
        k_scale=cks, v_scale=cvs)
    np.testing.assert_allclose(np.asarray(of), np.asarray(orf),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(ckf), np.asarray(ckr))
    np.testing.assert_array_equal(np.asarray(cvf), np.asarray(cvr))
    # scale rows: last-ulp f32 drift between in-kernel rope and the
    # reference's apply_rope can move an absmax by ~1e-9 — the int8
    # payloads above are bit-equal, which is the bit that matters
    np.testing.assert_allclose(np.asarray(ksf), np.asarray(ksr),
                               rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(np.asarray(vsf), np.asarray(vsr),
                               rtol=1e-5, atol=1e-8)


# ---------------- quant x prefix cache ----------------

def test_prefix_pages_carry_scale_rows_through_adopt_cow(serving_flags):
    """Shared-prefix pages on int8 pools: the second (full-cover)
    request adopts the cached pages, COW fires for the recompute row,
    and the store's pages — int8 payload AND f32 scale rows — stay
    bit-identical; outputs match the first request."""
    model, cfg = tiny_model(2)
    rng = np.random.default_rng(9)
    unit = rng.integers(1, cfg.vocab_size, 4)
    prompt = np.concatenate([unit] * 4)  # 16 tokens = 2 pages of 8
    serving_flags({"spec_decode": "ngram", "prefix_cache": True})
    eng = ContinuousBatchingEngine(
        model, tiny_ecfg(True, cache_dtype="int8"))
    r1 = eng.add_request(prompt, max_new_tokens=24)
    drain(eng)
    ref = eng._finished[r1].output
    assert eng.spec_stats["accepted"] > 0  # verify wrote K+1 windows
    pages = [p for p, _ns in eng._prefix._blocks.values()]
    assert len(pages) == 2
    before = [(np.asarray(c.k_pages[:, p]).copy(),
               np.asarray(c.k_scale[:, p]).copy(),
               np.asarray(c.v_scale[:, p]).copy())
              for c in eng.layer_caches for p in pages]

    r2 = eng.add_request(prompt, max_new_tokens=24)
    drain(eng)
    assert eng._finished[r2].output == ref
    assert eng.prefix_stats["cow_copies"] >= 1
    after = [(np.asarray(c.k_pages[:, p]),
              np.asarray(c.k_scale[:, p]),
              np.asarray(c.v_scale[:, p]))
             for c in eng.layer_caches for p in pages]
    for b, a in zip(before, after):
        for bb, aa in zip(b, a):
            np.testing.assert_array_equal(bb, aa)
    # evict returns the pages (and implicitly their scale rows) to the
    # pool cleanly — the refcount audit stays exact
    eng._evict_pages(10 ** 9)
    assert eng._prefix.cached_pages == 0


def test_contig_prefix_store_blocks_carry_scales(serving_flags):
    """Contiguous mode: stored prefix blocks are QuantizedKV pairs —
    a second identical prompt hits the store and reproduces the first
    stream exactly (scale rows inserted with the payload)."""
    model, cfg = tiny_model(2)
    rng = np.random.default_rng(4)
    unit = rng.integers(1, cfg.vocab_size, 8)
    prompt = np.concatenate([unit, unit])
    serving_flags({"prefix_cache": True})
    eng = ContinuousBatchingEngine(
        model, tiny_ecfg(False, cache_dtype="int8"))
    r1 = eng.add_request(prompt, max_new_tokens=8)
    drain(eng)
    base_hits = eng.prefix_stats["hits"]
    r2 = eng.add_request(prompt, max_new_tokens=8)
    drain(eng)
    assert eng.prefix_stats["hits"] > base_hits
    assert eng._finished[r2].output == eng._finished[r1].output


# ---------------- quant x spec rollback ----------------

def test_spec_rollback_int8_pure_length_non_advance(serving_flags):
    """All-rejected verify under int8 KV: the engine advances by
    exactly one token (rollback = length non-advance — scale rows are
    append-only like the pools) and the remaining stream matches the
    spec-off int8 oracle bit-for-bit."""
    model, cfg = tiny_model(6)
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, cfg.vocab_size, 9)
    serving_flags({"spec_decode": "off"})
    ref = ContinuousBatchingEngine(
        model, tiny_ecfg(True, cache_dtype="int8")).run(
        [prompt], max_new_tokens=12)[0].output

    class WrongDrafter(Drafter):
        def __init__(self, oracle):
            self.oracle = oracle
            self.fired = False

        def propose(self, history, k):
            if self.fired:
                return np.zeros((0,), np.int64)
            self.fired = True
            nxt = len(history) - 9
            wrong = [(self.oracle[nxt + j] + 1) % 256 for j in range(k)]
            return np.asarray(wrong, np.int64)

    serving_flags({"spec_decode": "ngram"})
    eng = ContinuousBatchingEngine(
        model, tiny_ecfg(True, cache_dtype="int8"),
        drafter=WrongDrafter(ref))
    rid = eng.add_request(prompt, max_new_tokens=12)
    eng._admit()
    len0 = int(eng.seq_lens[0])
    assert eng.step()
    assert eng.spec_stats["verify_calls"] == 1
    assert eng.spec_stats["accepted"] == 0
    assert int(eng.seq_lens[0]) == len0 + 1  # bonus token only
    drain(eng)
    assert eng._finished[rid].output == ref


# ---------------- quant x crash recovery ----------------

def test_recovery_replay_int8_deterministic_zero_new_programs(
        compile_counter, serving_flags):
    """A seeded step-fault storm on the fully-quantized engine (int8
    weights + int8 KV): outputs stay bit-identical to a clean run
    (deterministic replay re-prefills prompt+history, _rebuild is not
    needed for injected faults) and the whole chaos run compiles ZERO
    programs beyond the clean engine's set."""
    model, cfg = tiny_model(6)
    rng = np.random.default_rng(8)
    prompts = [rng.integers(1, cfg.vocab_size,
                            (int(rng.integers(5, 14)),))
               for _ in range(4)]

    def ecfg():
        return tiny_ecfg(True, cache_dtype="int8", weight_dtype="int8",
                         max_slots=2)

    clean = ContinuousBatchingEngine(model, ecfg())
    ref = [r.output for r in clean.run(prompts, max_new_tokens=10)]
    base = compile_counter()

    chaos = ContinuousBatchingEngine(
        model, ecfg(),
        fault_injector=FaultInjector("step:0.25,seed:3"))
    got = [r.output for r in chaos.run(prompts, max_new_tokens=10)]
    assert got == ref
    assert chaos.resilience_stats["recoveries"] > 0
    # the replayed engine compiled exactly the same program set the
    # clean engine did (each engine compiles its own closures), and
    # recovery added NOTHING on top
    after = compile_counter()
    delta = {k: after[k] - base.get(k, 0) for k in after
             if after[k] - base.get(k, 0)}
    assert delta == base, (
        f"chaos engine's program set {delta} != clean set {base}")
    compile_counter.assert_programs(set(base))


def test_hard_recovery_rebuilds_int8_scale_pools(serving_flags):
    """serve_recovery=all + a real (non-injected) failure: the cache
    REBUILD path reconstructs the int8 pools including their scale
    arrays with identical shapes, and the replayed outputs stay on the
    clean stream."""
    model, cfg = tiny_model(5)
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, cfg.vocab_size, 7)
    serving_flags({"serve_recovery": "all"})  # fixture restores
    eng = ContinuousBatchingEngine(
        model, tiny_ecfg(True, cache_dtype="int8"))
    ref = eng.run([prompt], max_new_tokens=8)[0].output
    shapes = [(c.k_scale.shape, c.v_scale.shape)
              for c in eng.layer_caches]

    eng2 = ContinuousBatchingEngine(
        model, tiny_ecfg(True, cache_dtype="int8"))
    rid = eng2.add_request(prompt, max_new_tokens=8)
    eng2._admit()
    # a host logic error mid-step, recovered under "all": hard path →
    # _rebuild_caches
    boom = {"armed": True}
    orig = eng2._cow_for_decode

    def exploding(k):
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("synthetic device loss")
        return orig(k)

    eng2._cow_for_decode = exploding
    drain(eng2)
    assert eng2.resilience_stats["rebuilds"] == 1
    assert [(c.k_scale.shape, c.v_scale.shape)
            for c in eng2.layer_caches] == shapes
    assert eng2._finished[rid].output == ref


# ---------------- trace-count guard ----------------

def test_int8_weight_serving_program_set_pinned(compile_counter,
                                                serving_flags):
    """int8-weight + int8-KV serving runs through ALL the compiled
    serving programs — prefill_chunk, decode_chunk, spec verify and
    the COW page copy — with no per-dtype program growth: exactly one
    specialization each (the single dtype-variant set)."""
    model, cfg = tiny_model(3)
    rng = np.random.default_rng(5)
    unit = rng.integers(1, cfg.vocab_size, 4)
    prompts = [np.concatenate([unit] * 4),
               rng.integers(1, cfg.vocab_size, 11)]
    serving_flags({"spec_decode": "ngram", "prefix_cache": True})
    eng = ContinuousBatchingEngine(
        model, tiny_ecfg(True, cache_dtype="int8",
                         weight_dtype="int8"))
    eng.run(prompts, max_new_tokens=20)
    # full-cover readmission: prefix adopt + COW page copy
    eng.run([prompts[0]], max_new_tokens=20)
    # per-token scheduler: the plain decode program too
    rid = eng.add_request(prompts[1], max_new_tokens=4)
    drain(eng)
    assert eng.spec_stats["verify_calls"] > 0
    assert eng.prefix_stats["cow_copies"] >= 1
    got = compile_counter()
    assert got == {"prefill_chunk": 1, "decode_chunk": 1,
                   "spec_verify": 1, "page_copy": 1, "decode_step": 1}, got


# ---------------- kernelbench models ----------------

def test_quant_models_report_expected_speedups():
    from benchmarks.kernelbench import (
        llama7b_weight_stream_bytes,
        quant_decode_model,
    )

    int8_alone = quant_decode_model("int8", "bf16", accept_rate=0.0)
    assert int8_alone["modeled_speedup"] >= 1.8
    compound = quant_decode_model("int8", "int8", accept_rate=0.6)
    assert 4.0 <= compound["modeled_speedup"] <= 5.2  # "~4.6x"
    # compounding is real: each factor multiplies
    int8_kv = quant_decode_model("int8", "int8", accept_rate=0.0)
    assert compound["modeled_speedup"] > int8_kv["modeled_speedup"] \
        > int8_alone["modeled_speedup"]
    # int4 halves the stream again
    int4 = quant_decode_model("int4", "bf16", accept_rate=0.0)
    assert int4["modeled_speedup"] > int8_alone["modeled_speedup"]
    # weight stream rows: scale overhead shrinks with group size
    w64 = llama7b_weight_stream_bytes("int8", group_size=64)
    w128 = llama7b_weight_stream_bytes("int8", group_size=128)
    assert w64["stream_bytes"] > w128["stream_bytes"]
    bf16 = llama7b_weight_stream_bytes("bf16")
    assert 1.9 < bf16["stream_bytes"] / w128["stream_bytes"] < 2.0
    # every row is a JSON line on any backend
    for row in (int8_alone, compound, int4, w64, bf16):
        json.dumps(row)


def test_spec_decode_model_weight_byte_width():
    from benchmarks.kernelbench import spec_decode_model

    bf16 = spec_decode_model(0.6, k=4, kvh=8, weight_byte_width=2)
    int8 = spec_decode_model(0.6, k=4, kvh=8, weight_byte_width=1)
    assert bf16["weight_bytes"] == 2 * int8["weight_bytes"]
    int8kv = spec_decode_model(0.6, k=4, kvh=8, weight_byte_width=1,
                               cache_bytes=1, cache_scale_bytes=4)
    assert int8kv["attn_bytes_verify"] < int8["attn_bytes_verify"]
