"""Runtime invariant sanitizer (analysis/sanitizer.py + the engine's
PT_FLAGS_sanitize hooks).

Three claims under test:

1. **Seeded corruptions are CAUGHT, named, and sited** — the PR 7
   fault injector grew state-corruption sites (``leak_ref`` /
   ``scale_desync`` / ``seq_shrink``) that mangle the engine's own
   bookkeeping at the per-tick corruption seam; a sanitized engine
   must raise :class:`SanitizerError` naming the violated invariant
   class and the hook site, for every corruption class, in both cache
   modes where the class applies.

2. **Off = identity** — ``PT_FLAGS_sanitize=off`` (the default)
   constructs NO sanitizer: every hook is a single ``is not None``
   check (the telemetry=off pattern), greedy outputs are bit-identical
   to a sanitized run, and sanitize-on compiles ZERO additional
   programs (compile-count guard).

3. **Thread ownership** — the first ticking thread owns the engine; a
   foreign thread may call only the registered copy-on-read readers
   (``SAFE_READS`` — the same list ptlint's CC rules keep honest), and
   a second thread ticking the engine is flagged immediately.

The whole module rides the chaos marker, so it runs sanitized via the
conftest chaos-lane fixture — the same wiring that makes the PR 7
storms in test_resilience/test_concurrency_soak run with the checker
on.
"""

import threading

import numpy as np
import pytest
import serving_utils as su

from paddle_tpu import flags as F
from paddle_tpu.analysis.sanitizer import SAFE_READS, SanitizerError
from paddle_tpu.inference.resilience import CORRUPT_SITES, FaultInjector
from paddle_tpu.inference.serving import ContinuousBatchingEngine

pytestmark = pytest.mark.chaos


@pytest.fixture
def model():
    m, cfg = su.tiny_model()
    m._tiny_cfg = cfg
    return m


def _prompts(cfg, n=2):
    rng = np.random.default_rng(7)
    return [rng.integers(1, cfg.vocab_size, 9) for _ in range(n)]


def _engine(model, paged, rates=None, **ecfg_kw):
    inj = FaultInjector(rates=rates) if rates else None
    return ContinuousBatchingEngine(
        model, su.tiny_ecfg(paged, **ecfg_kw), fault_injector=inj)


# ---------------------------------------------------------------------------
# 1. seeded corruption classes are caught
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("paged", [True, False])
def test_seq_shrink_caught(model, paged):
    """A cache length falling behind the host token ledger (the replay
    source of truth) trips the seq-len invariant at the same tick."""
    eng = _engine(model, paged, rates={"seq_shrink": 1.0})
    assert eng._san is not None  # chaos lane runs sanitized
    eng.add_request(_prompts(model._tiny_cfg)[0], 8)
    with pytest.raises(SanitizerError) as ei:
        su.drain(eng, step=lambda: eng.step_chunk(4))
    assert ei.value.invariant == "seq-len"
    assert ei.value.site == "step_chunk"
    assert "ledger" in str(ei.value)


def test_leak_ref_caught_paged(model):
    """A page refcount with no recounted owner (slot block tables +
    prefix-store retains) is a leak: the page can never free."""
    eng = _engine(model, paged=True, rates={"leak_ref": 1.0})
    eng.add_request(_prompts(model._tiny_cfg)[0], 8)
    with pytest.raises(SanitizerError) as ei:
        su.drain(eng, step=lambda: eng.step_chunk(4))
    assert ei.value.invariant == "page-conservation"
    assert "owner" in str(ei.value)


def test_leak_ref_contiguous_leaks_slot(model):
    """Contiguous mode has no page pool: the same site leaks a slot
    off the free heap instead — the slot-heap partition invariant."""
    eng = _engine(model, paged=False, rates={"leak_ref": 1.0},
                  max_slots=3)
    eng.add_request(_prompts(model._tiny_cfg)[0], 8)
    with pytest.raises(SanitizerError) as ei:
        su.drain(eng, step=lambda: eng.step_chunk(4))
    assert ei.value.invariant == "slot-heap"


@pytest.mark.parametrize("paged", [True, False])
def test_scale_desync_caught_int8(model, paged, serving_flags):
    """int8 pools: shearing a dequant-scale array off its payload pool
    (adopt/COW/rebuild bookkeeping gone wrong) trips shape agreement."""
    serving_flags({"kv_cache_dtype": "int8"})
    eng = _engine(model, paged, rates={"scale_desync": 1.0},
                  cache_dtype="int8")
    eng.add_request(_prompts(model._tiny_cfg)[0], 8)
    with pytest.raises(SanitizerError) as ei:
        su.drain(eng, step=lambda: eng.step_chunk(4))
    assert ei.value.invariant == "scale-pool"
    assert "scale" in str(ei.value)


def test_direct_corruption_without_injector(model):
    """The checker judges STATE, not the injector: hand-corrupting the
    pool is caught by an explicit check_tick call too."""
    eng = _engine(model, paged=True)
    eng.add_request(_prompts(model._tiny_cfg)[0], 6)
    eng.step_chunk(4)
    slot = next(iter(eng._slot_req))
    page = eng.pool.pages_of[slot][0]
    eng.pool.ref[page] += 1  # leak: one refcount, no owner
    with pytest.raises(SanitizerError) as ei:
        eng._san.check_tick(eng, "manual")
    assert ei.value.invariant == "page-conservation"
    assert ei.value.site == "manual"


def test_corrupt_sites_are_appended_not_inserted():
    """Corruption sites extend SITES at the END: per-site RNG streams
    seed on the site INDEX, so appending preserves every pre-existing
    chaos schedule (seeded storms stay reproducible across versions)."""
    from paddle_tpu.inference.resilience import ROUTER_SITES, SITES

    assert SITES[:4] == ("step", "nan", "latency", "pool")
    assert tuple(SITES[4:7]) == CORRUPT_SITES
    # PR 11's replica-level router sites append AFTER the corruption
    # sites — same index-seeded-stream reasoning, same pin
    assert tuple(SITES[7:]) == ROUTER_SITES
    # and a legacy spec still parses while new sites rate-limit to 0
    inj = FaultInjector("step:0.5,seed:3")
    assert all(inj.rates[s] == 0.0
               for s in CORRUPT_SITES + ROUTER_SITES)


# ---------------------------------------------------------------------------
# 2. off = identity; on = zero new programs, identical outputs
# ---------------------------------------------------------------------------
def test_sanitize_off_is_identity_and_on_changes_nothing(
        model, compile_counter):
    """Flag off constructs NO sanitizer (hooks are one identity check);
    flag on changes neither greedy outputs nor the compiled-program
    set — the telemetry no-op contract, applied to the sanitizer."""
    prompts = _prompts(model._tiny_cfg)
    saved = F.flag("sanitize")
    try:
        F.set_flags({"sanitize": False})
        eng_off = _engine(model, paged=True)
        assert eng_off._san is None
        outs_off = [r.output for r in eng_off.run(prompts, 12)]
        base = compile_counter()
        F.set_flags({"sanitize": True})
        eng_on = _engine(model, paged=True)
        assert eng_on._san is not None
        outs_on = [r.output for r in eng_on.run(prompts, 12)]
    finally:
        F.set_flags({"sanitize": saved})
    assert outs_on == outs_off
    # each engine compiles its own closures: the sanitized engine's
    # delta must be exactly the clean engine's program set again —
    # the sanitizer adds ZERO compiled programs
    after = compile_counter()
    delta = {k: after[k] - base.get(k, 0) for k in after
             if after[k] - base.get(k, 0)}
    assert delta == base, (
        f"sanitized engine's program set {delta} != clean set {base}")


# ---------------------------------------------------------------------------
# 3. thread ownership
# ---------------------------------------------------------------------------
def _run_in_thread(fn):
    box = {}

    def tgt():
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001
            box["exc"] = e

    t = threading.Thread(target=tgt)
    t.start()
    t.join(10)
    return box


def test_foreign_thread_reads(model):
    """Registered copy-on-read readers pass from a scrape thread;
    an unregistered read of scheduler state is flagged, naming the
    registration path."""
    eng = _engine(model, paged=True)
    eng.add_request(_prompts(model._tiny_cfg)[0], 6)
    eng.step_chunk(2)  # records the owner thread
    ok = _run_in_thread(lambda: (eng.backpressure(),
                                 eng.metrics_snapshot(),
                                 eng.slo_snapshot()))
    assert "exc" not in ok, ok.get("exc")
    bad = _run_in_thread(lambda: eng._san.check_read("raw_state_peek"))
    assert isinstance(bad.get("exc"), SanitizerError)
    assert bad["exc"].invariant == "thread-ownership"
    assert "SAFE_READS" in str(bad["exc"])


def test_second_thread_tick_flagged(model):
    eng = _engine(model, paged=False)
    eng.add_request(_prompts(model._tiny_cfg)[0], 6)
    eng.step_chunk(2)
    bad = _run_in_thread(lambda: eng.step_chunk(2))
    assert isinstance(bad.get("exc"), SanitizerError)
    assert bad["exc"].invariant == "scheduler-ownership"


def test_safe_reads_exist_on_engine(model):
    """SAFE_READS is a registry of real readers — a renamed snapshot
    method must update the registration (and the ptlint CC scope)
    with it. Engine readers live on the engine; the router-only
    readers (PR 11) live on ``EngineRouter``."""
    from paddle_tpu.inference.router import EngineRouter

    router_only = {"fleet_snapshot"}
    eng = _engine(model, paged=False)
    for name in SAFE_READS - router_only:
        assert callable(getattr(eng, name)), name
    # class-level: the contract needs no replica engines built
    for name in router_only | {"backpressure", "metrics_snapshot"}:
        assert callable(getattr(EngineRouter, name)), name


# ---------------------------------------------------------------------------
# sanitized chaos storm: recovery machinery keeps every invariant
# ---------------------------------------------------------------------------
def test_sanitized_chaos_storm_keeps_invariants(model):
    """PR 7's quarantine/replay under a step+NaN storm, with the
    checker on at every tick: recovery must leave conservation intact
    each tick (this is the lane-level claim `pytest -m chaos` now
    makes on every storm)."""
    eng = _engine(model, paged=True)
    assert eng._san is not None
    eng._injector = FaultInjector("step:0.2,nan:0.1", seed=11)
    for p in _prompts(model._tiny_cfg, 3):
        eng.add_request(p, 8)
    su.drain(eng, step=lambda: eng.step_chunk(4))
    assert eng.resilience_stats["recoveries"] > 0
    # post-storm: pool fully recovered (active slots drained)
    eng._san.check_tick(eng, "post-storm")
    assert not eng.active.any()
