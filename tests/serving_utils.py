"""Shared serving-engine test helpers.

The greedy-parity machinery (tiny model + engine config factories, the
drain loop, the mixed-prompt workload, and THE spec-off-vs-on parity
comparison) started life in test_spec_decode.py; the quantized-serving
suite (test_quant_serving.py) runs the same comparisons under int8
weights / int8 KV pools, so the helpers live here once instead of being
copy-pasted per suite. Import from test modules as ``import
serving_utils`` (pytest puts tests/ on sys.path).
"""

import numpy as np

from paddle_tpu.analysis.program_audit import (
    tiny_engine_config,
    tiny_model,  # noqa: F401  (re-export: suites import it from here)
)
from paddle_tpu.inference.serving import ContinuousBatchingEngine

# the tiny model/engine factories live with the contract auditor
# (analysis/program_audit.py) — ONE source of truth for the
# CPU-friendly shapes both the audits and these suites trace at
tiny_ecfg = tiny_engine_config


def drain(eng, step=None):
    step = step or eng.step
    while step() or eng._queue or eng.active.any():
        pass


def mixed_prompts(cfg, rng):
    """Repetitive prompts (drafts fire) + a random one + a ragged short
    one — and callers add one request whose 1-token budget can NEVER
    draft (see ``spec_parity_outputs``)."""
    unit = rng.integers(1, cfg.vocab_size, 4)
    return [
        np.concatenate([unit] * 5),                       # periodic
        rng.integers(1, cfg.vocab_size, 11),              # random
        np.concatenate([rng.integers(1, cfg.vocab_size, 3), unit, unit]),
    ]


def spec_parity_outputs(model, make_ecfg, prompts, set_flags,
                        max_new_tokens=24, never_drafts_probe=True,
                        flags_extra=None):
    """THE greedy spec-parity comparison: the same workload runs
    spec-off and spec-ngram (fresh engine per arm, ``make_ecfg()``
    builds each arm's config), returning ``({mode: outputs},
    {mode: spec_snapshot})``. ``never_drafts_probe`` appends a 1-token
    request whose budget leaves no draft headroom. ``flags_extra``
    merges extra serving flags into each arm (e.g. prefix_cache).
    Callers restore flags via their ``serving_flags`` fixture."""
    outs, snaps = {}, {}
    for mode in ("off", "ngram"):
        fl = {"spec_decode": mode}
        if flags_extra:
            fl.update(flags_extra)
        set_flags(fl)
        eng = ContinuousBatchingEngine(model, make_ecfg())
        reqs = eng.run(prompts, max_new_tokens=max_new_tokens)
        if never_drafts_probe:
            reqs += eng.run([prompts[0]], max_new_tokens=1)
        outs[mode] = [r.output for r in reqs]
        snaps[mode] = eng.spec_snapshot()
    return outs, snaps


def assert_spec_parity(outs, snaps, require_accepts=True):
    """Spec-on greedy outputs must be bit-identical to spec-off — and
    the spec arm must actually have accepted drafts (or the comparison
    proves nothing), while the off arm must never have verified."""
    if require_accepts:
        assert snaps["ngram"]["verify_calls"] > 0
        assert snaps["ngram"]["accepted"] > 0
        assert snaps["ngram"]["emitted"] > snaps["ngram"]["verify_calls"]
    assert snaps["off"]["verify_calls"] == 0
    assert snaps["off"]["proposed"] == 0
    assert outs["ngram"] == outs["off"]
