"""Extended nn coverage: conv variants, RNNs, activations, losses, norms
(parity: paddle.nn layer set, test/legacy_test op tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def _rand(shape, seed=0):
    return np.random.default_rng(seed).standard_normal(shape).astype(
        np.float32)


# ---------------- convs ----------------

def test_conv1d_matches_manual():
    x = jnp.asarray(_rand((2, 3, 10)))
    lyr = nn.Conv1D(3, 5, 3, padding=1)
    y = lyr(x)
    assert y.shape == (2, 5, 10)
    # compare against conv2d with a dummy height dim
    w2 = lyr.weight.value[:, :, None, :]
    y2 = F.conv2d(x[:, :, None, :], w2, lyr.bias, stride=1,
                  padding=[(0, 0), (1, 1)])
    np.testing.assert_allclose(np.asarray(y), np.asarray(y2[:, :, 0]),
                               rtol=1e-5, atol=1e-5)


def test_conv3d_shape_and_identity_kernel():
    x = jnp.asarray(_rand((1, 2, 4, 6, 6)))
    lyr = nn.Conv3D(2, 2, 1, bias_attr=False)
    # identity kernel: out[c] = in[c]
    eye = np.zeros((2, 2, 1, 1, 1), np.float32)
    eye[0, 0] = eye[1, 1] = 1.0
    lyr.weight.value = jnp.asarray(eye)
    np.testing.assert_allclose(np.asarray(lyr(x)), np.asarray(x),
                               rtol=1e-6)


def test_conv2d_transpose_inverts_stride():
    x = jnp.asarray(_rand((1, 3, 5, 5)))
    lyr = nn.Conv2DTranspose(3, 4, 3, stride=2, padding=1,
                             output_padding=1)
    y = lyr(x)
    assert y.shape == (1, 4, 10, 10)
    # torch cross-check (cpu torch is available in the image)
    import torch

    ty = torch.nn.functional.conv_transpose2d(
        torch.tensor(np.asarray(x)),
        torch.tensor(np.asarray(lyr.weight.value)),
        torch.tensor(np.asarray(lyr.bias.value)),
        stride=2, padding=1, output_padding=1)
    np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_pool1d():
    x = jnp.asarray(_rand((2, 3, 8)))
    my = nn.MaxPool1D(2)(x)
    ay = nn.AvgPool1D(2)(x)
    xr = np.asarray(x).reshape(2, 3, 4, 2)
    np.testing.assert_allclose(np.asarray(my), xr.max(-1), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ay), xr.mean(-1), rtol=1e-6)


# ---------------- rnns ----------------

@pytest.mark.parametrize("cls,gates", [(nn.SimpleRNN, 1), (nn.GRU, 3),
                                       (nn.LSTM, 4)])
def test_rnn_shapes_and_state(cls, gates):
    pt.seed(0)
    rnn = cls(6, 8, num_layers=2, direction="bidirect")
    x = jnp.asarray(_rand((3, 5, 6)))
    out, state = rnn(x)
    assert out.shape == (3, 5, 16)  # bidirectional concat
    if cls is nn.LSTM:
        h, c = state
        assert h.shape == (4, 3, 8) and c.shape == (4, 3, 8)
    else:
        assert state.shape == (4, 3, 8)


def test_lstm_matches_torch():
    import torch

    pt.seed(0)
    rnn = nn.LSTM(4, 5)
    t = torch.nn.LSTM(4, 5, batch_first=True)
    # copy our params into torch (torch stores transposed)
    sd = {
        "weight_ih_l0": np.asarray(rnn.weight_ih_l0.value).T,
        "weight_hh_l0": np.asarray(rnn.weight_hh_l0.value).T,
        "bias_ih_l0": np.asarray(rnn.bias_ih_l0.value),
        "bias_hh_l0": np.asarray(rnn.bias_hh_l0.value),
    }
    t.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    x = _rand((2, 7, 4))
    out, (h, c) = rnn(jnp.asarray(x))
    tout, (th, tc) = t(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(out), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h[0]), th[0].detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_gru_matches_torch():
    import torch

    pt.seed(1)
    rnn = nn.GRU(4, 5)
    t = torch.nn.GRU(4, 5, batch_first=True)
    sd = {
        "weight_ih_l0": np.asarray(rnn.weight_ih_l0.value).T,
        "weight_hh_l0": np.asarray(rnn.weight_hh_l0.value).T,
        "bias_ih_l0": np.asarray(rnn.bias_ih_l0.value),
        "bias_hh_l0": np.asarray(rnn.bias_hh_l0.value),
    }
    t.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    x = _rand((2, 7, 4))
    out, h = rnn(jnp.asarray(x))
    tout, th = t(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(out), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-4)


# ---------------- activations / misc ----------------

def test_new_activations_numerics():
    x = jnp.asarray(_rand((50,), seed=3))
    xn = np.asarray(x)
    np.testing.assert_allclose(np.asarray(nn.PReLU(1, 0.2)(x)),
                               np.where(xn > 0, xn, 0.2 * xn), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(nn.Softsign()(x)),
                               xn / (1 + np.abs(xn)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(nn.Tanhshrink()(x)),
                               xn - np.tanh(xn), rtol=1e-5, atol=1e-6)
    hs = np.asarray(nn.Hardshrink(0.5)(x))
    np.testing.assert_allclose(hs, np.where(np.abs(xn) > 0.5, xn, 0))
    ss = np.asarray(nn.Softshrink(0.5)(x))
    ref = np.where(xn > 0.5, xn - 0.5, np.where(xn < -0.5, xn + 0.5, 0))
    np.testing.assert_allclose(ss, ref, rtol=1e-6)


def test_prelu_per_channel():
    x = jnp.asarray(_rand((2, 3, 4, 4), seed=4))
    p = nn.PReLU(3, 0.1)
    p.weight.value = jnp.asarray([0.1, 0.2, 0.3])
    y = np.asarray(p(x))
    xn = np.asarray(x)
    for c, a in enumerate([0.1, 0.2, 0.3]):
        np.testing.assert_allclose(
            y[:, c], np.where(xn[:, c] > 0, xn[:, c], a * xn[:, c]),
            rtol=1e-6)


def test_losses():
    a = jnp.asarray(_rand((10,), seed=5))
    b = jnp.asarray(_rand((10,), seed=6))
    an, bn = np.asarray(a), np.asarray(b)
    sl = float(nn.SmoothL1Loss()(a, b))
    d = np.abs(an - bn)
    ref = np.where(d < 1, 0.5 * d * d, d - 0.5).mean()
    np.testing.assert_allclose(sl, ref, rtol=1e-5)

    logp = jnp.asarray(np.log(np.full((4, 3), 1 / 3, np.float32)))
    probs = jnp.asarray(np.array([[0.2, 0.3, 0.5]] * 4, np.float32))
    kl = float(nn.KLDivLoss(reduction="batchmean")(logp, probs))
    ref = (np.array([0.2, 0.3, 0.5]) *
           (np.log([0.2, 0.3, 0.5]) - np.log(1 / 3))).sum()
    np.testing.assert_allclose(kl, ref, rtol=1e-5)

    mr = float(nn.MarginRankingLoss(margin=0.1)(a, b,
                                                jnp.ones_like(a)))
    ref = np.maximum(0, -(an - bn) + 0.1).mean()
    np.testing.assert_allclose(mr, ref, rtol=1e-5)


def test_instance_norm_and_sync_bn():
    x = jnp.asarray(_rand((2, 3, 8, 8), seed=7))
    inorm = nn.InstanceNorm2D(3)
    y = np.asarray(inorm(x))
    np.testing.assert_allclose(y.mean(axis=(2, 3)), 0, atol=1e-5)
    np.testing.assert_allclose(y.std(axis=(2, 3)), 1, atol=1e-3)
    net = nn.Sequential(nn.Conv2D(3, 4, 1), nn.BatchNorm2D(4))
    nn.SyncBatchNorm.convert_sync_batchnorm(net)
    assert type(net._sub_layers["1"]) is nn.SyncBatchNorm


def test_misc_layers():
    x1 = jnp.asarray(_rand((4, 3), seed=8))
    x2 = jnp.asarray(_rand((4, 5), seed=9))
    bl = nn.Bilinear(3, 5, 2)
    y = bl(x1, x2)
    assert y.shape == (4, 2)
    ref = np.einsum("bi,oij,bj->bo", np.asarray(x1),
                    np.asarray(bl.weight.value), np.asarray(x2)) + \
        np.asarray(bl.bias.value)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5)

    ps = nn.PixelShuffle(2)
    x = jnp.asarray(_rand((1, 8, 3, 3), seed=10))
    out = ps(x)
    assert out.shape == (1, 2, 6, 6)
    import torch

    tref = torch.pixel_shuffle(torch.tensor(np.asarray(x)), 2).numpy()
    np.testing.assert_allclose(np.asarray(out), tref, rtol=1e-6)

    pad = nn.Pad2D([1, 2, 3, 4])
    assert pad(jnp.zeros((1, 1, 5, 5))).shape == (1, 1, 12, 8)

    cs = nn.CosineSimilarity()(x1, jnp.asarray(_rand((4, 3), seed=11)))
    assert cs.shape == (4,)
    uf = nn.Unflatten(1, (2, 4))
    assert uf(jnp.zeros((3, 8))).shape == (3, 2, 4)


def test_dropout2d_drops_whole_channels():
    pt.seed(0)
    d = nn.Dropout2D(0.5)
    x = jnp.ones((8, 16, 4, 4))
    y = np.asarray(d(x))
    per_channel = y.reshape(8, 16, -1)
    # each channel is either all zero or all scaled
    for b in range(8):
        for c in range(16):
            vals = np.unique(per_channel[b, c])
            assert len(vals) == 1 and vals[0] in (0.0, 2.0)
    d.eval()
    np.testing.assert_allclose(np.asarray(d(x)), np.asarray(x))


def test_lstm_initial_states_used():
    import torch

    pt.seed(2)
    rnn = nn.LSTM(4, 5)
    x = _rand((2, 3, 4), seed=12)
    h0 = _rand((1, 2, 5), seed=13)
    c0 = _rand((1, 2, 5), seed=14)
    out0, _ = rnn(jnp.asarray(x))
    out1, _ = rnn(jnp.asarray(x),
                  (jnp.asarray(h0), jnp.asarray(c0)))
    assert not np.allclose(np.asarray(out0), np.asarray(out1))
    t = torch.nn.LSTM(4, 5, batch_first=True)
    t.load_state_dict({
        "weight_ih_l0": torch.tensor(np.asarray(rnn.weight_ih_l0.value).T),
        "weight_hh_l0": torch.tensor(np.asarray(rnn.weight_hh_l0.value).T),
        "bias_ih_l0": torch.tensor(np.asarray(rnn.bias_ih_l0.value)),
        "bias_hh_l0": torch.tensor(np.asarray(rnn.bias_hh_l0.value)),
    })
    tout, _ = t(torch.tensor(x), (torch.tensor(h0), torch.tensor(c0)))
    np.testing.assert_allclose(np.asarray(out1), tout.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_maxpool1d_bf16_negative():
    x = jnp.asarray([[-5.0, -4.0, -3.0, -2.0]], jnp.bfloat16)[None]
    y = np.asarray(nn.MaxPool1D(2)(x), np.float32)
    np.testing.assert_allclose(y[0, 0], [-4.0, -2.0])


def test_avgpool1d_exclusive_padding():
    x = jnp.asarray([[[1.0, 2.0, 3.0, 4.0]]])
    y = np.asarray(nn.AvgPool1D(2, stride=2, padding=1)(x))
    np.testing.assert_allclose(y[0, 0], [1.0, 2.5, 4.0])


def test_instance_norm_attr_independence():
    a = nn.InstanceNorm2D(3, bias_attr=False)
    assert a.scale is not None and a.bias is None
    b = nn.InstanceNorm2D(3, weight_attr=False)
    assert b.scale is None and b.bias is not None
    x = jnp.asarray(_rand((1, 3, 4, 4), seed=15))
    assert a(x).shape == x.shape and b(x).shape == x.shape


def test_swish_is_silu_alias():
    assert nn.Swish is nn.SiLU


def test_transformer_decoder_and_seq2seq():
    """paddle.nn.Transformer parity: encoder-decoder forward, causal
    target mask, cross-attention over memory, decode cache."""
    import paddle_tpu as pt
    from paddle_tpu import nn

    pt.seed(0)
    model = nn.Transformer(d_model=32, nhead=4, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=64,
                           dropout=0.0)
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.standard_normal((2, 10, 32)), jnp.float32)
    tgt = jnp.asarray(rng.standard_normal((2, 6, 32)), jnp.float32)
    tmask = nn.Transformer.generate_square_subsequent_mask(6)
    out = model(src, tgt, tgt_mask=tmask[None, None])
    assert out.shape == (2, 6, 32)
    assert bool(jnp.all(jnp.isfinite(out)))
    # causal mask respected: truncating the target must not change the
    # outputs for the shared prefix
    out4 = model(src, tgt[:, :4], tgt_mask=tmask[None, None, :4, :4])
    np.testing.assert_allclose(np.asarray(out[:, :4]), np.asarray(out4),
                               rtol=2e-4, atol=2e-4)
    # memory matters: different encoder input changes the output
    out_b = model(src * 2.0, tgt, tgt_mask=tmask[None, None])
    assert float(jnp.abs(out - out_b).max()) > 1e-3
    # paddle-convention mask: additive float 0/-inf
    assert tmask.dtype == jnp.float32
    assert float(tmask[0, 1]) == float("-inf") and float(tmask[1, 0]) == 0.0
    # incremental decode cache threaded through the WHOLE decoder stack,
    # with the cross-attention K/V precomputed once (StaticCache)
    memory = model.encoder(src)
    static = model.decoder.gen_static_cache(memory)
    assert static[0][0].shape == (2, 10, 4, 8)
    k0 = jnp.zeros((2, 0, 4, 8), jnp.float32)
    caches = [(k0, k0) for _ in model.decoder.layers]
    y1, caches = model.decoder(tgt[:, :1], memory, cache=caches,
                               static_cache=static)
    assert caches[0][0].shape == (2, 1, 4, 8)
    y2, caches = model.decoder(tgt[:, 1:2], memory, cache=caches,
                               static_cache=static)
    assert caches[1][0].shape == (2, 2, 4, 8)
    # incremental outputs match the full (masked) forward
    full = model.decoder(tgt[:, :2], memory,
                         tgt_mask=tmask[None, None, :2, :2])
    np.testing.assert_allclose(np.asarray(y2[:, 0]),
                               np.asarray(full[:, 1]), rtol=2e-4,
                               atol=2e-4)
