"""Flight-data layer (ISSUE 13): time-series metric history, SLO
burn-rate alerting, and per-request device-cost attribution.

Claims pinned here:

1. **Off-flags are true no-ops** — with ``timeseries``/``alerts`` off
   (and with ``cost_attribution`` off) the engine compiles EXACTLY the
   same program set and emits bit-identical greedy outputs.
2. **Deterministic alerting** — a seeded saturation/fault storm fires
   the SLO burn-rate alert at the SAME ticks across runs and across
   both cache modes, with the triggering series window attached to the
   FlightRecorder artifact.
3. **Cost reconciliation** — at profiler cadence 1, per-request
   attributed device-ms sums reconcile with the profiler's per-program
   totals to float rounding; cost travels in the request ledger across
   drain/failover handoffs.
4. **Scrape safety** — timeline/alert/cost readers stay well-formed
   (no torn windows) under a producer-thread fault storm with the
   sanitizer on (chaos lane).
"""

import json
import threading
import time

import numpy as np
import pytest

import serving_utils
from paddle_tpu import flags as F
from paddle_tpu.inference.resilience import FaultInjector
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.observability import alerts as A
from paddle_tpu.observability import timeseries as TS

# the programs whose device wall is split across requests — page_copy
# and the prefix insert/read programs are engine overhead, documented
# as outside the attribution rule
ATTRIBUTED = {"decode_step", "decode_chunk", "spec_verify",
              "prefill_chunk", "prefill_bucket"}


@pytest.fixture
def flight_flags():
    """set_flags with restore for every knob this suite flips."""
    keys = ("timeseries", "timeseries_cadence", "timeseries_retention",
            "alerts", "cost_attribution", "slo_degradation",
            "telemetry", "telemetry_dump_dir", "trace_sample",
            "profile_programs", "profile_sample_every", "spec_decode",
            "fault_inject", "sanitize")
    saved = {k: F.flag(k) for k in keys}
    yield F.set_flags
    F.set_flags(saved)


# ---------------------------------------------------------------------------
# TimeSeriesStore mechanics
# ---------------------------------------------------------------------------
def test_store_windows_deltas_rates():
    st = TS.TimeSeriesStore(label="u", cadence=2, retention=16)
    vals = {"c": 0.0}

    def collect():
        return {"counters": dict(vals), "gauges": {"g": vals["c"]},
                "percentiles": {"p": None}}

    out = []
    for _ in range(6):
        vals["c"] += 3.0
        out.append(st.on_tick(collect))
    # windows close on ticks 2, 4, 6 only
    assert [s is not None for s in out] == [False, True] * 3
    series = st.series()
    assert [s["tick"] for s in series] == [2, 4, 6]
    # each window saw exactly 2 ticks x +3
    assert all(s["deltas"]["c"] == 6.0 for s in series)
    assert all(s["rates"]["c"] == 3.0 for s in series)
    assert series[0]["counters"]["c"] == 6.0  # cumulative view kept
    assert series[-1]["gauges"]["g"] == 18.0
    assert series[0]["window_ticks"] == 2


def test_store_counter_reset_never_goes_negative():
    """Prometheus counter-reset convention: a source reset between
    windows (the goodput sweep clears slo_stats/_finished per QPS
    step) restarts the delta from the post-reset count — a negative
    delta would poison every window-aggregating alert rule."""
    st = TS.TimeSeriesStore(label="u3", cadence=1, retention=8)
    st.on_tick(lambda: {"counters": {"c": 40.0}})
    st.on_tick(lambda: {"counters": {"c": 43.0}})
    # reset: the source dropped to 2 (counts since the reset)
    st.on_tick(lambda: {"counters": {"c": 2.0}})
    st.on_tick(lambda: {"counters": {"c": 5.0}})
    assert [s["deltas"]["c"] for s in st.series()] \
        == [40.0, 3.0, 2.0, 3.0]
    assert all(s["deltas"]["c"] >= 0 for s in st.series())


def test_store_retention_bound_and_copy_on_read():
    st = TS.TimeSeriesStore(label="u2", cadence=1, retention=3)
    for i in range(7):
        st.on_tick(lambda: {"counters": {"c": float(i)}})
    series = st.series()
    assert len(series) == 3 and len(st) == 3
    assert [s["tick"] for s in series] == [5, 6, 7]
    # reader owns its list: mutating it cannot touch the ring
    series.clear()
    assert len(st.series()) == 3
    assert st in TS.stores()


# ---------------------------------------------------------------------------
# alert rules: hysteresis + detectors over synthetic samples
# ---------------------------------------------------------------------------
def _sample(tick, deltas=None, gauges=None):
    return {"tick": tick, "window_ticks": 1, "t": 0.0, "wall_s": None,
            "counters": {}, "deltas": deltas or {},
            "gauges": gauges or {}, "percentiles": {}}


def _burn_sample(tick, met, violated):
    return _sample(tick, deltas={"slo_met:interactive": float(met),
                                 "slo_violated:interactive":
                                     float(violated)})


def test_burn_rule_hysteresis_no_flapping():
    r = A.SLOBurnRate(budget=0.1, threshold=2.0, fire_for=2,
                      clear_for=3)
    samples = [_burn_sample(1, 4, 0)]
    assert r.update(samples) is None and not r.active
    # one bad window: streak 1 < fire_for — no fire yet
    samples.append(_burn_sample(2, 0, 4))
    assert r.update(samples) is None and not r.active
    samples.append(_burn_sample(3, 0, 4))
    assert r.update(samples) == "fire" and r.active
    assert r.fired == 1 and r.value >= 2.0
    # healthy windows: needs clear_for consecutive to clear
    samples.append(_burn_sample(4, 4, 0))
    assert r.update(samples) is None and r.active
    samples.append(_burn_sample(5, 4, 0))
    assert r.update(samples) is None and r.active
    samples.append(_burn_sample(6, 4, 0))
    assert r.update(samples) == "clear" and not r.active
    # alternating bad/good can never fire a fire_for=2 rule
    r2 = A.SLOBurnRate(budget=0.1, threshold=2.0, fire_for=2,
                       clear_for=3)
    s2 = []
    for i in range(12):
        s2.append(_burn_sample(i + 1, 0 if i % 2 else 4,
                               4 if i % 2 else 0))
        assert r2.update(s2) is None
    assert not r2.active and r2.fired == 0


def test_burn_rule_needs_both_windows():
    # slow window healthy, fast window bad: min(fast, slow) stays low
    r = A.SLOBurnRate(budget=0.5, threshold=2.0, fast_windows=1,
                      slow_windows=4, fire_for=1)
    samples = [_burn_sample(i, 8, 0) for i in range(1, 4)]
    samples.append(_burn_sample(4, 0, 8))
    assert r.update(samples) is None
    assert r.value < 2.0


def test_queue_growth_and_hbm_and_recompile_rules():
    q = A.QueueDepthGrowth(windows=3, min_depth=2, fire_for=1)
    s = [_sample(1, gauges={"queue_depth": 1.0}),
         _sample(2, gauges={"queue_depth": 2.0}),
         _sample(3, gauges={"queue_depth": 4.0})]
    assert q.update(s) == "fire"
    # plateau is not growth
    q2 = A.QueueDepthGrowth(windows=3, min_depth=2, fire_for=1)
    s2 = s[:2] + [_sample(3, gauges={"queue_depth": 2.0})]
    assert q2.update(s2) is None

    h = A.HbmResidency(threshold=0.9, fire_for=1)
    assert h.update([_sample(1, gauges={"kv_utilization": 0.95})]) \
        == "fire"
    r = A.RecompilePostSeal()
    assert r.update([_sample(1, deltas={"recompiles": 1.0})]) == "fire"
    assert r.update([_sample(2, deltas={"recompiles": 0.0})]) is None


def test_ratio_collapse_needs_healthy_baseline():
    kw = dict(floor=0.25, healthy=0.5, baseline_windows=2,
              min_den=4.0, fire_for=1)
    mk = lambda t, hit, tot: _sample(  # noqa: E731
        t, deltas={"prefix_hit_tokens": float(hit),
                   "prefix_prompt_tokens": float(tot)})
    # healthy baseline then collapse: fires
    r = A.PrefixHitCollapse(**kw)
    s = [mk(1, 6, 10), mk(2, 6, 10), mk(3, 0, 10)]
    assert r.update(s) == "fire"
    # cold cache from the start: never "collapsed", no fire
    r2 = A.PrefixHitCollapse(**kw)
    s2 = [mk(1, 0, 10), mk(2, 0, 10), mk(3, 0, 10)]
    assert r2.update(s2) is None
    # spec twin shares the machinery
    r3 = A.SpecAcceptCollapse(floor=0.25, healthy=0.5,
                              baseline_windows=2, min_den=4.0,
                              fire_for=1)
    mk3 = lambda t, a, p: _sample(  # noqa: E731
        t, deltas={"spec_accepted": float(a),
                   "spec_proposed": float(p)})
    assert r3.update([mk3(1, 6, 10), mk3(2, 6, 10),
                      mk3(3, 0, 10)]) == "fire"


def test_manager_rejects_unregistered_rule():
    class Rogue(A.AlertRule):
        name = "not_in_registry"

        def check(self, samples):
            return False, {}

    with pytest.raises(ValueError, match="ALERT_RULES"):
        A.AlertManager(rules=[Rogue()])
    with pytest.raises(ValueError, match="duplicate"):
        A.AlertManager(rules=[A.SLOBurnRate(), A.SLOBurnRate()])


def test_alert_rules_registry_matches_defaults():
    """Runtime twin of ptlint OBS002: the default rule set covers the
    canonical registry exactly."""
    assert {r.name for r in A.default_rules()} == set(A.ALERT_RULES)


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------
def _run_workload(paged, max_new=8, n=3, seed=0):
    model, cfg = serving_utils.tiny_model(seed=seed)
    eng = ContinuousBatchingEngine(model, serving_utils.tiny_ecfg(paged))
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(1, cfg.vocab_size, 10) for _ in range(n)]
    reqs = eng.run(prompts, max_new_tokens=max_new, max_chunk=4)
    return eng, reqs


@pytest.mark.parametrize("paged", [False, True])
def test_off_flags_identity_zero_new_programs(paged, flight_flags,
                                              compile_counter):
    """timeseries/alerts/cost off vs on: bit-identical outputs and the
    EXACT same compiled-program set (the flight-data layer is host
    bookkeeping — zero new compiled programs either way)."""
    from paddle_tpu.inference import serving

    arms = {}
    programs = {}
    for arm, fl in (
            ("all_off", {"timeseries": False, "alerts": False,
                         "cost_attribution": False}),
            ("all_on", {"timeseries": True, "timeseries_cadence": 2,
                        "alerts": True, "cost_attribution": True}),
    ):
        flight_flags(fl)
        base = dict(serving.TRACE_COUNTS)
        eng, reqs = _run_workload(paged)
        arms[arm] = [r.output for r in reqs]
        programs[arm] = {k: v - base.get(k, 0)
                         for k, v in serving.TRACE_COUNTS.items()
                         if v - base.get(k, 0)}
        if arm == "all_off":
            assert eng._ts is None and eng._alerts is None
            assert not eng._cost_enabled
            assert eng.timeline_snapshot() == {"enabled": False}
            assert eng.alerts_snapshot() == {"enabled": False}
            assert eng.cost_snapshot() == {"enabled": False}
            assert all(r.device_ms == 0.0 for r in reqs)
    assert arms["all_on"] == arms["all_off"]
    assert programs["all_on"] == programs["all_off"]
    compile_counter.assert_programs(set(programs["all_off"]))


def test_engine_timeline_windows(flight_flags):
    flight_flags({"timeseries": True, "timeseries_cadence": 2,
                  "alerts": True})
    eng, reqs = _run_workload(paged=True)
    tl = eng.timeline_snapshot()
    assert tl["enabled"] and tl["cadence"] == 2
    series = tl["series"]
    assert tl["windows"] == len(series) >= 1
    # ticks land exactly on cadence multiples, strictly increasing
    assert all(s["tick"] % 2 == 0 for s in series)
    assert all(b["tick"] > a["tick"]
               for a, b in zip(series, series[1:]))
    # cumulative counters never go backwards; final totals match the
    # engine's own host counters
    for key in ("tokens", "finished"):
        vals = [s["counters"][key] for s in series]
        assert vals == sorted(vals)
    total = sum(len(r.output) for r in reqs)
    assert series[-1]["counters"]["tokens"] <= total  # last window may
    # have closed before the final tokens landed
    # deltas sum to the last cumulative value
    assert sum(s["deltas"]["tokens"] for s in series) \
        == series[-1]["counters"]["tokens"]
    # gauges present
    assert "kv_utilization" in series[-1]["gauges"]
    # alerts evaluated once per closed window; nothing fired on a
    # healthy run
    asn = eng.alerts_snapshot()
    assert asn["enabled"] and asn["active"] == []
    assert asn["stats"]["evaluated"] == len(series)
    assert asn["fired_total"] == 0


def test_timeline_tokens_count_first_tokens(flight_flags):
    """The 'tokens' counter includes each request's prefill-sampled
    FIRST token: a prefill-heavy window (max_new_tokens=1 traffic)
    must not read as zero tokens — per-token cost derivations over the
    series would divide by an undercount."""
    flight_flags({"timeseries": True, "timeseries_cadence": 1})
    model, cfg = serving_utils.tiny_model(seed=0)
    eng = ContinuousBatchingEngine(model,
                                   serving_utils.tiny_ecfg(False))
    rng = np.random.default_rng(0)
    reqs = eng.run([rng.integers(1, cfg.vocab_size, 8)
                    for _ in range(3)], max_new_tokens=1, max_chunk=2)
    total = sum(len(r.output) for r in reqs)
    assert total == 3  # pure first-token traffic
    series = eng.timeline_snapshot()["series"]
    assert series[-1]["counters"]["tokens"] == total


# ---------------------------------------------------------------------------
# the seeded storm: deterministic burn-rate firing + artifact
# ---------------------------------------------------------------------------
def _burn_storm(paged, set_flags, dump_dir, spec="step:0.08,seed:11"):
    """Saturation/fault storm: 2 slots, 8 tight-TTFT interactive
    requests (every finish violates), seeded step faults — drives the
    burn-rate alert deterministically."""
    set_flags({"timeseries": True, "timeseries_cadence": 2,
               "alerts": True, "telemetry": True,
               "telemetry_dump_dir": dump_dir,
               "cost_attribution": True})
    model, cfg = serving_utils.tiny_model(seed=0)
    eng = ContinuousBatchingEngine(
        model, serving_utils.tiny_ecfg(paged),
        fault_injector=FaultInjector(spec))
    rng = np.random.default_rng(0)
    for _ in range(8):
        eng.add_request(rng.integers(1, cfg.vocab_size, 10), 6,
                        slo="interactive", ttft_target_ms=0.001)
    while eng.step_chunk(2) or eng.active.any() or eng._queue:
        pass
    return eng


@pytest.mark.parametrize("paged", [False, True])
def test_burn_rate_storm_deterministic(paged, flight_flags, tmp_path):
    """The acceptance pin: same ticks, same windows, both cache modes,
    two fresh runs — and the artifact carries the triggering series
    window."""
    runs = []
    for i in range(2):
        d = tmp_path / f"run{i}"
        d.mkdir()
        eng = _burn_storm(paged, flight_flags, str(d))
        asn = eng.alerts_snapshot()
        runs.append([(t["rule"], t["event"], t["tick"])
                     for t in asn["transitions"]])
        if i == 0:
            assert ("slo_burn_rate", "fire") in [
                (r, e) for r, e, _ in runs[0]], runs[0]
            assert "slo_burn_rate" in asn["active"]
            assert asn["rules"]["slo_burn_rate"]["peak"] >= 2.0
            # the firing left exactly one artifact whose record
            # carries the triggering window of series samples
            dumps = sorted(d.glob("flight_*.json"))
            assert dumps, "no FlightRecorder artifact written"
            doc = json.loads(dumps[0].read_text())
            rec = next(r for r in doc["records"]
                       if r.get("kind") == "alert")
            assert rec["rule"] == "slo_burn_rate"
            assert rec["window"], "triggering window missing"
            assert all("deltas" in s for s in rec["window"])
            # forced tracer event survives sample thinning
            assert any(e["name"] == "alert"
                       and e["args"]["rule"] == "slo_burn_rate"
                       for e in eng._tracer.events())
            # registry surfaces the firing
            from paddle_tpu import observability as obs

            fired = obs.global_registry().get(
                "pt_serve_alerts_fired_total")
            assert any(v >= 1 for v in fired.series().values())
    assert runs[0] == runs[1], "storm transitions are not deterministic"


def test_slo_degradation_hook(flight_flags):
    """PT_FLAGS_slo_degradation: an active burn climbs the ladder's
    capacity rungs without real queue saturation; off leaves the
    ladder at 0 for the identical workload."""
    levels = {}
    for flag_on in (False, True):
        flight_flags({"timeseries": True, "timeseries_cadence": 2,
                      "alerts": True, "slo_degradation": flag_on})
        model, cfg = serving_utils.tiny_model(seed=0)
        ecfg = serving_utils.tiny_ecfg(False, max_slots=4)
        eng = ContinuousBatchingEngine(model, ecfg)
        rng = np.random.default_rng(0)
        # steady trickle (one arrival per 2 ticks, 4 slots): the queue
        # never backs up — no REAL saturation — but every finish
        # violates its 1µs TTFT target, so finishes land in every
        # window and the burn sustains through its hysteresis
        for _ in range(24):
            eng.add_request(rng.integers(1, cfg.vocab_size, 8), 4,
                            slo="interactive", ttft_target_ms=0.001)
            eng.step_chunk(2)
            eng.step_chunk(2)
        levels[flag_on] = eng.backpressure()["degradation_level"]
        assert eng.alerts_snapshot()["rules"]["slo_burn_rate"]["fired"] \
            >= 1
    assert levels[False] == 0
    assert levels[True] >= 1


# ---------------------------------------------------------------------------
# per-request device-cost attribution
# ---------------------------------------------------------------------------
def test_cost_accumulates_and_records_at_finish(flight_flags):
    flight_flags({"cost_attribution": True})
    eng, reqs = _run_workload(paged=True)
    assert all(r.device_ms > 0 for r in reqs)
    cs = eng.cost_snapshot()
    assert cs["enabled"]
    # profiler off: everything is the sync-wall estimate
    assert cs["profiled_ms"] == 0.0 and cs["estimated_ms"] > 0.0
    assert cs["requests_finished"] == len(reqs)
    assert cs["request_device_ms_total"] == pytest.approx(
        sum(r.device_ms for r in reqs))
    assert cs["request_device_ms_p50"] is not None
    assert cs["by_slo"]["untracked"]["requests"] == len(reqs)
    # attribution conserves each step's wall exactly (float rounding)
    assert sum(cs["attributed_ms"].values()) == pytest.approx(
        cs["profiled_ms"] + cs["estimated_ms"])
    # the unified snapshot embeds it
    assert eng.metrics_snapshot()["cost"]["enabled"]


@pytest.mark.parametrize("paged", [False, True])
def test_cost_reconciles_with_profiler_totals(paged, flight_flags,
                                              compile_counter):
    """THE reconciliation pin (acceptance criterion): at profiler
    cadence 1 every dispatch of every attributed program is measured,
    so per-request device-ms sums equal the profiler's per-program
    device totals to float rounding — and the profiler adds zero
    compiled programs while doing it."""
    from paddle_tpu.inference import serving

    flight_flags({"cost_attribution": True, "profile_programs": True,
                  "profile_sample_every": 1})
    base = dict(serving.TRACE_COUNTS)
    eng, reqs = _run_workload(paged, max_new=8, n=4)
    assert len(reqs) == 4 and all(r.done for r in reqs)
    cs = eng.cost_snapshot()
    # cadence 1: nothing fell back to the sync-wall estimate
    assert cs["estimated_ms"] == 0.0
    prof = eng.profile_snapshot()["programs"]
    prof_total = sum(
        st["sampled"] * st["device_ms_mean"]
        for name, st in prof.items()
        if name in ATTRIBUTED and st["sampled"])
    req_total = sum(r.device_ms for r in reqs)
    assert req_total == pytest.approx(prof_total, rel=1e-9)
    assert req_total == pytest.approx(cs["profiled_ms"], rel=1e-9)
    assert sum(r.device_ms_profiled for r in reqs) \
        == pytest.approx(req_total, rel=1e-9)
    # per-program cross-check
    for name, ms in cs["attributed_ms"].items():
        st = prof[name]
        assert ms == pytest.approx(
            st["sampled"] * st["device_ms_mean"], rel=1e-9)
    # zero new compiled programs from profiling + attribution
    grown = {k: v - base.get(k, 0)
             for k, v in serving.TRACE_COUNTS.items()
             if v - base.get(k, 0)}
    assert set(grown) <= ATTRIBUTED | {"prefix_insert", "prefix_read",
                                       "page_copy"}


def test_cost_rides_ledger_across_handoff(flight_flags):
    """Cost survives a drain handoff: the ledger carries device_ms and
    admit_ledger restores it, so the successor's finish-time record
    bills the request's WHOLE life."""
    flight_flags({"cost_attribution": True})
    model, cfg = serving_utils.tiny_model(seed=0)
    eng1 = ContinuousBatchingEngine(model,
                                    serving_utils.tiny_ecfg(True))
    rng = np.random.default_rng(0)
    rid = eng1.add_request(rng.integers(1, cfg.vocab_size, 10), 24)
    for _ in range(3):
        eng1.step_chunk(2)
    summary = eng1.drain(deadline_ms=1.0, max_chunk=2)
    led = next(l for l in summary["unfinished"] if l["rid"] == rid)
    assert led["device_ms"] > 0
    burned = led["device_ms"]
    eng2 = ContinuousBatchingEngine(model,
                                    serving_utils.tiny_ecfg(True))
    eng2.admit_ledger(led)
    while eng2.step_chunk(2) or eng2.active.any() or eng2._queue:
        pass
    req = eng2._finished[rid]
    assert req.device_ms > burned  # prior life + continued decode
    cs = eng2.cost_snapshot()
    assert cs["request_device_ms_total"] == pytest.approx(
        req.device_ms)


def test_cancel_and_timeout_record_cost(flight_flags):
    flight_flags({"cost_attribution": True})
    model, cfg = serving_utils.tiny_model(seed=0)
    eng = ContinuousBatchingEngine(model, serving_utils.tiny_ecfg(True))
    rng = np.random.default_rng(0)
    r1 = eng.add_request(rng.integers(1, cfg.vocab_size, 10), 24)
    r2 = eng.add_request(rng.integers(1, cfg.vocab_size, 10), 24,
                         deadline_ms=30.0)
    for _ in range(3):
        eng.step_chunk(2)
    assert eng.cancel(r1)
    time.sleep(0.04)  # r2's deadline expires
    eng.step_chunk(2)
    cs = eng.cost_snapshot()
    assert eng._finished[r1].finish_reason == "cancel"
    assert eng._finished[r2].finish_reason == "timeout"
    assert cs["requests_finished"] >= 2
    assert eng._finished[r1].device_ms > 0
    assert cs["request_device_ms_total"] >= \
        eng._finished[r1].device_ms


# ---------------------------------------------------------------------------
# endpoints / CLI / router
# ---------------------------------------------------------------------------
def test_timeline_endpoint(flight_flags):
    import urllib.error
    import urllib.request

    from paddle_tpu.inference import start_metrics_server

    flight_flags({"timeseries": True, "timeseries_cadence": 2,
                  "alerts": True, "telemetry": True})
    eng, _ = _run_workload(paged=False)
    srv = start_metrics_server(eng, port=0)
    try:
        with urllib.request.urlopen(
                srv.url + "/timeline", timeout=10) as resp:
            doc = json.loads(resp.read())
        assert doc["enabled"] and doc["windows"] >= 1
        assert doc["series"][0]["tick"] % 2 == 0
    finally:
        srv.shutdown()
    # off: 404, mirroring /trace
    flight_flags({"timeseries": False})
    model, _cfg = serving_utils.tiny_model(seed=0)
    eng2 = ContinuousBatchingEngine(model,
                                    serving_utils.tiny_ecfg(False))
    srv2 = start_metrics_server(eng2, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv2.url + "/timeline", timeout=10)
        assert ei.value.code == 404
    finally:
        srv2.shutdown()


def test_dump_cli_timeline(capsys, flight_flags):
    flight_flags({"timeseries": True, "timeseries_cadence": 2})
    from paddle_tpu.observability import dump

    _eng, _ = _run_workload(paged=False)
    assert dump.main(["--timeline"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert any(s["windows"] >= 1 for s in out)
    assert all("series" in s for s in out)


def test_router_fleet_timeline_and_alert_aggregation(flight_flags):
    from paddle_tpu.inference.router import EngineRouter

    flight_flags({"timeseries": True, "timeseries_cadence": 2,
                  "alerts": True})
    model, cfg = serving_utils.tiny_model(seed=0)
    router = EngineRouter(model, serving_utils.tiny_ecfg(True),
                          n_replicas=2)
    rng = np.random.default_rng(0)
    # tight targets: replica burn rules fire, the fleet view must see
    for _ in range(6):
        router.add_request(rng.integers(1, cfg.vocab_size, 8), 4,
                           slo="interactive", ttft_target_ms=0.001)
    while router.step(2):
        pass
    tl = router.timeline_snapshot()
    assert tl["enabled"]
    assert tl["router"]["windows"] >= 1
    assert len(tl["replicas"]) == 2
    assert all(r["enabled"] for r in tl["replicas"])
    # fleet counters windowed on the router's own store
    assert "routed" in tl["router"]["series"][-1]["counters"]
    fs = router.fleet_snapshot()
    assert fs["alerts"]["enabled"]
    assert fs["alerts"]["fired"] >= 1
    assert any(a["rule"] == "slo_burn_rate"
               for a in fs["alerts"]["active"])


# ---------------------------------------------------------------------------
# chaos lane: readers under a producer-thread fault storm (sanitized)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_flight_readers_under_fault_storm(flight_flags):
    """Timeline/alert/cost readers from a scrape thread while a
    producer thread feeds a seeded fault storm — sanitizer on (chaos
    autouse fixture): no torn windows (every sample fully formed,
    ticks strictly increasing on cadence), no scheduler-state mutation
    from the scrape thread, pool fully recovered after."""
    flight_flags({"timeseries": True, "timeseries_cadence": 2,
                  "alerts": True, "cost_attribution": True})
    model, cfg = serving_utils.tiny_model(seed=0)
    eng = ContinuousBatchingEngine(
        model, serving_utils.tiny_ecfg(True, max_slots=2),
        fault_injector=FaultInjector("step:0.05,nan:0.03,seed:7"))
    rng = np.random.default_rng(1)
    stop = threading.Event()
    errors = []

    def produce():
        try:
            for i in range(10):
                eng.add_request(rng.integers(1, cfg.vocab_size, 8), 4,
                                slo="interactive",
                                ttft_target_ms=0.001)
                time.sleep(0.005)
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    def scrape():
        try:
            while not stop.is_set():
                tl = eng.timeline_snapshot()
                if tl["enabled"]:
                    ticks = [s["tick"] for s in tl["series"]]
                    assert ticks == sorted(ticks)
                    assert all(t % 2 == 0 for t in ticks)
                    for s in tl["series"]:
                        assert {"counters", "deltas", "rates",
                                "gauges"} <= set(s)
                eng.alerts_snapshot()
                eng.cost_snapshot()
                eng.metrics_snapshot()
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    prod = threading.Thread(target=produce)
    scr = threading.Thread(target=scrape, daemon=True)
    prod.start()
    scr.start()
    deadline = time.monotonic() + 60
    while (eng.active.any() or eng._queue or prod.is_alive()) \
            and time.monotonic() < deadline:
        eng.step_chunk(2)
    prod.join(timeout=10)
    stop.set()
    scr.join(timeout=10)
    assert not errors, errors
    assert time.monotonic() < deadline, "storm did not converge"
    # every request accounted, pool recovered
    assert len(eng._finished) == 10
    assert not eng.active.any()
    assert eng.pool.free_pages > 0
    assert len(eng._free_heap) == eng.cfg.max_slots
    # the storm fired the burn alert through the fault noise too
    assert eng.alerts_snapshot()["rules"]["slo_burn_rate"]["fired"] \
        >= 1


@pytest.mark.chaos
@pytest.mark.slow
def test_flight_kill_storm_soak(flight_flags):
    """Heavier producer-thread kill-storm soak (cancel-every-3rd rid +
    step/nan/latency faults) with the scrape thread hammering every
    flight reader — slow lane (tier-1 budget guard): the fast chaos
    twin above keeps tier-1 coverage."""
    flight_flags({"timeseries": True, "timeseries_cadence": 2,
                  "timeseries_retention": 8, "alerts": True,
                  "cost_attribution": True})
    model, cfg = serving_utils.tiny_model(seed=0)
    eng = ContinuousBatchingEngine(
        model, serving_utils.tiny_ecfg(True, max_slots=2),
        fault_injector=FaultInjector(
            "step:0.08,nan:0.04,latency:0.05,latency_ms:2,seed:3"))
    rng = np.random.default_rng(2)
    rids, errors = [], []
    stop = threading.Event()

    def produce():
        try:
            for i in range(24):
                rid = eng.add_request(
                    rng.integers(1, cfg.vocab_size, 8), 4,
                    slo="interactive", ttft_target_ms=0.001)
                rids.append(rid)
                time.sleep(0.003)
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    def scrape():
        try:
            while not stop.is_set():
                tl = eng.timeline_snapshot()
                # retention ring bounded even under storm
                assert tl["windows"] <= 8
                eng.alerts_snapshot()
                eng.cost_snapshot()
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    prod = threading.Thread(target=produce)
    scr = threading.Thread(target=scrape, daemon=True)
    prod.start()
    scr.start()
    deadline = time.monotonic() + 120
    seen_cancel = set()
    while (eng.active.any() or eng._queue or prod.is_alive()) \
            and time.monotonic() < deadline:
        eng.step_chunk(2)
        # cancel-every-3rd from the scheduler thread (the engine's
        # documented cancel contract)
        for rid in list(rids):
            if rid % 3 == 0 and rid not in seen_cancel:
                seen_cancel.add(rid)
                eng.cancel(rid)
    prod.join(timeout=10)
    stop.set()
    scr.join(timeout=10)
    assert not errors, errors
    assert time.monotonic() < deadline, "soak did not converge"
    assert len(eng._finished) == 24
    assert not eng.active.any()
    assert len(eng._free_heap) == eng.cfg.max_slots
    # every finished request carries a recorded cost exactly once
    cs = eng.cost_snapshot()
    assert cs["requests_finished"] == 24
    assert cs["request_device_ms_total"] == pytest.approx(
        sum(r.device_ms for r in eng._finished.values()))
