"""Sequence/context parallel attention tests: ring attention and Ulysses
all-to-all attention must match the single-device reference (parity
model: PaddleNLP RingFlashAttention tests vs flash_attn baseline)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import distributed as dist
from paddle_tpu.distributed.sharding import mesh_context
from paddle_tpu.kernels.flash_attention import _reference_attention
from paddle_tpu.kernels.ring_attention import ring_attention


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = dist.build_mesh(sep=4)
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 32, 2, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    ref = _reference_attention(q, k, v, causal=causal)
    sh = NamedSharding(mesh, P(None, "sep", None, None))
    qd, kd, vd = (jax.device_put(t, sh) for t in (q, k, v))
    with mesh_context(mesh):
        out = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=causal)
        )(qd, kd, vd)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_ring_attention_grads_match():
    mesh = dist.build_mesh(sep=4)
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 16, 1, 8
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh=mesh, causal=True) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True) ** 2)

    with mesh_context(mesh):
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-3, atol=1e-4
        )


def test_llama_sep_modes_match_dense():
    """Llama forward under sep=2 (ulysses and ring) equals the unsharded
    forward."""
    from paddle_tpu.core.functional import extract_params, functional_call
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    ids = np.random.default_rng(3).integers(0, 256, (4, 32))
    for mode in ("ulysses", "ring"):
        pt.seed(21)
        cfg = LlamaConfig.tiny(use_flash_attention=False, sep_attention=mode)
        model = LlamaForCausalLM(cfg)
        ref = float(model(jnp.asarray(ids), labels=jnp.asarray(ids)))
        mesh = dist.build_mesh(dp=2, sep=2, tp=2)
        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = dist.HybridConfig(
            dp_degree=2, sep_degree=2, mp_degree=2
        )
        params = extract_params(model)
        objs = dict(model.named_parameters())
        sharded = {
            n: jax.device_put(
                v, NamedSharding(
                    mesh,
                    dist.param_partition_spec(n, v.shape, objs[n].spec,
                                              strategy),
                )
            )
            for n, v in params.items()
        }
        with mesh_context(mesh):
            out = jax.jit(
                lambda p, x: functional_call(model, p, x, labels=x)
            )(sharded, jax.device_put(
                jnp.asarray(ids),
                NamedSharding(mesh, P(("dp", "fsdp"), "sep")),
            ))
        np.testing.assert_allclose(float(out), ref, rtol=2e-4), mode


def test_ring_attention_flash_blocks():
    """Zigzag ring with the Pallas flash kernel per block (interpret on
    CPU) matches the dense reference, fwd + grad."""
    import os

    mesh = dist.build_mesh(sep=2)
    rng = np.random.default_rng(7)
    b, s, h, d = 1, 512, 2, 128  # local L = 128 -> flash-eligible blocks
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    os.environ["PADDLE_TPU_FORCE_PALLAS"] = "1"
    try:
        with mesh_context(mesh):
            out = jax.jit(
                lambda q, k, v: ring_attention(q, k, v, mesh=mesh,
                                               causal=True)
            )(q, k, v)
        ref = _reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
        )

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(_reference_attention(q, k, v, causal=True) ** 2)

        with mesh_context(mesh):
            g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_ring, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=5e-3, atol=5e-3
            )
    finally:
        os.environ.pop("PADDLE_TPU_FORCE_PALLAS", None)
