"""Distributed engine tests on the virtual 8-device CPU mesh (parity
model: test/collective/ run with Gloo-on-CPU + fake meshes, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import distributed as dist
from paddle_tpu.distributed.sharding import (
    fsdp_augment,
    mesh_context,
    opt_slot_partition_spec,
    param_partition_spec,
)
from paddle_tpu.distributed.strategy import DistributedStrategy, HybridConfig


@pytest.fixture
def mesh8():
    return dist.build_mesh(dp=2, fsdp=2, tp=2, pp=1, sep=1)


def _strategy(**hybrid):
    s = DistributedStrategy()
    s.hybrid_configs = HybridConfig(**hybrid)
    return s


def test_topology_queries():
    s = _strategy(dp_degree=2, mp_degree=2, sharding_degree=2)
    hcg = dist.HybridCommunicateGroup(s)
    assert hcg.mesh.shape == {"dp": 2, "pp": 1, "fsdp": 2, "ep": 1, "sep": 1, "tp": 2}
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_data_parallel_world_size() == 2
    g = hcg.get_model_parallel_group()
    assert g.nranks == 2 and g.axis == "tp"


def test_fsdp_augment_rules():
    # prefers leading unsharded divisible dim
    assert fsdp_augment((None, "tp"), (128, 256), "fsdp", 2) == ("fsdp", "tp")
    # composes onto sharded dim when no free dim
    assert fsdp_augment(("tp",), (128,), "fsdp", 2) == (("tp", "fsdp"),)
    # no change if fsdp already there
    assert fsdp_augment(("fsdp", None), (8, 8), "fsdp", 2) == ("fsdp", None)


def test_param_spec_stages():
    shape = (256, 512)
    s1 = _strategy(sharding_degree=2)
    s1.sharding_configs.stage = 1
    s1.sharding = True
    # stage 1: param replicated (except tp), opt slots sharded
    assert param_partition_spec("w", shape, (None, "tp"), s1) == P(None, "tp")
    assert opt_slot_partition_spec("w", shape, (None, "tp"), s1) == P("fsdp", "tp")
    s3 = _strategy(sharding_degree=2)
    s3.sharding_configs.stage = 3
    s3.sharding = True
    assert param_partition_spec("w", shape, (None, "tp"), s3) == P("fsdp", "tp")
    # small params stay whole under stage 3
    assert param_partition_spec("b", (64,), None, s3) == P(None)


def test_collectives_eager():
    s = _strategy(dp_degree=8)
    hcg = dist.fleet_init(s)
    x = jnp.arange(8.0)
    y = dist.all_reduce(x, mesh=hcg.mesh, group="dp")
    np.testing.assert_allclose(np.asarray(y), np.full(8, 28.0))
    # input: 8 ranks × local (8,4); output: each rank holds its reduced
    # (1,4) slice → global (8,4) of sums
    rs = dist.reduce_scatter(jnp.ones((64, 4)), mesh=hcg.mesh, group="dp")
    assert rs.shape == (8, 4)
    np.testing.assert_allclose(np.asarray(rs), np.full((8, 4), 8.0))


def test_collectives_extended():
    """scatter/gather/reduce/p2p/groups (parity:
    paddle.distributed.communication surface) on an 8-way dp mesh."""
    s = _strategy(dp_degree=8)
    hcg = dist.fleet_init(s)
    mesh = hcg.mesh

    # reduce to dst: dst rank's shard is the sum, others keep their own
    x = jnp.arange(8.0)
    r = dist.reduce(x, dst=3, mesh=mesh, group="dp")
    ref = np.arange(8.0)
    ref[3] = 28.0
    np.testing.assert_allclose(np.asarray(r), ref)

    # scatter from a list of per-rank pieces
    pieces = [jnp.full((2,), float(i)) for i in range(8)]
    sc = dist.scatter(None, pieces, mesh=mesh, group="dp")
    np.testing.assert_allclose(np.asarray(sc).reshape(8, 2),
                               np.repeat(np.arange(8.0)[:, None], 2, 1))

    # gather returns the per-rank chunks
    chunks = dist.gather(jnp.arange(8.0), mesh=mesh, group="dp")
    assert len(chunks) == 8
    np.testing.assert_allclose(np.asarray(chunks[5]), [5.0])

    # send/recv: the canonical ring edge (src -> src+1)
    moved = dist.recv(jnp.arange(8.0), src=2, mesh=mesh, group="dp")
    assert float(moved[3]) == 2.0          # rank 3 received rank 2's
    assert float(moved[0]) == 0.0          # others untouched
    t = dist.isend(jnp.arange(8.0), dst=5, mesh=mesh, group="dp")
    got = t.wait()
    assert float(got[5]) == 4.0

    # batch form
    ops = [dist.P2POp(dist.isend, jnp.arange(8.0), 1, "dp"),
           dist.P2POp(dist.irecv, jnp.arange(8.0), 6, "dp")]
    tasks = dist.batch_isend_irecv(ops)
    assert float(tasks[0].wait()[1]) == 0.0
    assert float(tasks[1].wait()[7]) == 6.0

    # alltoall_single uniform split: rank r's local [8] scatters chunk j
    # to rank j; global output position r*8+j holds 8j+r (transpose)
    a2a = dist.alltoall_single(jnp.arange(64.0), mesh=mesh, group="dp")
    ref_a2a = np.arange(64.0).reshape(8, 8).T.reshape(-1)
    np.testing.assert_allclose(np.asarray(a2a), ref_a2a)
    # ragged alltoall_single: per-rank split matrix (row r = rank r's
    # in_split_sizes); verify against a numpy alltoallv reference
    rng = np.random.default_rng(3)
    n, n_loc = 8, 8
    splits = np.zeros((n, n), np.int32)
    for r in range(n):
        cuts = np.sort(rng.integers(0, n_loc + 1, n - 1))
        row = np.diff(np.concatenate([[0], cuts, [n_loc]]))
        splits[r] = row
    data = np.arange(n * n_loc, dtype=np.float64)
    out = dist.alltoall_single(
        jnp.asarray(data), in_split_sizes=splits,
        out_split_sizes=splits.T, mesh=mesh, group="dp")
    offs = np.concatenate(
        [np.zeros((n, 1), np.int64), np.cumsum(splits, 1)], 1)
    for r in range(n):
        expect = np.concatenate(
            [data[s * n_loc + offs[s, r]: s * n_loc + offs[s, r + 1]]
             for s in range(n)])
        np.testing.assert_allclose(np.asarray(out[r]), expect)

    # ragged validation errors
    with pytest.raises(ValueError):
        dist.alltoall_single(jnp.arange(64.0), in_split_sizes=[9] * 8,
                             mesh=mesh, group="dp")
    with pytest.raises(ValueError):
        dist.alltoall_single(
            jnp.arange(64.0), in_split_sizes=[1] * 8,
            out_split_sizes=np.full((8, 8), 2), mesh=mesh, group="dp")

    # groups: axis binding and subgroup matching
    g = dist.new_group(axis="dp")
    assert g.nranks == 8 and dist.get_group(g.id) is g
    g2 = dist.new_group(ranks=list(range(8)))
    assert g2.axis == "dp"
    with pytest.raises(ValueError):
        dist.new_group(ranks=[0, 3])
    assert dist.is_initialized()
    dist.destroy_process_group()
    assert dist.get_group(g.id) is None

    # mesh state + shard_optimizer parity wrappers
    pm = dist.ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
    dist.set_mesh(pm)
    assert dist.get_mesh() is pm
    from paddle_tpu import optimizer as opt

    o = opt.AdamW(1e-3)
    assert dist.shard_optimizer(o) is o


def test_shard_tensor_api(mesh8):
    pm = dist.ProcessMesh(
        np.arange(8).reshape(2, 2, 2), dim_names=["dp", "fsdp", "tp"]
    )
    x = jnp.ones((8, 16))
    y = dist.shard_tensor(x, pm, [dist.Shard(0), dist.Shard(1), dist.Replicate()])
    spec = y.sharding.spec
    assert spec[0] == "dp" and spec[1] == "fsdp"
    placements = dist.get_placements(y, pm)
    assert placements[0] == dist.Shard(0)
    assert placements[1] == dist.Shard(1)
    assert placements[2] == dist.Replicate()
    z = dist.reshard(y, pm, [dist.Replicate(), dist.Replicate(), dist.Replicate()])
    np.testing.assert_allclose(np.asarray(z), np.asarray(x))


def test_tp_layer_correctness(mesh8):
    """Column→Row parallel pair must equal the dense computation."""
    pt.seed(7)
    from paddle_tpu.distributed.parallel_layers import (
        ColumnParallelLinear,
        RowParallelLinear,
    )

    col = ColumnParallelLinear(16, 32, has_bias=True)
    row = RowParallelLinear(32, 8, has_bias=True)
    x = jnp.asarray(np.random.randn(4, 16).astype(np.float32))

    # dense reference
    ref = (
        np.asarray(x) @ np.asarray(col.weight.value) + np.asarray(col.bias.value)
    ) @ np.asarray(row.weight.value) + np.asarray(row.bias.value)

    strategy = _strategy(dp_degree=2, sharding_degree=2, mp_degree=2)
    dist.place_params_on_mesh(col, mesh8, strategy)
    dist.place_params_on_mesh(row, mesh8, strategy)
    from paddle_tpu.core.functional import extract_params, functional_call

    params = {**{f"c.{k}": v for k, v in extract_params(col).items()},
              **{f"r.{k}": v for k, v in extract_params(row).items()}}

    def fwd(p, x):
        h = functional_call(col, {k[2:]: v for k, v in p.items()
                                  if k.startswith("c.")}, x)
        return functional_call(row, {k[2:]: v for k, v in p.items()
                                     if k.startswith("r.")}, h)

    with mesh_context(mesh8):
        y = jax.jit(fwd)(params, jax.device_put(
            x, NamedSharding(mesh8, P(("dp", "fsdp"), None))
        ))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-4)


def test_strategy_serialize_roundtrip():
    s = _strategy(dp_degree=2, mp_degree=4, sharding_degree=8)
    s.sharding = True
    s.sharding_configs.stage = 3
    text = s.serialize()
    s2 = DistributedStrategy.deserialize(text)
    assert s2.hybrid_configs.mp_degree == 4
    assert s2.sharding_configs.stage == 3
    assert s2.fsdp == 8


class TestObjectCollectives:
    """single-process semantics (multi-host path shares the frame codec,
    exercised by encoding symmetry below)."""

    def test_all_gather_object(self):
        from paddle_tpu import distributed as dist

        out = []
        dist.all_gather_object(out, {"rank": 0, "data": [1, 2, 3]})
        assert out == [{"rank": 0, "data": [1, 2, 3]}]

    def test_broadcast_object_list(self):
        from paddle_tpu import distributed as dist

        lst = ["a", {"b": 2}]
        dist.broadcast_object_list(lst, src=0)
        assert lst == ["a", {"b": 2}]

    def test_frame_codec_roundtrip(self):
        """the length-prefixed pickle frame decodes what it encodes."""
        import pickle

        obj = {"x": np.arange(5), "y": "hello"}
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
        frame = np.zeros((payload.size + 8,), np.uint8)
        frame[:8] = np.frombuffer(
            np.asarray([payload.size], np.int64).tobytes(), np.uint8)
        frame[8:] = payload
        n = int(np.frombuffer(frame[:8].tobytes(), np.int64)[0])
        back = pickle.loads(frame[8:8 + n].tobytes())
        assert back["y"] == "hello"
        np.testing.assert_array_equal(back["x"], obj["x"])
