"""ptlint over the repo: the tier-1 fast-lane gate.

Three claims:

1. **The repo is clean** — ``python -m paddle_tpu.analysis.lint
   paddle_tpu tests benchmarks`` reports zero violations beyond the
   committed baseline (``.ptlint-baseline.json``), so any NEW
   trace-safety / determinism / flags-hygiene / concurrency finding
   fails CI at the PR that introduces it.

2. **The core is suppression-free** — the baseline carries no entries
   under ``paddle_tpu/inference/`` or ``paddle_tpu/kernels/``, and no
   inline ``ptlint: disable`` markers live there either: in the
   serving/kernel core, findings get FIXED, not waived.

3. **The rules actually fire** — a synthetic module planted in a tmp
   repo trips each family (host-sync-in-jit, wall-clock, un-copied
   snapshot iteration, unknown flag read), and inline suppression +
   baseline machinery behave as documented.
"""

import json
import os
import textwrap

import pytest

from paddle_tpu.analysis import lint

pytestmark = pytest.mark.fast

REPO = lint.find_root(os.path.dirname(__file__))
SCAN_PATHS = [os.path.join(REPO, p)
              for p in ("paddle_tpu", "tests", "benchmarks")]
CORE_PREFIXES = ("paddle_tpu/inference/", "paddle_tpu/kernels/")


def _scan_repo():
    return lint.scan(SCAN_PATHS, REPO)


def test_repo_lint_clean():
    result = _scan_repo()
    baseline = lint.load_baseline(
        os.path.join(REPO, lint.BASELINE_NAME))
    new, _accepted = lint.apply_baseline(result.violations, baseline)
    assert not new, "new ptlint violations:\n" + "\n".join(
        f"  {v.file}:{v.line}: {v.rule} {v.message}" for v in new)


def test_core_is_suppression_free():
    """paddle_tpu/inference and paddle_tpu/kernels: no baseline
    entries, no inline disables — zero-suppression is the contract."""
    baseline = lint.load_baseline(
        os.path.join(REPO, lint.BASELINE_NAME))
    dirty = [k for k in baseline if k.startswith(CORE_PREFIXES)]
    assert not dirty, f"baseline entries in the core: {dirty}"
    result = _scan_repo()
    inline = [s for s in result.suppressions
              if s.file.startswith(CORE_PREFIXES)]
    assert not inline, (
        f"inline ptlint suppressions in the core: "
        f"{[(s.file, s.line) for s in inline]}")


def test_router_joins_reader_hook_contract():
    """The multi-engine router is part of the suppression-free core
    (its directory is covered by CORE_PREFIXES, pinned here by name):
    it lints clean with ZERO suppressions, AND ptlint's CC003 reader-
    hook rule actually has teeth on it — the module is sanitizer-
    bearing (references ``self._san``), every scrape reader carries
    its ``check_read`` hook, and each hooked name is registered in
    the sanitizer's SAFE_READS so the runtime check can fire."""
    import ast

    from paddle_tpu.analysis.sanitizer import SAFE_READS

    path = os.path.join(REPO, "paddle_tpu", "inference", "router.py")
    assert path.startswith(
        tuple(os.path.join(REPO, p) for p in CORE_PREFIXES))
    result = lint.scan([path], REPO)
    assert not result.violations, [
        (v.line, v.rule, v.message) for v in result.violations]
    assert not result.suppressions
    with open(path, encoding="utf-8") as f:
        src = f.read()
    assert "self._san" in src  # CC003 applies (sanitizer-bearing)
    tree = ast.parse(src)
    hooked = {
        n.args[0].value
        for n in ast.walk(tree)
        if isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == "check_read" and n.args
        and isinstance(n.args[0], ast.Constant)}
    assert {"backpressure", "metrics_snapshot",
            "fleet_snapshot"} <= hooked
    assert hooked <= SAFE_READS, (
        f"router readers {sorted(hooked - SAFE_READS)} hook "
        "check_read but are not registered in SAFE_READS — the "
        "runtime ownership check would reject every scrape")


def test_flag_registry_matches_runtime():
    """The AST-level registry the lint checks against == the runtime
    registry flags.registry() exposes (the satellite contract)."""
    import ast

    from paddle_tpu import flags as F
    from paddle_tpu.analysis.rules import FlagsHygiene

    project = lint.Project(REPO)
    rule = FlagsHygiene()
    for path in lint.iter_py_files([os.path.join(REPO, "paddle_tpu")]):
        rel = os.path.relpath(path, REPO).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        rule.check_module(project, tree, "", rel)
    assert set(project.flag_defs) == set(F.registry())


# ---------------------------------------------------------------------------
# synthetic violations: every family fires; suppressions/baseline work
# ---------------------------------------------------------------------------
_BAD_SERVING = textwrap.dedent("""\
    import time
    import jax
    import numpy as np

    def build():
        def fn(x, flag):
            if flag:                      # Python if on traced arg
                return float(x)           # host sync on traced value
            return x.item()
        return jax.jit(fn, static_argnums=())

    def stamp():
        return time.time()

    class Engine:
        def tick(self):
            if self._san is not None:         # sanitizer-bearing class
                self._san.check_tick(self)

        def spec_snapshot(self):              # no check_read hook
            out = {}
            for k, v in self.stats.items():   # un-copied iteration
                out[k] = v
            self.stats["reads"] += 1          # reader mutates state
            return out
    """)


@pytest.fixture
def tmp_repo(tmp_path):
    (tmp_path / "pyproject.toml").write_text("[project]\n")
    (tmp_path / "README.md").write_text("no flags documented\n")
    pkg = tmp_path / "paddle_tpu" / "inference"
    pkg.mkdir(parents=True)
    return tmp_path


def test_rules_fire_on_synthetic_module(tmp_repo):
    bad = tmp_repo / "paddle_tpu" / "inference" / "bad.py"
    bad.write_text(_BAD_SERVING)
    result = lint.scan([str(bad)], str(tmp_repo))
    rules = {v.rule for v in result.violations}
    assert {"TS001", "DT003", "CC001", "CC002", "CC003"} <= rules, rules
    # TS001 fired for all three shapes: if-on-traced, float(), .item()
    ts = [v for v in result.violations if v.rule == "TS001"]
    assert len(ts) == 3, [(v.line, v.message) for v in ts]


def test_obs001_fires_on_unlabeled_program(tmp_repo):
    """OBS001: a TRACE_COUNTS program name with no PROGRAM_LABELS
    timing label is a completeness violation; labeled names pass.
    Without the profiling module in the scan (partial scan) the rule
    stays silent, like FL001 without the flag registry."""
    prof = tmp_repo / "paddle_tpu" / "observability"
    prof.mkdir(parents=True)
    prof_py = prof / "profiling.py"
    prof_py.write_text(
        'PROGRAM_LABELS = {"known": "a labeled program"}\n')
    srv = tmp_repo / "paddle_tpu" / "inference" / "srv.py"
    srv.write_text(
        "import collections\n"
        "TRACE_COUNTS = collections.Counter()\n"
        "def a():\n"
        '    TRACE_COUNTS["known"] += 1\n'
        "def b():\n"
        '    TRACE_COUNTS["mystery"] += 1\n')
    result = lint.scan([str(tmp_repo / "paddle_tpu")], str(tmp_repo))
    obs = [v for v in result.violations if v.rule == "OBS001"]
    assert len(obs) == 1, obs
    assert "mystery" in obs[0].message
    assert obs[0].file.endswith("srv.py")
    # partial scan without the label registry: silent, not noisy
    result = lint.scan([str(srv)], str(tmp_repo))
    assert not [v for v in result.violations if v.rule == "OBS001"]


def test_obs002_fires_on_unregistered_alert_rule(tmp_repo):
    """OBS002: an AlertRule implementation missing from ALERT_RULES or
    the README alerts table is a completeness violation (transitive
    subclasses count); registered + documented rules pass. A scan that
    never sees alerts.py stays silent."""
    obsd = tmp_repo / "paddle_tpu" / "observability"
    obsd.mkdir(parents=True)
    alerts_py = obsd / "alerts.py"
    alerts_py.write_text(
        'ALERT_RULES = {"known": "a registered rule"}\n'
        "class AlertRule:\n"
        '    name = ""\n'
        "class Known(AlertRule):\n"
        '    name = "known"\n'
        "class _Shape(AlertRule):\n"
        "    pass\n"
        "class Mystery(_Shape):\n"
        '    name: str = "mystery"\n')  # AnnAssign spelling counts too
    (tmp_repo / "README.md").write_text("alerts: `known` only\n")
    result = lint.scan([str(tmp_repo / "paddle_tpu")], str(tmp_repo))
    obs = [v for v in result.violations if v.rule == "OBS002"]
    # mystery is both unregistered AND undocumented
    assert len(obs) == 2, obs
    assert all("mystery" in v.message for v in obs)
    msgs = " | ".join(v.message for v in obs)
    assert "ALERT_RULES" in msgs and "README" in msgs
    # a scan that never saw alerts.py: silent
    other = tmp_repo / "paddle_tpu" / "inference" / "x.py"
    other.write_text("pass\n")
    result = lint.scan([str(other)], str(tmp_repo))
    assert not [v for v in result.violations if v.rule == "OBS002"]


def test_pa001_fires_on_uncontracted_program(tmp_repo):
    """PA001: a TRACE_COUNTS program name with no PROGRAM_CONTRACTS
    entry is a completeness violation (the OBS001 shape, applied to
    the jaxpr contract auditor); contracted names pass. A partial
    scan that never sees program_audit.py stays silent."""
    ana = tmp_repo / "paddle_tpu" / "analysis"
    ana.mkdir(parents=True)
    (ana / "program_audit.py").write_text(
        'PROGRAM_CONTRACTS = {"known": "a contracted program"}\n')
    srv = tmp_repo / "paddle_tpu" / "inference" / "srv.py"
    srv.write_text(
        "import collections\n"
        "TRACE_COUNTS = collections.Counter()\n"
        "def a():\n"
        '    TRACE_COUNTS["known"] += 1\n'
        "def b():\n"
        '    TRACE_COUNTS["mystery"] += 1\n')
    result = lint.scan([str(tmp_repo / "paddle_tpu")], str(tmp_repo))
    pa = [v for v in result.violations if v.rule == "PA001"]
    assert len(pa) == 1, pa
    assert "mystery" in pa[0].message
    assert pa[0].file.endswith("srv.py")
    # partial scan without the contract registry: silent, not noisy
    result = lint.scan([str(srv)], str(tmp_repo))
    assert not [v for v in result.violations if v.rule == "PA001"]


def test_program_contract_registry_matches_runtime():
    """The AST-level PROGRAM_CONTRACTS view PA001 checks against ==
    the imported registry (the OBS001/FL001 runtime-twin contract) ==
    the attribution registry's program names."""
    import ast

    from paddle_tpu.analysis.program_audit import PROGRAM_CONTRACTS
    from paddle_tpu.analysis.rules import (
        PA001ProgramContractCompleteness,
    )
    from paddle_tpu.observability.profiling import PROGRAM_LABELS

    project = lint.Project(REPO)
    rule = PA001ProgramContractCompleteness()
    path = os.path.join(REPO, "paddle_tpu", "analysis",
                        "program_audit.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    rule.check_module(project, tree, "",
                      "paddle_tpu/analysis/program_audit.py")
    assert project.saw_audit_module
    assert project.program_contracts == set(PROGRAM_CONTRACTS)
    assert project.program_contracts == set(PROGRAM_LABELS)


def test_inline_suppression_and_skip_file(tmp_repo):
    bad = tmp_repo / "paddle_tpu" / "inference" / "bad.py"
    # the marker is assembled at runtime so scanning THIS test file
    # doesn't count a suppression against the repo
    marker = "# ptlint: " + "disable=DT003"
    bad.write_text(
        "import time\n"
        "def stamp():\n"
        f"    return time.time()  {marker}\n")
    result = lint.scan([str(bad)], str(tmp_repo))
    assert not result.violations
    assert len(result.suppressed) == 1
    assert result.suppressed[0].rule == "DT003"
    bad.write_text(
        "# ptlint: skip-file\nimport time\n"
        "def stamp():\n    return time.time()\n")
    result = lint.scan([str(bad)], str(tmp_repo))
    assert not result.violations


def test_baseline_allows_exactly_counted(tmp_repo):
    bad = tmp_repo / "paddle_tpu" / "inference" / "bad.py"
    bad.write_text(
        "import time\n"
        "def a():\n    return time.time()\n"
        "def b():\n    return time.time()\n")
    result = lint.scan([str(bad)], str(tmp_repo))
    assert len(result.violations) == 2
    baseline = {"paddle_tpu/inference/bad.py::DT003": 1}
    new, accepted = lint.apply_baseline(result.violations, baseline)
    assert len(new) == 1 and len(accepted) == 1


def test_cli_exit_codes(tmp_repo, capsys):
    bad = tmp_repo / "paddle_tpu" / "inference" / "bad.py"
    bad.write_text("import time\ndef a():\n    return time.time()\n")
    rc = lint.main([str(bad), "--root", str(tmp_repo),
                    "--no-baseline"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "DT003" in out
    bad.write_text("x = 1\n")
    rc = lint.main([str(bad), "--root", str(tmp_repo),
                    "--no-baseline"])
    assert rc == 0


def test_cli_missing_path_is_an_error(tmp_repo, capsys):
    """A typo'd path must not read as a vacuously clean scan."""
    rc = lint.main(["definitely_not_a_dir",
                    "--root", str(tmp_repo), "--no-baseline"])
    assert rc == 2
    assert "no such file" in capsys.readouterr().err


def test_malformed_baseline_is_a_clear_error(tmp_repo, capsys):
    bad = tmp_repo / "paddle_tpu" / "inference" / "ok.py"
    bad.write_text("x = 1\n")
    (tmp_repo / lint.BASELINE_NAME).write_text("{not json")
    rc = lint.main([str(bad), "--root", str(tmp_repo)])
    assert rc == 2
    assert "invalid ptlint baseline" in capsys.readouterr().err
    with pytest.raises(ValueError, match="invalid ptlint baseline"):
        lint.load_baseline(str(tmp_repo / lint.BASELINE_NAME))


def test_write_baseline_round_trip(tmp_repo):
    bad = tmp_repo / "paddle_tpu" / "inference" / "bad.py"
    bad.write_text("import time\ndef a():\n    return time.time()\n")
    rc = lint.main([str(bad), "--root", str(tmp_repo),
                    "--write-baseline"])
    assert rc == 0
    data = json.loads(
        (tmp_repo / lint.BASELINE_NAME).read_text())
    assert data["entries"] == {
        "paddle_tpu/inference/bad.py::DT003": 1}
    rc = lint.main([str(bad), "--root", str(tmp_repo)])
    assert rc == 0  # baselined -> clean exit
