"""Optimizer tests: numeric parity vs hand-rolled numpy updates (parity
model: upstream test/legacy_test/test_adamw_op.py etc.)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt
from paddle_tpu.core.functional import extract_params, functional_call

# core-engine fast lane (see README "Tests")
pytestmark = pytest.mark.fast


def _numpy_adamw(w, g, m, v, step, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8, wd=0.01):
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    mhat = m / (1 - b1**step)
    vhat = v / (1 - b2**step)
    w = w - lr * (mhat / (np.sqrt(vhat) + eps) + wd * w)
    return w, m, v


def test_adamw_matches_numpy():
    w0 = np.random.randn(4, 3).astype(np.float32)
    g0 = np.random.randn(4, 3).astype(np.float32)
    o = opt.AdamW(learning_rate=1e-3, weight_decay=0.01, multi_precision=False)
    params = {"w": jnp.asarray(w0)}
    state = o.init(params)
    grads = {"w": jnp.asarray(g0)}
    m = np.zeros_like(w0)
    v = np.zeros_like(w0)
    w = w0.copy()
    for step in range(1, 4):
        new_params, state = o.update(grads, state, params)
        params = new_params
        w, m, v = _numpy_adamw(w, g0, m, v, step)
    np.testing.assert_allclose(np.asarray(params["w"]), w, rtol=1e-5, atol=1e-6)


def test_sgd_momentum():
    w0 = np.ones((3,), np.float32)
    g = np.ones((3,), np.float32) * 0.5
    o = opt.Momentum(learning_rate=0.1, momentum=0.9, multi_precision=False)
    params = {"w": jnp.asarray(w0)}
    state = o.init(params)
    params, state = o.update({"w": jnp.asarray(g)}, state, params)
    # v = 0.5; w = 1 - 0.1*0.5 = 0.95
    np.testing.assert_allclose(np.asarray(params["w"]), 0.95, rtol=1e-6)
    params, state = o.update({"w": jnp.asarray(g)}, state, params)
    # v = 0.9*0.5+0.5 = 0.95; w = 0.95 - 0.095
    np.testing.assert_allclose(np.asarray(params["w"]), 0.855, rtol=1e-6)


def test_master_weights_bf16():
    """multi_precision: bf16 params keep an fp32 master; tiny updates must
    not be lost to bf16 rounding."""
    w0 = jnp.ones((4,), jnp.bfloat16)
    o = opt.SGD(learning_rate=1e-4, multi_precision=True)
    params = {"w": w0}
    state = o.init(params)
    assert state["master"]["w"].dtype == jnp.float32
    g = {"w": jnp.full((4,), 0.1, jnp.bfloat16)}
    for _ in range(10):
        params, state = o.update(g, state, params)
    # master accumulated 10 * 1e-4*0.1 = 1e-4 steps exactly in fp32
    np.testing.assert_allclose(
        np.asarray(state["master"]["w"]), 1.0 - 1e-4, rtol=1e-5
    )


def test_global_norm_clip():
    clip = opt.ClipGradByGlobalNorm(1.0)
    grads = {"a": jnp.ones((4,)) * 3.0, "b": jnp.ones((4,)) * 4.0}
    clipped = clip(grads)
    total = np.sqrt(
        sum(float(jnp.sum(g**2)) for g in clipped.values())
    )
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_lr_schedules():
    s = opt.lr.LinearWarmup(
        learning_rate=0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1
    )
    assert abs(float(s.lr_at(0))) < 1e-8
    np.testing.assert_allclose(float(s.lr_at(5)), 0.05, rtol=1e-6)
    np.testing.assert_allclose(float(s.lr_at(20)), 0.1, rtol=1e-6)
    c = opt.lr.CosineAnnealingDecay(learning_rate=1.0, T_max=100)
    np.testing.assert_allclose(float(c.lr_at(0)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(c.lr_at(100)), 0.0, atol=1e-6)
    # stateful API
    c.step()
    assert c.get_lr() is not None


def test_train_mlp_converges():
    """End-to-end: jitted train step drives loss down (the 'minimum
    end-to-end slice' sanity check)."""
    pt.seed(42)
    model = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    o = opt.AdamW(learning_rate=1e-2, multi_precision=False)
    params = extract_params(model)
    state = o.init(params)

    x = np.random.randn(64, 4).astype(np.float32)
    y = (x @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)).astype(
        np.float32
    )
    x, y = jnp.asarray(x), jnp.asarray(y)

    @jax.jit
    def step(params, state, x, y):
        def loss_fn(p):
            pred = functional_call(model, p, x)
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, state = o.update(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(100):
        params, state, loss = step(params, state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.05, losses[::20]


def test_optimizer_eager_step():
    model = nn.Linear(4, 2)
    o = opt.SGD(learning_rate=0.1, parameters=model.parameters(),
                multi_precision=False)
    x = jnp.ones((3, 4))
    target = jnp.zeros((3, 2))
    from paddle_tpu import autograd

    loss, grads = autograd.backward(
        model, lambda out, t: jnp.mean((out - t) ** 2), x, target
    )
    w_before = np.asarray(model.weight.value).copy()
    o.set_gradients(grads)
    o.step()
    w_after = np.asarray(model.weight.value)
    assert not np.allclose(w_before, w_after)


def test_extended_lr_schedulers():
    """Round-3 scheduler zoo additions (parity: paddle.optimizer.lr)."""
    from paddle_tpu.optimizer import lr as L

    ms = L.MultiStepDecay(0.1, milestones=[3, 6], gamma=0.1)
    vals = []
    for _ in range(8):
        vals.append(ms.get_lr())
        ms.step()
    np.testing.assert_allclose(vals[0], 0.1)
    np.testing.assert_allclose(vals[4], 0.01, rtol=1e-6)
    np.testing.assert_allclose(vals[7], 0.001, rtol=1e-6)

    ne = L.NaturalExpDecay(1.0, gamma=0.5)
    np.testing.assert_allclose(float(ne.lr_at(2)), np.exp(-1.0), rtol=1e-6)

    it = L.InverseTimeDecay(1.0, gamma=1.0)
    np.testing.assert_allclose(float(it.lr_at(3)), 0.25, rtol=1e-6)

    lam = L.LambdaDecay(0.5, lambda e: 0.95 ** e)
    np.testing.assert_allclose(float(lam.lr_at(2)), 0.5 * 0.95**2,
                               rtol=1e-6)

    mult = L.MultiplicativeDecay(1.0, lambda e: 0.9)
    for _ in range(3):
        mult.step()
    np.testing.assert_allclose(mult.get_lr(), 0.9**3, rtol=1e-5)

    oc = L.OneCycleLR(max_learning_rate=1.0, total_steps=100,
                      divide_factor=10.0, phase_pct=0.3)
    assert float(oc.lr_at(0)) == pytest.approx(0.1, rel=1e-5)
    assert float(oc.lr_at(30)) == pytest.approx(1.0, rel=1e-4)
    assert float(oc.lr_at(100)) < 0.01  # annealed to the end lr

    cy = L.CyclicLR(0.1, 1.0, step_size_up=10)
    assert float(cy.lr_at(0)) == pytest.approx(0.1, rel=1e-6)
    assert float(cy.lr_at(10)) == pytest.approx(1.0, rel=1e-6)
    assert float(cy.lr_at(20)) == pytest.approx(0.1, rel=1e-6)
    assert float(cy.lr_at(25)) == pytest.approx(0.55, rel=1e-5)

    rp = L.ReduceOnPlateau(1.0, patience=1, factor=0.5)
    rp.step(metrics=1.0)
    rp.step(metrics=1.0)  # no improvement (1)
    rp.step(metrics=1.0)  # no improvement (2) > patience → decay
    assert rp.get_lr() == pytest.approx(0.5)
    rp.step(metrics=0.2)  # improvement resets
    assert rp.get_lr() == pytest.approx(0.5)


def test_scheduler_drives_optimizer_in_jit():
    """The functional lr_at path must work on-device inside the train
    step (no host sync)."""
    from paddle_tpu.optimizer import lr as L

    sched = L.OneCycleLR(max_learning_rate=0.1, total_steps=50)
    o = opt.SGD(learning_rate=sched)
    params = {"w": jnp.ones((4,))}
    state = o.init(params)
    g = {"w": jnp.ones((4,))}

    @jax.jit
    def step(params, state):
        return o.update(g, state, params)

    p1, s1 = step(params, state)
    assert bool(jnp.all(jnp.isfinite(p1["w"])))


def test_lars_trust_ratio_matches_numpy():
    """Lars vs a numpy reference of the lars_momentum kernel recurrence."""
    rng = np.random.default_rng(0)
    w0 = rng.standard_normal((6, 4)).astype(np.float32)
    g = rng.standard_normal((6, 4)).astype(np.float32)
    lr, mu, coeff, decay = 0.1, 0.9, 0.001, 0.0005

    o = opt.Lars(learning_rate=lr, momentum=mu, lars_coeff=coeff,
                 lars_weight_decay=decay, multi_precision=False)
    params = {"w": jnp.asarray(w0)}
    state = o.init(params)
    grads = {"w": jnp.asarray(g)}

    w_np, v_np = w0.copy(), np.zeros_like(w0)
    for _ in range(4):
        params, state = o.update(grads, state, params)
        w_norm = np.linalg.norm(w_np)
        g_norm = np.linalg.norm(g)
        local_lr = lr * coeff * w_norm / (g_norm + decay * w_norm)
        v_np = mu * v_np + local_lr * (g + decay * w_np)
        w_np = w_np - v_np
    np.testing.assert_allclose(np.asarray(params["w"]), w_np,
                               rtol=1e-5, atol=1e-6)


def test_lars_exclude_from_weight_decay():
    o = opt.Lars(learning_rate=0.1, lars_weight_decay=0.5,
                 exclude_from_weight_decay=["bias"],
                 multi_precision=False)
    params = {"fc.bias": jnp.ones((3,))}
    state = o.init(params)
    grads = {"fc.bias": jnp.full((3,), 0.1)}
    p1, _ = o.update(grads, state, params)
    # reference without any decay
    o2 = opt.Lars(learning_rate=0.1, lars_weight_decay=0.0,
                  multi_precision=False)
    p2, _ = o2.update(grads, o2.init(params), params)
    np.testing.assert_allclose(np.asarray(p1["fc.bias"]),
                               np.asarray(p2["fc.bias"]), rtol=1e-6)


def test_adamw_bf16_moments_track_fp32():
    """moment_dtype='bfloat16' halves Adam slot storage (the HBM-bound
    update is 10% of the TPU headline step); the quantized-EMA
    trajectory must track fp32 moments closely over many steps."""
    rng = np.random.default_rng(0)
    w0 = rng.standard_normal((64, 32)).astype(np.float32)

    def run(moment_dtype):
        o = opt.AdamW(learning_rate=1e-2, weight_decay=0.01,
                      multi_precision=False, moment_dtype=moment_dtype)
        params = {"w": jnp.asarray(w0)}
        state = o.init(params)
        for i in range(40):
            # deterministic pseudo-grads varying per step
            g = jnp.asarray(
                np.sin(0.1 * i + np.arange(w0.size, dtype=np.float32))
                .reshape(w0.shape))
            params, state = o.update({"w": g}, state, params)
        return np.asarray(params["w"]), state

    w_ref, s_ref = run(None)
    w_bf, s_bf = run("bfloat16")
    assert s_bf["slots"]["w"]["moment1"].dtype == jnp.bfloat16
    assert s_ref["slots"]["w"]["moment1"].dtype == jnp.float32
    # parameters after 40 steps of lr=1e-2 updates have moved O(0.4);
    # bf16 moment rounding must stay ~1e-3-level noise on top
    drift = np.abs(w_bf - w_ref).max()
    moved = np.abs(w_ref - w0).max()
    assert moved > 0.1, "test not exercising real updates"
    assert drift < 0.02 * moved, (drift, moved)
