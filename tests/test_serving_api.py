"""Streaming front door (paddle_tpu.serving_api): OpenAI-compatible
SSE serving over a real loopback socket + the SLO-aware multi-tenant
scheduler.

The contract under test, in order of importance:

* SSE streaming is END-TO-END REAL: an OpenAI-shaped request over a
  real socket streams token deltas incrementally (first chunk before
  generation completes), and greedy outputs are bit-identical to the
  ``engine.step_chunk`` library path in both cache modes.
* Client disconnect mid-stream reaches ``cancel(rid)`` on the
  scheduler thread — slots/pages/prefix refs provably freed (the
  chaos storm runs SANITIZED via the ``chaos`` marker fixture).
* The SLO-fair scheduler beats FIFO where it claims to: interactive
  TTFT under a saturated mixed burst, and the tenant-starvation
  adversary's worst-small-tenant TTFT bound (preemption fires).
* Scheduler + front door compile ZERO new programs — the
  compile-counter guard pins the program set to the engine's own.
"""

import http.client
import json
import socket
import struct
import threading
import time
import urllib.parse

import numpy as np
import pytest

from paddle_tpu import flags as F
from paddle_tpu.inference.serving import (
    ContinuousBatchingEngine,
    EngineConfig,
    build_request,
)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving_api import (
    SLOFairScheduler,
    TenantQuota,
    parse_completion_request,
    start_api_server,
)
from paddle_tpu.serving_api.protocol import ProtocolError


def _model(seed=0):
    import paddle_tpu as pt

    pt.seed(seed)
    cfg = LlamaConfig.tiny()
    return LlamaForCausalLM(cfg), cfg


def _ecfg(paged, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("seq_buckets", (16,))
    if paged:
        kw.setdefault("page_size", 8)
    return EngineConfig(paged=paged, **kw)


# ---------------- HTTP/SSE client helpers ----------------

def _connect(url, timeout=60):
    u = urllib.parse.urlparse(url)
    return http.client.HTTPConnection(u.hostname, u.port,
                                      timeout=timeout)


def _post_json(url, path, body, timeout=60):
    conn = _connect(url, timeout)
    try:
        conn.request("POST", path, json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _sse_request(url, body, timeout=120):
    """POST a streaming completion; returns (status, events, stamps):
    decoded ``data:`` frames (minus [DONE]) and a receive timestamp
    per frame — the incrementality evidence."""
    conn = _connect(url, timeout)
    try:
        conn.request("POST", "/v1/completions",
                     json.dumps(dict(body, stream=True)),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            return resp.status, [json.loads(resp.read() or b"{}")], []
        events, stamps = [], []
        while True:
            line = resp.fp.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            if line == b"data: [DONE]":
                break
            events.append(json.loads(line[len(b"data: "):]))
            stamps.append(time.perf_counter())
        return resp.status, events, stamps
    finally:
        conn.close()


def _sse_tokens(events):
    return [t for e in events for t in e["choices"][0]["token_ids"]]


# ---------------- protocol validation (no model) ----------------

def test_parse_completion_request_validation():
    ok = parse_completion_request(
        {"prompt": [1, 2, 3], "max_tokens": 4, "stream": True,
         "tenant": "acme", "slo": "interactive", "temperature": 0.5})
    assert ok.stream and ok.tenant == "acme"
    assert list(ok.prompt) == [1, 2, 3]
    kw = ok.engine_kwargs()
    assert kw["max_new_tokens"] == 4 and kw["slo"] == "interactive"
    with pytest.raises(ProtocolError, match="token ids"):
        parse_completion_request({"prompt": "a string prompt"})
    with pytest.raises(ProtocolError, match="token ids"):
        parse_completion_request({"prompt": []})
    with pytest.raises(ProtocolError, match="max_tokens"):
        parse_completion_request({"prompt": [1], "max_tokens": 0})
    with pytest.raises(ProtocolError, match="unknown request field"):
        parse_completion_request({"prompt": [1], "max_new_tokens": 4})
    with pytest.raises(ProtocolError, match="n > 1"):
        parse_completion_request({"prompt": [1], "n": 2})
    with pytest.raises(ProtocolError, match="JSON object"):
        parse_completion_request([1, 2])


# ---------------- SSE end-to-end parity ----------------

@pytest.mark.parametrize("paged", [False, True])
def test_sse_stream_parity_and_incrementality(paged):
    """Acceptance pin: an OpenAI-shaped request over a REAL socket
    streams tokens incrementally (several frames, spread in time —
    the first arrives before generation completes) and the
    concatenated deltas are bit-identical to the library path, in
    both cache modes."""
    model, cfg = _model(3)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, n) for n in (9, 5)]

    ref_eng = ContinuousBatchingEngine(model, _ecfg(paged))
    refs = [r.output for r in
            ref_eng.run(prompts, max_new_tokens=10, max_chunk=2)]

    eng = ContinuousBatchingEngine(model, _ecfg(paged))
    srv = start_api_server(eng, scheduler=None, max_chunk=2)
    try:
        for prompt, ref in zip(prompts, refs):
            status, events, stamps = _sse_request(
                srv.url, {"prompt": [int(t) for t in prompt],
                          "max_tokens": 10})
            assert status == 200
            assert _sse_tokens(events) == ref
            # incrementality: multiple delta frames, spread in time —
            # not one burst after completion
            data_frames = [e for e in events
                           if e["choices"][0]["token_ids"]]
            assert len(data_frames) >= 2
            assert stamps[-1] - stamps[0] > 0
            assert events[-1]["choices"][0]["finish_reason"] \
                == "max_new_tokens"
    finally:
        srv.shutdown()


def test_aggregate_echo_and_errors():
    model, cfg = _model(3)
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    srv = start_api_server(eng, scheduler=None, max_chunk=2)
    try:
        status, body = _post_json(
            srv.url, "/v1/completions",
            {"prompt": [3, 7, 11], "max_tokens": 4, "echo": True})
        assert status == 200
        ids = body["choices"][0]["token_ids"]
        assert ids[:3] == [3, 7, 11] and len(ids) == 7
        assert body["usage"]["completion_tokens"] == 4
        # build_request's own validation surfaces as HTTP 400 — one
        # validation source, the library path's exact errors
        status, err = _post_json(
            srv.url, "/v1/completions",
            {"prompt": [1], "max_tokens": 500})
        assert status == 400 and "max_len" in err["error"]["message"]
        status, err = _post_json(
            srv.url, "/v1/completions",
            {"prompt": [1], "slo": "platinum"})
        assert status == 400 and "slo" in err["error"]["message"]
        # unknown endpoint
        status, _ = _post_json(srv.url, "/v2/chat", {})
        assert status == 404
        # tenant-cardinality cap: client-controlled tenant strings
        # mint permanent per-tenant state — past the cap, NEW tenants
        # get 429 while known tenants and untagged requests pass
        saved = F.flag("api_max_tenants")
        try:
            F.set_flags({"api_max_tenants": 1})
            status, _ = _post_json(
                srv.url, "/v1/completions",
                {"prompt": [1, 2], "max_tokens": 2, "tenant": "t1"})
            assert status == 200
            status, err = _post_json(
                srv.url, "/v1/completions",
                {"prompt": [1, 2], "max_tokens": 2, "tenant": "t2"})
            assert status == 429
            assert "cardinality" in err["error"]["message"]
            status, _ = _post_json(
                srv.url, "/v1/completions",
                {"prompt": [1, 2], "max_tokens": 2, "tenant": "t1"})
            assert status == 200  # known tenant still passes
        finally:
            F.set_flags({"api_max_tenants": saved})
        # /v1/models + the shared observability surface
        conn = _connect(srv.url)
        try:
            conn.request("GET", "/v1/models")
            models = json.loads(conn.getresponse().read())
            assert models["data"][0]["id"] == "paddle-tpu"
            conn.request("GET", "/healthz")
            health = json.loads(conn.getresponse().read())
            assert health["status"] in ("ok", "saturated")
            conn.request("GET", "/metrics")
            assert conn.getresponse().status == 200
        finally:
            conn.close()
    finally:
        srv.shutdown()


# ---------------- compile-count pin ----------------

def test_front_door_compiles_zero_new_programs(compile_counter):
    """Scheduler + front door are pure host policy/transport: serving
    through HTTP with the SLO-fair scheduler installed dispatches
    EXACTLY the engine's own compiled set — no new program names, and
    (single chunk length) no new specializations after warmup."""
    model, cfg = _model(5)
    rng = np.random.default_rng(2)
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    # warm at the front door's chunk length (K is a static shape)
    eng.run([rng.integers(1, cfg.vocab_size, 9)], max_new_tokens=4,
            max_chunk=2)
    base = compile_counter()
    sched = SLOFairScheduler(
        tenants={"acme": TenantQuota(weight=2.0)}, probe_chunk=2)
    srv = start_api_server(eng, scheduler=sched, max_chunk=2)
    try:
        for i in range(3):
            status, events, _ = _sse_request(
                srv.url,
                {"prompt": [int(t) for t in
                            rng.integers(1, cfg.vocab_size, 8)],
                 "max_tokens": 6, "tenant": "acme",
                 "slo": "interactive"})
            assert status == 200 and len(_sse_tokens(events)) == 6
    finally:
        srv.shutdown()
    compile_counter.assert_programs(
        set(base) | {"prefill_chunk", "decode_chunk", "page_copy"})


# ---------------- SLO-fair vs FIFO (the A/B the sweep ranks) -------

def _mixed_burst(eng, cfg, rng, n_batch=3, n_int=3, batch_tokens=10,
                 int_tokens=4, ttft_target=1e9):
    """Saturated by construction: the batch hog queues first, the
    interactive tail behind it."""
    for _ in range(n_batch):
        eng.add_request(rng.integers(1, cfg.vocab_size, 10),
                        batch_tokens, tenant="bulk", slo="batch")
    rids = [eng.add_request(rng.integers(1, cfg.vocab_size, 10),
                            int_tokens, tenant="acme",
                            slo="interactive",
                            ttft_target_ms=ttft_target)
            for _ in range(n_int)]
    while eng.step_chunk(4) or eng._queue or eng.active.any():
        pass
    return rids


def test_slo_fair_beats_fifo_at_saturation():
    """The acceptance A/B, structurally: same mixed-tenant burst, the
    only difference is admission policy. SLO-fair admits the
    interactive tail ahead of the batch hog — its median TTFT drops
    by a large factor, and with the target calibrated between the two
    arms (half the FIFO median, attainment computed post-hoc from
    recorded ttft_ms) its goodput strictly beats FIFO's."""
    model, cfg = _model(7)
    rng = np.random.default_rng(4)

    def run_arm(sched):
        eng = ContinuousBatchingEngine(model, _ecfg(True, max_slots=2))
        if sched is not None:
            eng.set_scheduler(sched)
        eng.run([rng.integers(1, cfg.vocab_size, 8)],
                max_new_tokens=2, max_chunk=4)  # warm outside timing
        eng._finished.clear()
        rids = _mixed_burst(eng, cfg, np.random.default_rng(4))
        ints = [eng._finished[r] for r in rids]
        return eng, ints

    def make_sched():
        return SLOFairScheduler(
            tenants={"bulk": TenantQuota(weight=1.0, max_slots=1),
                     "acme": TenantQuota(weight=2.0)})

    fifo_eng, fifo_ints = run_arm(None)
    fair_eng, fair_ints = run_arm(make_sched())

    # DETERMINISTIC ordering claim first (immune to wall-clock
    # stalls): under FIFO every batch request is admitted before any
    # interactive one; SLO-fair admits the whole interactive tail
    # ahead of the hog's queue tail
    def admits(eng):
        batch = [r for r in eng._finished.values() if r.slo == "batch"]
        return batch

    fifo_batch = admits(fifo_eng)
    assert min(r._admit_t for r in fifo_ints) \
        > max(r._admit_t for r in fifo_batch)
    assert all(i._admit_t < max(b._admit_t for b in admits(fair_eng))
               for i in fair_ints)

    fifo_med = float(np.median([r.ttft_ms for r in fifo_ints]))
    fair_med = float(np.median([r.ttft_ms for r in fair_ints]))
    assert fair_med < fifo_med, (fair_med, fifo_med)

    # goodput: target calibrated BETWEEN the arms' medians, so the
    # met-count comparison only needs the medians to separate
    target = (fair_med + fifo_med) / 2
    fifo_met = sum(1 for r in fifo_ints if r.ttft_ms <= target)
    fair_met = sum(1 for r in fair_ints if r.ttft_ms <= target)
    assert fair_met > fifo_met, (fair_met, fifo_met)
    # every request still finishes under both policies (reordering
    # defers, never drops), outputs are per-request greedy-identical
    assert [r.finish_reason for r in fifo_ints] \
        == [r.finish_reason for r in fair_ints] \
        == ["max_new_tokens"] * 3
    assert [r.output for r in fifo_ints] \
        == [r.output for r in fair_ints]
    assert fair_eng.sched_stats["policy"] == "slo_fair"


@pytest.mark.chaos
def test_tenant_starvation_adversary_bounded():
    """Chaos-lane adversary (runs SANITIZED): tenant "hog" floods
    batch requests; tenant "small" submits interactive behind the
    flood. FIFO starves the small tenant until the flood drains;
    SLO-fair bounds its worst TTFT — urgency-jump + slot quota +
    preemption (which must fire, and must free slots/pages cleanly
    under the sanitizer's per-tick invariants)."""
    model, cfg = _model(9)

    def run_arm(sched):
        rng = np.random.default_rng(6)
        eng = ContinuousBatchingEngine(model, _ecfg(True, max_slots=2))
        if sched is not None:
            eng.set_scheduler(sched)
        # warm: the compile must not land in anyone's TTFT — the
        # adversary's claim is about QUEUE WAIT, not jit time
        eng.run([rng.integers(1, cfg.vocab_size, 8)],
                max_new_tokens=2, max_chunk=4)
        eng._finished.clear()
        for _ in range(5):
            eng.add_request(rng.integers(1, cfg.vocab_size, 10), 10,
                            tenant="hog", slo="batch")
        small = [eng.add_request(rng.integers(1, cfg.vocab_size, 8),
                                 3, tenant="small", slo="interactive",
                                 ttft_target_ms=1e9)
                 for _ in range(2)]
        while eng.step_chunk(4) or eng._queue or eng.active.any():
            pass
        worst = max(eng._finished[r].ttft_ms for r in small)
        return eng, worst

    _, fifo_worst = run_arm(None)
    sched = SLOFairScheduler(
        tenants={"hog": TenantQuota(weight=1.0, max_slots=1),
                 "small": TenantQuota(weight=4.0)},
        ttft_margin_ms=1e9)  # every tracked request counts urgent
    eng, fair_worst = run_arm(sched)
    assert fair_worst < fifo_worst, (fair_worst, fifo_worst)
    assert eng.sched_stats["preemptions"] >= 1
    snap = eng.tenant_snapshot()
    assert snap["tenants"]["hog"]["preemptions"] >= 1
    assert snap["scheduler"]["policy"] == "slo_fair"
    # the preempted hog requests still finished (deferral, not drop)
    assert snap["tenants"]["hog"]["finished"] == 5
    # pool fully recovers once the store is drained
    free0 = eng.pool.n_pages - 1
    eng._evict_pages(10 ** 9)
    assert eng.pool.free_pages == free0 and not eng.pool.ref


def test_preemption_replay_bit_identical(compile_counter):
    """engine.preempt mid-decode: the victim re-queues with history,
    replays through the existing prefill program, and its greedy
    output is bit-identical to an unpreempted run — zero new
    programs, pool clean."""
    model, cfg = _model(11)
    prompt = np.arange(1, 10)

    ref_eng = ContinuousBatchingEngine(model, _ecfg(True, max_slots=1))
    ref = ref_eng.run([prompt], max_new_tokens=12, max_chunk=2)[0]

    eng = ContinuousBatchingEngine(model, _ecfg(True, max_slots=1))
    eng.run([prompt[:4]], max_new_tokens=2, max_chunk=2)  # warm
    base = compile_counter()
    rid = eng.add_request(prompt, max_new_tokens=12)
    eng.step_chunk(2)
    eng.step_chunk(2)
    req = eng._slot_req[0]
    mid_tokens = len(req.output)
    assert 0 < mid_tokens < 12
    assert eng.preempt(0)
    assert not eng.active.any() and eng._queue[0].rid == rid
    while eng.step_chunk(2) or eng._queue or eng.active.any():
        pass
    got = eng._finished[rid]
    assert got.output == ref.output
    assert got.finish_reason == "max_new_tokens"
    assert eng.sched_stats["preemptions"] == 1
    compile_counter.assert_programs(
        set(base) | {"prefill_chunk", "decode_chunk", "page_copy"})
    free0 = eng.pool.n_pages - 1
    eng._evict_pages(10 ** 9)
    assert eng.pool.free_pages == free0 and not eng.pool.ref


def test_tenant_slot_quota_enforced():
    """A tenant at its max_slots quota never claims another slot even
    with requests queued — the other tenant's traffic takes it."""
    model, cfg = _model(13)
    rng = np.random.default_rng(8)
    eng = ContinuousBatchingEngine(model, _ecfg(True, max_slots=2))
    eng.set_scheduler(SLOFairScheduler(
        tenants={"a": TenantQuota(weight=1.0, max_slots=1)}))
    for _ in range(3):
        eng.add_request(rng.integers(1, cfg.vocab_size, 8), 6,
                        tenant="a")
    rid_b = eng.add_request(rng.integers(1, cfg.vocab_size, 8), 6,
                            tenant="b")
    max_a = 0
    while eng.step_chunk(2) or eng._queue or eng.active.any():
        a_active = sum(1 for r in eng._slot_req.values()
                       if r.tenant == "a")
        max_a = max(max_a, a_active)
    assert max_a == 1  # quota held at every tick
    assert eng._finished[rid_b].done
    snap = eng.tenant_snapshot()
    assert snap["tenants"]["a"]["finished"] == 3


# ---------------- tenant prefix-cache namespaces ----------------

def test_tenant_prefix_namespace_isolation():
    """Two tenants submitting the SAME prompt don't share cached KV:
    tenant B's identical prompt is a miss where tenant A's re-run is
    a hit. With the flag off, the chains merge (shared namespace)."""
    model, cfg = _model(15)
    rng = np.random.default_rng(10)
    prompt = rng.integers(1, cfg.vocab_size, 16)  # 2 hash blocks of 8
    saved = F.flag("tenant_prefix_namespace")
    try:
        F.set_flags({"tenant_prefix_namespace": True})
        eng = ContinuousBatchingEngine(model, _ecfg(True))

        def run_as(tenant, p):
            rid = eng.add_request(p, 4, tenant=tenant)
            while eng.step_chunk(2) or eng._queue or eng.active.any():
                pass
            return eng._finished[rid]

        run_as("a", prompt)
        assert eng.prefix_stats["hits"] == 0
        run_as("b", prompt)  # same tokens, different namespace
        assert eng.prefix_stats["hits"] == 0
        assert eng.prefix_stats["misses"] == 2
        ra2 = run_as("a", prompt)  # tenant A re-run: real hit
        assert eng.prefix_stats["hits"] == 1
        # outputs stay greedy-identical regardless of hit/miss
        rb2 = run_as("b", prompt)
        assert eng.prefix_stats["hits"] == 2
        assert ra2.output == rb2.output

        # flag off: one shared namespace — B hits what A published
        F.set_flags({"tenant_prefix_namespace": False})
        p2 = rng.integers(1, cfg.vocab_size, 16)
        run_as("a", p2)
        h0 = eng.prefix_stats["hits"]
        run_as("b", p2)
        assert eng.prefix_stats["hits"] == h0 + 1
    finally:
        F.set_flags({"tenant_prefix_namespace": saved})


def test_contig_store_ns_eviction_protects_inserting_chain():
    """Same-namespace-first eviction must not cannibalize the chain
    being inserted: a full store inserting tenant B's N-block chain
    evicts OTHER entries, never B's own just-inserted blocks (which
    would leave a gap every later lookup stops at)."""
    from paddle_tpu.inference.prefix_cache import ContigPrefixStore

    store = ContigPrefixStore(max_blocks=3)
    for i in range(3):
        store.insert(b"a%d" % i, i, i, ns="a")
    chain = [b"b0", b"b1", b"b2"]
    for i, h in enumerate(chain):
        store.insert(h, i, i, ns="b", protect=chain)
    # the whole chain survives; tenant A's entries were evicted
    assert all(h in store for h in chain)
    assert store.evictions == 3
    # and same-ns preference still holds for non-chain inserts: B's
    # next insert evicts B's own LRU block, not a neighbor's
    store.insert(b"c0", 0, 0, ns="a")  # store: b1? -> evicts ns-a? none
    # (no ns-a entries left: fell back to global LRU = b0)
    assert b"b0" not in store and b"c0" in store


def test_tenant_validation():
    model, cfg = _model(15)
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    with pytest.raises(ValueError, match="tenant"):
        eng.add_request(np.arange(1, 5), 2, tenant="")
    with pytest.raises(ValueError, match="tenant"):
        eng.add_request(np.arange(1, 5), 2, tenant="has space")
    with pytest.raises(ValueError, match="reserved"):
        eng.add_request(np.arange(1, 5), 2, tenant="-")
    with pytest.raises(ValueError, match="tenant"):
        eng.add_request(np.arange(1, 5), 2, tenant="x" * 65)


# ---------------- scheduler policy unit tests (no model) ----------

class _FakeEngine:
    """The slice of engine surface the policy reads."""

    def __init__(self, max_slots=2):
        import collections

        class _Cfg:
            pass

        self.cfg = _Cfg()
        self.cfg.max_slots = max_slots
        self.cfg.max_len = 256
        self.active = np.zeros(max_slots, bool)
        self.seq_lens = np.zeros(max_slots, np.int64)
        self._draining = False
        self._pool_blocked_prev = False
        self._queue = collections.deque()
        self._slot_req = {}
        self._free_heap = list(range(max_slots))
        self.pool = None


def _req(rid, tenant=None, slo=None, ttft=None, prompt_len=8,
         max_new=8):
    return build_request(rid, np.arange(1, prompt_len + 1), max_new,
                         tenant=tenant, slo=slo, ttft_target_ms=ttft,
                         max_len=256)


def test_policy_pick_urgency_then_fair_share():
    eng = _FakeEngine()
    sched = SLOFairScheduler(ttft_margin_ms=50.0)
    hog = [_req(i, tenant="hog") for i in range(3)]
    small = _req(10, tenant="small")
    eng._queue.extend(hog + [small])
    cands = list(eng._queue)
    # fresh ledger: FIFO tiebreak picks the head
    first = sched.pick(eng, cands)
    assert first is hog[0]
    sched.note_admit(eng, first)
    # hog charged service → small's tenant now ranks first
    assert sched.pick(eng, cands[1:]) is small
    # urgency overrides fair share: an at-risk request jumps the queue
    urgent = _req(11, tenant="hog", slo="interactive", ttft=1.0)
    urgent._submit_t -= 10.0  # waited 10s: far past its 1ms target
    cands2 = [small, urgent]
    assert sched.pick(eng, cands2) is urgent


def test_policy_quota_blocks_and_unblocks():
    eng = _FakeEngine(max_slots=2)
    sched = SLOFairScheduler(
        tenants={"a": TenantQuota(weight=1.0, max_slots=1)})
    occupying = _req(0, tenant="a")
    eng._slot_req[0] = occupying
    queued_a = _req(1, tenant="a")
    queued_b = _req(2, tenant="b")
    assert sched.pick(eng, [queued_a, queued_b]) is queued_b
    assert sched.pick(eng, [queued_a]) is None  # quota-blocked
    del eng._slot_req[0]  # slot freed
    assert sched.pick(eng, [queued_a]) is queued_a


def test_policy_newcomer_joins_at_min_service():
    eng = _FakeEngine()
    sched = SLOFairScheduler()
    for i in range(4):
        sched.note_admit(eng, _req(i, tenant="old"))
    # the newcomer joins at the current minimum, not at zero-history
    # advantage vs a tenant that has been waiting politely
    assert sched._service_of("new") == pytest.approx(
        min(sched._service.values()))


def test_policy_chunk_len_and_slot_caps():
    eng = _FakeEngine()
    sched = SLOFairScheduler(probe_chunk=2, ttft_margin_ms=1e9)
    assert sched.chunk_len(eng, 8) == 8  # empty queue: full chunks
    batch = _req(0, tenant="bulk", slo="batch", max_new=100)
    eng._slot_req[0] = batch
    eng.active[0] = True
    urgent = _req(1, slo="interactive", ttft=100.0)
    eng._queue.append(urgent)
    # queued + a FREE slot: admission can happen now — probe chunk
    assert sched.chunk_len(eng, 8) == 2
    caps = sched.slot_caps(eng)
    assert caps is not None and caps[0] == 2  # batch slot bounded
    # all slots busy with LONG budgets: a short chunk buys nothing —
    # step_adaptive's discipline keeps the full chunk
    eng._slot_req[1] = _req(2, tenant="bulk", slo="batch",
                            max_new=100)
    eng.active[1] = True
    assert sched.chunk_len(eng, 8) == 8
    # a slot finishing INSIDE the chunk re-enables the probe
    eng._slot_req[1].output.extend([1] * 97)  # 3 tokens left
    assert sched.chunk_len(eng, 8) == 2
    eng._queue.clear()
    assert sched.slot_caps(eng) is None
    # quota-blocked urgency must NOT trigger the slot caps: the
    # request the cap would serve can never be placed
    sched2 = SLOFairScheduler(
        tenants={"a": TenantQuota(weight=1.0, max_slots=1)},
        probe_chunk=2, ttft_margin_ms=1e9)
    eng._slot_req[1] = _req(3, tenant="a")
    blocked = _req(4, tenant="a", slo="interactive", ttft=100.0)
    eng._queue.append(blocked)
    assert sched2.slot_caps(eng) is None


def test_default_scheduler_flag():
    from paddle_tpu.serving_api import default_scheduler

    saved = F.flag("sched_policy")
    try:
        F.set_flags({"sched_policy": "fifo"})
        assert default_scheduler() is None
        F.set_flags({"sched_policy": "slo_fair"})
        assert isinstance(default_scheduler(), SLOFairScheduler)
        F.set_flags({"sched_policy": "nope"})
        with pytest.raises(ValueError, match="sched_policy"):
            default_scheduler()
    finally:
        F.set_flags({"sched_policy": saved})


# ---------------- chaos: client-disconnect storm ----------------

@pytest.mark.chaos
def test_client_disconnect_storm_frees_everything():
    """The satellite storm, SANITIZED: every 3rd streaming client
    hard-disconnects (RST) mid-stream. The cancel path must free all
    slots/pages/prefix refs (pool fully recovers), and every
    SURVIVOR's streamed tokens must be exactly the library path's
    greedy outputs."""
    model, cfg = _model(21)
    rng = np.random.default_rng(12)
    prompts = [rng.integers(1, cfg.vocab_size, 9) for _ in range(6)]

    ref_eng = ContinuousBatchingEngine(model, _ecfg(True))
    refs = [r.output for r in
            ref_eng.run(prompts, max_new_tokens=16, max_chunk=2)]

    # sanitize is ON (chaos fixture): the engine compiles on the
    # DRIVER thread, which therefore owns every tick
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    srv = start_api_server(eng, scheduler=None, max_chunk=2)
    results = {}

    u = urllib.parse.urlparse(srv.url)

    def client(i):
        # raw-socket SSE client: full control of the fd, so the
        # disconnecting clients can RST mid-stream (SO_LINGER 0 —
        # the server's next write fails immediately, not after a
        # FIN/close-wait grace)
        body = json.dumps({"prompt": [int(t) for t in prompts[i]],
                           "max_tokens": 16, "stream": True}).encode()
        sock = socket.create_connection((u.hostname, u.port),
                                        timeout=120)
        f = sock.makefile("rb")
        try:
            sock.sendall(
                b"POST /v1/completions HTTP/1.1\r\n"
                b"Host: x\r\nContent-Type: application/json\r\n"
                b"Content-Length: %d\r\nConnection: close\r\n\r\n"
                % len(body) + body)
            while True:  # skip the response headers
                line = f.readline()
                if line in (b"\r\n", b""):
                    break
            toks = []
            frames = 0
            while True:
                line = f.readline()
                if not line:
                    break
                line = line.strip()
                if line == b"data: [DONE]":
                    break
                if not line.startswith(b"data: "):
                    continue
                ev = json.loads(line[len(b"data: "):])
                toks.extend(ev["choices"][0]["token_ids"])
                frames += 1
                if i % 3 == 2 and frames >= 1:
                    sock.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
                    f.close()
                    sock.close()
                    results[i] = ("disconnected", toks)
                    return
            results[i] = ("done", toks)
        finally:
            for c in (f, sock):
                try:
                    c.close()
                except OSError:
                    pass

    try:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        # the engine must observe every disconnect as a cancel (or the
        # request finished first — then nothing leaked either way);
        # tenant_snapshot is a SAFE_READS reader: legal off-thread
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:
            snap = eng.tenant_snapshot()["tenants"].get("-", {})
            if snap.get("cancelled", 0) + snap.get("finished", 0) \
                    >= len(prompts) and snap.get("active_slots") == 0:
                break
            time.sleep(0.05)
    finally:
        srv.shutdown()

    # survivors: streamed tokens bit-identical to the library path
    survivors = [i for i in range(len(prompts)) if i % 3 != 2]
    for i in survivors:
        kind, toks = results[i]
        assert kind == "done"
        assert toks == refs[i], f"survivor {i} diverged"
    # disconnected clients' requests were cancelled mid-flight
    snap = eng.tenant_snapshot()["tenants"]["-"]
    assert snap["cancelled"] == 2, snap
    assert snap["finished"] == len(survivors)
    # leak-free: no active slots, all rids terminal, pool recovers
    # fully once the (legitimately retained) prefix store drains
    assert not eng.active.any() and not eng._queue
    free0 = eng.pool.n_pages - 1
    eng._evict_pages(10 ** 9)
    assert eng.pool.free_pages == free0 and not eng.pool.ref


# ---------------- front door over a router fleet ----------------

@pytest.mark.slow
def test_front_door_over_router_fleet():
    """The same wire surface fronts an EngineRouter: SSE requests
    place/stream across replicas, /healthz aggregates fleet
    readiness, and the fleet tenant snapshot merges replicas."""
    from paddle_tpu.inference.router import EngineRouter

    model, cfg = _model(23)
    rng = np.random.default_rng(14)
    prompts = [rng.integers(1, cfg.vocab_size, 8) for _ in range(3)]

    ref_eng = ContinuousBatchingEngine(model, _ecfg(True, max_slots=1))
    refs = [r.output for r in
            ref_eng.run(prompts, max_new_tokens=6, max_chunk=2)]

    router = EngineRouter(model, _ecfg(True, max_slots=1),
                          n_replicas=2)
    srv = start_api_server(router, scheduler=None, max_chunk=2)
    try:
        for prompt, ref in zip(prompts, refs):
            status, events, _ = _sse_request(
                srv.url, {"prompt": [int(t) for t in prompt],
                          "max_tokens": 6, "tenant": "acme"})
            assert status == 200
            assert _sse_tokens(events) == ref
        conn = _connect(srv.url)
        try:
            conn.request("GET", "/healthz")
            health = json.loads(conn.getresponse().read())
            assert "routable_replicas" in health["backpressure"]
        finally:
            conn.close()
        snap = router.tenant_snapshot()
        assert snap["tenants"]["acme"]["finished"] == 3
    finally:
        srv.shutdown()
