"""Round-3 surface widening: CTC loss, the extended loss zoo, and the
extended optimizer zoo — numeric parity vs torch (CPU) where torch
implements the same formula, vs hand-rolled numpy otherwise.

Parity model: upstream test/legacy_test/test_warpctc_op.py,
test_ctc_loss.py, test_rmsprop_op.py, test_adamax_op.py,
test_adadelta_op.py, test_nadam_op.py, test_radam_op.py,
test_rprop_op.py, test_asgd_op.py, and the paddle.nn loss tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt
import paddle_tpu.nn.functional as F


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------
class TestCTC:
    def _case(self, seed=0, T=12, B=4, C=6, L=5):
        rng = np.random.default_rng(seed)
        logits = rng.normal(size=(T, B, C)).astype(np.float32)
        labels = rng.integers(1, C, (B, L))
        ilen = np.array([T, T - 2, T, 7], np.int64)[:B]
        llen = np.array([L, 3, 4, 0], np.int64)[:B]
        return logits, labels, ilen, llen

    @pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
    def test_vs_torch(self, reduction):
        logits, labels, ilen, llen = self._case()
        ours = F.ctc_loss(
            jnp.asarray(logits), jnp.asarray(labels), ilen, llen,
            blank=0, reduction=reduction,
        )
        ref = torch.nn.functional.ctc_loss(
            torch.log_softmax(torch.tensor(logits), dim=-1),
            torch.tensor(labels), torch.tensor(ilen), torch.tensor(llen),
            blank=0, reduction=reduction if reduction != "none" else "none",
        )
        np.testing.assert_allclose(
            np.asarray(ours), ref.numpy(), rtol=2e-4, atol=2e-4
        )

    def test_grad_vs_torch(self):
        logits, labels, ilen, llen = self._case(seed=3)
        g = jax.grad(
            lambda x: F.ctc_loss(
                x, jnp.asarray(labels), ilen, llen, reduction="mean"
            )
        )(jnp.asarray(logits))
        lt = torch.tensor(logits, requires_grad=True)
        torch.nn.functional.ctc_loss(
            torch.log_softmax(lt, -1), torch.tensor(labels),
            torch.tensor(ilen), torch.tensor(llen), reduction="mean",
        ).backward()
        np.testing.assert_allclose(
            np.asarray(g), lt.grad.numpy(), rtol=1e-3, atol=1e-4
        )

    def test_nonblank_zero(self):
        """blank can be any class id, not just 0."""
        logits, labels, ilen, llen = self._case(seed=1)
        labels = np.where(labels == 5, 1, labels)  # keep 5 free for blank
        ours = F.ctc_loss(
            jnp.asarray(logits), jnp.asarray(labels), ilen, llen,
            blank=5, reduction="mean",
        )
        ref = torch.nn.functional.ctc_loss(
            torch.log_softmax(torch.tensor(logits), dim=-1),
            torch.tensor(labels), torch.tensor(ilen), torch.tensor(llen),
            blank=5, reduction="mean",
        )
        np.testing.assert_allclose(
            np.asarray(ours), ref.numpy(), rtol=2e-4, atol=2e-4
        )

    def test_layer_and_jit(self):
        logits, labels, ilen, llen = self._case(seed=2)
        layer = nn.CTCLoss(blank=0, reduction="sum")
        eager = layer(jnp.asarray(logits), jnp.asarray(labels), ilen, llen)
        jitted = jax.jit(
            lambda x: layer(x, jnp.asarray(labels), ilen, llen)
        )(jnp.asarray(logits))
        np.testing.assert_allclose(
            np.asarray(eager), np.asarray(jitted), rtol=1e-6
        )


# ---------------------------------------------------------------------------
# loss zoo vs torch
# ---------------------------------------------------------------------------
class TestLossZoo:
    def setup_method(self, _):
        rng = np.random.default_rng(7)
        self.x = rng.normal(size=(8, 5)).astype(np.float32)
        self.y = rng.normal(size=(8, 5)).astype(np.float32)
        self.rng = rng

    def _cmp(self, ours, theirs, **tol):
        tol.setdefault("rtol", 1e-5)
        tol.setdefault("atol", 1e-6)
        np.testing.assert_allclose(
            np.asarray(ours), theirs.numpy(), **tol
        )

    def test_bce(self):
        p = 1.0 / (1.0 + np.exp(-self.x))
        t = (self.y > 0).astype(np.float32)
        self._cmp(
            nn.BCELoss()(jnp.asarray(p), jnp.asarray(t)),
            torch.nn.BCELoss()(torch.tensor(p), torch.tensor(t)),
        )

    def test_cosine_embedding(self):
        lab = np.where(self.rng.random(8) > 0.5, 1, -1).astype(np.int64)
        self._cmp(
            nn.CosineEmbeddingLoss(margin=0.2)(
                jnp.asarray(self.x), jnp.asarray(self.y), jnp.asarray(lab)
            ),
            torch.nn.CosineEmbeddingLoss(margin=0.2)(
                torch.tensor(self.x), torch.tensor(self.y),
                torch.tensor(lab),
            ),
        )

    def test_triplet_margin(self):
        z = self.rng.normal(size=(8, 5)).astype(np.float32)
        self._cmp(
            nn.TripletMarginLoss(margin=1.0)(
                jnp.asarray(self.x), jnp.asarray(self.y), jnp.asarray(z)
            ),
            torch.nn.TripletMarginLoss(margin=1.0)(
                torch.tensor(self.x), torch.tensor(self.y), torch.tensor(z)
            ),
            rtol=1e-4,
        )

    def test_soft_margin(self):
        lab = np.where(self.y > 0, 1.0, -1.0).astype(np.float32)
        self._cmp(
            nn.SoftMarginLoss()(jnp.asarray(self.x), jnp.asarray(lab)),
            torch.nn.SoftMarginLoss()(
                torch.tensor(self.x), torch.tensor(lab)
            ),
        )

    def test_hinge_embedding(self):
        lab = np.where(self.y > 0, 1.0, -1.0).astype(np.float32)
        self._cmp(
            nn.HingeEmbeddingLoss(margin=1.0)(
                jnp.asarray(self.x), jnp.asarray(lab)
            ),
            torch.nn.HingeEmbeddingLoss(margin=1.0)(
                torch.tensor(self.x), torch.tensor(lab)
            ),
        )

    @pytest.mark.parametrize("log_input,full", [(True, False), (False, True)])
    def test_poisson_nll(self, log_input, full):
        t = np.abs(self.y) * 3
        self._cmp(
            nn.PoissonNLLLoss(log_input=log_input, full=full)(
                jnp.asarray(self.x), jnp.asarray(t)
            ),
            torch.nn.PoissonNLLLoss(
                log_input=log_input, full=full, eps=1e-8
            )(torch.tensor(self.x), torch.tensor(t)),
            rtol=1e-4, atol=1e-5,
        )

    def test_gaussian_nll(self):
        var = np.abs(self.y) + 0.1
        self._cmp(
            nn.GaussianNLLLoss(full=True)(
                jnp.asarray(self.x), jnp.asarray(self.y), jnp.asarray(var)
            ),
            torch.nn.GaussianNLLLoss(full=True)(
                torch.tensor(self.x), torch.tensor(self.y),
                torch.tensor(var),
            ),
        )

    def test_multilabel_soft_margin(self):
        t = (self.y > 0).astype(np.float32)
        self._cmp(
            nn.MultiLabelSoftMarginLoss()(
                jnp.asarray(self.x), jnp.asarray(t)
            ),
            torch.nn.MultiLabelSoftMarginLoss()(
                torch.tensor(self.x), torch.tensor(t)
            ),
        )


# ---------------------------------------------------------------------------
# optimizer zoo
# ---------------------------------------------------------------------------
def _run_ours(o, w0, grads_seq):
    params = {"w": jnp.asarray(w0)}
    state = o.init(params)
    for g in grads_seq:
        params, state = o.update({"w": jnp.asarray(g)}, state, params)
    return np.asarray(params["w"])


def _run_torch(cls, w0, grads_seq, **kw):
    w = torch.tensor(w0.copy(), requires_grad=True)
    o = cls([w], **kw)
    for g in grads_seq:
        w.grad = torch.tensor(g)
        o.step()
    return w.detach().numpy()


@pytest.fixture
def grads_seq():
    rng = np.random.default_rng(11)
    return [rng.normal(size=(6, 4)).astype(np.float32) for _ in range(5)]


@pytest.fixture
def w0():
    return np.random.default_rng(5).normal(size=(6, 4)).astype(np.float32)


class TestOptimizerZoo:
    def test_adamax_vs_torch(self, w0, grads_seq):
        ours = _run_ours(
            opt.Adamax(learning_rate=0.01, multi_precision=False),
            w0, grads_seq,
        )
        ref = _run_torch(torch.optim.Adamax, w0, grads_seq, lr=0.01)
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_adadelta_vs_torch(self, w0, grads_seq):
        ours = _run_ours(
            opt.Adadelta(learning_rate=0.5, rho=0.9, epsilon=1e-6,
                         multi_precision=False),
            w0, grads_seq,
        )
        ref = _run_torch(torch.optim.Adadelta, w0, grads_seq,
                         lr=0.5, rho=0.9, eps=1e-6)
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_nadam_vs_torch(self, w0, grads_seq):
        ours = _run_ours(
            opt.NAdam(learning_rate=0.01, multi_precision=False),
            w0, grads_seq,
        )
        ref = _run_torch(torch.optim.NAdam, w0, grads_seq, lr=0.01)
        np.testing.assert_allclose(ours, ref, rtol=1e-3, atol=1e-4)

    def test_radam_vs_torch(self, w0, grads_seq):
        ours = _run_ours(
            opt.RAdam(learning_rate=0.01, multi_precision=False),
            w0, grads_seq,
        )
        ref = _run_torch(torch.optim.RAdam, w0, grads_seq, lr=0.01)
        np.testing.assert_allclose(ours, ref, rtol=1e-3, atol=1e-4)

    def test_rprop_vs_torch(self, w0, grads_seq):
        ours = _run_ours(
            opt.Rprop(learning_rate=0.01, multi_precision=False),
            w0, grads_seq,
        )
        ref = _run_torch(torch.optim.Rprop, w0, grads_seq, lr=0.01)
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_rmsprop_vs_numpy(self, w0, grads_seq):
        """paddle semantics: denom = sqrt(ms + eps) (torch uses
        sqrt(ms) + eps, so compare against numpy, not torch)."""
        rho, eps, lr, mom = 0.95, 1e-6, 0.01, 0.9
        ours = _run_ours(
            opt.RMSProp(learning_rate=lr, rho=rho, epsilon=eps,
                        momentum=mom, multi_precision=False),
            w0, grads_seq,
        )
        w = w0.copy().astype(np.float64)
        ms = np.zeros_like(w)
        v = np.zeros_like(w)
        for g in grads_seq:
            g = g.astype(np.float64)
            ms = rho * ms + (1 - rho) * g * g
            v = mom * v + lr * g / np.sqrt(ms + eps)
            w = w - v
        np.testing.assert_allclose(ours, w, rtol=1e-4, atol=1e-5)

    def test_rmsprop_centered(self, w0, grads_seq):
        rho, eps, lr = 0.9, 1e-6, 0.01
        ours = _run_ours(
            opt.RMSProp(learning_rate=lr, rho=rho, epsilon=eps,
                        centered=True, multi_precision=False),
            w0, grads_seq,
        )
        w = w0.copy().astype(np.float64)
        ms = np.zeros_like(w)
        mg = np.zeros_like(w)
        v = np.zeros_like(w)
        for g in grads_seq:
            g = g.astype(np.float64)
            ms = rho * ms + (1 - rho) * g * g
            mg = rho * mg + (1 - rho) * g
            v = lr * g / np.sqrt(ms - mg * mg + eps)
            w = w - v
        np.testing.assert_allclose(ours, w, rtol=1e-4, atol=1e-5)

    def test_asgd_window_mean(self, w0):
        """ASGD with batch_num=n: d converges to the running mean of the
        last grads; with a constant grad, the update equals plain SGD."""
        g = np.full((6, 4), 0.5, np.float32)
        ours = _run_ours(
            opt.ASGD(learning_rate=0.1, batch_num=4,
                     multi_precision=False),
            w0, [g] * 3,
        )
        np.testing.assert_allclose(ours, w0 - 3 * 0.1 * 0.5, rtol=1e-5)

    def test_all_converge_quadratic(self):
        """every optimizer shrinks f(w)=||w||^2 (integration smoke)."""
        for cls, kw in [
            (opt.RMSProp, {}), (opt.Adamax, {}), (opt.Adadelta,
                                                  {"learning_rate": 1.0}),
            (opt.NAdam, {}), (opt.RAdam, {}), (opt.ASGD, {}),
            (opt.Rprop, {}),
        ]:
            o = cls(multi_precision=False, **kw)
            params = {"w": jnp.ones((8,), jnp.float32)}
            state = o.init(params)
            for _ in range(50):
                g = {"w": 2.0 * params["w"]}
                params, state = o.update(g, state, params)
            assert float(jnp.sum(params["w"] ** 2)) < 8.0, cls.__name__

    def test_eager_step_api(self, w0):
        """paddle-style: opt(parameters=...), backward, step."""
        lin = nn.Linear(4, 2)
        o = opt.RMSProp(learning_rate=0.01,
                        parameters=lin.parameters())
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(3, 4)).astype(np.float32))

        from paddle_tpu.core.functional import extract_params, functional_call

        params = extract_params(lin)
        grads = jax.grad(
            lambda p: jnp.sum(functional_call(lin, p, x) ** 2)
        )(params)
        before = np.asarray(lin.weight.value).copy()
        o.set_gradients(grads)
        o.step()
        assert not np.allclose(before, np.asarray(lin.weight.value))


# ---------------------------------------------------------------------------
# io: samplers + dataset combinators
# ---------------------------------------------------------------------------
class TestIoSamplers:
    def test_sequence_and_random_sampler(self):
        from paddle_tpu import io

        ds = io.TensorDataset(np.arange(10))
        assert list(io.SequenceSampler(ds)) == list(range(10))
        idx = list(io.RandomSampler(ds, generator=0))
        assert sorted(idx) == list(range(10))
        idx2 = list(io.RandomSampler(ds, replacement=True, num_samples=30))
        assert len(idx2) == 30 and max(idx2) < 10

    def test_weighted_sampler(self):
        from paddle_tpu import io

        s = io.WeightedRandomSampler([0.0, 0.0, 1.0], num_samples=20)
        assert list(s) == [2] * 20

    def test_sampler_drives_batch_sampler(self):
        from paddle_tpu import io

        ds = io.TensorDataset(np.arange(8))
        bs = io.BatchSampler(
            sampler=io.SequenceSampler(ds), batch_size=3
        )
        assert list(bs) == [[0, 1, 2], [3, 4, 5], [6, 7]]

    def test_concat_compose_chain(self):
        from paddle_tpu import io

        a = io.TensorDataset(np.arange(3))
        b = io.TensorDataset(np.arange(10, 14))
        cat = io.ConcatDataset([a, b])
        assert len(cat) == 7
        assert int(cat[3][0]) == 10 and int(cat[-1][0]) == 13

        comp = io.ComposeDataset([a, io.TensorDataset(np.arange(100, 103))])
        assert len(comp) == 3
        assert tuple(int(v) for v in comp[1]) == (1, 101)

        class It(io.IterableDataset):
            def __init__(self, vals):
                self.vals = vals

            def __iter__(self):
                return iter(self.vals)

        ch = io.ChainDataset([It([1, 2]), It([3])])
        assert list(ch) == [1, 2, 3]

    def test_worker_info_main_process(self):
        from paddle_tpu import io

        assert io.get_worker_info() is None


# ---------------------------------------------------------------------------
# LBFGS
# ---------------------------------------------------------------------------
class TestLBFGS:
    def _quad_setup(self):
        rng = np.random.default_rng(3)
        A = rng.normal(size=(6, 6)).astype(np.float32)
        A = A @ A.T + 6 * np.eye(6, dtype=np.float32)
        b = rng.normal(size=(6,)).astype(np.float32)
        w0 = rng.normal(size=(6,)).astype(np.float32)
        return A, b, w0

    def test_parity_vs_torch_no_linesearch(self):
        A, b, w0 = self._quad_setup()
        from paddle_tpu.core.parameter import Parameter

        p = Parameter(jnp.asarray(w0.copy()), name="w")
        o = opt.LBFGS(learning_rate=0.5, max_iter=4, parameters=[p])
        Aj, bj = jnp.asarray(A), jnp.asarray(b)

        def closure():
            w = p.value
            loss = 0.5 * w @ Aj @ w - bj @ w
            p.grad = Aj @ w - bj
            return loss

        o.step(closure)
        ours = np.asarray(p.value)

        wt = torch.tensor(w0.copy(), requires_grad=True)
        ot = torch.optim.LBFGS([wt], lr=0.5, max_iter=4)
        At, bt = torch.tensor(A), torch.tensor(b)

        def tclosure():
            ot.zero_grad()
            loss = 0.5 * wt @ At @ wt - bt @ wt
            loss.backward()
            return loss

        ot.step(tclosure)
        np.testing.assert_allclose(ours, wt.detach().numpy(),
                                   rtol=1e-3, atol=1e-4)

    def test_strong_wolfe_parity_vs_torch(self):
        """Cubic-interpolation zoom matches torch's _strong_wolfe: same
        line-search evaluation sequence => same iterates on a
        non-quadratic objective (not just the same limit point)."""
        A, b, w0 = self._quad_setup()
        from paddle_tpu.core.parameter import Parameter

        p = Parameter(jnp.asarray(w0.copy()), name="w")
        o = opt.LBFGS(learning_rate=1.0, max_iter=6,
                      line_search_fn="strong_wolfe", parameters=[p])
        Aj, bj = jnp.asarray(A), jnp.asarray(b)

        def closure():
            w = p.value
            loss = 0.5 * w @ Aj @ w - bj @ w + 0.1 * jnp.sum(w ** 4)
            p.grad = Aj @ w - bj + 0.4 * w ** 3
            return loss

        o.step(closure)
        ours = np.asarray(p.value)

        wt = torch.tensor(w0.copy(), requires_grad=True)
        ot = torch.optim.LBFGS([wt], lr=1.0, max_iter=6,
                               line_search_fn="strong_wolfe")
        At, bt = torch.tensor(A), torch.tensor(b)

        def tclosure():
            ot.zero_grad()
            loss = (0.5 * wt @ At @ wt - bt @ wt
                    + 0.1 * torch.sum(wt ** 4))
            loss.backward()
            return loss

        ot.step(tclosure)
        np.testing.assert_allclose(ours, wt.detach().numpy(),
                                   rtol=1e-3, atol=1e-4)

    def test_strong_wolfe_converges_rosenbrock(self):
        from paddle_tpu.core.parameter import Parameter

        p = Parameter(jnp.asarray(np.array([-1.2, 1.0], np.float32)),
                      name="w")
        o = opt.LBFGS(learning_rate=1.0, max_iter=100,
                      line_search_fn="strong_wolfe", parameters=[p])

        def rosen(w):
            return (1 - w[0]) ** 2 + 100.0 * (w[1] - w[0] ** 2) ** 2

        def closure():
            loss, g = jax.value_and_grad(rosen)(p.value)
            p.grad = g
            return loss

        for _ in range(8):
            o.step(closure)
        w = np.asarray(p.value)
        assert float(rosen(jnp.asarray(w))) < 1e-4, w

    def test_backward_populates_param_grad(self):
        lin = nn.Linear(3, 2)
        from paddle_tpu import autograd

        x = jnp.ones((4, 3))
        loss, grads = autograd.backward(
            lin, lambda out: jnp.sum(out ** 2), x
        )
        assert lin.weight.grad is not None
        np.testing.assert_allclose(
            np.asarray(lin.weight.grad),
            np.asarray(grads[lin.weight.name]),
        )


# ---------------------------------------------------------------------------
# gradient merge (strategy.gradient_merge) in TrainStep
# ---------------------------------------------------------------------------
class TestGradientMerge:
    def _mk(self, merge_k):
        import jax

        from paddle_tpu import distributed as dist
        from paddle_tpu.distributed.strategy import DistributedStrategy
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.trainer import TrainStep

        pt.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2,
                               use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        strategy = DistributedStrategy()
        if merge_k > 1:
            strategy.gradient_merge = True
            strategy.gradient_merge_k_steps = merge_k
        mesh = dist.build_mesh(devices=jax.devices()[:1])
        o = opt.AdamW(learning_rate=1e-3, multi_precision=False)
        return TrainStep(model, o, mesh, strategy), cfg

    def test_merged_equals_full_batch(self):
        """k micro-batches with mean-accumulated grads == one full-batch
        step (same data, dropout off)."""
        ts1, cfg = self._mk(1)
        ts4, _ = self._mk(4)
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 16)))
        batch = {"input_ids": ids, "labels": ids}
        l1 = ts1.run(batch)
        l4 = ts4.run(batch)
        np.testing.assert_allclose(float(l1), float(l4), rtol=2e-5)
        for n in ts1.params:
            np.testing.assert_allclose(
                np.asarray(ts1.params[n]), np.asarray(ts4.params[n]),
                rtol=2e-4, atol=2e-5,
            )
        assert ts4.gradient_merge_k == 4

    def test_indivisible_batch_raises(self):
        ts3, cfg = self._mk(3)
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, cfg.vocab_size, (8, 16)))
        with pytest.raises(ValueError, match="not divisible"):
            ts3.run({"input_ids": ids, "labels": ids})


class TestFunctionalLossForms:
    """F.* loss spellings vs torch (the layer classes are already
    covered; these check the functional forms paddle users call)."""

    def setup_method(self, _):
        rng = np.random.default_rng(11)
        self.x = rng.normal(size=(6, 4)).astype(np.float32)
        self.y = rng.normal(size=(6, 4)).astype(np.float32)

    def test_kl_div(self):
        logp = np.log(np.abs(self.x) / np.abs(self.x).sum(-1, keepdims=True))
        q = np.abs(self.y) / np.abs(self.y).sum(-1, keepdims=True)
        ours = F.kl_div(jnp.asarray(logp), jnp.asarray(q),
                        reduction="batchmean")
        ref = torch.nn.functional.kl_div(
            torch.tensor(logp), torch.tensor(q), reduction="batchmean")
        np.testing.assert_allclose(float(ours), float(ref), rtol=1e-5)

    def test_smooth_l1_delta(self):
        # paddle smooth_l1 with delta: 0.5 d^2/delta vs d - delta/2
        ours = F.smooth_l1_loss(jnp.asarray(self.x), jnp.asarray(self.y),
                                delta=2.0, reduction="none")
        ref = torch.nn.functional.smooth_l1_loss(
            torch.tensor(self.x), torch.tensor(self.y), beta=2.0,
            reduction="none")
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_focal_loss_vs_numpy(self):
        t = (self.y > 0).astype(np.float32)
        ours = float(F.sigmoid_focal_loss(
            jnp.asarray(self.x), jnp.asarray(t), reduction="sum"))
        p = 1 / (1 + np.exp(-self.x.astype(np.float64)))
        ce = -(t * np.log(p) + (1 - t) * np.log(1 - p))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = 0.25 * t + 0.75 * (1 - t)
        ref = (a_t * (1 - p_t) ** 2 * ce).sum()
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_dice_log_square(self):
        probs = np.abs(self.x) / np.abs(self.x).sum(-1, keepdims=True)
        labels = np.random.default_rng(3).integers(0, 4, (6, 1))
        d = float(F.dice_loss(jnp.asarray(probs), jnp.asarray(labels)))
        assert 0.0 < d < 1.0
        pr = 1 / (1 + np.exp(-self.x))
        t = (self.y > 0).astype(np.float32)
        ll = np.asarray(F.log_loss(jnp.asarray(pr), jnp.asarray(t)))
        ref = -(t * np.log(pr + 1e-4) + (1 - t) * np.log(1 - pr + 1e-4))
        np.testing.assert_allclose(ll, ref, rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(F.square_error_cost(jnp.asarray(self.x),
                                           jnp.asarray(self.y))),
            (self.x - self.y) ** 2, rtol=1e-6)

    def test_functional_matches_layers(self):
        """the F.* forms agree with the (already torch-verified) layer
        classes."""
        lab = np.where(self.y > 0, 1.0, -1.0).astype(np.float32)
        pairs = [
            (F.soft_margin_loss(jnp.asarray(self.x), jnp.asarray(lab)),
             nn.SoftMarginLoss()(jnp.asarray(self.x), jnp.asarray(lab))),
            (F.hinge_embedding_loss(jnp.asarray(self.x),
                                    jnp.asarray(lab)),
             nn.HingeEmbeddingLoss()(jnp.asarray(self.x),
                                     jnp.asarray(lab))),
            (F.margin_ranking_loss(jnp.asarray(self.x),
                                   jnp.asarray(self.y),
                                   jnp.asarray(lab)),
             nn.MarginRankingLoss()(jnp.asarray(self.x),
                                    jnp.asarray(self.y),
                                    jnp.asarray(lab))),
            (F.gaussian_nll_loss(jnp.asarray(self.x), jnp.asarray(self.y),
                                 jnp.asarray(np.abs(self.y) + 0.1)),
             nn.GaussianNLLLoss()(jnp.asarray(self.x),
                                  jnp.asarray(self.y),
                                  jnp.asarray(np.abs(self.y) + 0.1))),
        ]
        for ours, layer_out in pairs:
            np.testing.assert_allclose(float(ours), float(layer_out),
                                       rtol=1e-6)
