"""Examples must actually run: each script is executed end-to-end in a
subprocess on the CPU mesh (they self-bootstrap via examples/_cpu_mesh).
The examples are the migrating user's first contact; a broken import or
API drift there must fail CI, not ship silently."""

import os
import subprocess
import sys

import pytest

EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "examples")

SCRIPTS = [
    "train_llama_hybrid.py",
    "migrate_from_paddle.py",
    "finetune_bert_classifier.py",
    "generate_text.py",
    "audio_keyword_spotting.py",
]


@pytest.mark.parametrize("script", SCRIPTS)
def test_example_runs(script):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, script],
        cwd=EXAMPLES_DIR, env=env,
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, (
        f"{script} failed:\nSTDOUT:\n{r.stdout[-1500:]}\n"
        f"STDERR:\n{r.stderr[-1500:]}")
    assert r.stdout.strip(), f"{script} printed nothing"
