"""paddle_tpu.audio parity (upstream model: test/legacy_test/test_audio_*
— mel/DCT checked against the librosa formulas the reference follows)."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import audio
from paddle_tpu.audio import functional as AF


class TestFunctional:
    def test_hz_mel_roundtrip(self):
        for htk in (False, True):
            f = np.array([0.0, 250.0, 999.0, 1000.0, 4000.0, 11025.0])
            back = AF.mel_to_hz(AF.hz_to_mel(f, htk), htk)
            np.testing.assert_allclose(back, f, rtol=1e-10, atol=1e-8)

    def test_hz_to_mel_htk_formula(self):
        np.testing.assert_allclose(
            AF.hz_to_mel(700.0, htk=True), 2595.0 * math.log10(2.0)
        )

    def test_mel_frequencies_monotone(self):
        freqs = AF.mel_frequencies(40, 50.0, 8000.0)
        assert freqs.shape == (40,)
        assert np.all(np.diff(freqs) > 0)
        np.testing.assert_allclose(freqs[0], 50.0, atol=1e-6)
        np.testing.assert_allclose(freqs[-1], 8000.0, rtol=1e-6)

    def test_fbank_matrix_properties(self):
        fb = AF.compute_fbank_matrix(16000, 512, n_mels=40, f_min=0.0,
                                     norm=None)
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # triangles: each filter has a single peak
        for row in fb:
            peak = row.argmax()
            assert (np.diff(row[: peak + 1]) >= -1e-7).all()
            assert (np.diff(row[peak:]) <= 1e-7).all()
        # slaney norm: filters scaled by 2/bandwidth
        fb_s = AF.compute_fbank_matrix(16000, 512, n_mels=40, f_min=0.0,
                                       norm="slaney")
        assert fb_s.shape == (40, 257) and fb_s.max() < fb.max()

    def test_create_dct_orthonormal(self):
        d = AF.create_dct(13, 40, norm="ortho").astype(np.float64)
        # columns of an orthonormal DCT-II basis are orthonormal
        gram = d.T @ d
        np.testing.assert_allclose(gram, np.eye(13), atol=1e-6)

    def test_get_window_periodic(self):
        w = AF.get_window("hann", 64)
        assert w.shape == (64,)
        np.testing.assert_allclose(w, np.hanning(65)[:-1], atol=1e-7)
        w2 = AF.get_window("hamming", 32, fftbins=False)
        np.testing.assert_allclose(w2, np.hamming(32), atol=1e-7)
        w3 = AF.get_window(("kaiser", 8.0), 32, fftbins=False)
        np.testing.assert_allclose(w3, np.kaiser(32, 8.0), atol=1e-7)

    def test_power_to_db(self):
        s = jnp.asarray([1.0, 0.1, 1e-12])
        db = np.asarray(AF.power_to_db(s, top_db=None))
        np.testing.assert_allclose(db[:2], [0.0, -10.0], atol=1e-5)
        np.testing.assert_allclose(db[2], -100.0, atol=1e-4)  # amin floor
        db2 = np.asarray(AF.power_to_db(s, top_db=30.0))
        assert db2.min() >= db2.max() - 30.0


class TestFeatures:
    @pytest.fixture
    def wave(self):
        t = np.arange(16000) / 16000.0
        x = np.sin(2 * np.pi * 440.0 * t).astype(np.float32)
        return jnp.asarray(x[None, :])  # [1, T]

    def test_spectrogram_peak_at_440(self, wave):
        layer = audio.Spectrogram(n_fft=512, hop_length=256)
        s = np.asarray(layer(wave))
        assert s.shape[1] == 257
        freqs = AF.fft_frequencies(16000, 512)
        peak_bin = s.mean(axis=-1)[0].argmax()
        assert abs(freqs[peak_bin] - 440.0) < 16000 / 512  # within a bin

    def test_mel_pipeline_shapes(self, wave):
        mel = audio.MelSpectrogram(sr=16000, n_fft=512, n_mels=40)
        m = np.asarray(mel(wave))
        assert m.shape[:2] == (1, 40) and (m >= 0).all()
        logmel = audio.LogMelSpectrogram(sr=16000, n_fft=512, n_mels=40)
        lm = np.asarray(logmel(wave))
        assert lm.shape == m.shape
        mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)
        c = np.asarray(mfcc(wave))
        assert c.shape[:2] == (1, 13)

    def test_mfcc_equals_manual_dct(self, wave):
        logmel = audio.LogMelSpectrogram(sr=16000, n_fft=512, n_mels=40)
        lm = np.asarray(logmel(wave))[0]              # [40, T]
        dct = AF.create_dct(13, 40).astype(np.float64)
        manual = dct.T @ lm
        mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)
        np.testing.assert_allclose(
            np.asarray(mfcc(wave))[0], manual, rtol=1e-4, atol=1e-4
        )

    def test_jit_and_grad(self, wave):
        import jax

        mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_fft=512, n_mels=40)
        jitted = jax.jit(lambda x: mfcc(x))
        np.testing.assert_allclose(
            np.asarray(jitted(wave)), np.asarray(mfcc(wave)),
            rtol=1e-5, atol=1e-5,
        )
        g = jax.grad(lambda x: jnp.sum(mfcc(x) ** 2))(wave)
        assert np.isfinite(np.asarray(g)).all()
