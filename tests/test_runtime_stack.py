"""Flags, profiler scheduler, metrics, hapi Model, launch CLI (parity
model: the aux-subsystem tests in SURVEY.md §4/§5)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags, io, metric, nn, optimizer as opt
from paddle_tpu.hapi import EarlyStopping, Model
from paddle_tpu.profiler import ProfilerState, make_scheduler


def test_flags_roundtrip():
    assert flags.flag("io_prefetch_depth") == 2
    flags.set_flags({"FLAGS_io_prefetch_depth": 4})
    assert flags.get_flags("FLAGS_io_prefetch_depth") == {
        "FLAGS_io_prefetch_depth": 4
    }
    with pytest.raises(KeyError):
        flags.set_flags({"FLAGS_nope": 1})
    flags.set_flags({"FLAGS_io_prefetch_depth": 2})


def test_profiler_scheduler():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sched(i) for i in range(6)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN
    assert states[4] == ProfilerState.CLOSED  # repeat exhausted


def test_profiler_timer_only():
    from paddle_tpu.profiler import Profiler

    p = Profiler(timer_only=True)
    p.start()
    for _ in range(2):
        p.step()
    # stop() records the final in-flight step (work since the last
    # step() call would otherwise vanish from summary())
    p.stop()
    assert "steps: 3" in p.summary()
    p.stop()  # idempotent: no double-record
    assert "steps: 3" in p.summary()


def test_export_chrome_tracing_repoints_before_start(tmp_path):
    from paddle_tpu.profiler import Profiler, export_chrome_tracing

    target = str(tmp_path / "chrome_out")
    cb = export_chrome_tracing(target)
    p = Profiler(log_dir=str(tmp_path / "default"), timer_only=True,
                 on_trace_ready=cb)
    # the export dir must be in effect BEFORE any start_trace, not
    # swapped in by the callback after the trace was already written
    assert p.log_dir == target
    p.start()
    p.step()
    p.stop()
    assert p.log_dir == target


def test_metrics():
    acc = metric.Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
    label = np.array([1, 2])
    acc.update(pred, label)
    top1, top2 = acc.accumulate()
    assert top1 == 0.5
    assert top2 == 0.5
    p = metric.Precision()
    p.update(np.array([0.9, 0.9, 0.1]), np.array([1, 0, 1]))
    assert p.accumulate() == 0.5
    r = metric.Recall()
    r.update(np.array([0.9, 0.9, 0.1]), np.array([1, 0, 1]))
    assert r.accumulate() == 0.5
    auc = metric.Auc()
    auc.update(np.array([0.9, 0.8, 0.2, 0.1]), np.array([1, 1, 0, 0]))
    assert auc.accumulate() > 0.9


def test_hapi_model_fit_evaluate_predict(tmp_path):
    pt.seed(0)
    x = np.random.default_rng(0).standard_normal((64, 4)).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = (x @ w).astype(np.float32)
    ds = io.TensorDataset(x, y)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    model = Model(net)
    model.prepare(
        optimizer=opt.AdamW(learning_rate=1e-2, multi_precision=False),
        loss=lambda out, label: ((out - label) ** 2).mean(),
    )
    model.fit(ds, batch_size=16, epochs=25, verbose=0)
    logs = model.evaluate(ds, batch_size=16, verbose=0)
    assert logs["loss"] < 0.5
    preds = model.predict(ds, batch_size=16)
    assert preds.shape == (64, 1)
    model.save(str(tmp_path / "m"))
    model2 = Model(
        nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    )
    model2.prepare(loss=lambda o, l: ((o - l) ** 2).mean())
    model2.load(str(tmp_path / "m"))
    logs2 = model2.evaluate(ds, batch_size=16, verbose=0)
    np.testing.assert_allclose(logs2["loss"], logs["loss"], rtol=1e-4)


def test_launch_cli_single_node(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os
        print("rank", os.environ["PADDLE_TRAINER_ID"],
              "of", os.environ["PADDLE_TRAINERS_NUM"])
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "log"),
         str(script)],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    logs = sorted((tmp_path / "log").glob("workerlog.*"))
    assert len(logs) == 2
    content = "".join(p.read_text() for p in logs)
    assert "rank 0 of 2" in content and "rank 1 of 2" in content


def test_launch_cli_elastic_restart(tmp_path):
    # worker fails on first run (marker file absent), succeeds on restart
    script = tmp_path / "flaky.py"
    marker = tmp_path / "marker"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        m = {str(repr(str(marker)))}
        if not os.path.exists(m):
            open(m, "w").close()
            sys.exit(1)
        print("recovered")
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--elastic", "--max_restarts", "2",
         "--poll_interval", "0.2", "--log_dir", str(tmp_path / "log"),
         str(script)],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "elastic restart" in r.stdout
    log = (tmp_path / "log" / "workerlog.0").read_text()
    assert "recovered" in log


def test_early_stopping():
    es = EarlyStopping(monitor="loss", patience=1)

    class FakeModel:
        stop_training = False

    es.set_model(FakeModel())
    es.on_eval_end({"loss": 1.0})
    es.on_eval_end({"loss": 0.9})
    es.on_eval_end({"loss": 0.95})
    assert not es.model.stop_training
    es.on_eval_end({"loss": 0.96})
    assert es.model.stop_training


def test_xplane_device_op_summary(tmp_path):
    """Per-op device-time table from a (synthesized, TPU-shaped) chrome
    trace: aggregation, percentages, category rollup."""
    import gzip
    import json

    from paddle_tpu.profiler import xplane

    run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    run.mkdir(parents=True)
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 10, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 2, "tid": 20, "name": "thread_name",
         "args": {"name": "python"}},
        # device ops (dur in us)
        {"ph": "X", "pid": 1, "tid": 10, "name": "fusion.dot.1",
         "ts": 0, "dur": 3000.0},
        {"ph": "X", "pid": 1, "tid": 10, "name": "fusion.dot.1",
         "ts": 4000, "dur": 1000.0},
        {"ph": "X", "pid": 1, "tid": 10, "name": "all-reduce.2",
         "ts": 8000, "dur": 2000.0},
        {"ph": "X", "pid": 1, "tid": 10, "name": "copy.3",
         "ts": 11000, "dur": 500.0},
        # host noise that must NOT be counted
        {"ph": "X", "pid": 2, "tid": 20, "name": "PjitFunction",
         "ts": 0, "dur": 99999.0},
    ]
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    s = xplane.device_op_summary(str(tmp_path))
    assert s is not None and s.plane == "/device:TPU:0"
    rows = {r.name: r for r in s.rows}
    assert rows["fusion.dot.1"].total_ms == 4.0
    assert rows["fusion.dot.1"].count == 2
    assert rows["fusion.dot.1"].category == "matmul/conv"
    assert rows["all-reduce.2"].category == "collective"
    assert rows["copy.3"].category == "copy/layout"
    assert s.total_ms == 6.5
    cats = s.by_category()
    assert cats["matmul/conv"] == 4.0 and cats["collective"] == 2.0
    text = xplane.format_summary(s)
    assert "fusion.dot.1" in text and "category rollup" in text
    # rows sorted by total time
    assert s.rows[0].name == "fusion.dot.1"


def test_xplane_hlo_category_attribution(tmp_path):
    """The trace's ``hlo_category`` arg wins over name heuristics
    (fused GEMMs named "bitcast_add_fusion" ARE matmuls; Pallas kernels
    are custom-calls), and while/cond container events — which duplicate
    the body ops they wrap — are excluded from the totals."""
    import gzip
    import json

    from paddle_tpu.profiler import xplane

    run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_01"
    run.mkdir(parents=True)
    ev = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 10, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        # fused GEMM with a copy-looking name: hlo_category must win
        {"ph": "X", "pid": 1, "tid": 10, "name": "bitcast_add_fusion.2",
         "ts": 0, "dur": 1000.0,
         "args": {"hlo_category": "convolution fusion"}},
        # pallas flash attention
        {"ph": "X", "pid": 1, "tid": 10, "name": "jvp__.7",
         "ts": 2000, "dur": 2000.0,
         "args": {"hlo_category": "custom-call"}},
        # scan wrapper duplicating its body — excluded
        {"ph": "X", "pid": 1, "tid": 10, "name": "while.9",
         "ts": 0, "dur": 3000.0, "args": {"hlo_category": "while"}},
        # an XLA category with no bucket surfaces as-is
        {"ph": "X", "pid": 1, "tid": 10, "name": "rsqrt.4",
         "ts": 5000, "dur": 500.0,
         "args": {"hlo_category": "non-fusion elementwise"}},
    ]
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": ev}, f)

    s = xplane.device_op_summary(str(tmp_path))
    rows = {r.name: r for r in s.rows}
    assert "while.9" not in rows
    assert rows["bitcast_add_fusion.2"].category == "matmul/conv"
    assert rows["jvp__.7"].category == "custom-call (pallas)"
    assert rows["rsqrt.4"].category == "non-fusion elementwise"
    assert s.total_ms == 3.5


def test_profiler_summary_with_real_trace(tmp_path):
    """End-to-end on the CPU backend: trace capture + summary must not
    crash and must state that the CPU trace has no device op events."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.profiler import Profiler

    prof = Profiler(log_dir=str(tmp_path / "prof"))
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((64, 64))
    with prof:
        for _ in range(2):
            f(x).block_until_ready()
            prof.step()
    text = prof.summary()
    assert "step time summary" in text
    assert ("no device op events" in text) or ("device op summary" in text)


def test_xplane_long_tail_categories():
    """The round-4 capture left 16.2% of device time as one opaque
    'other' bucket; fusion-name heuristics must attribute the tail."""
    from paddle_tpu.profiler.xplane import categorize

    assert categorize("loop_add_fusion.3") == "elementwise"
    assert categorize("wrapped_convert") == "elementwise"
    assert categorize("fused_reduce.1") == "reduce"
    assert categorize("scatter.42") == "scatter/gather/slice"
    assert categorize("dynamic-update-slice.7") == "scatter/gather/slice"
    assert categorize("rng_bit_generator") == "rng"
    # hlo_category still wins over name heuristics
    assert categorize("loop_add_fusion", "convolution fusion") \
        == "matmul/conv"
    # truly unknown stays honest
    assert categorize("fusion.99") == "other"


def test_xplane_long_name_attribution():
    """Anonymous fusion.N events carry the HLO text in long_name; the
    round-5 headline's 12.9% 'other' decoded into AdamW master updates
    and the embedding-grad scatter this way."""
    from paddle_tpu.profiler.xplane import categorize

    adamw = ("%fusion.23 = (f32[32000,3072]{1,0}, f32[32000,3072]{1,0}) "
             "fusion(f32[32000,3072]{1,0} "
             "%opt_state__master____model_embed_tokens_weight__.1, "
             "f32[] %sub.427), kind=kLoop, calls=%fused_computation.9")
    assert categorize("fusion.23", "loop fusion", adamw) \
        == "optimizer update"
    scatter = ("%fusion.2 = bf16[32000,3072]{1,0} fusion(s32[8192]{0} "
               "%gte, bf16[8192,3072]{1,0} %b), kind=kCustom, "
               "calls=%scatter_computation")
    assert categorize("fusion.2", "custom fusion", scatter) \
        == "scatter/gather/slice"
    # an elementwise fusion CONSUMING an all-gather output (TP trace)
    # must not be booked as scatter/gather
    tp = ("%fusion.7 = bf16[4,2048,3072]{2,1,0} fusion(bf16[...] "
          "%all-gather.5, bf16[...] %model_embed_tokens_weight), "
          "kind=kLoop, calls=%fused_computation.3")
    assert categorize("fusion.7", "loop fusion", tp) == "other"
    # ...nor a fusion merely fed by a standalone %gather.12 output
    fed = ("%fusion.8 = bf16[4,2048]{1,0} fusion(bf16[8,2048]{1,0} "
           "%gather.12, bf16[4,2048]{1,0} %y), kind=kLoop, "
           "calls=%fused_computation.4")
    assert categorize("fusion.8", "loop fusion", fed) == "other"
    # a NAMED op never defers to long_name (its own tokens win)
    assert categorize("loop_add_fusion.3", "", adamw) == "elementwise"
    # anonymous fusion with uninformative long_name stays honest
    assert categorize("fusion.99", "loop fusion",
                      "%fusion.99 = f32[8,8] fusion(f32[8,8] %x)") \
        == "other"
