"""Flags, profiler scheduler, metrics, hapi Model, launch CLI (parity
model: the aux-subsystem tests in SURVEY.md §4/§5)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags, io, metric, nn, optimizer as opt
from paddle_tpu.hapi import EarlyStopping, Model
from paddle_tpu.profiler import ProfilerState, make_scheduler


def test_flags_roundtrip():
    assert flags.flag("io_prefetch_depth") == 2
    flags.set_flags({"FLAGS_io_prefetch_depth": 4})
    assert flags.get_flags("FLAGS_io_prefetch_depth") == {
        "FLAGS_io_prefetch_depth": 4
    }
    with pytest.raises(KeyError):
        flags.set_flags({"FLAGS_nope": 1})
    flags.set_flags({"FLAGS_io_prefetch_depth": 2})


def test_profiler_scheduler():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sched(i) for i in range(6)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN
    assert states[4] == ProfilerState.CLOSED  # repeat exhausted


def test_profiler_timer_only():
    from paddle_tpu.profiler import Profiler

    p = Profiler(timer_only=True)
    p.start()
    for _ in range(3):
        p.step()
    p.stop()
    assert "steps: 3" in p.summary()


def test_metrics():
    acc = metric.Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
    label = np.array([1, 2])
    acc.update(pred, label)
    top1, top2 = acc.accumulate()
    assert top1 == 0.5
    assert top2 == 0.5
    p = metric.Precision()
    p.update(np.array([0.9, 0.9, 0.1]), np.array([1, 0, 1]))
    assert p.accumulate() == 0.5
    r = metric.Recall()
    r.update(np.array([0.9, 0.9, 0.1]), np.array([1, 0, 1]))
    assert r.accumulate() == 0.5
    auc = metric.Auc()
    auc.update(np.array([0.9, 0.8, 0.2, 0.1]), np.array([1, 1, 0, 0]))
    assert auc.accumulate() > 0.9


def test_hapi_model_fit_evaluate_predict(tmp_path):
    pt.seed(0)
    x = np.random.default_rng(0).standard_normal((64, 4)).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = (x @ w).astype(np.float32)
    ds = io.TensorDataset(x, y)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    model = Model(net)
    model.prepare(
        optimizer=opt.AdamW(learning_rate=1e-2, multi_precision=False),
        loss=lambda out, label: ((out - label) ** 2).mean(),
    )
    model.fit(ds, batch_size=16, epochs=25, verbose=0)
    logs = model.evaluate(ds, batch_size=16, verbose=0)
    assert logs["loss"] < 0.5
    preds = model.predict(ds, batch_size=16)
    assert preds.shape == (64, 1)
    model.save(str(tmp_path / "m"))
    model2 = Model(
        nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    )
    model2.prepare(loss=lambda o, l: ((o - l) ** 2).mean())
    model2.load(str(tmp_path / "m"))
    logs2 = model2.evaluate(ds, batch_size=16, verbose=0)
    np.testing.assert_allclose(logs2["loss"], logs["loss"], rtol=1e-4)


def test_launch_cli_single_node(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os
        print("rank", os.environ["PADDLE_TRAINER_ID"],
              "of", os.environ["PADDLE_TRAINERS_NUM"])
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(tmp_path / "log"),
         str(script)],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    logs = sorted((tmp_path / "log").glob("workerlog.*"))
    assert len(logs) == 2
    content = "".join(p.read_text() for p in logs)
    assert "rank 0 of 2" in content and "rank 1 of 2" in content


def test_launch_cli_elastic_restart(tmp_path):
    # worker fails on first run (marker file absent), succeeds on restart
    script = tmp_path / "flaky.py"
    marker = tmp_path / "marker"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        m = {str(repr(str(marker)))}
        if not os.path.exists(m):
            open(m, "w").close()
            sys.exit(1)
        print("recovered")
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--elastic", "--max_restarts", "2",
         "--poll_interval", "0.2", "--log_dir", str(tmp_path / "log"),
         str(script)],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "elastic restart" in r.stdout
    log = (tmp_path / "log" / "workerlog.0").read_text()
    assert "recovered" in log


def test_early_stopping():
    es = EarlyStopping(monitor="loss", patience=1)

    class FakeModel:
        stop_training = False

    es.set_model(FakeModel())
    es.on_eval_end({"loss": 1.0})
    es.on_eval_end({"loss": 0.9})
    es.on_eval_end({"loss": 0.95})
    assert not es.model.stop_training
    es.on_eval_end({"loss": 0.96})
    assert es.model.stop_training
