"""paddle_tpu.incubate tests: fused functional parity vs the unfused
composition, LookAhead/ModelAverage/EMA wrapper math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import incubate, optimizer as opt
from paddle_tpu.incubate.nn import functional as IF
import paddle_tpu.nn.functional as F


class TestFusedFunctional:
    def setup_method(self, _):
        rng = np.random.default_rng(0)
        self.x = jnp.asarray(rng.normal(size=(2, 6, 32)).astype(np.float32))
        self.rng = rng

    def test_fused_norms(self):
        w = jnp.ones((32,)) * 1.5
        b = jnp.ones((32,)) * 0.1
        np.testing.assert_allclose(
            np.asarray(IF.fused_rms_norm(self.x, w)),
            np.asarray(F.rms_norm(self.x, w)), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(IF.fused_layer_norm(self.x, w, b)),
            np.asarray(F.layer_norm(self.x, weight=w, bias=b)), rtol=1e-6)

    def test_fused_bias_act_linear_dropout_add(self):
        w = jnp.asarray(self.rng.normal(size=(32, 16)).astype(np.float32))
        b = jnp.zeros((16,))
        np.testing.assert_allclose(
            np.asarray(IF.fused_linear(self.x, w, b)),
            np.asarray(self.x @ w), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(IF.fused_bias_act(self.x, None, "relu")),
            np.maximum(np.asarray(self.x), 0))
        y = jnp.ones_like(self.x)
        np.testing.assert_allclose(
            np.asarray(IF.fused_dropout_add(self.x, y, p=0.0)),
            np.asarray(self.x + y))

    def test_fused_rope_matches_kernel(self):
        from paddle_tpu.kernels.rope import apply_rope, rope_frequencies

        q = jnp.asarray(self.rng.normal(size=(2, 6, 4, 16))
                        .astype(np.float32))
        k = jnp.asarray(self.rng.normal(size=(2, 6, 4, 16))
                        .astype(np.float32))
        cos, sin = rope_frequencies(16, 6)
        q_ref, k_ref = apply_rope(q, k, cos, sin)
        q_f, k_f, v_f = IF.fused_rotary_position_embedding(q, k)
        np.testing.assert_allclose(np.asarray(q_f), np.asarray(q_ref),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(k_f), np.asarray(k_ref),
                                   rtol=1e-6)
        assert v_f is None
        # paddle-shaped duplicated-half tables give the same result
        cos_p = jnp.concatenate([cos, cos], -1).reshape(1, 6, 1, 16)
        sin_p = jnp.concatenate([sin, sin], -1).reshape(1, 6, 1, 16)
        q_f2, _, _ = IF.fused_rotary_position_embedding(
            q, sin=sin_p, cos=cos_p)
        np.testing.assert_allclose(np.asarray(q_f2), np.asarray(q_ref),
                                   rtol=1e-6)

    def test_fused_mha_matches_sdpa(self):
        h, nh = 32, 4
        qkv_w = jnp.asarray(self.rng.normal(size=(h, 3 * h))
                            .astype(np.float32)) * 0.1
        out_w = jnp.asarray(self.rng.normal(size=(h, h))
                            .astype(np.float32)) * 0.1
        got = IF.fused_multi_head_attention(
            self.x, qkv_w, linear_weight=out_w, num_heads=nh, causal=True,
            training=False)
        b, s, _ = self.x.shape
        qkv = (self.x @ qkv_w).reshape(b, s, 3, nh, h // nh)
        ref = F.scaled_dot_product_attention(
            qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], is_causal=True,
            training=False).reshape(b, s, h) @ out_w
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestFusedLinearCrossEntropy:
    """Chunked head+CE must be EXACT vs cross_entropy(linear(x)) —
    softmax is row-wise so sequence chunking changes no math — including
    gradients (the chunk body is remat'd; dW accumulates across the
    scan), ignore_index masking, non-multiple seq lengths, and the
    [V, H] tied-embedding weight layout."""

    def _setup(self, S=37):
        rng = np.random.default_rng(0)
        B, H, V = 3, 16, 29
        x = jnp.asarray(rng.standard_normal((B, S, H)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((H, V)) * 0.3, jnp.float32)
        y = jnp.asarray(rng.integers(0, V, (B, S)))
        y = y.at[0, 3].set(-100).at[2, 10].set(-100)
        return x, w, y

    def test_matches_reference_with_grads(self):
        x, w, y = self._setup()

        def ref(x, w):
            return F.cross_entropy(x @ w, y, ignore_index=-100)

        def fused(x, w):
            return IF.fused_linear_cross_entropy(x, w, y, seq_chunk=8)

        l1, (gx1, gw1) = jax.value_and_grad(ref, argnums=(0, 1))(x, w)
        l2, (gx2, gw2) = jax.value_and_grad(fused, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                                   rtol=1e-5, atol=1e-7)

    def test_transpose_weight_and_bias(self):
        x, w, y = self._setup(S=16)
        bias = jnp.asarray(
            np.random.default_rng(1).standard_normal(w.shape[1]) * 0.1,
            jnp.float32)
        ref = F.cross_entropy(x @ w + bias, y, ignore_index=-100)
        out = IF.fused_linear_cross_entropy(
            x, w.T, y, bias=bias, transpose_weight=True, seq_chunk=8)
        np.testing.assert_allclose(float(ref), float(out), rtol=1e-6)

    def test_gpt_config_flag(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        losses = {}
        for chunk in (0, 4):
            pt.seed(0)
            m = GPTForCausalLM(GPTConfig.tiny(
                use_flash_attention=False, fused_head_loss_chunk=chunk,
                hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
            ids = jnp.asarray(
                np.random.default_rng(2).integers(0, 256, (2, 10)))
            losses[chunk] = float(m(ids, labels=ids))
        np.testing.assert_allclose(losses[0], losses[4], rtol=1e-6)

    def test_llama_config_flag(self):
        """fused_head_loss_chunk routes the CausalLM loss through the
        chunked head; loss must match the default full-logits path."""
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        rng = np.random.default_rng(2)
        losses = {}
        for chunk in (0, 4):
            pt.seed(0)
            cfg = LlamaConfig.tiny(use_flash_attention=False,
                                   fused_head_loss_chunk=chunk)
            model = LlamaForCausalLM(cfg)
            ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 10)))
            losses[chunk] = float(model(ids, labels=ids))
            rng = np.random.default_rng(2)  # same ids both configs
        np.testing.assert_allclose(losses[0], losses[4], rtol=1e-6)


class TestWrapperOptimizers:
    def _params(self):
        return {"w": jnp.ones((4,), jnp.float32)}

    def test_lookahead_sync_math(self):
        inner = opt.SGD(learning_rate=0.1, multi_precision=False)
        la = incubate.LookAhead(inner, alpha=0.5, k=2)
        params = self._params()
        state = la.init(params)
        g = {"w": jnp.ones((4,), jnp.float32)}
        # step1: fast = 1 - .1 = .9, no sync
        params, state = la.update(g, state, params)
        np.testing.assert_allclose(np.asarray(params["w"]), 0.9, rtol=1e-6)
        # step2: fast = .8; sync: slow = 1 + .5*(.8-1) = .9; fast = slow
        params, state = la.update(g, state, params)
        np.testing.assert_allclose(np.asarray(params["w"]), 0.9, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(state["slow"]["w"]), 0.9,
                                   rtol=1e-6)

    def test_model_average(self):
        inner = opt.SGD(learning_rate=0.1, multi_precision=False)
        ma = incubate.ModelAverage(inner_optimizer=inner,
                                   max_average_window=100)
        params = self._params()
        state = ma.init(params)
        g = {"w": jnp.ones((4,), jnp.float32)}
        seen = [np.asarray(params["w"]).copy()]
        for _ in range(3):
            params, state = ma.update(g, state, params)
            seen.append(np.asarray(params["w"]).copy())
        # avg over {w0, w1, w2, w3} (cumulative incl. init)
        expect = np.mean(seen, axis=0)
        np.testing.assert_allclose(
            np.asarray(ma.apply(state, params)["w"]), expect, rtol=1e-6)

    def test_ema(self):
        ema = incubate.EMA(decay=0.9, zero_debias=True)
        params = self._params()
        state = ema.init(params)
        for _ in range(5):
            state = ema.update(state, params)
        # constant params → debiased ema == params EXACTLY (the debias
        # factor tracks the product of the varying decays)
        out = ema.apply(state, params)
        np.testing.assert_allclose(np.asarray(out["w"]), 1.0, rtol=1e-6)

    def test_lookahead_in_train_loop(self):
        """integration: LookAhead(AdamW) shrinks a quadratic under jit."""
        la = incubate.LookAhead(
            opt.AdamW(learning_rate=0.05, multi_precision=False), k=3)
        params = {"w": jnp.full((8,), 3.0)}
        state = la.init(params)

        @jax.jit
        def step(params, state):
            g = {"w": 2.0 * params["w"]}
            return la.update(g, state, params)

        for _ in range(250):
            params, state = step(params, state)
        assert float(jnp.sum(params["w"] ** 2)) < 0.5
