"""interpolate/grid_sample vs torch; weight_norm/spectral_norm
reparameterization (eager + functional/jit); summary/flops.
Upstream models: test/legacy_test/test_bilinear_interp_v2_op.py,
test_grid_sampler_op.py, test_weight_norm_hook.py,
test_spectral_norm_op.py, hapi model_summary tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import paddle_tpu as pt
from paddle_tpu import nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.functional import extract_params, functional_call


@pytest.fixture
def x4d():
    return np.random.default_rng(0).normal(size=(2, 3, 7, 9)).astype(
        np.float32)


class TestInterpolate:
    @pytest.mark.parametrize("mode,ac", [
        ("nearest", False), ("bilinear", False), ("bilinear", True),
        ("bicubic", False), ("bicubic", True), ("area", False),
    ])
    def test_vs_torch(self, x4d, mode, ac):
        ours = np.asarray(F.interpolate(
            jnp.asarray(x4d), size=(13, 5), mode=mode, align_corners=ac))
        if mode in ("nearest", "area"):
            ref = torch.nn.functional.interpolate(
                torch.tensor(x4d), size=(13, 5), mode=mode)
        else:
            ref = torch.nn.functional.interpolate(
                torch.tensor(x4d), size=(13, 5), mode=mode,
                align_corners=ac)
        np.testing.assert_allclose(ours, ref.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_scale_factor_and_layers(self, x4d):
        up = nn.UpsamplingBilinear2D(scale_factor=2)
        out = np.asarray(up(jnp.asarray(x4d)))
        ref = torch.nn.functional.interpolate(
            torch.tensor(x4d), scale_factor=2, mode="bilinear",
            align_corners=True).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
        nearest = nn.UpsamplingNearest2D(scale_factor=2)
        refn = torch.nn.functional.interpolate(
            torch.tensor(x4d), scale_factor=2, mode="nearest").numpy()
        np.testing.assert_allclose(
            np.asarray(nearest(jnp.asarray(x4d))), refn)

    def test_adaptive_pool_nondivisible(self, x4d):
        out = np.asarray(F.adaptive_avg_pool2d(jnp.asarray(x4d), (3, 4)))
        ref = torch.nn.functional.adaptive_avg_pool2d(
            torch.tensor(x4d), (3, 4)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


class TestGridSample:
    @pytest.mark.parametrize("pad", ["zeros", "border", "reflection"])
    @pytest.mark.parametrize("ac", [True, False])
    def test_vs_torch(self, x4d, pad, ac):
        rng = np.random.default_rng(1)
        grid = (rng.random((2, 5, 6, 2)).astype(np.float32) * 2.4 - 1.2)
        for mode in ("bilinear", "nearest"):
            ours = np.asarray(F.grid_sample(
                jnp.asarray(x4d), jnp.asarray(grid), mode=mode,
                padding_mode=pad, align_corners=ac))
            ref = torch.nn.functional.grid_sample(
                torch.tensor(x4d), torch.tensor(grid), mode=mode,
                padding_mode=pad, align_corners=ac).numpy()
            np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_grad_flows(self, x4d):
        grid = jnp.asarray(np.random.default_rng(2).random(
            (2, 4, 4, 2)).astype(np.float32) - 0.5)
        g = jax.grad(lambda gr: jnp.sum(
            F.grid_sample(jnp.asarray(x4d), gr) ** 2))(grid)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


class TestWeightNorm:
    def test_decomposition_and_forward(self):
        pt.seed(0)
        lin = nn.Linear(6, 4)
        w0 = np.asarray(lin.weight.value).copy()
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(3, 6)).astype(np.float32))
        y0 = np.asarray(lin(x))
        nn.utils.weight_norm(lin, dim=0)
        names = [n for n, _ in lin.named_parameters()]
        assert "weight_g" in names and "weight_v" in names
        assert "weight" not in names
        # reparameterized forward reproduces the original
        np.testing.assert_allclose(np.asarray(lin(x)), y0, rtol=1e-5,
                                   atol=1e-6)
        # g shape: norm kept along dim 0 → [in_features, 1] for the
        # [in, out] weight layout
        assert lin.weight_g.shape == (6, 1)

    def test_grad_flows_to_g_and_v(self):
        pt.seed(0)
        lin = nn.Linear(5, 3)
        nn.utils.weight_norm(lin)
        x = jnp.ones((2, 5))
        params = extract_params(lin)
        grads = jax.grad(lambda p: jnp.sum(
            functional_call(lin, p, x) ** 2))(params)
        gk = [k for k in grads if k.endswith("weight_g")][0]
        vk = [k for k in grads if k.endswith("weight_v")][0]
        assert np.abs(np.asarray(grads[gk])).sum() > 0
        assert np.abs(np.asarray(grads[vk])).sum() > 0

    def test_remove_restores(self):
        pt.seed(0)
        lin = nn.Linear(4, 4)
        x = jnp.ones((1, 4))
        y0 = np.asarray(lin(x))
        nn.utils.weight_norm(lin)
        nn.utils.remove_weight_norm(lin)
        names = [n for n, _ in lin.named_parameters()]
        assert "weight" in names and "weight_g" not in names
        np.testing.assert_allclose(np.asarray(lin(x)), y0, rtol=1e-5,
                                   atol=1e-6)


class TestSpectralNorm:
    def test_sigma_normalized(self):
        pt.seed(0)
        lin = nn.Linear(16, 16)
        # inflate the weight so sigma >> 1
        lin.weight.value = lin.weight.value * 10.0
        nn.utils.spectral_norm(lin, n_power_iterations=20)
        x = jnp.ones((1, 16))
        lin(x)  # trigger recompute with converged u
        w = np.asarray(lin.weight)
        sigma = np.linalg.svd(w, compute_uv=False)[0]
        assert sigma == pytest.approx(1.0, rel=1e-2)

    def test_functional_grad(self):
        pt.seed(0)
        lin = nn.Linear(8, 8)
        nn.utils.spectral_norm(lin)
        x = jnp.ones((2, 8))
        params = extract_params(lin)
        grads = jax.grad(lambda p: jnp.sum(
            functional_call(lin, p, x) ** 2))(params)
        k = [k for k in grads if k.endswith("weight_orig")][0]
        assert np.isfinite(np.asarray(grads[k])).all()


class TestSummaryFlops:
    def test_summary_counts(self, capsys):
        pt.seed(0)
        net = nn.Sequential(
            nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        info = pt.summary(net, (2, 8))
        out = capsys.readouterr().out
        assert "Linear" in out and "Total params" in out
        assert info["total_params"] == 8 * 16 + 16 + 16 * 4 + 4
        assert info["trainable_params"] == info["total_params"]

    def test_summary_big_model_is_free(self):
        """abstract trace: no multi-GB allocation for a big model —
        just assert it runs fast on shapes alone."""
        pt.seed(0)
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig.tiny(num_hidden_layers=2)
        model = LlamaForCausalLM(cfg)
        info = pt.summary(model, (1, 16), dtypes=[jnp.int32])
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        assert info["total_params"] == n_params

    def test_flops_linear_conv(self):
        pt.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 4))
        got = pt.flops(net, (2, 8))
        # paddle.flops counts weight MACs only (2*tokens*in*out); bias
        # adds are excluded (round-3 advisor fix)
        expect = 2 * 2 * (8 * 16) + 2 * 2 * (16 * 4)
        assert got == expect
        conv = nn.Conv2D(3, 8, 3, padding=1)
        got_c = pt.flops(conv, (1, 3, 16, 16))
        expect_c = 2 * 16 * 16 * (8 * 3 * 9)
        assert got_c == expect_c
        # layout-aware spatial count: NHWC must match NCHW
        conv_nhwc = nn.Conv2D(3, 8, 3, padding=1, data_format="NHWC")
        assert pt.flops(conv_nhwc, (1, 16, 16, 3)) == expect_c


class TestDtypePreservation:
    def test_interp_and_pool_keep_bf16(self):
        x = jnp.ones((1, 2, 7, 9), jnp.bfloat16)
        for mode in ("nearest", "bilinear", "bicubic", "area"):
            out = F.interpolate(x, size=(13, 5), mode=mode)
            assert out.dtype == jnp.bfloat16, mode
        assert F.adaptive_avg_pool2d(x, (3, 4)).dtype == jnp.bfloat16

    def test_grid_sample_rejects_bad_args(self):
        x = jnp.ones((1, 1, 4, 4))
        g = jnp.zeros((1, 2, 2, 2))
        with pytest.raises(ValueError):
            F.grid_sample(x, g, mode="biliner")
        with pytest.raises(ValueError):
            F.grid_sample(x, g, padding_mode="reflect")


class TestInterpolate3D5D:
    def test_linear_1d_vs_torch(self):
        x = np.random.default_rng(3).normal(size=(2, 3, 11)).astype(
            np.float32)
        for ac in (False, True):
            ours = np.asarray(F.interpolate(
                jnp.asarray(x), size=7, mode="linear", align_corners=ac))
            ref = torch.nn.functional.interpolate(
                torch.tensor(x), size=7, mode="linear",
                align_corners=ac).numpy()
            np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
        ours_n = np.asarray(F.interpolate(jnp.asarray(x), size=7,
                                          mode="nearest"))
        ref_n = torch.nn.functional.interpolate(
            torch.tensor(x), size=7, mode="nearest").numpy()
        np.testing.assert_allclose(ours_n, ref_n)

    def test_trilinear_vs_torch(self):
        x = np.random.default_rng(4).normal(size=(1, 2, 5, 6, 7)).astype(
            np.float32)
        for ac in (False, True):
            ours = np.asarray(F.interpolate(
                jnp.asarray(x), size=(8, 4, 9), mode="trilinear",
                align_corners=ac))
            ref = torch.nn.functional.interpolate(
                torch.tensor(x), size=(8, 4, 9), mode="trilinear",
                align_corners=ac).numpy()
            np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
        ours_n = np.asarray(F.interpolate(jnp.asarray(x), size=(8, 4, 9),
                                          mode="nearest"))
        ref_n = torch.nn.functional.interpolate(
            torch.tensor(x), size=(8, 4, 9), mode="nearest").numpy()
        np.testing.assert_allclose(ours_n, ref_n)


class TestReviewRound3Fixes:
    def test_summary_list_input_size(self):
        net = nn.Sequential(nn.Linear(8, 4))
        info = pt.summary(net, [2, 8])     # paddle's canonical LIST form
        assert info["total_params"] == 8 * 4 + 4
        # weight MACs only — bias excluded from the multiply count
        assert pt.flops(net, [2, 8]) == 2 * 2 * (8 * 4)

    def test_renorm_negative_axis(self):
        x = np.random.default_rng(0).normal(size=(4, 5)).astype(
            np.float32) * 3
        ours = np.asarray(pt.renorm(jnp.asarray(x), 2.0, -1, 1.0))
        ref = torch.renorm(torch.tensor(x), 2, -1, 1.0).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_weight_norm_negative_dim(self):
        lin = nn.Linear(6, 4)
        nn.utils.weight_norm(lin, dim=-1)
        assert lin.weight_g.shape == (1, 4)   # per-column norms kept

    def test_grid_sample_keeps_bf16(self):
        x = jnp.ones((1, 2, 4, 4), jnp.bfloat16)
        g = jnp.zeros((1, 2, 2, 2))
        assert F.grid_sample(x, g).dtype == jnp.bfloat16

    def test_align_mode_1(self):
        """paddle align_mode=1 (asymmetric): src = i*in/out."""
        x = jnp.asarray(np.arange(4, dtype=np.float32)[None, None])
        out = np.asarray(F.interpolate(x, size=8, mode="linear",
                                       align_mode=1))
        # src = i*0.5, clamped at the last sample → halves of the ramp
        # with the final position clipped to x[-1] (paddle boundary rule)
        expect = np.minimum(np.arange(8) * 0.5, 3.0)
        np.testing.assert_allclose(out[0, 0], expect, atol=1e-6)
