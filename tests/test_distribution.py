"""Distribution parity tests (reference: test/distribution/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import distribution as D


def setup_function(_):
    pt.seed(0)


def _moments(dist, n=20000):
    s = np.asarray(dist.sample((n,)))
    return s.mean(axis=0), s.var(axis=0)


def test_exponential():
    d = D.Exponential(2.0)
    m, v = _moments(d)
    np.testing.assert_allclose(m, 0.5, rtol=0.05)
    np.testing.assert_allclose(v, 0.25, rtol=0.15)
    # log_prob: f(x) = rate * exp(-rate x)
    np.testing.assert_allclose(float(d.log_prob(1.0)),
                               np.log(2.0) - 2.0, rtol=1e-6)
    assert float(d.log_prob(-1.0)) == -np.inf
    np.testing.assert_allclose(float(d.entropy()), 1 - np.log(2.0),
                               rtol=1e-6)


def test_laplace_and_gumbel():
    lap = D.Laplace(1.0, 2.0)
    m, v = _moments(lap)
    np.testing.assert_allclose(m, 1.0, atol=0.1)
    np.testing.assert_allclose(v, 2 * 4.0, rtol=0.2)
    np.testing.assert_allclose(float(lap.log_prob(1.0)),
                               -np.log(4.0), rtol=1e-6)
    g = D.Gumbel(0.0, 1.0)
    m, v = _moments(g)
    np.testing.assert_allclose(m, np.euler_gamma, atol=0.05)
    np.testing.assert_allclose(v, np.pi**2 / 6, rtol=0.1)


def test_gamma_beta():
    g = D.Gamma(3.0, 2.0)
    m, v = _moments(g)
    np.testing.assert_allclose(m, 1.5, rtol=0.05)
    np.testing.assert_allclose(v, 3 / 4, rtol=0.15)
    # log_prob at x=1: a log b + (a-1) log x - b x - lgamma(a)
    import math

    ref = 3 * np.log(2.0) - 2.0 - math.lgamma(3.0)
    np.testing.assert_allclose(float(g.log_prob(1.0)), ref, rtol=1e-5)

    b = D.Beta(2.0, 3.0)
    m, v = _moments(b)
    np.testing.assert_allclose(m, 0.4, rtol=0.05)
    ref = (np.log(0.5) * 1 + np.log(0.5) * 2
           - (math.lgamma(2) + math.lgamma(3) - math.lgamma(5)))
    np.testing.assert_allclose(float(b.log_prob(0.5)), ref, rtol=1e-5)


def test_dirichlet():
    d = D.Dirichlet(jnp.asarray([1.0, 2.0, 3.0]))
    s = np.asarray(d.sample((5000,)))
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)
    np.testing.assert_allclose(s.mean(0), [1 / 6, 2 / 6, 3 / 6],
                               atol=0.02)
    lp = float(d.log_prob(jnp.asarray([0.2, 0.3, 0.5])))
    import math

    ref = (0 * np.log(0.2) + 1 * np.log(0.3) + 2 * np.log(0.5)
           + math.lgamma(6) - (math.lgamma(1) + math.lgamma(2)
                               + math.lgamma(3)))
    np.testing.assert_allclose(lp, ref, rtol=1e-5)


def test_lognormal_multinomial_poisson():
    ln = D.LogNormal(0.0, 0.5)
    m, _ = _moments(ln)
    np.testing.assert_allclose(m, np.exp(0.125), rtol=0.05)

    mn = D.Multinomial(10, jnp.asarray([0.2, 0.3, 0.5]))
    s = np.asarray(mn.sample((2000,)))
    assert (s.sum(-1) == 10).all()
    np.testing.assert_allclose(s.mean(0), [2, 3, 5], rtol=0.1)
    # log_prob of an observed count vector


def test_poisson():
    p = D.Poisson(4.0)
    s = np.asarray(p.sample((20000,)))
    np.testing.assert_allclose(s.mean(), 4.0, rtol=0.05)
    np.testing.assert_allclose(s.var(), 4.0, rtol=0.1)
    import math

    ref = 2 * np.log(4.0) - 4.0 - math.lgamma(3.0)
    np.testing.assert_allclose(float(p.log_prob(2.0)), ref, rtol=1e-5)


def test_kl_pairs():
    # closed forms verified against hand computation
    kl = D.kl_divergence(D.Exponential(2.0), D.Exponential(1.0))
    r = 2.0
    np.testing.assert_allclose(float(kl), np.log(r) + 1 / r - 1, rtol=1e-6)

    kl = D.kl_divergence(D.Bernoulli(0.3), D.Bernoulli(0.5))
    ref = 0.3 * np.log(0.3 / 0.5) + 0.7 * np.log(0.7 / 0.5)
    np.testing.assert_allclose(float(kl), ref, rtol=1e-5)

    # KL(p||p) == 0 for every registered pair
    pairs = [
        (D.Normal(0.0, 1.0), D.Normal(0.0, 1.0)),
        (D.Gamma(2.0, 3.0), D.Gamma(2.0, 3.0)),
        (D.Beta(2.0, 3.0), D.Beta(2.0, 3.0)),
        (D.Dirichlet(jnp.asarray([1.0, 2.0])),
         D.Dirichlet(jnp.asarray([1.0, 2.0]))),
        (D.Uniform(0.0, 1.0), D.Uniform(0.0, 1.0)),
        (D.Exponential(1.5), D.Exponential(1.5)),
        (D.Bernoulli(0.4), D.Bernoulli(0.4)),
    ]
    for p, q in pairs:
        np.testing.assert_allclose(float(D.kl_divergence(p, q)), 0.0,
                                   atol=1e-5)

    # KL via monte carlo for Gamma pair
    p, q = D.Gamma(2.0, 1.0), D.Gamma(3.0, 2.0)
    s = p.sample((50000,))
    mc = float(jnp.mean(p.log_prob(s) - q.log_prob(s)))
    np.testing.assert_allclose(float(D.kl_divergence(p, q)), mc,
                               rtol=0.05)


def test_entropy_matches_mc():
    for d in [D.Gamma(2.0, 1.5), D.Beta(2.0, 3.0), D.Laplace(0.0, 1.0),
              D.Gumbel(1.0, 2.0)]:
        s = d.sample((50000,))
        mc = float(-jnp.mean(d.log_prob(s)))
        np.testing.assert_allclose(float(jnp.sum(d.entropy())), mc,
                                   rtol=0.05)


def test_transformed_distribution_lognormal_equivalence():
    """Normal pushed through Exp must equal LogNormal exactly."""
    from paddle_tpu.distribution import (
        ExpTransform,
        LogNormal,
        Normal,
        TransformedDistribution,
    )

    td = TransformedDistribution(Normal(0.3, 0.8), ExpTransform())
    ln = LogNormal(0.3, 0.8)
    for v in (0.4, 1.0, 2.7):
        np.testing.assert_allclose(
            float(td.log_prob(jnp.asarray(v))),
            float(ln.log_prob(jnp.asarray(v))), rtol=1e-5)


def test_affine_and_chain_transforms():
    from paddle_tpu.distribution import (
        AffineTransform,
        ChainTransform,
        Normal,
        SigmoidTransform,
        TanhTransform,
        TransformedDistribution,
    )

    aff = AffineTransform(2.0, 3.0)
    x = jnp.asarray([0.5, -1.0])
    np.testing.assert_allclose(np.asarray(aff.inverse(aff.forward(x))),
                               np.asarray(x), rtol=1e-6)
    # affine of a normal == shifted/scaled normal
    td = TransformedDistribution(Normal(0.0, 1.0), aff)
    ref = Normal(2.0, 3.0)
    for v in (-1.0, 2.0, 5.5):
        np.testing.assert_allclose(
            float(td.log_prob(jnp.asarray(v))),
            float(ref.log_prob(jnp.asarray(v))), rtol=1e-5)
    # chain: tanh then affine; roundtrip + finite log-det
    chain = ChainTransform([TanhTransform(), AffineTransform(0.0, 2.0)])
    y = chain.forward(x)
    np.testing.assert_allclose(np.asarray(chain.inverse(y)),
                               np.asarray(x), rtol=1e-4)
    assert bool(jnp.all(jnp.isfinite(chain.forward_log_det_jacobian(x))))
    # sigmoid ldj identity check vs autodiff
    sg = SigmoidTransform()
    v = 0.7
    autodiff = float(jnp.log(jnp.abs(jax.grad(
        lambda t: sg.forward(t))(jnp.asarray(v)))))
    np.testing.assert_allclose(
        float(sg.forward_log_det_jacobian(jnp.asarray(v))), autodiff,
        rtol=1e-5)
