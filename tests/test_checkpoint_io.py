"""Checkpoint (incl. cross-topology reshard-on-load) and data pipeline
tests (parity model: test/distributed checkpoint tests + DataLoader unit
tests)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import distributed as dist, io
from paddle_tpu.distributed import checkpoint as ckpt


def test_save_load_replicated(tmp_path):
    sd = {
        "w": jnp.arange(24, dtype=jnp.float32).reshape(4, 6),
        "b": jnp.ones((6,), jnp.float32),
    }
    ckpt.save_state_dict(sd, str(tmp_path / "c1"))
    loaded = ckpt.load_state_dict(str(tmp_path / "c1"))
    np.testing.assert_allclose(np.asarray(loaded["w"]), np.asarray(sd["w"]))
    np.testing.assert_allclose(np.asarray(loaded["b"]), np.asarray(sd["b"]))


def test_cross_topology_reshard_on_load(tmp_path):
    """Save sharded over (fsdp=4, tp=2); load onto a (fsdp=2, tp=4) mesh —
    slices must be reassembled exactly."""
    mesh_a = dist.build_mesh(fsdp=4, tp=2)
    w = jnp.arange(32 * 16, dtype=jnp.float32).reshape(32, 16)
    w_a = jax.device_put(w, NamedSharding(mesh_a, P("fsdp", "tp")))
    ckpt.save_state_dict({"w": w_a}, str(tmp_path / "c2"))

    mesh_b = dist.build_mesh(fsdp=2, tp=4)
    target_sharding = NamedSharding(mesh_b, P("tp", "fsdp"))
    loaded = ckpt.load_state_dict(
        str(tmp_path / "c2"), shardings={"w": target_sharding}
    )
    assert loaded["w"].sharding.spec == P("tp", "fsdp")
    np.testing.assert_allclose(np.asarray(loaded["w"]), np.asarray(w))


def test_save_load_model_roundtrip(tmp_path):
    from paddle_tpu import nn

    m1 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    ckpt.save_model(m1, str(tmp_path / "m"))
    pt.seed(999)
    m2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    ckpt.load_model(m2, str(tmp_path / "m"))
    x = jnp.ones((2, 8))
    np.testing.assert_allclose(
        np.asarray(m1(x)), np.asarray(m2(x)), rtol=1e-6
    )


def test_bf16_roundtrip(tmp_path):
    sd = {"w": jnp.full((8, 8), 1.5, jnp.bfloat16)}
    ckpt.save_state_dict(sd, str(tmp_path / "bf"))
    loaded = ckpt.load_state_dict(str(tmp_path / "bf"))
    assert loaded["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(loaded["w"].astype(jnp.float32)), 1.5
    )


def test_paddle_save_load(tmp_path):
    obj = {"a": jnp.ones((3,)), "nested": {"b": jnp.zeros((2, 2))}, "x": 5}
    path = str(tmp_path / "obj.pdparams")
    pt.save(obj, path)
    loaded = pt.load(path)
    assert loaded["x"] == 5
    np.testing.assert_allclose(np.asarray(loaded["nested"]["b"]), 0.0)


# ---------------------------------------------------------------------------
# io
# ---------------------------------------------------------------------------
def test_dataloader_basic():
    ds = io.TensorDataset(np.arange(10), np.arange(10) * 2)
    dl = io.DataLoader(ds, batch_size=3)
    batches = list(dl)
    assert len(batches) == 4
    np.testing.assert_array_equal(batches[0][0], [0, 1, 2])
    np.testing.assert_array_equal(batches[0][1], [0, 2, 4])
    dl = io.DataLoader(ds, batch_size=3, drop_last=True)
    assert len(list(dl)) == 3


def test_dataloader_shuffle_deterministic():
    ds = io.TensorDataset(np.arange(20))
    dl = io.DataLoader(ds, batch_size=5, shuffle=True)
    a = np.concatenate([b[0] for b in dl])
    b = np.concatenate([b[0] for b in dl])
    np.testing.assert_array_equal(a, b)  # same epoch → same order
    assert not np.array_equal(a, np.arange(20))
    assert sorted(a.tolist()) == list(range(20))


def test_distributed_batch_sampler_partition():
    ds = io.TensorDataset(np.arange(16))
    seen = []
    for rank in range(4):
        s = io.DistributedBatchSampler(
            ds, batch_size=2, num_replicas=4, rank=rank
        )
        for batch in s:
            seen.extend(batch)
        assert len(s) == 2
    assert sorted(seen) == list(range(16))


def test_dataloader_workers():
    ds = io.TensorDataset(np.arange(32))
    dl = io.DataLoader(ds, batch_size=4, num_workers=2)
    got = np.concatenate([b[0] for b in dl])
    np.testing.assert_array_equal(got, np.arange(32))


def test_iterable_dataset():
    class Stream(io.IterableDataset):
        def __iter__(self):
            yield from range(7)

    dl = io.DataLoader(Stream(), batch_size=3)
    batches = list(dl)
    assert [len(np.atleast_1d(b)) for b in batches] == [3, 3, 1]


def test_prefetch_to_device():
    ds = io.TensorDataset(np.arange(8).astype(np.float32))
    dl = io.DataLoader(ds, batch_size=4)
    out = list(io.prefetch_to_device(iter(dl)))
    assert len(out) == 2
    assert isinstance(out[0][0], jax.Array)
