"""Checkpoint (incl. cross-topology reshard-on-load) and data pipeline
tests (parity model: test/distributed checkpoint tests + DataLoader unit
tests)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import distributed as dist, io
from paddle_tpu.distributed import checkpoint as ckpt


def test_save_load_replicated(tmp_path):
    sd = {
        "w": jnp.arange(24, dtype=jnp.float32).reshape(4, 6),
        "b": jnp.ones((6,), jnp.float32),
    }
    ckpt.save_state_dict(sd, str(tmp_path / "c1"))
    loaded = ckpt.load_state_dict(str(tmp_path / "c1"))
    np.testing.assert_allclose(np.asarray(loaded["w"]), np.asarray(sd["w"]))
    np.testing.assert_allclose(np.asarray(loaded["b"]), np.asarray(sd["b"]))


def test_cross_topology_reshard_on_load(tmp_path):
    """Save sharded over (fsdp=4, tp=2); load onto a (fsdp=2, tp=4) mesh —
    slices must be reassembled exactly."""
    mesh_a = dist.build_mesh(fsdp=4, tp=2)
    w = jnp.arange(32 * 16, dtype=jnp.float32).reshape(32, 16)
    w_a = jax.device_put(w, NamedSharding(mesh_a, P("fsdp", "tp")))
    ckpt.save_state_dict({"w": w_a}, str(tmp_path / "c2"))

    mesh_b = dist.build_mesh(fsdp=2, tp=4)
    target_sharding = NamedSharding(mesh_b, P("tp", "fsdp"))
    loaded = ckpt.load_state_dict(
        str(tmp_path / "c2"), shardings={"w": target_sharding}
    )
    assert loaded["w"].sharding.spec == P("tp", "fsdp")
    np.testing.assert_allclose(np.asarray(loaded["w"]), np.asarray(w))


def test_save_load_model_roundtrip(tmp_path):
    from paddle_tpu import nn

    m1 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    ckpt.save_model(m1, str(tmp_path / "m"))
    pt.seed(999)
    m2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    ckpt.load_model(m2, str(tmp_path / "m"))
    x = jnp.ones((2, 8))
    np.testing.assert_allclose(
        np.asarray(m1(x)), np.asarray(m2(x)), rtol=1e-6
    )


def test_bf16_roundtrip(tmp_path):
    sd = {"w": jnp.full((8, 8), 1.5, jnp.bfloat16)}
    ckpt.save_state_dict(sd, str(tmp_path / "bf"))
    loaded = ckpt.load_state_dict(str(tmp_path / "bf"))
    assert loaded["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(loaded["w"].astype(jnp.float32)), 1.5
    )


def test_paddle_save_load(tmp_path):
    obj = {"a": jnp.ones((3,)), "nested": {"b": jnp.zeros((2, 2))}, "x": 5}
    path = str(tmp_path / "obj.pdparams")
    pt.save(obj, path)
    loaded = pt.load(path)
    assert loaded["x"] == 5
    np.testing.assert_allclose(np.asarray(loaded["nested"]["b"]), 0.0)


def test_replicated_axis_dedup(tmp_path):
    """Sharded over fsdp but replicated over tp: every offset has
    replica_id 0..tp-1 shards. Exactly one chunk per offset must be
    written, and load must reproduce the data regardless of which replica
    enumerates first in addressable_shards (round-2 bug: a non-zero
    replica seen first suppressed the real writer)."""
    mesh = dist.build_mesh(fsdp=4, tp=2)
    w = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    w_s = jax.device_put(w, NamedSharding(mesh, P("fsdp", None)))
    # sanity: there ARE non-zero replicas in this layout
    assert any(s.replica_id != 0 for s in w_s.addressable_shards)
    ckpt.save_state_dict({"w": w_s}, str(tmp_path / "rep"))
    import json as _json

    with open(tmp_path / "rep" / "metadata.json") as f:
        meta = _json.load(f)
    offsets = [tuple(c["offset"]) for c in meta["w"]["chunks"]]
    assert sorted(offsets) == [(0, 0), (2, 0), (4, 0), (6, 0)]
    assert len(set(offsets)) == len(offsets)
    loaded = ckpt.load_state_dict(str(tmp_path / "rep"))
    np.testing.assert_allclose(np.asarray(loaded["w"]), np.asarray(w))


def test_crashed_save_preserves_previous(tmp_path, monkeypatch):
    """A crash mid-save must leave the previous committed checkpoint
    loadable — the torn write only ever touches <path>.tmp."""
    path = str(tmp_path / "atom")
    ckpt.save_state_dict({"w": jnp.ones((4, 4))}, path)

    def boom(snap, tmp):
        # simulate dying after some chunk files landed
        with open(os.path.join(tmp, "partial.npy"), "wb") as f:
            f.write(b"torn")
        raise RuntimeError("simulated crash mid-save")

    monkeypatch.setattr(ckpt, "_write_snapshot", boom)
    with pytest.raises(RuntimeError):
        ckpt.save_state_dict({"w": jnp.zeros((4, 4))}, path)
    # previous checkpoint intact and committed
    assert ckpt.is_committed(path)
    loaded = ckpt.load_state_dict(path)
    np.testing.assert_allclose(np.asarray(loaded["w"]), 1.0)
    # and the torn tmp dir is not mistaken for a checkpoint
    assert not ckpt.is_committed(path + ".tmp")


def test_uncommitted_dir_rejected(tmp_path):
    d = tmp_path / "torn"
    d.mkdir()
    (d / "w__0.npy").write_bytes(b"junk")
    with pytest.raises(FileNotFoundError):
        ckpt.load_state_dict(str(d))


def test_async_checkpointer(tmp_path):
    saver = ckpt.AsyncCheckpointer()
    state = {"w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4)}
    path = str(tmp_path / "async1")
    saver.save(state, path)
    saver.wait_until_finished()
    assert ckpt.is_committed(path)
    loaded = ckpt.load_state_dict(path)
    np.testing.assert_allclose(np.asarray(loaded["w"]), np.asarray(state["w"]))
    # back-to-back saves serialize correctly
    p2 = str(tmp_path / "async2")
    saver.save({"w": jnp.zeros((2,))}, p2)
    saver.save({"w": jnp.ones((2,))}, str(tmp_path / "async3"))
    saver.wait_until_finished()
    assert ckpt.is_committed(p2)
    assert ckpt.is_committed(str(tmp_path / "async3"))


def test_async_checkpointer_surfaces_errors(tmp_path, monkeypatch):
    saver = ckpt.AsyncCheckpointer()

    def boom(snap, tmp):
        raise OSError("disk full")

    monkeypatch.setattr(ckpt, "_write_snapshot", boom)
    saver.save({"w": jnp.ones((2,))}, str(tmp_path / "err"))
    with pytest.raises(OSError):
        saver.wait_until_finished()


# ---------------------------------------------------------------------------
# io
# ---------------------------------------------------------------------------
def test_dataloader_basic():
    ds = io.TensorDataset(np.arange(10), np.arange(10) * 2)
    dl = io.DataLoader(ds, batch_size=3)
    batches = list(dl)
    assert len(batches) == 4
    np.testing.assert_array_equal(batches[0][0], [0, 1, 2])
    np.testing.assert_array_equal(batches[0][1], [0, 2, 4])
    dl = io.DataLoader(ds, batch_size=3, drop_last=True)
    assert len(list(dl)) == 3


def test_dataloader_shuffle_deterministic():
    ds = io.TensorDataset(np.arange(20))
    dl = io.DataLoader(ds, batch_size=5, shuffle=True)
    a = np.concatenate([b[0] for b in dl])
    b = np.concatenate([b[0] for b in dl])
    np.testing.assert_array_equal(a, b)  # same epoch → same order
    assert not np.array_equal(a, np.arange(20))
    assert sorted(a.tolist()) == list(range(20))


def test_distributed_batch_sampler_partition():
    ds = io.TensorDataset(np.arange(16))
    seen = []
    for rank in range(4):
        s = io.DistributedBatchSampler(
            ds, batch_size=2, num_replicas=4, rank=rank
        )
        for batch in s:
            seen.extend(batch)
        assert len(s) == 2
    assert sorted(seen) == list(range(16))


def test_dataloader_workers():
    ds = io.TensorDataset(np.arange(32))
    dl = io.DataLoader(ds, batch_size=4, num_workers=2)
    got = np.concatenate([b[0] for b in dl])
    np.testing.assert_array_equal(got, np.arange(32))


def test_iterable_dataset():
    class Stream(io.IterableDataset):
        def __iter__(self):
            yield from range(7)

    dl = io.DataLoader(Stream(), batch_size=3)
    batches = list(dl)
    assert [len(np.atleast_1d(b)) for b in batches] == [3, 3, 1]


def test_prefetch_to_device():
    ds = io.TensorDataset(np.arange(8).astype(np.float32))
    dl = io.DataLoader(ds, batch_size=4)
    out = list(io.prefetch_to_device(iter(dl)))
    assert len(out) == 2
    assert isinstance(out[0][0], jax.Array)


def test_crash_between_commit_renames_recovers(tmp_path):
    """Crash window inside _commit (old moved aside, new not yet in
    place): the next load or save must restore the previous checkpoint
    from '.old' instead of failing."""
    path = str(tmp_path / "swap")
    ckpt.save_state_dict({"w": jnp.ones((2, 2))}, path)
    # simulate: commit got as far as renaming path -> path.old
    os.rename(path, path + ".old")
    assert not os.path.isdir(path)
    assert ckpt.is_committed(path)  # triggers recovery
    loaded = ckpt.load_state_dict(path)
    np.testing.assert_allclose(np.asarray(loaded["w"]), 1.0)
    assert not os.path.isdir(path + ".old")


def test_dataloader_process_workers():
    """Real OS-process workers (fork context): order preserved, data
    intact — the reference's multiprocess DataLoader semantics."""
    ds = io.TensorDataset(np.arange(24, dtype=np.float32) * 3)
    dl = io.DataLoader(ds, batch_size=4, num_workers=2,
                       use_process_workers=True)
    got = np.concatenate([b[0] for b in dl])
    np.testing.assert_array_equal(got, np.arange(24) * 3)
