"""Detection-op tests vs hand-rolled numpy references (upstream model:
test/legacy_test/test_nms_op.py, test_roi_align_op.py,
test_roi_pool_op.py, test_deformable_conv_op.py, test_box_coder_op.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.vision import ops


def _np_nms(boxes, scores, thresh):
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if suppressed[j] or j == i:
                continue
            xx1 = max(boxes[i, 0], boxes[j, 0])
            yy1 = max(boxes[i, 1], boxes[j, 1])
            xx2 = min(boxes[i, 2], boxes[j, 2])
            yy2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(0, xx2 - xx1) * max(0, yy2 - yy1)
            a_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            a_j = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            if inter / max(a_i + a_j - inter, 1e-10) > thresh:
                suppressed[j] = True
    return np.array(keep)


class TestNMS:
    def test_vs_numpy(self):
        rng = np.random.default_rng(0)
        xy = rng.uniform(0, 90, (60, 2))
        wh = rng.uniform(5, 30, (60, 2))
        boxes = np.concatenate([xy, xy + wh], -1).astype(np.float32)
        scores = rng.random(60).astype(np.float32)
        got = np.asarray(ops.nms(boxes, 0.5, scores=scores))
        ref = _np_nms(boxes, scores, 0.5)
        np.testing.assert_array_equal(got, ref)

    def test_categories(self):
        """same geometry, different categories → nothing suppressed."""
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10.]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        got = np.asarray(ops.nms(boxes, 0.5, scores=scores,
                                 category_idxs=np.array([0, 1]),
                                 categories=[0, 1]))
        assert len(got) == 2
        got2 = np.asarray(ops.nms(boxes, 0.5, scores=scores))
        assert len(got2) == 1

    def test_top_k(self):
        boxes = np.array([[i * 20, 0, i * 20 + 10, 10]
                          for i in range(5)], np.float32)
        scores = np.linspace(1, 0.5, 5).astype(np.float32)
        got = np.asarray(ops.nms(boxes, 0.5, scores=scores, top_k=3))
        assert len(got) == 3


class TestRoIAlign:
    def test_unit_scale_identity_bins(self):
        """a 2x2 ROI aligned to pixel centers reproduces the pixels."""
        feat = jnp.asarray(
            np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        # box covering pixel centers (0.5..2.5) → 2x2 output == centers
        boxes = jnp.asarray([[0.0, 0.0, 2.0, 2.0]], jnp.float32)
        out = ops.roi_align(feat, boxes, [1], output_size=2,
                            sampling_ratio=1, aligned=False)
        # with aligned=False, sampling point of bin (i,j) is at
        # (i+0.5, j+0.5) in feature coords → bilinear of the 4 corners
        assert out.shape == (1, 1, 2, 2)
        ref = np.array([[2.5, 3.5], [6.5, 7.5]], np.float32)
        np.testing.assert_allclose(np.asarray(out)[0, 0], ref, atol=1e-5)

    def test_grad_flows(self):
        rng = np.random.default_rng(1)
        feat = jnp.asarray(rng.normal(size=(2, 3, 8, 8)).astype(np.float32))
        boxes = jnp.asarray([[1, 1, 6, 6], [0, 0, 4, 7], [2, 2, 7, 7.]],
                            jnp.float32)
        g = jax.grad(lambda f: jnp.sum(
            ops.roi_align(f, boxes, [2, 1], 4) ** 2))(feat)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0

    def test_adaptive_ratio_matches_per_roi_reference(self):
        """sampling_ratio=-1 must follow the reference's PER-ROI
        ceil(roi/output) rule exactly — a mixed-size batch where every
        ROI gets a different adaptive ratio is checked bin-for-bin
        against a dense numpy re-implementation."""
        rng = np.random.default_rng(7)
        feat = rng.normal(size=(1, 2, 16, 16)).astype(np.float32)
        # roi sizes 2.4 / 7 / 12.8 on a 2x2 output -> ratios 2, 4, 7
        boxes = np.array([[1.0, 1.0, 3.4, 3.4],
                          [4.0, 2.0, 11.0, 9.0],
                          [0.6, 2.1, 13.4, 14.9]], np.float32)
        ph = pw = 2
        out = np.asarray(ops.roi_align(
            jnp.asarray(feat), jnp.asarray(boxes), [3], 2,
            sampling_ratio=-1, aligned=True))

        def bilin(img, y, x):
            H, W = img.shape[-2:]
            y = min(max(y, 0.0), H - 1.0)
            x = min(max(x, 0.0), W - 1.0)
            y0, x0 = int(np.floor(y)), int(np.floor(x))
            y1, x1 = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
            wy, wx = y - y0, x - x0
            return (img[:, y0, x0] * (1 - wy) * (1 - wx)
                    + img[:, y0, x1] * (1 - wy) * wx
                    + img[:, y1, x0] * wy * (1 - wx)
                    + img[:, y1, x1] * wy * wx)

        for k, box in enumerate(boxes):
            x1, y1, x2, y2 = box - 0.5
            rw, rh = max(x2 - x1, 1e-4), max(y2 - y1, 1e-4)
            bh, bw = rh / ph, rw / pw
            ry = int(np.ceil(rh / ph))
            rx = int(np.ceil(rw / pw))
            for i in range(ph):
                for jj in range(pw):
                    acc = np.zeros(2, np.float32)
                    for sy in range(ry):
                        for sx in range(rx):
                            yy = y1 + i * bh + (sy + 0.5) * bh / ry
                            xx = x1 + jj * bw + (sx + 0.5) * bw / rx
                            acc += bilin(feat[0], yy, xx)
                    ref = acc / (ry * rx)
                    np.testing.assert_allclose(
                        out[k, :, i, jj], ref, rtol=1e-5, atol=1e-5,
                        err_msg=f"roi {k} bin ({i},{jj}) ratio ({ry},{rx})")

    def test_batch_routing(self):
        """ROIs index the right image via boxes_num."""
        f = np.zeros((2, 1, 4, 4), np.float32)
        f[1] = 7.0
        boxes = jnp.asarray([[0, 0, 3, 3], [0, 0, 3, 3.]], jnp.float32)
        out = ops.roi_align(jnp.asarray(f), boxes, [1, 1], 2)
        np.testing.assert_allclose(np.asarray(out)[0], 0.0)
        np.testing.assert_allclose(np.asarray(out)[1], 7.0)


class TestRoIPool:
    def test_max_in_bins(self):
        feat = jnp.asarray(
            np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        boxes = jnp.asarray([[0, 0, 3, 3.]], jnp.float32)
        out = ops.roi_pool(feat, boxes, [1], output_size=2)
        # 4x4 → 2x2 bins of 2x2 → maxes are 5, 7, 13, 15
        np.testing.assert_allclose(
            np.asarray(out)[0, 0], [[5, 7], [13, 15]])

    def test_grad_flows(self):
        feat = jnp.asarray(np.random.default_rng(2).normal(
            size=(1, 2, 6, 6)).astype(np.float32))
        boxes = jnp.asarray([[0, 0, 5, 5.]], jnp.float32)
        g = jax.grad(lambda f: jnp.sum(
            ops.roi_pool(f, boxes, [1], 3)))(feat)
        # max-pool grad: one 1 per bin per channel
        assert float(jnp.sum(g)) == pytest.approx(2 * 9)


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.default_rng(3)
        priors = np.abs(rng.normal(size=(10, 4))).astype(np.float32)
        priors[:, 2:] = priors[:, :2] + 1 + np.abs(
            rng.normal(size=(10, 2))).astype(np.float32)
        targets = priors + rng.normal(size=(10, 4)).astype(np.float32) * 0.1
        var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
        enc = ops.box_coder(priors, var, targets,
                            code_type="encode_center_size")
        dec = ops.box_coder(priors, var, enc,
                            code_type="decode_center_size")
        np.testing.assert_allclose(np.asarray(dec), targets, rtol=1e-4,
                                   atol=1e-4)


class TestPriorBox:
    def test_shapes_and_range(self):
        feat = jnp.zeros((1, 8, 4, 4))
        img = jnp.zeros((1, 3, 64, 64))
        boxes, var = ops.prior_box(feat, img, min_sizes=[16.0],
                                   max_sizes=[32.0],
                                   aspect_ratios=[2.0], flip=True,
                                   clip=True)
        # A = 1 (ar=1) + 2 (ar=2 flip) + 1 (max_size) = 4
        assert boxes.shape == (4, 4, 4, 4)
        assert var.shape == boxes.shape
        b = np.asarray(boxes)
        assert (b >= 0).all() and (b <= 1).all()
        assert (b[..., 2] > b[..., 0]).all()


class TestDeformConv:
    def test_zero_offset_equals_conv(self):
        """deform_conv2d with zero offsets == plain conv2d."""
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(size=(2, 4, 8, 8)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=(6, 4, 3, 3)).astype(np.float32))
        offset = jnp.zeros((2, 2 * 9, 6, 6), jnp.float32)
        got = ops.deform_conv2d(x, offset, w, padding=0)
        ref = F.conv2d(x, w, padding=0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_integer_offset_shifts_sampling(self):
        """1x1 kernel with integer offset (dy=1,dx=0) samples one row
        down."""
        x = jnp.asarray(np.arange(16, dtype=np.float32)
                        .reshape(1, 1, 4, 4))
        w = jnp.ones((1, 1, 1, 1), jnp.float32)
        offset = jnp.zeros((1, 2, 4, 4), jnp.float32)
        offset = offset.at[:, 0].set(1.0)  # dy=1
        got = np.asarray(ops.deform_conv2d(x, offset, w))[0, 0]
        ref = np.asarray(x)[0, 0]
        # rows shift up by one (sampling one row down); the last row's
        # taps fall OUTSIDE the map and read 0 (reference zero-padding
        # semantics, not edge clamping)
        np.testing.assert_allclose(got[:3], ref[1:])
        np.testing.assert_allclose(got[3], 0.0)

    def test_modulated_mask_and_grad(self):
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(1, 2, 6, 6)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=(3, 2, 3, 3)).astype(np.float32))
        offset = jnp.asarray(rng.normal(size=(1, 18, 4, 4))
                             .astype(np.float32)) * 0.5
        mask = jax.nn.sigmoid(jnp.asarray(
            rng.normal(size=(1, 9, 4, 4)).astype(np.float32)))
        out = ops.deform_conv2d(x, offset, w, mask=mask)
        assert out.shape == (1, 3, 4, 4)
        g = jax.grad(lambda o: jnp.sum(
            ops.deform_conv2d(x, o, w, mask=mask) ** 2))(offset)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0

    def test_groups_and_deformable_groups(self):
        rng = np.random.default_rng(6)
        x = jnp.asarray(rng.normal(size=(1, 4, 6, 6)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=(4, 2, 3, 3)).astype(np.float32))
        offset = jnp.zeros((1, 2 * 2 * 9, 4, 4), jnp.float32)
        out = ops.deform_conv2d(x, offset, w, groups=2,
                                deformable_groups=2)
        import paddle_tpu.nn.functional as F

        ref = F.conv2d(x, w, groups=2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


class TestMatrixNMS:
    def test_score_threshold_prefilters_originals(self):
        """score_threshold prunes ORIGINAL scores; decayed survivors are
        kept unless below post_threshold."""
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
        scores = np.array([0.9, 0.5], np.float32)
        # heavy overlap decays box 1 to ~0.09; with post_threshold=0 it
        # must STILL be kept (paddle keeps decayed boxes)
        ns, keep = ops.matrix_nms(boxes, scores, score_threshold=0.3,
                                  post_threshold=0.0)
        assert 1 in list(np.asarray(keep))
        # but a box under score_threshold never participates
        scores2 = np.array([0.9, 0.1], np.float32)
        ns2, keep2 = ops.matrix_nms(boxes, scores2, score_threshold=0.3)
        assert list(np.asarray(keep2)) == [0]
        assert float(ns2[1]) == 0.0

    def test_decay_behavior(self):
        """overlapping lower-scored boxes get decayed, disjoint ones
        keep their score."""
        boxes = np.array([
            [0, 0, 10, 10],      # top box
            [1, 1, 11, 11],      # heavy overlap with top
            [50, 50, 60, 60],    # disjoint
        ], np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        new_scores, keep = ops.matrix_nms(boxes, scores,
                                          score_threshold=0.0)
        ns = np.asarray(new_scores)
        assert ns[0] == pytest.approx(0.9)      # top box untouched
        assert ns[1] < 0.4                      # heavily decayed
        assert ns[2] == pytest.approx(0.7)      # disjoint untouched
        assert list(np.asarray(keep)[:2]) == [0, 2]

    def test_gaussian_kernel_and_threshold(self):
        boxes = np.array([[0, 0, 10, 10], [2, 2, 12, 12]], np.float32)
        scores = np.array([0.9, 0.85], np.float32)
        ns_lin, _ = ops.matrix_nms(boxes, scores, score_threshold=0.0)
        # sigma MULTIPLIES the exponent (reference convention): a large
        # sigma suppresses harder than the linear kernel
        ns_g, _ = ops.matrix_nms(boxes, scores, score_threshold=0.0,
                                 use_gaussian=True, gaussian_sigma=8.0)
        assert np.asarray(ns_g)[1] < np.asarray(ns_lin)[1]
        _, keep = ops.matrix_nms(boxes, scores, post_threshold=0.88)
        assert list(np.asarray(keep)) == [0]


class TestPSRoIPool:
    def test_position_sensitive_selection(self):
        """each output bin reads its OWN channel group."""
        ph = pw = 2
        C = 1
        x = np.zeros((1, C * ph * pw, 4, 4), np.float32)
        # channel k holds constant value k+1 everywhere
        for k in range(4):
            x[0, k] = k + 1
        boxes = jnp.asarray([[0, 0, 4, 4.]], jnp.float32)
        out = ops.psroi_pool(jnp.asarray(x), boxes, [1], 2)
        # bin (i, j) reads channel i*pw+j → value i*pw+j+1
        np.testing.assert_allclose(
            np.asarray(out)[0, 0], [[1, 2], [3, 4]])

    def test_grad(self):
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(1, 8, 6, 6)).astype(np.float32))
        boxes = jnp.asarray([[0, 0, 5, 5.]], jnp.float32)
        g = jax.grad(lambda f: jnp.sum(
            ops.psroi_pool(f, boxes, [1], 2) ** 2))(x)
        assert np.isfinite(np.asarray(g)).all()


class TestGeometricTransforms:
    def test_rotate_90_exact(self):
        from paddle_tpu.vision import transforms as T

        img = jnp.asarray(np.arange(16, dtype=np.float32)
                          .reshape(1, 4, 4))
        out = np.asarray(T.rotate(img, 90.0, interpolation="nearest"))
        # positive angle = counter-clockwise in display coords (y down)
        # == np.rot90(k=1) on the array
        assert out.shape == (1, 4, 4)
        np.testing.assert_allclose(
            out[0], np.rot90(np.asarray(img)[0], 1), atol=1e-4)

    def test_identity_affine(self):
        from paddle_tpu.vision import transforms as T

        img = jnp.asarray(np.random.default_rng(0).normal(
            size=(3, 8, 8)).astype(np.float32))
        out = np.asarray(T.affine(img))
        np.testing.assert_allclose(out, np.asarray(img), atol=1e-4)

    def test_translate_shifts(self):
        from paddle_tpu.vision import transforms as T

        img = jnp.zeros((1, 6, 6)).at[0, 2, 2].set(1.0)
        out = np.asarray(T.affine(img, translate=(1, 0),
                                  interpolation="nearest"))
        assert out[0, 2, 3] == 1.0 and out[0, 2, 2] == 0.0

    def test_perspective_identity_and_roundtrip(self):
        from paddle_tpu.vision import transforms as T

        img = jnp.asarray(np.random.default_rng(1).normal(
            size=(1, 8, 8)).astype(np.float32))
        pts = [[0, 0], [7, 0], [7, 7], [0, 7]]
        out = np.asarray(T.perspective(img, pts, pts))
        np.testing.assert_allclose(out, np.asarray(img), atol=1e-4)

    def test_random_transforms_run(self):
        from paddle_tpu.vision import transforms as T

        img = jnp.ones((3, 8, 8))
        r1 = T.RandomRotation(30.0, seed=0)(img)
        r2 = T.RandomAffine(15.0, translate=(0.1, 0.1), scale=(0.9, 1.1),
                            seed=0)(img)
        assert r1.shape == r2.shape == (3, 8, 8)
        assert np.isfinite(np.asarray(r1)).all()

    def test_random_affine_tuple_shear(self):
        from paddle_tpu.vision import transforms as T

        img = jnp.ones((1, 8, 8))
        out = T.RandomAffine(10.0, shear=(-5.0, 5.0), seed=0)(img)
        assert out.shape == (1, 8, 8)


class TestLayoutPolicy:
    """NHWC<->NCHW round-trip parity for the conv-workload fast path
    (nn/layout.py): conv/pool outputs and grads bit-compared across
    layouts, GroupNorm within fp32 tolerance (its fused kernel reduces
    in a different order), and the scope/resolve mechanics."""

    def _x(self, shape=(2, 8, 9, 10), seed=0):
        rng = np.random.default_rng(seed)
        return jnp.asarray(rng.standard_normal(shape), jnp.float32)

    def test_scope_recursion_ops_no_infinite_resolve(self):
        """Ops whose NHWC branch transposes and recurses into their own
        NCHW implementation must suspend scope resolution — declared
        NCHW inside channels_last_scope used to re-resolve to NHWC on
        every recursive call (RecursionError)."""
        from paddle_tpu.nn import functional as F
        from paddle_tpu.nn import layout

        x_nchw = jnp.moveaxis(self._x((2, 6, 8, 4)), -1, 1)  # [2,4,6,8]
        x_nhwc = jnp.transpose(x_nchw, (0, 2, 3, 1))
        want_interp = F.interpolate(x_nchw, scale_factor=2,
                                    mode="bilinear")
        want_amp = F.adaptive_max_pool2d(x_nchw, 2)
        with layout.channels_last_scope():
            got_interp = F.interpolate(x_nhwc, scale_factor=2,
                                       mode="bilinear")
            got_amp = F.adaptive_max_pool2d(x_nhwc, 2)
        np.testing.assert_allclose(
            np.asarray(want_interp),
            np.asarray(jnp.transpose(got_interp, (0, 3, 1, 2))),
            rtol=1e-6)
        np.testing.assert_array_equal(
            np.asarray(want_amp),
            np.asarray(jnp.transpose(got_amp, (0, 3, 1, 2))))

    def test_conv2d_layout_roundtrip_bitexact(self):
        from paddle_tpu.nn import functional as F

        x = self._x()
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.standard_normal((6, 10, 3, 3)), jnp.float32)
        b = jnp.asarray(rng.standard_normal(6), jnp.float32)
        x = jnp.moveaxis(x, -1, 1)  # NCHW [2, 10, 8, 9]
        xl = jnp.transpose(x, (0, 2, 3, 1))
        y0 = F.conv2d(x, w, b, stride=2, padding=1)
        y1 = F.conv2d(xl, w, b, stride=2, padding=1, data_format="NHWC")
        np.testing.assert_array_equal(
            np.asarray(y0), np.asarray(jnp.transpose(y1, (0, 3, 1, 2))))

    def test_conv2d_layout_grads_bitexact(self):
        from paddle_tpu.nn import functional as F

        x = jnp.moveaxis(self._x(seed=2), -1, 1)
        w = jnp.asarray(
            np.random.default_rng(3).standard_normal((4, 10, 3, 3)),
            jnp.float32)

        def f_nchw(x, w):
            return jnp.sum(F.conv2d(x, w, None, padding=1) ** 2)

        def f_nhwc(x, w):
            xl = jnp.transpose(x, (0, 2, 3, 1))
            return jnp.sum(F.conv2d(xl, w, None, padding=1,
                                    data_format="NHWC") ** 2)

        g0 = jax.grad(f_nchw, argnums=(0, 1))(x, w)
        g1 = jax.grad(f_nhwc, argnums=(0, 1))(x, w)
        np.testing.assert_array_equal(np.asarray(g0[0]), np.asarray(g1[0]))
        np.testing.assert_array_equal(np.asarray(g0[1]), np.asarray(g1[1]))

    def test_pool_layout_roundtrip_bitexact(self):
        from paddle_tpu.nn import functional as F

        x = jnp.moveaxis(self._x(seed=4), -1, 1)
        xl = jnp.transpose(x, (0, 2, 3, 1))
        for fn in (F.max_pool2d, F.avg_pool2d):
            y0 = fn(x, 2, 2)
            y1 = fn(xl, 2, 2, data_format="NHWC")
            np.testing.assert_array_equal(
                np.asarray(y0),
                np.asarray(jnp.transpose(y1, (0, 3, 1, 2))))
        y0 = F.adaptive_avg_pool2d(x, 2)
        y1 = F.adaptive_avg_pool2d(xl, 2, data_format="NHWC")
        np.testing.assert_array_equal(
            np.asarray(y0), np.asarray(jnp.transpose(y1, (0, 3, 1, 2))))

    def test_group_norm_layout_roundtrip(self):
        from paddle_tpu.nn import functional as F

        x = jnp.moveaxis(self._x((2, 6, 5, 32), seed=5), -1, 1)
        rng = np.random.default_rng(6)
        gamma = jnp.asarray(rng.standard_normal(32), jnp.float32)
        beta = jnp.asarray(rng.standard_normal(32), jnp.float32)
        xl = jnp.transpose(x, (0, 2, 3, 1))

        y0 = F.group_norm(x, 8, gamma, beta)
        y1 = F.group_norm(xl, 8, gamma, beta, data_format="NHWC")
        np.testing.assert_allclose(
            np.asarray(y0), np.asarray(jnp.transpose(y1, (0, 3, 1, 2))),
            rtol=1e-5, atol=1e-5)

        def f_nchw(x, ga, be):
            return jnp.sum(F.group_norm(x, 8, ga, be) ** 2)

        def f_nhwc(x, ga, be):
            xl = jnp.transpose(x, (0, 2, 3, 1))
            return jnp.sum(F.group_norm(xl, 8, ga, be,
                                        data_format="NHWC") ** 2)

        g0 = jax.grad(f_nchw, argnums=(0, 1, 2))(x, gamma, beta)
        g1 = jax.grad(f_nhwc, argnums=(0, 1, 2))(x, gamma, beta)
        for a, b in zip(g0, g1):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-5)

    def test_scope_resolves_declared_nchw(self):
        from paddle_tpu.nn import layout

        assert layout.resolve("NCHW") == "NCHW"
        with layout.channels_last_scope(True):
            assert layout.active()
            assert layout.resolve("NCHW") == "NHWC"
            assert layout.resolve("NHWC") == "NHWC"  # idempotent
            assert layout.resolve("NCL") == "NCL"    # 1-D untouched
        assert not layout.active()
        with layout.channels_last_scope(False):
            assert not layout.active()

    def test_conv_layout_flag_policy(self):
        import paddle_tpu as pt
        from paddle_tpu.nn import layout

        orig = pt.flags.flag("conv_layout")
        try:
            pt.flags.set_flags({"FLAGS_conv_layout": "NHWC"})
            assert layout.decide(None) is True
            pt.flags.set_flags({"FLAGS_conv_layout": "NCHW"})
            assert layout.decide(None) is False
            assert layout.decide(True) is True   # explicit overrides
            pt.flags.set_flags({"FLAGS_conv_layout": "auto"})
            # auto on the CPU test backend = channels-first
            assert layout.decide(None) is False
        finally:
            pt.flags.set_flags({"FLAGS_conv_layout": orig})

    def test_layer_under_scope_runs_channels_last(self):
        """A Conv2D declared NCHW, fed NHWC inside the scope, matches
        the plain NCHW run bit-for-bit."""
        import paddle_tpu as pt
        from paddle_tpu.nn import layout
        from paddle_tpu.nn.layer.conv import Conv2D

        pt.seed(7)
        conv = Conv2D(10, 4, 3, padding=1)
        x = jnp.moveaxis(self._x(seed=8), -1, 1)
        y0 = conv(x)
        with layout.channels_last_scope(True):
            y1 = conv(jnp.transpose(x, (0, 2, 3, 1)))
        np.testing.assert_array_equal(
            np.asarray(y0), np.asarray(jnp.transpose(y1, (0, 3, 1, 2))))

    def test_unet_channels_last_parity(self):
        import dataclasses

        import paddle_tpu as pt
        from paddle_tpu.core.functional import (
            extract_params,
            functional_call,
        )
        from paddle_tpu.models import UNet2DConditionModel, UNetConfig

        pt.seed(0)
        cfg = UNetConfig.tiny()
        net = UNet2DConditionModel(cfg)
        rng = np.random.default_rng(0)
        sample = jnp.asarray(rng.standard_normal((2, 4, 16, 16)),
                             jnp.float32)
        t = jnp.asarray([1, 500])
        ctx = jnp.asarray(rng.standard_normal((2, 8, 32)), jnp.float32)

        net.config = dataclasses.replace(cfg, channels_last=False)
        out_nchw = net(sample, t, ctx)
        net.config = dataclasses.replace(cfg, channels_last=True)
        out_nhwc = net(sample, t, ctx)
        np.testing.assert_allclose(np.asarray(out_nhwc),
                                   np.asarray(out_nchw),
                                   rtol=1e-4, atol=1e-5)

        params = extract_params(net)

        def loss(p, cl):
            net.config = dataclasses.replace(cfg, channels_last=cl)
            pred = functional_call(net, p, sample, t, ctx)
            return jnp.mean((pred - sample) ** 2)

        g0 = jax.grad(lambda p: loss(p, False))(params)
        g1 = jax.grad(lambda p: loss(p, True))(params)
        for k in g0:
            np.testing.assert_allclose(np.asarray(g1[k]),
                                       np.asarray(g0[k]),
                                       rtol=1e-3, atol=1e-5, err_msg=k)

    def test_resnet_channels_last_parity(self):
        import paddle_tpu as pt
        from paddle_tpu.core.functional import (
            extract_params,
            functional_call,
        )
        from paddle_tpu.nn.layer.norm import GroupNorm
        from paddle_tpu.vision.models.resnet import resnet18

        pt.seed(0)
        net = resnet18(num_classes=10, norm_layer=lambda c: GroupNorm(4, c))
        x = jnp.asarray(
            np.random.default_rng(0).standard_normal((2, 3, 32, 32)),
            jnp.float32)
        labels = jnp.asarray([1, 2])

        net.channels_last = False
        y0 = net(x)
        net.channels_last = True
        y1 = net(x)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                                   rtol=1e-4, atol=1e-5)

        params = extract_params(net)

        def loss(p, cl):
            net.channels_last = cl
            return functional_call(net, p, x, labels).mean()

        g0 = jax.grad(lambda p: loss(p, False))(params)
        g1 = jax.grad(lambda p: loss(p, True))(params)
        for k in g0:
            np.testing.assert_allclose(np.asarray(g1[k]),
                                       np.asarray(g0[k]),
                                       rtol=1e-3, atol=1e-5, err_msg=k)

    def test_vit_channels_last_parity(self):
        import dataclasses

        import paddle_tpu as pt
        from paddle_tpu.models import ViT, ViTConfig

        pt.seed(1)
        cfg = ViTConfig.tiny()
        vit = ViT(cfg)
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((2, 3, 32, 32)),
            jnp.float32)
        vit.config = dataclasses.replace(cfg, channels_last=False)
        y0 = vit(x)
        vit.config = dataclasses.replace(cfg, channels_last=True)
        y1 = vit(x)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                                   rtol=1e-5, atol=1e-6)

    def test_interpolate_nearest_nhwc_native(self):
        from paddle_tpu.nn import functional as F

        x = jnp.moveaxis(self._x(seed=9), -1, 1)
        xl = jnp.transpose(x, (0, 2, 3, 1))
        y0 = F.interpolate(x, scale_factor=2, mode="nearest")
        y1 = F.interpolate(xl, scale_factor=2, mode="nearest",
                           data_format="NHWC")
        np.testing.assert_array_equal(
            np.asarray(y0), np.asarray(jnp.transpose(y1, (0, 3, 1, 2))))
