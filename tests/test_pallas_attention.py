"""Pallas flash-attention kernel numerics vs the XLA reference (interpret
mode on CPU; the same code compiles via Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.flash_attention import _reference_attention
from paddle_tpu.kernels.pallas_attention import mha


@pytest.mark.parametrize("causal", [False, True])
def test_mha_forward_matches_reference(causal):
    rng = np.random.default_rng(0)
    b, s, h, d = 1, 256, 2, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    out = mha(q, k, v, causal=causal, q_block=128, k_block=128)
    ref = _reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_mha_grad_matches_reference():
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 256, 1, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

    def loss_pallas(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, q_block=128, k_block=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-3, atol=5e-3
        )


def test_mha_gqa():
    rng = np.random.default_rng(2)
    b, s, d = 1, 128, 128
    q = jnp.asarray(rng.standard_normal((b, s, 4, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)
    out = mha(q, k, v, causal=True, q_block=128, k_block=128)
    ref = _reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_mha_gqa_grad():
    """GQA backward: dk/dv group-reduction happens inside the kernel."""
    rng = np.random.default_rng(3)
    b, s, d = 1, 256, 128
    q = jnp.asarray(rng.standard_normal((b, s, 4, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)

    def loss_pallas(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, q_block=128, k_block=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-3, atol=5e-3
        )


def _segment_reference(q, k, v, seg_q, seg_kv, causal):
    """Dense reference for packed/varlen attention."""
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d ** -0.5)
    logits = logits.astype(jnp.float32)
    mask = seg_q[:, None, :, None] == seg_kv[:, None, None, :]
    if causal:
        sk = k.shape[1]
        mask = jnp.logical_and(mask, jnp.tril(jnp.ones((sq, sk), bool)))
    logits = jnp.where(mask, logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@pytest.mark.parametrize("causal", [False, True])
def test_mha_varlen_segments(causal):
    """Packed sequences: attention stays within segment boundaries."""
    rng = np.random.default_rng(4)
    b, s, h, d = 1, 512, 2, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    # three packed sequences of lengths 200, 200, 112
    seg = jnp.asarray(
        np.concatenate([np.zeros(200), np.ones(200), 2 * np.ones(112)]),
        jnp.int32,
    )[None, :]
    out = mha(q, k, v, causal=causal, q_block=128, k_block=128,
              segment_ids=seg)
    ref = _segment_reference(q, k, v, seg, seg, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_mha_varlen_grad():
    rng = np.random.default_rng(5)
    b, s, h, d = 1, 256, 1, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    seg = jnp.asarray(
        np.concatenate([np.zeros(100), np.ones(156)]), jnp.int32
    )[None, :]

    def loss_pallas(q, k, v):
        return jnp.sum(
            mha(q, k, v, causal=True, q_block=128, k_block=128,
                segment_ids=seg) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_segment_reference(q, k, v, seg, seg, True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-3, atol=5e-3
        )


def test_mha_nonsquare_blocks():
    """q_block != k_block exercises the causal pruning index arithmetic."""
    rng = np.random.default_rng(6)
    b, s, h, d = 1, 512, 1, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    out = mha(q, k, v, causal=True, q_block=256, k_block=128)
    ref = _reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )

    def loss(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, q_block=128, k_block=256) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-3, atol=5e-3
        )


@pytest.mark.parametrize("d", [64, 96])
@pytest.mark.parametrize("causal", [False, True])
def test_mha_unaligned_head_dim(d, causal):
    """head_dim 64/96 (GPT/ViT): kernel zero-pads to lane width — must
    match the dense reference exactly, not fall back to it."""
    rng = np.random.default_rng(7)
    b, s, h = 1, 256, 2
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    out = mha(q, k, v, causal=causal, q_block=128, k_block=128)
    assert out.shape == (b, s, h, d)
    ref = _reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_mha_unaligned_head_dim_grad():
    rng = np.random.default_rng(8)
    b, s, h, d = 1, 256, 4, 64
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)  # +GQA
    v = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)

    def loss_pallas(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, q_block=128, k_block=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        assert a.shape == b_.shape
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-3, atol=5e-3
        )


@pytest.mark.parametrize("window", [64, 128, 200])
def test_mha_sliding_window(window):
    """Mistral-style local attention (parity: flash_attn window_size):
    kernel output must match the dense windowed reference."""
    rng = np.random.default_rng(20)
    b, s, h, d = 1, 512, 2, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    out = mha(q, k, v, causal=True, q_block=128, k_block=128, window=window)
    ref = _reference_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_mha_sliding_window_grad():
    rng = np.random.default_rng(21)
    b, s, h, d = 1, 256, 2, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    w = 96

    def loss_pallas(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, q_block=128, k_block=128,
                           window=w) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True,
                                            window=w) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-3, atol=5e-3
        )


def test_mha_window_requires_causal():
    q = jnp.ones((1, 128, 1, 128))
    with pytest.raises(ValueError):
        mha(q, q, q, causal=False, window=64)


def test_flash_attention_window_fallback_paths():
    """window_size must be honored (or loudly rejected) on every wrapper
    path — dense fallback, segment fallback, dropout path."""
    from paddle_tpu.kernels.flash_attention import flash_attention

    rng = np.random.default_rng(30)
    q = jnp.asarray(rng.standard_normal((1, 64, 2, 16)), jnp.float32)
    # dense fallback (unaligned seq → no pallas)
    out = flash_attention(q, q, q, causal=True, window_size=16)
    ref = _reference_attention(q, q, q, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # segment fallback honors the window too
    seg = jnp.zeros((1, 64), jnp.int32)
    out = flash_attention(q, q, q, causal=True, segment_ids=seg,
                          window_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # non-causal window is rejected on every path
    with pytest.raises(ValueError):
        flash_attention(q, q, q, causal=False, window_size=16)
    with pytest.raises(ValueError):
        flash_attention(q, q, q, causal=False, window_size=16,
                        dropout_p=0.5)


def test_mha_grad_two_pass_path_matches_fused():
    """n_kb > _FUSED_BWD_MAX_KB falls back to the two-pass backward;
    both paths must produce identical gradients."""
    from paddle_tpu.kernels import pallas_attention as pa

    rng = np.random.default_rng(11)
    # seq 768 / k_block 128 -> n_kb = 6 > pa._FUSED_BWD_MAX_KB
    # (two-pass); k_block 256 -> n_kb = 3 (fused). Same math either way.
    assert 768 // 128 > pa._FUSED_BWD_MAX_KB >= 768 // 256
    q = jnp.asarray(rng.standard_normal((1, 2, 768, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 2, 768, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 2, 768, 64)), jnp.float32)

    def loss(blk):
        def f(q, k, v):
            return jnp.sum(
                mha(q, k, v, causal=True, q_block=128, k_block=blk) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_two = loss(128)   # n_kb=6: two-pass
    g_fused = loss(256)  # n_kb=3: fused
    for a, b in zip(g_two, g_fused):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
