"""Pallas flash-attention kernel numerics vs the XLA reference (interpret
mode on CPU; the same code compiles via Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.flash_attention import _reference_attention
from paddle_tpu.kernels.pallas_attention import mha


@pytest.mark.parametrize("causal", [False, True])
def test_mha_forward_matches_reference(causal):
    rng = np.random.default_rng(0)
    b, s, h, d = 1, 256, 2, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    out = mha(q, k, v, causal=causal, q_block=128, k_block=128)
    ref = _reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )


def test_mha_grad_matches_reference():
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 256, 1, 128
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

    def loss_pallas(q, k, v):
        return jnp.sum(mha(q, k, v, causal=True, q_block=128, k_block=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal=True) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-3, atol=5e-3
        )


def test_mha_gqa():
    rng = np.random.default_rng(2)
    b, s, d = 1, 128, 128
    q = jnp.asarray(rng.standard_normal((b, s, 4, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, 2, d)), jnp.float32)
    out = mha(q, k, v, causal=True, q_block=128, k_block=128)
    ref = _reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3
    )
