"""Long-tail tensor/nn surface (parity: python/paddle/tensor/ module
APIs + nn layers) — numerics pinned to torch / numpy references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import paddle_tpu as pt
from paddle_tpu import nn
import paddle_tpu.nn.functional as F

# core-engine fast lane (see README "Tests")
pytestmark = pytest.mark.fast


class TestMathOps:
    def setup_method(self, _):
        self.rng = np.random.default_rng(0)

    def test_mv_bmm_dist_cdist(self):
        a = self.rng.standard_normal((3, 4)).astype(np.float32)
        v = self.rng.standard_normal((4,)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(pt.mv(jnp.asarray(a), jnp.asarray(v))),
                                   a @ v, rtol=1e-5)
        x = self.rng.standard_normal((2, 3, 4)).astype(np.float32)
        y = self.rng.standard_normal((2, 4, 5)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(pt.bmm(jnp.asarray(x), jnp.asarray(y))),
                                   np.einsum("bij,bjk->bik", x, y), rtol=1e-5)
        with pytest.raises(ValueError):
            pt.bmm(jnp.asarray(a), jnp.asarray(a))
        p_ = self.rng.standard_normal((5, 3)).astype(np.float32)
        q_ = self.rng.standard_normal((4, 3)).astype(np.float32)
        ref = torch.cdist(torch.tensor(p_), torch.tensor(q_), p=2.0).numpy()
        np.testing.assert_allclose(
            np.asarray(pt.cdist(jnp.asarray(p_), jnp.asarray(q_))), ref,
            rtol=1e-4, atol=1e-5)
        ref = torch.dist(torch.tensor(p_), torch.tensor(p_ * 2), p=3).numpy()
        np.testing.assert_allclose(
            float(pt.dist(jnp.asarray(p_), jnp.asarray(p_ * 2), p=3)), ref,
            rtol=1e-5)

    def test_special_functions(self):
        x = jnp.asarray(self.rng.uniform(0.1, 3.0, (50,)).astype(np.float32))
        t = torch.tensor(np.asarray(x))
        for ours, theirs in ((pt.lgamma, torch.lgamma),
                             (pt.digamma, torch.digamma),
                             (pt.i0, torch.i0),
                             (pt.sinc, torch.sinc)):
            np.testing.assert_allclose(np.asarray(ours(x)),
                                       theirs(t).numpy(), rtol=2e-4,
                                       atol=1e-5)
        u = jnp.asarray(self.rng.uniform(-0.9, 0.9, (50,)).astype(np.float32))
        np.testing.assert_allclose(np.asarray(pt.erfinv(u)),
                                   torch.erfinv(torch.tensor(np.asarray(u))).numpy(),
                                   rtol=1e-4, atol=1e-5)
        m, e = pt.frexp(jnp.asarray([8.0, 0.5, -3.0]))
        mm, ee = torch.frexp(torch.tensor([8.0, 0.5, -3.0]))
        np.testing.assert_allclose(np.asarray(m), mm.numpy())
        np.testing.assert_array_equal(np.asarray(e), ee.numpy())
        np.testing.assert_allclose(
            np.asarray(pt.ldexp(jnp.asarray([1.5, 2.0]), jnp.asarray([2, 3]))),
            np.ldexp([1.5, 2.0], [2, 3]))

    def test_trapezoid(self):
        y = self.rng.standard_normal((4, 7)).astype(np.float32)
        x = np.sort(self.rng.standard_normal((7,))).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(pt.trapezoid(jnp.asarray(y), x=jnp.asarray(x))),
            np.trapezoid(y, x=x, axis=-1), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(pt.cumulative_trapezoid(jnp.asarray(y), dx=0.5)),
            torch.cumulative_trapezoid(torch.tensor(y), dx=0.5).numpy(),
            rtol=1e-5, atol=1e-6)

    def test_kthvalue_nanmedian(self):
        x = self.rng.standard_normal((3, 9)).astype(np.float32)
        vals, idx = pt.kthvalue(jnp.asarray(x), 4, axis=1)
        tv, ti = torch.kthvalue(torch.tensor(x), 4, dim=1)
        np.testing.assert_allclose(np.asarray(vals), tv.numpy())
        np.testing.assert_array_equal(np.asarray(idx), ti.numpy())
        xn = x.copy()
        xn[0, :2] = np.nan
        np.testing.assert_allclose(
            float(pt.nanmedian(jnp.asarray(xn))), np.nanmedian(xn))

    def test_cov_corrcoef_logspace(self):
        from paddle_tpu import linalg

        x = self.rng.standard_normal((3, 40)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(linalg.cov(jnp.asarray(x))),
                                   np.cov(x), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(linalg.corrcoef(jnp.asarray(x))), np.corrcoef(x),
            rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pt.logspace(0, 3, 4)),
                                   [1.0, 10.0, 100.0, 1000.0], rtol=1e-5)

    def test_histogramdd(self):
        x = self.rng.standard_normal((100, 2)).astype(np.float32)
        hist, edges = pt.histogramdd(jnp.asarray(x), bins=5)
        ref_h, ref_e = np.histogramdd(x, bins=5)
        np.testing.assert_allclose(np.asarray(hist), ref_h)
        assert len(edges) == 2


class TestManipulation:
    def setup_method(self, _):
        self.rng = np.random.default_rng(1)

    def test_masked_scatter_index_put(self):
        x = jnp.zeros((2, 3))
        mask = jnp.asarray([[True, False, True], [False, True, False]])
        out = pt.masked_scatter(x, mask, jnp.asarray([1.0, 2.0, 3.0, 9.0]))
        ref = torch.zeros(2, 3).masked_scatter_(
            torch.tensor(np.asarray(mask)),
            torch.tensor([1.0, 2.0, 3.0, 9.0])).numpy()
        np.testing.assert_allclose(np.asarray(out), ref)
        y = pt.index_put(jnp.zeros((3, 3)),
                         (jnp.asarray([0, 2]), jnp.asarray([1, 2])),
                         jnp.asarray([5.0, 7.0]))
        assert y[0, 1] == 5.0 and y[2, 2] == 7.0
        y2 = pt.index_put(y, (jnp.asarray([0]), jnp.asarray([1])),
                          jnp.asarray([5.0]), accumulate=True)
        assert y2[0, 1] == 10.0

    def test_splits_unflatten_diagflat(self):
        x = jnp.asarray(self.rng.standard_normal((6, 4, 2)).astype(np.float32))
        for ours, ref in ((pt.vsplit(x, 3), np.vsplit(np.asarray(x), 3)),
                          (pt.hsplit(x, 2), np.hsplit(np.asarray(x), 2)),
                          (pt.dsplit(x, 2), np.dsplit(np.asarray(x), 2)),
                          (pt.tensor_split(x, 4), np.array_split(np.asarray(x), 4))):
            for a, b in zip(ours, ref):
                np.testing.assert_allclose(np.asarray(a), b)
        u = pt.unflatten(x, 0, (2, 3))
        assert u.shape == (2, 3, 4, 2)
        u2 = pt.unflatten(x, 1, (-1, 2))
        assert u2.shape == (6, 2, 2, 2)
        np.testing.assert_allclose(np.asarray(pt.diagflat(jnp.asarray([1.0, 2.0]))),
                                   np.diagflat([1.0, 2.0]))

    def test_as_strided_unfold_view(self):
        x = jnp.asarray(np.arange(24, dtype=np.float32))
        out = pt.as_strided(x, (3, 4), (8, 2), offset=1)
        ref = np.lib.stride_tricks.as_strided(
            np.arange(24, dtype=np.float32)[1:], (3, 4), (32, 8))
        np.testing.assert_allclose(np.asarray(out), ref)
        t = torch.arange(24, dtype=torch.float32).reshape(4, 6)
        ours = pt.unfold(x.reshape(4, 6), 1, 3, 2)
        np.testing.assert_allclose(np.asarray(ours),
                                   t.unfold(1, 3, 2).numpy())
        v = pt.view(jnp.asarray([1.0, -2.0]), "int32")
        ref_v = torch.tensor([1.0, -2.0]).view(torch.int32).numpy()
        np.testing.assert_array_equal(np.asarray(v), ref_v)
        assert pt.view_as(x, jnp.zeros((4, 6))).shape == (4, 6)

    def test_unique_consecutive(self):
        x = jnp.asarray([1, 1, 2, 2, 2, 3, 1, 1])
        out, inv, cnt = pt.unique_consecutive(
            x, return_inverse=True, return_counts=True)
        to, ti, tc = torch.unique_consecutive(
            torch.tensor(np.asarray(x)), return_inverse=True,
            return_counts=True)
        np.testing.assert_array_equal(np.asarray(out), to.numpy())
        np.testing.assert_array_equal(np.asarray(inv), ti.numpy())
        np.testing.assert_array_equal(np.asarray(cnt), tc.numpy())

    def test_inplace_spellings_and_misc(self):
        x = jnp.zeros((2, 3))
        assert pt.reshape_(x, [6]).shape == (6,)
        assert pt.squeeze_(jnp.zeros((1, 3)), 0).shape == (3,)
        assert pt.unsqueeze_(x, 0).shape == (1, 2, 3)
        assert float(pt.clip_(jnp.asarray([5.0]), max=1.0)[0]) == 1.0
        assert pt.is_tensor(x) and not pt.is_tensor([1, 2])
        assert int(pt.rank(x)) == 2


class TestNewLayers:
    def test_fold_unfold_layers_roundtrip(self):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((2, 3, 6, 6)).astype(np.float32))
        cols = nn.Unfold(2, strides=2)(x)
        back = nn.Fold((6, 6), 2, strides=2)(cols)
        # non-overlapping windows: fold(unfold(x)) == x
        np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                                   atol=1e-6)

    def test_lrn_layer_vs_torch(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((2, 7, 5, 5)).astype(np.float32)
        ours = np.asarray(nn.LocalResponseNorm(5)(jnp.asarray(x)))
        ref = torch.nn.LocalResponseNorm(5)(torch.tensor(x)).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)

    def test_alpha_dropout_and_zeropad(self):
        import paddle_tpu as pt_

        pt_.seed(0)
        layer = nn.AlphaDropout(0.4)
        big = jnp.asarray(np.random.default_rng(4)
                          .standard_normal((100000,)).astype(np.float32))
        out = np.asarray(layer(big))
        # SELU-preserving: mean ~0, std ~1
        assert abs(out.mean()) < 0.02 and abs(out.std() - 1.0) < 0.03
        layer.eval()
        np.testing.assert_allclose(np.asarray(layer(big)), np.asarray(big))
        zp = nn.ZeroPad2D([1, 2, 3, 4])(jnp.zeros((1, 1, 2, 2)))
        assert zp.shape == (1, 1, 9, 5)


class TestSchedulerSamplerTail:
    def test_cosine_warm_restarts_vs_torch(self):
        from paddle_tpu import optimizer as opt

        sch = opt.lr.CosineAnnealingWarmRestarts(0.1, T_0=5, T_mult=2,
                                                 eta_min=0.01)
        tsch = torch.optim.lr_scheduler.CosineAnnealingWarmRestarts(
            torch.optim.SGD([torch.nn.Parameter(torch.zeros(1))], lr=0.1),
            T_0=5, T_mult=2, eta_min=0.01)
        ours, theirs = [], []
        for _ in range(20):
            ours.append(float(sch.lr_at(len(ours))))
            theirs.append(tsch.get_last_lr()[0])
            tsch.step()
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-7)
        with pytest.raises(ValueError):
            opt.lr.CosineAnnealingWarmRestarts(0.1, T_0=0)

    def test_subset_random_sampler_and_amp_predicates(self):
        from paddle_tpu import amp, io

        s = io.SubsetRandomSampler([3, 7, 9])
        assert sorted(iter(s)) == [3, 7, 9] and len(s) == 3
        # successive epochs reshuffle (with 3! = 6 orders, 8 draws
        # repeating identically is ~0.03% if shuffling works)
        orders = {tuple(iter(s)) for _ in range(8)}
        assert len(orders) > 1
        assert amp.is_bfloat16_supported() and amp.is_float16_supported()
