"""Test env: force CPU backend with 8 virtual devices so every multi-chip
sharding path runs on CI hardware (parity with the reference's
Gloo-on-CPU + fake-mesh test strategy, SURVEY.md §4).

Note: this sandbox pre-imports jax via sitecustomize with
JAX_PLATFORMS=axon (the real TPU tunnel), so the platform must be
overridden through jax.config *before first backend use*, not via env.
"""

import os

# telemetry defaults ON for real runs, but the suite's hundreds of
# tiny-model TrainStep compilations would each pay the instrumented
# step's extra grad-norm output for no assertion value — keep the CI
# session un-instrumented; tests/test_observability.py flips the flag
# on (set_flags) for the paths that actually assert on telemetry
os.environ.setdefault("PT_FLAGS_telemetry", "off")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Deregister the axon (remote TPU tunnel) PJRT plugin if the sandbox's
# sitecustomize installed it: jax initializes every registered plugin on
# first backend use regardless of JAX_PLATFORMS, and a down/flaky tunnel
# then hangs the entire test run inside PJRT init.
try:
    from jax._src import xla_bridge as _xb

    for _reg in ("_backend_factories", "backend_factories"):
        _d = getattr(_xb, _reg, None)
        if isinstance(_d, dict):
            _d.pop("axon", None)
except Exception:
    pass
# numerics tests compare against float64/float32 numpy references; pin
# matmul precision (prod default stays bf16-on-MXU, the TPU analog of the
# reference's TF32-on-A100 default)
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture
def compile_counter():
    """Compile-count guard for engine tests: returns a callable giving
    the number of jit SPECIALIZATIONS of a named serving program since
    the fixture was set up (trace-time counters in
    ``paddle_tpu.inference.serving.TRACE_COUNTS``). Called with NO
    argument it returns the full {program: delta} dict (zero deltas
    omitted) so a test can pin the EXACT compiled-program set of a
    workload — e.g. spec-decode-off must compile precisely the PR-4
    set, spec-on at most verify + fallback on top. The regression this
    exists to prevent: a serving program silently re-specializing per
    prompt length / seq bucket / scheduler mode."""
    from paddle_tpu.inference import serving

    base = serving.TRACE_COUNTS.copy()

    def counter(key=None):
        if key is None:
            return {k: v - base[k]
                    for k, v in serving.TRACE_COUNTS.items()
                    if v - base[k]}
        return serving.TRACE_COUNTS[key] - base[key]

    def assert_programs(allowed):
        """Pin the compiled-program set: fail on any specialization
        outside ``allowed`` since the fixture (or the last snapshot
        the caller diffs against). The recovery/replay guard calls
        this to prove that quarantine + deterministic replay adds
        ZERO new compiled programs — replay must reuse the existing
        ``prefill_chunk``/``decode_chunk`` programs."""
        got = counter()
        extra = {k: v for k, v in got.items() if k not in set(allowed)}
        assert not extra, (
            f"unexpected compiled-program specializations: {extra} "
            f"(allowed: {sorted(allowed)})")

    counter.assert_programs = assert_programs
    return counter


@pytest.fixture
def serving_flags():
    """set_flags with restore for the serving knobs the engine suites
    flip (spec decode, prefix cache, prefill chunking, fused decode,
    KV/weight dtypes). Shared by test_spec_decode and
    test_quant_serving — yield the setter, restore on teardown."""
    from paddle_tpu import flags as F

    keys = ("spec_decode", "prefix_cache", "prefill_chunk",
            "fused_decode", "kv_cache_dtype", "serve_weight_dtype",
            "serve_recovery")
    saved = {k: F.flag(k) for k in keys}
    yield F.set_flags
    F.set_flags(saved)


@pytest.fixture(autouse=True)
def _sanitize_chaos_lane(request):
    """The chaos lane runs SANITIZED: every ``-m chaos`` storm
    executes with ``PT_FLAGS_sanitize=on``, so a fault-recovery bug
    that corrupts pool/slot/scale bookkeeping trips the invariant
    checker (analysis/sanitizer.py) at the tick that caused it,
    instead of shipping a poisoned trace the parity oracle flags
    hundreds of tokens later."""
    if request.node.get_closest_marker("chaos") is None:
        yield
        return
    from paddle_tpu import flags as F

    saved = F.flag("sanitize")
    F.set_flags({"sanitize": True})
    yield
    F.set_flags({"sanitize": saved})


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as pt

    pt.seed(2024)
    yield
    # don't leak the global mesh/HCG between tests
    from paddle_tpu.distributed import topology

    topology._global_hcg = None
