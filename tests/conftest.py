"""Test env: force CPU backend with 8 virtual devices so every multi-chip
sharding path runs on CI hardware (parity with the reference's
Gloo-on-CPU + fake-mesh test strategy, SURVEY.md §4)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# numerics tests compare against float64/float32 numpy references; pin
# matmul precision (prod default stays bf16-on-MXU, the TPU analog of the
# reference's TF32-on-A100 default)
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as pt

    pt.seed(2024)
    yield
