"""Measured per-program device-time attribution + runtime recompile /
HBM watchdogs (``observability/profiling.py`` + the serving engine's
dispatch-seam hooks).

Under test:
  - **off == identity**: ``PT_FLAGS_profile_programs`` off leaves the
    engine with no profiler (one identity check per seam); on, the
    compiled-program set is UNCHANGED (compile_counter equality) and
    greedy outputs are bit-identical — the profiler only measures;
  - sampled dispatches record the MEASURED schedule/dispatch/device
    decomposition (host stats + ``pt_serve_program_ms`` histograms +
    ``profiled=True`` tracer step events); unsampled dispatches keep
    the honest ``sync_wall_ms`` fallback;
  - the sampling cadence is deterministic per program;
  - the recompile watchdog seals after warmup (tick budget or
    ``seal_programs()``) and fires on a deliberately shape-busting
    dispatch: host counters, the registry counter, and a
    FlightRecorder artifact carrying the offending arg shapes;
  - HBM accounting: kv_pool / kv_scales (int8) / weights_<dtype> /
    prefix_store components from array metadata only;
  - ``PROGRAM_LABELS`` covers every TRACE_COUNTS program name — the
    runtime twin of ptlint's OBS001 static rule.
"""

import ast
import glob
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest
import serving_utils

from paddle_tpu import flags as F
from paddle_tpu import observability as obs
from paddle_tpu.inference import serving
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.observability.profiling import (
    PROGRAM_LABELS,
    ProgramProfiler,
)

pytestmark = pytest.mark.fast


def _model(seed=0):
    return serving_utils.tiny_model(seed)


def _ecfg(paged, **kw):
    return serving_utils.tiny_ecfg(paged, **kw)


def _prompts(cfg, n=3, seed=5, lo=6, hi=20):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (int(rng.integers(lo, hi)),))
            for _ in range(n)]


@pytest.fixture
def prof_flags():
    keys = ("profile_programs", "profile_sample_every",
            "recompile_watchdog", "recompile_warmup_ticks",
            "telemetry", "trace_sample", "telemetry_dump_dir",
            "spec_decode")
    saved = {k: F.flag(k) for k in keys}
    yield F.set_flags
    F.set_flags(saved)


# ---------------- off == identity ----------------

@pytest.mark.parametrize("paged", [False, True])
def test_profiler_off_identity_on_changes_nothing(paged, prof_flags,
                                                  compile_counter):
    """Flag off: no profiler object. Flag on (every dispatch
    sampled): same compiled-program set, bit-identical outputs — the
    profiler measures, it never participates."""
    model, cfg = _model(0)
    prompts = _prompts(cfg)
    eng_off = ContinuousBatchingEngine(model, _ecfg(paged))
    assert eng_off._prof is None
    out_off = [r.output for r in eng_off.run(prompts, 8, max_chunk=2)]
    base = compile_counter()

    prof_flags({"profile_programs": True, "profile_sample_every": 1})
    eng_on = ContinuousBatchingEngine(model, _ecfg(paged))
    assert eng_on._prof is not None
    out_on = [r.output for r in eng_on.run(prompts, 8, max_chunk=2)]
    assert out_on == out_off
    # zero NEW compiled programs vs the unprofiled run's set
    after = compile_counter()
    grown = {k: v - base.get(k, 0) for k, v in after.items()
             if v - base.get(k, 0)}
    assert set(grown) <= set(base), (
        f"profiler added compiled programs: {grown}")
    snap = eng_on.profile_snapshot()
    assert snap["enabled"] and snap["programs"]["decode_chunk"][
        "sampled"] > 0
    assert eng_off.profile_snapshot() == {"enabled": False}


def test_profiler_cadence_deterministic(prof_flags):
    """sample_every=3 measures every 3rd dispatch of each program —
    and the unsampled dispatches never pay a block_until_ready."""
    model, cfg = _model(1)
    prof_flags({"profile_programs": True, "profile_sample_every": 3})
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    eng.run(_prompts(cfg, n=4), 10, max_chunk=2)
    st = eng.profile_snapshot()["programs"]["decode_chunk"]
    assert st["sampled"] == st["dispatches"] // 3


def test_unknown_program_name_rejected():
    prof = ProgramProfiler(engine_id="t")
    with pytest.raises(ValueError, match="PROGRAM_LABELS"):
        prof.want("not_a_program")


# ---------------- measured decomposition ----------------

def test_sampled_steps_carry_measured_decomposition(prof_flags):
    """Telemetry + profiler on, every dispatch sampled: tracer step
    events report the measured schedule/dispatch/device split
    (profiled=True, no sync_wall_ms estimate), the host snapshot
    accumulates the same numbers, and the registry histogram holds
    one observation per sampled dispatch."""
    model, cfg = _model(2)
    prof_flags({"telemetry": True, "trace_sample": 1.0,
                "profile_programs": True, "profile_sample_every": 1})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    eng.run(_prompts(cfg), 6, max_chunk=2)
    steps = [e for e in eng._tracer.events() if e["kind"] == "step"
             and e["name"] in ("decode", "decode_chunk", "verify")]
    assert steps
    for e in steps:
        assert e["args"]["profiled"] is True
        assert e["args"]["device_ms"] >= 0
        assert e["args"]["schedule_ms"] >= 0
        assert e["args"]["dispatch_ms"] >= 0
        assert "sync_wall_ms" not in e["args"]
    snap = eng.profile_snapshot()
    st = snap["programs"]["decode_chunk"]
    assert st["device_ms_p50"] >= 0 and st["device_ms_max"] >= \
        st["device_ms_p50"] >= 0
    hist = obs.global_registry().get("pt_serve_program_ms")
    lab = {"engine": eng._prof.engine_id, "program": "decode_chunk"}
    assert hist.window_len(**lab) == st["sampled"]


def test_unsampled_steps_keep_sync_wall_fallback(prof_flags):
    """A cadence that never fires within the run leaves every step on
    the renamed honest estimate — and no host sync is charged to the
    profiler (sampled == 0)."""
    model, cfg = _model(3)
    prof_flags({"telemetry": True, "trace_sample": 1.0,
                "profile_programs": True,
                "profile_sample_every": 10_000})
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    eng.run(_prompts(cfg, n=2), 6, max_chunk=2)
    steps = [e for e in eng._tracer.events() if e["kind"] == "step"
             and e["name"] in ("decode", "decode_chunk")]
    assert steps
    for e in steps:
        assert "profiled" not in e["args"]
        assert e["args"]["sync_wall_ms"] >= 0
    st = eng.profile_snapshot()["programs"]["decode_chunk"]
    assert st["sampled"] == 0 and st["dispatches"] > 0


# ---------------- recompile watchdog ----------------

def test_watchdog_fires_on_shape_busting_dispatch(prof_flags,
                                                  tmp_path):
    """Seal after warmup, then deliberately shape-bust the chunked
    prefill (new chunk length + a fresh jit wrapper — the TS003
    hazard at runtime): the watchdog counts the recompile, the
    registry counter increments, and a FlightRecorder artifact names
    the offending arg shapes."""
    model, cfg = _model(4)
    prof_flags({"telemetry": True, "trace_sample": 0.0,
                "telemetry_dump_dir": str(tmp_path)})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    prompts = _prompts(cfg, n=2, seed=11)
    eng.run(prompts, 4, max_chunk=2)
    assert not eng.recompile_snapshot()["sealed"]
    eng.seal_programs()
    assert eng.recompile_snapshot()["sealed"]

    eng._chunk_len = 5  # shape drift mid-life
    eng._prefill_chunk_c = None  # fresh wrapper: retrace guaranteed
    eng.add_request(prompts[0], 4)
    while eng.step_chunk(2):
        pass
    snap = eng.recompile_snapshot()
    assert snap["recompiles"].get("prefill_chunk", 0) >= 1
    ctr = obs.global_registry().get("pt_serve_recompiles_total")
    assert ctr.value(engine=eng._tel.engine_id,
                     program="prefill_chunk") >= 1
    dumps = glob.glob(os.path.join(str(tmp_path), "flight_*.json"))
    assert dumps, "no FlightRecorder artifact written"
    with open(sorted(dumps)[-1]) as f:
        payload = json.load(f)
    assert "recompile" in payload["reason"]
    rec = next(r for r in payload["records"]
               if r.get("kind") == "serve_recompile")
    assert rec["program"] == "prefill_chunk"
    shapes = rec["arg_shapes"]["ids"]
    # TRACE_SHAPES records the offending specialization: [slots, C']
    assert list(shapes)[-1] == 5


def test_watchdog_auto_seals_and_stays_quiet(prof_flags):
    """The tick budget seals without an explicit call, and a
    steady-shape workload records ZERO post-seal recompiles."""
    model, cfg = _model(5)
    prof_flags({"recompile_warmup_ticks": 3})
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    eng.run(_prompts(cfg, n=4), 10, max_chunk=2)
    snap = eng.recompile_snapshot()
    assert snap["sealed"] and snap["ticks"] >= 3
    assert snap["recompiles"] == {}


def test_watchdog_off_is_identity(prof_flags):
    model, cfg = _model(6)
    prompts = _prompts(cfg)
    ref = [r.output for r in ContinuousBatchingEngine(
        model, _ecfg(False)).run(prompts, 6, max_chunk=2)]
    prof_flags({"recompile_watchdog": False})
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    assert eng._watchdog is None
    assert eng.recompile_snapshot() == {"enabled": False}
    assert [r.output for r in eng.run(prompts, 6, max_chunk=2)] == ref


# ---------------- HBM accounting ----------------

def test_hbm_components_paged_int8():
    """int8 KV pools report scale rows as their own component; weight
    bytes split by dtype; totals are consistent."""
    model, cfg = _model(7)
    eng = ContinuousBatchingEngine(
        model, _ecfg(True, cache_dtype="int8"))
    hbm = eng.hbm_snapshot()
    assert hbm["kv_pool"] > 0 and hbm["kv_scales"] > 0
    assert any(k.startswith("weights_") for k in hbm)
    assert hbm["total"] == sum(v for k, v in hbm.items()
                               if k != "total")
    # int8 payload + f32 per-row scales: scales are d/4 the payload
    # footprint per row (1 f32 per kvh*page row vs d int8 payload)
    assert hbm["kv_scales"] < hbm["kv_pool"]


def test_hbm_prefix_store_bytes_grow_contiguous():
    """The contiguous prefix store is REAL device memory on top of
    the engine's own cache — its bytes appear once blocks publish."""
    model, cfg = _model(8)
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    assert eng.hbm_snapshot()["prefix_store"] == 0
    rng = np.random.default_rng(2)
    prompt = rng.integers(1, cfg.vocab_size, (24,))  # 3 hash blocks
    eng.run([prompt], 4, max_chunk=2)
    assert eng._prefix is not None and len(eng._prefix) > 0
    assert eng.hbm_snapshot()["prefix_store"] > 0


def test_hbm_gauges_in_registry(prof_flags):
    model, cfg = _model(9)
    prof_flags({"telemetry": True, "trace_sample": 0.0})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    eng.metrics_snapshot()
    g = obs.global_registry().get("pt_serve_hbm_bytes")
    val = g.value(engine=eng._tel.engine_id, component="kv_pool")
    assert val == eng.hbm_snapshot()["kv_pool"] > 0
    peak = obs.global_registry().get("pt_serve_hbm_bytes_peak")
    lab = {"engine": eng._tel.engine_id, "component": "kv_pool"}
    assert peak.value(**lab) >= val
    # the watermark is per-WINDOW, like every other peak gauge
    eng.metrics_window_reset()
    assert peak.value(**lab) == 0
    eng.metrics_snapshot()
    assert peak.value(**lab) == val


# ---------------- label registry completeness (runtime twin) -------

def test_program_labels_cover_trace_counts():
    """Every TRACE_COUNTS program name in serving.py carries a timing
    label — the runtime twin of ptlint's OBS001 static rule (same
    AST walk the rule does, against the live PROGRAM_LABELS)."""
    src = open(serving.__file__, encoding="utf-8").read()
    tree = ast.parse(src)
    names = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.AugAssign)
                and isinstance(node.target, ast.Subscript)
                and isinstance(node.target.value, ast.Name)
                and node.target.value.id == "TRACE_COUNTS"
                and isinstance(node.target.slice, ast.Constant)):
            names.add(node.target.slice.value)
    assert names, "no TRACE_COUNTS bumps found — walker broken?"
    missing = names - set(PROGRAM_LABELS)
    assert not missing, (
        f"programs without a timing label: {missing} — add them to "
        "observability.profiling.PROGRAM_LABELS")
    # shape notes ride along with every bump: a recompile dump can
    # name arg shapes for any program the watchdog reports
    noted = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_shape_note" and node.args
                and isinstance(node.args[0], ast.Constant)):
            noted.add(node.args[0].value)
    assert noted == names, (
        f"TRACE_COUNTS programs without a _shape_note: "
        f"{names - noted}")
