"""Fleet-correlated tracing: ONE merged Perfetto document for the
whole fleet (``observability.tracing.fleet_chrome_trace``).

Under test:
  - a scripted 2-replica crash-failover produces a merged trace that
    validates against the Chrome trace-event schema AND shows each
    victim rid's spans on BOTH replicas' request tracks, joined by
    flow events (``ph: s``/``f`` with ``id == rid``) — the acceptance
    pin: a failed-over request's journey is ONE timeline;
  - ``/trace?fleet=1`` on the fleet metrics server serves the merged
    document (plain ``/trace`` keeps serving the router's own event
    stream);
  - ``python -m paddle_tpu.observability.dump --fleet`` exports the
    fleet snapshot + merged trace for every in-process router;
  - telemetry off: no tracers exist, the merged export degrades to an
    empty event list without error (the fleet snapshot stays
    host-side).
"""

import io
import contextlib
import json
import urllib.request

import numpy as np
import pytest
import serving_utils

from paddle_tpu import flags as F
from paddle_tpu.inference.resilience import FaultInjector
from paddle_tpu.inference.router import EngineRouter
from paddle_tpu.inference.serving import start_metrics_server
from paddle_tpu.observability import dump as dump_cli
from paddle_tpu.observability import tracing

pytestmark = pytest.mark.chaos


def _model(seed=0):
    return serving_utils.tiny_model(seed)


def _ecfg(paged=True, **kw):
    return serving_utils.tiny_ecfg(paged, **kw)


class ScriptedInjector(FaultInjector):
    """fire() hits at EXACT scripted consultation indices per site
    (same idiom as test_router's scripted scenarios)."""

    def __init__(self, plan):
        super().__init__("")
        self._plan = {s: set(v) for s, v in plan.items()}

    def fire(self, site):
        n = self.draws[site]
        self.draws[site] = n + 1
        hit = n in self._plan.get(site, ())
        if hit:
            self.fires[site] += 1
        return hit


@pytest.fixture
def obs_flags():
    keys = ("telemetry", "trace_sample")
    saved = {k: F.flag(k) for k in keys}
    yield F.set_flags
    F.set_flags(saved)


def _validate_chrome(doc):
    """Chrome trace-event schema incl. flow events (the shape
    Perfetto loads): X/i/M as in test_tracing, plus s/f flows with a
    numeric id."""
    assert isinstance(doc, dict) and isinstance(doc["traceEvents"], list)
    json.loads(json.dumps(doc, default=str))
    for e in doc["traceEvents"]:
        assert isinstance(e["name"], str) and e["name"]
        assert e["ph"] in ("X", "i", "M", "s", "f")
        assert isinstance(e["pid"], int)
        assert isinstance(e["tid"], int)
        if e["ph"] != "M":
            assert isinstance(e["ts"], float) and e["ts"] >= 0.0
        if e["ph"] == "X":
            assert e["dur"] >= 0.0
        if e["ph"] == "i":
            assert e["s"] in ("t", "p", "g")
        if e["ph"] in ("s", "f"):
            assert isinstance(e["id"], int)
        if e["ph"] == "f":
            assert e["bp"] == "e"  # bind to the ENCLOSING slice


def _scripted_crash_fleet(obs_flags, seed=0):
    """2-replica fleet, replica 0 crashed mid-flight at tick 3 —
    returns (router, victim rids)."""
    obs_flags({"telemetry": True, "trace_sample": 1.0})
    model, cfg = _model(seed)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, cfg.vocab_size,
                            (int(rng.integers(6, 20)),))
               for _ in range(4)]
    inj = ScriptedInjector({"replica_crash": {4}})
    router = EngineRouter(model, _ecfg(), n_replicas=2,
                          fault_injector=inj)
    for p in prompts:
        router.add_request(p, 8)
    router.step(2)
    router.step(2)
    victims = [r.rid for r
               in router._replicas[0].engine._slot_req.values()]
    assert victims, "replica 0 held nothing — scenario is vacuous"
    while router.step(2):
        pass
    assert router.fleet_stats["failovers"] == 1
    return router, victims


def test_fleet_trace_crash_failover_flow_correlation(obs_flags):
    """THE acceptance pin: the merged trace validates against the
    Chrome schema and shows each victim rid's spans on BOTH replicas,
    connected by an s→f flow pair with id == rid."""
    router, victims = _scripted_crash_fleet(obs_flags)
    doc = router.fleet_chrome_trace()
    _validate_chrome(doc)
    evs = doc["traceEvents"]
    replica_pids = {tracing._pid(rep.engine._tracer)
                    for rep in router._replicas}
    assert len(replica_pids) == 2
    for rid in victims:
        # spans on BOTH replicas' request tracks
        span_pids = {e["pid"] for e in evs if e["ph"] == "X"
                     and e.get("args", {}).get("rid") == rid}
        assert span_pids >= replica_pids, (
            f"rid {rid} spans missing on a replica: {span_pids}")
        # ...joined by a flow: start on the dead replica, finish on
        # the survivor, same id, request tid on both sides
        starts = [e for e in evs if e["ph"] == "s" and e["id"] == rid]
        ends = [e for e in evs if e["ph"] == "f" and e["id"] == rid]
        assert len(starts) == 1 and len(ends) == 1
        assert starts[0]["pid"] != ends[0]["pid"]
        assert {starts[0]["pid"], ends[0]["pid"]} == replica_pids
        assert starts[0]["tid"] == ends[0]["tid"] == rid + 1
        assert starts[0]["ts"] <= ends[0]["ts"]
    # non-victims never grew a flow
    flows = {e["id"] for e in evs if e["ph"] in ("s", "f")}
    assert flows == set(victims)
    # the router's own control-plane stream rides the same document
    names = {e["name"] for e in evs}
    assert "failover" in names and "route" in names


def test_fleet_trace_server_endpoint(obs_flags):
    """/trace?fleet=1 serves the merged document; plain /trace keeps
    the router-tracer-only view (backwards compatible)."""
    router, victims = _scripted_crash_fleet(obs_flags, seed=1)
    srv = start_metrics_server(router)
    try:
        host, port = srv.server_address[:2]
        with urllib.request.urlopen(
                f"http://{host}:{port}/trace?fleet=1") as resp:
            assert resp.status == 200
            doc = json.loads(resp.read())
        _validate_chrome(doc)
        assert any(e["ph"] in ("s", "f") for e in doc["traceEvents"])
        with urllib.request.urlopen(
                f"http://{host}:{port}/trace") as resp:
            assert resp.status == 200
            solo = json.loads(resp.read())
        # router-only view: control-plane instants, no request spans
        assert not any(e.get("cat") == "request"
                       for e in solo["traceEvents"])
    finally:
        srv.shutdown()


def test_dump_fleet_cli(obs_flags):
    router, victims = _scripted_crash_fleet(obs_flags, seed=2)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = dump_cli.main(["--fleet"])
    assert rc == 0
    out = json.loads(buf.getvalue())
    mine = next(o for o in out
                if o["fleet_snapshot"]["failovers"] == 1
                and o["fleet_snapshot"]["n_replicas"] == 2)
    _validate_chrome(mine["trace"])
    assert router in tracing.fleets()


def test_fleet_trace_telemetry_off_degrades_cleanly():
    """conftest default (telemetry off): no tracers exist anywhere —
    the merged export is an empty event list, the fleet snapshot
    stays available, nothing raises."""
    model, cfg = _model(3)
    router = EngineRouter(model, _ecfg(), n_replicas=2)
    assert router._tracer is None
    doc = router.fleet_chrome_trace()
    assert doc["traceEvents"] == []
    assert router.fleet_snapshot()["n_replicas"] == 2
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert dump_cli.main(["--fleet"]) == 0
    assert isinstance(json.loads(buf.getvalue()), list)
