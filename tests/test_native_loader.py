"""Native C++ token loader: build, read-back correctness, shuffle
determinism, epoch exhaustion (parity model: reader op unit tests)."""

import numpy as np
import pytest

pytest.importorskip("ctypes")


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("data") / "tokens.bin"
    # 32 sequences of length 16, tokens = seq_index*100 + position (uint16)
    arr = np.zeros((32, 16), np.uint16)
    for i in range(32):
        arr[i] = i * 100 + np.arange(16)
    arr.tofile(path)
    return str(path)


def test_build_and_read(token_file):
    from paddle_tpu.io.native import TokenBinDataset

    ds = TokenBinDataset(token_file, seq_len=16)
    assert len(ds) == 32
    batches = list(ds.batches(batch_size=8, shuffle=False, seed=0))
    assert len(batches) == 4
    np.testing.assert_array_equal(
        batches[0][0], np.arange(16)
    )
    np.testing.assert_array_equal(
        batches[3][7], 3100 + np.arange(16)
    )
    ds.close()


def test_shuffle_deterministic_and_complete(token_file):
    from paddle_tpu.io.native import TokenBinDataset

    ds = TokenBinDataset(token_file, seq_len=16)
    a = np.concatenate(
        [b[:, 0] for b in ds.batches(8, seed=7, shuffle=True)]
    )
    b = np.concatenate(
        [b[:, 0] for b in ds.batches(8, seed=7, shuffle=True)]
    )
    c = np.concatenate(
        [b[:, 0] for b in ds.batches(8, seed=8, shuffle=True)]
    )
    np.testing.assert_array_equal(a, b)  # same seed → same order
    assert not np.array_equal(a, c)  # different seed → different order
    assert sorted(a.tolist()) == sorted((np.arange(32) * 100).tolist())
    ds.close()


def test_drop_last_false(token_file):
    from paddle_tpu.io.native import TokenBinDataset

    ds = TokenBinDataset(token_file, seq_len=16)
    batches = list(ds.batches(batch_size=5, shuffle=False, drop_last=False))
    assert [len(b) for b in batches] == [5, 5, 5, 5, 5, 5, 2]
    ds.close()


def test_native_ckpt_writer_batch(tmp_path):
    """The C thread-pool chunk writer must produce byte-valid .npy files
    np.load can read back (incl. bf16-as-uint16 payloads)."""
    from paddle_tpu.distributed.checkpoint import _native_write_chunks

    rng = np.random.default_rng(0)
    files = []
    refs = []
    for i in range(10):
        a = rng.standard_normal((32, 17)).astype(np.float32)
        files.append((str(tmp_path / f"chunk_{i}.npy"), a))
        refs.append(a)
    u16 = (rng.integers(0, 2**16, (8, 8))).astype(np.uint16)
    files.append((str(tmp_path / "bits.npy"), u16))
    assert _native_write_chunks(files) is True
    for (path, _), ref in zip(files[:-1], refs):
        np.testing.assert_array_equal(np.load(path), ref)
    np.testing.assert_array_equal(np.load(str(tmp_path / "bits.npy")), u16)


def test_ckpt_writer_python_fallback(tmp_path, monkeypatch):
    """With the native library unavailable, saves still succeed via the
    np.save loop."""
    from paddle_tpu.distributed import checkpoint as ckpt

    monkeypatch.setattr(ckpt, "_native_write_chunks", lambda files: False)
    import jax.numpy as jnp

    ckpt.save_state_dict({"w": jnp.ones((4, 4))}, str(tmp_path / "fb"))
    loaded = ckpt.load_state_dict(str(tmp_path / "fb"))
    np.testing.assert_allclose(np.asarray(loaded["w"]), 1.0)
