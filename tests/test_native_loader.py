"""Native C++ token loader: build, read-back correctness, shuffle
determinism, epoch exhaustion (parity model: reader op unit tests)."""

import numpy as np
import pytest

pytest.importorskip("ctypes")


@pytest.fixture(scope="module")
def token_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("data") / "tokens.bin"
    # 32 sequences of length 16, tokens = seq_index*100 + position (uint16)
    arr = np.zeros((32, 16), np.uint16)
    for i in range(32):
        arr[i] = i * 100 + np.arange(16)
    arr.tofile(path)
    return str(path)


def test_build_and_read(token_file):
    from paddle_tpu.io.native import TokenBinDataset

    ds = TokenBinDataset(token_file, seq_len=16)
    assert len(ds) == 32
    batches = list(ds.batches(batch_size=8, shuffle=False, seed=0))
    assert len(batches) == 4
    np.testing.assert_array_equal(
        batches[0][0], np.arange(16)
    )
    np.testing.assert_array_equal(
        batches[3][7], 3100 + np.arange(16)
    )
    ds.close()


def test_shuffle_deterministic_and_complete(token_file):
    from paddle_tpu.io.native import TokenBinDataset

    ds = TokenBinDataset(token_file, seq_len=16)
    a = np.concatenate(
        [b[:, 0] for b in ds.batches(8, seed=7, shuffle=True)]
    )
    b = np.concatenate(
        [b[:, 0] for b in ds.batches(8, seed=7, shuffle=True)]
    )
    c = np.concatenate(
        [b[:, 0] for b in ds.batches(8, seed=8, shuffle=True)]
    )
    np.testing.assert_array_equal(a, b)  # same seed → same order
    assert not np.array_equal(a, c)  # different seed → different order
    assert sorted(a.tolist()) == sorted((np.arange(32) * 100).tolist())
    ds.close()


def test_drop_last_false(token_file):
    from paddle_tpu.io.native import TokenBinDataset

    ds = TokenBinDataset(token_file, seq_len=16)
    batches = list(ds.batches(batch_size=5, shuffle=False, drop_last=False))
    assert [len(b) for b in batches] == [5, 5, 5, 5, 5, 5, 2]
    ds.close()
