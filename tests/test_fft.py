"""paddle.fft parity: signatures (x/n/axis/norm keywords), norm modes,
length overrides, validation — numerics vs numpy.fft."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import fft


@pytest.fixture
def x():
    rng = np.random.default_rng(0)
    return rng.standard_normal((4, 16)) + 1j * rng.standard_normal((4, 16))


def test_fft_keywords_and_norms(x):
    for norm in ("backward", "ortho", "forward"):
        got = fft.fft(x=jnp.asarray(x), n=16, axis=-1, norm=norm)
        want = np.fft.fft(x, n=16, axis=-1, norm=norm)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


def test_rfft_irfft_roundtrip():
    r = np.random.default_rng(1).standard_normal((3, 32))
    spec = fft.rfft(x=jnp.asarray(r), norm="ortho")
    np.testing.assert_allclose(
        np.asarray(spec), np.fft.rfft(r, norm="ortho"), atol=1e-5)
    back = fft.irfft(spec, n=32, norm="ortho")
    np.testing.assert_allclose(np.asarray(back), r, atol=1e-5)


def test_fft_n_truncates_and_pads(x):
    np.testing.assert_allclose(
        np.asarray(fft.fft(jnp.asarray(x), n=8)),
        np.fft.fft(x, n=8), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(fft.fft(jnp.asarray(x), n=32)),
        np.fft.fft(x, n=32), atol=1e-5)


def test_2d_and_nd(x):
    np.testing.assert_allclose(
        np.asarray(fft.fft2(jnp.asarray(x), s=(4, 8), norm="forward")),
        np.fft.fft2(x, s=(4, 8), norm="forward"), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(fft.ifftn(jnp.asarray(x), axes=(0, 1))),
        np.fft.ifftn(x, axes=(0, 1)), atol=1e-5)
    r = np.random.default_rng(2).standard_normal((4, 6, 8))
    np.testing.assert_allclose(
        np.asarray(fft.rfftn(jnp.asarray(r), s=(6, 8), axes=(1, 2))),
        np.fft.rfftn(r, s=(6, 8), axes=(1, 2)), atol=1e-5)


def test_hfft_ihfft():
    r = np.random.default_rng(3).standard_normal((5, 9))
    np.testing.assert_allclose(
        np.asarray(fft.ihfft(jnp.asarray(r), norm="ortho")),
        np.fft.ihfft(r, norm="ortho"), atol=1e-5)
    c = np.fft.ihfft(r)
    np.testing.assert_allclose(
        np.asarray(fft.hfft(jnp.asarray(c), n=9)),
        np.fft.hfft(c, n=9), atol=1e-5)


def test_helpers_and_dtype():
    f = fft.fftfreq(8, d=0.5, dtype="float64")
    np.testing.assert_allclose(np.asarray(f), np.fft.fftfreq(8, 0.5))
    rf = fft.rfftfreq(8, d=2.0)
    np.testing.assert_allclose(np.asarray(rf), np.fft.rfftfreq(8, 2.0))
    a = jnp.arange(8.0)
    np.testing.assert_allclose(
        np.asarray(fft.fftshift(a)), np.fft.fftshift(np.arange(8.0)))
    np.testing.assert_allclose(
        np.asarray(fft.ifftshift(fft.fftshift(a))), np.arange(8.0))


def test_validation():
    with pytest.raises(ValueError, match="[Nn]orm"):
        fft.fft(jnp.ones(4), norm="bogus")
    with pytest.raises(ValueError, match="positive"):
        fft.fft(jnp.ones(4), n=0)
    with pytest.raises(ValueError, match="positive"):
        fft.fft2(jnp.ones((4, 4)), s=(0, 4))
