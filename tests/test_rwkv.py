"""RWKV linear-recurrence family (BASELINE.json "Mamba-2 / RWKV").

The associative-scan WKV must match the naive sequential recurrence (the
reference CUDA kernel's math) and the model must train.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.rwkv import (
    RWKVConfig,
    RWKVForCausalLM,
    wkv_associative,
    wkv_reference,
)


@pytest.mark.parametrize("seed,shape", [(0, (2, 16, 8)), (1, (1, 33, 4))])
def test_wkv_matches_sequential_reference(seed, shape):
    rng = np.random.default_rng(seed)
    b, s, d = shape
    k = jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)
    w = jnp.asarray(np.abs(rng.standard_normal(d)) + 0.1, jnp.float32)
    u = jnp.asarray(rng.standard_normal(d) * 0.3, jnp.float32)
    out = wkv_associative(k, v, w, u)
    ref = wkv_reference(k, v, w, u)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_wkv_numerical_stability_large_k():
    """Huge k magnitudes must not overflow (running-max stabilization)."""
    k = jnp.asarray([[[80.0], [-90.0], [85.0], [0.0]]], jnp.float32)
    v = jnp.ones((1, 4, 1), jnp.float32)
    out = wkv_associative(k, v, jnp.asarray([0.5]), jnp.asarray([0.2]))
    assert bool(jnp.all(jnp.isfinite(out)))
    ref = wkv_reference(k, v, np.asarray([0.5]), np.asarray([0.2]))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_wkv_grads_finite():
    rng = np.random.default_rng(3)
    k = jnp.asarray(rng.standard_normal((1, 8, 4)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 8, 4)), jnp.float32)
    w = jnp.asarray(np.abs(rng.standard_normal(4)) + 0.1, jnp.float32)
    u = jnp.asarray(rng.standard_normal(4) * 0.3, jnp.float32)
    g = jax.grad(lambda *a: jnp.sum(wkv_associative(*a) ** 2),
                 argnums=(0, 1, 2, 3))(k, v, w, u)
    for x in g:
        assert bool(jnp.all(jnp.isfinite(x)))
        assert float(jnp.abs(x).max()) > 0


def test_rwkv_model_trains():
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.trainer import TrainStep

    pt.seed(0)
    cfg = RWKVConfig.tiny()
    model = RWKVForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 24)))
    mesh = dist.build_mesh()
    ts = TrainStep(model, opt.AdamW(learning_rate=3e-3), mesh)
    losses = [float(ts.run({"input_ids": ids, "labels": ids}))
              for _ in range(6)]
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(l) for l in losses)
