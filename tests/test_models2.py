"""UNet + ERNIE-MoE model tests."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.core.functional import extract_params, functional_call
from paddle_tpu.models import (
    ErnieMoEConfig,
    ErnieMoEForCausalLM,
    UNet2DConditionModel,
    UNetConfig,
)


def test_unet_forward_and_grads():
    pt.seed(0)
    cfg = UNetConfig.tiny()
    net = UNet2DConditionModel(cfg)
    rng = np.random.default_rng(0)
    sample = jnp.asarray(rng.standard_normal((2, 4, 16, 16)), jnp.float32)
    t = jnp.asarray([1, 500])
    ctx = jnp.asarray(rng.standard_normal((2, 8, 32)), jnp.float32)
    out = net(sample, t, ctx)
    assert out.shape == (2, 4, 16, 16)
    params = extract_params(net)

    def loss(p):
        noise_pred = functional_call(net, p, sample, t, ctx)
        return jnp.mean((noise_pred - sample) ** 2)

    lv, grads = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(lv))
    nonzero = sum(
        float(jnp.sum(jnp.abs(g))) > 0 for g in grads.values()
    )
    assert nonzero > len(grads) * 0.9


def test_ernie_moe_trains_and_routes():
    pt.seed(1)
    cfg = ErnieMoEConfig.tiny(
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        use_flash_attention=False,
    )
    model = ErnieMoEForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(2).integers(0, 256, (4, 16)))
    params = extract_params(model)
    from paddle_tpu import optimizer as opt

    o = opt.AdamW(3e-3, multi_precision=False)
    state = o.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(
            lambda p: functional_call(model, p, ids, labels=ids)
        )(params)
        params, state = o.update(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(12):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # expert weights get gradients (routing is live)
    g = jax.grad(
        lambda p: functional_call(model, p, ids, labels=ids)
    )(params)
    assert float(jnp.sum(jnp.abs(g["blocks.0.moe.experts.w1"]))) > 0
