"""BERT/ERNIE encoder family (parity: paddlenlp bert/ernie modeling
tests — shapes, padding-mask equivalence, MLM ignore_index, training)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.bert import (
    BertConfig,
    BertForMaskedLM,
    BertForSequenceClassification,
    BertModel,
    ErnieModel,
)


def test_bert_forward_shapes():
    pt.seed(0)
    cfg = BertConfig.tiny()
    model = BertModel(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 16)))
    seq_out, pooled = model(ids)
    assert seq_out.shape == (2, 16, cfg.hidden_size)
    assert pooled.shape == (2, cfg.hidden_size)
    assert ErnieModel is BertModel  # paddle-named surface


def test_bert_padding_mask_matches_truncation():
    """A sequence padded + masked must produce the same token outputs as
    the unpadded sequence (the flash segment path and the dense mask
    path must both get this right)."""
    pt.seed(1)
    cfg = BertConfig.tiny()
    model = BertModel(cfg)
    model.eval()
    rng = np.random.default_rng(1)
    real = rng.integers(1, 256, (1, 10))
    ids_short = jnp.asarray(real)
    out_short, _ = model(ids_short)

    padded = np.zeros((1, 16), np.int64)
    padded[0, :10] = real
    mask = np.zeros((1, 16), np.int64)
    mask[0, :10] = 1
    out_pad, _ = model(jnp.asarray(padded),
                       attention_mask=jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(out_pad[0, :10]), np.asarray(out_short[0]),
        rtol=2e-4, atol=2e-4)


def test_bert_sequence_classification_trains():
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.trainer import TrainStep

    pt.seed(2)
    cfg = BertConfig.tiny(num_labels=3)
    model = BertForSequenceClassification(cfg)
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, 256, (4, 16)))
    labels = jnp.asarray(rng.integers(0, 3, (4,)))
    ts = TrainStep(model, opt.AdamW(learning_rate=1e-3),
                   dist.build_mesh())
    losses = [float(ts.run({"input_ids": ids, "labels": labels}))
              for _ in range(6)]
    assert losses[-1] < losses[0], losses


def test_bert_masked_lm_ignore_index():
    pt.seed(3)
    cfg = BertConfig.tiny()
    model = BertForMaskedLM(cfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, 256, (2, 16)))
    # only two positions carry labels; the rest are ignored
    labels = np.full((2, 16), -100, np.int64)
    labels[0, 3] = 7
    labels[1, 9] = 42
    loss = model(ids, labels=jnp.asarray(labels))
    assert np.isfinite(float(loss))
    # loss over all-ignored labels is defined (0-valid guard)
    loss0 = model(ids, labels=jnp.asarray(np.full((2, 16), -100)))
    assert np.isfinite(float(loss0))
    # logits head ties the embedding matrix
    logits = model(ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
