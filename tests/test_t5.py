"""T5 encoder-decoder tests (parity model: PaddleNLP
tests/transformers/t5/test_modeling.py — shape/grad/decode behavior +
the reference bucket function checked against the published algorithm)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.functional import extract_params, functional_call
from paddle_tpu.models import T5Config, T5ForConditionalGeneration, T5Model
from paddle_tpu.models.t5 import _relative_position_bucket


def _torch_t5_bucket(relative_position, bidirectional, num_buckets,
                     max_distance):
    """The published T5 bucket algorithm, re-stated in numpy as an
    independent oracle."""
    import numpy as np

    rp = relative_position.astype(np.int64)
    ret = np.zeros_like(rp)
    if bidirectional:
        num_buckets //= 2
        ret += (rp > 0).astype(np.int64) * num_buckets
        rp = np.abs(rp)
    else:
        rp = -np.minimum(rp, 0)
    max_exact = num_buckets // 2
    is_small = rp < max_exact
    large = max_exact + (
        np.log(np.maximum(rp, 1) / max_exact)
        / np.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(np.int64)
    large = np.minimum(large, num_buckets - 1)
    return ret + np.where(is_small, rp, large)


class TestT5:
    def test_bucket_function_matches_oracle(self):
        q = np.arange(40)[:, None]
        k = np.arange(40)[None, :]
        rel = k - q
        for bidir in (True, False):
            ours = np.asarray(_relative_position_bucket(
                jnp.asarray(rel), bidir, 32, 128))
            ref = _torch_t5_bucket(rel, bidir, 32, 128)
            np.testing.assert_array_equal(ours, ref)

    def test_forward_shapes_and_loss(self):
        pt.seed(0)
        cfg = T5Config.tiny()
        model = T5ForConditionalGeneration(cfg)
        rng = np.random.default_rng(0)
        src = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 12)))
        tgt = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 8)))
        logits = model(src, decoder_input_ids=tgt)
        assert logits.shape == (2, 8, cfg.vocab_size)
        loss = model(src, labels=tgt)
        assert np.isfinite(float(loss))

    def test_grads_and_training_step(self):
        pt.seed(0)
        cfg = T5Config.tiny()
        model = T5ForConditionalGeneration(cfg)
        rng = np.random.default_rng(1)
        src = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 12)))
        tgt = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 8)))
        params = extract_params(model)

        @jax.jit
        def loss_fn(p):
            return functional_call(model, p, src, labels=tgt)

        losses = []
        from paddle_tpu import optimizer as opt

        o = opt.AdamW(learning_rate=5e-3, multi_precision=False)
        state = o.init(params)
        grad_fn = jax.jit(jax.value_and_grad(loss_fn))
        for _ in range(8):
            loss, grads = grad_fn(params)
            params, state = o.update(grads, state, params)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_causality(self):
        """decoder position t output must not depend on future decoder
        inputs."""
        pt.seed(0)
        cfg = T5Config.tiny()
        model = T5Model(cfg)
        rng = np.random.default_rng(2)
        src = jnp.asarray(rng.integers(1, cfg.vocab_size, (1, 6)))
        tgt = jnp.asarray(rng.integers(1, cfg.vocab_size, (1, 8)))
        out1 = model(src, tgt)
        tgt2 = tgt.at[:, 5:].set(7)  # perturb the future
        out2 = model(src, tgt2)
        np.testing.assert_allclose(
            np.asarray(out1[:, :5]), np.asarray(out2[:, :5]),
            rtol=1e-4, atol=1e-5,
        )
        assert not np.allclose(np.asarray(out1[:, 5:]),
                               np.asarray(out2[:, 5:]))

    def test_encoder_padding_mask(self):
        """padded encoder positions must not change unmasked outputs."""
        pt.seed(0)
        cfg = T5Config.tiny(use_flash_attention=False)
        model = T5Model(cfg)
        rng = np.random.default_rng(3)
        src = jnp.asarray(rng.integers(1, cfg.vocab_size, (1, 8)))
        mask = jnp.asarray([[1, 1, 1, 1, 1, 0, 0, 0]])
        tgt = jnp.asarray(rng.integers(1, cfg.vocab_size, (1, 4)))
        out1 = model(src, tgt, attention_mask=mask)
        src2 = src.at[:, 5:].set(9)   # change only padded tokens
        out2 = model(src2, tgt, attention_mask=mask)
        np.testing.assert_allclose(
            np.asarray(out1), np.asarray(out2), rtol=1e-4, atol=1e-5
        )

    def test_generate_greedy(self):
        pt.seed(0)
        cfg = T5Config.tiny()
        model = T5ForConditionalGeneration(cfg)
        rng = np.random.default_rng(4)
        src = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 10)))
        out = model.generate(src, max_length=6)
        assert out.shape == (2, 6)
        assert (np.asarray(out[:, 0]) == cfg.decoder_start_token_id).all()
        # greedy scan == step-by-step recompute
        enc = model.t5.encode(src)
        buf = np.asarray(out)
        hidden = model.t5.decode(jnp.asarray(buf), enc)
        logits = model._logits(hidden)
        for t in range(5):
            nxt = np.argmax(np.asarray(logits[:, t]), axis=-1)
            np.testing.assert_array_equal(nxt, buf[:, t + 1])

    def test_gated_gelu_variant(self):
        pt.seed(0)
        cfg = T5Config.tiny(feed_forward_proj="gated-gelu",
                            tie_word_embeddings=False)
        model = T5ForConditionalGeneration(cfg)
        src = jnp.asarray(np.random.default_rng(5).integers(
            1, cfg.vocab_size, (2, 6)))
        loss = model(src, labels=src[:, :4])
        assert np.isfinite(float(loss))
        names = [n for n, _ in model.named_parameters()]
        assert any("wi_1" in n for n in names)
        assert any("lm_head" in n for n in names)

    def test_decoder_padding_mask(self):
        """padded decoder positions must not influence earlier real
        positions via self-attention (left-context is causal anyway, so
        check that changing pad CONTENT with the mask on is inert for
        positions the mask hides from cross/self attention)."""
        pt.seed(0)
        cfg = T5Config.tiny(use_flash_attention=False)
        model = T5Model(cfg)
        rng = np.random.default_rng(6)
        src = jnp.asarray(rng.integers(1, cfg.vocab_size, (1, 6)))
        tgt = jnp.asarray(rng.integers(1, cfg.vocab_size, (1, 8)))
        dmask = jnp.asarray([[1, 1, 1, 1, 1, 1, 0, 0]])
        out1 = model(src, tgt, decoder_attention_mask=dmask)
        tgt2 = tgt.at[:, 6:].set(3)   # change only masked positions
        out2 = model(src, tgt2, decoder_attention_mask=dmask)
        np.testing.assert_allclose(
            np.asarray(out1[:, :6]), np.asarray(out2[:, :6]),
            rtol=1e-4, atol=1e-5,
        )

    def test_cached_generate_matches_reference_path(self):
        """incremental KV-cache decode == full-recompute decode, token
        for token (bias rows, cache masks, cross K/V all must agree)."""
        pt.seed(0)
        cfg = T5Config.tiny(num_layers=3, vocab_size=64)
        model = T5ForConditionalGeneration(cfg)
        model.eval()
        rng = np.random.default_rng(7)
        src = jnp.asarray(rng.integers(1, cfg.vocab_size, (3, 9)))
        slow = np.asarray(model.generate(src, max_length=8,
                                         use_cache=False))
        fast = np.asarray(model.generate(src, max_length=8,
                                         use_cache=True))
        np.testing.assert_array_equal(slow, fast)

    def test_cached_generate_with_encoder_mask(self):
        pt.seed(0)
        cfg = T5Config.tiny(num_layers=2, vocab_size=64,
                            use_flash_attention=False)
        model = T5ForConditionalGeneration(cfg)
        model.eval()
        rng = np.random.default_rng(8)
        src = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 8)))
        mask = jnp.asarray([[1] * 6 + [0] * 2, [1] * 8])
        slow = np.asarray(model.generate(src, max_length=6,
                                         attention_mask=mask,
                                         use_cache=False))
        fast = np.asarray(model.generate(src, max_length=6,
                                         attention_mask=mask,
                                         use_cache=True))
        np.testing.assert_array_equal(slow, fast)
