"""Pipeline parallel tests on the virtual 8-device mesh: pipelined
forward/backward must equal the sequential reference (parity model:
fleet PP tests comparing pipeline vs single-card runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import distributed as dist, nn
from paddle_tpu.distributed.pipeline import LayerDesc, PipelineLayer, pipeline_apply
from paddle_tpu.distributed.sharding import mesh_context


def test_pipeline_apply_matches_sequential():
    mesh = dist.build_mesh(pp=4)
    pp = 4
    rng = np.random.default_rng(0)
    # one linear stage per pp rank: y = tanh(x @ w)
    ws = jnp.asarray(rng.standard_normal((pp, 8, 8)) * 0.5, jnp.float32)
    x = jnp.asarray(rng.standard_normal((4, 2, 8)), jnp.float32)  # 4 micro

    def stage_fn(w, mb):
        return jnp.tanh(mb @ w)

    ys = pipeline_apply(
        stage_fn, ws, x, mesh=mesh, n_micro=4,
    )
    # sequential reference
    ref = x
    for i in range(pp):
        ref = jnp.tanh(ref @ ws[i])
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_pipeline_apply_grads_match():
    mesh = dist.build_mesh(pp=4)
    pp = 4
    rng = np.random.default_rng(1)
    ws = jnp.asarray(rng.standard_normal((pp, 8, 8)) * 0.5, jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 2, 8)), jnp.float32)

    def stage_fn(w, mb):
        return jnp.tanh(mb @ w)

    def loss_pp(ws):
        y = pipeline_apply(stage_fn, ws, x, mesh=mesh, n_micro=2)
        return jnp.sum(y**2)

    def loss_seq(ws):
        ref = x
        for i in range(pp):
            ref = jnp.tanh(ref @ ws[i])
        return jnp.sum(ref**2)

    g_pp = jax.grad(loss_pp)(ws)
    g_seq = jax.grad(loss_seq)(ws)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_layer_matches_sequential():
    pt.seed(77)
    mesh = dist.build_mesh(pp=4)
    trunk = PipelineLayer(
        LayerDesc(nn.Linear, 16, 16), num_layers=8, num_stages=4
    )
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((4, 16)), jnp.float32
    )
    seq = trunk(x)  # no mesh → sequential scan
    with mesh_context(mesh):
        piped = jax.jit(
            lambda p, x: __import__(
                "paddle_tpu.core.functional", fromlist=["functional_call"]
            ).functional_call(trunk, p, x, n_micro=2, mesh=mesh)
        )(
            {n: v for n, v in
             __import__("paddle_tpu.core.functional",
                        fromlist=["extract_params"]).extract_params(trunk).items()},
            x,
        )
    np.testing.assert_allclose(
        np.asarray(piped), np.asarray(seq), rtol=1e-4, atol=1e-5
    )
