"""paddle.sparse parity tests (reference: test/legacy_test sparse op tests,
python/paddle/sparse/)."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import sparse


def _rand_coo(shape=(4, 5), nnz=6, seed=0):
    rng = np.random.default_rng(seed)
    flat = rng.choice(shape[0] * shape[1], size=nnz, replace=False)
    rows, cols = np.unravel_index(flat, shape)
    indices = np.stack([rows, cols]).astype(np.int32)
    values = rng.standard_normal(nnz).astype(np.float32)
    return indices, values


def test_coo_roundtrip():
    indices, values = _rand_coo()
    s = sparse.sparse_coo_tensor(indices, values, shape=(4, 5))
    assert sparse.is_sparse(s) and sparse.is_sparse_coo(s)
    d = sparse.to_dense(s)
    ref = np.zeros((4, 5), np.float32)
    ref[indices[0], indices[1]] = values
    np.testing.assert_allclose(np.asarray(d), ref, rtol=1e-6)
    # dense -> coo -> dense
    s2 = sparse.to_sparse_coo(ref)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(s2)), ref)
    assert sparse.nnz(s) == 6


def test_coo_infers_shape():
    s = sparse.sparse_coo_tensor([[0, 2], [1, 3]], [1.0, 2.0])
    assert s.shape == (3, 4)


def test_csr_roundtrip():
    crows = [0, 2, 3, 3]
    cols = [1, 3, 2]
    values = [1.0, 2.0, 3.0]
    s = sparse.sparse_csr_tensor(crows, cols, values, shape=(3, 4))
    assert sparse.is_sparse_csr(s)
    ref = np.array([[0, 1, 0, 2], [0, 0, 3, 0], [0, 0, 0, 0]], np.float32)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(s)), ref)
    coo = sparse.to_sparse_coo(s)
    assert sparse.is_sparse_coo(coo)
    back = sparse.to_sparse_csr(coo)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(back)), ref)


def test_elementwise_and_scalar_ops():
    ia, va = _rand_coo(seed=1)
    ib, vb = _rand_coo(seed=2)
    a = sparse.sparse_coo_tensor(ia, va, shape=(4, 5))
    b = sparse.sparse_coo_tensor(ib, vb, shape=(4, 5))
    da, db = np.asarray(sparse.to_dense(a)), np.asarray(sparse.to_dense(b))
    np.testing.assert_allclose(
        np.asarray(sparse.to_dense(sparse.add(a, b))), da + db, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(sparse.to_dense(sparse.subtract(a, b))), da - db,
        rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(sparse.to_dense(sparse.divide(a, 2.0))), da / 2.0,
        rtol=1e-6)


def test_matmul_sparse_dense():
    ia, va = _rand_coo(seed=3)
    a = sparse.sparse_coo_tensor(ia, va, shape=(4, 5))
    x = np.random.default_rng(0).standard_normal((5, 3)).astype(np.float32)
    out = sparse.matmul(a, x)
    ref = np.asarray(sparse.to_dense(a)) @ x
    np.testing.assert_allclose(np.asarray(sparse.to_dense(out)), ref,
                               rtol=1e-5, atol=1e-5)


def test_masked_matmul_sddmm():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((4, 6)).astype(np.float32)
    y = rng.standard_normal((6, 5)).astype(np.float32)
    im, vm = _rand_coo(seed=5)
    mask = sparse.sparse_coo_tensor(im, np.ones_like(vm), shape=(4, 5))
    out = sparse.masked_matmul(x, y, mask)
    full = x @ y
    ref = np.zeros((4, 5), np.float32)
    ref[im[0], im[1]] = full[im[0], im[1]]
    np.testing.assert_allclose(np.asarray(sparse.to_dense(out)), ref,
                               rtol=1e-5, atol=1e-5)


def test_transpose_and_relu():
    ia, va = _rand_coo(seed=6)
    a = sparse.sparse_coo_tensor(ia, va, shape=(4, 5))
    t = sparse.transpose(a, [1, 0])
    np.testing.assert_allclose(
        np.asarray(sparse.to_dense(t)),
        np.asarray(sparse.to_dense(a)).T, rtol=1e-6)
    r = sparse.relu(a)
    np.testing.assert_allclose(
        np.asarray(sparse.to_dense(r)),
        np.maximum(np.asarray(sparse.to_dense(a)), 0), rtol=1e-6)


def test_coalesce_sums_duplicates():
    s = sparse.sparse_coo_tensor([[0, 0, 1], [1, 1, 2]], [1.0, 2.0, 3.0],
                                 shape=(2, 3))
    c = sparse.coalesce(s)
    d = np.asarray(sparse.to_dense(c))
    assert d[0, 1] == pytest.approx(3.0)
    assert d[1, 2] == pytest.approx(3.0)
    assert sparse.nnz(c) == 2  # padded slots are not counted


def test_softmax_3d_normalizes_last_axis_only():
    dense = np.zeros((1, 2, 2), np.float32)
    dense[0, 0] = [1.0, 2.0]
    dense[0, 1] = [3.0, 4.0]
    s = sparse.to_sparse_coo(dense)
    out = np.asarray(sparse.to_dense(sparse.nn.Softmax()(s)))
    np.testing.assert_allclose(out[0, 0].sum(), 1.0, rtol=1e-6)
    np.testing.assert_allclose(out[0, 1].sum(), 1.0, rtol=1e-6)


def test_sparse_batchnorm_jit_and_state_dict():
    import jax

    dense = np.zeros((6, 4), np.float32)
    dense[[0, 2, 5]] = np.random.default_rng(1).standard_normal(
        (3, 4)).astype(np.float32)
    s = sparse.to_sparse_coo(dense, sparse_dim=1)
    bn = sparse.nn.BatchNorm(4)
    # jitted training call must not leak tracers into running stats
    jax.jit(lambda t: bn(t).data)(s)
    bn.eval()
    bn(s)  # would raise UnexpectedTracerError before the fix
    # running stats live in state_dict
    assert "_mean" in bn.state_dict() and "_variance" in bn.state_dict()


def test_sparse_nn_layers():
    ia, va = _rand_coo(seed=7)
    a = sparse.sparse_coo_tensor(ia, va, shape=(4, 5))
    da = np.asarray(sparse.to_dense(a))

    relu = sparse.nn.ReLU()
    np.testing.assert_allclose(np.asarray(sparse.to_dense(relu(a))),
                               np.maximum(da, 0), rtol=1e-6)

    leaky = sparse.nn.LeakyReLU(0.1)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(leaky(a))),
                               np.where(da > 0, da, 0.1 * da),
                               rtol=1e-5, atol=1e-6)


def test_sparse_softmax_rows():
    # one fully-stored row → softmax over stored entries must sum to 1
    s = sparse.sparse_coo_tensor([[0, 0, 1], [0, 1, 2]], [1.0, 2.0, 5.0],
                                 shape=(2, 3))
    out = sparse.nn.Softmax()(s)
    d = np.asarray(sparse.to_dense(out))
    np.testing.assert_allclose(d[0].sum(), 1.0, rtol=1e-6)
    ref0 = np.exp([1.0, 2.0]) / np.exp([1.0, 2.0]).sum()
    np.testing.assert_allclose(d[0, :2], ref0, rtol=1e-6)
    np.testing.assert_allclose(d[1, 2], 1.0, rtol=1e-6)


def test_relu_on_uncoalesced_matches_dense_semantics():
    # duplicate index (0,1): stored 2.0 and -3.0 → dense value -1 → relu 0
    s = sparse.sparse_coo_tensor([[0, 0], [1, 1]], [2.0, -3.0], shape=(2, 3))
    d = np.asarray(sparse.to_dense(sparse.relu(s)))
    assert d[0, 1] == pytest.approx(0.0)
    # softmax over duplicates: row 1 has entries 1+1 (dup) and 2 → equal
    s2 = sparse.sparse_coo_tensor([[1, 1, 1], [0, 0, 2]], [1.0, 1.0, 2.0],
                                  shape=(2, 3))
    d2 = np.asarray(sparse.to_dense(sparse.nn.Softmax()(s2)))
    np.testing.assert_allclose(d2[1, 0], 0.5, rtol=1e-6)
    np.testing.assert_allclose(d2[1, 2], 0.5, rtol=1e-6)


def test_empty_indices_require_shape():
    with pytest.raises(ValueError, match="shape must be given"):
        sparse.sparse_coo_tensor(np.zeros((2, 0)), np.zeros((0,)))
    s = sparse.sparse_coo_tensor(np.zeros((2, 0), np.int32),
                                 np.zeros((0,), np.float32), shape=(3, 4))
    np.testing.assert_allclose(np.asarray(sparse.to_dense(s)),
                               np.zeros((3, 4)))


def test_sparse_batchnorm_dense_channel():
    rng = np.random.default_rng(9)
    dense = np.zeros((6, 4), np.float32)
    rows = [0, 2, 5]
    dense[rows] = rng.standard_normal((3, 4)).astype(np.float32)
    s = sparse.to_sparse_coo(dense, sparse_dim=1)  # values [nnz, C]
    bn = sparse.nn.BatchNorm(4)
    out = bn(s)
    v = np.asarray(out.data)
    kept = v[np.any(v != 0, axis=1)]
    np.testing.assert_allclose(kept.mean(axis=0), 0.0, atol=1e-5)
    # eval mode uses running stats, not batch stats
    bn.eval()
    out2 = np.asarray(bn(s).data)
    assert not np.allclose(out2, v)
    # wrong layout (no dense channel) → clear error
    flat = sparse.sparse_coo_tensor([[0], [1]], [1.0], shape=(2, 3))
    with pytest.raises(ValueError, match="trailing dense channel"):
        sparse.nn.BatchNorm(4)(flat)


def test_sparse_under_jit():
    import jax

    ia, va = _rand_coo(seed=8)
    a = sparse.sparse_coo_tensor(ia, va, shape=(4, 5))
    x = jnp.ones((5, 2), jnp.float32)

    @jax.jit
    def f(s, x):
        return sparse.to_dense(sparse.matmul(s, x))

    out = f(a, x)
    ref = np.asarray(sparse.to_dense(a)) @ np.asarray(x)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_masked_matmul_duplicate_mask_indices():
    mask = sparse.sparse_coo_tensor([[0, 0], [1, 1]], [1.0, 1.0],
                                    shape=(2, 2))
    out = sparse.masked_matmul(np.ones((2, 3), np.float32),
                               np.ones((3, 2), np.float32), mask)
    d = np.asarray(sparse.to_dense(out))
    assert d[0, 1] == pytest.approx(3.0)  # not doubled


def test_nnz_csr_after_duplicated_coo():
    d = sparse.sparse_coo_tensor([[0, 0, 1], [1, 1, 2]], [1.0, 2.0, 3.0],
                                 shape=(2, 3))
    assert sparse.nnz(sparse.to_sparse_csr(d)) == 2


def test_batchnorm_stats_ignore_padded_slots():
    dense = np.zeros((4, 2), np.float32)
    dense[[0, 2]] = [[0.4, 0.6], [0.4, 0.6]]
    s = sparse.to_sparse_coo(dense, sparse_dim=1)
    x = sparse.add(s, s)  # creates duplicate indices → coalesce pads
    bn = sparse.nn.BatchNorm(2, momentum=0.0)
    bn(x)
    np.testing.assert_allclose(np.asarray(bn._buffers["_mean"]),
                               [0.8, 1.2], rtol=1e-5)
