"""Layer system tests (parity model: upstream test/legacy_test layer
tests + OpTest-style numpy cross-checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.core.functional import extract_params, functional_call

# core-engine fast lane (see README "Tests")
pytestmark = pytest.mark.fast


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def test_parameter_registration():
    m = MLP()
    names = dict(m.named_parameters())
    assert set(names) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
    assert names["fc1.weight"].shape == (8, 16)
    assert len(m.parameters()) == 4
    assert len(m.sublayers()) == 3


def test_forward_matches_numpy():
    m = MLP()
    x = np.random.randn(3, 8).astype(np.float32)
    y = m(jnp.asarray(x))
    w1 = np.asarray(m.fc1.weight.value)
    b1 = np.asarray(m.fc1.bias.value)
    w2 = np.asarray(m.fc2.weight.value)
    b2 = np.asarray(m.fc2.bias.value)
    ref = np.maximum(x @ w1 + b1, 0) @ w2 + b2
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


def test_state_dict_roundtrip():
    m1, m2 = MLP(), MLP()
    sd = m1.state_dict()
    assert set(sd) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
    missing, unexpected = m2.set_state_dict(sd)
    assert not missing and not unexpected
    x = jnp.ones((2, 8))
    np.testing.assert_allclose(
        np.asarray(m1(x)), np.asarray(m2(x)), rtol=1e-6
    )


def test_functional_call_pure():
    m = MLP()
    params = extract_params(m)
    x = jnp.ones((2, 8))
    eager = m(x)
    fn = jax.jit(lambda p, x: functional_call(m, p, x))
    jitted = fn(params, x)
    np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted), rtol=1e-5, atol=1e-6)
    # grads flow
    g = jax.grad(lambda p: functional_call(m, p, x).sum())(params)
    assert set(g) == set(params)
    assert g["fc1.weight"].shape == (8, 16)


def test_hooks():
    m = MLP()
    calls = []
    h1 = m.register_forward_pre_hook(lambda layer, args: calls.append("pre"))
    h2 = m.register_forward_post_hook(
        lambda layer, args, out: calls.append("post")
    )
    m(jnp.ones((1, 8)))
    assert calls == ["pre", "post"]
    h1.remove()
    h2.remove()
    calls.clear()
    m(jnp.ones((1, 8)))
    assert calls == []


def test_train_eval_mode_dropout():
    drop = nn.Dropout(0.5)
    x = jnp.ones((100, 100))
    pt.seed(0)
    y = drop(x)
    assert float(jnp.mean((np.asarray(y) == 0))) > 0.3
    drop.eval()
    y2 = drop(x)
    np.testing.assert_array_equal(np.asarray(y2), np.asarray(x))


def test_to_dtype_cast():
    m = MLP()
    m.to(pt.bfloat16)
    assert m.fc1.weight.dtype == jnp.bfloat16
    y = m(jnp.ones((2, 8), jnp.bfloat16))
    assert y.dtype == jnp.bfloat16


def test_buffers():
    class WithBuf(nn.Layer):
        def __init__(self):
            super().__init__()
            self.register_buffer("running", jnp.zeros((3,)))
            self.register_buffer("tmp", jnp.ones((2,)), persistable=False)

        def forward(self, x):
            return x + self.running[0]

    m = WithBuf()
    sd = m.state_dict()
    assert "running" in sd and "tmp" not in sd


def test_layerlist_sequential():
    seq = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
    assert len(seq) == 3
    y = seq(jnp.ones((1, 4)))
    assert y.shape == (1, 2)
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(list(ll.parameters())) == 8
