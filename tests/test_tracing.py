"""Per-request lifecycle tracing, SLO accounting, cancel, and the
serving front-end's honest readiness — the observability contract this
PR adds on top of PR 2's aggregate telemetry.

Under test:
  - lifecycle spans reconstruct a request's queued → admitted →
    prefill → decode/verify → finish timeline EXACTLY (token counts
    match the engine's host-side state) in BOTH cache modes × spec
    decode on/off;
  - the exported trace is valid Chrome trace-event JSON (Perfetto-
    loadable shape: ph/ts/dur/pid/tid on every event);
  - ``PT_FLAGS_telemetry=off`` leaves the engine with NO tracer and no
    telemetry objects — every hook site is a single identity check;
  - ``PT_FLAGS_trace_sample`` thins deterministically (a sampled
    request's events are complete, never a torn subset);
  - tracing + SLO accounting add ZERO compiled programs to the PR-5
    program set (the whole layer is host-side);
  - SLO attainment (met/violated/goodput) lands in slo_snapshot, the
    unified metrics_snapshot, and the registry counters;
  - ``cancel()`` frees the slot, paged KV pages and prefix-cache refs
    leak-free, queued or mid-flight;
  - ``/healthz`` returns 503 while admission is saturated; ``/trace``
    serves the tracer; flight-recorder dumps attach the trace tail.
"""

import json
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags as F
from paddle_tpu import observability as obs
from paddle_tpu.inference.serving import (
    ContinuousBatchingEngine,
    EngineConfig,
    start_metrics_server,
)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import tracing

pytestmark = pytest.mark.fast


def _model(seed=0):
    pt.seed(seed)
    cfg = LlamaConfig.tiny()
    return LlamaForCausalLM(cfg), cfg


def _ecfg(paged, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 128)
    kw.setdefault("seq_buckets", (32,))
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("page_size", 8)
    return EngineConfig(paged=paged, **kw)


def _drain(eng, step=None):
    step = step or eng.step
    while step() or eng._queue or eng.active.any():
        pass


@pytest.fixture
def obs_flags():
    """set_flags with restore for the flags this file flips (telemetry
    defaults OFF in conftest — tracing tests turn it on explicitly)."""
    keys = ("telemetry", "trace_sample", "trace_buffer", "spec_decode",
            "prefix_cache", "prefill_chunk")
    saved = {k: F.flag(k) for k in keys}
    yield F.set_flags
    F.set_flags(saved)


def _validate_chrome(doc):
    """Minimal Chrome trace-event JSON schema check (the shape
    Perfetto / chrome://tracing loads)."""
    assert isinstance(doc, dict) and isinstance(doc["traceEvents"], list)
    json.loads(json.dumps(doc))  # fully JSON-serializable
    for e in doc["traceEvents"]:
        assert isinstance(e["name"], str) and e["name"]
        assert e["ph"] in ("X", "i", "M")
        assert isinstance(e["pid"], int)
        assert isinstance(e["tid"], int)
        if e["ph"] != "M":
            assert isinstance(e["ts"], float) and e["ts"] >= 0.0
        if e["ph"] == "X":
            assert e["dur"] >= 0.0
        if e["ph"] == "i":
            assert e["s"] in ("t", "p", "g")


# ---------------- lifecycle reconstruction ----------------

@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("spec", ["off", "ngram"])
def test_lifecycle_trace_reconstructs(paged, spec, obs_flags):
    """The exported spans reconstruct each request's admit → prefill →
    decode/verify → finish timeline exactly: token counts derived from
    the trace equal the engine's own host-side state, in both cache
    modes with spec decode on and off."""
    model, cfg = _model(1)
    obs_flags({"telemetry": True, "trace_sample": 1.0,
               "spec_decode": spec})
    eng = ContinuousBatchingEngine(model, _ecfg(paged))
    rng = np.random.default_rng(2)
    unit = rng.integers(1, cfg.vocab_size, 4)
    prompts = [np.concatenate([unit] * 5),
               rng.integers(1, cfg.vocab_size, 9),
               rng.integers(1, cfg.vocab_size, 17)]
    rids = [eng.add_request(p, max_new_tokens=10) for p in prompts]
    _drain(eng)

    tr = eng._tracer
    assert tr is not None
    _validate_chrome(tracing.chrome_trace([tr]))

    raw = tr.events()
    steps = [e for e in raw if e["kind"] == "step"]
    assert {e["name"] for e in steps} >= {"prefill_chunk", "decode"} \
        if spec == "off" else True
    for rid in rids:
        req = eng._finished[rid]
        mine = [e for e in raw if e.get("rid") == rid]
        names = [e["name"] for e in mine]
        assert names.count("queued") == 1
        assert names.count("admitted") == 1
        assert names.count("active") == 1
        assert "prefill_chunk" in names  # chunked admission default
        admitted = next(e for e in mine if e["name"] == "admitted")
        active = next(e for e in mine if e["name"] == "active")
        # spans: queued..admitted covers TTFT; admitted..finish covers
        # decode; they tile the request's life in order
        assert admitted["t1"] is not None and active["t1"] is not None
        assert admitted["t0"] <= admitted["t1"] <= active["t0"] \
            <= active["t1"]
        assert admitted["args"]["first_tokens"] == 1
        assert admitted["args"]["prompt_tokens"] == \
            eng._finished[rid].prompt.size
        # EXACT reconstruction: prefill's first token + every step
        # event's per-request advancement == the tokens the engine
        # actually emitted
        advanced = sum(
            e["args"]["advanced"].get(rid, 0) for e in steps
            if "advanced" in e["args"])
        assert 1 + advanced == len(req.output)
        assert active["args"]["tokens"] == len(req.output)
        assert active["args"]["reason"] == "max_new_tokens"
    if spec == "ngram" and eng.spec_stats["verify_calls"] > 0:
        verifies = [e for e in steps if e["name"] == "verify"]
        assert len(verifies) == eng.spec_stats["verify_calls"]
        assert sum(e["args"]["proposed"] for e in verifies) == \
            eng.spec_stats["proposed"]
        assert sum(e["args"]["accepted"] for e in verifies) == \
            eng.spec_stats["accepted"]
    # step composition fields are present on every sampled decode step
    for e in steps:
        if e["name"] in ("decode", "decode_chunk", "verify"):
            assert 0 < e["args"]["occupancy"] <= 1.0
            assert e["args"]["chunk_budget_spent"] >= 1
            assert e["args"]["dispatch_ms"] >= 0
            # profiler off (this file's default): the honest fallback
            # estimate — host wall dispatch-done -> token sync (the
            # field PR 6 called device_wall_ms_est; renamed because it
            # is a host-wall upper bound, not a device measurement)
            assert e["args"]["sync_wall_ms"] >= 0
            assert "device_ms" not in e["args"]


def test_chunked_scheduler_trace_and_jsonl(obs_flags):
    """step_chunk drives produce decode_chunk step events; the JSONL
    export round-trips every raw event."""
    model, cfg = _model(2)
    obs_flags({"telemetry": True})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    rng = np.random.default_rng(0)
    eng.run([rng.integers(1, cfg.vocab_size, 8) for _ in range(3)],
            max_new_tokens=6, max_chunk=4)
    raw = eng._tracer.events()
    chunks = [e for e in raw if e["name"] == "decode_chunk"]
    assert chunks and all(e["args"]["chunk_budget_spent"] == 4
                          for e in chunks)
    lines = tracing.jsonl([eng._tracer]).splitlines()
    assert len(lines) == len(raw)
    ts = [json.loads(l)["t0"] for l in lines]
    assert ts == sorted(ts)


# ---------------- off-switch + sampling ----------------

def test_telemetry_off_is_noop():
    """conftest default: PT_FLAGS_telemetry=off — the engine holds no
    tracer and no telemetry, and serving works untouched."""
    assert not obs.enabled()
    model, cfg = _model(3)
    before = set(map(id, tracing.all_tracers()))
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    assert eng._tracer is None and eng._tel is None
    reqs = eng.run([np.arange(1, 9)], max_new_tokens=4)
    assert len(reqs[0].output) == 4
    after = set(map(id, tracing.all_tracers()))
    assert after <= before  # no tracer was registered


def test_trace_sample_zero_disables_tracer(obs_flags):
    obs_flags({"telemetry": True, "trace_sample": 0.0})
    model, _ = _model(3)
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    assert eng._tel is not None and eng._tracer is None


def test_trace_sample_thins_deterministically(obs_flags):
    """rate 0.5 → every 2nd request id is traced COMPLETELY; the
    others leave no events at all (never a torn subset)."""
    obs_flags({"telemetry": True, "trace_sample": 0.5})
    model, cfg = _model(4)
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    assert eng._tracer.period == 2
    rng = np.random.default_rng(1)
    rids = [eng.add_request(rng.integers(1, cfg.vocab_size, 8),
                            max_new_tokens=3) for _ in range(4)]
    _drain(eng)
    raw = eng._tracer.events()
    traced = {e["rid"] for e in raw if e["kind"] == "request"}
    assert traced == {r for r in rids if r % 2 == 0}
    for rid in traced:
        names = [e["name"] for e in raw if e.get("rid") == rid]
        assert {"queued", "admitted", "active"} <= set(names)


def test_trace_ring_bounded(obs_flags):
    obs_flags({"telemetry": True, "trace_buffer": 8})
    model, cfg = _model(4)
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    rng = np.random.default_rng(2)
    eng.run([rng.integers(1, cfg.vocab_size, 8) for _ in range(4)],
            max_new_tokens=8)
    assert len(eng._tracer) <= 8  # old events fell off, no growth


# ---------------- compile-count guard ----------------

def test_tracing_and_slo_add_zero_compiled_programs(compile_counter,
                                                    obs_flags):
    """The whole observability layer is host-side: an engine with
    telemetry + tracing + SLO accounting + a mid-flight cancel compiles
    EXACTLY the same program set as the telemetry-off PR-5 engine."""
    model, cfg = _model(5)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, cfg.vocab_size, n) for n in (7, 13, 19)]

    eng = ContinuousBatchingEngine(model, _ecfg(True))
    eng.run(prompts, max_new_tokens=8, max_chunk=4)
    off_set = compile_counter()
    assert off_set == {"prefill_chunk": 1, "decode_chunk": 1}

    obs_flags({"telemetry": True, "trace_sample": 1.0})
    eng2 = ContinuousBatchingEngine(model, _ecfg(True))
    rids = [eng2.add_request(p, max_new_tokens=8, slo="interactive")
            for p in prompts]
    eng2.step_chunk(4)
    eng2.cancel(rids[-1])
    while eng2.step_chunk(4) or eng2._queue or eng2.active.any():
        pass
    assert eng2._tracer is not None and len(eng2._tracer) > 0
    assert eng2.slo_snapshot()["met"] + eng2.slo_snapshot()["violated"] \
        >= 2
    on_set = compile_counter()
    delta = {k: on_set[k] - off_set.get(k, 0) for k in on_set
             if on_set[k] - off_set.get(k, 0)}
    # the second engine re-specializes its OWN two programs (fresh jit
    # closures per engine) and nothing else: tracing/SLO/cancel added
    # zero programs
    assert delta == off_set


# ---------------- SLO accounting ----------------

def test_slo_met_and_violated(obs_flags):
    obs_flags({"telemetry": True})
    model, cfg = _model(6)
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    rng = np.random.default_rng(4)
    # impossible targets → violated; absurdly generous → met
    r_bad = eng.add_request(rng.integers(1, cfg.vocab_size, 8),
                            max_new_tokens=3, slo="interactive",
                            ttft_target_ms=1e-6, tpot_target_ms=1e-6)
    r_good = eng.add_request(rng.integers(1, cfg.vocab_size, 8),
                             max_new_tokens=3, slo="interactive",
                             ttft_target_ms=1e9, tpot_target_ms=1e9)
    _drain(eng)
    snap = eng.slo_snapshot()
    cls = snap["classes"]["interactive"]
    assert cls["met"] == 1 and cls["violated"] == 1
    assert cls["ttft_violations"] == 1
    assert snap["goodput"] == 0.5
    assert eng._finished[r_bad].slo_met is False
    assert eng._finished[r_good].slo_met is True
    assert eng._finished[r_good].tpot_ms > 0
    # registry counters + goodput gauge carry the slo label
    reg = obs.global_registry()
    lab = {"engine": eng._tel.engine_id, "slo": "interactive",
           "tenant": "-"}
    assert reg.get("pt_serve_slo_met_total").value(**lab) == 1
    assert reg.get("pt_serve_slo_violated_total").value(**lab) == 1
    assert reg.get("pt_serve_slo_goodput").value(**lab) == 0.5
    # unified document carries the same numbers
    m = eng.metrics_snapshot()
    assert m["slo"]["classes"]["interactive"]["met"] == 1
    assert m["request_tpot_ms"]["count"] == 2


def test_slo_class_defaults_and_validation():
    model, cfg = _model(6)
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    with pytest.raises(ValueError, match="slo"):
        eng.add_request(np.arange(1, 5), slo="platinum")
    with pytest.raises(ValueError, match="ttft_target_ms"):
        eng.add_request(np.arange(1, 5), slo="batch", ttft_target_ms=-1)
    rid = eng.add_request(np.arange(1, 5), max_new_tokens=2,
                          slo="batch")
    req = next(r for r in eng._queue if r.rid == rid)
    assert req.ttft_target_ms == 5000.0  # class default applied
    assert req.tpot_target_ms == 1000.0
    # bare targets imply the "custom" class
    rid2 = eng.add_request(np.arange(1, 5), max_new_tokens=2,
                           ttft_target_ms=1e9)
    req2 = next(r for r in eng._queue if r.rid == rid2)
    assert req2.slo == "custom" and req2.tpot_target_ms is None
    # a targetless "custom" would trivially always be met — rejected
    with pytest.raises(ValueError, match="custom"):
        eng.add_request(np.arange(1, 5), slo="custom")
    _drain(eng)
    snap = eng.slo_snapshot()
    assert set(snap["classes"]) == {"batch", "custom"}


def test_metrics_snapshot_unified_with_telemetry_off():
    """One document, no stitching: prefix/spec/SLO sub-snapshots ride
    metrics_snapshot even when the registry is off."""
    model, cfg = _model(7)
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    eng.run([np.arange(1, 10)], max_new_tokens=3)
    snap = eng.metrics_snapshot()
    assert snap["telemetry"] == "off"
    assert snap["prefix_cache"]["enabled"] is True
    assert snap["spec_decode"]["mode"] == "off"
    assert snap["slo"] == {"classes": {}, "met": 0, "violated": 0,
                           "goodput": None}
    assert snap["slots"]["max"] == 2


# ---------------- cancel ----------------

def test_cancel_queued_and_active_leak_free(obs_flags):
    """Cancel frees the slot, every paged KV page and the adopted
    prefix refs mid-flight; the pool is fully recoverable and the
    engine keeps serving."""
    obs_flags({"telemetry": True})
    model, cfg = _model(8)
    eng = ContinuousBatchingEngine(model, _ecfg(True, max_slots=2))
    free0 = eng.pool.free_pages
    rng = np.random.default_rng(5)
    shared = rng.integers(1, cfg.vocab_size, 16)  # two hash blocks
    mk = lambda: np.concatenate(  # noqa: E731
        [shared, rng.integers(1, cfg.vocab_size, 4)])
    rids = [eng.add_request(mk(), max_new_tokens=20) for _ in range(3)]
    eng.step()  # admit 2, third queues
    assert eng.cancel(rids[2])  # queued cancel
    eng.step()
    assert eng.cancel(rids[0])  # active cancel, mid-flight
    assert not eng.cancel(rids[0])  # idempotent: already gone
    assert not eng.cancel(10**9)  # unknown rid
    _drain(eng)
    for rid in rids:
        assert rid in eng._finished
    assert eng._finished[rids[2]].cancelled
    assert eng._finished[rids[2]].output == []  # never admitted
    assert eng._finished[rids[0]].cancelled
    assert eng._finished[rids[0]].finish_reason == "cancel"
    assert len(eng._finished[rids[1]].output) == 20  # survivor intact
    # cancel events in the trace
    raw = eng._tracer.events()
    cancels = [e for e in raw if e["name"] == "cancel"]
    assert {e["rid"] for e in cancels} == {rids[0], rids[2]}
    assert {e["args"]["stage"] for e in cancels} == {"queued", "active"}
    # leak-free: beyond store-retained prefix pages (evictable), the
    # pool fully recovers
    eng._evict_pages(10 ** 9)
    assert eng.pool.free_pages == free0
    assert not eng.pool.ref
    assert sorted(eng._free_heap) == [0, 1]
    # cancelled counter exported
    assert eng.metrics_snapshot()["requests"]["cancelled"] == 2
    # engine still serves after the churn
    assert len(eng.run([mk()], max_new_tokens=4)[0].output) == 4


def test_cancel_contiguous_mode():
    model, cfg = _model(8)
    eng = ContinuousBatchingEngine(model, _ecfg(False, max_slots=1))
    r0 = eng.add_request(np.arange(1, 9), max_new_tokens=30)
    r1 = eng.add_request(np.arange(1, 9), max_new_tokens=3)
    eng.step()
    assert eng.cancel(r0)  # active → slot frees for the queued r1
    _drain(eng)
    assert eng._finished[r0].cancelled
    assert len(eng._finished[r1].output) == 3


# ---------------- endpoints + recorder + dump ----------------

def test_healthz_backpressure_and_trace_endpoint(obs_flags):
    obs_flags({"telemetry": True})
    model, cfg = _model(9)
    eng = ContinuousBatchingEngine(model, _ecfg(False, max_slots=1))
    r0 = eng.add_request(np.arange(1, 9), max_new_tokens=40)
    r1 = eng.add_request(np.arange(1, 9), max_new_tokens=2)
    eng.step()  # r0 admitted, r1 waits: saturated
    bp = eng.backpressure()
    assert bp == {"queue_depth": 1, "free_slots": 0, "occupancy": 1.0,
                  "saturated": True, "draining": False,
                  "degraded": False, "degradation_level": 0}
    srv = start_metrics_server(eng, port=0)
    try:
        port = srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert ei.value.code == 503
        hz = json.loads(ei.value.read())
        assert hz["status"] == "saturated"
        assert hz["backpressure"]["queue_depth"] == 1
        # routers need the RUNG, not just the flag: the payload
        # carries the numeric ladder level alongside the degraded bit
        assert hz["degraded"] is False
        assert hz["degradation_level"] == 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace", timeout=10) as r:
            doc = json.loads(r.read())
        _validate_chrome(doc)
        _drain(eng)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert r.status == 200
            assert json.loads(r.read())["status"] == "ok"
    finally:
        srv.shutdown()
    assert len(eng._finished[r1].output) == 2


def test_backpressure_sees_pool_exhaustion():
    """The paged engine's dominant stall — slots FREE but the pool out
    of pages — must read as saturated, not as a healthy replica."""
    model, cfg = _model(9)
    # pool sized for exactly one resident request (+ sink page)
    eng = ContinuousBatchingEngine(model, _ecfg(
        True, max_slots=2, max_len=128, page_size=8, n_pages=10))
    rng = np.random.default_rng(6)
    r0 = eng.add_request(rng.integers(1, cfg.vocab_size, 8),
                         max_new_tokens=56)  # 64 tokens = 8 pages
    r1 = eng.add_request(rng.integers(1, cfg.vocab_size, 8),
                         max_new_tokens=56)
    eng.step()  # admits r0; r1 blocks on pages with a slot still free
    bp = eng.backpressure()
    assert bp["free_slots"] >= 1
    assert bp["queue_depth"] == 1
    assert bp["pool_blocked"] and bp["saturated"]
    _drain(eng)  # r0 finishes -> pages free -> r1 admits and finishes
    assert len(eng._finished[r1].output) == 56
    bp = eng.backpressure()
    assert not bp["saturated"] and not bp["pool_blocked"]


def test_trace_endpoint_404_when_tracing_off():
    model, cfg = _model(9)
    eng = ContinuousBatchingEngine(model, _ecfg(False))  # telemetry off
    srv = start_metrics_server(eng, port=0)
    try:
        port = srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace", timeout=10)
        assert ei.value.code == 404
    finally:
        srv.shutdown()


def test_flight_recorder_attaches_trace_tail(tmp_path, obs_flags):
    import time as _time

    obs_flags({"telemetry": True})
    tr = tracing.Tracer(engine_id="fr-test")
    # timestamps beyond any event earlier tests' still-live tracers
    # recorded: recent_events is process-wide and keeps the NEWEST
    base = _time.perf_counter() + 3600.0
    for i in range(5):
        tr.step(tr.next_step(), "decode", base + i, base + i + 0.5,
                tokens_advanced=1)
    rec = obs.FlightRecorder(capacity=4, dump_dir=str(tmp_path),
                             trace_tail=3)
    rec.record(step=1, loss=float("nan"))
    path = rec.dump("nan loss")
    payload = json.load(open(path))
    tail = payload["trace_tail"]
    assert len(tail) == 3  # bounded to trace_tail
    assert all(e["name"] == "decode" for e in tail)
    # the tail is the MOST RECENT events
    assert [e["t0"] for e in tail] == [base + 2, base + 3, base + 4]
    # trace_tail=0 disables the attachment entirely
    rec2 = obs.FlightRecorder(capacity=4, dump_dir=str(tmp_path),
                              trace_tail=0)
    rec2.record(step=1, loss=1.0)
    assert "trace_tail" not in json.load(open(rec2.dump("x")))


def test_dump_cli_trace(capsys, obs_flags):
    obs_flags({"telemetry": True})
    from paddle_tpu.observability import dump
    tr = tracing.Tracer(engine_id="cli-test")
    tr.request(0, "queued", prompt_tokens=4)
    tr.step(tr.next_step(), "decode", 0.0, 0.1, tokens_advanced=1)
    assert dump.main(["--trace", "--no-device"]) == 0
    doc = json.loads(capsys.readouterr().out)
    _validate_chrome(doc)
    assert any(e["name"] == "decode" for e in doc["traceEvents"])
    assert dump.main(["--trace-jsonl"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert any(json.loads(l)["name"] == "queued" for l in lines)


# ---------------- goodput bench scenario ----------------

def test_goodput_scenario_emits_per_qps_rows():
    """bench_serve7b's closed-loop load generator: one JSON row per
    QPS step with goodput-under-SLO + p99 TTFT/TPOT."""
    from benchmarks.suite import _goodput_scenario

    model, cfg = _model(10)
    ecfg = _ecfg(True, max_slots=2, max_len=64, page_size=8)
    out = _goodput_scenario(model, ecfg, tpu=False)
    assert out["slo_class"] == "interactive"
    assert len(out["sweep"]) == 2
    json.dumps(out)  # ledger-serializable
    for row in out["sweep"]:
        assert row["qps"] > 0
        assert row["n_requests"] == out["n_requests_per_step"]
        assert row["slo_met"] + row["slo_violated"] == row["n_requests"]
        assert row["goodput"] == pytest.approx(
            row["slo_met"] / row["n_requests"])
        assert row["p99_ttft_ms"] > 0
        assert row["p99_tpot_ms"] is None or row["p99_tpot_ms"] > 0
        assert row["served_tokens_per_sec"] > 0
        assert row["goodput_tokens_per_sec"] <= \
            row["served_tokens_per_sec"]
