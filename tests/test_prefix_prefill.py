"""Prefix-aware serving prefill: shared-prefix KV reuse (copy-on-write
pages / copied token blocks) + the single-program chunked prefill.

The contract under test:
  - token-level parity: ``PT_FLAGS_prefix_cache=on`` greedy outputs are
    IDENTICAL to the ``off`` path in both cache modes (incl. bf16
    caches) — a cached block holds bit-identical KV to a recompute;
  - copy-on-write: a write to a shared page never mutates the cached
    prefix entry;
  - compile count: mixed prompt lengths drive ≤ 2 prefill
    specializations (one, in practice) vs one-per-bucket legacy;
  - admission back-pressure keeps FIFO order across pool exhaustion.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import flags as F
from paddle_tpu.inference.prefix_cache import (
    ContigPrefixStore,
    PagedPrefixStore,
    block_hashes,
)
from paddle_tpu.inference.serving import (
    ContinuousBatchingEngine,
    EngineConfig,
)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

pytestmark = pytest.mark.fast


def _model(seed=0):
    import paddle_tpu as pt

    pt.seed(seed)
    cfg = LlamaConfig.tiny()
    return LlamaForCausalLM(cfg), cfg


@pytest.fixture
def serving_flags():
    """set_flags with restore for the serving admission knobs."""
    saved = {k: F.flag(k) for k in ("prefix_cache", "prefill_chunk")}
    yield F.set_flags
    F.set_flags(saved)


def _ecfg(paged, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("seq_buckets", (16,))
    kw.setdefault("cache_dtype", jnp.float32)
    # paged: page size; contiguous: prefix block length
    kw.setdefault("page_size", 8)
    return EngineConfig(paged=paged, **kw)


# ---------------- rolling hash / stores ----------------

def test_block_hashes_chain():
    p = np.arange(1, 40)
    h = block_hashes(p, 8)
    assert len(h) == 4  # 39 tokens -> 4 full blocks, tail unhashed
    # chained: same leading blocks, different later block -> shared
    # prefix digests equal, divergence point differs
    q = p.copy()
    q[20] += 1
    h2 = block_hashes(q, 8)
    assert h[:2] == h2[:2] and h[2] != h2[2] and h[3] != h2[3]
    # chain property: block i's digest depends on everything before it
    r = p.copy()
    r[0] += 1
    h3 = block_hashes(r, 8)
    assert all(a != b for a, b in zip(h, h3))


def test_contig_store_lru_cap():
    store = ContigPrefixStore(max_blocks=2)
    store.insert(b"a", 1, 1)
    store.insert(b"b", 2, 2)
    store.match([b"a"])  # refresh a -> b is now LRU
    store.insert(b"c", 3, 3)
    assert len(store) == 2 and store.evictions == 1
    assert b"b" not in store and b"a" in store and b"c" in store


def test_paged_store_evicts_lru_unborrowed_only():
    from paddle_tpu.inference.paged import PagePool

    pool = PagePool(n_pages=6, page_size=4, slots=2, max_pages_per_slot=4)
    store = PagedPrefixStore()
    assert pool.alloc(0, 8)  # pages for 2 blocks
    p0, p1 = pool.pages_of[0]
    store.insert(b"h0", p0, pool)
    store.insert(b"h1", p1, pool)
    pool.free(0)  # slot drops its refs; store keeps both pages alive
    assert pool.free_pages == 4
    # borrow p0 into slot 1 (ref 2) -> only p1 is evictable
    assert pool.adopt(1, [p0])
    freed = store.evict(pool, 2)
    assert freed == 1 and b"h1" not in store and b"h0" in store
    pool.free(1)
    assert store.evict(pool, 1) == 1
    assert pool.free_pages == 6


# ---------------- satellites: config/request validation ----------------

def test_empty_prompt_raises():
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    with pytest.raises(ValueError, match="non-empty prompt"):
        eng.add_request(np.zeros((0,), np.int64), max_new_tokens=4)


def test_seq_buckets_validated_and_normalized():
    model, cfg = _model()
    with pytest.raises(ValueError, match="non-empty"):
        ContinuousBatchingEngine(model, _ecfg(False, seq_buckets=()))
    with pytest.raises(ValueError, match="positive ints"):
        ContinuousBatchingEngine(model, _ecfg(False, seq_buckets=(8, 0)))
    with pytest.raises(ValueError, match="positive ints"):
        ContinuousBatchingEngine(model,
                                 _ecfg(False, seq_buckets=(8, 16.5)))
    # unsorted + duplicated + oversized input normalizes (sorted,
    # unique, clamped to max_len) instead of breaking the bisect lookup
    eng = ContinuousBatchingEngine(model, _ecfg(
        False, seq_buckets=(128, 16, 8, 16), max_len=32))
    assert eng._buckets == [8, 16, 32]
    assert eng._bucket(9) == 16 and eng._bucket(20) == 32


def test_page_size_validated_in_both_modes():
    """page_size is load-bearing in contiguous mode too (the prefix
    hash block length) — a zero value must fail at init, not with a
    ZeroDivisionError at first admission."""
    model, cfg = _model()
    for paged in (False, True):
        with pytest.raises(ValueError, match="page_size"):
            ContinuousBatchingEngine(model, _ecfg(paged, page_size=0))


# ---------------- parity: prefix on == off, both modes ----------------

@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("cache_dtype", [jnp.float32, jnp.bfloat16])
def test_prefix_cache_token_parity(paged, cache_dtype, serving_flags):
    """Greedy outputs for requests sharing a prefix must be identical
    with the prefix cache on and off — cached blocks hold bit-identical
    KV to a recompute (same chunk shapes, per-row math)."""
    model, cfg = _model(3)
    rng = np.random.default_rng(5)
    shared = rng.integers(1, cfg.vocab_size, 24)  # 3 blocks of 8
    prompts = [np.concatenate([shared, rng.integers(1, cfg.vocab_size, k)])
               for k in (5, 9, 2)]
    prompts.append(shared.copy())  # full-cover hit (block-aligned)

    outs = {}
    for arm in (True, False):
        serving_flags({"prefix_cache": arm})
        eng = ContinuousBatchingEngine(
            model, _ecfg(paged, cache_dtype=cache_dtype))
        got = []
        for p in prompts:  # sequential: later requests can hit
            got.append(eng.run([p], max_new_tokens=6)[0].output)
        outs[arm] = got
        if arm:
            snap = eng.prefix_snapshot()
            assert snap["hits"] >= 3 and snap["hit_tokens"] >= 3 * 24 - 1
        else:
            assert eng.prefix_snapshot()["hits"] == 0
    assert outs[True] == outs[False]


def test_prefix_hits_across_admission_waves(serving_flags):
    """Batched run(): the first wave misses, later waves hit the blocks
    the first wave published; outputs still match the off arm."""
    model, cfg = _model(7)
    rng = np.random.default_rng(11)
    shared = rng.integers(1, cfg.vocab_size, 16)
    prompts = [np.concatenate([shared, rng.integers(1, cfg.vocab_size, k)])
               for k in (4, 6, 3, 8, 5)]
    outs = {}
    for arm in (True, False):
        serving_flags({"prefix_cache": arm})
        eng = ContinuousBatchingEngine(model, _ecfg(True))
        reqs = eng.run(prompts, max_new_tokens=5)
        outs[arm] = [r.output for r in reqs]
    assert outs[True] == outs[False]


# ---------------- copy-on-write ----------------

def test_cow_write_never_mutates_cached_prefix():
    """Full-cover hit: the new slot adopts every cached page and
    recomputes only the last token — that write lands in a SHARED page
    and must trigger a private copy, leaving the store's pages
    bit-identical. Subsequent decode writes stay private too."""
    model, cfg = _model(2)
    rng = np.random.default_rng(9)
    prompt = rng.integers(1, cfg.vocab_size, 16)  # exactly 2 pages of 8
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    ref = eng.run([prompt], max_new_tokens=8)[0].output
    store = eng._prefix
    pages = [p for p, _ns in store._blocks.values()]
    assert len(pages) == 2
    before = [[np.asarray(c.k_pages[:, p]).copy() for p in pages]
              for c in eng.layer_caches]

    out2 = eng.run([prompt], max_new_tokens=8)[0].output  # full cover
    assert eng.prefix_stats["cow_copies"] >= 1
    after = [[np.asarray(c.k_pages[:, p]) for p in pages]
             for c in eng.layer_caches]
    for lb, la in zip(before, after):
        for b, a in zip(lb, la):
            np.testing.assert_array_equal(b, a)
    assert out2 == ref
    # and a third request still reuses the untouched entries correctly
    assert eng.run([prompt], max_new_tokens=8)[0].output == ref


def test_cow_for_decode_guard_copies_shared_page():
    """The defensive decode-time guard: if the page the next append
    lands in is shared (simulated here by pinning it into the store),
    the engine copies it before dispatching the decode chunk."""
    model, cfg = _model(4)
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, cfg.vocab_size, 5)
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    rid = eng.add_request(prompt, max_new_tokens=6)
    eng._admit()
    slot = eng._slot_req[0].slot
    # pin the page decode is about to write (position 5 -> block 0)
    page = int(eng.pool.block_tables[slot, 0])
    eng.pool.retain(page)
    snap = np.asarray(eng.layer_caches[0].k_pages[:, page]).copy()
    while eng.step():
        pass
    assert eng.prefix_stats["cow_copies"] >= 1
    np.testing.assert_array_equal(
        snap, np.asarray(eng.layer_caches[0].k_pages[:, page]))
    assert eng._finished[rid].done
    eng.pool.release(page)


# ---------------- compile-count guard ----------------

def test_chunked_prefill_compile_count(compile_counter):
    """THE regression this PR exists to prevent: across a mixed-length
    prompt sweep the chunked path must hold at ≤ 2 prefill
    specializations (it is 1 by construction: the chunk shape is
    fixed), where the legacy path compiles one per bucket."""
    model, cfg = _model(6)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, cfg.vocab_size, n)
               for n in (3, 7, 12, 19, 30, 45)]
    eng = ContinuousBatchingEngine(model, _ecfg(
        False, seq_buckets=(8, 16, 32, 64), max_len=64))
    eng.run(prompts, max_new_tokens=3)
    assert compile_counter("prefill_chunk") <= 2
    assert compile_counter("prefill_chunk") >= 1
    assert compile_counter("prefill_bucket") == 0


def test_legacy_bucketed_path_compiles_per_bucket(compile_counter,
                                                  serving_flags):
    """PT_FLAGS_prefill_chunk=0 reproduces the per-bucket trace (the
    parity oracle) — and its outputs match the chunked path's."""
    model, cfg = _model(6)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, cfg.vocab_size, n)
               for n in (3, 12, 30)]  # buckets 8, 16, 32
    chunked = ContinuousBatchingEngine(model, _ecfg(
        False, seq_buckets=(8, 16, 32, 64), max_len=64))
    ref = [r.output for r in chunked.run(prompts, max_new_tokens=4)]
    chunk_traces_before = compile_counter("prefill_chunk")

    serving_flags({"prefill_chunk": 0})
    eng = ContinuousBatchingEngine(model, _ecfg(
        False, seq_buckets=(8, 16, 32, 64), max_len=64))
    assert eng._prefix is None  # prefix reuse rides the chunked path
    got = [r.output for r in eng.run(prompts, max_new_tokens=4)]
    assert compile_counter("prefill_bucket") == 3  # one per bucket hit
    assert compile_counter("prefill_chunk") == chunk_traces_before
    assert got == ref


def test_prefill_chunk_floor_of_two(serving_flags):
    """prefill_chunk=1 must clamp to 2: a 1-token chunk program would
    take the models' s == 1 decode branch, whose append CLAMPS the
    idle-slot start=max_len sentinel into a real page (corrupting a
    decoding slot's KV) instead of dropping it. Regression: admit a
    request mid-decode at the degenerate chunk size and check the
    in-flight request's output is unaffected."""
    model, cfg = _model(5)
    rng = np.random.default_rng(6)
    # fully-allocated block table: prompt 8 + 8 new == 2 whole pages
    pa = rng.integers(1, cfg.vocab_size, 8)
    pb = rng.integers(1, cfg.vocab_size, 8)
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        [pa], max_new_tokens=8)[0].output

    serving_flags({"prefill_chunk": 1})
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    assert eng._chunk_len == 2
    ra = eng.add_request(pa, max_new_tokens=8)
    eng.step()  # admit A, decode one token
    eng.step()
    rb = eng.add_request(pb, max_new_tokens=4)  # admission mid-decode
    while eng.step() or eng._queue or eng.active.any():
        pass
    assert eng._finished[ra].output == ref  # A's KV never corrupted
    assert eng._finished[rb].done


# ---------------- admission back-pressure ----------------

def test_backpressure_fifo_after_pool_exhaustion(serving_flags):
    """When PagePool.alloc fails mid-queue the admission loop breaks;
    requests behind the blocked head must be admitted AFTER a finisher
    frees pages, in FIFO order (the prefix store's retained pages are
    evicted, not deadlocked, under that pressure)."""
    model, cfg = _model(8)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, cfg.vocab_size, 8) for _ in range(3)]
    # pool: sink + 2 pages == exactly one request (8 prompt + 8 new)
    eng = ContinuousBatchingEngine(model, EngineConfig(
        max_slots=3, max_len=32, seq_buckets=(8,), paged=True,
        page_size=8, n_pages=3, cache_dtype=jnp.float32))
    rids = [eng.add_request(p, max_new_tokens=8) for p in prompts]
    admit_wave = {}
    wave = 0
    while eng.step() or eng._queue or eng.active.any():
        wave += 1
        done_or_running = ({r.rid for r in eng._slot_req.values()}
                          | set(eng._finished))
        for rid in rids:
            if rid in done_or_running and rid not in admit_wave:
                admit_wave[rid] = wave
    for rid in rids:
        assert eng._finished[rid].done
    # FIFO preserved, and back-pressure actually happened (the pool
    # can't hold two requests at once)
    assert admit_wave[rids[0]] <= admit_wave[rids[1]] <= \
        admit_wave[rids[2]]
    assert admit_wave[rids[1]] > admit_wave[rids[0]]
    # freeing required evicting the finished requests' cached pages
    assert eng.prefix_stats["evictions"] >= 1
    # sequential parity unaffected by the waves
    serving_flags({"prefix_cache": False})
    ref_eng = ContinuousBatchingEngine(model, EngineConfig(
        max_slots=3, max_len=32, seq_buckets=(8,), paged=True,
        page_size=8, cache_dtype=jnp.float32))
    refs = ref_eng.run(prompts, max_new_tokens=8)
    for rid, ref in zip(rids, refs):
        assert eng._finished[rid].output == ref.output


def test_blocked_admission_does_not_churn_prefix_store(serving_flags):
    """A pool-blocked request retries admission every scheduler tick;
    the feasibility precheck must turn those retries into pure host
    bookkeeping — no COW device copy, and above all no LRU eviction
    that drains the store without admitting anyone."""
    model, cfg = _model(8)
    rng = np.random.default_rng(7)
    P = rng.integers(1, cfg.vocab_size, 8)   # the shared prompt
    Q = rng.integers(1, cfg.vocab_size, 8)   # the long-runner
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        [P], max_new_tokens=8)[0].output

    eng = ContinuousBatchingEngine(model, EngineConfig(
        max_slots=2, max_len=32, seq_buckets=(8,), paged=True,
        page_size=8, n_pages=5, cache_dtype=jnp.float32))
    assert eng.run([P], max_new_tokens=8)[0].output == ref  # publish P
    assert len(eng._prefix) == 1
    rb = eng.add_request(Q, max_new_tokens=16)  # 3 of the 4 pool pages
    eng.step()                                  # admit the long-runner
    rc = eng.add_request(P, max_new_tokens=8)   # full-cover hit; blocked
    cows0 = eng.prefix_stats["cow_copies"]
    blocked_ticks = 0
    for _ in range(8):
        eng.step()
        if not eng._queue:
            break
        blocked_ticks += 1
        # blocked retries must leave the store and pool untouched
        # (2 entries: P's block + the long-runner's block, published
        # at ITS admission commit)
        assert len(eng._prefix) == 2
        assert eng.prefix_stats["evictions"] == 0
        assert eng.prefix_stats["cow_copies"] == cows0
    assert blocked_ticks > 0  # back-pressure actually happened
    while eng.step() or eng._queue or eng.active.any():
        pass
    assert eng._finished[rb].done
    # the cached prefix SURVIVED the blocked period and served the hit
    assert eng._finished[rc].output == ref
    assert eng.prefix_stats["hits"] >= 1
    assert eng.prefix_stats["cow_copies"] > cows0


def test_prefill_rollback_on_admission_error(serving_flags):
    """A failure mid-wave rolls every claimed request back (slot,
    pages, queue position) — the engine must not shrink."""
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    # one full prefix block (8 tokens) + 1: stats-eligible, uncached
    rid = eng.add_request(np.arange(1, 10), max_new_tokens=4)
    orig = eng._drive_prefill_chunks

    def boom(jobs):
        raise RuntimeError("injected")

    eng._drive_prefill_chunks = boom
    with pytest.raises(RuntimeError, match="injected"):
        eng._admit()
    assert len(eng._queue) == 1 and not eng.active.any()
    assert len(eng._free_heap) == eng.cfg.max_slots
    assert eng.pool.free_pages == eng.pool.n_pages - 1  # sink reserved
    assert eng.prefix_stats["misses"] == 0  # rolled back: not counted
    eng._drive_prefill_chunks = orig
    out = eng.run([], max_new_tokens=4)  # drain the requeued request
    assert eng._finished[rid].done
    # stats count the request ONCE (commit-time, not claim-time)
    assert eng.prefix_stats["misses"] == 1
    assert eng.prefix_stats["prompt_tokens"] == 9


def test_claim_failure_leaves_slot_clean():
    """An error escaping the page-claim itself (here: the full-cover
    COW device dispatch) happens BEFORE the request joins the wave's
    jobs list — the claim must free its own adopted pages, or the next
    occupant adopts onto a dirty slot (wedge) / writes shared pages
    without copy-on-write (corruption)."""
    model, cfg = _model(2)
    rng = np.random.default_rng(9)
    prompt = rng.integers(1, cfg.vocab_size, 16)  # exactly 2 pages of 8
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    ref = eng.run([prompt], max_new_tokens=4)[0].output  # publish blocks
    free_before = eng.pool.free_pages

    def boom(*a, **k):
        raise RuntimeError("cow dispatch failed")

    eng._copy_page_c = None
    eng._copy_page = boom  # full-cover hit must COW its last page
    rid = eng.add_request(prompt, max_new_tokens=4)
    with pytest.raises(RuntimeError, match="cow dispatch"):
        eng._admit()
    # slot left clean: no leaked pages, nothing active, request queued
    assert eng.pool.free_pages == free_before
    assert all(not pages for pages in eng.pool.pages_of.values())
    assert not eng.active.any() and len(eng._queue) == 1
    # recovery: restore the program and the request completes correctly
    del eng._copy_page
    eng._copy_page_c = None
    out = eng.run([], max_new_tokens=4)
    assert eng._finished[rid].output == ref


# ---------------- modeled prefill cost (kernelbench) ----------------

def test_prefill_flops_proportional_to_suffix():
    """Modeled-cost A/B: with prefix reuse, prefill FLOPs scale with
    the SUFFIX rounded to the chunk — not with the seq bucket."""
    from benchmarks.kernelbench import prefill_admission_flops

    dims = dict(hidden=4096, inter=11008, n_layers=32, vocab=32000,
                chunk=64, buckets=(512, 1024, 2048))
    # 260-token prompt pays a 512 bucket on the legacy path
    r = prefill_admission_flops(prompt_len=260, prefix_len=0, **dims)
    assert r["bucket"] == 512
    assert r["legacy_flops"] > r["chunked_flops"]
    # shared prefix: FLOPs ∝ suffix, independent of the bucket
    hit = prefill_admission_flops(prompt_len=260, prefix_len=256, **dims)
    assert hit["chunked_prefix_flops"] < 0.3 * hit["chunked_flops"]
    big = prefill_admission_flops(prompt_len=1500, prefix_len=1280,
                                  **dims)
    small = prefill_admission_flops(prompt_len=700, prefix_len=512,
                                    **dims)
    # ~same suffix (220 vs 188 tokens): same chunked+prefix cost class
    # despite wildly different buckets/prompt lengths
    assert big["chunked_prefix_flops"] < 1.5 * \
        small["chunked_prefix_flops"]
    assert big["legacy_flops"] > 2 * small["legacy_flops"]
