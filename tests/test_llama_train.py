"""End-to-end: Llama on the hybrid mesh — the 'minimum end-to-end slice'
(SURVEY.md §7): forward parity vs a numpy-free reference run, sharded
train step convergence, stage-3 state sharding, recompute equivalence.

Parity model: test/collective/fleet/ convergence-equivalence tests — the
parallel run must match the single-device run within tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import distributed as dist, optimizer as opt
from paddle_tpu.core.functional import extract_params, functional_call
from paddle_tpu.distributed.sharding import mesh_context
from paddle_tpu.distributed.strategy import DistributedStrategy, HybridConfig
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.trainer import TrainStep


def _strategy(stage=3, **hybrid):
    s = DistributedStrategy()
    s.hybrid_configs = HybridConfig(**hybrid)
    s.sharding = stage > 0
    s.sharding_configs.stage = stage
    return s


@pytest.fixture(scope="module")
def tiny_model():
    pt.seed(123)
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    return LlamaForCausalLM(cfg)


def test_llama_forward_shapes(tiny_model):
    ids = jnp.asarray(np.random.randint(0, 256, (2, 16)))
    logits = tiny_model(ids)
    assert logits.shape == (2, 16, 256)
    loss = tiny_model(ids, labels=ids)
    assert loss.shape == ()
    assert float(loss) > 0


def test_llama_single_vs_mesh_parity(tiny_model):
    """The sharded forward must equal the unsharded forward bit-for-near."""
    ids = jnp.asarray(np.random.randint(0, 256, (4, 16)))
    ref = np.asarray(tiny_model(ids, labels=ids))

    mesh = dist.build_mesh(dp=2, fsdp=2, tp=2)
    strategy = _strategy(stage=3, dp_degree=2, sharding_degree=2, mp_degree=2)
    params = extract_params(tiny_model)
    from paddle_tpu.distributed.sharding import param_partition_spec

    objs = dict(tiny_model.named_parameters())
    sharded = {
        n: jax.device_put(
            v, NamedSharding(
                mesh, param_partition_spec(n, v.shape, objs[n].spec, strategy)
            )
        )
        for n, v in params.items()
    }
    with mesh_context(mesh):
        out = jax.jit(
            lambda p, x: functional_call(tiny_model, p, x, labels=x)
        )(sharded, jax.device_put(
            ids, NamedSharding(mesh, P(("dp", "fsdp"), None))
        ))
    np.testing.assert_allclose(float(out), float(ref), rtol=2e-4)


def test_train_step_stage3_convergence(tiny_model):
    """Sharded AdamW training drives loss down on a memorization task."""
    pt.seed(5)
    mesh = dist.build_mesh(dp=2, fsdp=2, tp=2)
    strategy = _strategy(stage=3, dp_degree=2, sharding_degree=2, mp_degree=2)
    o = opt.AdamW(learning_rate=3e-3, weight_decay=0.0, multi_precision=False,
                  grad_clip=opt.ClipGradByGlobalNorm(1.0))
    ts = TrainStep(tiny_model, o, mesh, strategy)

    ids = np.random.randint(0, 256, (8, 16))
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}
    losses = [float(ts.run(batch)) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.7, losses[::10]

    # optimizer state is genuinely sharded over fsdp for big params
    slot = ts.opt_state["slots"]["model.embed_tokens.weight"]["moment1"]
    spec = slot.sharding.spec
    assert "fsdp" in str(spec), spec
    # params sharded too (stage 3)
    pspec = ts.params["model.embed_tokens.weight"].sharding.spec
    assert "fsdp" in str(pspec) or "tp" in str(pspec)


def test_stage1_vs_stage3_same_result(tiny_model):
    """ZeRO stages are numerically identical — only layouts differ."""
    ids = np.random.randint(0, 256, (4, 8))
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}
    results = []
    for stage in (1, 3):
        pt.seed(9)
        cfg = LlamaConfig.tiny(use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        mesh = dist.build_mesh(fsdp=4, tp=2)
        strategy = _strategy(stage=stage, sharding_degree=4, mp_degree=2)
        o = opt.AdamW(learning_rate=1e-3, multi_precision=False)
        ts = TrainStep(model, o, mesh, strategy)
        for _ in range(3):
            loss = ts.run(batch)
        results.append(float(loss))
    np.testing.assert_allclose(results[0], results[1], rtol=1e-4)


def test_recompute_matches_no_recompute():
    ids = np.random.randint(0, 256, (2, 16))
    outs = []
    for use_rc in (False, True):
        pt.seed(11)
        cfg = LlamaConfig.tiny(use_flash_attention=False, use_recompute=use_rc)
        model = LlamaForCausalLM(cfg)
        params = extract_params(model)
        loss, grads = jax.value_and_grad(
            lambda p: functional_call(
                model, p, jnp.asarray(ids), labels=jnp.asarray(ids)
            )
        )(params)
        outs.append((float(loss), grads))
    np.testing.assert_allclose(outs[0][0], outs[1][0], rtol=1e-5)
    g0 = outs[0][1]["model.layers.0.self_attn.q_proj.weight"]
    g1 = outs[1][1]["model.layers.0.self_attn.q_proj.weight"]
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1), rtol=1e-4,
                               atol=1e-6)


def test_kv_cache_decode_matches_full_forward(tiny_model):
    """Greedy decode with kv cache equals argmax over full-context logits."""
    tiny_model.eval()
    ids = np.random.randint(0, 256, (1, 8))
    full_logits = np.asarray(tiny_model(jnp.asarray(ids)))
    caches = tiny_model.init_kv_caches(1, 16, dtype=jnp.float32)
    # prefill one token at a time (worst case for cache correctness)
    for t in range(8):
        tok = jnp.asarray(ids[:, t:t + 1])
        pos = jnp.full((1, 1), t, jnp.int32)
        logits, caches = tiny_model(
            tok, position_ids=pos, kv_caches=caches, cache_index=t
        )
    np.testing.assert_allclose(
        np.asarray(logits[0, 0]), full_logits[0, -1], rtol=2e-3, atol=2e-3
    )
    tiny_model.train()


def test_master_only_residency_matches_paired():
    """master_residency='master_only' is bit-identical to 'paired':
    the stored bf16 param is exactly cast(master) after every update, so
    dropping the persistent bf16 copy changes residency, not numerics."""
    ids = np.random.randint(0, 256, (4, 8))
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}
    runs = {}
    for mode in ("paired", "master_only"):
        pt.seed(17)
        cfg = LlamaConfig.tiny(use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        model.to(pt.bfloat16)
        mesh = dist.build_mesh(fsdp=4)
        strategy = _strategy(stage=3, sharding_degree=4)
        o = opt.AdamW(learning_rate=1e-3, multi_precision=True)
        ts = TrainStep(model, o, mesh, strategy, master_residency=mode)
        losses = [float(ts.run(batch)) for _ in range(3)]
        runs[mode] = (ts, losses)

    ts_m, losses_m = runs["master_only"]
    ts_p, losses_p = runs["paired"]
    np.testing.assert_array_equal(losses_p, losses_m)

    # the bf16 copies are not carried by the master_only step
    name = "model.embed_tokens.weight"
    assert name not in ts_m.params and name in ts_p.params
    np.testing.assert_array_equal(
        np.asarray(ts_p.opt_state["master"][name], np.float32),
        np.asarray(ts_m.opt_state["master"][name], np.float32))

    # state_dict still carries full params (cast back on demand), and
    # sync_to_model rematerializes the Layer tree from the masters
    sd = ts_m.state_dict()
    assert sd["params"][name].dtype == jnp.bfloat16
    ts_m.sync_to_model()
    live = dict(ts_m.model.named_parameters())[name].value
    np.testing.assert_array_equal(
        np.asarray(live, np.float32),
        np.asarray(sd["params"][name], np.float32))


def test_master_only_requires_masters():
    pt.seed(3)
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    model = LlamaForCausalLM(cfg)  # fp32 params: no masters
    mesh = dist.build_mesh(fsdp=4)
    o = opt.AdamW(learning_rate=1e-3, multi_precision=True)
    with pytest.raises(ValueError, match="master_only"):
        TrainStep(model, o, mesh, _strategy(stage=1, sharding_degree=4),
                  master_residency="master_only")


def test_master_only_params_only_restore():
    """set_state_dict with params but no opt_state must refresh the
    masters (the resident form) — not silently drop the restore."""
    pt.seed(21)
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    model.to(pt.bfloat16)
    mesh = dist.build_mesh(fsdp=4)
    o = opt.AdamW(learning_rate=1e-3, multi_precision=True)
    ts = TrainStep(model, o, mesh, _strategy(stage=3, sharding_degree=4),
                   master_residency="master_only")
    name = "model.embed_tokens.weight"
    new_w = jnp.full(ts.opt_state["master"][name].shape, 0.125, jnp.bfloat16)
    ts.set_state_dict({"params": {name: new_w}})
    np.testing.assert_array_equal(
        np.asarray(ts.opt_state["master"][name]),
        np.full(new_w.shape, 0.125, np.float32))
    # and the forward now uses the restored value
    sd = ts.state_dict()
    np.testing.assert_array_equal(
        np.asarray(sd["params"][name], np.float32),
        np.full(new_w.shape, 0.125, np.float32))
