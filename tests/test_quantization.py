"""Quantization parity tests (reference: test/quantization/, phi
weight_only_linear kernels)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu import quantization as Q
from paddle_tpu.kernels import quant_matmul as qmm


def _rand(shape, seed=0, dtype=np.float32):
    return np.random.default_rng(seed).standard_normal(shape).astype(dtype)


def test_int8_grouped_quantize_roundtrip():
    w = _rand((256, 64))
    q, s = qmm.quantize_weight_int8_grouped(w, group_size=128)
    assert q.dtype == jnp.int8 and s.shape == (2, 64)
    deq = np.asarray(q, np.float32).reshape(2, 128, 64) * \
        np.asarray(s)[:, None, :]
    np.testing.assert_allclose(deq.reshape(256, 64), w, atol=np.abs(w).max() / 100)


def test_int4_pack_unpack_roundtrip():
    w = _rand((256, 64), seed=1)
    packed, s = qmm.quantize_weight_int4_grouped(w, group_size=128)
    assert packed.shape == (128, 64) and packed.dtype == jnp.int8
    unpacked = np.asarray(qmm._unpack_int4(packed))
    assert unpacked.shape == (256, 64)
    assert unpacked.min() >= -8 and unpacked.max() <= 7
    deq = unpacked.astype(np.float32).reshape(2, 128, 64) * \
        np.asarray(s)[:, None, :]
    # int4 is coarse: tolerance is half a quant step per group
    err = np.abs(deq.reshape(256, 64) - w)
    step = np.repeat(np.asarray(s), 128, axis=0)
    assert (err <= step * 0.5 + 1e-6).all()


@pytest.mark.parametrize("wdtype", ["int8", "int4"])
def test_pallas_matmul_matches_xla(wdtype):
    w = _rand((512, 256), seed=2)
    x = _rand((256, 512), seed=3)
    if wdtype == "int4":
        q, s = qmm.quantize_weight_int4_grouped(w, group_size=128)
    else:
        q, s = qmm.quantize_weight_int8_grouped(w, group_size=128)
    ref = np.asarray(qmm.weight_only_matmul_xla(
        jnp.asarray(x), q, s, group_size=128, weight_dtype=wdtype))
    out = np.asarray(qmm.weight_only_matmul_pallas(
        jnp.asarray(x), q, s, group_size=128, weight_dtype=wdtype))
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)
    # and both approximate the fp matmul: per-element error grows
    # ~sqrt(k)·step, so check the relative Frobenius error instead
    rel = np.linalg.norm(out - x @ w) / np.linalg.norm(x @ w)
    assert rel < (0.01 if wdtype == "int8" else 0.2)


@pytest.mark.parametrize("wdtype", ["int8", "int4"])
def test_weight_only_linear_layer(wdtype):
    lin = nn.Linear(128, 64)
    x = jnp.asarray(_rand((4, 128), seed=4))
    ref = np.asarray(lin(x))
    wol = Q.WeightOnlyLinear(lin, weight_dtype=wdtype, group_size=64)
    out = np.asarray(wol(x))
    tol = 0.05 if wdtype == "int8" else 0.6
    assert np.abs(out - ref).max() < tol
    # state_dict carries quantized buffers
    sd = wol.state_dict()
    assert "qweight" in sd and "scale" in sd


def test_quantize_model_weight_only_int4():
    model = nn.Sequential(nn.Linear(64, 64), nn.ReLU(), nn.Linear(64, 32))
    x = jnp.asarray(_rand((2, 64), seed=5))
    ref = np.asarray(model(x))
    qmodel = Q.quantize_model_weight_only(model, weight_dtype="int4",
                                          group_size=32)
    out = np.asarray(qmodel(x))
    assert np.abs(out - ref).max() < 0.5


def test_observers():
    x = jnp.asarray(_rand((1000,), seed=6))
    for obs_cls in [Q.AbsmaxObserver, Q.EMAObserver, Q.PercentileObserver,
                    Q.MSEObserver]:
        obs = obs_cls()
        obs(x)
        s = obs.scale(127)
        assert s > 0
        # scale roughly amax/127
        assert s <= float(jnp.max(jnp.abs(x))) / 127 * 1.5 + 1e-6
    # percentile clips outliers below absmax
    y = jnp.concatenate([x, jnp.asarray([100.0])])
    pobs, aobs = Q.PercentileObserver(99.0), Q.AbsmaxObserver()
    pobs(y); aobs(y)
    assert pobs.scale() < aobs.scale()


def test_qat_roundtrip_and_convert():
    model = nn.Sequential(nn.Linear(32, 32), nn.ReLU(), nn.Linear(32, 8))
    x = jnp.asarray(_rand((4, 32), seed=7))
    ref = np.asarray(model(x))

    qat = Q.QAT(Q.QuantConfig())
    qmodel = qat.quantize(model, inplace=False)
    assert any(isinstance(m, Q.QuantedLinear)
               for m in qmodel.sublayers(include_self=True))
    out = np.asarray(qmodel(x))
    assert np.abs(out - ref).max() < 0.5  # fake-quant ~ close to fp

    # STE: gradients flow through fake-quant to the source weights
    from paddle_tpu.core.functional import functional_call

    params = {n: p.value for n, p in qmodel.named_parameters()}

    def loss_fn(params):
        y = functional_call(qmodel, params, x)
        return jnp.mean(y ** 2)

    grads = jax.grad(loss_fn)(params)
    gnorms = [float(jnp.linalg.norm(g)) for g in grads.values()]
    assert any(g > 0 for g in gnorms)

    infer = qat.convert(qmodel, inplace=False)
    assert any(isinstance(m, Q.WeightOnlyLinear)
               for m in infer.sublayers(include_self=True))
    out2 = np.asarray(infer(x))
    assert np.abs(out2 - ref).max() < 0.5


def test_ptq_calibrate_convert():
    model = nn.Sequential(nn.Linear(32, 32), nn.ReLU(), nn.Linear(32, 8))
    x = jnp.asarray(_rand((16, 32), seed=8))
    ref = np.asarray(model(x))
    ptq = Q.PTQ(Q.QuantConfig(activation=Q.AbsmaxObserver))
    pmodel = ptq.quantize(model, inplace=False)
    for i in range(3):  # calibration passes
        pmodel(x)
    infer = ptq.convert(pmodel, inplace=False)
    wols = [m for m in infer.sublayers(include_self=True)
            if isinstance(m, Q.WeightOnlyLinear)]
    assert len(wols) == 2
    assert all(getattr(m, "act_scale", 0) > 0 for m in wols)
    out = np.asarray(infer(x))
    assert np.abs(out - ref).max() < 0.2


def test_quantconfig_instance_template_and_none_semantics():
    # docstring usage: a pre-configured quanter INSTANCE as template
    cfg = Q.QuantConfig(activation=Q.FakeQuant(bits=4), weight=None)
    model = nn.Sequential(nn.Linear(16, 16), nn.Linear(16, 16))
    qm = Q.QAT(cfg).quantize(model, inplace=False)
    qls = [m for m in qm.sublayers(include_self=True)
           if isinstance(m, Q.QuantedLinear)]
    assert len(qls) == 2
    # each layer got its OWN copy (no shared stats) and weight=None stuck
    assert qls[0].act_quanter is not qls[1].act_quanter
    assert qls[0].act_quanter.qmax == 7  # bits=4 template honored
    assert all(q.wt_quanter is None for q in qls)
    # explicit None for both → layer left untouched
    cfg2 = Q.QuantConfig(activation=None, weight=None)
    qm2 = Q.QAT(cfg2).quantize(model, inplace=False)
    assert not any(isinstance(m, Q.QuantedLinear)
                   for m in qm2.sublayers(include_self=True))
    # override inherits unset fields from global config
    cfg3 = Q.QuantConfig(activation=Q.FakeQuant(bits=4))
    lyr = model._sub_layers["0"]
    cfg3.add_layer_config(lyr, weight=None)
    got = cfg3._for(lyr)
    assert got["weight"] is None and got["activation"] is not None


def test_weight_only_linear_shape_ctor_degenerate_group():
    wol = Q.WeightOnlyLinear(100, 64, weight_dtype="int4")  # 100 % 128 != 0
    assert wol.group_size == 100
    assert wol._buffers["scale"].shape == (1, 64)
    assert wol._buffers["qweight"].shape == (50, 64)
    x = jnp.zeros((2, 100))
    assert wol(x).shape == (2, 64)
    with pytest.raises(ValueError, match="even in_features"):
        Q.WeightOnlyLinear(99, 64, weight_dtype="int4")


def test_crop_fully_outside_returns_zeros():
    from paddle_tpu.vision import transforms as T

    img = np.ones((10, 10, 3), np.uint8)
    out = T.crop(img, -5, 0, 3, 10)
    assert out.shape == (3, 10, 3)
    assert (out == 0).all()


def test_ptq_act_scale_survives_state_dict():
    model = nn.Sequential(nn.Linear(16, 16))
    ptq = Q.PTQ()
    pmodel = ptq.quantize(model, inplace=False)
    pmodel(jnp.asarray(_rand((4, 16), seed=11)))
    infer = ptq.convert(pmodel, inplace=False)
    wol = next(m for m in infer.sublayers(include_self=True)
               if isinstance(m, Q.WeightOnlyLinear))
    assert float(wol.act_scale) > 0
    sd = infer.state_dict()
    key = next(k for k in sd if k.endswith("act_scale"))
    assert float(sd[key]) > 0


def test_percentile_observer_bounded_memory():
    obs = Q.PercentileObserver(99.0, max_samples=1000)
    for i in range(50):
        obs.observe(jnp.asarray(_rand((4096,), seed=i)))
    assert obs._reservoir.size == 1000  # bounded despite 200k samples
    assert obs.scale() > 0


@pytest.mark.parametrize("m", [1, 8, 120, 300])
def test_weight_only_pallas_small_m_padding(m):
    """Decode-sized activations (m = a few slots) must route through the
    Pallas blockwise-dequant kernel via m-padding — the XLA fallback
    dequantizes the whole weight per call."""
    rng = np.random.default_rng(0)
    k, n = 256, 512
    w = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
    from paddle_tpu.kernels import quant_matmul as qmm

    q8, s8 = qmm.quantize_weight_int8_grouped(w, 128)
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    y_pallas = Q.weight_only_linear(x, q8, s8, weight_dtype="int8",
                                    group_size=128, use_pallas=True)
    y_xla = Q.weight_only_linear(x, q8, s8, weight_dtype="int8",
                                 group_size=128, use_pallas=False)
    assert y_pallas.shape == (m, n)
    np.testing.assert_allclose(np.asarray(y_pallas), np.asarray(y_xla),
                               rtol=2e-5, atol=2e-5)
