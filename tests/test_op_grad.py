"""OpTest-style numeric gradient checker (parity model:
test/legacy_test/op_test.py — the reference's core op-correctness
mechanism checks analytic gradients against central finite differences).

TPU-world form: for every op with parameters we verify
⟨∇f, dir⟩ ≈ (φ(h) − φ(−h)) / 2h for random directions ``dir``, with the
check run in fp64 on CPU (`jax.experimental.enable_x64`) so the finite
difference itself is trustworthy. The same directional check (fp32,
looser tolerance) covers the Pallas kernels' custom VJPs — those are
hand-written backward passes, exactly what a finite-difference check
exists to catch. Plus a bf16/fp32 dtype sweep on the forward surface.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.nn.functional as F
from paddle_tpu.jax_compat import enable_x64 as _enable_x64

# core-engine fast lane (see README "Tests")
pytestmark = pytest.mark.fast


def _rand(shape, seed, scale=1.0):
    return np.random.default_rng(seed).standard_normal(shape) * scale


def directional_grad_check(fn, args, argnums, h=1e-5, rtol=1e-4, atol=1e-6,
                           seed=0):
    """Check ⟨jax.grad(fn), dir⟩ against a central difference of the
    scalar map t ↦ fn(x + t·dir), per differentiable argument."""
    rng = np.random.default_rng(seed + 1000)
    args = [jnp.asarray(a) for a in args]
    grads = jax.grad(lambda *a: jnp.sum(fn(*a)), argnums=argnums)(*args)
    if not isinstance(grads, tuple):
        grads = (grads,)
    for argnum, g in zip(argnums, grads):
        x = args[argnum]
        direction = rng.standard_normal(x.shape).astype(np.float64)
        direction /= np.linalg.norm(direction) + 1e-30
        d = jnp.asarray(direction, x.dtype)

        def phi(t):
            shifted = list(args)
            shifted[argnum] = x + t * d
            return float(jnp.sum(fn(*shifted)))

        numeric = (phi(h) - phi(-h)) / (2 * h)
        analytic = float(jnp.sum(g * d))
        np.testing.assert_allclose(
            analytic, numeric, rtol=rtol, atol=atol,
            err_msg=f"analytic vs central-difference grad for arg {argnum}")


# ---------------------------------------------------------------------------
# op inventory: (name, fn, args builder, argnums to check)
# Inputs chosen away from non-differentiable kinks (|x| > 0.05 for
# relu-family) so the finite difference is valid.
# ---------------------------------------------------------------------------
def _kink_free(shape, seed, margin=0.05):
    x = _rand(shape, seed)
    return np.where(np.abs(x) < margin, x + 4 * margin, x)


_label3 = np.array([0, 2, 1])


OPS = [
    ("linear", lambda x, w, b: F.linear(x, w, b),
     lambda: [_rand((4, 8), 0), _rand((8, 6), 1), _rand((6,), 2)],
     (0, 1, 2)),
    ("matmul", jnp.matmul,
     lambda: [_rand((4, 8), 3), _rand((8, 5), 4)], (0, 1)),
    ("embedding", lambda w: F.embedding(jnp.asarray([[0, 2], [1, 1]]), w),
     lambda: [_rand((5, 8), 5)], (0,)),
    ("relu", F.relu, lambda: [_kink_free((4, 8), 6)], (0,)),
    ("relu6", F.relu6,
     lambda: [np.clip(_kink_free((4, 8), 7), -5, 5.9)], (0,)),
    ("gelu", F.gelu, lambda: [_rand((4, 8), 8)], (0,)),
    ("gelu_tanh", functools.partial(F.gelu, approximate=True),
     lambda: [_rand((4, 8), 9)], (0,)),
    ("silu", F.silu, lambda: [_rand((4, 8), 10)], (0,)),
    ("sigmoid", F.sigmoid, lambda: [_rand((4, 8), 11)], (0,)),
    ("tanh", F.tanh, lambda: [_rand((4, 8), 12)], (0,)),
    ("leaky_relu", F.leaky_relu, lambda: [_kink_free((4, 8), 13)], (0,)),
    ("elu", F.elu, lambda: [_kink_free((4, 8), 14)], (0,)),
    ("softplus", F.softplus, lambda: [_rand((4, 8), 15)], (0,)),
    ("mish", F.mish, lambda: [_rand((4, 8), 16)], (0,)),
    ("softmax", F.softmax, lambda: [_rand((4, 8), 17)], (0,)),
    ("log_softmax", F.log_softmax, lambda: [_rand((4, 8), 18)], (0,)),
    ("swiglu", F.swiglu, lambda: [_rand((4, 16), 19)], (0,)),
    ("layer_norm",
     lambda x, w, b: F.layer_norm(x, (8,), w, b),
     lambda: [_rand((4, 8), 20), 1 + 0.1 * _rand((8,), 21),
              _rand((8,), 22)],
     (0, 1, 2)),
    ("rms_norm", lambda x, w: F.rms_norm(x, w),
     lambda: [_rand((4, 8), 23), 1 + 0.1 * _rand((8,), 24)], (0, 1)),
    ("group_norm",
     lambda x, w, b: F.group_norm(x, 2, w, b),
     lambda: [_rand((2, 4, 3, 3), 25), 1 + 0.1 * _rand((4,), 26),
              _rand((4,), 27)],
     (0, 1, 2)),
    ("cross_entropy",
     lambda x: F.cross_entropy(x, jnp.asarray(_label3)),
     lambda: [_rand((3, 5), 28)], (0,)),
    ("cross_entropy_smooth",
     lambda x: F.cross_entropy(x, jnp.asarray(_label3),
                               label_smoothing=0.1),
     lambda: [_rand((3, 5), 29)], (0,)),
    ("nll_loss",
     lambda x: F.nll_loss(F.log_softmax(x), jnp.asarray(_label3)),
     lambda: [_rand((3, 5), 30)], (0,)),
    ("mse_loss",
     lambda x, y: F.mse_loss(x, y),
     lambda: [_rand((4, 8), 31), _rand((4, 8), 32)], (0, 1)),
    ("bce_with_logits",
     lambda x: F.binary_cross_entropy_with_logits(
         x, jnp.asarray((_rand((4, 8), 33) > 0).astype(np.float64))),
     lambda: [_rand((4, 8), 34)], (0,)),
    ("conv2d",
     lambda x, w, b: F.conv2d(x, w, b, stride=1, padding=1),
     lambda: [_rand((2, 3, 6, 6), 35), _rand((4, 3, 3, 3), 36) * 0.3,
              _rand((4,), 37)],
     (0, 1, 2)),
    ("conv1d",
     lambda x, w: F.conv1d(x, w, padding=1),
     lambda: [_rand((2, 3, 8), 38), _rand((4, 3, 3), 39) * 0.3], (0, 1)),
    ("conv2d_transpose",
     lambda x, w: F.conv2d_transpose(x, w, stride=2),
     lambda: [_rand((1, 3, 4, 4), 40), _rand((3, 2, 2, 2), 41) * 0.3],
     (0, 1)),
    ("avg_pool2d",
     lambda x: F.avg_pool2d(x, 2), lambda: [_rand((2, 3, 6, 6), 42)], (0,)),
    ("max_pool2d",
     lambda x: F.max_pool2d(x, 2),
     # well-separated values → argmax stable under ±h perturbation
     lambda: [np.arange(72).reshape(2, 1, 6, 6)
              + 0.1 * _rand((2, 1, 6, 6), 43)], (0,)),
    ("sdpa",
     lambda q, k, v: F.scaled_dot_product_attention(q, k, v, is_causal=True),
     lambda: [_rand((1, 8, 2, 4), 44), _rand((1, 8, 2, 4), 45),
              _rand((1, 8, 2, 4), 46)],
     (0, 1, 2)),
    ("normalize", F.normalize, lambda: [_rand((4, 8), 47)], (0,)),
    ("cosine_similarity",
     F.cosine_similarity,
     lambda: [_rand((4, 8), 48), _rand((4, 8), 49)], (0, 1)),
    ("glu", F.glu, lambda: [_rand((4, 16), 50)], (0,)),
    # --- round-3 additions: CTC, resampling, signal ---
    ("ctc_loss",
     lambda lg: F.ctc_loss(
         lg, jnp.asarray([[1, 2, 1], [2, 2, 1]]),
         jnp.asarray([8, 7]), jnp.asarray([3, 2]), reduction="sum"),
     lambda: [_rand((8, 2, 4), 80)], (0,)),
    ("interpolate_bilinear",
     lambda x: F.interpolate(x, size=(7, 5), mode="bilinear"),
     lambda: [_rand((2, 3, 4, 6), 81)], (0,)),
    ("interpolate_bicubic",
     lambda x: F.interpolate(x, size=(9, 5), mode="bicubic"),
     lambda: [_rand((2, 2, 5, 7), 82)], (0,)),
    ("grid_sample",
     lambda x, g: F.grid_sample(x, g, padding_mode="border"),
     # grad w.r.t. grid is piecewise (kinks at cell crossings): place
     # sampling points mid-cell (pix = k + 0.5 → frac 0.5) so the
     # central difference stays inside one cell
     lambda: [_rand((1, 2, 6, 6), 83),
              ((np.arange(1, 5)[None, :, None, None] + 0.5
                + 0.1 * _rand((1, 4, 4, 2), 84))
               / 2.5 - 1.0).astype(np.float64)],
     (0, 1)),
    ("stft_power",
     lambda x: jnp.sum(jnp.abs(__import__(
         "paddle_tpu.signal", fromlist=["stft"]).stft(x, 16, 8)) ** 2),
     lambda: [_rand((2, 64), 85)], (0,)),
    ("adaptive_avg_pool_nondiv",
     lambda x: F.adaptive_avg_pool2d(x, (3, 4)),
     lambda: [_rand((2, 2, 7, 9), 86)], (0,)),
]


@pytest.mark.parametrize("name,fn,build,argnums", OPS,
                         ids=[o[0] for o in OPS])
def test_numeric_grad_fp64(name, fn, build, argnums):
    with _enable_x64(True):
        args = [jnp.asarray(a, jnp.float64)
                if np.asarray(a).dtype.kind == "f" else jnp.asarray(a)
                for a in build()]
        directional_grad_check(fn, args, argnums)


# ---------------------------------------------------------------------------
# Pallas custom VJPs (fp32 — the kernels are fp32-accumulating by design).
# A random-direction probe drowns in f32 summation noise (the directional
# derivative of a random direction cancels to ~1e-6/element), so these use
# per-coordinate central differences at the largest-|grad| coordinates,
# where the signal is orders of magnitude above the noise floor.
# ---------------------------------------------------------------------------
def _f32(x):
    return jnp.asarray(x, jnp.float32)


def coordinate_grad_check(fn, args, argnums, h=0.05, rtol=3e-2, n_coords=6):
    args = [jnp.asarray(a) for a in args]
    grads = jax.grad(lambda *a: jnp.sum(fn(*a)), argnums=argnums)(*args)
    if not isinstance(grads, tuple):
        grads = (grads,)
    for argnum, g in zip(argnums, grads):
        x = args[argnum]
        gn = np.asarray(g).ravel()
        coords = np.argsort(-np.abs(gn))[:n_coords]
        for c in coords:
            e = np.zeros(x.size, np.float32)
            e[c] = h
            e = jnp.asarray(e.reshape(x.shape))
            shifted_p, shifted_m = list(args), list(args)
            shifted_p[argnum] = x + e
            shifted_m[argnum] = x - e
            numeric = (float(jnp.sum(fn(*shifted_p)))
                       - float(jnp.sum(fn(*shifted_m)))) / (2 * h)
            np.testing.assert_allclose(
                gn[c], numeric, rtol=rtol, atol=1e-3,
                err_msg=f"arg {argnum} coord {c}")


def test_numeric_grad_flash_mha():
    from paddle_tpu.kernels.pallas_attention import mha

    q = _f32(_rand((1, 128, 2, 64), 60) * 0.5)
    k = _f32(_rand((1, 128, 1, 64), 61) * 0.5)  # GQA path
    v = _f32(_rand((1, 128, 1, 64), 62) * 0.5)
    coordinate_grad_check(
        lambda q, k, v: mha(q, k, v, causal=True, q_block=128, k_block=128),
        [q, k, v], (0, 1, 2))


def test_numeric_grad_flash_mha_with_lse():
    from paddle_tpu.kernels.pallas_attention import mha_with_lse

    q = _f32(_rand((1, 128, 1, 128), 63) * 0.5)
    k = _f32(_rand((1, 128, 1, 128), 64) * 0.5)
    v = _f32(_rand((1, 128, 1, 128), 65) * 0.5)

    def fn(q, k, v):
        o, lse = mha_with_lse(q, k, v, causal=False)
        return jnp.sum(o) + jnp.sum(lse)  # exercises the dlse path too

    coordinate_grad_check(fn, [q, k, v], (0, 1, 2))


def test_numeric_grad_selective_scan():
    from paddle_tpu.kernels.selective_scan import chunked_selective_scan

    rng = np.random.default_rng(66)
    b, s, d, n = 1, 32, 16, 4
    u = _f32(rng.standard_normal((b, s, d)))
    delta = _f32(np.abs(rng.standard_normal((b, s, d))) * 0.1)
    A = _f32(-np.abs(rng.standard_normal((d, n))))
    B = _f32(rng.standard_normal((b, s, n)))
    C = _f32(rng.standard_normal((b, s, n)))
    D = _f32(rng.standard_normal((d,)))
    coordinate_grad_check(
        lambda *a: chunked_selective_scan(*a, chunk=16),
        [u, delta, A, B, C, D], (0, 1, 2, 3, 4, 5))


def test_numeric_grad_rope():
    from paddle_tpu.kernels.rope import apply_rope, rope_frequencies

    q = _f32(_rand((1, 32, 2, 64), 67))
    k = _f32(_rand((1, 32, 2, 64), 68))
    cos, sin = rope_frequencies(64, 32)

    def fn(q, k):
        oq, ok = apply_rope(q, k, cos, sin)
        return jnp.sum(oq) + jnp.sum(ok)

    coordinate_grad_check(fn, [q, k], (0, 1))


def test_numeric_grad_ring_attention():
    from paddle_tpu.kernels.ring_attention import ring_attention
    from paddle_tpu.distributed.sharding import mesh_context

    import paddle_tpu.distributed as dist

    mesh = dist.build_mesh(sep=2)
    q = _f32(_rand((1, 256, 2, 64), 69) * 0.5)
    k = _f32(_rand((1, 256, 2, 64), 70) * 0.5)
    v = _f32(_rand((1, 256, 2, 64), 71) * 0.5)
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh,
                                                axis="sep", causal=True))
    with mesh_context(mesh):
        coordinate_grad_check(fn, [q, k, v], (0, 1, 2))


# ---------------------------------------------------------------------------
# dtype sweep: ops must accept bf16 inputs and stay close to fp32
# ---------------------------------------------------------------------------
SWEEP_OPS = [
    ("linear", lambda x: F.linear(x, jnp.asarray(_rand((8, 6), 1), x.dtype))),
    ("gelu", F.gelu),
    ("silu", F.silu),
    ("softmax", F.softmax),
    ("layer_norm", lambda x: F.layer_norm(x, (8,))),
    ("rms_norm", lambda x: F.rms_norm(x)),
]


@pytest.mark.parametrize("name,fn", SWEEP_OPS, ids=[o[0] for o in SWEEP_OPS])
@pytest.mark.parametrize("dtype", ["bfloat16", "float32"])
def test_dtype_sweep(name, fn, dtype):
    x32 = jnp.asarray(_rand((4, 8), 80), jnp.float32)
    x = x32.astype(dtype)
    out = fn(x)
    ref = fn(x32)
    assert out.shape == ref.shape
    assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref),
        rtol=0.1 if dtype == "bfloat16" else 1e-6, atol=0.1)
