"""Error taxonomy (paddle/common/errors.h parity): typed categories,
builtin compatibility, enforce helpers, and adoption at raise sites."""

import pytest

import paddle_tpu as pt
from paddle_tpu import errors


def test_categories_subclass_builtins():
    assert issubclass(errors.InvalidArgumentError, ValueError)
    assert issubclass(errors.NotFoundError, FileNotFoundError)
    assert issubclass(errors.OutOfRangeError, IndexError)
    assert issubclass(errors.UnimplementedError, NotImplementedError)
    assert issubclass(errors.PermissionDeniedError, PermissionError)
    assert issubclass(errors.ExecutionTimeoutError, TimeoutError)
    assert issubclass(errors.ResourceExhaustedError, MemoryError)
    for n in errors.__all__:
        obj = getattr(errors, n)
        if isinstance(obj, type) and issubclass(obj, errors.Error):
            assert obj is errors.Error or obj.code != "UNKNOWN"


def test_enforce_helpers():
    errors.enforce(True, "fine")
    with pytest.raises(errors.InvalidArgumentError, match="INVALID"):
        errors.enforce(False, "nope")
    with pytest.raises(errors.InvalidArgumentError, match="expected 3"):
        errors.enforce_eq(2, 3, "count")
    with pytest.raises(errors.InvalidArgumentError, match="must be > 0"):
        errors.enforce_gt(0, 0, "n")
    errors.enforce_ge(1, 1)
    with pytest.raises(errors.InvalidArgumentError, match="one of"):
        errors.enforce_in("x", ("a", "b"), "mode")
    errors.enforce_shape_match((2, 3), (2, None))
    with pytest.raises(errors.InvalidArgumentError, match="shape"):
        errors.enforce_shape_match((2, 3), (2, 4))
    with pytest.raises(errors.PreconditionNotMetError):
        errors.enforce(False, "state", errors.PreconditionNotMetError)


def test_adopted_sites():
    # fft validation raises the typed error (still a ValueError)
    import jax.numpy as jnp

    with pytest.raises(errors.InvalidArgumentError):
        pt.fft.fft(jnp.ones(4), norm="bogus")
    # build_mesh with too few devices: PreconditionNotMet, coded message
    from paddle_tpu import distributed as dist

    with pytest.raises(errors.PreconditionNotMetError,
                       match="PRECONDITION_NOT_MET"):
        dist.build_mesh(tp=512)
