"""Elastic membership manager tests (reference:
python/paddle/distributed/fleet/elastic/ — mocked-etcd style tests;
here the store is a real temp directory)."""

import json
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.distributed.elastic import (
    ElasticManager,
    FileStore,
    WorldSpec,
    latest_checkpoint,
    parse_np_range,
)


def test_parse_np_range():
    assert parse_np_range("2:4") == (2, 4)
    assert parse_np_range("3") == (3, 3)


def _mgr(tmp_path, node_id, np=(1, 4), **kw):
    store = FileStore(str(tmp_path), "job1")
    kw.setdefault("heartbeat_interval", 0.05)
    kw.setdefault("fault_timeout", 0.5)
    return ElasticManager(store, np, node_id=node_id, **kw)


def test_membership_and_rerank(tmp_path):
    a = _mgr(tmp_path, "nodeA").register()
    b = _mgr(tmp_path, "nodeB").register()
    try:
        alive, faulted = a.scan()
        assert sorted(alive) == ["nodeA", "nodeB"] and not faulted
        # ranks identical from both observers, ordered by node id
        sa, sb = a.plan(), b.plan()
        assert sa.nnodes == sb.nnodes == 2
        assert sa.node_rank == 0 and sb.node_rank == 1
        assert sa.hosts == sb.hosts
    finally:
        a.deregister()
        b.deregister()


def test_fault_detection_and_eviction(tmp_path):
    a = _mgr(tmp_path, "nodeA").register()
    b = _mgr(tmp_path, "nodeB").register()
    try:
        # kill B's heartbeat (simulated crash) and age its file
        b._stop.set()
        b._hb_thread.join(timeout=2)
        old = time.time() - 10
        os.utime(b.store._path("nodeB"), (old, old))
        alive, faulted = a.scan()
        assert alive == ["nodeA"] and faulted == ["nodeB"]
        assert a.evict_faulted() == ["nodeB"]
        # membership shrinks within np range → re-ranked single world
        spec = a.plan()
        import socket

        assert spec == WorldSpec(nnodes=1, node_rank=0,
                                 hosts=[socket.gethostname()],
                                 node_ids=["nodeA"])
    finally:
        a.deregister()
        b.deregister()


def test_plan_respects_np_range(tmp_path):
    a = _mgr(tmp_path, "nodeA", np=(2, 3)).register()
    try:
        assert a.plan() is None  # below min_np
        b = _mgr(tmp_path, "nodeB", np=(2, 3)).register()
        assert a.plan() is not None
        c = _mgr(tmp_path, "nodeC", np=(2, 3)).register()
        d = _mgr(tmp_path, "nodeD", np=(2, 3)).register()
        assert a.plan() is None  # above max_np
        for m in (b, c, d):
            m.deregister()
    finally:
        a.deregister()


def test_wait_for_world_scale_up(tmp_path):
    a = _mgr(tmp_path, "nodeA", np=(2, 2)).register()
    try:
        import threading

        def join_later():
            time.sleep(0.3)
            _mgr(tmp_path, "nodeB", np=(2, 2)).register()

        t = threading.Thread(target=join_later)
        t.start()
        spec = a.wait_for_world(timeout=5.0, poll=0.05)
        t.join()
        assert spec is not None and spec.nnodes == 2
    finally:
        a.deregister()


def test_latest_checkpoint_skips_incomplete(tmp_path):
    root = tmp_path / "ckpts"
    for step, complete in [(10, True), (20, True), (30, False)]:
        d = root / f"step_{step}"
        d.mkdir(parents=True)
        if complete:
            (d / "metadata.json").write_text(json.dumps({}))
    assert latest_checkpoint(str(root)) == str(root / "step_20")
    assert latest_checkpoint(str(tmp_path / "missing")) is None


def test_launch_elastic_np_membership(tmp_path):
    # end-to-end: launch with --np 1:2 supervises a script that fails
    # once then succeeds after restart (checkpoint-resume pattern)
    script = tmp_path / "worker.py"
    marker = tmp_path / "attempted"
    script.write_text(
        "import os, sys\n"
        f"m = {str(repr(str(marker)))}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').write('x'); sys.exit(1)\n"
        "print('resumed ok', os.environ['PADDLE_TRAINERS_NUM'])\n"
    )
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # plain CPU interpreter for speed
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--elastic", "--max_restarts", "2",
         "--np", "1:1", "--job_id", "t1",
         "--elastic_store", str(tmp_path),
         "--log_dir", str(tmp_path / "log"), str(script)],
        capture_output=True, text=True, timeout=180, env=env,
        cwd="/root/repo",
    )
    assert r.returncode == 0, r.stdout + r.stderr
    log = (tmp_path / "log" / "workerlog.0").read_bytes().decode()
    assert "resumed ok 1" in log


def test_launch_stop_deregisters_heartbeat(tmp_path):
    # after a successful run the heartbeat file must be gone — a ghost
    # node would corrupt the next launch's world
    script = tmp_path / "ok.py"
    script.write_text("print('fine')\n")
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--np", "1:1", "--job_id", "t2",
         "--elastic_store", str(tmp_path), "--elastic_settle", "0.2",
         "--log_dir", str(tmp_path / "log"), str(script)],
        capture_output=True, text=True, timeout=180, env=env,
        cwd="/root/repo",
    )
    assert r.returncode == 0, r.stdout + r.stderr
    reg = tmp_path / "elastic_t2"
    assert not any(f.startswith("node_") for f in os.listdir(reg))
