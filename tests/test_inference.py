"""Inference predictor tests: greedy generate with AOT prefill/decode must
match naive full-context re-forward decoding (parity model: inference
pass tests comparing optimized predictor vs no-pass baseline)."""

import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt
from paddle_tpu.inference import Config, Predictor, create_predictor
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def _naive_greedy(model, ids, n_new):
    ids = np.asarray(ids)
    for _ in range(n_new):
        logits = np.asarray(model(jnp.asarray(ids)))
        nxt = logits[:, -1, :].argmax(-1)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return ids[:, -n_new:]


def test_generate_matches_naive():
    pt.seed(42)
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    prompt = np.random.default_rng(0).integers(0, 256, (2, 7))
    ref = _naive_greedy(model, prompt, 6)

    c = Config()
    c.max_seq_len = 64
    c.seq_buckets = (16, 32)
    c.decode_dtype = jnp.float32
    pred = create_predictor(model, c)
    out = pred.generate(prompt, max_new_tokens=6)
    np.testing.assert_array_equal(out, ref)
    assert pred.last_ttft_ms is not None and pred.last_ttft_ms > 0


def test_run_logits_shape():
    pt.seed(1)
    model = LlamaForCausalLM(LlamaConfig.tiny(use_flash_attention=False))
    pred = Predictor(model)
    logits = pred.run(np.array([[1, 2, 3]]))
    assert logits.shape == (1, 3, 256)


def test_config_parity_knobs():
    c = Config("/some/model/dir")
    c.enable_memory_optim()
    c.switch_ir_optim(True)
    c.set_cpu_math_library_num_threads(4)
    s = c.summary()
    assert s["model_dir"] == "/some/model/dir"
    assert s["cpu_threads"] == 4
