"""Replicated serving: the multi-engine front door.

Under test (``inference/router.py``):
  - health-weighted routing: prefix-affinity (the rolling block-hash
    chain lands shared-prefix traffic where its pages live) with
    least-loaded fallback via ``backpressure()``; fleet-level shedding
    (router holds requests when no replica is routable);
  - the per-replica circuit breaker: closed → open on repeated faults
    in a sliding window (immediately on a crash) → half-open canary →
    closed; deterministic seeded backoff schedules;
  - CROSS-REPLICA FAILOVER: a crashed/hung replica's in-flight and
    queued requests are reclaimed from the host token ledger and
    replayed through a survivor's existing prefill program — greedy
    outputs bit-identical to a fault-free run in BOTH cache modes,
    original admission timestamps preserved for SLO accounting, zero
    leaked slots/pages/prefix refs, zero new compiled programs;
  - cancel/deadline racing a failover: terminal rids are never
    replayed; every rid is accounted exactly once (soak);
  - the engine-side handoff API: ``drain()``'s ``unfinished`` ledger
    payload and ``admit_ledger`` re-admission;
  - the fleet sanitizer invariant (rid owned by exactly one replica
    or queue) and the aggregate ``/healthz``.

The whole module runs in the chaos lane (sanitized via the conftest
autouse fixture), like ``test_resilience.py``.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest
import serving_utils

import paddle_tpu as pt
from paddle_tpu import flags as F
from paddle_tpu.inference.resilience import FaultInjector
from paddle_tpu.inference.router import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    EngineRouter,
)
from paddle_tpu.inference.serving import (
    ContinuousBatchingEngine,
    EngineConfig,
    request_ledger,
    start_metrics_server,
)

pytestmark = pytest.mark.chaos


def _model(seed=0):
    return serving_utils.tiny_model(seed)


def _ecfg(paged, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 128)
    kw.setdefault("seq_buckets", (32,))
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("page_size", 8)
    return EngineConfig(paged=paged, **kw)


def _prompts(cfg, n=6, seed=3, lo=6, hi=20):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, cfg.vocab_size, (int(rng.integers(lo, hi)),))
            for _ in range(n)]


class ScriptedInjector(FaultInjector):
    """fire() hits at EXACT scripted consultation indices per site —
    chaos scenarios that need a fault at one specific (tick, replica)
    point rather than a seeded rate."""

    def __init__(self, plan):
        super().__init__("")
        self._plan = {s: set(v) for s, v in plan.items()}

    def fire(self, site):
        n = self.draws[site]
        self.draws[site] = n + 1
        hit = n in self._plan.get(site, ())
        if hit:
            self.fires[site] += 1
        return hit


def _assert_fleet_no_leaks(router):
    for rep in router._replicas:
        eng = rep.engine
        assert not eng.active.any(), f"replica {rep.idx} leaked a slot"
        assert sorted(eng._free_heap) == list(range(eng.cfg.max_slots))
        assert not eng._slot_req
        if eng.cfg.paged:
            eng._evict_pages(10 ** 9)
            assert eng.pool.free_pages == eng.pool.n_pages - 1, \
                f"replica {rep.idx} leaked pages"
            assert not eng.pool.ref


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_state_machine():
    rng = np.random.default_rng((0xB4EA, 0, 0))
    br = CircuitBreaker(window=8, trip=3, cooldown=4,
                        schedule="1,2,4", rng=rng)
    assert br.state(1) == BREAKER_CLOSED
    assert not br.note_fault(1)
    assert not br.note_fault(2)
    opened = br.note_fault(3)  # 3rd fault in window trips
    assert opened and br.state(3) == BREAKER_OPEN
    t_half = br.reopen_at
    assert t_half >= 3 + 4  # cooldown * schedule[0] (+ jitter)
    # read-only view half-opens at cooldown; advance() commits
    assert br.state(t_half) == BREAKER_HALF_OPEN
    assert br.advance(t_half) == BREAKER_HALF_OPEN
    # canary failure reopens with the NEXT schedule entry (2x)
    assert br.note_fault(t_half)
    assert br.reopen_at >= t_half + 8
    t2 = br.reopen_at
    assert br.advance(t2) == BREAKER_HALF_OPEN
    br.note_ok(t2)  # canary success: closed, backoff reset
    assert br.state(t2) == BREAKER_CLOSED
    assert br.snapshot()["attempt"] == 0
    assert br.opens == 2


def test_breaker_window_ages_out_faults():
    br = CircuitBreaker(4, 3, 4, [1],
                        np.random.default_rng((0xB4EA, 0, 1)))
    assert not br.note_fault(1)
    assert not br.note_fault(2)
    # ticks 1, 2 aged out of the 4-tick window by tick 7: no trip
    assert not br.note_fault(7)
    assert br.state(7) == BREAKER_CLOSED


def test_breaker_backoff_deterministic_per_seed():
    def opens(seed, idx):
        br = CircuitBreaker(
            8, 1, 4, "1,2,4",
            np.random.default_rng((0xB4EA, seed, idx)))
        out = []
        t = 0
        for _ in range(4):
            t += 1
            br.note_fault(t)  # trip=1: every fault opens
            out.append(br.reopen_at - t)
            t = br.reopen_at
            br.advance(t)
        return out
    assert opens(0, 0) == opens(0, 0)  # same stream → same schedule
    durations = opens(0, 0)
    # successive opens back off per the schedule (jitter < cooldown/2
    # can never cancel a 2x multiplier step)
    assert durations[1] > durations[0]
    assert durations[2] > durations[1]


def test_breaker_and_router_validation():
    model, _ = _model()
    with pytest.raises(ValueError, match="n_replicas"):
        EngineRouter(model, _ecfg(False), n_replicas=0)
    with pytest.raises(ValueError, match="hang_ticks"):
        EngineRouter(model, _ecfg(False), hang_ticks=0)
    with pytest.raises(ValueError, match="schedule"):
        EngineRouter(model, _ecfg(False), retry_schedule="1,0")
    with pytest.raises(ValueError, match="breaker"):
        EngineRouter(model, _ecfg(False), breaker_trip=0)


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_fleet_parity_and_spread():
    """A fault-free fleet completes every request with outputs
    bit-identical to a single engine, and balances load across
    replicas (least-loaded fallback). Paged here; the contiguous
    mode's fleet parity is covered by the crash-storm A/B below."""
    model, cfg = _model()
    prompts = _prompts(cfg, n=5)
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        prompts, max_new_tokens=6)
    router = EngineRouter(model, _ecfg(True), n_replicas=2)
    reqs = router.run(prompts, max_new_tokens=6)
    assert [r.output for r in reqs] == [r.output for r in ref]
    owners = {router._owner[r.rid] for r in reqs}
    assert owners == {0, 1}, "least-loaded routing never spread load"
    _assert_fleet_no_leaks(router)


def test_prefix_affinity_routes_to_warm_replica():
    """Shared-prefix traffic lands where its pages already live: after
    the first request publishes its blocks on one replica, later
    requests with the same prefix route there (affinity beats
    least-loaded), while unrelated prompts still balance away."""
    model, cfg = _model()
    rng = np.random.default_rng(11)
    shared = rng.integers(1, cfg.vocab_size, 24)  # 3 hash blocks
    router = EngineRouter(model, _ecfg(True, max_slots=2),
                          n_replicas=2)
    r0 = router.add_request(
        np.concatenate([shared, rng.integers(1, cfg.vocab_size, 4)]), 4)
    while router.step(2):
        pass
    warm = router._owner[r0]
    assert router.result(r0) is not None
    # the warm replica's store answers the affinity probe; cold doesn't
    hashes = router._affinity_hashes(
        router.result(r0))
    assert router._replicas[warm].engine.prefix_affinity_tokens(
        hashes) >= 24
    followers = [
        router.add_request(
            np.concatenate([shared,
                            rng.integers(1, cfg.vocab_size, 5)]), 4)
        for _ in range(3)]
    unrelated = router.add_request(
        rng.integers(1, cfg.vocab_size, 16), 4)
    assert all(router._owner[rid] == warm for rid in followers)
    assert router._owner[unrelated] != warm
    assert router.fleet_stats["affinity_routed"] >= 3
    while router.step(2):
        pass
    _assert_fleet_no_leaks(router)


def test_fleet_shed_holds_when_all_saturated():
    """No routable un-saturated replica → the router HOLDS the request
    in its own queue (fleet-level shed: deferral, never drop) and
    places it as soon as a finisher frees capacity."""
    model, cfg = _model()
    router = EngineRouter(model, _ecfg(False, max_slots=1),
                          n_replicas=2)
    rng = np.random.default_rng(5)
    first = [router.add_request(rng.integers(1, cfg.vocab_size, 8), 12)
             for _ in range(2)]
    router.step(2)  # both replicas occupied
    # queue one request per replica: both become saturated
    second = [router.add_request(rng.integers(1, cfg.vocab_size, 8), 4)
              for _ in range(2)]
    router.step(2)
    held = router.add_request(rng.integers(1, cfg.vocab_size, 8), 4)
    assert held not in router._owner
    assert any(r.rid == held for r in router._queue)
    assert router.fleet_stats["held"] >= 1
    assert router.backpressure()["saturated"]
    while router.step(2):
        pass
    for rid in first + second + [held]:
        req = router.result(rid)
        assert req is not None and req.done
        assert len(req.output) == req.max_new_tokens
    _assert_fleet_no_leaks(router)


# ---------------------------------------------------------------------------
# cross-replica failover
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("paged", [False, True])
def test_crash_storm_outputs_bit_identical(paged, compile_counter):
    """THE acceptance bar: under a seeded replica-crash storm, greedy
    outputs across the fleet are bit-identical to a fault-free run,
    surviving replicas leak nothing, and zero new programs compile
    beyond the post-warmup set."""
    model, cfg = _model()
    prompts = _prompts(cfg, n=6)
    # the fault-free reference is a single engine: fleet placement is
    # output-invariant (pinned by test_fleet_parity_and_spread), so
    # one engine's greedy chains ARE the fault-free fleet's
    ref = ContinuousBatchingEngine(model, _ecfg(paged)).run(
        prompts, max_new_tokens=8, max_chunk=2)
    assert len(ref) == len(prompts)

    inj = FaultInjector("replica_crash:0.25,seed:5")
    router = EngineRouter(model, _ecfg(paged, max_retries=50),
                          n_replicas=2, fault_injector=inj,
                          breaker_cooldown=3)
    # warm-up: compile EVERY replica's programs outside the guard (two
    # prompts spread over both replicas; least-loaded guarantees it) —
    # twice, so the second pass HITS each prefix store and compiles
    # the lazy hit-path programs too (contig insert/read, paged COW
    # copy). Warmup prompts span >= 2 hash blocks so the store
    # publish/read paths definitely trace on BOTH replicas.
    wrng = np.random.default_rng(99)
    warm = [wrng.integers(1, cfg.vocab_size, 20) for _ in range(2)]
    router.run(warm, max_new_tokens=2, max_chunk=2)
    router.run(warm, max_new_tokens=2, max_chunk=2)
    base = compile_counter()
    reqs = router.run(prompts, max_new_tokens=8, max_chunk=2)
    fs = router.fleet_snapshot()
    assert fs["failovers"] >= 1, "storm never killed a replica"
    assert fs["replayed"] + fs["held"] >= 1
    assert [r.output for r in reqs] == [r.output for r in ref]
    assert all(r.finish_reason == "max_new_tokens" for r in reqs)
    # failover replays ride the EXISTING prefill/decode programs:
    # zero new compiled programs per replica (rebuilds keep shapes)
    compile_counter.assert_programs(set(base))
    assert compile_counter() == base
    _assert_fleet_no_leaks(router)


def test_single_crash_preserves_admission_timestamps():
    """A scripted crash mid-generation: the victims' original
    TTFT/admit instants survive the move (SLO accounting keeps the
    honest wall from FIRST admission), ownership transfers to the
    survivor, and outputs stay exact."""
    model, cfg = _model()
    prompts = _prompts(cfg, n=4, seed=9)
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        prompts, max_new_tokens=8, max_chunk=2)
    # 2 replicas, both closed: crash consultation 4 = tick 3, replica 0
    inj = ScriptedInjector({"replica_crash": {4}})
    router = EngineRouter(model, _ecfg(True), n_replicas=2,
                          fault_injector=inj)
    rids = [router.add_request(p, 8, slo="interactive")
            for p in prompts]
    router.step(2)
    router.step(2)
    stamped = {
        req.rid: (req.ttft_ms, req._admit_t, req._submit_t)
        for rep in router._replicas
        for req in rep.engine._slot_req.values()}
    victims = [req.rid for req
               in router._replicas[0].engine._slot_req.values()]
    assert victims, "replica 0 held nothing — scenario is vacuous"
    while router.step(2):
        pass
    assert router.fleet_stats["failovers"] == 1
    assert inj.fires["replica_crash"] == 1
    for i, rid in enumerate(rids):
        req = router.result(rid)
        assert req is not None
        assert req.output == ref[i].output
    for rid, (ttft, admit, submit) in stamped.items():
        req = router.result(rid)
        assert req.ttft_ms == ttft, "TTFT rewritten by failover"
        assert req._admit_t == admit
        assert req._submit_t == submit
    for rid in victims:
        assert router._owner[rid] == 1, "victim not moved to survivor"
        assert router._replicas[1].engine._finished[rid].slo_met \
            is not None  # SLO accounted on the survivor
    _assert_fleet_no_leaks(router)


def test_hang_opens_breaker_then_canary_recovers():
    """A hung replica (no-progress health probes) opens its breaker
    after `trip` stalled ticks and fails its work over; once the hang
    passes and the cooldown elapses, the half-open canary closes the
    breaker and the replica serves again."""
    model, cfg = _model()
    prompts = _prompts(cfg, n=4, seed=7)
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        prompts, max_new_tokens=6, max_chunk=2)
    inj = ScriptedInjector({"replica_hang": {2}})  # tick 2, replica 0
    router = EngineRouter(model, _ecfg(True), n_replicas=2,
                          fault_injector=inj, breaker_trip=2,
                          breaker_cooldown=2, hang_ticks=4)
    rids = [router.add_request(p, 6) for p in prompts]
    while router.step(2):
        pass
    fs = router.fleet_snapshot()
    assert fs["breaker_opens"] >= 1
    assert fs["failovers"] >= 1
    assert [router.result(r).output for r in rids] \
        == [r.output for r in ref]
    # idle fleet ticks: the hang passes, the cooldown elapses, and the
    # half-open canary probe closes the breaker again
    for _ in range(8):
        router.step(2)
    fs = router.fleet_snapshot()
    assert all(b["name"] == "closed" for b in fs["breakers"])
    # …and the recovered replica takes traffic again
    more = router.run(_prompts(cfg, n=3, seed=8), max_new_tokens=4,
                      max_chunk=2)
    assert len(more) == 3
    assert {router._owner[r.rid] for r in more} == {0, 1}
    _assert_fleet_no_leaks(router)


def test_flaky_probe_does_not_flap_breaker():
    """Isolated flaky health-probe verdicts stay UNDER the breaker's
    trip threshold: no open, no failover — the sliding window is the
    flap damping."""
    model, cfg = _model()
    # two flakes, far apart (well outside the 4-tick window)
    inj = ScriptedInjector({"probe_flaky": {1, 40}})
    router = EngineRouter(model, _ecfg(False), n_replicas=2,
                          fault_injector=inj, breaker_window=4,
                          breaker_trip=2)
    reqs = router.run(_prompts(cfg, n=4), max_new_tokens=6,
                      max_chunk=2)
    assert len(reqs) == 4
    fs = router.fleet_snapshot()
    assert fs["breaker_opens"] == 0
    assert fs["failovers"] == 0
    assert inj.fires["probe_flaky"] >= 1


def test_cancel_and_deadline_expiry_never_replay():
    """The failover race the satellite pins: a cancelled rid and a
    deadline-expired rid caught in a replica crash must NOT be
    replayed onto the survivor — each is accounted exactly once, in
    exactly one terminal registry."""
    model, cfg = _model()
    prompts = _prompts(cfg, n=4, seed=13, lo=8, hi=12)
    inj = ScriptedInjector({"replica_crash": {4}})  # tick 3, replica 0
    router = EngineRouter(model, _ecfg(True, max_slots=2),
                          n_replicas=2, fault_injector=inj)
    rids = [router.add_request(p, 40) for p in prompts]
    doomed = router.add_request(prompts[0], 40, deadline_ms=1.0)
    router.step(2)
    router.step(2)
    # cancel one request currently ACTIVE on replica 0 (the replica
    # the scripted crash will hit next tick)
    vic = next(iter(
        router._replicas[0].engine._slot_req.values())).rid
    assert router.cancel(vic)
    time.sleep(0.005)  # the doomed deadline expires
    while router.step(2):
        pass
    assert router.fleet_stats["failovers"] == 1
    cancelled = router.result(vic)
    assert cancelled.cancelled and cancelled.finish_reason == "cancel"
    expired = router.result(doomed)
    assert expired.finish_reason == "timeout"
    # neither lives anywhere in the fleet
    for rep in router._replicas:
        assert vic not in [r.rid for r in rep.engine._queue]
        assert vic not in [r.rid for r
                           in rep.engine._slot_req.values()]
    # every rid accounted EXACTLY once across all finish registries
    regs = [router._finished] + [rep.engine._finished
                                 for rep in router._replicas]
    for rid in rids + [doomed]:
        places = sum(1 for reg in regs if rid in reg)
        assert places == 1, (rid, places)
    _assert_fleet_no_leaks(router)


def test_hard_runtime_error_opens_breaker_immediately():
    """A REAL runtime error escaping the engine's own recovery may
    have consumed donated device buffers: the router must open the
    breaker and rebuild NOW — never keep stepping an untrusted
    replica while a fault window fills."""
    from paddle_tpu.inference.resilience import RUNTIME_ERRORS

    if not RUNTIME_ERRORS:
        pytest.skip("no XLA runtime error class in this jaxlib")
    model, cfg = _model()
    router = EngineRouter(model, _ecfg(True), n_replicas=2)
    rids = [router.add_request(p, 6) for p in _prompts(cfg, n=3)]
    router.step(2)
    victim = router._replicas[0].engine
    real_step = victim.step_chunk

    def boom(max_chunk=8):
        victim.step_chunk = real_step  # fail exactly once
        raise RUNTIME_ERRORS[0]("donated buffer consumed")

    victim.step_chunk = boom
    router.step(2)  # the failing tick
    fs = router.fleet_snapshot()
    assert fs["breaker_opens"] == 1 and fs["failovers"] == 1
    assert fs["breakers"][0]["name"] == "open"
    assert victim.resilience_stats["rebuilds"] == 1
    while router.step(2):
        pass
    for rid in rids:
        req = router.result(rid)
        assert req is not None and len(req.output) == 6
    _assert_fleet_no_leaks(router)


def test_fresh_arrivals_queue_behind_held_requests():
    """FIFO fairness: while older requests sit held at the router, a
    fresh arrival must not steal capacity a finisher frees — held
    requests place first (admission order is completion order on a
    1-slot fleet)."""
    model, cfg = _model()
    router = EngineRouter(model, _ecfg(False, max_slots=1),
                          n_replicas=1)
    rng = np.random.default_rng(4)
    base = [router.add_request(rng.integers(1, cfg.vocab_size, 8), 6)
            for _ in range(2)]  # slot + replica queue: saturated
    router.step(2)
    held_a = router.add_request(rng.integers(1, cfg.vocab_size, 8), 4)
    assert any(r.rid == held_a for r in router._queue)
    router.step(2)
    fresh_b = router.add_request(rng.integers(1, cfg.vocab_size, 8), 4)
    # B arrived while A was held: it must queue BEHIND A, even if a
    # slot frees between the submissions
    assert [r.rid for r in router._queue
            if r.rid in (held_a, fresh_b)] == [held_a, fresh_b]
    while router.step(2):
        pass
    a, b = router.result(held_a), router.result(fresh_b)
    assert a._admit_t < b._admit_t, "fresh arrival jumped the line"
    for rid in base + [held_a, fresh_b]:
        assert len(router.result(rid).output) \
            == router.result(rid).max_new_tokens
    _assert_fleet_no_leaks(router)


def test_held_expiry_counts_against_fleet_slo():
    """An SLO-tracked request that expires while HELD at the router
    is a real violation: it must land in the fleet slo_snapshot
    (goodput must not be inflated by requests that never reached an
    engine), and a held cancel counts as cancelled, not violated."""
    model, cfg = _model()
    router = EngineRouter(model, _ecfg(False, max_slots=1),
                          n_replicas=1)
    rng = np.random.default_rng(8)
    for _ in range(2):  # saturate the 1-slot fleet
        router.add_request(rng.integers(1, cfg.vocab_size, 8), 20,
                           slo="interactive")
    router.step(2)
    doomed = router.add_request(rng.integers(1, cfg.vocab_size, 8), 4,
                                slo="interactive", deadline_ms=1.0)
    cancelled = router.add_request(
        rng.integers(1, cfg.vocab_size, 8), 4, slo="interactive")
    assert any(r.rid == doomed for r in router._queue)
    assert router.cancel(cancelled)
    time.sleep(0.005)
    router.step(2)
    assert router.result(doomed).finish_reason == "timeout"
    st = router.slo_snapshot()["classes"]["interactive"]
    assert st["timeouts"] == 1 and st["violated"] == 1
    assert st["cancelled"] == 1
    while router.step(2):
        pass
    snap = router.slo_snapshot()
    cls = snap["classes"]["interactive"]
    # the two served requests met-or-violated on their engine; the
    # held timeout stays merged in — fleet goodput sees all three
    assert cls["met"] + cls["violated"] == 3
    assert cls["violated"] >= 1
    assert snap["goodput"] is not None and snap["goodput"] < 1.0


def test_fleet_sanitizer_catches_dual_ownership():
    """PT_FLAGS_sanitize (on for the chaos lane): a rid present on two
    replicas at once — the bug class failover exists to avoid — trips
    the fleet invariant at the next router tick."""
    from paddle_tpu.analysis.sanitizer import SanitizerError

    model, cfg = _model()
    router = EngineRouter(model, _ecfg(False), n_replicas=2)
    rid = router.add_request(np.arange(1, 9), 16)
    owner = router._owner[rid]
    other = router._replicas[1 - owner].engine
    req = next(
        (r for r in router._replicas[owner].engine._queue
         if r.rid == rid), None) \
        or router._replicas[owner].engine._slot_req.get(0)
    other._queue.append(req)  # the corruption: same rid, two owners
    with pytest.raises(SanitizerError, match="rid-ownership"):
        router.step(2)


# ---------------------------------------------------------------------------
# handoff API (drain ledgers -> admit_ledger)
# ---------------------------------------------------------------------------

def test_admit_ledger_continues_bit_identically():
    """Mid-generation handoff: drain a single engine, re-admit its
    unfinished ledgers on a FRESH engine — the continuation is the
    same greedy chain, token for token, with the original TTFT."""
    model, cfg = _model()
    prompts = _prompts(cfg, n=2, seed=21)
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        prompts, max_new_tokens=10)
    src = ContinuousBatchingEngine(model, _ecfg(True))
    for p in prompts:
        src.add_request(p, 10)
    src.step_chunk(3)  # admit + a few tokens
    summary = src.drain(deadline_ms=1.0, max_chunk=2)
    assert summary["expired"] == 2
    ledgers = summary["unfinished"]
    assert len(ledgers) == 2
    assert all(0 < len(led["output"]) < 10 for led in ledgers)
    dst = ContinuousBatchingEngine(model, _ecfg(True))
    for led in ledgers:
        assert dst.admit_ledger(led) == led["rid"]
    while dst.step_chunk(3) or dst._queue or dst.active.any():
        pass
    for led, r in zip(ledgers, ref):
        got = dst._finished[led["rid"]]
        assert got.output == r.output
        assert got.ttft_ms == led["ttft_ms"]  # first admission's TTFT
        assert got.finish_reason == "max_new_tokens"


def test_admit_ledger_rejects_known_rid():
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    rid = eng.add_request(np.arange(1, 9), 4)
    led = request_ledger(list(eng._queue)[0])
    with pytest.raises(ValueError, match="already owned"):
        eng.admit_ledger(led)
    # and rids adopted from a ledger keep the local counter ahead
    eng2 = ContinuousBatchingEngine(model, _ecfg(False))
    eng2.admit_ledger(led)
    assert eng2.add_request(np.arange(1, 9), 4) == rid + 1


def test_router_drain_returns_fleet_handoff_payload():
    model, cfg = _model()
    router = EngineRouter(model, _ecfg(False, max_slots=1),
                          n_replicas=2)
    rng = np.random.default_rng(2)
    rids = [router.add_request(rng.integers(1, cfg.vocab_size, 8), 30)
            for _ in range(3)]
    router.step(2)
    summary = router.drain(deadline_ms=10.0, max_chunk=2)
    assert summary["drained"] and summary["expired"] >= 1
    got = {led["rid"] for led in summary["unfinished"]}
    done = {rid for rid in rids if router.result(rid) is not None
            and router.result(rid).finish_reason == "max_new_tokens"}
    assert got == set(rids) - done
    assert router.backpressure()["draining"]
    router.resume()
    assert not router.backpressure()["draining"]


# ---------------------------------------------------------------------------
# aggregate healthz + snapshots
# ---------------------------------------------------------------------------

def test_router_aggregate_healthz():
    model, cfg = _model()
    router = EngineRouter(model, _ecfg(False), n_replicas=2)
    router.run(_prompts(cfg, n=2), max_new_tokens=3)
    srv = start_metrics_server(router, port=0)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert r.status == 200
            hz = json.loads(r.read())
        assert hz["status"] == "ok"
        assert hz["degradation_level"] == 0
        bp = hz["backpressure"]
        assert bp["routable_replicas"] == 2
        assert len(bp["replicas"]) == 2
        assert all(rep["breaker"] == "closed"
                   for rep in bp["replicas"])
        assert len(hz["engine"]["replicas"]) == 2
        # fleet drain → aggregate healthz fails readiness
        router.drain(deadline_ms=5.0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "draining"
        router.resume()
    finally:
        srv.shutdown()


def test_fleet_snapshot_and_metrics_always_present():
    """Host-side fleet counters survive telemetry=off (the session
    default), and the unified snapshot carries router + replicas."""
    model, cfg = _model()
    inj = FaultInjector("replica_crash:0.3,seed:2")
    router = EngineRouter(model, _ecfg(True, max_retries=50),
                          n_replicas=2, fault_injector=inj,
                          breaker_cooldown=2)
    assert router._tel is None  # telemetry off in the test session
    router.run(_prompts(cfg, n=4), max_new_tokens=6, max_chunk=2)
    snap = router.metrics_snapshot()
    assert snap["telemetry"] == "off"
    fs = snap["fleet"]
    assert fs["failovers"] >= 1 and fs["routed"] >= 4
    assert len(fs["breakers"]) == 2
    assert fs["injector"]["enabled"]
    assert len(snap["replicas"]) == 2
    for rsnap in snap["replicas"]:
        assert "resilience" in rsnap and "slots" in rsnap


def test_router_telemetry_counters():
    """With telemetry ON: routed/failover/breaker series land in the
    registry under the router's label and the tracer records
    route/failover events."""
    saved = {k: F.flag(k) for k in ("telemetry",)}
    F.set_flags({"telemetry": True})
    try:
        from paddle_tpu import observability as obs

        model, cfg = _model()
        inj = ScriptedInjector({"replica_crash": {4}})
        router = EngineRouter(model, _ecfg(True), n_replicas=2,
                              fault_injector=inj)
        assert router._tel is not None
        router.run(_prompts(cfg, n=4), max_new_tokens=6, max_chunk=2)
        snap = router._tel.snapshot()
        assert snap["routed"] >= 4
        assert snap["failovers"] == 1
        assert snap["breaker_opens"] == 1
        text = obs.get_registry().prometheus_text()
        assert "pt_router_requests_routed_total" in text
        assert "pt_router_failovers_total" in text
        assert "pt_router_breaker_state" in text
        events = [e["name"] for e in router._tracer.events()]
        assert "route" in events and "failover" in events
        assert "breaker_open" in events
        # idle ticks advance the open breaker's cooldown; once the
        # canary runs, the open->half_open commit must be visible
        # (gauge encoding 2 reachable; /metrics agrees with /healthz)
        for _ in range(64):
            router.step(2)
            if all(r.breaker._state == BREAKER_CLOSED
                   for r in router._replicas):
                break
        events = [e["name"] for e in router._tracer.events()]
        assert "breaker_half_open" in events and "breaker_close" in events
        # held terminals: saturate the fleet, then let one held
        # request expire and cancel another — the pt_router_* twins
        # of the engine-side timeout/cancel counters must fire (a
        # dashboard watching only pt_serve_* would miss these)
        rng = np.random.default_rng(4)
        for _ in range(6):  # 2 active + 1 queued per replica
            router.add_request(rng.integers(1, cfg.vocab_size, 8), 12)
        router.step(2)
        doomed = router.add_request(
            rng.integers(1, cfg.vocab_size, 8), 4, deadline_ms=1.0)
        gone = router.add_request(rng.integers(1, cfg.vocab_size, 8), 4)
        assert any(r.rid == gone for r in router._queue)
        assert router.cancel(gone)
        time.sleep(0.005)
        while router.step(2):
            pass
        assert router.result(doomed).finish_reason == "timeout"
        snap = router._tel.snapshot()
        assert snap["held_timeouts"] == 1
        assert snap["held_cancels"] == 1
        text = obs.get_registry().prometheus_text()
        assert "pt_router_requests_timeout_total" in text
        assert "pt_router_requests_cancelled_total" in text
        events = [e["name"] for e in router._tracer.events()]
        assert "held_timeout" in events and "held_cancel" in events
    finally:
        F.set_flags(saved)


# ---------------------------------------------------------------------------
# replica-kill storm soak
# ---------------------------------------------------------------------------

def test_fleet_kill_storm_soak():
    """The replica-kill storm: producer-thread arrivals × seeded
    crash/hang/flaky storm × a cancel storm, sanitized (fleet
    rid-ownership invariant checked every tick). Afterwards: every
    rid is accounted EXACTLY once across the fleet's finish
    registries, survivors carry their exact token counts, every pool
    recovers, and the fleet still serves."""
    model, cfg = _model()
    inj = FaultInjector(
        "replica_crash:0.06,replica_hang:0.05,probe_flaky:0.08,seed:19")
    router = EngineRouter(model, _ecfg(True, max_slots=2,
                                       max_retries=100),
                          n_replicas=3, fault_injector=inj,
                          breaker_cooldown=2, hang_ticks=2)
    n_requests, new_tokens = 13, 6
    rng = np.random.default_rng(3)
    shared = rng.integers(1, cfg.vocab_size, 16)
    prompts = [np.concatenate(
        [shared, rng.integers(1, cfg.vocab_size,
                              (int(rng.integers(2, 10)),))])
        for _ in range(n_requests)]
    ids = []
    errs = []
    prng = np.random.default_rng(7)

    def producer():
        try:
            for p in prompts:
                ids.append(router.add_request(p, new_tokens))
                time.sleep(float(prng.uniform(0.0, 0.01)))
        except BaseException as e:
            errs.append(e)

    t = threading.Thread(target=producer)
    t.start()
    cancelled = set()
    deadline = time.time() + 240
    while time.time() < deadline:
        busy = router.step(4)
        for rid in list(ids):
            if rid % 4 == 0 and rid not in cancelled \
                    and router.cancel(rid):
                cancelled.add(rid)
        if not t.is_alive() and not busy:
            done = sum(1 for rid in ids
                       if router.result(rid) is not None)
            if done >= n_requests:
                break
    t.join(timeout=10)
    assert not errs, errs
    assert router.fleet_stats["failovers"] >= 1, "storm was vacuous"
    assert cancelled
    regs = [router._finished] + [rep.engine._finished
                                 for rep in router._replicas]
    for rid in ids:
        places = sum(1 for reg in regs if rid in reg)
        assert places == 1, \
            f"rid {rid} accounted {places} times (must be exactly 1)"
        req = router.result(rid)
        if rid in cancelled:
            assert req.cancelled
        elif req.finish_reason == "max_new_tokens":
            assert len(req.output) == new_tokens
        else:
            assert req.finish_reason in ("timeout", "failed")
    _assert_fleet_no_leaks(router)
    # the fleet still serves after the storm
    router._injector = None
    out = router.run([prompts[0]], max_new_tokens=4, max_chunk=2)
    assert len(out) == 1 and len(out[0].output) == 4
