"""Benchmark-suite contract tests.

Round-3 postmortem: the SD-UNet config shipped with an NHWC sample fed
to an NCHW model and crashed on every backend, and the driver-facing
JSON line ballooned past parseability. These tests pin both contracts:
every BASELINE config must execute end-to-end on CPU, and the printed
line must stay small and parseable no matter how much diagnostic bloat
the run accumulates (reference: Paddle's benchmark suite smoke jobs,
test/legacy_test pattern of running each trainer config tiny on CPU).
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

CONFIGS = ["moe", "vit", "unet", "mamba", "infer", "serve7b"]


@pytest.mark.parametrize("name", CONFIGS)
def test_config_runs_on_cpu(name):
    """Each BASELINE secondary config must run end-to-end (model
    construction, data layout, train/infer step) on the CPU smoke size —
    so benchmark/model input contracts cannot drift silently."""
    from benchmarks.suite import run_config

    r = run_config(name)
    assert r["unit"] not in ("error", "skipped"), r
    assert r["value"] > 0, r
    assert isinstance(r["metric"], str) and r["metric"]
    # every result must be one JSON-serializable dict
    json.dumps(r)


def test_headline_cpu_smoke():
    """The headline llama bench body itself (not via subprocess)."""
    import bench

    r = bench.bench_llama_train(None)
    assert r["value"] > 0
    assert r["unit"] == "tokens/s/chip"


def _fat_result():
    """A worst-case result dict shaped like round 3's failure: embedded
    tracebacks and duplicated probe diagnostics in every secondary."""
    probe = {"tpu_unavailable": True,
             "attempts": [{"attempt": i, "rc": "timeout",
                           "stderr_tail": "x" * 800} for i in range(2)]}
    sec = {}
    for name in CONFIGS:
        sec[name] = {
            "metric": f"bench_{name}_failed", "value": 0.0,
            "unit": "error", "vs_baseline": 0.0,
            "extra": {"error": "E" * 500, "traceback": "T" * 1500,
                      "tpu_probe": probe},
        }
    return {
        "metric": "llama_train_cpu_smoke_tokens_per_sec",
        "value": 1234.5, "unit": "tokens/s/chip", "vs_baseline": 1.0,
        "extra": {"platform": "cpu", "n_chips": 1, "params": 10 ** 9,
                  "step_ms": 10.0, "loss": 2.5, "tpu_probe": probe,
                  "op_summary": {"top_ops": [{"name": "o" * 60}] * 8},
                  "secondary": sec},
    }


def test_compact_line_contract(tmp_path, monkeypatch):
    """The driver-facing line must stay < 2KB and parseable even when
    every secondary fails with a full traceback; full diagnostics land
    in BENCH_DETAILS.json."""
    import bench

    details = tmp_path / "BENCH_DETAILS.json"
    monkeypatch.setattr(bench, "DETAILS_PATH", str(details))
    line = bench._compact_line(_fat_result())
    assert len(line) < 2048, len(line)
    parsed = json.loads(line)
    assert parsed["metric"] == "llama_train_cpu_smoke_tokens_per_sec"
    assert parsed["value"] == 1234.5
    # secondaries survive compaction with truncated errors
    sec = parsed["extra"]["secondary"]
    assert set(sec) == set(CONFIGS)
    for row in sec.values():
        assert len(row.get("error", "")) <= 120
    # full diagnostics preserved in the side file
    full = json.loads(details.read_text())
    assert full["extra"]["secondary"]["moe"]["extra"]["traceback"] == \
        "T" * 1500


def test_compact_line_cpu_fallback_carries_capture_pointer(
        tmp_path, monkeypatch):
    """Any cpu-plane headline (probe failure OR explicit
    JAX_PLATFORMS=cpu) must name the freshest COMMITTED device capture
    (timestamp + commit + headline metric) so the driver ledger always
    points at verifiable evidence — and the pointer must survive the
    final over-2KB shed. A tpu-plane result must NOT carry one."""
    import bench

    monkeypatch.setattr(bench, "DETAILS_PATH",
                        str(tmp_path / "BENCH_DETAILS.json"))
    cap = tmp_path / "BENCH_TPU_CAPTURE.json"
    cap.write_text(json.dumps({
        "captured_at": "2026-07-31T07:16:14Z", "headline": "llama_b4",
        "configs": {"llama_b4": {
            "metric": "llama876m_train_tokens_per_sec_per_chip",
            "value": 25933.2, "unit": "tokens/s/chip"}}}))
    monkeypatch.setattr(bench, "CAPTURE_PATH", str(cap))

    fat = _fat_result()
    parsed = json.loads(bench._compact_line(fat))
    ptr = parsed["extra"]["last_device_capture"]
    assert ptr["captured_at"] == "2026-07-31T07:16:14Z"
    assert ptr["metric"] == "llama876m_train_tokens_per_sec_per_chip"
    assert ptr["value"] == 25933.2
    # uncommitted tmp file: identity rides without git provenance
    assert "commit" not in ptr

    # explicit-cpu line (no tpu_probe at all) still carries it
    slim = {"metric": "llama_train_cpu_smoke_tokens_per_sec",
            "value": 90.0, "unit": "tokens/s/chip", "vs_baseline": 1.0,
            "extra": {"platform": "cpu", "n_chips": 1}}
    parsed = json.loads(bench._compact_line(slim))
    assert parsed["extra"]["last_device_capture"]["value"] == 25933.2

    # the final shed keeps it: with the byte budget squeezed below the
    # compacted fat line, extra collapses to its survival set and the
    # pointer must be in it
    monkeypatch.setattr(bench, "MAX_LINE_BYTES", 500)
    shed = json.loads(bench._compact_line(fat))
    assert set(shed["extra"]) <= {"platform", "n_chips",
                                  "last_device_capture"}
    assert shed["extra"]["last_device_capture"]["value"] == 25933.2
    monkeypatch.setattr(bench, "MAX_LINE_BYTES", 2000)

    # a tpu-plane result never points at itself
    tpu = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
           "extra": {"platform": "tpu", "n_chips": 1}}
    assert "last_device_capture" not in \
        json.loads(bench._compact_line(tpu))["extra"]


def test_compact_line_headline_error(tmp_path, monkeypatch):
    """A failed headline must carry its own truncated diagnostics on the
    printed line (round-3 regression: only secondaries kept errors)."""
    import bench

    monkeypatch.setattr(bench, "DETAILS_PATH",
                        str(tmp_path / "BENCH_DETAILS.json"))
    r = {"metric": "bench_llama_failed", "value": 0.0, "unit": "error",
         "vs_baseline": 0.0,
         "extra": {"rc": 1, "stderr": "S" * 900,
                   "secondary": {"mamba": {
                       "metric": "bench_mamba_timeout", "value": 0.0,
                       "unit": "error", "extra": {"timeout_s": 420}}}}}
    parsed = json.loads(bench._compact_line(r))
    assert parsed["extra"]["error"] == "S" * 120
    assert parsed["extra"]["secondary"]["mamba"]["error"] == \
        "timeout after 420s"


def test_compact_line_healthy_result(tmp_path, monkeypatch):
    """A green TPU-shaped result keeps its headline scalars."""
    import bench

    monkeypatch.setattr(bench, "DETAILS_PATH",
                        str(tmp_path / "BENCH_DETAILS.json"))
    r = {"metric": "llama876m_train_tokens_per_sec_per_chip",
         "value": 21083.0, "unit": "tokens/s/chip", "vs_baseline": 1.0,
         "extra": {"platform": "tpu", "n_chips": 1, "mfu_est": 0.563,
                   "step_ms": 388.0,
                   "secondary": {"infer": {"metric": "infer_p50_ttft_ms",
                                           "value": 12.0, "unit": "ms",
                                           "vs_baseline": 1.0,
                                           "extra": {"platform": "tpu"}}}}}
    parsed = json.loads(bench._compact_line(r))
    assert parsed["extra"]["mfu_est"] == 0.563
    assert parsed["extra"]["secondary"]["infer"]["value"] == 12.0
    assert "error" not in parsed["extra"]["secondary"]["infer"]


def test_compact_line_carries_audit_verdict(tmp_path, monkeypatch):
    """The serve7b ptaudit verdict rides the ledger line (programs /
    op_counts_ok / violations — compact, never the full report) and
    is shed with the other secondary detail when the line must
    shrink."""
    import bench

    monkeypatch.setattr(bench, "DETAILS_PATH",
                        str(tmp_path / "BENCH_DETAILS.json"))
    r = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
         "extra": {"platform": "tpu", "n_chips": 1, "secondary": {
             "serve7b": {
                 "metric": "serve7b_tokens_per_sec", "value": 100.0,
                 "unit": "tokens/s", "vs_baseline": 1.0,
                 "extra": {"audit": {
                     "programs": 20, "op_counts_ok": True,
                     "violations": 0, "rules": [],
                     "wall_s": 3.2}}}}}}
    row = json.loads(bench._compact_line(r))["extra"]["secondary"][
        "serve7b"]
    # compact triple only — rules/wall stay in BENCH_DETAILS.json
    assert row["audit"] == {"programs": 20, "op_counts_ok": True,
                            "violations": 0}
    monkeypatch.setattr(bench, "MAX_LINE_BYTES", 200)
    shed = json.loads(bench._compact_line(r))
    sec = shed["extra"].get("secondary", {}).get("serve7b", {})
    assert "audit" not in sec


def test_compact_line_carries_flight_scalars(tmp_path, monkeypatch):
    """The serve7b flight-data summary rides the ledger line
    (burn_rate_peak / req_device_ms_p50 / alerts_fired, plus the
    mid-QPS row's burn_rate) and is shed with the other secondary
    detail when the line must shrink."""
    import bench

    monkeypatch.setattr(bench, "DETAILS_PATH",
                        str(tmp_path / "BENCH_DETAILS.json"))
    r = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
         "extra": {"platform": "tpu", "n_chips": 1, "secondary": {
             "serve7b": {
                 "metric": "serve7b_tokens_per_sec", "value": 100.0,
                 "unit": "tokens/s", "vs_baseline": 1.0,
                 "extra": {"goodput_under_slo": {
                     "sweep": [
                         {"qps": 2.0, "goodput": 1.0,
                          "p99_ttft_ms": 30.0, "p99_tpot_ms": 8.0,
                          "burn_rate": 0.0},
                         {"qps": 8.0, "goodput": 0.5,
                          "p99_ttft_ms": 90.0, "p99_tpot_ms": 20.0,
                          "burn_rate": 5.0},
                     ],
                     "flight": {"burn_rate_peak": 5.0,
                                "req_device_ms_p50": 1.25,
                                "alerts_fired": 2}}}}}}}
    row = json.loads(bench._compact_line(r))["extra"]["secondary"][
        "serve7b"]
    assert row["flight"] == {"burn_rate_peak": 5.0,
                             "req_device_ms_p50": 1.25,
                             "alerts_fired": 2}
    assert row["goodput"]["burn_rate"] == 0.0  # mid row of 2 = first
    monkeypatch.setattr(bench, "MAX_LINE_BYTES", 400)
    shed = json.loads(bench._compact_line(r))
    sec = shed["extra"].get("secondary", {}).get("serve7b", {})
    assert "flight" not in sec and "goodput" not in sec
