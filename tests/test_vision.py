"""Vision package: transforms, datasets, model zoo (parity:
python/paddle/vision/ tests — transform shape/value checks, folder
datasets, model forward shapes)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.functional import extract_params, functional_call
from paddle_tpu.vision import datasets, models, transforms as T


class TestTransforms:
    def test_to_tensor_chw_scaling(self):
        img = np.full((4, 6, 3), 255, dtype=np.uint8)
        out = T.ToTensor()(img)
        assert out.shape == (3, 4, 6)
        assert np.allclose(out, 1.0)

    def test_normalize(self):
        img = np.ones((3, 4, 4), dtype=np.float32)
        out = T.Normalize(mean=[1, 1, 1], std=[2, 2, 2])(img)
        assert np.allclose(out, 0.0)

    def test_resize_bilinear_matches_pil(self):
        from PIL import Image

        # smooth horizontal ramp: any sane resampler reproduces it
        ramp = np.tile(
            np.linspace(0, 255, 16, dtype=np.uint8), (16, 1)
        )[:, :, None].repeat(3, axis=2)
        out_np = T.Resize((8, 8))(ramp)
        out_pil = np.asarray(T.Resize((8, 8))(Image.fromarray(ramp)))
        assert out_np.shape == (8, 8, 3)
        assert out_pil.shape == (8, 8, 3)
        assert np.abs(out_np.astype(int) - out_pil.astype(int)).mean() < 10

    def test_resize_int_preserves_aspect_ratio(self):
        arr = np.zeros((100, 50, 3), dtype=np.uint8)  # portrait
        out = T.Resize(60)(arr)
        assert out.shape[:2] == (120, 60)  # shorter edge → 60
        out2 = T.Resize(60)(np.zeros((50, 100, 3), dtype=np.uint8))
        assert out2.shape[:2] == (60, 120)

    def test_normalize_grayscale_stays_single_channel(self):
        img = np.full((1, 8, 8), 0.5, dtype=np.float32)
        out = T.Normalize(mean=0.5, std=0.5)(img)
        assert out.shape == (1, 8, 8)
        assert np.allclose(out, 0.0)

    def test_center_crop_and_flip(self):
        arr = np.arange(5 * 5).reshape(5, 5).astype(np.uint8)[:, :, None]
        c = T.CenterCrop(3)(arr)
        assert c.shape == (3, 3, 1)
        assert c[1, 1, 0] == arr[2, 2, 0]
        f = T.RandomHorizontalFlip(prob=1.0)(arr)
        assert np.array_equal(f[:, ::-1], arr)

    def test_random_resized_crop_shape(self):
        arr = np.zeros((32, 48, 3), dtype=np.uint8)
        out = T.RandomResizedCrop(16)(arr)
        assert out.shape[:2] == (16, 16)

    def test_compose_pipeline(self):
        pipe = T.Compose([
            T.Resize(12),
            T.CenterCrop(8),
            T.ToTensor(),
            T.Normalize(mean=[0.5] * 3, std=[0.5] * 3),
        ])
        out = pipe(np.zeros((20, 24, 3), dtype=np.uint8))
        assert out.shape == (3, 8, 8)
        assert np.allclose(out, -1.0)


class TestDatasets:
    def test_fake_data_deterministic(self):
        ds = datasets.FakeData(num_samples=8, image_shape=(3, 8, 8))
        img1, y1 = ds[3]
        img2, y2 = ds[3]
        assert np.array_equal(img1, img2) and y1 == y2
        assert len(ds) == 8

    def test_mnist_idx_roundtrip(self, tmp_path):
        import struct

        n, r, c = 5, 4, 4
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (n, r, c), dtype=np.uint8)
        labels = rng.integers(0, 10, (n,), dtype=np.uint8)
        ip = tmp_path / "images-idx3-ubyte"
        lp = tmp_path / "labels-idx1-ubyte"
        ip.write_bytes(struct.pack(">IIII", 2051, n, r, c) + imgs.tobytes())
        lp.write_bytes(struct.pack(">II", 2049, n) + labels.tobytes())
        ds = datasets.MNIST(image_path=str(ip), label_path=str(lp))
        assert len(ds) == n
        img, y = ds[2]
        assert np.array_equal(img, imgs[2]) and y == labels[2]

    def test_dataset_folder(self, tmp_path):
        from PIL import Image

        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                Image.fromarray(
                    np.zeros((6, 6, 3), dtype=np.uint8)
                ).save(d / f"{i}.png")
        ds = datasets.DatasetFolder(str(tmp_path), transform=T.ToTensor())
        assert len(ds) == 4
        assert ds.classes == ["cat", "dog"]
        img, y = ds[3]
        assert img.shape == (3, 6, 6) and y == 1

    def test_download_refused(self):
        with pytest.raises(RuntimeError, match="download"):
            datasets.MNIST()

    def test_dataloader_integration(self):
        from paddle_tpu.io import DataLoader

        ds = datasets.FakeData(
            num_samples=8, image_shape=(8, 8, 3), transform=T.ToTensor()
        )
        dl = DataLoader(ds, batch_size=4, shuffle=False)
        batch = next(iter(dl))
        imgs, labels = batch
        assert imgs.shape == (4, 3, 8, 8)
        assert labels.shape == (4,)


class TestModels:
    @pytest.mark.parametrize("ctor,feat", [
        (models.resnet18, 512),
        (models.resnet50, 2048),
    ])
    def test_resnet_forward_shapes(self, ctor, feat):
        model = ctor(num_classes=7)
        x = jnp.zeros((2, 3, 64, 64), jnp.float32)
        out = model(x)
        assert out.shape == (2, 7)
        # feature extractor mode
        trunk = ctor(num_classes=0)
        assert trunk(x).shape[1] == feat

    def test_mobilenet_forward(self):
        model = models.mobilenet_v2(scale=0.5, num_classes=5)
        out = model(jnp.zeros((1, 3, 64, 64), jnp.float32))
        assert out.shape == (1, 5)

    def test_resnet_trains_jit(self):
        """One AdamW step under jit decreases loss on a fixed batch."""
        from paddle_tpu import optimizer as opt

        pt.seed(0)
        model = models.resnet18(num_classes=4)
        model.eval()  # frozen BN stats → pure-functional under jit
        params = extract_params(model)
        optimizer = opt.AdamW(learning_rate=1e-3)
        opt_state = optimizer.init(params)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 3, 32, 32)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 4, (4,)))

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                return functional_call(model, p, x, labels=y)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new_params, new_state = optimizer.update(grads, opt_state, params)
            return new_params, new_state, loss

        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestClassicZoo:
    """Round-3 zoo widening: forward shapes + one gradient smoke
    (upstream: test/legacy_test/test_vision_models.py)."""

    @pytest.mark.parametrize("ctor,size", [
        ("vgg11", 64), ("alexnet", 224), ("squeezenet1_1", 64),
        ("densenet121", 64), ("shufflenet_v2_x1_0", 64),
    ])
    def test_forward_shapes(self, ctor, size):
        from paddle_tpu.vision import models as M

        pt.seed(0)
        net = getattr(M, ctor)(num_classes=7)
        net.eval()
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 3, size, size)).astype(np.float32))
        out = net(x)
        assert out.shape == (2, 7)
        assert np.isfinite(np.asarray(out)).all()

    def test_shufflenet_trains(self):
        import jax

        from paddle_tpu import optimizer as opt
        from paddle_tpu.core.functional import (
            extract_params,
            functional_call,
        )
        from paddle_tpu.vision import models as M

        pt.seed(0)
        net = M.shufflenet_v2_x1_0(num_classes=4)
        x = jnp.asarray(np.random.default_rng(1).normal(
            size=(4, 3, 32, 32)).astype(np.float32))
        y = jnp.asarray([0, 1, 2, 3])
        params = extract_params(net, trainable_only=True)
        o = opt.SGD(learning_rate=0.05, multi_precision=False)
        st = o.init(params)

        def loss_fn(p):
            return pt.nn.functional.cross_entropy(
                functional_call(net, p, x), y)

        l0 = float(loss_fn(params))
        gv = jax.jit(jax.value_and_grad(loss_fn))
        for _ in range(8):
            loss, g = gv(params)
            params, st = o.update(g, st, params)
        assert float(loss) < l0
