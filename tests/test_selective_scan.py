"""Chunked Pallas selective scan vs associative-scan reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels.selective_scan import chunked_selective_scan
from paddle_tpu.models.mamba import (
    MambaConfig,
    MambaForCausalLM,
    selective_scan,
)


def _inputs(b=2, s=64, d=32, n=8, seed=0):
    rng = np.random.default_rng(seed)
    u = rng.standard_normal((b, s, d)).astype(np.float32)
    delta = np.abs(rng.standard_normal((b, s, d))).astype(np.float32) * 0.1
    A = -np.abs(rng.standard_normal((d, n))).astype(np.float32)
    B = rng.standard_normal((b, s, n)).astype(np.float32)
    C = rng.standard_normal((b, s, n)).astype(np.float32)
    D = rng.standard_normal((d,)).astype(np.float32)
    return map(jnp.asarray, (u, delta, A, B, C, D))


@pytest.mark.parametrize("chunk", [16, 64])
def test_chunked_matches_associative(chunk):
    u, delta, A, B, C, D = _inputs()
    ref = np.asarray(selective_scan(u, delta, A, B, C, D))
    out = np.asarray(chunked_selective_scan(u, delta, A, B, C, D,
                                            chunk=chunk))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_chunked_d_blocking():
    u, delta, A, B, C, D = _inputs(d=64)
    ref = np.asarray(selective_scan(u, delta, A, B, C, D))
    out = np.asarray(chunked_selective_scan(u, delta, A, B, C, D,
                                            chunk=32, d_block=32))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_state_carries_across_chunks():
    # long-memory input: impulse at t=0, tiny delta afterwards → later
    # outputs depend on state carried through many chunk boundaries
    b, s, d, n = 1, 64, 8, 4
    u = np.zeros((b, s, d), np.float32)
    u[:, 0] = 1.0
    delta = np.full((b, s, d), 0.01, np.float32)
    A = -np.full((d, n), 0.1, np.float32)
    B = np.ones((b, s, n), np.float32)
    C = np.ones((b, s, n), np.float32)
    D = np.zeros((d,), np.float32)
    args = map(jnp.asarray, (u, delta, A, B, C, D))
    out = np.asarray(chunked_selective_scan(*args, chunk=8))
    ref = np.asarray(selective_scan(*map(jnp.asarray,
                                         (u, delta, A, B, C, D))))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)
    assert abs(out[0, -1].sum()) > 1e-4  # state survived to the end


def test_mamba_model_chunked_flag():
    import paddle_tpu as pt

    pt.seed(0)
    cfg = MambaConfig.tiny(use_chunked_scan=True, scan_chunk=8)
    model = MambaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16))
    logits = model(jnp.asarray(ids))
    cfg2 = MambaConfig.tiny()
    pt.seed(0)
    model2 = MambaForCausalLM(cfg2)
    ref = model2(jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_chunked_grad_flows():
    u, delta, A, B, C, D = _inputs(b=1, s=16, d=8, n=4)

    def loss(u, delta, A, B, C, D):
        return jnp.sum(chunked_selective_scan(u, delta, A, B, C, D,
                                              chunk=8) ** 2)

    g = jax.grad(loss, argnums=(0, 2))(u, delta, A, B, C, D)
    assert all(float(jnp.linalg.norm(x)) > 0 for x in g)


def test_chunked_bwd_grads_match_associative():
    """All six gradients from the recompute-based Pallas backward must
    match autodiff through the associative reference."""
    u, delta, A, B, C, D = _inputs(b=2, s=64, d=32, n=8, seed=3)

    def loss_chunked(*args):
        out = chunked_selective_scan(*args, chunk=16)
        return jnp.sum(jnp.sin(out))  # non-trivial cotangent

    def loss_ref(*args):
        return jnp.sum(jnp.sin(selective_scan(*args)))

    gc = jax.grad(loss_chunked, argnums=tuple(range(6)))(u, delta, A, B, C, D)
    gr = jax.grad(loss_ref, argnums=tuple(range(6)))(u, delta, A, B, C, D)
    for name, a, b in zip("u delta A B C D".split(), gc, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4,
            err_msg=f"grad mismatch for {name}")


def test_chunked_bwd_no_bsdn_materialization():
    """The backward jaxpr must contain no [b,s,d,n] (or [b,s,n,d])
    tensor — the whole point of the recompute-based VJP. (The round-2
    backward called jax.vjp(associative_selective_scan), whose jaxpr is
    full of them.)"""
    b, s, d, n = 2, 64, 32, 8
    u, delta, A, B, C, D = _inputs(b=b, s=s, d=d, n=n)

    def loss(*args):
        return jnp.sum(chunked_selective_scan(*args, chunk=16) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=tuple(range(6))))(
        u, delta, A, B, C, D)
    text = str(jaxpr)
    for shape in (f"{b},{s},{d},{n}", f"{b},{s},{n},{d}"):
        assert f"f32[{shape}]" not in text, (
            f"[b,s,d,n] tensor materialized in backward: f32[{shape}]")
    # sanity: the associative form DOES contain it (detector works)
    ref_jaxpr = jax.make_jaxpr(
        jax.grad(lambda *a: jnp.sum(selective_scan(*a) ** 2),
                 argnums=tuple(range(6))))(u, delta, A, B, C, D)
    assert f"f32[{b},{s},{d},{n}]" in str(ref_jaxpr)
