"""Fused single-pass decode attention (in-kernel RoPE + KV-append +
length-pruned attention) vs the lax reference path, both cache modes
(interpret mode on CPU; compiles via Mosaic on TPU), plus the
fused-vs-unfused engine token-parity run and the modeled-HBM A/B."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.flags as flags
from paddle_tpu.kernels import decode_attention as da
from paddle_tpu.kernels.paged_attention import fused_paged_decode_attention
from paddle_tpu.kernels.rope import rope_frequencies

pytestmark = pytest.mark.fast

# GQA ratios: kvh 1/4/8 at 8 query heads
GQA = [(1, 8), (4, 2), (8, 1)]


@pytest.fixture
def fused_on():
    flags.set_flags({"fused_decode": "on"})
    yield
    flags.set_flags({"fused_decode": "auto"})


def _paged_setup(kvh, group, pool_dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    slots, d, ps, n_pages, max_pages = 3, 32, 16, 32, 4
    cos, sin = rope_frequencies(d, 128)
    kp = jnp.asarray(rng.standard_normal((kvh, n_pages, ps, d)), pool_dtype)
    vp = jnp.asarray(rng.standard_normal((kvh, n_pages, ps, d)), pool_dtype)
    # distinct page ids per slot (vLLM-style arbitrary mapping)
    bt = jnp.asarray(
        rng.permutation(n_pages)[: slots * max_pages].reshape(
            slots, max_pages), jnp.int32)
    # ragged: mid-page, exact page boundary (new token starts page 2),
    # and an empty slot
    lens = jnp.asarray([37, 16, 0], jnp.int32)
    q = jnp.asarray(rng.standard_normal((slots, kvh, group, 32)),
                    jnp.float32)
    kn = jnp.asarray(rng.standard_normal((slots, kvh, 32)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((slots, kvh, 32)), jnp.float32)
    return q, kn, vn, kp, vp, bt, lens, cos, sin


@pytest.mark.parametrize("kvh,group", GQA)
def test_fused_paged_matches_reference(kvh, group):
    q, kn, vn, kp, vp, bt, lens, cos, sin = _paged_setup(kvh, group)
    out, kp2, vp2 = fused_paged_decode_attention(
        q, kn, vn, kp, vp, bt, lens, lens, cos, sin)
    ref, kpr, vpr = da.fused_paged_decode_reference(
        q, kn, vn, kp, vp, bt, lens, lens, cos, sin)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    # the append wrote EXACTLY the rotated rows the scatter writes
    np.testing.assert_allclose(np.asarray(kp2), np.asarray(kpr),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vp2), np.asarray(vpr),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("kvh,group", GQA)
def test_fused_contiguous_matches_reference(kvh, group):
    rng = np.random.default_rng(1)
    slots, d, max_len = 3, 32, 48
    cos, sin = rope_frequencies(d, 128)
    q = jnp.asarray(rng.standard_normal((slots, kvh, group, d)), jnp.float32)
    kn = jnp.asarray(rng.standard_normal((slots, kvh, d)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((slots, kvh, d)), jnp.float32)
    ck = jnp.asarray(rng.standard_normal((slots, max_len, kvh, d)),
                     jnp.float32)
    cv = jnp.asarray(rng.standard_normal((slots, max_len, kvh, d)),
                     jnp.float32)
    # ragged incl. a chunk-boundary crossing (chunk = gcd(48, 128) = 16)
    lens = jnp.asarray([37, 16, 0], jnp.int32)
    out, ck2, cv2 = da.fused_contiguous_decode_attention(
        q, kn, vn, ck, cv, lens, lens, cos, sin)
    ref, ckr, cvr = da.fused_contiguous_decode_reference(
        q, kn, vn, ck, cv, lens, lens, cos, sin)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(ck2), np.asarray(ckr),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(cv2), np.asarray(cvr),
                               rtol=1e-6, atol=1e-6)


def test_fused_append_touches_only_new_rows():
    """Everything in the pool except each slot's append row survives
    bit-identically — the in-kernel write is row-granular."""
    q, kn, vn, kp, vp, bt, lens, cos, sin = _paged_setup(4, 2)
    ps = kp.shape[2]
    _, kp2, _ = fused_paged_decode_attention(
        q, kn, vn, kp, vp, bt, lens, lens, cos, sin)
    before, after = np.asarray(kp), np.asarray(kp2)
    mask = np.zeros(before.shape, bool)
    for s in range(3):
        L = int(lens[s])
        mask[:, int(bt[s, L // ps]), L % ps, :] = True
    assert (before[~mask] == after[~mask]).all()
    assert (before[mask] != after[mask]).any()


def test_fused_kernels_accept_bf16_pools():
    """PT_FLAGS_kv_cache_dtype=auto serves bf16 pools on TPU — the
    fused kernels must take bf16 caches with f32 activations."""
    q, kn, vn, kp, vp, bt, lens, cos, sin = _paged_setup(
        2, 2, pool_dtype=jnp.bfloat16)
    out, kp2, vp2 = fused_paged_decode_attention(
        q, kn, vn, kp, vp, bt, lens, lens, cos, sin)
    ref, kpr, _ = da.fused_paged_decode_reference(
        q, kn, vn, kp, vp, bt, lens, lens, cos, sin)
    assert kp2.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=3e-2, atol=3e-2)
    np.testing.assert_array_equal(  # same bf16 rounding as the scatter
        np.asarray(kp2, np.float32), np.asarray(kpr, np.float32))

    rng = np.random.default_rng(3)
    ck = jnp.asarray(rng.standard_normal((3, 32, 2, 32)), jnp.bfloat16)
    cv = jnp.asarray(rng.standard_normal((3, 32, 2, 32)), jnp.bfloat16)
    clens = jnp.asarray([20, 16, 0], jnp.int32)  # within max_len=32
    out, ck2, cv2 = da.fused_contiguous_decode_attention(
        q, kn, vn, ck, cv, clens, clens, cos, sin)
    ref, ckr, _ = da.fused_contiguous_decode_reference(
        q, kn, vn, ck, cv, clens, clens, cos, sin)
    assert ck2.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=3e-2, atol=3e-2)
    np.testing.assert_array_equal(
        np.asarray(ck2, np.float32), np.asarray(ckr, np.float32))


def test_fused_decode_flag_gating():
    assert not da.fused_decode_active(16, 8)  # auto, off-TPU → lax path
    flags.set_flags({"fused_decode": "on"})
    try:
        assert da.fused_decode_active(16, 8)  # forced → interpret mode
    finally:
        flags.set_flags({"fused_decode": "off"})
    try:
        assert not da.fused_decode_active(128, 64)
    finally:
        flags.set_flags({"fused_decode": "auto"})


@pytest.mark.parametrize("paged", [False, True])
def test_engine_fused_decode_token_parity(fused_on, paged):
    """End-to-end step_chunk run with PT_FLAGS_fused_decode=on (Pallas
    interpret mode on CPU) must emit exactly the tokens of the unfused
    engine — the fused kernel replaces append_kv + rope + attention
    without changing a single greedy token."""
    import paddle_tpu as pt
    from paddle_tpu.inference import ContinuousBatchingEngine, EngineConfig
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(11)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    prompts = [np.arange(1, 6), np.arange(3, 10), np.arange(2, 4)]
    ecfg = dict(max_slots=2, max_len=32, seq_buckets=(16,), paged=paged,
                page_size=8)

    flags.set_flags({"fused_decode": "off"})
    eng = ContinuousBatchingEngine(model, EngineConfig(**ecfg))
    ref = [r.output for r in eng.run(prompts, max_new_tokens=6,
                                     max_chunk=4)]

    flags.set_flags({"fused_decode": "on"})
    eng = ContinuousBatchingEngine(model, EngineConfig(**ecfg))
    got = [r.output for r in eng.run(prompts, max_new_tokens=6,
                                     max_chunk=4)]
    assert got == ref


def test_fused_decode_trace_has_no_append_scatter(fused_on):
    """Acceptance: the fused path removes the separate append_kv
    program — the decode trace carries no scatter op (the unfused trace
    does: append_kv's ``.at[...].set``)."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.inference.paged import (
        PagedLayerCache,
        PagedState,
        init_paged_pool,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    cache = init_paged_pool(1, 8, 8, 2, 16, dtype=jnp.float32)[0]
    state = PagedState(jnp.zeros((2, 4), jnp.int32),
                       jnp.asarray([3, 1], jnp.int32))
    layer = model.model.layers[0].self_attn
    cos = model.model._buffers["rope_cos"]
    sin = model.model._buffers["rope_sin"]

    x = jnp.zeros((2, 1, 64), jnp.float32)

    def trace(flag):
        # fresh closure per trace: jax caches jaxprs on fn identity, so
        # reusing one fn would return the first flag's trace for both
        flags.set_flags({"fused_decode": flag})

        def step(x, cache, state):
            out, (cache, state) = layer(
                x, cos, sin, position_ids=state.seq_lens[:, None],
                kv_cache=(cache, state), cache_index=state.seq_lens)
            return out, cache

        return str(jax.make_jaxpr(step)(x, cache, state))

    assert "scatter" not in trace("on")
    assert "scatter" in trace("off")


@pytest.mark.parametrize("mode", ["paged", "contiguous"])
@pytest.mark.parametrize("kvh,group", GQA)
def test_fused_modeled_hbm_bytes_lower(mode, kvh, group):
    """Acceptance: the kernelbench A/B model prices the fused path
    below the unfused one at every tested GQA config, both modes."""
    from benchmarks.kernelbench import decode_hbm_bytes

    lens = [937, 512, 120, 64, 0, 1000, 333, 240]
    kw = dict(page_size=64) if mode == "paged" else dict(max_len=1024)
    fused = decode_hbm_bytes(mode, True, lens, kvh, group, 128, **kw)
    unfused = decode_hbm_bytes(mode, False, lens, kvh, group, 128, **kw)
    assert fused < unfused


def test_engine_free_slot_heap_and_bucket_lookup():
    """Admission bookkeeping after the O(slots²)→O(log slots) cleanup:
    the free-slot heap tracks the active mask through admit/finish
    cycles (lowest index first, as before) and the bisect bucket lookup
    matches the old linear scan."""
    import paddle_tpu as pt
    from paddle_tpu.inference import ContinuousBatchingEngine, EngineConfig
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    eng = ContinuousBatchingEngine(model, EngineConfig(
        max_slots=3, max_len=64, seq_buckets=(8, 16, 128)))
    assert eng._free_slots() == [0, 1, 2]
    for n, want in ((1, 8), (8, 8), (9, 16), (17, 64), (200, 64)):
        assert eng._bucket(n) == want, n
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 200, size=n) for n in (4, 7, 3, 9, 5)]
    reqs = eng.run(prompts, max_new_tokens=4)
    assert all(r.done for r in reqs)
    assert eng._free_slots() == [0, 1, 2]
    assert not eng.active.any()

    # the failure-injection halves below pin the LEGACY bucketed
    # admission path (per-request dispatch; partial-batch integration)
    # — the chunked path's all-or-nothing rollback is pinned in
    # tests/test_prefix_prefill.py
    from paddle_tpu import flags as F

    saved_chunk = F.flag("prefill_chunk")
    F.set_flags({"prefill_chunk": 0})
    try:
        eng = ContinuousBatchingEngine(model, EngineConfig(
            max_slots=3, max_len=64, seq_buckets=(8, 16, 128)))
        # a claimed slot is returned to the heap when admission fails
        # mid-dispatch (the heap no longer self-heals from the active
        # mask)
        eng.add_request(np.arange(1, 5), max_new_tokens=4)
        import pytest as _pytest

        def boom(*a, **k):
            raise RuntimeError("prefill exploded")

        eng._prefill_c = boom
        with _pytest.raises(RuntimeError, match="prefill exploded"):
            eng._admit()
        eng._prefill_c = None
        assert eng._free_slots() == [0, 1, 2]
        assert len(eng._queue) == 1  # request requeued, not dropped
        while eng.step_chunk(4) or eng._queue or eng.active.any():
            pass
        assert all(r.done for r in eng._finished.values())

        # partial-batch failure: first request admits, second prefill
        # blows up — the admitted one must be INTEGRATED (length +
        # first token), the failed one requeued, and both complete
        # after recovery
        real = eng._prefill()
        calls = {"n": 0}

        def flaky(*a, **k):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("second prefill exploded")
            return real(*a, **k)

        eng._prefill_c = flaky
        p1, p2 = np.arange(1, 5), np.arange(2, 8)
        r1 = eng.add_request(p1, max_new_tokens=3)
        r2 = eng.add_request(p2, max_new_tokens=3)
        with _pytest.raises(RuntimeError, match="second prefill"):
            eng._admit()
        slot1 = next(s for s, r in eng._slot_req.items() if r.rid == r1)
        assert eng.seq_lens[slot1] == p1.size  # integrated, not stranded
        assert len(eng._slot_req[slot1].output) == 1
        eng._prefill_c = real
        while eng.step_chunk(4) or eng._queue or eng.active.any():
            pass
        assert eng._finished[r1].done and eng._finished[r2].done
        ref = ContinuousBatchingEngine(model, EngineConfig(
            max_slots=3, max_len=64, seq_buckets=(8, 16, 128))).run(
            [p1, p2], max_new_tokens=3)
        assert eng._finished[r1].output == ref[0].output
        assert eng._finished[r2].output == ref[1].output
    finally:
        F.set_flags({"prefill_chunk": saved_chunk})
