"""Worker entry for the multi-process (real ``jax.distributed``) tests.

Run as: ``python mp_worker.py <mode> <out_dir>`` with the PADDLE_*
rendezvous env set by the test (or by the launch CLI). Each mode prints
``MP_OK <mode>`` on success; assertions crash the worker otherwise.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def _global_array(mesh, spec, host_local):
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        host_local, mesh, spec)


def mode_collective(out_dir):
    """Eager collectives + object collectives across 2 real processes."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed import collective as coll
    from paddle_tpu.distributed.sharding import mesh_context

    rank = jax.process_index()
    world = jax.process_count()
    assert world == 2, world
    mesh = dist.build_mesh(dp=len(jax.devices()))

    with mesh_context(mesh):
        # every process contributes its rank+1; allreduce(SUM) must give
        # the same total on every shard
        local = np.full((jax.local_device_count(), 4), float(rank + 1),
                        np.float32)
        x = _global_array(mesh, P("dp"), local)
        y = coll.all_reduce(x, mesh=mesh)
        got = np.asarray(
            [np.asarray(s.data) for s in y.addressable_shards])
        expect = sum(
            (r + 1) * jax.local_device_count() for r in range(world))
        np.testing.assert_allclose(got, float(expect))

        # object collectives ride the coordination service
        objs = []
        coll.all_gather_object(objs, {"rank": rank, "tag": "mp"})
        assert [o["rank"] for o in objs] == list(range(world)), objs

        lst = [{"v": rank}]
        coll.broadcast_object_list(lst, src=1)
        assert lst[0]["v"] == 1, lst
    print(f"MP_OK collective rank={rank}", flush=True)


def mode_ckpt_roundtrip(out_dir):
    """save_state_dict across 2 processes (real barriers, one writer per
    chunk) then reshard-on-load; every rank verifies content."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed import checkpoint as ckpt

    rank = jax.process_index()
    mesh = dist.build_mesh(dp=len(jax.devices()))
    full = np.arange(4 * 6, dtype=np.float32).reshape(4, 6)
    n_local = 4 // len(jax.devices()) * jax.local_device_count()
    local = full[rank * n_local:(rank + 1) * n_local]
    x = _global_array(mesh, P("dp"), local)
    state = {"w": x, "b": _global_array(
        mesh, P(), np.float32([1, 2, 3]))}

    path = os.path.join(out_dir, "ckpt")
    ckpt.save_state_dict(state, path)
    assert ckpt.is_committed(path)

    # reshard-on-load: everyone loads the FULL tensor replicated
    loaded = ckpt.load_state_dict(
        path, shardings={"w": NamedSharding(mesh, P()),
                         "b": NamedSharding(mesh, P())})
    np.testing.assert_allclose(np.asarray(loaded["w"]), full)
    np.testing.assert_allclose(np.asarray(loaded["b"]), [1, 2, 3])
    print(f"MP_OK ckpt_roundtrip rank={rank}", flush=True)


def mode_ckpt_kill_rank(out_dir):
    """Async save with rank 1 dying mid-save (after the tmpdir barrier,
    before its metadata lands): rank 0's metadata quorum must TIME OUT,
    refuse to commit, and leave the previous checkpoint intact."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed import checkpoint as ckpt

    rank = jax.process_index()
    mesh = dist.build_mesh(dp=len(jax.devices()))
    local = np.full((jax.local_device_count(), 2), 7.0, np.float32)
    state = {"w": _global_array(mesh, P("dp"), local)}
    path = os.path.join(out_dir, "ckpt_async")

    # a good committed checkpoint first (both ranks alive)
    ckpt.save_state_dict(state, path)
    assert ckpt.is_committed(path)

    if rank == 1:
        # die mid-save: after the snapshot+barrier, before any shard or
        # metadata file is written
        real_write = ckpt._write_snapshot

        def _die(*a, **k):
            os._exit(0)

        ckpt._write_snapshot = _die
        saver = ckpt.AsyncCheckpointer(commit_timeout=6.0)
        saver.save(state, path)
        saver.wait_until_finished()  # unreachable: _die exits the proc
        raise AssertionError("rank 1 should have died in _write_snapshot")

    saver = ckpt.AsyncCheckpointer(commit_timeout=6.0)
    saver.save(state, path)
    try:
        saver.wait_until_finished()
        raise AssertionError("commit quorum should have timed out")
    except TimeoutError as e:
        assert "1/2" in str(e) or "metadata" in str(e), e
    # the torn tmp dir must NOT have been committed; the previous
    # checkpoint survives
    assert ckpt.is_committed(path)
    assert not os.path.exists(
        os.path.join(path, "..", "ckpt_async.tmp", ckpt.COMMITTED_MARKER))
    print(f"MP_OK ckpt_kill_rank rank={rank}", flush=True)
    # rank 1 is already dead: skip atexit distributed shutdown, which
    # would wait on the lost peer
    os._exit(0)


def mode_launch_hello(out_dir):
    """Body for the launch-CLI rendezvous test: prove the PADDLE_* env
    the launcher injected forms a real 2-process jax world."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed import collective as coll
    from paddle_tpu.distributed.sharding import mesh_context

    rank = jax.process_index()
    world = jax.process_count()
    assert world == int(os.environ["PADDLE_TRAINERS_NUM"]), world
    mesh = dist.build_mesh(dp=len(jax.devices()))
    with mesh_context(mesh):
        x = _global_array(
            mesh, P("dp"),
            np.full((jax.local_device_count(),), float(rank + 1),
                    np.float32))
        y = coll.all_reduce(x, mesh=mesh)
        total = float(np.asarray(y.addressable_shards[0].data)[0])
    print(f"MP_OK launch_hello rank={rank} world={world} sum={total}",
          flush=True)


MODES = {
    "collective": mode_collective,
    "ckpt_roundtrip": mode_ckpt_roundtrip,
    "ckpt_kill_rank": mode_ckpt_kill_rank,
    "launch_hello": mode_launch_hello,
}


if __name__ == "__main__":
    mode, out_dir = sys.argv[1], sys.argv[2]
    from paddle_tpu.distributed import env as dist_env

    dist_env.init_parallel_env()
    MODES[mode](out_dir)
