"""Pallas paged decode-attention kernel vs the dense-gather reference
(interpret mode on CPU; compiles via Mosaic on TPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference.paged import (
    PagedLayerCache,
    PagedState,
    gather_kv,
)
from paddle_tpu.kernels.paged_attention import paged_decode_attention


def _dense_reference(q, cache, state):
    """q: [slots, kvh, group, d] — dense masked attention over the
    gathered full-context view."""
    slots, kvh, group, d = q.shape
    k, v = gather_kv(cache, state)  # [slots, ctx, kvh, d]
    ctx = k.shape[1]
    h = kvh * group
    qf = q.reshape(slots, 1, h, d).astype(jnp.float32) * (d ** -0.5)
    kr = jnp.repeat(k, group, axis=2).astype(jnp.float32)
    vr = jnp.repeat(v, group, axis=2).astype(jnp.float32)
    s = jnp.einsum("sqhd,skhd->shqk", qf, kr)
    mask = jnp.arange(ctx)[None, :] <= state.seq_lens[:, None]
    s = jnp.where(mask[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shqk,skhd->sqhd", p, vr)
    return out[:, 0].reshape(slots, kvh, group, d)


import jax  # noqa: E402


@pytest.mark.parametrize("group", [1, 4])
def test_paged_decode_matches_dense(group):
    rng = np.random.default_rng(0)
    slots, kvh, d = 3, 2, 128
    page_size, n_pages, max_pages = 16, 32, 4

    k_pages = jnp.asarray(
        rng.standard_normal((kvh, n_pages, page_size, d)), jnp.float32)
    v_pages = jnp.asarray(
        rng.standard_normal((kvh, n_pages, page_size, d)), jnp.float32)
    # distinct page ids per slot (vLLM-style arbitrary mapping)
    bt = jnp.asarray(
        rng.permutation(n_pages)[: slots * max_pages].reshape(
            slots, max_pages), jnp.int32)
    # ragged lengths incl. a page boundary and a single-token slot
    lens = jnp.asarray([37, 16, 0], jnp.int32)

    q = jnp.asarray(
        rng.standard_normal((slots, kvh, group, d)), jnp.float32)
    cache = PagedLayerCache(k_pages, v_pages)
    state = PagedState(bt, lens)

    out = paged_decode_attention(q, k_pages, v_pages, bt, lens)
    ref = _dense_reference(q, cache, state)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_paged_attention_api_uses_kernel(monkeypatch):
    """inference.paged.paged_attention routes to the Pallas kernel and
    matches the dense path."""
    import paddle_tpu.inference.paged as pg

    rng = np.random.default_rng(1)
    slots, kvh, h, d = 2, 2, 4, 128
    page_size, n_pages, max_pages = 16, 8, 2
    k_pages = jnp.asarray(
        rng.standard_normal((kvh, n_pages, page_size, d)), jnp.float32)
    v_pages = jnp.asarray(
        rng.standard_normal((kvh, n_pages, page_size, d)), jnp.float32)
    bt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    lens = jnp.asarray([20, 5], jnp.int32)
    cache = pg.PagedLayerCache(k_pages, v_pages)
    state = pg.PagedState(bt, lens)
    q = jnp.asarray(rng.standard_normal((slots, 1, h, d)), jnp.float32)

    monkeypatch.setenv("PADDLE_TPU_FORCE_PALLAS", "1")
    out_kernel = pg.paged_attention(q, cache, state)
    monkeypatch.delenv("PADDLE_TPU_FORCE_PALLAS")
    monkeypatch.setattr(pg, "_use_pallas_decode", lambda c: False)
    out_dense = pg.paged_attention(q, cache, state)
    np.testing.assert_allclose(
        np.asarray(out_kernel), np.asarray(out_dense), rtol=2e-3, atol=2e-3)
