"""1F1B / interleaved-VPP pipeline engine tests.

Parity model: fleet pipeline_parallel.py 1F1B schedule tests — grads and
loss must match the non-pipelined computation exactly, and the 1F1B
memory property (activation footprint ∝ pp, not n_micro) is asserted on
the compiled program's memory analysis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.distributed.pipeline import (
    pipeline_1f1b_step,
    pipeline_apply,
    segment_layers,
)

H = 16


def _first_fn(fp, x):
    return jnp.tanh(x @ fp["emb"])


def _stage_fn(cp, h):
    return jnp.tanh(h @ cp["w"] + cp["b"])


def _last_fn(lp, y, aux):
    logits = y @ lp["head"]
    return jnp.mean((logits - aux) ** 2)


def _make(V, n_micro, mb=2, seed=0):
    rng = np.random.default_rng(seed)
    fp = {"emb": jnp.asarray(rng.standard_normal((8, H)) * 0.3, jnp.float32)}
    sp = {
        "w": jnp.asarray(rng.standard_normal((V, H, H)) * 0.3, jnp.float32),
        "b": jnp.asarray(rng.standard_normal((V, H)) * 0.1, jnp.float32),
    }
    lp = {"head": jnp.asarray(rng.standard_normal((H, 4)) * 0.3, jnp.float32)}
    x = jnp.asarray(rng.standard_normal((n_micro, mb, 8)), jnp.float32)
    aux = jnp.asarray(rng.standard_normal((n_micro, mb, 4)), jnp.float32)
    return fp, sp, lp, x, aux


def _sequential_ref(fp, sp, lp, x, aux):
    V = sp["w"].shape[0]
    n_micro = x.shape[0]

    def loss_of(fp, sp, lp):
        total = 0.0
        for f in range(n_micro):
            h = _first_fn(fp, x[f])
            for v in range(V):
                h = _stage_fn({"w": sp["w"][v], "b": sp["b"][v]}, h)
            total = total + _last_fn(lp, h, aux[f])
        return total / n_micro

    loss, grads = jax.value_and_grad(loss_of, argnums=(0, 1, 2))(fp, sp, lp)
    return loss, grads


@pytest.mark.parametrize("vpp,n_micro", [(1, 6), (2, 5), (1, 2)])
def test_1f1b_matches_sequential(vpp, n_micro):
    pp = 4
    V = pp * vpp
    mesh = dist.build_mesh(pp=pp)
    fp, sp, lp, x, aux = _make(V, n_micro)
    loss, dfp, dsp, dlp = pipeline_1f1b_step(
        _first_fn, _stage_fn, _last_fn, fp, sp, lp, x, aux,
        mesh=mesh, vpp=vpp)
    ref_loss, (rfp, rsp, rlp) = _sequential_ref(fp, sp, lp, x, aux)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dfp["emb"]),
                               np.asarray(rfp["emb"]), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dsp["w"]), np.asarray(rsp["w"]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dsp["b"]), np.asarray(rsp["b"]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dlp["head"]),
                               np.asarray(rlp["head"]), rtol=1e-4, atol=1e-6)


def test_1f1b_memory_independent_of_n_micro():
    """The 1F1B property: peak temp memory must NOT grow with n_micro
    (ring buffers are sized by pp·vpp). The GPipe (autodiff) schedule's
    residuals DO grow ∝ n_micro — checked as the contrast so the test
    can't pass vacuously."""
    pp = 4
    mesh = dist.build_mesh(pp=pp)

    def peak_1f1b(n_micro):
        fp, sp, lp, x, aux = _make(pp, n_micro, mb=2)
        f = jax.jit(lambda fp, sp, lp, x, aux: pipeline_1f1b_step(
            _first_fn, _stage_fn, _last_fn, fp, sp, lp, x, aux,
            mesh=mesh, vpp=1))
        c = f.lower(fp, sp, lp, x, aux).compile()
        return c.memory_analysis().temp_size_in_bytes

    def peak_gpipe(n_micro):
        fp, sp, lp, x, aux = _make(pp, n_micro, mb=2)

        def loss_of(fp, sp, lp, x, aux):
            h0 = jax.vmap(lambda xm: _first_fn(fp, xm))(x)
            ys = pipeline_apply(
                _stage_fn, sp, h0, mesh=mesh, n_micro=n_micro, remat=True)
            losses = jax.vmap(lambda y, a: _last_fn(lp, y, a))(ys, aux)
            return jnp.mean(losses)

        f = jax.jit(jax.grad(loss_of, argnums=(0, 1, 2)))
        c = f.lower(fp, sp, lp, x, aux).compile()
        return c.memory_analysis().temp_size_in_bytes

    a, b = peak_1f1b(4), peak_1f1b(16)
    growth_1f1b = b / a
    ga, gb = peak_gpipe(4), peak_gpipe(16)
    growth_gpipe = gb / ga
    # 4x the microbatches: 1F1B stays ~flat; GPipe grows materially
    assert growth_1f1b < 1.6, (
        f"1F1B temp memory grew {growth_1f1b:.2f}x with n_micro "
        f"(4→16): {a}→{b} bytes")
    assert growth_gpipe > growth_1f1b + 0.4, (
        f"expected GPipe residual growth ({growth_gpipe:.2f}x) to exceed "
        f"1F1B ({growth_1f1b:.2f}x)")


def test_1f1b_schedule_with_gpipe_stage_fn_shapes():
    """vpp=2 places chunks round-robin: virtual stage v on device v%pp.
    Verify the device-major permutation round-trips through the engine
    (grads land back in virtual-stage order)."""
    pp, vpp = 2, 3
    V = pp * vpp
    mesh = dist.build_mesh(pp=pp)
    fp, sp, lp, x, aux = _make(V, 4, seed=3)
    loss, dfp, dsp, dlp = pipeline_1f1b_step(
        _first_fn, _stage_fn, _last_fn, fp, sp, lp, x, aux,
        mesh=mesh, vpp=vpp)
    _, (rfp, rsp, rlp) = _sequential_ref(fp, sp, lp, x, aux)
    np.testing.assert_allclose(np.asarray(dsp["w"]), np.asarray(rsp["w"]),
                               rtol=1e-4, atol=1e-6)


def test_segment_layers():
    # uniform costs → equal split
    assert segment_layers([1] * 8, 4) == [0, 2, 4, 6, 8]
    # heavy head: bottleneck minimized by isolating it
    bounds = segment_layers([10, 1, 1, 1], 2)
    assert bounds == [0, 1, 4]
    # heavy tail
    bounds = segment_layers([1, 1, 1, 10], 2)
    assert bounds == [0, 3, 4]
    # every stage gets at least one layer even with zero costs
    bounds = segment_layers([0, 0, 0, 5], 4)
    assert bounds[-1] == 4 and len(bounds) == 5
    assert all(b > a for a, b in zip(bounds, bounds[1:]))
    with pytest.raises(ValueError):
        segment_layers([1, 2], 3)


# ---------------------------------------------------------------------------
# PipelineModule: heterogeneous descs, tied weights, schedule selection
# ---------------------------------------------------------------------------
def _tied_module(vocab=12, h=16, L=4, num_stages=2):
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.distributed.pipeline import (
        LayerDesc, PipelineModule, SharedLayerDesc)

    pt.seed(0)
    descs = (
        [SharedLayerDesc("emb", nn.Embedding, vocab, h)]
        + [LayerDesc(nn.Linear, h, h) for _ in range(L)]
        + [SharedLayerDesc(
            "emb", nn.Embedding, vocab, h,
            forward_func=lambda layer, x: x @ layer.weight.value.T)]
    )
    return PipelineModule(descs, num_stages=num_stages)


def test_pipeline_module_heterogeneous_and_tied():
    """Embedding tied to the lm head (SharedLayerDesc.key consumed): the
    parameter exists ONCE; the GPipe forward matches a hand-computed
    reference; the trunk is the homogeneous Linear run."""
    import paddle_tpu.distributed as dist

    m = _tied_module()
    assert m.trunk_range == (1, 5)
    # exactly one shared embedding parameter
    emb_params = [n for n, _ in m.named_parameters()
                  if n.startswith("shared_emb")]
    assert len(emb_params) == 1
    mesh = dist.build_mesh(pp=2)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 12, (4, 6)))
    from paddle_tpu.distributed.sharding import mesh_context

    with mesh_context(mesh):
        logits = m(ids, n_micro=2, mesh=mesh)
    # reference: same params applied sequentially
    emb = m._shared["emb"].weight.value
    hh = emb[ids]
    tp = m.trunk.stage_params()  # stacked [L, ...] trunk params
    for i in range(4):
        hh = hh @ tp["weight"][i] + tp["bias"][i]
    ref = hh @ emb.T
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("schedule,vpp", [("1F1B", 1), ("1F1B", 2),
                                          ("F-then-B", 1)])
def test_pipeline_train_step_schedules(schedule, vpp):
    """PipelineTrainStep honors strategy.pipeline_configs.schedule_mode
    and vpp_degree; loss decreases under both schedules and grads flow
    into the tied embedding from both of its uses."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.distributed.pipeline import PipelineTrainStep
    from paddle_tpu.distributed.strategy import DistributedStrategy

    m = _tied_module(L=4)
    mesh = dist.build_mesh(pp=2)
    strategy = DistributedStrategy()
    strategy.pipeline = True
    strategy.pipeline_configs.schedule_mode = schedule
    strategy.pipeline_configs.vpp_degree = vpp
    strategy.pipeline_configs.accumulate_steps = 2

    def loss_fn(logits, labels):
        return jnp.mean((logits - jax.nn.one_hot(labels, 12)) ** 2)

    ts = PipelineTrainStep(m, opt.SGD(learning_rate=0.02), mesh,
                           strategy, loss_fn)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 12, (4, 6)))
    labels = jnp.asarray(rng.integers(0, 12, (4, 6)))
    emb_name = [n for n in ts.params if n.startswith("shared_emb")][0]
    emb_before = np.asarray(ts.params[emb_name])
    losses = [float(ts.run(ids, labels)) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    # tied embedding received updates
    assert np.abs(np.asarray(ts.params[emb_name]) - emb_before).max() > 1e-6


def test_1f1b_vs_fthenb_same_trajectory():
    """Both schedules compute the same gradients — loss trajectories of
    two identically-initialized modules must coincide."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.distributed.pipeline import PipelineTrainStep
    from paddle_tpu.distributed.strategy import DistributedStrategy

    mesh = dist.build_mesh(pp=2)
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, 12, (4, 6)))
    labels = jnp.asarray(rng.integers(0, 12, (4, 6)))

    def loss_fn(logits, labels):
        return jnp.mean((logits - jax.nn.one_hot(labels, 12)) ** 2)

    traj = {}
    for schedule in ("1F1B", "F-then-B"):
        m = _tied_module(L=4)
        strategy = DistributedStrategy()
        strategy.pipeline_configs.schedule_mode = schedule
        strategy.pipeline_configs.accumulate_steps = 2
        ts = PipelineTrainStep(m, opt.SGD(learning_rate=0.02), mesh,
                               strategy, loss_fn)
        traj[schedule] = [float(ts.run(ids, labels)) for _ in range(4)]
    np.testing.assert_allclose(traj["1F1B"], traj["F-then-B"],
                               rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("L,vpp", [(5, 1), (6, 2), (7, 1)])
def test_nonuniform_segmentation_matches_sequential(L, vpp):
    """Cost-balanced NON-uniform partition (L % (pp·vpp) != 0): masked
    padding slots must be exact no-ops — 1F1B loss/grads and the
    F-then-B trajectory must coincide with each other (both reduce to
    the same sequential math). Parity: fleet pp_layers.segment_layers
    raggedness."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.distributed.pipeline import PipelineTrainStep
    from paddle_tpu.distributed.strategy import DistributedStrategy

    mesh = dist.build_mesh(pp=2)
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, 12, (4, 6)))
    labels = jnp.asarray(rng.integers(0, 12, (4, 6)))

    def loss_fn(logits, labels):
        return jnp.mean((logits - jax.nn.one_hot(labels, 12)) ** 2)

    traj = {}
    for schedule in ("1F1B", "F-then-B"):
        m = _tied_module(L=L)
        strategy = DistributedStrategy()
        strategy.pipeline_configs.schedule_mode = schedule
        strategy.pipeline_configs.vpp_degree = vpp if schedule == "1F1B" \
            else 1
        strategy.pipeline_configs.accumulate_steps = 2
        ts = PipelineTrainStep(m, opt.SGD(learning_rate=0.02), mesh,
                               strategy, loss_fn)
        if schedule == "1F1B":
            assert not ts._plan_v.uniform  # the point of the test
        traj[schedule] = [float(ts.run(ids, labels)) for _ in range(4)]
    np.testing.assert_allclose(traj["1F1B"], traj["F-then-B"],
                               rtol=1e-4, atol=1e-6)


def test_nonuniform_forward_matches_sequential():
    """PipelineLayer forward with L=5 on pp=2 (padded stage of 3+2):
    pipelined output must equal the sequential scan exactly."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.sharding import mesh_context

    m = _tied_module(L=5)
    mesh = dist.build_mesh(pp=2)
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 12, (4, 6)))
    with mesh_context(mesh):
        out_pp = m(ids, n_micro=2, mesh=mesh)
    out_seq = m(ids, n_micro=1, mesh=None)
    np.testing.assert_allclose(np.asarray(out_pp), np.asarray(out_seq),
                               rtol=1e-4, atol=1e-5)


def test_seg_method_and_cost_fn():
    """seg_method='layer:<regex>' and cost_fn drive the recorded
    segmentation (fleet convention); bad regexes fail loudly."""
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.distributed.pipeline import (
        LayerDesc, PipelineModule)

    pt.seed(0)
    descs = ([LayerDesc(nn.Embedding, 12, 16)] * 1
             + [LayerDesc(nn.Linear, 16, 16) for _ in range(5)])
    m = PipelineModule(descs, num_stages=2, seg_method="layer:Linear")
    assert m.segments == [0, 3, 5]  # balanced 3+2 split
    m2 = PipelineModule(descs, num_stages=2,
                        cost_fn=lambda d: 1.0)
    assert m2.segments == [0, 3, 5]
    with pytest.raises(ValueError):
        PipelineModule(descs, num_stages=2, seg_method="layer:Conv2D")
    with pytest.raises(ValueError):
        PipelineModule(descs, num_stages=2, seg_method="bogus")


def test_llama_pipeline_module_trains():
    """Flagship-path PP: the Llama PipelineModule (tied embeddings)
    trains under 1F1B on a pp=2 mesh and its loss matches the F-then-B
    schedule exactly."""
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn, optimizer as opt
    from paddle_tpu.distributed.pipeline import PipelineTrainStep
    from paddle_tpu.distributed.strategy import DistributedStrategy
    from paddle_tpu.models.llama import LlamaConfig, llama_pipeline_module

    cfg = LlamaConfig.tiny(num_hidden_layers=4, tie_word_embeddings=True,
                           use_flash_attention=False)
    mesh = dist.build_mesh(pp=2)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)))
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)))

    def loss_fn(logits, labels):
        return nn.functional.cross_entropy(
            logits.reshape(-1, cfg.vocab_size), labels.reshape(-1))

    traj = {}
    for mode in ("1F1B", "F-then-B"):
        pt.seed(0)
        m = llama_pipeline_module(cfg, num_stages=2)
        st = DistributedStrategy()
        st.pipeline_configs.schedule_mode = mode
        st.pipeline_configs.accumulate_steps = 2
        ts = PipelineTrainStep(m, opt.AdamW(learning_rate=1e-3), mesh,
                               st, loss_fn)
        traj[mode] = [float(ts.run(ids, labels)) for _ in range(5)]
    assert traj["1F1B"][-1] < traj["1F1B"][0]
    np.testing.assert_allclose(traj["1F1B"], traj["F-then-B"],
                               rtol=2e-4, atol=1e-5)


def test_llama_pipeline_pp_x_tp_composition():
    """pp × tp on one mesh: trunk stacked over pp (manual axis in
    shard_map) with Column/RowParallel weights sharded over tp (GSPMD
    auto axis). Loss trajectory must match the pp-only run exactly."""
    import paddle_tpu as pt
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn, optimizer as opt
    from paddle_tpu.distributed.pipeline import PipelineTrainStep
    from paddle_tpu.distributed.strategy import DistributedStrategy
    from paddle_tpu.models.llama import LlamaConfig, llama_pipeline_module

    cfg = LlamaConfig.tiny(num_hidden_layers=4, tie_word_embeddings=True,
                           use_flash_attention=False)
    rng = np.random.default_rng(5)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)))
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)))

    def loss_fn(logits, labels):
        return nn.functional.cross_entropy(
            logits.reshape(-1, cfg.vocab_size), labels.reshape(-1))

    traj = {}
    for axes in ({"pp": 2}, {"pp": 2, "tp": 2}):
        pt.seed(0)
        m = llama_pipeline_module(cfg, num_stages=2)
        mesh = dist.build_mesh(**axes)
        st = DistributedStrategy()
        st.pipeline_configs.schedule_mode = "1F1B"
        st.pipeline_configs.accumulate_steps = 2
        ts = PipelineTrainStep(m, opt.AdamW(learning_rate=1e-3), mesh,
                               st, loss_fn)
        if "tp" in axes:
            # attention qkv weights must genuinely shard over tp
            sharded = [n for n, sh in ts.param_shardings.items()
                       if "tp" in str(sh.spec)]
            assert sharded, "no parameter sharded over tp"
        traj[tuple(axes)] = [float(ts.run(ids, labels)) for _ in range(4)]
    np.testing.assert_allclose(traj[("pp",)], traj[("pp", "tp")],
                               rtol=2e-4, atol=1e-5)
