"""Model-zoo tests: forward shapes, grads finite, training reduces loss,
and (for mamba) the associative-scan recurrence vs a sequential numpy
reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import distributed as dist, optimizer as opt
from paddle_tpu.core.functional import extract_params, functional_call
from paddle_tpu.models import (
    GPTConfig,
    GPTForCausalLM,
    MambaConfig,
    MambaForCausalLM,
    ViT,
    ViTConfig,
)
from paddle_tpu.trainer import TrainStep


def test_gpt_forward_and_train():
    pt.seed(0)
    model = GPTForCausalLM(GPTConfig.tiny(hidden_dropout_prob=0.0,
                                          attention_probs_dropout_prob=0.0,
                                          use_flash_attention=False))
    ids = jnp.asarray(np.random.randint(0, 256, (2, 16)))
    logits = model(ids)
    assert logits.shape == (2, 16, 256)
    mesh = dist.build_mesh(dp=2, fsdp=2, tp=2)
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = dist.HybridConfig(
        dp_degree=2, sharding_degree=2, mp_degree=2
    )
    strategy.sharding = True
    strategy.sharding_configs.stage = 3
    ts = TrainStep(model, opt.AdamW(3e-3, multi_precision=False), mesh,
                   strategy)
    ids8 = jnp.asarray(np.random.randint(0, 256, (8, 16)))
    batch = {"input_ids": ids8, "labels": ids8}
    losses = [float(ts.run(batch)) for _ in range(12)]
    assert losses[-1] < losses[0], losses


def test_vit_forward_and_grads():
    pt.seed(1)
    model = ViT(ViTConfig.tiny())
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 3, 32, 32)), jnp.float32
    )
    logits = model(x)
    assert logits.shape == (2, 10)
    labels = jnp.asarray([1, 2])
    params = extract_params(model)
    loss, grads = jax.value_and_grad(
        lambda p: functional_call(model, p, x, labels=labels)
    )(params)
    assert np.isfinite(float(loss))
    for n, g in grads.items():
        assert bool(jnp.all(jnp.isfinite(g))), n


def test_mamba_scan_matches_sequential():
    from paddle_tpu.models.mamba import selective_scan

    rng = np.random.default_rng(0)
    b, s, d, n = 2, 12, 4, 3
    u = rng.standard_normal((b, s, d)).astype(np.float32)
    delta = np.abs(rng.standard_normal((b, s, d))).astype(np.float32)
    A = -np.abs(rng.standard_normal((d, n))).astype(np.float32)
    B = rng.standard_normal((b, s, n)).astype(np.float32)
    C = rng.standard_normal((b, s, n)).astype(np.float32)
    D = rng.standard_normal((d,)).astype(np.float32)

    y = selective_scan(*map(jnp.asarray, (u, delta, A, B, C, D)))

    # sequential reference
    h = np.zeros((b, d, n), np.float32)
    ys = np.zeros((b, s, d), np.float32)
    for t in range(s):
        dA = np.exp(delta[:, t, :, None] * A[None])
        dBu = (delta[:, t] * u[:, t])[..., None] * B[:, t, None, :]
        h = dA * h + dBu
        ys[:, t] = np.einsum("bdn,bn->bd", h, C[:, t]) + u[:, t] * D
    np.testing.assert_allclose(np.asarray(y), ys, rtol=1e-4, atol=1e-4)


def test_mamba_lm_trains():
    pt.seed(3)
    model = MambaForCausalLM(MambaConfig.tiny())
    ids = jnp.asarray(np.random.randint(0, 256, (4, 16)))
    params = extract_params(model)
    o = opt.AdamW(5e-3, multi_precision=False)
    state = o.init(params)

    @jax.jit
    def step(params, state):
        loss, grads = jax.value_and_grad(
            lambda p: functional_call(model, p, ids, labels=ids)
        )(params)
        params, state = o.update(grads, state, params)
        return params, state, loss

    losses = []
    for _ in range(15):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
