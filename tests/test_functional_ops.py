"""Functional op numeric tests vs numpy references (parity model:
upstream OpTest in test/legacy_test/op_test.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import nn
from paddle_tpu.nn import functional as F

# core-engine fast lane (see README "Tests")
pytestmark = pytest.mark.fast


def test_layer_norm_vs_numpy():
    x = np.random.randn(2, 5, 8).astype(np.float32)
    w = np.random.randn(8).astype(np.float32)
    b = np.random.randn(8).astype(np.float32)
    y = F.layer_norm(jnp.asarray(x), (8,), jnp.asarray(w), jnp.asarray(b))
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mean) / np.sqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


def test_rms_norm_vs_numpy():
    x = np.random.randn(2, 4, 8).astype(np.float32)
    w = np.random.randn(8).astype(np.float32)
    y = F.rms_norm(jnp.asarray(x), jnp.asarray(w), 1e-6)
    ref = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6) * w
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


def test_cross_entropy_vs_numpy():
    logits = np.random.randn(6, 10).astype(np.float32)
    labels = np.random.randint(0, 10, (6,))
    loss = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(6), labels]).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_cross_entropy_ignore_index():
    logits = np.random.randn(4, 5).astype(np.float32)
    labels = np.array([1, -100, 3, -100])
    loss = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -(np.log(p[0, 1]) + np.log(p[2, 3])) / 2
    np.testing.assert_allclose(float(loss), ref, rtol=1e-4)


def test_attention_causal():
    q = np.random.randn(2, 6, 4, 8).astype(np.float32)
    k = np.random.randn(2, 6, 4, 8).astype(np.float32)
    v = np.random.randn(2, 6, 4, 8).astype(np.float32)
    out = F.scaled_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), is_causal=True
    )
    # numpy reference
    scale = 8**-0.5
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = np.tril(np.ones((6, 6), bool))
    logits = np.where(mask, logits, -1e30)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_attention_gqa():
    """grouped-query attention: 8 q heads, 2 kv heads."""
    q = np.random.randn(1, 4, 8, 16).astype(np.float32)
    k = np.random.randn(1, 4, 2, 16).astype(np.float32)
    v = np.random.randn(1, 4, 2, 16).astype(np.float32)
    out = F.scaled_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )
    assert out.shape == (1, 4, 8, 16)
    # head 0..3 use kv head 0
    k_rep = np.repeat(k, 4, axis=2)
    v_rep = np.repeat(v, 4, axis=2)
    logits = np.einsum("bqhd,bkhd->bhqk", q, k_rep) * 16**-0.5
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", probs, v_rep)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_conv2d_vs_torch_style():
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    w = np.random.randn(4, 3, 3, 3).astype(np.float32)
    y = F.conv2d(jnp.asarray(x), jnp.asarray(w), stride=1, padding=1)
    assert y.shape == (2, 4, 8, 8)
    # center pixel check vs naive conv
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    ref00 = np.sum(xp[0, :, 3:6, 3:6] * w[1])
    np.testing.assert_allclose(float(y[0, 1, 3, 3]), ref00, rtol=1e-4)


def test_pool():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    y = F.max_pool2d(jnp.asarray(x), 2)
    np.testing.assert_allclose(
        np.asarray(y)[0, 0], np.array([[5.0, 7.0], [13.0, 15.0]])
    )
    y = F.avg_pool2d(jnp.asarray(x), 2)
    np.testing.assert_allclose(
        np.asarray(y)[0, 0], np.array([[2.5, 4.5], [10.5, 12.5]])
    )


def test_activations_finite():
    x = jnp.linspace(-5, 5, 11)
    for name in ["relu", "gelu", "silu", "sigmoid", "tanh", "mish",
                 "hardswish", "hardsigmoid", "softplus", "relu6"]:
        y = getattr(F, name)(x)
        assert bool(jnp.all(jnp.isfinite(y))), name


def test_mha_layer():
    mha = nn.MultiHeadAttention(16, 4)
    x = jnp.ones((2, 5, 16))
    y = mha(x)
    assert y.shape == (2, 5, 16)


def test_transformer_encoder_layer():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    layer.eval()
    x = jnp.ones((2, 5, 16))
    y = layer(x)
    assert y.shape == (2, 5, 16)


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    y = emb(jnp.asarray([[0, 1]]))
    np.testing.assert_allclose(np.asarray(y[0, 0]), np.zeros(4))


class TestActivationFunctionalForms:
    """Round-3: the F.* activation spellings vs torch."""

    def setup_method(self, _):
        self.x = np.random.default_rng(0).normal(
            size=(4, 6)).astype(np.float32) * 2

    def _cmp(self, ours, ref, **tol):
        tol.setdefault("rtol", 1e-5)
        tol.setdefault("atol", 1e-6)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), **tol)

    def test_vs_torch(self):
        import torch

        x = jnp.asarray(self.x)
        t = torch.tensor(self.x)
        self._cmp(F.log_sigmoid(x),
                  torch.nn.functional.logsigmoid(t))
        self._cmp(F.softsign(x), torch.nn.functional.softsign(t))
        self._cmp(F.selu(x), torch.nn.functional.selu(t))
        self._cmp(F.celu(x, 1.3), torch.nn.functional.celu(t, 1.3))
        self._cmp(F.hardshrink(x, 0.4),
                  torch.nn.functional.hardshrink(t, 0.4))
        self._cmp(F.softshrink(x, 0.4),
                  torch.nn.functional.softshrink(t, 0.4))
        self._cmp(F.tanhshrink(x), torch.nn.functional.tanhshrink(t))
        self._cmp(F.hardtanh(x, -0.7, 0.9),
                  torch.nn.functional.hardtanh(t, -0.7, 0.9))
        w = np.asarray([0.2], np.float32)
        self._cmp(F.prelu(x, jnp.asarray(w)),
                  torch.nn.functional.prelu(t, torch.tensor(w)))

    def test_prelu_channelwise(self):
        import torch

        x4 = np.random.default_rng(1).normal(
            size=(2, 3, 4, 4)).astype(np.float32)
        w = np.asarray([0.1, 0.2, 0.3], np.float32)
        ours = F.prelu(jnp.asarray(x4), jnp.asarray(w))
        ref = torch.nn.functional.prelu(torch.tensor(x4),
                                        torch.tensor(w))
        self._cmp(ours, ref)

    def test_rrelu_bounds_and_eval(self):
        x = jnp.asarray(self.x)
        y = np.asarray(F.rrelu(x, 0.1, 0.3, training=True,
                               rng_key=jax.random.PRNGKey(0)))
        neg = self.x < 0
        ratio = y[neg] / self.x[neg]
        assert (ratio >= 0.1 - 1e-6).all() and (ratio <= 0.3 + 1e-6).all()
        y_eval = np.asarray(F.rrelu(x, 0.1, 0.3, training=False))
        np.testing.assert_allclose(
            y_eval[neg], 0.2 * self.x[neg], rtol=1e-6)

    def test_maxout(self):
        x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 12))
        out = np.asarray(F.maxout(x, groups=3, axis=1))
        assert out.shape == (2, 4)
        np.testing.assert_allclose(out[0], [2, 5, 8, 11])
        with pytest.raises(ValueError):
            F.maxout(x, groups=5)

    def test_thresholded_relu(self):
        x = jnp.asarray([-1.0, 0.5, 1.5])
        np.testing.assert_allclose(
            np.asarray(F.thresholded_relu(x, 1.0)), [0.0, 0.0, 1.5])

    def test_maxout_negative_axis(self):
        x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 12))
        np.testing.assert_allclose(
            np.asarray(F.maxout(x, groups=3, axis=-1)),
            np.asarray(F.maxout(x, groups=3, axis=1)))

    def test_selu_grad_large_input(self):
        g = jax.grad(lambda v: jnp.sum(F.selu(v)))(
            jnp.asarray([100.0, -1.0]))
        assert np.isfinite(np.asarray(g)).all()

    def test_prelu_layer_delegates(self):
        import paddle_tpu as pt
        from paddle_tpu import nn

        pt.seed(0)
        layer = nn.PReLU(num_parameters=3, init=0.3)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 3, 4)).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(layer(x)),
            np.asarray(F.prelu(x, layer.weight)), rtol=1e-6)


# ---------------------------------------------------------------------------
# RNN-T loss (parity: warprnnt — SURVEY §2.3)
# ---------------------------------------------------------------------------

def _rnnt_brute(lp, label, T, U):
    """Enumerate every monotone lattice path (T-1 blanks interleaved with
    U emits, final blank) and logsumexp the path scores."""
    import itertools

    V = lp.shape[-1]
    paths = []
    # a path is a sequence of moves: 'b' (t+1) or 'e' (u+1), with
    # exactly T-1 b-moves and U e-moves in any order, ending with the
    # final blank at (T-1, U)
    for moves in set(itertools.permutations("b" * (T - 1) + "e" * U)):
        t = u = 0
        s = 0.0
        for m in moves:
            if m == "b":
                s += float(lp[t, u, 0])
                t += 1
            else:
                s += float(lp[t, u, label[u]])
                u += 1
        s += float(lp[T - 1, U, 0])  # final blank
        paths.append(s)
    m = max(paths)
    return -(m + np.log(sum(np.exp(p - m) for p in paths)))


def test_rnnt_loss_matches_brute_force():
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(0)
    B, T, U, V = 2, 3, 2, 5
    logits = rng.standard_normal((B, T, U + 1, V)).astype(np.float32)
    label = np.array([[1, 3], [2, 2]], np.int32)
    got = F.rnnt_loss(jnp.asarray(logits), jnp.asarray(label),
                      np.array([T, T]), np.array([U, U]),
                      blank=0, fastemit_lambda=0.0, reduction="none")
    lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    want = [_rnnt_brute(lp[b], label[b], T, U) for b in range(B)]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_rnnt_loss_variable_lengths():
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(1)
    B, T, U, V = 2, 4, 3, 6
    logits = rng.standard_normal((B, T, U + 1, V)).astype(np.float32)
    label = rng.integers(1, V, (B, U)).astype(np.int32)
    # sample 1 uses a shorter lattice (T=2, U=1): must equal the brute
    # force on the TRUNCATED lattice, regardless of padding content
    got = F.rnnt_loss(jnp.asarray(logits), jnp.asarray(label),
                      np.array([T, 2]), np.array([U, 1]),
                      blank=0, fastemit_lambda=0.0, reduction="none")
    lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    want0 = _rnnt_brute(lp[0], label[0], T, U)
    want1 = _rnnt_brute(lp[1], label[1], 2, 1)
    np.testing.assert_allclose(np.asarray(got), [want0, want1], rtol=1e-5)


def test_rnnt_loss_gradients_and_fastemit():
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(2)
    B, T, U, V = 1, 3, 2, 4
    logits = jnp.asarray(rng.standard_normal((B, T, U + 1, V)), jnp.float32)
    label = jnp.asarray([[1, 2]], jnp.int32)
    il, ll = np.array([T]), np.array([U])

    def loss(x, lam):
        return F.rnnt_loss(x, label, il, ll, fastemit_lambda=lam,
                           reduction="sum")

    # finite-difference check at lambda=0
    g = jax.grad(loss)(logits, 0.0)
    eps = 1e-3
    for idx in [(0, 0, 0, 1), (0, 1, 1, 0), (0, 2, 2, 3)]:
        lp_ = logits.at[idx].add(eps)
        lm_ = logits.at[idx].add(-eps)
        fd = (float(loss(lp_, 0.0)) - float(loss(lm_, 0.0))) / (2 * eps)
        np.testing.assert_allclose(float(g[idx]), fd, rtol=2e-3, atol=2e-4)

    # FastEmit: identical VALUE, different gradients (emit arcs scaled)
    v0, v1 = float(loss(logits, 0.0)), float(loss(logits, 0.5))
    np.testing.assert_allclose(v0, v1, rtol=1e-6)
    g1 = jax.grad(loss)(logits, 0.5)
    assert not np.allclose(np.asarray(g), np.asarray(g1))


def test_rnnt_loss_layer_and_empty_label():
    import paddle_tpu as pt

    rng = np.random.default_rng(3)
    layer = pt.nn.RNNTLoss(blank=0, fastemit_lambda=0.0, reduction="mean")
    B, T, U, V = 2, 3, 2, 4
    logits = jnp.asarray(rng.standard_normal((B, T, U + 1, V)), jnp.float32)
    label = jnp.asarray([[1, 2], [3, 1]], jnp.int32)
    out = layer(logits, label, np.array([T, T]), np.array([U, 0]))
    assert np.isfinite(float(out))
    # empty-label sample = pure blank path
    lp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
    import paddle_tpu.nn.functional as F
    per = F.rnnt_loss(logits, label, np.array([T, T]), np.array([U, 0]),
                      reduction="none")
    want1 = -sum(lp[1, t, 0, 0] for t in range(T))
    np.testing.assert_allclose(float(per[1]), want1, rtol=1e-5)
