"""Functional op numeric tests vs numpy references (parity model:
upstream OpTest in test/legacy_test/op_test.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def test_layer_norm_vs_numpy():
    x = np.random.randn(2, 5, 8).astype(np.float32)
    w = np.random.randn(8).astype(np.float32)
    b = np.random.randn(8).astype(np.float32)
    y = F.layer_norm(jnp.asarray(x), (8,), jnp.asarray(w), jnp.asarray(b))
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mean) / np.sqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


def test_rms_norm_vs_numpy():
    x = np.random.randn(2, 4, 8).astype(np.float32)
    w = np.random.randn(8).astype(np.float32)
    y = F.rms_norm(jnp.asarray(x), jnp.asarray(w), 1e-6)
    ref = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6) * w
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


def test_cross_entropy_vs_numpy():
    logits = np.random.randn(6, 10).astype(np.float32)
    labels = np.random.randint(0, 10, (6,))
    loss = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(6), labels]).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_cross_entropy_ignore_index():
    logits = np.random.randn(4, 5).astype(np.float32)
    labels = np.array([1, -100, 3, -100])
    loss = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -(np.log(p[0, 1]) + np.log(p[2, 3])) / 2
    np.testing.assert_allclose(float(loss), ref, rtol=1e-4)


def test_attention_causal():
    q = np.random.randn(2, 6, 4, 8).astype(np.float32)
    k = np.random.randn(2, 6, 4, 8).astype(np.float32)
    v = np.random.randn(2, 6, 4, 8).astype(np.float32)
    out = F.scaled_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), is_causal=True
    )
    # numpy reference
    scale = 8**-0.5
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = np.tril(np.ones((6, 6), bool))
    logits = np.where(mask, logits, -1e30)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_attention_gqa():
    """grouped-query attention: 8 q heads, 2 kv heads."""
    q = np.random.randn(1, 4, 8, 16).astype(np.float32)
    k = np.random.randn(1, 4, 2, 16).astype(np.float32)
    v = np.random.randn(1, 4, 2, 16).astype(np.float32)
    out = F.scaled_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )
    assert out.shape == (1, 4, 8, 16)
    # head 0..3 use kv head 0
    k_rep = np.repeat(k, 4, axis=2)
    v_rep = np.repeat(v, 4, axis=2)
    logits = np.einsum("bqhd,bkhd->bhqk", q, k_rep) * 16**-0.5
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", probs, v_rep)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_conv2d_vs_torch_style():
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    w = np.random.randn(4, 3, 3, 3).astype(np.float32)
    y = F.conv2d(jnp.asarray(x), jnp.asarray(w), stride=1, padding=1)
    assert y.shape == (2, 4, 8, 8)
    # center pixel check vs naive conv
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    ref00 = np.sum(xp[0, :, 3:6, 3:6] * w[1])
    np.testing.assert_allclose(float(y[0, 1, 3, 3]), ref00, rtol=1e-4)


def test_pool():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    y = F.max_pool2d(jnp.asarray(x), 2)
    np.testing.assert_allclose(
        np.asarray(y)[0, 0], np.array([[5.0, 7.0], [13.0, 15.0]])
    )
    y = F.avg_pool2d(jnp.asarray(x), 2)
    np.testing.assert_allclose(
        np.asarray(y)[0, 0], np.array([[2.5, 4.5], [10.5, 12.5]])
    )


def test_activations_finite():
    x = jnp.linspace(-5, 5, 11)
    for name in ["relu", "gelu", "silu", "sigmoid", "tanh", "mish",
                 "hardswish", "hardsigmoid", "softplus", "relu6"]:
        y = getattr(F, name)(x)
        assert bool(jnp.all(jnp.isfinite(y))), name


def test_mha_layer():
    mha = nn.MultiHeadAttention(16, 4)
    x = jnp.ones((2, 5, 16))
    y = mha(x)
    assert y.shape == (2, 5, 16)


def test_transformer_encoder_layer():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    layer.eval()
    x = jnp.ones((2, 5, 16))
    y = layer(x)
    assert y.shape == (2, 5, 16)


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    y = emb(jnp.asarray([[0, 1]]))
    np.testing.assert_allclose(np.asarray(y[0, 0]), np.zeros(4))
