"""Round-4 breadth tail: color/photometric transforms, model-zoo
variants, long-tail distributions, hapi callbacks — numerics pinned to
torch where a reference exists."""

import numpy as np
import jax.numpy as jnp
import pytest
import torch

import paddle_tpu as pt
from paddle_tpu import distribution as D
from paddle_tpu.vision import models as M, transforms as T


class TestColorTransforms:
    def setup_method(self, _):
        rng = np.random.default_rng(0)
        self.img = rng.uniform(0, 255, (16, 20, 3)).astype(np.uint8)

    def test_adjust_ops_match_torchvision_math(self):
        a = self.img.astype(np.float32)
        np.testing.assert_array_equal(
            T.adjust_brightness(self.img, 0.5),
            np.clip(np.round(a * 0.5), 0, 255).astype(np.uint8))
        g = 0.299 * a[..., 0] + 0.587 * a[..., 1] + 0.114 * a[..., 2]
        np.testing.assert_array_equal(
            T.adjust_contrast(self.img, 1.3),
            np.clip(np.round(g.mean() + 1.3 * (a - g.mean())),
                    0, 255).astype(np.uint8))
        np.testing.assert_array_equal(
            T.adjust_saturation(self.img, 0.0)[..., 0],
            T.to_grayscale(self.img)[..., 0])
        # hue: zero shift is identity; any shift preserves value channel
        np.testing.assert_allclose(T.adjust_hue(self.img, 0.0),
                                   self.img, atol=1)
        shifted = T.adjust_hue(self.img, 0.25)
        np.testing.assert_allclose(shifted.max(-1), self.img.max(-1),
                                   atol=1)
        with pytest.raises(ValueError):
            T.adjust_hue(self.img, 0.7)

    def test_jitter_pad_gray_erase_perspective(self):
        out = T.ColorJitter(0.4, 0.4, 0.4, 0.2, seed=0)(self.img)
        assert out.shape == self.img.shape and out.dtype == np.uint8
        assert T.Pad(2)(self.img).shape == (20, 24, 3)
        assert T.Pad((1, 2))(self.img).shape == (20, 22, 3)
        assert T.Grayscale(3)(self.img).shape == self.img.shape
        e = T.RandomErasing(prob=1.0, seed=1)(self.img.copy())
        assert (e != self.img).any()
        chw = np.transpose(self.img, (2, 0, 1))
        e2 = T.RandomErasing(prob=1.0, value=None, seed=2)(chw.copy())
        assert e2.shape == chw.shape
        p = T.RandomPerspective(prob=1.0, seed=3)(self.img)
        assert np.asarray(p).shape == self.img.shape
        assert T.RandomPerspective(prob=0.0)(self.img) is self.img


class TestModelZooVariants:
    def test_wide_and_resnext_param_counts(self):
        """Parameter counts must match the torchvision/paddle references
        (1000-class config): wide_resnet50_2 68.88M, resnext50_32x4d
        25.03M."""
        pt.seed(0)
        w = M.wide_resnet50_2()
        n = sum(int(np.prod(p.shape)) for _, p in w.named_parameters())
        assert abs(n - 68_883_240) < 10_000, n
        r = M.resnext50_32x4d()
        n = sum(int(np.prod(p.shape)) for _, p in r.named_parameters())
        assert abs(n - 25_028_904) < 10_000, n

    def test_forward_shapes(self):
        pt.seed(0)
        x = jnp.zeros((1, 3, 64, 64))
        assert M.resnext50_32x4d(num_classes=7)(x).shape == (1, 7)
        assert M.LeNet()(jnp.zeros((2, 1, 28, 28))).shape == (2, 10)
        y = M.squeezenet1_0(num_classes=5)(jnp.zeros((1, 3, 96, 96)))
        assert y.shape == (1, 5)

    def test_mobilenet_v3_param_counts_exact(self):
        """Exactly torchvision's counts: large 5,483,032 / small
        2,542,856."""
        pt.seed(0)
        n = sum(int(np.prod(p.shape)) for _, p in
                M.mobilenet_v3_large().named_parameters())
        assert n == 5_483_032, n
        n = sum(int(np.prod(p.shape)) for _, p in
                M.mobilenet_v3_small().named_parameters())
        assert n == 2_542_856, n
        y = M.mobilenet_v3_small(num_classes=4)(jnp.zeros((1, 3, 64, 64)))
        assert y.shape == (1, 4)

    def test_googlenet_inception_aux_heads(self):
        """Training mode returns (out, aux...) like the reference; eval
        returns the logits; param counts track torchvision (head-size
        delta accounted: 13.00M/27.16M at 1000 classes)."""
        pt.seed(0)
        g = M.googlenet(num_classes=10)
        outs = g(jnp.zeros((1, 3, 96, 96)))
        assert len(outs) == 3 and all(o.shape == (1, 10) for o in outs)
        n = sum(int(np.prod(p.shape)) for _, p in g.named_parameters())
        assert abs(n - 9_960_638) < 5_000, n
        g.eval()
        assert g(jnp.zeros((1, 3, 96, 96))).shape == (1, 10)
        iv = M.inception_v3(num_classes=10)
        outs = iv(jnp.zeros((1, 3, 299, 299)))
        assert len(outs) == 2 and outs[0].shape == (1, 10)
        n = sum(int(np.prod(p.shape)) for _, p in iv.named_parameters())
        assert abs(n - 24_371_444) < 5_000, n
        iv.eval()
        assert iv(jnp.zeros((1, 3, 299, 299))).shape == (1, 10)

    def test_datasets_exist(self):
        from paddle_tpu.vision import datasets as DS

        assert issubclass(DS.FashionMNIST, DS.MNIST)
        assert DS.Cifar100._batches_train == ["train"]


class TestDistributionTail:
    def test_log_prob_vs_torch(self):
        cases = (
            (D.Geometric(0.3), torch.distributions.Geometric(0.3), 4.0),
            (D.Cauchy(1.0, 2.0), torch.distributions.Cauchy(1.0, 2.0), 0.7),
            (D.StudentT(5.0, 1.0, 2.0),
             torch.distributions.StudentT(5.0, 1.0, 2.0), 0.3),
            (D.Binomial(10, 0.4),
             torch.distributions.Binomial(10, 0.4), 3.0),
            (D.ContinuousBernoulli(0.3),
             torch.distributions.ContinuousBernoulli(0.3), 0.7),
            # the lambda ~ 0.5 Taylor branch
            (D.ContinuousBernoulli(0.5),
             torch.distributions.ContinuousBernoulli(0.5), 0.7),
        )
        for ours, theirs, v in cases:
            np.testing.assert_allclose(
                float(ours.log_prob(v)),
                float(theirs.log_prob(torch.tensor(v))), atol=2e-4,
                err_msg=type(ours).__name__)

    def test_entropy_vs_torch(self):
        for ours, theirs in (
                (D.Cauchy(1.0, 2.0), torch.distributions.Cauchy(1.0, 2.0)),
                (D.StudentT(5.0, 1.0, 2.0),
                 torch.distributions.StudentT(5.0, 1.0, 2.0)),
                (D.Geometric(0.3), torch.distributions.Geometric(0.3))):
            np.testing.assert_allclose(float(ours.entropy()),
                                       float(theirs.entropy()), atol=2e-4)

    def test_independent_and_register_kl(self):
        base = D.Normal(jnp.zeros((3, 4)), jnp.ones((3, 4)))
        ind = D.Independent(base, 1)
        tb = torch.distributions.Independent(
            torch.distributions.Normal(torch.zeros(3, 4),
                                       torch.ones(3, 4)), 1)
        np.testing.assert_allclose(
            np.asarray(ind.log_prob(jnp.zeros((3, 4)))),
            tb.log_prob(torch.zeros(3, 4)).numpy(), rtol=1e-5)
        # Independent KL reduces over event dims
        q = D.Independent(D.Normal(jnp.ones((3, 4)),
                                   jnp.ones((3, 4))), 1)
        kl = D.kl_divergence(ind, q)
        assert kl.shape == (3,)
        # registered kernels take precedence
        class _Marker(D.Geometric):
            pass

        @D.register_kl(_Marker, _Marker)
        def _kl(p, q):  # noqa: ANN001
            return jnp.asarray(42.0)

        assert float(D.kl_divergence(_Marker(0.3), _Marker(0.5))) == 42.0
        # Cauchy-Cauchy closed form is positive and zero at identity
        assert float(D.kl_divergence(D.Cauchy(0.0, 1.0),
                                     D.Cauchy(0.0, 1.0))) < 1e-6
        assert float(D.kl_divergence(D.Cauchy(0.0, 1.0),
                                     D.Cauchy(1.0, 2.0))) > 0

    def test_exponential_family_autograd_entropy(self):
        class _NormalEF(D.ExponentialFamily):
            def __init__(self, loc, scale):
                self.loc = jnp.float32(loc)
                self.scale = jnp.float32(scale)

            @property
            def _natural_parameters(self):
                return (self.loc / self.scale ** 2,
                        -0.5 / self.scale ** 2)

            def _log_normalizer(self, t1, t2):
                return -t1 ** 2 / (4 * t2) - 0.5 * jnp.log(-2 * t2)

            @property
            def _mean_carrier_measure(self):
                return 0.5 * np.log(2 * np.pi)

        np.testing.assert_allclose(
            float(_NormalEF(1.0, 2.0).entropy()),
            float(torch.distributions.Normal(1.0, 2.0).entropy()),
            atol=1e-4)


class TestCallbackTail:
    def test_reduce_lr_on_plateau(self):
        from paddle_tpu import hapi, optimizer as opt

        cb = hapi.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                    patience=2, verbose=0)

        class _FakeModel:
            _optimizer = opt.SGD(learning_rate=0.8)

        cb.model = _FakeModel()
        cb.on_eval_end({"loss": 1.0})
        for _ in range(3):
            cb.on_eval_end({"loss": 2.0})   # no improvement
        assert abs(float(_FakeModel._optimizer.base_lr) - 0.4) < 1e-9

    def test_visualdl_writes_jsonl(self, tmp_path):
        import json

        from paddle_tpu import hapi

        cb = hapi.VisualDL(log_dir=str(tmp_path))
        for i in range(10):
            cb.on_train_batch_end(i, {"loss": 1.0 / (i + 1)})
        cb.on_eval_end({"acc": 0.9})
        lines = [json.loads(l) for l in
                 (tmp_path / "scalars.jsonl").read_text().splitlines()]
        assert any(r["tag"] == "train" for r in lines)
        assert any(r["tag"] == "eval" and r["acc"] == 0.9 for r in lines)
