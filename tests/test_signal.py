"""paddle_tpu.signal parity vs torch.stft/istft (upstream model:
test/legacy_test/test_stft_op.py, test_istft_op.py, test_frame_op.py,
test_overlap_add_op.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from paddle_tpu import signal as S


@pytest.fixture
def x():
    return np.random.default_rng(0).normal(size=(2, 400)).astype(np.float32)


class TestStft:
    def test_stft_vs_torch(self, x):
        n_fft, hop, win = 64, 16, 64
        w = np.hanning(win).astype(np.float32)
        ours = np.asarray(
            S.stft(jnp.asarray(x), n_fft, hop, win, jnp.asarray(w))
        )
        ref = torch.stft(
            torch.tensor(x), n_fft, hop, win, torch.tensor(w),
            center=True, pad_mode="reflect", return_complex=True,
        ).numpy()
        assert ours.shape == ref.shape
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)

    def test_stft_normalized_short_window(self, x):
        w = np.hanning(48).astype(np.float32)
        ours = np.asarray(
            S.stft(jnp.asarray(x), 64, 16, 48, jnp.asarray(w),
                   normalized=True)
        )
        ref = torch.stft(
            torch.tensor(x), 64, 16, 48, torch.tensor(w),
            normalized=True, return_complex=True,
        ).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)

    def test_istft_roundtrip(self, x):
        n_fft, hop, win = 64, 16, 64
        w = np.hanning(win).astype(np.float32)
        spec = S.stft(jnp.asarray(x), n_fft, hop, win, jnp.asarray(w))
        y = np.asarray(
            S.istft(spec, n_fft, hop, win, jnp.asarray(w), length=400)
        )
        ref = torch.istft(
            torch.stft(torch.tensor(x), n_fft, hop, win, torch.tensor(w),
                       return_complex=True),
            n_fft, hop, win, torch.tensor(w), center=True, length=400,
        ).numpy()
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)
        # least-squares inverse reconstructs the interior exactly
        np.testing.assert_allclose(y[:, 32:-32], x[:, 32:-32],
                                   rtol=1e-3, atol=1e-3)

    def test_frame_overlap_add_inverse(self, x):
        fr = S.frame(jnp.asarray(x), 32, 32)  # non-overlapping
        assert fr.shape == (2, 32, 400 // 32)
        back = S.overlap_add(fr, 32)
        np.testing.assert_allclose(
            np.asarray(back), x[:, : back.shape[-1]], rtol=1e-6
        )

    def test_grad_flows(self, x):
        w = jnp.asarray(np.hanning(64).astype(np.float32))

        def loss(v):
            sp = S.stft(v, 64, 16, 64, w)
            return jnp.sum(jnp.abs(sp) ** 2)

        g = jax.grad(loss)(jnp.asarray(x))
        assert np.isfinite(np.asarray(g)).all()
