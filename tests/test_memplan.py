"""AOT scale-proof tests (SURVEY §6 north star).

The 7B plan runs for real in a subprocess (own
--xla_force_host_platform_device_count=8); the meta-init machinery it
rides on is unit-tested here directly. The 70B/128-device plan is too
slow for the suite — `python benchmarks/memplan.py` produces it into
MEMPLAN.md (committed artifact).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")


def test_meta_init_builds_abstract_params():
    import paddle_tpu as pt
    from paddle_tpu.core.meta import materialize, meta_init
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    with meta_init():
        model = LlamaForCausalLM(LlamaConfig.tiny())
    vals = [p.value for _, p in model.named_parameters()]
    assert vals and all(isinstance(v, jax.ShapeDtypeStruct) for v in vals)
    # to(dtype) recasts abstract placeholders
    model.to(pt.bfloat16)
    assert all(p.value.dtype == jnp.bfloat16
               for _, p in model.named_parameters())
    model.to(pt.float32)
    # materialize runs the kept init_fns → a runnable model
    materialize(model, seed=0)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 16)))
    loss = model(ids, labels=ids)
    assert bool(jnp.isfinite(loss))


def test_abstract_train_step_lowers_and_fits():
    """TrainStep(abstract=True) lowers/compiles the full ZeRO-3 step from
    a meta model; memory_analysis is readable and run() refuses."""
    from paddle_tpu import distributed as dist, optimizer as opt
    from paddle_tpu.core.meta import meta_init
    from paddle_tpu.distributed.strategy import (
        DistributedStrategy,
        HybridConfig,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.trainer import TrainStep

    with meta_init():
        model = LlamaForCausalLM(
            LlamaConfig.tiny(use_flash_attention=False))
    mesh = dist.build_mesh(fsdp=2, tp=2)
    strategy = DistributedStrategy()
    strategy.hybrid_configs = HybridConfig(sharding_degree=2, mp_degree=2)
    strategy.sharding = True
    strategy.sharding_configs.stage = 3
    ts = TrainStep(model, opt.AdamW(1e-3, multi_precision=True), mesh,
                   strategy, abstract=True)
    ids = jax.ShapeDtypeStruct((2, 32), jnp.int32)
    compiled = ts.lower({"input_ids": ids, "labels": ids}).compile()
    ma = compiled.memory_analysis()
    assert ma.argument_size_in_bytes > 0
    with pytest.raises(RuntimeError, match="abstract"):
        ts.run({"input_ids": None, "labels": None})


def test_memplan_7b_fits_v5p():
    """The real 7B plan: ZeRO-3 x tp2 x sep2 on a virtual 8-device mesh
    must fit v5p HBM with nothing large replicated."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "memplan.py"), "7b"],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    plan = json.loads(
        [l for l in r.stdout.splitlines() if l.startswith("{")][-1])
    assert plan["fits_v5p"], plan
    assert plan["params_b"] > 6.5e9
    assert plan["replicated_over_64mb"] == [], plan["replicated_over_64mb"]
    # ZeRO-3: per-device argument bytes must be well under params*14/n —
    # replication of params or moments would push it over
    full_state_gb = plan["params_b"] * 14 / 1024**3
    assert plan["xla_argument_gb_per_device"] < full_state_gb / 2
