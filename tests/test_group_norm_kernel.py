"""Fused Pallas GroupNorm(+SiLU) kernel: fwd+bwd parity vs the lax
reference in interpreter mode, fallback behavior, and the functional
dispatch under the NHWC layout policy."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt  # noqa: F401  (installs tensor methods)
from paddle_tpu.kernels import group_norm as gn
from paddle_tpu.nn import functional as F

pytestmark = pytest.mark.fast


def _case(n=2, h=5, w=7, c=32, g=8, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, h, w, c)), dtype)
    gamma = jnp.asarray(rng.standard_normal(c), jnp.float32)
    beta = jnp.asarray(rng.standard_normal(c), jnp.float32)
    return x, gamma, beta


@pytest.mark.parametrize("act", [None, "silu"])
def test_fused_forward_matches_reference(act):
    x, gamma, beta = _case()
    ref = gn.group_norm_reference(x, gamma, beta, 8, 1e-5, act)
    got = gn.fused_group_norm(x, gamma, beta, 8, 1e-5, act)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("act", [None, "silu"])
def test_fused_backward_matches_reference(act):
    x, gamma, beta = _case(seed=1)
    ct = jnp.asarray(
        np.random.default_rng(9).standard_normal(x.shape), jnp.float32)

    def loss(f):
        return lambda x, ga, be: jnp.sum(f(x, ga, be, 8, 1e-5, act) * ct)

    ref = jax.grad(loss(gn.group_norm_reference),
                   argnums=(0, 1, 2))(x, gamma, beta)
    got = jax.grad(loss(gn.fused_group_norm),
                   argnums=(0, 1, 2))(x, gamma, beta)
    for name, a, b in zip(("dx", "dgamma", "dbeta"), ref, got):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-5,
            err_msg=name)


def test_fused_under_jit_and_grad_of_mean():
    """The UNet-shaped use: jitted loss with the kernel inside."""
    x, gamma, beta = _case(c=16, g=4, seed=2)

    @jax.jit
    def loss(x, ga, be):
        y = gn.fused_group_norm(x, ga, be, 4, 1e-5, "silu")
        return jnp.mean(y ** 2)

    l0 = loss(x, gamma, beta)
    g0 = jax.jit(jax.grad(loss))(x, gamma, beta)
    assert np.isfinite(float(l0))
    assert g0.shape == x.shape and np.isfinite(np.asarray(g0)).all()


def test_bfloat16_inputs():
    x, gamma, beta = _case(dtype=jnp.bfloat16, seed=3)
    ref = gn.group_norm_reference(x, gamma, beta, 8, 1e-5, "silu")
    got = gn.fused_group_norm(x, gamma, beta, 8, 1e-5, "silu")
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


def test_channel_blocking_paths():
    """Shapes forcing different group-aligned channel slabs (c_block ==
    cg, and c_block == c) agree with the reference."""
    for (c, g) in ((24, 8), (64, 2), (10, 10)):
        x, gamma, beta = _case(c=c, g=g, seed=c)
        ref = gn.group_norm_reference(x, gamma, beta, g, 1e-5, None)
        got = gn.fused_group_norm(x, gamma, beta, g, 1e-5, None)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"c={c} g={g}")


def test_supports_fused_budget_gate(monkeypatch):
    assert gn.supports_fused((2, 5, 7, 32), 8)
    assert not gn.supports_fused((2, 5, 7, 30), 8)  # c % g != 0
    assert not gn.supports_fused((2, 5, 7, 32, 1), 8)  # not 4-D
    # shrink the budget below one group's slab -> fallback
    monkeypatch.setattr(gn, "VMEM_BUDGET_BYTES", 64)
    assert not gn.supports_fused((2, 5, 7, 32), 8)


def test_functional_dispatch_nhwc_vs_nchw():
    """F.group_norm NHWC (fused kernel) == NCHW jnp path on transposed
    input, with and without the fused activation."""
    x, gamma, beta = _case(seed=4)
    x_nchw = jnp.transpose(x, (0, 3, 1, 2))
    for act in (None, "silu"):
        y_nhwc = F.group_norm(x, 8, gamma, beta, 1e-5, "NHWC",
                              activation=act)
        y_nchw = F.group_norm(x_nchw, 8, gamma, beta, 1e-5, "NCHW",
                              activation=act)
        np.testing.assert_allclose(
            np.asarray(jnp.transpose(y_nhwc, (0, 3, 1, 2))),
            np.asarray(y_nchw), rtol=1e-5, atol=1e-5)


def test_functional_fallback_matches_fused(monkeypatch):
    """Over-budget shapes route to the lax reference with identical
    semantics (same tolerance band as the kernel)."""
    x, gamma, beta = _case(seed=5)
    fused = F.group_norm(x, 8, gamma, beta, 1e-5, "NHWC",
                         activation="silu")
    monkeypatch.setattr(gn, "VMEM_BUDGET_BYTES", 64)
    fallback = F.group_norm(x, 8, gamma, beta, 1e-5, "NHWC",
                            activation="silu")
    np.testing.assert_allclose(np.asarray(fallback), np.asarray(fused),
                               rtol=1e-5, atol=1e-5)


def test_groupnorm_no_affine_nhwc():
    x, _, _ = _case(seed=6)
    ref = gn.group_norm_reference(x, None, None, 8, 1e-5, None)
    got = F.group_norm(x, 8, None, None, 1e-5, "NHWC")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
