"""autograd functional transforms, dlpack interop, device namespace,
iinfo/finfo (upstream models: test/legacy_test/test_jacobian.py,
test_hessian.py, test_vjp_jvp.py, test_dlpack.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import paddle_tpu as pt
from paddle_tpu import autograd
from paddle_tpu.jax_compat import enable_x64 as _enable_x64


class TestFunctionalAutograd:
    def test_jacobian_matches_analytic(self):
        A = jnp.asarray(np.random.default_rng(0).normal(
            size=(3, 4)).astype(np.float64))
        x = jnp.asarray(np.random.default_rng(1).normal(
            size=(4,)).astype(np.float64))
        with _enable_x64(True):
            J = autograd.jacobian(lambda v: A @ v, x)
        np.testing.assert_allclose(np.asarray(J), np.asarray(A),
                                   rtol=1e-10)

    def test_jacobian_tuple_inputs(self):
        x = jnp.asarray([1.0, 2.0])
        y = jnp.asarray([3.0, 4.0])
        J = autograd.jacobian(lambda a, b: a * b, (x, y))
        np.testing.assert_allclose(np.asarray(J[0]), np.diag([3.0, 4.0]))
        np.testing.assert_allclose(np.asarray(J[1]), np.diag([1.0, 2.0]))

    def test_hessian_quadratic(self):
        A = np.array([[2.0, 1.0], [1.0, 4.0]], np.float32)
        H = autograd.hessian(
            lambda v: 0.5 * v @ jnp.asarray(A) @ v, jnp.ones(2))
        np.testing.assert_allclose(np.asarray(H), A, rtol=1e-5)

    def test_vjp_jvp(self):
        x = jnp.asarray([1.0, 2.0, 3.0])
        out, g = autograd.vjp(lambda v: jnp.sum(v ** 2), x)
        np.testing.assert_allclose(float(out), 14.0)
        np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(x))
        out2, t = autograd.jvp(lambda v: v ** 2, x,
                               jnp.asarray([1.0, 0.0, 0.0]))
        np.testing.assert_allclose(np.asarray(t), [2.0, 0.0, 0.0])


class TestDlpack:
    def test_torch_roundtrip(self):
        t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
        ours = pt.utils.dlpack.from_dlpack(t)
        np.testing.assert_allclose(np.asarray(ours), t.numpy())
        back = torch.from_dlpack(ours)  # jax array __dlpack__ direct
        np.testing.assert_allclose(back.numpy(), t.numpy())

    def test_capsule_export(self):
        x = jnp.arange(6.0)
        cap = pt.utils.dlpack.to_dlpack(x)
        assert cap is not None
        back = pt.utils.dlpack.from_dlpack(x)
        np.testing.assert_allclose(np.asarray(back), np.arange(6.0))


class TestDeviceInfo:
    def test_device_queries(self):
        d = pt.device.get_device()
        assert ":" in d
        assert pt.device.device_count() >= 1
        pt.device.synchronize()
        s = pt.device.current_stream()
        s.synchronize()
        assert not pt.device.is_compiled_with_cuda()

    def test_iinfo_finfo(self):
        assert pt.iinfo("int32").max == 2**31 - 1
        assert pt.finfo(pt.float32).eps == np.finfo(np.float32).eps
        assert float(pt.finfo(pt.bfloat16).max) > 3e38


class TestReviewFixes:
    def test_upsample_nhwc(self):
        x = jnp.ones((1, 2, 2, 3))
        out = pt.nn.Upsample(size=(4, 4), mode="nearest",
                             data_format="NHWC")(x)
        assert out.shape == (1, 4, 4, 3)

    def test_iinfo_dtype_objects(self):
        assert pt.iinfo(pt.int32).max == 2**31 - 1
        assert pt.iinfo(jnp.int8).min == -128

    def test_custom_device_query_is_name_specific(self):
        assert pt.device.is_compiled_with_custom_device("cpu")
        assert not pt.device.is_compiled_with_custom_device("npu")

    def test_set_device_unknown_raises(self):
        with pytest.raises(ValueError):
            pt.device.set_device("npu:0")
