"""ptaudit — the jaxpr-level contract auditor (tier-1 gate).

Four claims:

1. **The repo's real serving program set audits clean** — every
   contracted program, both cache modes x bf16/int8 arms, against the
   committed ``.ptaudit-baseline.json`` (AL donation, DQ dtype
   discipline, TX transfer bans, DD dead operands, SZ size pins).

2. **Every rule family actually fires** — hand-built violating
   programs (undonated pool write, unallowlisted upcast, io_callback
   smuggled into a jit, dead input, passthrough output, budget bust)
   each trip the named rule through the same ``audit_traced`` path
   the engine auditor uses.

3. **Audits are invisible to compile accounting** — audit-off is an
   identity (``{"enabled": False}``, zero behavior change), audit-on
   adds ZERO compiled programs (``compile_counter.assert_programs``)
   and restores ``TRACE_COUNTS`` exactly.

4. **The fixes the auditor forced stay fixed** — ``prefill_bucket``
   donates its bucket cache (the missing-donation finding) and the
   quantized engine ships no dead ``act_scale`` buffers (the
   dead-input finding); both pinned structurally here.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import serving_utils
from paddle_tpu import flags
from paddle_tpu.analysis import program_audit as PA
from paddle_tpu.analysis.program_audit import (
    AUDIT_ARMS,
    PROGRAM_CONTRACTS,
    ProgramContract,
    audit_traced,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the repo program set (audits cached once per session — tracing all
# arms costs seconds, and every test below reads the same report)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="session")
def repo_audit():
    return PA.audit_repo()


def test_contract_registry_matches_program_labels():
    """Runtime twin of ptlint PA001: the contract registry covers
    exactly the attribution registry (PROGRAM_LABELS, itself pinned
    to TRACE_COUNTS by OBS001) — no uncontracted program, no stale
    contract."""
    from paddle_tpu.observability.profiling import PROGRAM_LABELS

    assert set(PROGRAM_CONTRACTS) == set(PROGRAM_LABELS)
    # ...and every contract has a probe: PA001 forces the contract,
    # this pin forces the probe (a contracted-but-unprobeable program
    # is a clean AuditError, but it must never get that far)
    assert set(PA._PROBES) == set(PROGRAM_CONTRACTS)


def test_repo_program_set_audits_clean(repo_audit):
    assert not repo_audit["violations"], "\n".join(
        f"  {v.arm}::{v.program}: {v.rule} {v.message}"
        for v in repo_audit["violations"])
    # every canonical arm audited, with the expected program counts
    # (contig carries the prefix-store + legacy-insert programs,
    # paged the scatter/copy ones, int8 drops the legacy prefill)
    got = {a: sorted(r["programs"])
           for a, r in repo_audit["arms"].items()}
    assert set(got) == set(AUDIT_ARMS)
    assert got["contig-bf16"] == [
        "decode_chunk", "decode_step", "prefill_bucket",
        "prefill_chunk", "prefill_insert", "prefix_insert",
        "prefix_read", "spec_verify"]
    assert got["paged-bf16"] == [
        "decode_chunk", "decode_step", "page_copy", "prefill_bucket",
        "prefill_chunk", "prefill_scatter", "spec_verify"]
    assert got["paged-int8"] == [
        "decode_chunk", "decode_step", "page_copy", "prefill_chunk",
        "spec_verify"]
    # int8 legacy-prefill skips carry their reason
    assert "prefill_bucket" in repo_audit["arms"]["paged-int8"][
        "skipped"]


def test_committed_baseline_is_an_exact_pin(repo_audit):
    """The committed baseline equals the current traces exactly —
    op-count drift in EITHER direction shows up as a reviewable
    baseline diff, never silently."""
    baseline = PA.load_baseline(os.path.join(REPO, PA.BASELINE_NAME))
    assert baseline == repo_audit["entries"]


def test_pool_writers_donate_and_narrow_streams_stay_narrow(
        repo_audit):
    """The two headline promises, read off the report: every
    pool-writing program's pool operand is donated in every arm, and
    the int8 arm's only monitored widening is the allowlisted dequant
    pair (int8->float32) — no hidden f32 re-widening of the streams
    the bytes-per-token models price as narrow."""
    for arm, r in repo_audit["arms"].items():
        for name, entry in r["programs"].items():
            want = sorted(PROGRAM_CONTRACTS[name].donate)
            assert entry["donated"] == want, (arm, name, entry)
    int8 = repo_audit["arms"]["paged-int8"]["programs"]
    widen_pairs = {p for e in int8.values() for p in e["widen"]}
    assert widen_pairs <= {"int8->float32"}
    # and the dequant pair actually occurs (the check has teeth)
    assert any(e["widen"].get("int8->float32") for e in int8.values())


def test_prefill_bucket_donation_stays_fixed(repo_audit):
    """Regression pin for ptaudit's first real finding: the legacy
    per-bucket prefill used to fill its bucket cache WITHOUT donating
    it (a full bucket-cache copy per legacy prefill)."""
    for arm in ("contig-bf16", "paged-bf16"):
        entry = repo_audit["arms"][arm]["programs"]["prefill_bucket"]
        assert entry["donated"] == ["caches"], (arm, entry)


def test_quantized_engine_ships_no_act_scale(repo_audit):
    """Regression pin for ptaudit's dead-input finding: PTQ's
    act_scale calibration buffers are unread by every weight-only
    serving forward and used to ride each int8 program as dead args."""
    eng = PA.build_audit_engine("paged-int8")
    assert not [n for n in eng.buffers if n.endswith(".act_scale")]
    # ...and the model tree still carries them for state_dict
    assert [n for n, _ in eng.model.named_buffers()
            if n.endswith(".act_scale")]
    # no pb leaf is dead in the int8 report
    for name, entry in repo_audit["arms"]["paged-int8"][
            "programs"].items():
        assert not [d for d in entry["dead"] if d.startswith("pb")], (
            name, entry["dead"])


# ---------------------------------------------------------------------------
# rule families fire on hand-built violating programs
# ---------------------------------------------------------------------------
def _rules(viol):
    return [v.rule for v in viol]


def _audit(fn, args, contract, *, static=(), names=None,
           baseline_entry=None, check_size=False):
    return audit_traced(
        "synthetic", fn, args, static,
        names or tuple(f"a{i}" for i in range(len(args) - len(static))),
        contract, arm="test", baseline_entry=baseline_entry,
        check_size=check_size)


def test_al001_fires_on_undonated_pool_write():
    def fn(pool, x):
        return pool.at[0].set(x), x.sum()

    contract = ProgramContract(modes=("paged",), donate=("pool",))
    args = (jnp.zeros((4, 2)), jnp.ones((2,)))
    _entry, viol = _audit(jax.jit(fn), args, contract,
                          names=("pool", "x"))
    assert _rules(viol) == ["AL001"]
    assert "pool" in viol[0].message
    # donated -> clean
    _entry, viol = _audit(jax.jit(fn, donate_argnums=(0,)), args,
                          contract, names=("pool", "x"))
    assert not viol


def test_al002_fires_on_undeclared_donation():
    def fn(pool, x):
        return pool.at[0].set(x)

    contract = ProgramContract(modes=("paged",))  # declares nothing
    _entry, viol = _audit(
        jax.jit(fn, donate_argnums=(0,)),
        (jnp.zeros((4, 2)), jnp.ones((2,))), contract,
        names=("pool", "x"))
    assert _rules(viol) == ["AL002"]


def test_dq001_fires_on_unallowlisted_upcast():
    def fn(x):
        return x.astype(jnp.float32) * 2.0

    x = jnp.ones((4,), jnp.bfloat16)
    _entry, viol = _audit(jax.jit(fn), (x,),
                          ProgramContract(modes=("paged",)))
    assert _rules(viol) == ["DQ001"]
    assert "bfloat16->float32" in viol[0].message
    # allowlisted -> clean, and the count lands in the entry
    entry, viol = _audit(
        jax.jit(fn), (x,),
        ProgramContract(modes=("paged",),
                        widen_allow={"bfloat16->float32": "test"}))
    assert not viol
    assert entry["widen"] == {"bfloat16->float32": 1}


def test_dq001_sees_implicit_dot_accumulation():
    """preferred_element_type lets a matmul widen bf16/int8 operands
    straight into an f32 output with NO convert eqn — the auditor
    must count that as the same monitored widening (a movement-
    contract program gaining an f32-accum dot is a DQ001, not
    invisible)."""
    def fn(x, w):
        return jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    args = (jnp.ones((2, 4), jnp.bfloat16), jnp.ones((4, 3),
                                                     jnp.bfloat16))
    entry, viol = _audit(jax.jit(fn), args,
                         ProgramContract(modes=("paged",)),
                         names=("x", "w"))
    assert _rules(viol) == ["DQ001"]
    assert entry["widen"] == {"bfloat16->float32": 1}
    _entry, viol = _audit(
        jax.jit(fn), args,
        ProgramContract(modes=("paged",),
                        widen_allow={"bfloat16->float32": "accum"}),
        names=("x", "w"))
    assert not viol


def test_dq001_sees_int8_to_bf16_dequant():
    """int8 -> bfloat16 is a widening too ("bfloat16" doesn't match
    the float* name check — dequanting to the serving dtype is the
    most natural regression and must not slip the monitor)."""
    def fn(q):
        return q.astype(jnp.bfloat16) * 2

    entry, viol = _audit(jax.jit(fn), (jnp.ones((4,), jnp.int8),),
                         ProgramContract(modes=("paged",)))
    assert _rules(viol) == ["DQ001"]
    assert entry["widen"] == {"int8->bfloat16": 1}


def test_dq002_fires_on_widen_count_creep():
    def fn(x):
        return x.astype(jnp.float32) + x.astype(jnp.float32)[::-1]

    contract = ProgramContract(
        modes=("paged",), widen_allow={"bfloat16->float32": "test"})
    x = jnp.ones((4,), jnp.bfloat16)
    _entry, viol = _audit(
        jax.jit(fn), (x,), contract,
        baseline_entry={"eqns": 0, "widen": {"bfloat16->float32": 1}})
    assert "DQ002" in _rules(viol)
    assert "1 -> 2" in [v for v in viol if v.rule == "DQ002"][0].message
    # exact pin: a SHRINK reports too — silent headroom would let a
    # later upcast site creep back in under the old allowance
    _entry, viol = _audit(
        jax.jit(fn), (x,), contract,
        baseline_entry={"eqns": 0, "widen": {"bfloat16->float32": 3}})
    dq = [v for v in viol if v.rule == "DQ002"]
    assert dq and "shrank 3 -> 2" in dq[0].message
    # a pin whose pair vanished entirely (site + allowance removed
    # together) is a stale-baseline finding, not a silent pass
    def clean(x):
        return x * 2

    _entry, viol = _audit(
        jax.jit(clean), (jnp.ones((4,), jnp.bfloat16),),
        ProgramContract(modes=("paged",)), check_size=False,
        baseline_entry={"eqns": 0, "widen": {"int8->float32": 2}})
    dq = [v for v in viol if v.rule == "DQ002"]
    assert dq and "stale pin" in dq[0].message


def test_tx001_fires_on_io_callback_in_jit():
    from jax.experimental import io_callback

    def fn(x):
        io_callback(lambda v: None, None, x)
        return x + 1

    _entry, viol = _audit(jax.jit(fn), (jnp.ones((2,)),),
                          ProgramContract(modes=("paged",)))
    assert _rules(viol) == ["TX001"]
    assert "io_callback" in viol[0].message

    # a callback can't hide inside a cond BRANCH (branch jaxprs live
    # in a tuple param — the walker descends into those too)
    def hidden(x):
        return jax.lax.cond(
            x.sum() > 0,
            lambda v: jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct(v.shape, v.dtype), v),
            lambda v: v * 2, x)

    _entry, viol = _audit(jax.jit(hidden), (jnp.ones((2,)),),
                          ProgramContract(modes=("paged",)))
    assert _rules(viol) == ["TX001"]
    assert "pure_callback" in viol[0].message


def test_dd001_fires_on_dead_input():
    def fn(x, unused):
        return x * 2

    args = (jnp.ones((2,)), jnp.ones((3,)))
    _entry, viol = _audit(jax.jit(fn), args,
                          ProgramContract(modes=("paged",)),
                          names=("x", "unused"))
    assert _rules(viol) == ["DD001"]
    assert "unused" in viol[0].message
    # allowlisted via dead_ok -> clean
    _entry, viol = _audit(
        jax.jit(fn), args,
        ProgramContract(modes=("paged",), dead_ok=("unused",)),
        names=("x", "unused"))
    assert not viol


def test_dd002_fires_on_passthrough_and_constant_outputs():
    def fn(pool, x):
        return pool, x + 1, jnp.int32(7)

    _entry, viol = _audit(
        jax.jit(fn), (jnp.zeros((3,)), jnp.ones((2,))),
        ProgramContract(modes=("paged",)), names=("pool", "x"))
    rules = _rules(viol)
    assert rules.count("DD002") == 2, viol
    msgs = " | ".join(v.message for v in viol)
    assert "passes input 'pool'" in msgs and "constant" in msgs
    # a pure passthrough is ALSO a dead input (nothing reads it)
    assert rules.count("DD001") == 1
    # contract allowances (passthrough_ok + dead_ok) -> only the
    # constant output still fires
    _entry, viol = _audit(
        jax.jit(fn), (jnp.zeros((3,)), jnp.ones((2,))),
        ProgramContract(modes=("paged",), passthrough_ok=("pool",),
                        dead_ok=("pool",)),
        names=("pool", "x"))
    assert len(viol) == 1 and "constant" in viol[0].message


def test_sz_rules_fire_on_budget_bust_and_missing_entry():
    def fn(x):
        return x * 2 + 1

    contract = ProgramContract(modes=("paged",))
    args = (jnp.ones((2,)),)
    entry, viol = _audit(jax.jit(fn), args, contract,
                         baseline_entry=None, check_size=True)
    assert _rules(viol) == ["SZ002"]
    # exact pin: growth AND shrinkage both report
    _entry, viol = _audit(
        jax.jit(fn), args, contract, check_size=True,
        baseline_entry={"eqns": entry["eqns"] - 1, "widen": {}})
    assert _rules(viol) == ["SZ001"] and "grew" in viol[0].message
    _entry, viol = _audit(
        jax.jit(fn), args, contract, check_size=True,
        baseline_entry={"eqns": entry["eqns"] + 5, "widen": {}})
    assert _rules(viol) == ["SZ001"] and "shrank" in viol[0].message
    # matching pin -> clean
    _entry, viol = _audit(
        jax.jit(fn), args, contract, check_size=True,
        baseline_entry={"eqns": entry["eqns"], "widen": {}})
    assert not viol


def test_audit_restores_trace_accounting():
    """Tracing a real engine program bumps TRACE_COUNTS at trace time;
    the auditor must put every count (and shape note) back."""
    from paddle_tpu.inference import serving as S

    eng = PA.build_audit_engine("contig-bf16")
    before_counts = dict(S.TRACE_COUNTS)
    before_shapes = dict(S.TRACE_SHAPES)
    r = PA.audit_engine(eng, arm="probe")
    assert r["programs"]  # it really traced
    assert dict(S.TRACE_COUNTS) == before_counts
    assert dict(S.TRACE_SHAPES) == before_shapes


# ---------------------------------------------------------------------------
# engine path: audit-off identity, audit-on-seal, zero new programs
# ---------------------------------------------------------------------------
def _run_tiny_workload(eng):
    rng = np.random.default_rng(0)
    reqs = eng.run([rng.integers(1, 64, 9), rng.integers(1, 64, 5)],
                   max_new_tokens=6)
    return [r.output for r in reqs]


def test_audit_off_is_identity(compile_counter):
    """Default flags: no audit object, audit_snapshot is the off
    sentinel, seal_programs() stays cheap, and the workload compiles
    exactly the usual chunked-prefill program set."""
    assert flags.flag("audit_on_seal") is False
    model, _cfg = serving_utils.tiny_model()
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    eng = ContinuousBatchingEngine(model, serving_utils.tiny_ecfg(True))
    out_off = _run_tiny_workload(eng)
    eng.seal_programs()
    assert eng.audit_snapshot() == {"enabled": False}
    assert eng.metrics_snapshot()["audit"] == {"enabled": False}
    compile_counter.assert_programs(
        {"prefill_chunk", "decode_chunk", "decode_step"})
    assert out_off  # real tokens came out


def test_audit_on_seal_zero_new_programs(compile_counter):
    """audit_on_seal: the same workload, the same outputs, ZERO new
    compiled programs from the audit (trace-only), TRACE_COUNTS
    restored, and the verdict on metrics_snapshot()."""
    from paddle_tpu.inference import serving as S
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    model, _cfg = serving_utils.tiny_model()
    ref = ContinuousBatchingEngine(model, serving_utils.tiny_ecfg(True))
    want = _run_tiny_workload(ref)

    flags.set_flags({"audit_on_seal": True})
    try:
        eng = ContinuousBatchingEngine(model,
                                       serving_utils.tiny_ecfg(True))
        got = _run_tiny_workload(eng)
        before = dict(S.TRACE_COUNTS)
        eng.seal_programs()
        assert dict(S.TRACE_COUNTS) == before
        snap = eng.audit_snapshot()
        assert snap["enabled"] and snap["sealed"]
        assert snap["violations"] == []
        # the full paged program set (f32 cache: legacy prefill legal)
        assert snap["programs"] == 7 and snap["skipped"] == 3
        assert eng.metrics_snapshot()["audit"] == snap
    finally:
        flags.set_flags({"audit_on_seal": False})
    assert got == want
    # across BOTH engines and the seal-audit: only the usual programs
    compile_counter.assert_programs(
        {"prefill_chunk", "decode_chunk", "decode_step"})


def test_audit_on_seal_survives_legacy_prefill_engine():
    """Regression: a PT_FLAGS_prefill_chunk=0 engine has no [slots,C]
    program to trace — the seal-time self-audit must SKIP it with a
    reason, not crash the seal call."""
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    model, _cfg = serving_utils.tiny_model()
    flags.set_flags({"audit_on_seal": True, "prefill_chunk": 0})
    try:
        eng = ContinuousBatchingEngine(model,
                                       serving_utils.tiny_ecfg(False))
        eng.seal_programs()
        snap = eng.audit_snapshot()
        assert snap["sealed"] and snap["violations"] == []
        reason = eng._audit_report["skipped"]["prefill_chunk"]
        assert "prefill_chunk=0" in reason
    finally:
        flags.set_flags({
            "audit_on_seal": False,
            "prefill_chunk":
                flags.registry()["prefill_chunk"]["default"]})


def test_audit_on_seal_never_raises(monkeypatch):
    """A broken probe (signature drift a later PR forgot to mirror)
    must surface as an error VERDICT on the snapshot, never crash the
    production seal call — the recompile watchdog's 'never raises'
    contract applies to the self-audit too."""
    from paddle_tpu.analysis import program_audit as mod
    from paddle_tpu.inference.serving import ContinuousBatchingEngine

    model, _cfg = serving_utils.tiny_model()
    flags.set_flags({"audit_on_seal": True})
    try:
        eng = ContinuousBatchingEngine(model,
                                       serving_utils.tiny_ecfg(False))

        def broken(engine):
            raise mod.AuditError("probe drift")

        monkeypatch.setitem(mod._PROBES, "decode_step", broken)
        eng.seal_programs()  # must not raise
        snap = eng.audit_snapshot()
        assert snap["sealed"] and "probe drift" in snap["error"]
        assert snap["programs"] == 0 and snap["violations"] == []
    finally:
        flags.set_flags({"audit_on_seal": False})


def test_audit_on_seal_before_seal_reports_unsealed():
    flags.set_flags({"audit_on_seal": True})
    try:
        eng = PA.build_audit_engine("contig-bf16")
        assert eng.audit_snapshot() == {"enabled": True,
                                        "sealed": False}
    finally:
        flags.set_flags({"audit_on_seal": False})


# ---------------------------------------------------------------------------
# CLI: audit + combined check
# ---------------------------------------------------------------------------
def test_audit_cli_rules_and_json(tmp_path, capsys):
    rc = PA.main(["--rules"])
    assert rc == 0
    out = capsys.readouterr().out
    for rid in ("AL001", "DQ001", "TX001", "DD001", "SZ001"):
        assert rid in out
    rc = PA.main(["--arms", "paged-bf16", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["violations"] == []
    assert "decode_step" in doc["arms"]["paged-bf16"]["programs"]
    # unknown arm is a usage error, not a vacuously clean audit
    assert PA.main(["--arms", "nope"]) == 2


def test_audit_cli_write_baseline_round_trip(tmp_path, capsys):
    path = tmp_path / "base.json"
    rc = PA.main(["--arms", "paged-bf16", "--write-baseline",
                  "--baseline", str(path)])
    assert rc == 0
    data = json.loads(path.read_text())
    assert "paged-bf16::decode_step" in data["entries"]
    rc = PA.main(["--arms", "paged-bf16", "--baseline", str(path)])
    assert rc == 0
    # re-writing PRUNES stale pins within the audited arms (a deleted
    # program's entry must not ambush a future re-add) while keeping
    # other arms' pins untouched
    data = json.loads(path.read_text())
    data["entries"]["paged-bf16::retired_program"] = {
        "eqns": 1, "widen": {}}
    data["entries"]["contig-bf16::decode_step"] = {
        "eqns": 7, "widen": {}}
    path.write_text(json.dumps(data))
    rc = PA.main(["--arms", "paged-bf16", "--write-baseline",
                  "--baseline", str(path)])
    assert rc == 0
    entries = json.loads(path.read_text())["entries"]
    assert "paged-bf16::retired_program" not in entries
    assert entries["contig-bf16::decode_step"] == {"eqns": 7,
                                                   "widen": {}}
    # a bust against a doctored pin exits 1 and names SZ001
    data["entries"]["paged-bf16::decode_step"]["eqns"] -= 1
    path.write_text(json.dumps(data))
    rc = PA.main(["--arms", "paged-bf16", "--baseline", str(path)])
    assert rc == 1
    assert "SZ001" in capsys.readouterr().out
    # malformed baseline is a loud usage error on the READ path, and
    # the write path (the documented recovery command) replaces it
    # with a warning instead of dying on the corruption it fixes
    path.write_text("{not json")
    assert PA.main(["--arms", "paged-bf16",
                    "--baseline", str(path)]) == 2
    rc = PA.main(["--arms", "paged-bf16", "--write-baseline",
                  "--baseline", str(path)])
    err = capsys.readouterr().err
    assert rc == 0 and "replacing malformed baseline" in err
    assert "paged-bf16::decode_step" in json.loads(
        path.read_text())["entries"]


def test_write_baseline_cannot_accept_structural_violations(
        tmp_path, capsys, monkeypatch):
    """--write-baseline re-pins sizes; an AL/DQ001/TX/DD violation the
    same audit found must still print and fail the command — a
    baseline write is not a waiver."""
    from paddle_tpu.analysis.program_audit import AuditViolation

    real = PA.audit_repo

    def with_structural(*a, **kw):
        rep = real(*a, **kw)
        rep["violations"].append(AuditViolation(
            "paged-bf16", "decode_step", "AL001", "synthetic"))
        return rep

    monkeypatch.setattr(PA, "audit_repo", with_structural)
    path = tmp_path / "base.json"
    rc = PA.main(["--arms", "paged-bf16", "--write-baseline",
                  "--baseline", str(path)])
    cap = capsys.readouterr()
    assert rc == 1
    assert "AL001" in cap.out and "cannot accept" in cap.err
    # the size pins still landed (the write half did its job)
    assert "paged-bf16::decode_step" in json.loads(
        path.read_text())["entries"]


def test_check_cli_runs_both_gates(capsys):
    from paddle_tpu.analysis import check

    rc = check.main(["--arms", "paged-bf16", "--json"])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 0, doc
    assert doc["lint"]["violations"] == []
    assert doc["audit"]["violations"] == []
    assert any(p.startswith("paged-bf16::")
               for p in doc["audit"]["programs"])
