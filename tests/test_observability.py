"""Always-on telemetry: registry semantics, Prometheus exposition,
TrainStep sampling cadence + flight-recorder NaN dump, serving engine
metrics smoke, collective byte accounting, and the dump CLI."""

import json
import re
import subprocess
import sys
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import distributed as dist, observability as obs
from paddle_tpu import optimizer as opt
from paddle_tpu.trainer import TrainStep


@pytest.fixture(autouse=True)
def _telemetry_on():
    """conftest runs the suite with telemetry off (CI compile-time);
    this module tests the instrumented paths, so flip it on per-test
    and restore."""
    prev = pt.flags.flag("telemetry")
    pt.flags.set_flags({"FLAGS_telemetry": True})
    yield
    pt.flags.set_flags({"FLAGS_telemetry": prev})


# ---------------- registry semantics ----------------

@pytest.mark.fast
def test_counter_gauge_labels():
    reg = obs.MetricsRegistry()
    c = reg.counter("req_total", "requests", labels=("op",))
    c.inc(op="read")
    c.inc(2, op="read")
    c.inc(op="write")
    assert c.value(op="read") == 3
    assert c.value(op="write") == 1
    assert c.value(op="never") == 0
    with pytest.raises(ValueError):
        c.inc(bad_label="x")
    with pytest.raises(ValueError):
        c.inc(-1, op="read")
    g = reg.gauge("depth", "queue depth")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value() == 3
    g.set_max(10)
    g.set_max(7)  # lower: keeps the peak
    assert g.value() == 10
    # get-or-create is idempotent; kind/label mismatch raises
    assert reg.counter("req_total", labels=("op",)) is c
    with pytest.raises(ValueError):
        reg.gauge("req_total")


@pytest.mark.fast
def test_histogram_bucket_edges():
    reg = obs.MetricsRegistry()
    edges = obs.exp_buckets(1.0, 2.0, 4)  # 1, 2, 4, 8
    assert edges == (1.0, 2.0, 4.0, 8.0)
    h = reg.histogram("lat_ms", "latency", buckets=edges)
    for v in (0.5, 1.0, 3.0, 8.0, 100.0):
        h.observe(v)
    assert h.count() == 5
    snap = reg.snapshot()["lat_ms"]["series"][0]
    # per-bucket (non-cumulative) counts: le=1 gets 0.5 and 1.0;
    # 3.0 -> le=4; 8.0 -> le=8; 100.0 -> +Inf
    assert snap["buckets"] == {"1": 2, "2": 0, "4": 1, "8": 1}
    assert snap["inf"] == 1
    assert snap["sum"] == pytest.approx(112.5)
    assert h.percentile(50) == 3.0
    with pytest.raises(ValueError):
        reg.histogram("bad", buckets=(4.0, 2.0))
    with pytest.raises(ValueError):
        obs.exp_buckets(0, 2, 3)


@pytest.mark.fast
def test_prometheus_exposition_parses():
    reg = obs.MetricsRegistry()
    reg.counter("a_total", "with \"quotes\"", labels=("op",)).inc(
        op='weird "value"\nline')
    reg.gauge("b_bytes", "a gauge").set(1.5)
    h = reg.histogram("c_ms", "a histogram", labels=("route",),
                      buckets=(1.0, 10.0))
    h.observe(0.5, route="/x")
    h.observe(20.0, route="/x")
    text = reg.prometheus_text()
    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})?'
        r' -?[0-9.eE+-]+(inf|nan)?$')
    seen_types = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            seen_types[name] = kind
            continue
        assert sample_re.match(line), f"unparseable sample line: {line!r}"
    assert seen_types == {"a_total": "counter", "b_bytes": "gauge",
                          "c_ms": "histogram"}
    # histogram contract: cumulative le buckets + +Inf + _sum/_count
    assert 'c_ms_bucket{route="/x",le="1"} 1' in text
    assert 'c_ms_bucket{route="/x",le="10"} 1' in text
    assert 'c_ms_bucket{route="/x",le="+Inf"} 2' in text
    assert 'c_ms_count{route="/x"} 2' in text


@pytest.mark.fast
def test_noop_registry_when_disabled():
    assert obs.enabled()  # default is on
    pt.flags.set_flags({"FLAGS_telemetry": False})
    try:
        reg = obs.get_registry()
        assert isinstance(reg, obs.NullRegistry)
        c = reg.counter("nope_total", "x")
        c.inc()
        c.inc(100)
        assert c.value() == 0.0
        h = reg.histogram("nope_ms", "x")
        h.observe(5.0)
        assert h.percentile(50) is None
        assert reg.prometheus_text() == ""
        assert reg.snapshot() == {}
        # the same shared null object backs every metric: no dict churn
        assert reg.gauge("other") is c
    finally:
        pt.flags.set_flags({"FLAGS_telemetry": True})
    assert isinstance(obs.get_registry(), obs.MetricsRegistry)


# ---------------- trainer instrumentation ----------------

class _Reg(pt.Layer):
    def __init__(self):
        super().__init__()
        self.fc = pt.nn.Linear(8, 8)

    def forward(self, x):
        return self.fc(x)


def _mse(o, l):
    return jnp.mean((o - l) ** 2)


@pytest.mark.fast
def test_trainstep_sampling_cadence_and_gnorm(tmp_path):
    pt.seed(0)
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    tel = obs.TrainTelemetry(sample_every=3, flight_window=16,
                             dump_dir=str(tmp_path))
    ts = TrainStep(_Reg(), opt.AdamW(1e-3), mesh, loss_fn=_mse,
                   telemetry=tel)
    x = jnp.ones((4, 8))
    y = jnp.zeros((4, 8))
    for _ in range(7):
        ts.run({"input": x, "label": y})
    # steps 3 and 6 sampled; every step leaves a ring record
    assert tel.samples == 2
    recs = tel.recorder.records()
    assert len(recs) == 7
    sampled = [r for r in recs if "loss" in r]
    assert [r["step"] for r in sampled] == [3, 6]
    for r in sampled:
        assert np.isfinite(r["loss"])
        assert np.isfinite(r["grad_norm"]) and r["grad_norm"] > 0
        assert r["tokens_per_sec"] > 0
    # non-sampled records carry only host-side fields (no device sync)
    unsampled = [r for r in recs if "loss" not in r]
    assert all(set(r) == {"step", "wall_ms", "tokens"} for r in unsampled)
    assert not tel.watchdog.tripped


@pytest.mark.fast
def test_flight_recorder_dump_on_nan(tmp_path):
    pt.seed(0)
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    tel = obs.TrainTelemetry(sample_every=1, flight_window=8,
                             dump_dir=str(tmp_path / "fr"))
    ts = TrainStep(_Reg(), opt.AdamW(1e-3), mesh, loss_fn=_mse,
                   telemetry=tel)
    x = jnp.ones((4, 8))
    y = jnp.zeros((4, 8))
    for _ in range(3):
        ts.run({"input": x, "label": y})
    ts.run({"input": x, "label": jnp.full((4, 8), jnp.nan)})
    assert len(tel.watchdog.tripped) == 1
    step, reason, path = tel.watchdog.tripped[0]
    assert step == 4 and "non-finite loss" in reason
    dump = json.loads(open(path).read())
    assert dump["reason"] == reason
    # the window holds the K steps leading into the anomaly, with
    # grad-norms (sample_every=1 -> every record is sampled)
    assert [r["step"] for r in dump["records"]] == [1, 2, 3, 4]
    assert all("grad_norm" in r for r in dump["records"])
    assert not np.isfinite(dump["records"][-1]["loss"])


@pytest.mark.fast
def test_watchdog_grad_spike(tmp_path):
    rec = obs.FlightRecorder(capacity=4, dump_dir=str(tmp_path))
    wd = obs.AnomalyWatchdog(rec, spike_factor=10.0, min_history=3)
    for s in range(5):
        rec.record(step=s, grad_norm=1.0)
        assert wd.check(s, 0.5, 1.0) is None
    path = wd.check(5, 0.5, 50.0)  # 50x the median
    assert path and "spike" in wd.tripped[0][1]
    assert json.loads(open(path).read())["n_records"] == 4


def test_log_memory_stats_flag(tmp_path):
    pt.seed(0)
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    pt.flags.set_flags({"FLAGS_log_memory_stats": True})
    try:
        tel = obs.TrainTelemetry(sample_every=1, dump_dir=str(tmp_path))
        ts = TrainStep(_Reg(), opt.AdamW(1e-3), mesh, loss_fn=_mse,
                       telemetry=tel)
        ts.run({"input": jnp.ones((2, 8)), "label": jnp.zeros((2, 8))})
    finally:
        pt.flags.set_flags({"FLAGS_log_memory_stats": False})
    rec = tel.recorder.records()[-1]
    # CPU backends may not implement memory_stats(); when they do, the
    # sampled record and the registry gauge must carry it
    if "memory" in rec:
        assert rec["memory"]["bytes_in_use"] >= 0
        g = obs.global_registry().get("pt_device_memory_bytes")
        assert g.value(stat="bytes_in_use") == rec["memory"]["bytes_in_use"]


# ---------------- serving instrumentation ----------------

def _tiny_engine(paged=False):
    from paddle_tpu.inference import ContinuousBatchingEngine, EngineConfig
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    cfg = EngineConfig(max_slots=2, max_len=64, seq_buckets=(16,),
                       paged=paged, page_size=16)
    return ContinuousBatchingEngine(model, cfg), model.config


def test_serving_metrics_smoke():
    eng, mcfg = _tiny_engine(paged=True)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, mcfg.vocab_size, (10,)) for _ in range(4)]
    reqs = eng.run(prompts, max_new_tokens=5, max_chunk=2)
    assert all(len(r.output) == 5 for r in reqs)
    snap = eng.metrics_snapshot()
    assert snap["ttft_ms"]["count"] == 4
    assert snap["ttft_ms"]["p50"] > 0
    assert snap["ttft_ms"]["p90"] >= snap["ttft_ms"]["p50"]
    # 4 requests into 2 slots: at least 2 had to queue
    assert snap["queue_depth"]["peak"] >= 2
    assert snap["batch_occupancy"]["peak"] == 1.0
    assert snap["kv_pool"]["total"] > 0
    assert snap["kv_pool"]["peak_utilization"] > 0
    assert snap["requests"] == {"submitted": 4, "admitted": 4,
                                "finished": 4, "cancelled": 0}
    assert snap["tokens_generated"] >= 4 * 5
    assert snap["tpot_ms"]["p50"] > 0
    # window reset clears percentiles/peaks, keeps counters
    eng.metrics_window_reset()
    snap2 = eng.metrics_snapshot()
    assert snap2["ttft_ms"]["count"] == 0
    assert snap2["queue_depth"]["peak"] == 0
    assert snap2["requests"]["finished"] == 4


def test_serving_metrics_endpoint():
    from paddle_tpu.inference import start_metrics_server

    import urllib.error

    eng, mcfg = _tiny_engine(paged=False)
    eng.run([np.arange(8)], max_new_tokens=3, max_chunk=2)
    srv = start_metrics_server(eng, port=0)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            text = r.read().decode()
        assert "pt_serve_ttft_ms_bucket" in text
        # every serve series carries the engine label
        assert re.search(
            r'pt_serve_requests_finished_total\{engine="\d+"\} \d+', text)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            hz = json.loads(r.read())
        assert hz["status"] == "ok"
        assert hz["engine"]["requests"]["finished"] >= 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        srv.shutdown()


@pytest.mark.fast
def test_serving_telemetry_per_engine_isolation():
    a = obs.ServingTelemetry()
    b = obs.ServingTelemetry()
    a.on_submit(3)
    a.on_admit(10.0)
    b.on_submit(1)
    assert a.snapshot()["queue_depth"]["peak"] == 3
    assert b.snapshot()["queue_depth"]["peak"] == 1
    # one engine's window reset must not clobber the other's series
    a.window_reset()
    assert a.snapshot()["ttft_ms"]["count"] == 0
    assert a.snapshot()["queue_depth"]["peak"] == 0
    assert b.snapshot()["queue_depth"]["peak"] == 1
    assert a.snapshot()["requests"]["submitted"] == 1
    assert b.snapshot()["requests"]["submitted"] == 1
    # cumulative histogram totals survive the window reset
    reg = obs.global_registry()
    assert reg.get("pt_serve_ttft_ms").count(engine=a.engine_id) == 1


# ---------------- collective byte accounting ----------------

@pytest.mark.fast
def test_collective_byte_accounting():
    obs.reset_comm_log()
    mesh = dist.build_mesh(dp=8)
    x = jnp.arange(32, dtype=jnp.float32)
    out = dist.all_reduce(x, mesh=mesh)
    assert out.shape == x.shape
    log = [e for e in obs.comm_log() if e["op"] == "all_reduce"]
    assert len(log) == 1
    # per-participant payload at trace time: 32/8 rows of 4 bytes
    assert log[0]["bytes"] == 16
    assert log[0]["axis"] == "dp"
    assert log[0]["traced_calls"] == 1
    # call-site attribution points at THIS file, not the plumbing
    assert log[0]["site"].startswith("test_observability.py:")
    # a second execution of the SAME compiled program adds nothing
    dist.all_reduce(x, mesh=mesh)
    log2 = [e for e in obs.comm_log() if e["op"] == "all_reduce"]
    assert log2[0]["traced_calls"] <= 2  # retrace at most (new shard_map)
    c = obs.global_registry().get("pt_collective_traced_bytes_total")
    assert c.value(op="all_reduce", axis="dp") >= 16


# ---------------- dump CLI ----------------

def test_dump_cli_smoke():
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PT_FLAGS_telemetry="on")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.observability.dump",
         "--no-device"],
        capture_output=True, text=True, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 0, proc.stderr
    snap = json.loads(proc.stdout)
    assert snap["telemetry_enabled"] is True
    assert "metrics" in snap and "collectives" in snap
    assert "device_memory" not in snap
