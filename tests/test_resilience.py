"""Fault-tolerant serving: the resilience layer.

Under test:
  - ``FaultInjector`` determinism: same spec + seed → the exact same
    fire schedule per site, sites mutually isolated; spec validation;
  - deterministic REPLAY PARITY: under injected step faults and
    NaN-logits storms the engine quarantines the step, re-queues the
    in-flight requests, re-prefills prompt+history through the
    existing chunked-prefill program — and greedy outputs stay
    bit-identical to a fault-free run, in BOTH cache modes, with zero
    leaked slots / KV pages / prefix refs;
  - recovery/replay adds ZERO new compiled programs (the
    compile-counter guard: replay reuses ``prefill_chunk`` +
    ``decode_chunk``);
  - bounded retries: a permanently-faulting step fails the request
    with ``finish_reason="failed"`` instead of looping forever;
  - HARD recovery (``serve_recovery=all`` / XLA runtime errors):
    cache pools rebuilt, prefix store dropped, outputs still exact;
  - per-request deadlines: queued and mid-decode expiry through
    ``_finish_accounting(reason="timeout")`` + ``_release_slot`` —
    slots, pages and prefix refs provably freed; SLO accounting
    counts timeouts as violations; ``add_request`` validation;
  - the degradation ladder: saturation → shed_batch → throttle
    (capped), faults → min_service; engine actions (batch deferral,
    prefix/spec disable) change throughput only, never outputs;
  - ``engine.drain()``: admission stops, in-flight completes (or
    expires at the drain deadline), ``/healthz`` reports draining;
  - ``start_metrics_server`` returns a handle whose ``shutdown()``
    joins the thread and closes the socket (no leaked listeners).
"""

import json
import socket
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import flags as F
from paddle_tpu.inference.resilience import (
    DegradationController,
    FaultInjector,
)
from paddle_tpu.inference.serving import (
    ContinuousBatchingEngine,
    EngineConfig,
    start_metrics_server,
)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

pytestmark = pytest.mark.chaos


def _model(seed=0):
    pt.seed(seed)
    cfg = LlamaConfig.tiny()
    return LlamaForCausalLM(cfg), cfg


def _ecfg(paged, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_len", 128)
    kw.setdefault("seq_buckets", (32,))
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("page_size", 8)
    return EngineConfig(paged=paged, **kw)


def _prompts(cfg, n=6, seed=0, lo=4, hi=20):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size,
                         (int(rng.integers(lo, hi)),))
            for _ in range(n)]


def _drive(eng, max_chunk=4):
    while eng.step_chunk(max_chunk) or eng._queue or eng.active.any():
        pass


def _assert_no_leaks(eng):
    """Slots back on the heap, and — paged — the pool fully recovers
    once store-retained (evictable) prefix pages are released."""
    assert not eng.active.any()
    assert sorted(eng._free_heap) == list(range(eng.cfg.max_slots))
    assert not eng._slot_req
    if eng.cfg.paged:
        eng._evict_pages(10 ** 9)
        assert eng.pool.free_pages == eng.pool.n_pages - 1
        assert not eng.pool.ref


@pytest.fixture
def res_flags():
    keys = ("fault_inject", "serve_recovery", "degradation",
            "telemetry", "spec_decode", "prefix_cache",
            "prefill_chunk", "telemetry_dump_dir")
    saved = {k: F.flag(k) for k in keys}
    yield F.set_flags
    F.set_flags(saved)


# ---------------------------------------------------------------------------
# fault injector
# ---------------------------------------------------------------------------

def test_injector_determinism_and_isolation():
    """Same spec + seed → identical schedules; adding a site to the
    spec must not shift another site's stream (independent RNGs)."""
    a = FaultInjector("step:0.3,nan:0.2", seed=11)
    b = FaultInjector("step:0.3,nan:0.2", seed=11)
    seq_a = [(a.fire("step"), a.fire("nan")) for _ in range(64)]
    seq_b = [(b.fire("step"), b.fire("nan")) for _ in range(64)]
    assert seq_a == seq_b
    assert a.fires == b.fires and a.draws == b.draws
    assert any(s for s, _ in seq_a) and any(n for _, n in seq_a)
    # isolation: step's schedule is identical with/without nan enabled
    c = FaultInjector("step:0.3", seed=11)
    assert [c.fire("step") for _ in range(64)] == [s for s, _ in seq_a]
    # a different seed gives a different schedule
    d = FaultInjector("step:0.3,nan:0.2", seed=12)
    assert [(d.fire("step"), d.fire("nan")) for _ in range(64)] != seq_a
    # rate-0 sites never draw
    assert c.fire("pool") is False and c.draws["pool"] == 0


def test_injector_spec_validation():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultInjector("bogus:0.5")
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        FaultInjector("step:1.5")
    with pytest.raises(ValueError, match="key:value"):
        FaultInjector("step")
    with pytest.raises(ValueError, match="latency_ms"):
        FaultInjector("latency_ms:0")
    inj = FaultInjector("step:1.0,seed:5,latency_ms:3.5")
    assert inj.seed == 5 and inj.latency_ms == 3.5
    assert inj.fire("step") is True  # rate 1.0 always fires
    assert inj.snapshot()["rates"]["step"] == 1.0


def test_injector_from_flag(res_flags):
    res_flags({"fault_inject": "step:0.25,seed:9"})
    model, _ = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    assert eng._injector is not None
    assert eng._injector.rates["step"] == 0.25
    assert eng._injector.seed == 9
    res_flags({"fault_inject": ""})
    eng2 = ContinuousBatchingEngine(model, _ecfg(False))
    assert eng2._injector is None  # empty flag: zero overhead


# ---------------------------------------------------------------------------
# deterministic replay
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("paged", [False, True])
def test_replay_parity_under_step_and_nan_faults(paged):
    """THE chaos parity claim: under injected step faults + NaN storms
    + latency spikes, greedy outputs are bit-identical to a fault-free
    run and nothing leaks."""
    model, cfg = _model()
    prompts = _prompts(cfg)
    ref = ContinuousBatchingEngine(model, _ecfg(paged)).run(
        prompts, max_new_tokens=6)
    inj = FaultInjector("step:0.2,nan:0.1,latency:0.05", seed=3,
                        latency_ms=1.0)
    eng = ContinuousBatchingEngine(model, _ecfg(paged),
                                   fault_injector=inj)
    rids = [eng.add_request(p, 6) for p in prompts]
    _drive(eng)
    rs = eng.resilience_stats
    assert rs["recoveries"] > 0, "storm never fired — vacuous test"
    assert rs["retries"] > 0
    assert rs["nan_steps"] > 0
    for r, rid in zip(ref, rids):
        got = eng._finished[rid]
        assert got.finish_reason == "max_new_tokens"
        assert got.output == r.output  # bit-identical greedy replay
    _assert_no_leaks(eng)
    # and the injector can simply be removed: the engine keeps serving
    eng._injector = None
    out = eng.run([prompts[0]], max_new_tokens=4)
    assert len(out[0].output) == 4


def test_replay_parity_with_spec_decode(res_flags):
    """Replay composes with speculative decoding: quarantines during
    verify passes (and the drafter's history growing by replayed
    tokens) still reproduce the fault-free greedy chain exactly."""
    res_flags({"spec_decode": "ngram"})
    model, cfg = _model()
    rng = np.random.default_rng(2)
    base = rng.integers(0, cfg.vocab_size, (8,))
    prompts = [np.concatenate(
        [base, base, rng.integers(0, cfg.vocab_size, (3,))])
        for _ in range(4)]
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        prompts, max_new_tokens=8)
    inj = FaultInjector("step:0.25,nan:0.1", seed=5)
    eng = ContinuousBatchingEngine(
        model, _ecfg(True, max_retries=20), fault_injector=inj)
    rids = [eng.add_request(p, 8) for p in prompts]
    while eng.step() or eng._queue or eng.active.any():
        pass
    assert eng.resilience_stats["recoveries"] > 0
    assert eng.spec_stats["verify_calls"] > 0
    for r, rid in zip(ref, rids):
        assert eng._finished[rid].output == r.output
    _assert_no_leaks(eng)


def test_replay_reuses_compiled_programs(compile_counter):
    """Recovery/replay adds ZERO compiled programs: after the engine's
    program set is warm, a fault storm (with its re-queues and
    prompt+history re-prefills) must not trigger a single new jit
    specialization — replay rides the existing ``prefill_chunk`` and
    ``decode_chunk`` programs."""
    model, cfg = _model()
    prompts = _prompts(cfg, n=5, seed=2)
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    # warm with the same chunk K the storm uses (K is a static shape)
    eng.run(prompts[:2], max_new_tokens=4, max_chunk=4)
    warm = compile_counter()
    inj = FaultInjector("step:0.3,nan:0.15", seed=7)
    eng._injector = inj
    rids = [eng.add_request(p, 6) for p in prompts]
    _drive(eng)
    assert eng.resilience_stats["recoveries"] > 0
    after = compile_counter()
    assert after == warm, (
        f"recovery/replay compiled new programs: "
        f"{ {k: after.get(k, 0) - warm.get(k, 0) for k in after} }")
    compile_counter.assert_programs(
        {"prefill_chunk", "decode_chunk", "page_copy"})


def test_retry_exhaustion_fails_request():
    """A permanently-faulting engine must not loop: each quarantine
    charges one retry, and past the bound the request finishes with
    reason ``failed`` — never a hang, never a leak."""
    model, cfg = _model()
    inj = FaultInjector("step:1.0", seed=0)  # every seam faults
    eng = ContinuousBatchingEngine(
        model, _ecfg(True, max_retries=1), fault_injector=inj)
    rids = [eng.add_request(p, 4) for p in _prompts(cfg, n=3, seed=4)]
    _drive(eng)
    for rid in rids:
        assert eng._finished[rid].finish_reason == "failed"
    assert eng.resilience_stats["failed"] == 3
    _assert_no_leaks(eng)
    # per-request override beats the engine default
    eng._injector = None
    r_ok = eng.add_request(np.arange(1, 9), 3, max_retries=0)
    _drive(eng)
    assert eng._finished[r_ok].finish_reason == "max_new_tokens"


def test_hard_fault_rebuilds_and_replays(res_flags):
    """A real (non-injected) runtime failure mid-chunk: with
    ``serve_recovery=all`` the engine requeues every active request,
    drops the prefix store, rebuilds the cache pools — and still
    produces bit-identical greedy outputs through replay."""
    res_flags({"serve_recovery": "all"})
    model, cfg = _model()
    prompts = _prompts(cfg, n=4, seed=5)
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        prompts, max_new_tokens=6)
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    real = eng._decode_n()
    calls = {"n": 0}

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("device fell over")
        return real(*a, **k)

    eng._decode_nc = flaky
    rids = [eng.add_request(p, 6) for p in prompts]
    _drive(eng, max_chunk=2)
    assert eng.resilience_stats["rebuilds"] == 1
    assert eng.resilience_stats["faults"].get("error") == 1
    for r, rid in zip(ref, rids):
        assert eng._finished[rid].output == r.output
    _assert_no_leaks(eng)


def test_auto_mode_propagates_host_errors():
    """``serve_recovery=auto`` must NOT swallow host logic errors: a
    plain RuntimeError from the decode path propagates (the existing
    failure-injection tests' contract)."""
    model, _ = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False))

    def boom(*a, **k):
        raise RuntimeError("host bug")

    eng._decode_nc = boom
    eng.add_request(np.arange(1, 9), 4)
    with pytest.raises(RuntimeError, match="host bug"):
        eng.step_chunk(2)  # admits, then the decode dispatch raises
    assert eng.resilience_stats["recoveries"] == 0


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

def test_deadline_queued_and_active_expiry():
    model, cfg = _model()
    eng = ContinuousBatchingEngine(
        model, _ecfg(True, max_slots=1, n_pages=13))
    free0 = eng.pool.free_pages
    r0 = eng.add_request(np.arange(1, 9), 24, slo="interactive")
    r1 = eng.add_request(np.arange(2, 9), 4, deadline_ms=30.0,
                         slo="interactive")
    eng.step_chunk(2)  # r0 admitted; r1 queued behind a 1-slot engine
    time.sleep(0.05)
    _drive(eng, 2)
    q = eng._finished[r1]
    assert q.finish_reason == "timeout" and not q.output
    assert q.slo_met is False  # timeout = forced SLO violation
    assert len(eng._finished[r0].output) == 24
    snap = eng.slo_snapshot()["classes"]["interactive"]
    assert snap["timeouts"] == 1 and snap["violated"] >= 1
    assert eng.resilience_stats["timeouts"] == 1

    # active expiry mid-decode: partial output kept, pages freed
    r2 = eng.add_request(np.arange(3, 10), 60, deadline_ms=40.0)
    eng.step_chunk(2)
    time.sleep(0.06)
    eng.step_chunk(2)
    req = eng._finished[r2]
    assert req.finish_reason == "timeout"
    assert 0 < len(req.output) < 60  # expired mid-flight
    eng._evict_pages(10 ** 9)
    assert eng.pool.free_pages == free0 and not eng.pool.ref


def test_deadline_defaults_and_validation():
    model, _ = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    # SLO classes carry a default hard deadline
    rid = eng.add_request(np.arange(1, 9), 2, slo="interactive")
    req = next(r for r in eng._queue if r.rid == rid)
    assert req.deadline_ms == 30_000.0 and req._deadline_t > 0
    # untracked requests default to no deadline
    rid2 = eng.add_request(np.arange(1, 9), 2)
    req2 = next(r for r in eng._queue if r.rid == rid2)
    assert req2.deadline_ms is None and req2._deadline_t == 0.0
    with pytest.raises(ValueError, match="deadline_ms must be > 0"):
        eng.add_request(np.arange(1, 9), 2, deadline_ms=0)
    with pytest.raises(ValueError, match="deadline_ms must be > 0"):
        eng.add_request(np.arange(1, 9), 2, deadline_ms=-5.0)
    with pytest.raises(ValueError, match="shorter than a single"):
        eng.add_request(np.arange(1, 9), 2, deadline_ms=0.5)
    with pytest.raises(ValueError, match="max_retries"):
        eng.add_request(np.arange(1, 9), 2, max_retries=-1)
    with pytest.raises(ValueError, match="max_retries"):
        eng.add_request(np.arange(1, 9), 2, max_retries=True)
    _drive(eng)


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------

def test_degradation_controller_transitions():
    ctl = DegradationController(trip_after=3, recover_after=2,
                                fault_window=8, fault_trip=2)
    # saturation climbs one rung per streak, capped at sat_max_level
    for _ in range(3):
        ctl.observe(saturated=True)
    assert ctl.level == 1 and ctl.shed_batch and not ctl.throttle
    for _ in range(3):
        ctl.observe(saturated=True)
    assert ctl.level == 2 and ctl.throttle and not ctl.disable_spec
    for _ in range(12):
        ctl.observe(saturated=True)
    assert ctl.level == 2  # saturation alone never reaches min_service
    # repeated faults jump straight to min_service
    ctl.observe(saturated=False, faults=1)
    ctl.observe(saturated=False, faults=1)
    assert ctl.level == 3 and ctl.disable_spec and ctl.disable_prefix
    # recovery: good ticks walk back down only after the fault window
    # slides past the trip count
    for _ in range(20):
        ctl.observe(saturated=False)
    assert ctl.level == 0 and not ctl.degraded
    ts = list(ctl.transitions)
    assert [t["to"] for t in ts] == [1, 2, 3, 2, 1, 0]
    with pytest.raises(ValueError, match="trip_after"):
        DegradationController(trip_after=0)
    with pytest.raises(ValueError, match="sat_max_level"):
        DegradationController(sat_max_level=4, max_level=3)


def test_degradation_engine_actions_preserve_outputs():
    """min_service disables prefix adoption and spec drafting; shed
    defers batch-class admissions — throughput levers only, outputs
    identical."""
    model, cfg = _model()
    rng = np.random.default_rng(1)
    shared = rng.integers(0, cfg.vocab_size, (16,))
    prompts = [np.concatenate(
        [shared, rng.integers(0, cfg.vocab_size, (4,))])
        for _ in range(3)]
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        prompts, max_new_tokens=5)

    eng = ContinuousBatchingEngine(model, _ecfg(True))
    eng.run([prompts[0]], max_new_tokens=2)  # publishes prefix blocks
    hits0 = eng.prefix_stats["hits"]
    eng._degctl.level = 3  # force min_service...
    eng._degctl.recover_after = 10 ** 9  # ...and hold it there
    rids = [eng.add_request(p, 5) for p in prompts]
    _drive(eng)
    assert eng.prefix_stats["hits"] == hits0  # adoption disabled
    for r, rid in zip(ref, rids):
        assert eng._finished[rid].output == r.output
    assert eng.backpressure()["degraded"]
    assert eng.backpressure()["degradation_level"] == 3
    assert eng.metrics_snapshot()["resilience"]["degradation"]["name"] \
        == "min_service"


def test_degradation_sheds_batch_class():
    """At shed_batch, a queued batch-class request is DEFERRED while
    interactive traffic admits past it; recovery re-admits it."""
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False, max_slots=1))
    eng._degctl.level = 1
    rb = eng.add_request(np.arange(1, 9), 3, slo="batch")
    ri = eng.add_request(np.arange(2, 9), 8, slo="interactive")
    eng.step_chunk(2)  # admission wave: batch deferred, interactive in
    assert eng._slot_req and next(
        iter(eng._slot_req.values())).rid == ri
    assert any(r.rid == rb for r in eng._queue)  # deferred, not lost
    while eng.active.any():
        eng.step_chunk(2)
    assert rb not in eng._finished  # still shed, still queued
    eng._degctl.level = 0  # recovered: batch class admits again
    _drive(eng)
    assert eng._finished[rb].finish_reason == "max_new_tokens"
    assert len(eng._finished[rb].output) == 3


def test_pool_exhaust_injection_drives_saturation():
    """The pool site simulates exhaustion at admission: backpressure
    reports saturated/pool_blocked, no request is harmed, and the
    next clean tick self-heals."""
    model, cfg = _model()
    inj = FaultInjector("pool:1.0", seed=0)
    eng = ContinuousBatchingEngine(model, _ecfg(True),
                                   fault_injector=inj)
    rid = eng.add_request(np.arange(1, 9), 3)
    eng.step_chunk(2)
    assert eng._pool_blocked and eng.backpressure()["saturated"]
    assert not eng.active.any()  # admission blocked, request queued
    inj.rates["pool"] = 0.0  # storm ends
    _drive(eng)
    assert len(eng._finished[rid].output) == 3
    assert eng.resilience_stats["faults"]["pool"] >= 1


# ---------------------------------------------------------------------------
# drain
# ---------------------------------------------------------------------------

def test_drain_completes_inflight_stops_admission():
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False, max_slots=2))
    rids = [eng.add_request(p, 5) for p in _prompts(cfg, n=4, seed=6)]
    eng.step_chunk(2)  # two admitted, two queued
    summary = eng.drain()
    unfinished = summary.pop("unfinished")
    assert summary == {"drained": True, "expired": 0, "active": 0,
                       "queued": 2}
    # the two still-queued fresh requests ARE the handoff payload
    assert sorted(led["rid"] for led in unfinished) \
        == [r for r in rids if r not in eng._finished]
    done = [r for r in rids if r in eng._finished]
    assert len(done) == 2
    for rid in done:
        assert len(eng._finished[rid].output) == 5
    bp = eng.backpressure()
    assert bp["draining"]
    # healthz fails readiness while draining
    srv = start_metrics_server(eng, port=0)
    try:
        port = srv.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "draining"
    finally:
        srv.shutdown()
    eng.resume()
    assert not eng.backpressure()["draining"]
    _drive(eng)
    assert all(r in eng._finished for r in rids)
    with pytest.raises(ValueError, match="deadline_ms"):
        eng.drain(deadline_ms=0)


def test_drain_completes_quarantined_replays():
    """A quarantine just before (or during) a drain re-queues its
    victims with history — drain() owes them completion: the closed
    admission gate must still admit in-flight-once replays, and the
    drained outputs must match a fault-free run."""
    from paddle_tpu.inference.resilience import InjectedFault

    model, cfg = _model()
    prompts = _prompts(cfg, n=2, seed=9)
    ref = ContinuousBatchingEngine(model, _ecfg(True)).run(
        prompts, max_new_tokens=6)
    eng = ContinuousBatchingEngine(model, _ecfg(True))
    rids = [eng.add_request(p, 6) for p in prompts]
    eng.step_chunk(2)  # admit + a couple of tokens
    assert eng.active.any()
    # quarantine mid-flight: victims go back to the queue with history
    eng._recover_step(InjectedFault("step", "decode_chunk"),
                      eng.active.copy(), "decode_chunk")
    assert not eng.active.any() and eng._drain_pending()
    summary = eng.drain(max_chunk=2)
    assert summary["active"] == 0 and summary["queued"] == 0
    for r, rid in zip(ref, rids):
        got = eng._finished[rid]
        assert got.finish_reason == "max_new_tokens"
        assert got.output == r.output
    _assert_no_leaks(eng)
    eng.resume()


def test_drain_deadline_expires_stragglers():
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(True, max_slots=2))
    free0 = eng.pool.free_pages
    rid = eng.add_request(np.arange(1, 9), 100)  # outlives the drain deadline
    eng.step_chunk(2)
    summary = eng.drain(deadline_ms=25.0, max_chunk=2)
    assert summary["expired"] == 1 and summary["active"] == 0
    req = eng._finished[rid]
    assert req.finish_reason == "timeout" and len(req.output) > 0
    # the straggler timed out HERE, but its ledger is in the handoff
    # payload (captured before teardown) so a caller can re-admit it
    assert [led["rid"] for led in summary["unfinished"]] == [rid]
    assert summary["unfinished"][0]["output"] == req.output
    eng._evict_pages(10 ** 9)
    assert eng.pool.free_pages == free0 and not eng.pool.ref


def test_drain_ledger_payload_shape():
    """Pin the handoff payload: drain()'s ``unfinished`` entries carry
    the full host token ledger — prompt, generated tokens, sampling
    params, SLO targets, deadline and timing state — exactly the
    fields ``admit_ledger`` consumes."""
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(True, max_slots=1))
    rid = eng.add_request(
        np.arange(1, 11), 60, eos_token_id=7777, temperature=0.7,
        top_k=5, top_p=0.9, greedy=True, slo="interactive",
        max_retries=4)
    eng.step_chunk(2)
    led = eng.drain(deadline_ms=10.0, max_chunk=2)["unfinished"][0]
    eng.resume()
    assert set(led) == {
        "rid", "prompt", "output", "max_new_tokens", "eos_token_id",
        "temperature", "top_k", "top_p", "greedy", "tenant", "slo",
        "ttft_target_ms", "tpot_target_ms", "deadline_t",
        "max_retries", "retries", "ttft_ms", "submit_t", "admit_t",
        "device_ms", "device_ms_profiled",
    }
    assert led["rid"] == rid
    assert led["prompt"] == list(range(1, 11))
    assert led["output"] and all(isinstance(t, int)
                                 for t in led["output"])
    assert led["max_new_tokens"] == 60 and led["eos_token_id"] == 7777
    assert led["temperature"] == 0.7 and led["top_k"] == 5
    assert led["top_p"] == 0.9 and led["greedy"] is True
    assert led["slo"] == "interactive"
    # class defaults were resolved at admission and travel explicitly
    assert led["ttft_target_ms"] == 250.0
    assert led["tpot_target_ms"] == 100.0
    assert led["deadline_t"] and led["deadline_t"] > led["submit_t"]
    assert led["max_retries"] == 4 and led["retries"] == 0
    assert led["ttft_ms"] is not None and led["admit_t"] > 0
    import json as _json

    _json.dumps(led)  # the payload is wire-serializable


def test_resume_after_drain_readmits_queued():
    """resume() after a drain: the requests the closed admission gate
    kept queued admit on the next tick and finish with their full
    token count — and their TTFT keeps counting from the ORIGINAL
    submission (the drain window is honest queue wait)."""
    model, cfg = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False, max_slots=1))
    r0 = eng.add_request(np.arange(1, 9), 4)
    r1 = eng.add_request(np.arange(1, 9), 5)  # waits behind r0
    eng.step_chunk(2)
    summary = eng.drain(max_chunk=2)
    assert [led["rid"] for led in summary["unfinished"]] == [r1]
    assert r1 not in eng._finished  # still queued, not expired
    queued = next(r for r in eng._queue if r.rid == r1)
    submit_t = queued._submit_t
    eng.resume()
    _drive(eng)
    req = eng._finished[r1]
    assert req.finish_reason == "max_new_tokens"
    assert len(req.output) == 5
    assert req._submit_t == submit_t
    assert req.ttft_ms >= (req._admit_t - submit_t) * 1e3 * 0.99


# ---------------------------------------------------------------------------
# metrics server handle
# ---------------------------------------------------------------------------

def test_metrics_server_clean_shutdown():
    model, _ = _model()
    eng = ContinuousBatchingEngine(model, _ecfg(False))
    eng.run([np.arange(1, 9)], max_new_tokens=2)
    srv = start_metrics_server(eng, port=0)
    host, port = srv.server_address[:2]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
        assert r.status == 200
    srv.shutdown()
    # the serving thread is joined and the listening socket closed:
    # a fresh connection must be refused, not accepted-and-hung
    assert not srv._thread.is_alive()
    with pytest.raises(OSError):
        s = socket.create_connection(("127.0.0.1", port), timeout=2)
        # macOS/Linux may accept into the TIME_WAIT backlog; prove the
        # listener is gone by expecting an empty response instead
        try:
            s.sendall(b"GET /healthz HTTP/1.0\r\n\r\n")
            if s.recv(64) == b"":
                raise ConnectionRefusedError("listener closed")
        finally:
            s.close()
    srv.shutdown()  # idempotent
    # context-manager form
    with start_metrics_server(eng, port=0) as srv2:
        p2 = srv2.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{p2}/metrics", timeout=10) as r:
            assert r.status == 200
    assert not srv2._thread.is_alive()


# ---------------------------------------------------------------------------
# snapshots + telemetry
# ---------------------------------------------------------------------------

def test_resilience_snapshot_always_present():
    """Host-side counters survive telemetry=off (the conftest default
    for this suite), and the unified snapshot carries them."""
    model, cfg = _model()
    inj = FaultInjector("step:0.5", seed=1)
    eng = ContinuousBatchingEngine(model, _ecfg(False),
                                   fault_injector=inj)
    assert eng._tel is None  # telemetry off in the test session
    eng.run(_prompts(cfg, n=2, seed=7), max_new_tokens=4)
    snap = eng.metrics_snapshot()
    rs = snap["resilience"]
    assert rs["recoveries"] >= 1
    assert rs["injector"]["enabled"]
    assert rs["degradation"]["enabled"]
    assert rs["degradation"]["level"] in (0, 1, 2, 3)
    assert rs["recovery_mode"] == "auto" and rs["draining"] is False


def test_resilience_telemetry_counters(res_flags, tmp_path):
    """With telemetry ON: recovery/retry/timeout counters land in the
    registry, the NaN storm writes a flight-recorder dump with the
    tracer tail, and the degradation gauge exists."""
    res_flags({"telemetry": True,
               "telemetry_dump_dir": str(tmp_path)})
    from paddle_tpu import observability as obs

    model, cfg = _model()
    inj = FaultInjector("nan:0.8", seed=2)
    eng = ContinuousBatchingEngine(model, _ecfg(False),
                                   fault_injector=inj)
    assert eng._tel is not None
    eng.run(_prompts(cfg, n=3, seed=8), max_new_tokens=4)
    assert eng.resilience_stats["nan_steps"] >= 1
    lab = {"engine": eng._tel.engine_id}
    reg = obs.get_registry()
    text = reg.prometheus_text()
    assert "pt_serve_recoveries_total" in text
    assert "pt_serve_retries_total" in text
    assert eng._tel._recoveries.value(**lab) >= 1
    assert eng._tel._retries.value(**lab) >= 1
    # NaN dump artifact exists and attaches the trace tail
    dumps = list(tmp_path.glob("flight_*.json"))
    assert dumps, "NaN storm wrote no flight-recorder dump"
    doc = json.loads(dumps[0].read_text())
    assert "NaN-logits" in doc["reason"]
    assert doc.get("trace_tail"), "dump missing tracer tail"
    # timeout counter
    r = eng.add_request(np.arange(1, 9), 50, deadline_ms=20.0)
    eng._injector = None
    eng.step_chunk(2)
    time.sleep(0.03)
    eng.step_chunk(2)
    assert eng._finished[r].finish_reason == "timeout"
    assert eng._tel._timeouts.value(**lab) == 1
