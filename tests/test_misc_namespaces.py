"""linalg / fft / distribution / jit / quantization surfaces."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import distribution, fft, jit, linalg, nn, quantization as q
from paddle_tpu.jax_compat import enable_x64 as _enable_x64


def test_linalg_basics():
    a = jnp.asarray(np.random.default_rng(0).standard_normal((4, 4)),
                    jnp.float32)
    spd = a @ a.T + 4 * jnp.eye(4)
    np.testing.assert_allclose(
        np.asarray(linalg.inv(spd) @ spd), np.eye(4), atol=1e-4
    )
    L = linalg.cholesky(spd)
    np.testing.assert_allclose(np.asarray(L @ L.T), np.asarray(spd),
                               rtol=1e-4, atol=1e-4)
    u, s, vt = linalg.svd(a)
    np.testing.assert_allclose(
        np.asarray((u * s) @ vt), np.asarray(a), rtol=1e-4, atol=1e-4
    )
    x = linalg.solve(spd, jnp.ones((4,)))
    np.testing.assert_allclose(np.asarray(spd @ x), 1.0, rtol=1e-4)


def test_fft_roundtrip():
    x = jnp.asarray(np.random.default_rng(1).standard_normal(32), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(fft.ifft(fft.fft(x)).real), np.asarray(x), atol=1e-5
    )


def test_distributions():
    pt.seed(3)
    n = distribution.Normal(0.0, 1.0)
    s = n.sample((1000,))
    assert abs(float(s.mean())) < 0.15
    np.testing.assert_allclose(
        float(n.log_prob(jnp.asarray(0.0))), -0.9189385, rtol=1e-5
    )
    kl = distribution.kl_divergence(
        distribution.Normal(0.0, 1.0), distribution.Normal(0.0, 1.0)
    )
    np.testing.assert_allclose(float(kl), 0.0, atol=1e-6)
    c = distribution.Categorical(logits=jnp.asarray([0.0, 0.0]))
    assert float(c.entropy()) == pytest.approx(np.log(2), rel=1e-5)
    b = distribution.Bernoulli(0.5)
    assert float(b.entropy()) == pytest.approx(np.log(2), rel=1e-4)


def test_jit_to_static_and_save_load(tmp_path):
    pt.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    traced = jit.to_static(net)
    x = jnp.ones((3, 4))
    ref = net(x)
    np.testing.assert_allclose(np.asarray(traced(x)), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    path = str(tmp_path / "model")
    jit.save(traced, path, input_spec=[x])
    loaded = jit.load(path)
    np.testing.assert_allclose(
        np.asarray(loaded(x)), np.asarray(ref), rtol=1e-5, atol=1e-6
    )


def test_weight_only_int8():
    pt.seed(1)
    lin = nn.Linear(16, 8)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((4, 16)),
                    jnp.float32)
    ref = np.asarray(lin(x))
    wql = q.WeightOnlyLinear(lin)
    out = np.asarray(wql(x))
    # int8 per-channel quantization error stays small
    denom = np.maximum(np.abs(ref), 1.0)
    assert np.max(np.abs(out - ref) / denom) < 0.05
    assert wql._buffers["qweight"].dtype == jnp.int8


def test_quantize_model_sweep():
    net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
    q.quantize_model_weight_only(net)
    from paddle_tpu.quantization import WeightOnlyLinear

    kinds = [type(l).__name__ for l in net._sub_layers.values()]
    assert kinds.count("WeightOnlyLinear") == 2
    y = net(jnp.ones((1, 8)))
    assert y.shape == (1, 4)


def test_fake_quant_ste_grad():
    import jax

    fq = q.FakeQuant(bits=8)
    fq.eval()
    x = jnp.linspace(-1, 1, 8)
    g = jax.grad(lambda x: jnp.sum(fq(x) ** 2))(x)
    # straight-through: gradient ≈ 2x
    np.testing.assert_allclose(np.asarray(g), np.asarray(2 * x), atol=0.1)


def test_paddle_flatten_semantics():
    x = jnp.zeros((2, 3, 4, 5))
    assert pt.flatten(x).shape == (120,)
    assert pt.flatten(x, 1).shape == (2, 60)          # the canonical call
    assert pt.flatten(x, 1, 2).shape == (2, 12, 5)
    assert pt.flatten(x, -2, -1).shape == (2, 3, 20)
    with pytest.raises(ValueError):
        pt.flatten(x, 3, 1)


def test_paddle_topk_semantics():
    x = jnp.asarray([[3.0, 1.0, 4.0, 1.5], [2.0, 7.0, 1.0, 8.0]])
    v, i = pt.topk(x, 2)
    np.testing.assert_allclose(np.asarray(v), [[4.0, 3.0], [8.0, 7.0]])
    np.testing.assert_array_equal(np.asarray(i), [[2, 0], [3, 1]])
    v, i = pt.topk(x, 2, largest=False)
    np.testing.assert_allclose(np.asarray(v), [[1.0, 1.5], [1.0, 2.0]])
    v, i = pt.topk(x, 1, axis=0)
    np.testing.assert_allclose(np.asarray(v), [[3.0, 7.0, 4.0, 8.0]])


def test_paddle_norm_semantics():
    x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    # axis=None on ndim>2 flattens (jnp.linalg.norm would raise)
    np.testing.assert_allclose(
        float(pt.norm(x)), np.linalg.norm(np.asarray(x).ravel()), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(pt.norm(x, p=1, axis=-1)),
        np.abs(np.asarray(x)).sum(-1), rtol=1e-6)
    np.testing.assert_allclose(
        float(pt.norm(x, p=float("inf"))), 23.0)
    np.testing.assert_allclose(
        np.asarray(linalg.norm(x, axis=(1, 2))),
        np.linalg.norm(np.asarray(x), axis=(1, 2)), rtol=1e-6)


def test_gather_scatter_family():
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(4, 3))
    np.testing.assert_allclose(
        np.asarray(pt.gather(x, jnp.asarray([2, 0]))),
        np.asarray(x)[[2, 0]])
    idx = jnp.asarray([[0, 1], [3, 2]])
    np.testing.assert_allclose(
        np.asarray(pt.gather_nd(x, idx)), [1.0, 11.0])
    upd = jnp.asarray([[9.0, 9.0, 9.0], [7.0, 7.0, 7.0]])
    out = pt.scatter(x, jnp.asarray([1, 3]), upd)
    np.testing.assert_allclose(np.asarray(out)[1], 9.0)
    np.testing.assert_allclose(np.asarray(out)[3], 7.0)
    np.testing.assert_allclose(np.asarray(out)[0], np.asarray(x)[0])
    out = pt.scatter_nd_add(jnp.zeros((4, 3)), idx,
                            jnp.asarray([1.0, 2.0]))
    assert float(out[0, 1]) == 1.0 and float(out[3, 2]) == 2.0


def test_huber_vs_smooth_l1_delta():
    a = jnp.asarray(np.linspace(-4, 4, 33, dtype=np.float32))
    b = jnp.zeros((33,))
    d = np.abs(np.asarray(a))
    delta = 2.0
    sl = nn.SmoothL1Loss(delta=delta)(a, b)
    ref_sl = np.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    np.testing.assert_allclose(float(sl), ref_sl.mean(), rtol=1e-5)
    hb = nn.HuberLoss(delta=delta)(a, b)
    ref_hb = np.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    np.testing.assert_allclose(float(hb), ref_hb.mean(), rtol=1e-5)
    # they must now genuinely differ for delta != 1
    assert abs(float(sl) - float(hb)) > 1e-3


def test_distribution_support_guards():
    for dist_, bad, good in [
        (distribution.Gamma(2.0, 1.0), -1.0, 1.0),
        (distribution.Beta(2.0, 2.0), 1.5, 0.5),
        (distribution.LogNormal(0.0, 1.0), -0.5, 1.0),
        (distribution.Poisson(3.0), -1.0, 2.0),
        (distribution.Exponential(1.0), -2.0, 1.0),
        (distribution.Uniform(0.0, 1.0), 2.0, 0.5),
    ]:
        assert float(dist_.log_prob(jnp.asarray(bad))) == float("-inf")
        assert np.isfinite(float(dist_.log_prob(jnp.asarray(good))))


def test_round3_tensor_surface():
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert pt.trace(x).item() == 0 + 5 + 10
    np.testing.assert_allclose(np.asarray(pt.diagonal(x)), [0, 5, 10])
    np.testing.assert_allclose(
        float(pt.logsumexp(x)), float(jnp.log(jnp.sum(jnp.exp(x)))),
        rtol=1e-6)
    assert pt.unbind(x, 0)[1].shape == (4,)
    assert [c.shape for c in pt.chunk(x, 2, axis=1)] == [(3, 2), (3, 2)]
    np.testing.assert_allclose(
        np.asarray(pt.masked_fill(x, x > 5, -1.0))[2], [-1, -1, -1, -1])
    np.testing.assert_allclose(float(pt.median(x)), 5.5)
    v, i = pt.mode(jnp.asarray([[1, 2, 2, 3], [7, 7, 1, 1]]))
    np.testing.assert_array_equal(np.asarray(v), [2, 1])
    assert np.asarray(jnp.asarray([[1, 2, 2, 3]]))[0, int(i[0])] == 2
    u, counts = pt.unique(jnp.asarray([3, 1, 3, 2, 1]),
                          return_counts=True)
    np.testing.assert_array_equal(np.asarray(u), [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(counts), [2, 1, 2])
    np.testing.assert_array_equal(
        np.asarray(pt.searchsorted(jnp.asarray([1.0, 3.0, 5.0]),
                                   jnp.asarray([2.0, 5.0]))), [1, 2])
    np.testing.assert_array_equal(
        np.asarray(pt.searchsorted(jnp.asarray([1.0, 3.0, 5.0]),
                                   jnp.asarray([5.0]), right=True)), [3])
    np.testing.assert_allclose(
        np.asarray(pt.lerp(jnp.zeros(3), jnp.ones(3), 0.25)), 0.25)
    # logcumsumexp matches the log of cumsum of exp
    a = jnp.asarray([0.1, 2.0, -1.0])
    np.testing.assert_allclose(
        np.asarray(pt.logcumsumexp(a)),
        np.log(np.cumsum(np.exp(np.asarray(a)))), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(pt.addmm(jnp.ones((2, 2)), jnp.eye(2), jnp.eye(2),
                            beta=2.0, alpha=3.0)),
        2.0 * np.ones((2, 2)) + 3.0 * np.eye(2))
    assert pt.histogram(jnp.asarray([0.0, 0.5, 1.0]), bins=2).sum() == 3
    nz = pt.nonzero(jnp.asarray([[1, 0], [0, 2]]))
    np.testing.assert_array_equal(np.asarray(nz), [[0, 0], [1, 1]])
    rows, cols = pt.nonzero(jnp.asarray([[1, 0], [0, 2]]), as_tuple=True)
    np.testing.assert_array_equal(np.asarray(rows), [0, 1])


def test_group_sharded_and_recompute_api():
    import paddle_tpu.distributed as dist
    from paddle_tpu import nn

    model = nn.Linear(4, 4)
    m, o, strategy, scaler = dist.group_sharded_parallel(model, object(),
                                                         level="os_g")
    assert strategy.sharding and strategy.sharding_configs.stage == 2
    assert scaler is None  # fixed arity: scaler slot present regardless
    with pytest.raises(ValueError):
        dist.group_sharded_parallel(model, object(), level="bogus")

    calls = []

    def f(a):
        calls.append(1)
        return jnp.sin(a) * a

    x = jnp.asarray(np.random.default_rng(0).standard_normal(8),
                    jnp.float32)
    y, vjp = jax.vjp(lambda a: dist.recompute(f, a), x)
    ref, ref_vjp = jax.vjp(f, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(vjp(jnp.ones(8))[0]),
                               np.asarray(ref_vjp(jnp.ones(8))[0]),
                               rtol=1e-6)


@pytest.fixture(autouse=True)
def _linalg_x64(request):
    """fp64 comparisons against numpy/torch need x64 jax (CPU tests)."""
    if "TestLinalgExtended" in request.node.nodeid:
        import jax

        with _enable_x64(True):
            yield
    else:
        yield


class TestLinalgExtended:
    """Round-3 widening: the remaining paddle.linalg surface, checked
    against torch.linalg / numpy."""

    def setup_method(self, _):
        import numpy as np

        rng = np.random.default_rng(42)
        a = rng.normal(size=(5, 5)).astype(np.float64)
        self.spd = (a @ a.T + 5 * np.eye(5)).astype(np.float64)
        self.a = a
        self.rect = rng.normal(size=(8, 5)).astype(np.float64)

    def test_cholesky_solve(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu import linalg as L

        b = np.ones((5, 2))
        chol = np.linalg.cholesky(self.spd)
        x = np.asarray(L.cholesky_solve(jnp.asarray(b), jnp.asarray(chol)))
        np.testing.assert_allclose(self.spd @ x, b, atol=1e-8)

    def test_eigvals_eigvalsh(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu import linalg as L

        ours = np.sort(np.asarray(L.eigvalsh(jnp.asarray(self.spd))))
        ref = np.sort(np.linalg.eigvalsh(self.spd))
        np.testing.assert_allclose(ours, ref, rtol=1e-6)
        ev = np.asarray(L.eigvals(jnp.asarray(self.spd)))
        np.testing.assert_allclose(
            np.sort(ev.real), ref, rtol=1e-6, atol=1e-8
        )

    def test_lu_roundtrip(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu import linalg as L

        lu_mat, piv = L.lu(jnp.asarray(self.a))
        P, Lm, U = L.lu_unpack(lu_mat, piv)
        np.testing.assert_allclose(
            np.asarray(P @ Lm @ U), self.a, atol=1e-8
        )

    def test_cov_corrcoef(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu import linalg as L

        np.testing.assert_allclose(
            np.asarray(L.cov(jnp.asarray(self.rect.T))),
            np.cov(self.rect.T), rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(L.corrcoef(jnp.asarray(self.rect.T))),
            np.corrcoef(self.rect.T), rtol=1e-6,
        )

    def test_multi_dot_matrix_exp_svdvals(self):
        import numpy as np
        import jax.numpy as jnp
        import torch
        from paddle_tpu import linalg as L

        mats = [self.rect, self.spd, self.a]
        np.testing.assert_allclose(
            np.asarray(L.multi_dot([jnp.asarray(m) for m in mats])),
            np.linalg.multi_dot(mats), rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(L.matrix_exp(jnp.asarray(self.a * 0.1))),
            torch.linalg.matrix_exp(torch.tensor(self.a * 0.1)).numpy(),
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(L.svdvals(jnp.asarray(self.rect))),
            np.linalg.svd(self.rect, compute_uv=False), rtol=1e-6,
        )

    def test_vector_matrix_norms(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu import linalg as L

        np.testing.assert_allclose(
            float(L.vector_norm(jnp.asarray(self.rect), p=3.0)),
            np.sum(np.abs(self.rect) ** 3) ** (1 / 3), rtol=1e-6,
        )
        np.testing.assert_allclose(
            float(L.matrix_norm(jnp.asarray(self.rect), p="fro")),
            np.linalg.norm(self.rect, "fro"), rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(L.matrix_transpose(jnp.asarray(self.rect))),
            self.rect.T,
        )

    def test_householder_product(self):
        import numpy as np
        import jax.numpy as jnp
        import torch
        from paddle_tpu import linalg as L

        At = torch.tensor(self.rect)
        h, tau = torch.geqrf(At)
        ours = np.asarray(
            L.householder_product(jnp.asarray(h.numpy()),
                                  jnp.asarray(tau.numpy()))
        )
        ref = torch.linalg.householder_product(h, tau).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-8)

    def test_lowrank(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu import linalg as L

        # rank-3 matrix: svd_lowrank with q=3 reconstructs it
        u = self.rect[:, :3]
        m = (u @ u.T).astype(np.float64)  # 8x8 rank<=3
        U, s, V = L.svd_lowrank(jnp.asarray(m), q=3, niter=4)
        rec = np.asarray(U) * np.asarray(s) @ np.asarray(V).T
        np.testing.assert_allclose(rec, m, atol=1e-6)
        U2, s2, V2 = L.pca_lowrank(jnp.asarray(m), q=2)
        assert U2.shape == (8, 2) and s2.shape == (2,)


class TestInitializers:
    def _mk(self, init, shape, dtype=jnp.float32):
        import jax

        return np.asarray(init(jax.random.PRNGKey(0), shape, dtype))

    def test_orthogonal(self):
        from paddle_tpu.nn import initializer as I

        for shape in [(8, 8), (4, 12), (12, 4), (6, 2, 3)]:
            w = self._mk(I.Orthogonal(), shape).reshape(shape[0], -1)
            rows, cols = w.shape
            if rows <= cols:
                np.testing.assert_allclose(w @ w.T, np.eye(rows),
                                           atol=1e-5)
            else:
                np.testing.assert_allclose(w.T @ w, np.eye(cols),
                                           atol=1e-5)

    def test_dirac_identity_conv(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.nn import initializer as I

        w = jnp.asarray(self._mk(I.Dirac(), (3, 3, 3, 3)))
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(1, 3, 6, 6)).astype(np.float32))
        y = F.conv2d(x, w, padding=1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x),
                                   atol=1e-5)

    def test_assign_and_gain(self):
        from paddle_tpu.nn import initializer as I

        v = np.arange(6, dtype=np.float32).reshape(2, 3)
        w = self._mk(I.Assign(v), (2, 3))
        np.testing.assert_array_equal(w, v)
        with pytest.raises(ValueError):
            self._mk(I.Assign(v), (3, 2))
        assert I.calculate_gain("relu") == pytest.approx(np.sqrt(2))
        assert I.calculate_gain("tanh") == pytest.approx(5 / 3)

    def test_bilinear_upsample_kernel(self):
        from paddle_tpu.nn import initializer as I

        w = self._mk(I.Bilinear(), (2, 2, 4, 4))
        # reference: EVERY (out, in) filter carries the separable ramp
        assert w[0, 0].max() > 0
        np.testing.assert_allclose(w[0, 1], w[0, 0], atol=1e-6)
        np.testing.assert_allclose(w[1, 0], w[0, 0], atol=1e-6)
        np.testing.assert_allclose(w[0, 0], w[0, 0].T, atol=1e-6)


class TestTensorOpsRound3:
    def test_tensordot(self):
        import torch

        a = np.random.default_rng(0).normal(size=(3, 4, 5))
        b = np.random.default_rng(1).normal(size=(4, 5, 6))
        ours = np.asarray(pt.tensor.tensordot(jnp.asarray(a),
                                              jnp.asarray(b), axes=2))
        ref = torch.tensordot(torch.tensor(a), torch.tensor(b),
                              dims=2).numpy()
        # ours runs f32 (jnp default) vs torch's f64; the contraction
        # order XLA picks varies by version, so allow f32-edge slack
        np.testing.assert_allclose(ours, ref, rtol=3e-5, atol=1e-6)

    def test_renorm(self):
        import torch

        x = np.random.default_rng(2).normal(size=(4, 5)).astype(
            np.float32) * 3
        ours = np.asarray(pt.tensor.renorm(jnp.asarray(x), 2.0, 0, 1.0))
        ref = torch.renorm(torch.tensor(x), 2, 0, 1.0).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
        norms = np.linalg.norm(ours, axis=1)
        assert (norms <= 1.0 + 1e-5).all()

    def test_scatter_nd(self):
        idx = jnp.asarray([[1], [2], [1]])
        upd = jnp.asarray([9.0, 10.0, 11.0])
        out = np.asarray(pt.tensor.scatter_nd(idx, upd, [4]))
        np.testing.assert_allclose(out, [0.0, 20.0, 10.0, 0.0])
        x = jnp.ones((4,))
        out2 = np.asarray(pt.tensor.scatter_nd_add(x, idx, upd))
        np.testing.assert_allclose(out2, [1.0, 21.0, 11.0, 1.0])


class TestRandomCreation:
    def test_shapes_and_ranges(self):
        pt.seed(7)
        r = pt.rand((3, 4))
        assert r.shape == (3, 4) and (np.asarray(r) >= 0).all() \
            and (np.asarray(r) < 1).all()
        n = pt.randn((5,))
        assert n.shape == (5,)
        i = pt.randint(2, 9, (100,))
        ai = np.asarray(i)
        assert ai.min() >= 2 and ai.max() < 9
        p = np.asarray(pt.randperm(10))
        assert sorted(p.tolist()) == list(range(10))
        u = np.asarray(pt.uniform((50,), min=3.0, max=4.0))
        assert u.min() >= 3.0 and u.max() < 4.0

    def test_seed_reproducible(self):
        pt.seed(123)
        a = np.asarray(pt.randn((4,)))
        pt.seed(123)
        b = np.asarray(pt.randn((4,)))
        np.testing.assert_array_equal(a, b)
        c = np.asarray(pt.randn((4,)))
        assert not np.array_equal(b, c)   # stream advances

    def test_multinomial(self):
        pt.seed(0)
        probs = jnp.asarray([0.0, 0.7, 0.3, 0.0])
        s = np.asarray(pt.multinomial(probs, 200, replacement=True))
        assert set(np.unique(s)) <= {1, 2}
        assert (s == 1).mean() > 0.5
        nr = np.asarray(pt.multinomial(jnp.ones(6), 6))
        assert sorted(nr.tolist()) == list(range(6))

    def test_multinomial_overdraw_raises(self):
        with pytest.raises(ValueError, match="nonzero"):
            pt.multinomial(jnp.asarray([0.0, 0.5, 0.5, 0.0]), 3)

    def test_dtype_strings(self):
        assert pt.rand((2,), "float32").dtype == jnp.float32
        assert pt.randint(0, 5, (3,), "int32").dtype == jnp.int32


class TestInputSpec:
    def test_jit_save_load_with_input_spec(self, tmp_path):
        from paddle_tpu import jit, static

        pt.seed(0)
        lin = pt.nn.Linear(4, 2)
        spec = static.InputSpec([3, 4], "float32", name="x")
        path = str(tmp_path / "model")
        jit.save(lin, path, input_spec=[spec])
        loaded = jit.load(path)
        x = jnp.ones((3, 4))
        np.testing.assert_allclose(
            np.asarray(loaded(x)), np.asarray(lin(x)), rtol=1e-5)

    def test_dynamic_dim_resolution(self):
        from paddle_tpu import static

        spec = static.InputSpec([None, 8], "int64")
        s = spec.to_struct(batch_size=4)
        assert s.shape == (4, 8)
        with pytest.raises(ValueError, match="dynamic dim"):
            static.InputSpec([4, None], "int64").to_struct()

    def test_dynamic_batch_export(self, tmp_path):
        """None dims export batch-POLYMORPHIC StableHLO: one saved
        module serves every batch size."""
        from paddle_tpu import jit, static

        pt.seed(0)
        lin = pt.nn.Linear(4, 2)
        path = str(tmp_path / "dyn")
        jit.save(lin, path,
                 input_spec=[static.InputSpec([None, 4], "float32")])
        loaded = jit.load(path)
        for b in (1, 3, 6):
            x = jnp.ones((b, 4))
            np.testing.assert_allclose(
                np.asarray(loaded(x)), np.asarray(lin(x)), rtol=1e-5)

    def test_to_static_validates_spec(self):
        from paddle_tpu import jit, static

        pt.seed(0)
        lin = pt.nn.Linear(4, 2)
        ts = jit.to_static(lin,
                           input_spec=[static.InputSpec([None, 4])])
        ts(jnp.ones((3, 4)))       # matches
        with pytest.raises(ValueError, match="does not match"):
            ts(jnp.ones((3, 5)))

    def test_from_tensor(self):
        from paddle_tpu import static

        t = jnp.zeros((2, 3), jnp.float32)
        spec = static.InputSpec.from_tensor(t, name="t")
        assert spec.shape == (2, 3) and spec.name == "t"

    def test_multi_dynamic_input_export(self, tmp_path):
        """two dynamic-batch inputs share one symbolic scope."""
        from paddle_tpu import jit, static

        pt.seed(0)

        class TwoIn(pt.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = pt.nn.Linear(4, 2)

            def forward(self, a, b):
                return self.lin(a) + self.lin(b)

        m = TwoIn()
        path = str(tmp_path / "two")
        jit.save(m, path, input_spec=[
            static.InputSpec([None, 4], "float32"),
            static.InputSpec([None, 4], "float32"),
        ])
        loaded = jit.load(path)
        for bsz in (2, 5):
            a = jnp.ones((bsz, 4))
            np.testing.assert_allclose(
                np.asarray(loaded(a, a * 2)),
                np.asarray(m(a, a * 2)), rtol=1e-5)
