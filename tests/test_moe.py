"""MoE layer tests (parity model: incubate MoE tests — routing
conservation, capacity, aux loss, expert-parallel sharding)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import distributed as dist
from paddle_tpu.core.functional import extract_params, functional_call
from paddle_tpu.distributed.moe import MoELayer, _switch_gating, _top2_gating
from paddle_tpu.distributed.sharding import mesh_context


def test_top2_gating_conservation():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((32, 4)), jnp.float32)
    combine, dispatch, aux, dropped = _top2_gating(logits, capacity=16)
    assert combine.shape == (32, 4, 16)
    # each token dispatched to ≤2 expert/slot pairs with weights summing ≤1
    per_token = np.asarray(jnp.sum(combine, axis=(1, 2)))
    assert np.all(per_token <= 1.0 + 1e-5)
    assert np.all(per_token > 0.5)  # ample capacity → everyone routed
    # no slot used twice per expert
    slot_use = np.asarray(jnp.sum(dispatch.astype(jnp.int32), axis=0))
    assert slot_use.max() <= 1
    assert float(aux) > 0
    # capacity 16/expert on 64 assignments: a few gate-2 picks past the
    # shared slots drop; the fraction must be small and exactly zero once
    # capacity covers every assignment
    assert 0.0 <= float(dropped) < 0.15
    _, _, _, dropped_ample = _top2_gating(logits, capacity=64)
    assert float(dropped_ample) == 0.0


def test_switch_gating_capacity_drop():
    # all tokens prefer expert 0 → capacity forces drops
    logits = jnp.tile(jnp.asarray([[10.0, 0.0]]), (16, 1))
    combine, dispatch, aux, dropped = _switch_gating(logits, capacity=4)
    routed = np.asarray(jnp.sum(combine, axis=(1, 2)) > 0)
    assert routed.sum() == 4  # only capacity survivors
    np.testing.assert_allclose(float(dropped), 12 / 16)  # 12 of 16 dropped


def test_moe_layer_forward_and_grad():
    pt.seed(0)
    layer = MoELayer(d_model=16, num_experts=4, d_hidden=32)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 8, 16)),
                    jnp.float32)
    y, aux = layer(x)
    assert y.shape == (2, 8, 16)
    params = extract_params(layer)

    def loss(p):
        out, aux = functional_call(layer, p, x)
        return jnp.sum(out**2) + aux

    g = jax.grad(loss)(params)
    for name, grad in g.items():
        assert bool(jnp.all(jnp.isfinite(grad))), name
    # experts actually receive gradient
    assert float(jnp.sum(jnp.abs(g["experts.w1"]))) > 0


def test_moe_expert_parallel_matches_single():
    """EP-sharded MoE == unsharded MoE numerically."""
    pt.seed(3)
    layer = MoELayer(d_model=16, num_experts=8, d_hidden=32)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((4, 16, 16)),
                    jnp.float32)
    ref, _ = layer(x)
    params = extract_params(layer)
    mesh = dist.build_mesh(ep=4, tp=2)
    from jax.sharding import NamedSharding, PartitionSpec as P

    objs = dict(layer.named_parameters())
    strategy = dist.DistributedStrategy()
    sharded = {
        n: jax.device_put(
            v, NamedSharding(
                mesh,
                dist.param_partition_spec(n, v.shape, objs[n].spec, strategy),
            )
        )
        for n, v in params.items()
    }
    with mesh_context(mesh):
        y, _ = jax.jit(lambda p, x: functional_call(layer, p, x))(
            sharded, jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"))))
        )
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(ref), rtol=2e-4, atol=2e-4
    )



def test_moe_layer_reports_drop_fraction():
    pt.seed(5)
    layer = MoELayer(d_model=8, num_experts=2, d_hidden=16, gate="switch",
                     capacity_factor=0.5)
    # skew inputs so one expert overflows its (tiny) capacity
    x = jnp.asarray(np.ones((2, 16, 8)), jnp.float32)
    _, _ = layer(x)
    assert float(layer.last_drop_fraction) > 0.0
    layer2 = MoELayer(d_model=8, num_experts=2, d_hidden=16,
                      capacity_factor=8.0)
    _, _ = layer2(jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 16, 8)), jnp.float32))
    assert float(layer2.last_drop_fraction) < 0.2


def test_moe_ep_x_fsdp_composition():
    """EP and FSDP on separate axes of one mesh: expert weights sharded
    over ep, dense batch over dp+fsdp — numerics match unsharded."""
    pt.seed(7)
    layer = MoELayer(d_model=16, num_experts=4, d_hidden=32)
    x = jnp.asarray(np.random.default_rng(4).standard_normal((4, 8, 16)),
                    jnp.float32)
    ref, _ = layer(x)
    params = extract_params(layer)
    mesh = dist.build_mesh(fsdp=2, ep=2, tp=2)
    from jax.sharding import NamedSharding, PartitionSpec as P

    objs = dict(layer.named_parameters())
    strategy = dist.DistributedStrategy()
    sharded = {
        n: jax.device_put(
            v, NamedSharding(
                mesh,
                dist.param_partition_spec(n, v.shape, objs[n].spec, strategy),
            )
        )
        for n, v in params.items()
    }
    # expert weights must actually be split over the ep axis
    assert "ep" in str(sharded["experts.w1"].sharding.spec)
    with mesh_context(mesh):
        y, _ = jax.jit(lambda p, x: functional_call(layer, p, x))(
            sharded, jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"))))
        )
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_dropless_moe_matches_dense_reference():
    """Grouped-matmul dropless dispatch == explicit per-token expert
    compute (no capacity, nothing dropped)."""
    from paddle_tpu.distributed.moe import DroplessMoELayer

    pt.seed(9)
    layer = DroplessMoELayer(d_model=16, num_experts=4, d_hidden=32,
                             top_k=2)
    x = jnp.asarray(np.random.default_rng(5).standard_normal((2, 8, 16)),
                    jnp.float32)
    y, aux = layer(x)
    assert y.shape == (2, 8, 16)
    assert float(layer.last_drop_fraction) == 0.0

    # dense reference: every token through its top-k experts explicitly
    import jax as _jax

    xf = np.asarray(x.reshape(16, 16))
    logits = xf @ np.asarray(layer.gate_weight.value)
    probs = np.asarray(_jax.nn.softmax(jnp.asarray(logits), axis=-1))
    w1 = np.asarray(layer.experts.w1.value)
    b1 = np.asarray(layer.experts.b1.value)
    w2 = np.asarray(layer.experts.w2.value)
    b2 = np.asarray(layer.experts.b2.value)
    ref = np.zeros_like(xf)
    for t in range(16):
        top = np.argsort(-probs[t])[:2]
        g = probs[t][top] / probs[t][top].sum()
        for gi, e in zip(g, top):
            h = np.asarray(layer.experts.act(
                jnp.asarray(xf[t] @ w1[e] + b1[e])))
            ref[t] += gi * (h @ w2[e] + b2[e])
    np.testing.assert_allclose(np.asarray(y).reshape(16, 16), ref,
                               rtol=2e-4, atol=2e-4)


def test_dropless_moe_grads():
    from paddle_tpu.distributed.moe import DroplessMoELayer

    pt.seed(10)
    layer = DroplessMoELayer(d_model=8, num_experts=4, d_hidden=16)
    x = jnp.asarray(np.random.default_rng(6).standard_normal((1, 8, 8)),
                    jnp.float32)
    params = extract_params(layer)

    def loss(p):
        out, aux = functional_call(layer, p, x)
        return jnp.sum(out ** 2) + aux

    g = jax.grad(loss)(params)
    for name, grad in g.items():
        assert bool(jnp.all(jnp.isfinite(grad))), name
    assert float(jnp.sum(jnp.abs(g["experts.w1"]))) > 0
    assert float(jnp.sum(jnp.abs(g["gate_weight"]))) > 0


def test_dropless_ep_matches_single_shard():
    """Sort-based all-to-all dispatch over an ep=2 mesh == the
    single-shard dropless path (round-3 verdict: dropless x EP must
    compose, parity with global_scatter/global_gather)."""
    from paddle_tpu.distributed.moe import DroplessMoELayer

    pt.seed(11)
    layer = DroplessMoELayer(d_model=16, num_experts=4, d_hidden=32,
                             top_k=2)
    x = jnp.asarray(np.random.default_rng(6).standard_normal((2, 8, 16)),
                    jnp.float32)
    ref, ref_aux = layer(x)  # no active mesh -> single-shard ragged path

    mesh = dist.build_mesh(ep=2)
    params = extract_params(layer)
    from jax.sharding import NamedSharding, PartitionSpec as P

    objs = dict(layer.named_parameters())
    strategy = dist.DistributedStrategy()
    sharded = {
        n: jax.device_put(
            v, NamedSharding(
                mesh,
                dist.param_partition_spec(n, v.shape, objs[n].spec,
                                          strategy)))
        for n, v in params.items()
    }
    # expert weights actually split over ep
    assert "ep" in str(sharded["experts.w1"].sharding.spec)
    with mesh_context(mesh):
        y, aux = jax.jit(
            lambda p, x: functional_call(layer, p, x))(sharded, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-4)


def test_dropless_ep_composes_with_fsdp_and_grads():
    """dropless EP inside a dp x fsdp x ep x tp mesh: output matches the
    meshless reference and expert-weight grads flow."""
    from paddle_tpu.distributed.moe import DroplessMoELayer

    pt.seed(12)
    layer = DroplessMoELayer(d_model=8, num_experts=4, d_hidden=16,
                             top_k=2)
    x = jnp.asarray(np.random.default_rng(7).standard_normal((4, 4, 8)),
                    jnp.float32)
    ref, _ = layer(x)

    mesh = dist.build_mesh(fsdp=2, ep=2, tp=2)
    params = extract_params(layer)
    from jax.sharding import NamedSharding, PartitionSpec as P

    objs = dict(layer.named_parameters())
    strategy = dist.DistributedStrategy()
    sharded = {
        n: jax.device_put(
            v, NamedSharding(
                mesh,
                dist.param_partition_spec(n, v.shape, objs[n].spec,
                                          strategy)))
        for n, v in params.items()
    }
    with mesh_context(mesh):
        y, _ = jax.jit(
            lambda p, x: functional_call(layer, p, x))(
                sharded,
                jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp")))))
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

        def loss(p):
            out, aux = functional_call(layer, p, x)
            return jnp.sum(out ** 2) + aux

        g = jax.jit(jax.grad(loss))(sharded)
    assert float(jnp.sum(jnp.abs(g["experts.w1"]))) > 0
    assert float(jnp.sum(jnp.abs(g["gate_weight"]))) > 0
