"""MoE layer tests (parity model: incubate MoE tests — routing
conservation, capacity, aux loss, expert-parallel sharding)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import distributed as dist
from paddle_tpu.core.functional import extract_params, functional_call
from paddle_tpu.distributed.moe import MoELayer, _switch_gating, _top2_gating
from paddle_tpu.distributed.sharding import mesh_context


def test_top2_gating_conservation():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((32, 4)), jnp.float32)
    combine, dispatch, aux = _top2_gating(logits, capacity=16)
    assert combine.shape == (32, 4, 16)
    # each token dispatched to ≤2 expert/slot pairs with weights summing ≤1
    per_token = np.asarray(jnp.sum(combine, axis=(1, 2)))
    assert np.all(per_token <= 1.0 + 1e-5)
    assert np.all(per_token > 0.5)  # ample capacity → everyone routed
    # no slot used twice per expert
    slot_use = np.asarray(jnp.sum(dispatch.astype(jnp.int32), axis=0))
    assert slot_use.max() <= 1
    assert float(aux) > 0


def test_switch_gating_capacity_drop():
    # all tokens prefer expert 0 → capacity forces drops
    logits = jnp.tile(jnp.asarray([[10.0, 0.0]]), (16, 1))
    combine, dispatch, aux = _switch_gating(logits, capacity=4)
    routed = np.asarray(jnp.sum(combine, axis=(1, 2)) > 0)
    assert routed.sum() == 4  # only capacity survivors


def test_moe_layer_forward_and_grad():
    pt.seed(0)
    layer = MoELayer(d_model=16, num_experts=4, d_hidden=32)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 8, 16)),
                    jnp.float32)
    y, aux = layer(x)
    assert y.shape == (2, 8, 16)
    params = extract_params(layer)

    def loss(p):
        out, aux = functional_call(layer, p, x)
        return jnp.sum(out**2) + aux

    g = jax.grad(loss)(params)
    for name, grad in g.items():
        assert bool(jnp.all(jnp.isfinite(grad))), name
    # experts actually receive gradient
    assert float(jnp.sum(jnp.abs(g["experts.w1"]))) > 0


def test_moe_expert_parallel_matches_single():
    """EP-sharded MoE == unsharded MoE numerically."""
    pt.seed(3)
    layer = MoELayer(d_model=16, num_experts=8, d_hidden=32)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((4, 16, 16)),
                    jnp.float32)
    ref, _ = layer(x)
    params = extract_params(layer)
    mesh = dist.build_mesh(fsdp=4, tp=2)
    from jax.sharding import NamedSharding, PartitionSpec as P

    objs = dict(layer.named_parameters())
    strategy = dist.DistributedStrategy()
    sharded = {
        n: jax.device_put(
            v, NamedSharding(
                mesh,
                dist.param_partition_spec(n, v.shape, objs[n].spec, strategy),
            )
        )
        for n, v in params.items()
    }
    with mesh_context(mesh):
        y, _ = jax.jit(lambda p, x: functional_call(layer, p, x))(
            sharded, jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"))))
        )
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(ref), rtol=2e-4, atol=2e-4
    )
