"""Paged KV cache + continuous batching engine tests (reference decode
path: phi masked_multihead_attention / fused_multi_transformer caches)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference import (
    Config,
    ContinuousBatchingEngine,
    EngineConfig,
    Predictor,
)
from paddle_tpu.inference.paged import (
    PagedLayerCache,
    PagedState,
    PagePool,
    append_kv,
    init_paged_pool,
    paged_attention,
)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def _model(seed=0):
    import paddle_tpu as pt

    pt.seed(seed)
    cfg = LlamaConfig.tiny()
    return LlamaForCausalLM(cfg), cfg


# ---------------- paged primitives ----------------

def test_page_pool_alloc_free():
    pool = PagePool(n_pages=8, page_size=4, slots=2, max_pages_per_slot=4)
    assert pool.free_pages == 8
    assert pool.alloc(0, 10)  # 3 pages
    assert pool.free_pages == 5
    assert len(pool.pages_of[0]) == 3
    assert pool.alloc(0, 12)  # grow to exactly 3 pages → no-op
    assert pool.alloc(0, 13)  # grow to 4
    assert pool.free_pages == 4
    assert not pool.alloc(1, 100)  # exceeds per-slot max
    assert pool.alloc(1, 16)
    assert pool.free_pages == 0
    pool.free(0)
    assert pool.free_pages == 4
    assert (pool.block_tables[0] == 0).all()


def test_paged_append_gather_attention_matches_dense():
    slots, ps, n_pages, kvh, d, h = 2, 4, 9, 2, 8, 4
    pool = PagePool(n_pages, ps, slots, max_pages_per_slot=4,
                    reserve_sink=True)
    cache = init_paged_pool(1, n_pages, ps, kvh, d, dtype=jnp.float32)[0]
    rng = np.random.default_rng(0)
    lens = [6, 3]  # tokens already cached per slot
    dense_k = np.zeros((slots, 16, kvh, d), np.float32)
    dense_v = np.zeros((slots, 16, kvh, d), np.float32)
    for s in range(slots):
        pool.alloc(s, lens[s] + 1)
        for t in range(lens[s]):
            k = rng.standard_normal((slots, 1, kvh, d)).astype(np.float32)
            v = rng.standard_normal((slots, 1, kvh, d)).astype(np.float32)
            state = pool.device_state(
                np.array([t if i == s else 0 for i in range(slots)]))
            # append only writes slot s meaningfully; other slot writes
            # land at its own (stale) position — emulate per-slot append
            cache = append_kv(cache, state, jnp.asarray(k), jnp.asarray(v))
            dense_k[s, t] = k[s, 0]
            dense_v[s, t] = v[s, 0]
            # restore the other slot's stale-position value
            o = 1 - s
            dense_k[o, 0] = k[o, 0]
            dense_v[o, 0] = v[o, 0]

    # now append the "current token" for both slots at their real lens
    k = rng.standard_normal((slots, 1, kvh, d)).astype(np.float32)
    v = rng.standard_normal((slots, 1, kvh, d)).astype(np.float32)
    state = pool.device_state(np.array(lens))
    cache = append_kv(cache, state, jnp.asarray(k), jnp.asarray(v))
    for s in range(slots):
        dense_k[s, lens[s]] = k[s, 0]
        dense_v[s, lens[s]] = v[s, 0]

    q = rng.standard_normal((slots, 1, h, d)).astype(np.float32)
    out = np.asarray(paged_attention(jnp.asarray(q), cache, state))
    # dense reference with GQA repeat + causal-length mask
    for s in range(slots):
        L = lens[s] + 1
        kk = np.repeat(dense_k[s, :L], h // kvh, axis=1)
        vv = np.repeat(dense_v[s, :L], h // kvh, axis=1)
        att = np.einsum("qhd,khd->hqk", q[s] / np.sqrt(d), kk)
        p = np.exp(att - att.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("hqk,khd->qhd", p, vv)
        np.testing.assert_allclose(out[s], ref, rtol=1e-4, atol=1e-5)


# ---------------- engine end-to-end ----------------

@pytest.mark.parametrize("paged", [False, True])
def test_engine_matches_sequential_predictor(paged):
    model, cfg = _model()
    prompt = np.array([3, 7, 11, 2, 9])
    pred = Predictor(model, Config())
    ref = pred.generate(prompt, max_new_tokens=8)[0]

    ecfg = EngineConfig(max_slots=2, max_len=64, seq_buckets=(8, 16),
                        paged=paged, page_size=8)
    eng = ContinuousBatchingEngine(model, ecfg)
    reqs = eng.run([prompt], max_new_tokens=8)
    assert reqs[0].done
    assert reqs[0].ttft_ms is not None and reqs[0].ttft_ms > 0
    np.testing.assert_array_equal(np.array(reqs[0].output), ref)


@pytest.mark.parametrize("paged", [False, True])
def test_engine_continuous_batching_many_requests(paged):
    model, cfg = _model()
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, cfg.vocab_size, size=n)
               for n in (4, 7, 3, 9, 5)]
    # sequential reference
    pred = Predictor(model, Config())
    refs = [pred.generate(p, max_new_tokens=6)[0] for p in prompts]

    # 5 requests through 2 slots → forced admission waves
    ecfg = EngineConfig(max_slots=2, max_len=32, seq_buckets=(16,),
                        paged=paged, page_size=8)
    eng = ContinuousBatchingEngine(model, ecfg)
    reqs = eng.run(prompts, max_new_tokens=6)
    for req, ref in zip(reqs, refs):
        assert req.done
        np.testing.assert_array_equal(np.array(req.output), ref)


def test_engine_eos_frees_slot_early():
    model, cfg = _model()
    prompt = np.array([1, 2, 3])
    pred = Predictor(model, Config())
    ref = pred.generate(prompt, max_new_tokens=1)[0]
    eos = int(ref[0])  # first generated token == eos → stops immediately
    eng = ContinuousBatchingEngine(
        model, EngineConfig(max_slots=1, max_len=32, seq_buckets=(8,)))
    reqs = eng.run([prompt], max_new_tokens=10, eos_token_id=eos)
    assert reqs[0].done and len(reqs[0].output) == 1
    assert not eng.active.any()


def test_paged_pool_oversubscription():
    # pool smaller than slots*max_len still serves requests in waves
    model, cfg = _model()
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, cfg.vocab_size, size=5) for _ in range(3)]
    ecfg = EngineConfig(max_slots=3, max_len=32, seq_buckets=(8,),
                        paged=True, page_size=8,
                        n_pages=1 + 2 * (32 // 8))  # sink + 2 slots' worth
    eng = ContinuousBatchingEngine(model, ecfg)
    reqs = eng.run(prompts, max_new_tokens=4)
    assert all(r.done for r in reqs)
    pred = Predictor(model, Config())
    for req, p in zip(reqs, prompts):
        ref = pred.generate(p, max_new_tokens=4)[0]
        np.testing.assert_array_equal(np.array(req.output), ref)


def test_engine_bucket_never_exceeds_max_len():
    model, cfg = _model()
    # default-ish buckets larger than max_len must clamp, not crash
    ecfg = EngineConfig(max_slots=1, max_len=16, seq_buckets=(64, 128))
    eng = ContinuousBatchingEngine(model, ecfg)
    reqs = eng.run([np.array([1, 2, 3])], max_new_tokens=4)
    assert reqs[0].done and len(reqs[0].output) == 4


def test_engine_default_pool_admits_max_len_request():
    model, cfg = _model()
    ecfg = EngineConfig(max_slots=1, max_len=32, seq_buckets=(16,),
                        paged=True, page_size=8)
    eng = ContinuousBatchingEngine(model, ecfg)
    # prompt + max_new == max_len: needs every page of the slot
    reqs = eng.run([np.arange(1, 17)], max_new_tokens=16)
    assert reqs[0].done


def test_engine_paged_pool_too_small_raises():
    model, cfg = _model()
    ecfg = EngineConfig(max_slots=1, max_len=32, seq_buckets=(16,),
                        paged=True, page_size=8, n_pages=2)  # sink + 1
    eng = ContinuousBatchingEngine(model, ecfg)
    with pytest.raises(RuntimeError, match="size n_pages up"):
        eng.run([np.arange(1, 17)], max_new_tokens=16)


def test_engine_cache_dtype_is_ctor_arg():
    model, cfg = _model()
    ecfg = EngineConfig(max_slots=1, max_len=16, seq_buckets=(8,),
                        cache_dtype=jnp.bfloat16)
    eng = ContinuousBatchingEngine(model, ecfg)
    assert eng.caches[0][0].dtype == jnp.bfloat16


def test_engine_sampled_first_token_not_always_argmax():
    model, cfg = _model()
    prompt = np.array([1, 2, 3])
    ecfg = EngineConfig(max_slots=1, max_len=32, seq_buckets=(8,),
                        greedy=False, temperature=5.0, seed=0)
    firsts = set()
    for seed in range(6):
        ecfg2 = EngineConfig(max_slots=1, max_len=32, seq_buckets=(8,),
                             greedy=False, temperature=5.0, seed=seed)
        eng = ContinuousBatchingEngine(model, ecfg2)
        reqs = eng.run([prompt], max_new_tokens=1)
        firsts.add(reqs[0].output[0])
    assert len(firsts) > 1  # high temperature → varies across seeds


def test_engine_paged_bucket_page_divisibility_checked():
    model, cfg = _model()
    with pytest.raises(ValueError, match="not divisible by page_size"):
        ContinuousBatchingEngine(model, EngineConfig(
            max_slots=1, max_len=32, seq_buckets=(12,),
            paged=True, page_size=8))


def test_chunked_decode_matches_per_token():
    """step_chunk (K decode steps fused into one device program, one
    host sync per chunk) must produce byte-identical greedy outputs to
    the per-token step() loop."""
    model, cfg = _model(11)
    prompts = [np.arange(1, 6), np.arange(3, 10), np.arange(2, 4)]

    eng1 = ContinuousBatchingEngine(
        model, EngineConfig(max_slots=2, max_len=64, seq_buckets=(16,)))
    rids = [eng1.add_request(p, max_new_tokens=9) for p in prompts]
    while eng1.step() or eng1._queue or eng1.active.any():
        pass
    ref = [eng1._finished[r].output for r in rids]

    eng2 = ContinuousBatchingEngine(
        model, EngineConfig(max_slots=2, max_len=64, seq_buckets=(16,)))
    out = eng2.run(prompts, max_new_tokens=9, max_chunk=4)
    assert [r.output for r in out] == ref


def test_adaptive_chunking_matches_fixed():
    """step_adaptive (short chunks while admission work is queued, full
    chunks in steady decode) must produce the same greedy outputs as the
    per-token reference — scheduling granularity is invisible to
    results."""
    model, cfg = _model(11)
    prompts = [np.arange(1, 6), np.arange(3, 10), np.arange(2, 4),
               np.arange(4, 9)]

    eng1 = ContinuousBatchingEngine(
        model, EngineConfig(max_slots=2, max_len=64, seq_buckets=(16,)))
    rids = [eng1.add_request(p, max_new_tokens=9) for p in prompts]
    while eng1.step() or eng1._queue or eng1.active.any():
        pass
    ref = [eng1._finished[r].output for r in rids]

    # 4 requests into 2 slots: the queue stays non-empty across the
    # first chunks, exercising the probe-chunk path, then drains into
    # full-chunk steady state
    eng2 = ContinuousBatchingEngine(
        model, EngineConfig(max_slots=2, max_len=64, seq_buckets=(16,)))
    rids2 = [eng2.add_request(p, max_new_tokens=9) for p in prompts]
    while eng2.step_adaptive(max_chunk=4) or eng2.active.any():
        pass
    got = [eng2._finished[r].output for r in rids2]
    assert got == ref


def test_chunked_decode_eos_mid_chunk():
    """A sequence hitting EOS inside a chunk stops exactly at EOS —
    overshoot tokens generated device-side are discarded."""
    model, cfg = _model(12)
    eng = ContinuousBatchingEngine(
        model, EngineConfig(max_slots=1, max_len=64, seq_buckets=(16,)))
    # first find what greedy emits, then re-run using token[1] as "eos"
    probe = eng.run([np.arange(1, 6)], max_new_tokens=8)[0].output
    eos = probe[2]
    model2, _ = _model(12)
    eng2 = ContinuousBatchingEngine(
        model2, EngineConfig(max_slots=1, max_len=64, seq_buckets=(16,)))
    out = eng2.run([np.arange(1, 6)], max_new_tokens=8,
                   eos_token_id=eos, max_chunk=8)[0]
    # stop at the FIRST occurrence of eos (greedy streams can repeat a
    # token, so probe[2]'s value may appear earlier), inclusive, with
    # the chunk's device-side overshoot tokens discarded
    assert out.output == probe[:probe.index(eos) + 1]
    assert out.done


def test_chunk_budget_respects_limits():
    model, cfg = _model(13)
    eng = ContinuousBatchingEngine(
        model, EngineConfig(max_slots=2, max_len=32, seq_buckets=(16,)))
    out = eng.run([np.arange(1, 5)], max_new_tokens=3, max_chunk=16)[0]
    assert len(out.output) == 3  # chunk clamped to the token budget


@pytest.mark.parametrize("paged", [False, True])
def test_engine_mid_decode_admission_overlap(paged):
    """Requests arriving WHILE earlier sequences decode (the overlapped
    admission path: chunk dispatched first, prefill behind it, pending
    integrated after readback) produce exactly the sequential outputs."""
    model, cfg = _model()
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, cfg.vocab_size, size=n)
               for n in (5, 4, 6, 3)]
    pred = Predictor(model, Config())
    refs = [pred.generate(p, max_new_tokens=10)[0] for p in prompts]

    ecfg = EngineConfig(max_slots=2, max_len=64, seq_buckets=(16,),
                        paged=paged, page_size=8)
    eng = ContinuousBatchingEngine(model, ecfg)
    rids = [eng.add_request(prompts[0], 10)]
    arrivals = iter(prompts[1:])
    while eng.step_chunk(4) or eng._queue or eng.active.any():
        # one new request lands after every chunk, mid-decode
        nxt = next(arrivals, None)
        if nxt is not None:
            rids.append(eng.add_request(nxt, 10))
    for rid, ref in zip(rids, refs):
        req = eng._finished[rid]
        assert req.done and req.ttft_ms is not None
        np.testing.assert_array_equal(np.array(req.output), ref)


def test_engine_with_weight_only_int8_model():
    """Weight-only int8 Llama through the continuous-batching engine:
    qweight/scale buffers must ride as ARGUMENTS of the compiled
    prefill/decode programs (never jit constants — a 7B model would bake
    ~7 GB into every executable), and greedy decode must match the
    quantized model's plain KV forward."""
    import paddle_tpu as pt
    from paddle_tpu import quantization as Q

    pt.seed(7)
    cfg = LlamaConfig(
        vocab_size=256, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    qmodel = Q.quantize_model_weight_only(model, weight_dtype="int8",
                                          group_size=64)
    qmodel.eval()

    eng = ContinuousBatchingEngine(qmodel, EngineConfig(
        max_slots=2, max_len=64, seq_buckets=(16,),
        cache_dtype=jnp.float32))
    # the quant weights must be engine buffers, not constants
    assert any("qweight" in k for k in eng.buffers), list(eng.buffers)[:4]

    prompt = np.random.default_rng(0).integers(0, 256, (10,))
    out = eng.run([prompt], max_new_tokens=6)
    toks = out[0].output
    assert len(toks) == 6

    # reference: greedy step-by-step with the same quantized model
    caches = qmodel.init_kv_caches(1, 64, dtype=jnp.float32)
    ids = jnp.asarray(prompt)[None, :]
    pos = jnp.arange(10)[None, :]
    logits, caches = qmodel(ids, position_ids=pos, kv_caches=caches,
                            cache_index=0)
    ref = [int(jnp.argmax(logits[0, 9]))]
    n = 10
    for _ in range(5):
        tok = jnp.asarray([[ref[-1]]])
        logits, caches = qmodel(
            tok, position_ids=jnp.asarray([[n]]),
            kv_caches=caches, cache_index=jnp.asarray([n]))
        ref.append(int(jnp.argmax(logits[0, -1])))
        n += 1
    assert toks == ref, (toks, ref)


@pytest.mark.parametrize("paged", [False, True])
def test_engine_tensor_parallel_matches_single_device(paged):
    """TP-sharded serving (mesh with a tp axis): greedy decode must be
    numerically identical to the single-device engine — GSPMD inserts
    the TP collectives; the engine only places params/caches."""
    import paddle_tpu as pt
    from paddle_tpu import distributed as dist

    pt.seed(0)
    cfg = LlamaConfig(
        vocab_size=256, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128, use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    model.eval()
    prompt = np.random.default_rng(0).integers(0, 256, (10,))

    ecfg = dict(max_slots=2, max_len=64, seq_buckets=(16,),
                cache_dtype=jnp.float32, paged=paged)
    if paged:
        ecfg["page_size"] = 16

    ref_eng = ContinuousBatchingEngine(model, EngineConfig(**ecfg))
    ref = ref_eng.run([prompt], max_new_tokens=6)[0].output

    mesh = dist.build_mesh(tp=2)
    tp_eng = ContinuousBatchingEngine(model, EngineConfig(**ecfg),
                                      mesh=mesh)
    # params actually sharded over tp
    w = tp_eng.params["model.layers.0.self_attn.q_proj.weight"]
    assert "tp" in str(w.sharding.spec), w.sharding
    got = tp_eng.run([prompt], max_new_tokens=6)[0].output
    assert got == ref, (got, ref)
