"""Generation utilities: processor math, sampling, beam search vs
brute force, and Predictor integration (parity model: PaddleNLP
tests/generation/test_generation_utils.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import generation as G


class TestProcessors:
    def test_top_k(self):
        logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0]])
        out = np.asarray(G.top_k_filter(logits, 2))
        kept = out > G.NEG_INF / 2
        assert kept.sum() == 2 and kept[0, 1] and kept[0, 4]

    def test_top_p(self):
        # probs 0.5, 0.3, 0.15, 0.05 → p=0.6 keeps the first two
        probs = np.array([[0.5, 0.3, 0.15, 0.05]])
        logits = jnp.asarray(np.log(probs))
        out = np.asarray(G.top_p_filter(logits, 0.6))
        kept = out > G.NEG_INF / 2
        assert kept.tolist() == [[True, True, False, False]]
        # top token always survives even with tiny p
        out2 = np.asarray(G.top_p_filter(logits, 1e-6))
        assert (out2 > G.NEG_INF / 2).sum() == 1

    def test_repetition_penalty(self):
        logits = jnp.asarray([[2.0, -2.0, 1.0]])
        gen = jnp.asarray([[0, 1]])
        out = np.asarray(G.repetition_penalty_(logits, gen, 2.0))
        np.testing.assert_allclose(out[0], [1.0, -4.0, 1.0])

    def test_process_logits_batch_matches_scalar_rows(self):
        """The vectorized per-row stack (serving's per-request
        sampling) must agree with the scalar processors row by row for
        distinct-logit rows (rank-cut vs value-cut top-k only differ on
        exact ties)."""
        rng = np.random.default_rng(1)
        # distinct values per row -> no top-k tie ambiguity
        logits = jnp.asarray(
            rng.permutation(np.arange(32, dtype=np.float32))
            .reshape(1, -1))
        logits = jnp.concatenate(
            [logits, logits[:, ::-1] * 0.37 + 1.0], axis=0)
        params = [(1.0, 5, 1.0), (2.5, 0, 0.6), (0.7, 4, 0.8)]
        for temp, k, p in params:
            batch = np.asarray(G.process_logits_batch(
                logits,
                jnp.full((2,), temp), jnp.full((2,), k, jnp.int32),
                jnp.full((2,), p)))
            for row in range(2):
                ref = np.asarray(G.process_logits(
                    logits[row:row + 1], temperature=temp, top_k=k,
                    top_p=p))[0]
                kept_b = batch[row] > G.NEG_INF / 2
                kept_r = ref > G.NEG_INF / 2
                np.testing.assert_array_equal(kept_b, kept_r)
                np.testing.assert_allclose(
                    batch[row][kept_b], ref[kept_r], rtol=1e-6)

    def test_process_logits_batch_per_row_params(self):
        """Different params per row in ONE call: row 0 disabled (pass
        through), row 1 top-k=1, row 2 tight top-p — and the top token
        always survives even degenerate per-row settings."""
        logits = jnp.asarray(np.log(np.array(
            [[0.5, 0.3, 0.15, 0.05]] * 3, np.float32)))
        out = np.asarray(G.process_logits_batch(
            logits,
            jnp.asarray([1.0, 1.0, 1.0]),
            jnp.asarray([0, 1, 0], jnp.int32),
            jnp.asarray([1.0, 1.0, 1e-9])))
        kept = out > G.NEG_INF / 2
        assert kept[0].all()                 # all filters off
        assert kept[1].tolist() == [True, False, False, False]
        assert kept[2].tolist() == [True, False, False, False]
        np.testing.assert_allclose(out[0], np.asarray(logits[0]))

    def test_process_logits_batch_jits(self):
        rng = np.random.default_rng(3)
        logits = jnp.asarray(rng.normal(size=(4, 64)).astype(np.float32))
        f = jax.jit(G.process_logits_batch)
        out = f(logits, jnp.full((4,), 1.3),
                jnp.asarray([0, 3, 8, 1], jnp.int32),
                jnp.asarray([1.0, 0.9, 0.5, 1.0]))
        kept = np.asarray(out) > G.NEG_INF / 2
        assert kept[3].sum() == 1  # top-k=1 row
        assert kept[0].sum() == 64  # disabled row

    def test_sampling_topk1_is_greedy(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
        tok = G.sample_token(logits, jax.random.PRNGKey(0), top_k=1)
        np.testing.assert_array_equal(
            np.asarray(tok), np.argmax(np.asarray(logits), -1))

    def test_sampling_respects_filter(self):
        logits = jnp.asarray([[0.0, 10.0, 0.0, 9.5]])
        toks = [int(G.sample_token(logits, jax.random.PRNGKey(i),
                                   top_k=2, temperature=2.0)[0])
                for i in range(30)]
        assert set(toks) <= {1, 3} and len(set(toks)) == 2


class TestBeamSearch:
    def _brute_force(self, trans, start_lp, steps, nb_vocab):
        """exhaustive best path under sum of logprobs."""
        import itertools

        best, best_seq = -1e30, None
        for seq in itertools.product(range(nb_vocab), repeat=steps):
            score = start_lp[seq[0]]
            for a, b in zip(seq[:-1], seq[1:]):
                score += trans[a][b]
            if score > best:
                best, best_seq = score, seq
        return best_seq, best

    def test_beam_matches_brute_force(self):
        """Markov toy model: beam width = vocab ⇒ exact search."""
        v, steps = 4, 5
        rng = np.random.default_rng(0)
        start = np.log(rng.dirichlet(np.ones(v)))
        trans = np.log(rng.dirichlet(np.ones(v), size=v))

        state = G.BeamState(1, v, steps)
        lp0 = jnp.asarray(np.tile(start[None], (v, 1)).astype(np.float32))
        state, _, _ = G.beam_step(state, lp0, 0)
        for t in range(1, steps):
            last = np.asarray(state.tokens[0, :, t - 1])
            lp = jnp.asarray(trans[last].astype(np.float32))
            state, _, _ = G.beam_step(state, lp, t)
        tokens, score = G.beam_finalize(state, length_penalty=0.0)
        ref_seq, ref_score = self._brute_force(trans, start, steps, v)
        np.testing.assert_array_equal(np.asarray(tokens)[0], ref_seq)
        np.testing.assert_allclose(float(score[0]), ref_score, rtol=1e-5)

    def test_eos_freezing(self):
        """a finished beam keeps its score and pads with eos."""
        v, eos = 3, 0
        state = G.BeamState(1, 2, 4)
        # step 0: beam 0 takes eos (finishes), beam 1 takes token 1
        lp = jnp.asarray(np.log(np.array(
            [[0.6, 0.3, 0.1], [0.6, 0.3, 0.1]], np.float32)))
        state, _, _ = G.beam_step(state, lp, 0, eos_token_id=eos)
        assert bool(state.finished[0, 0])
        s0 = float(state.scores[0, 0])
        lp2 = jnp.asarray(np.log(np.array(
            [[1 / 3, 1 / 3, 1 / 3], [0.01, 0.01, 0.98]], np.float32)))
        state, _, _ = G.beam_step(state, lp2, 1, eos_token_id=eos)
        # the finished beam's score is unchanged
        assert any(abs(float(x) - s0) < 1e-6 for x in state.scores[0])


class TestPredictorIntegration:
    @pytest.fixture(scope="class")
    def predictor(self):
        from paddle_tpu.inference import Config, Predictor
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

        pt.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2,
                               use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        c = Config()
        c.max_seq_len = 64
        c.seq_buckets = (16, 32)
        c.decode_dtype = jnp.float32
        return Predictor(model, c), cfg

    def test_greedy_unchanged(self, predictor):
        pred, cfg = predictor
        ids = np.random.default_rng(0).integers(1, cfg.vocab_size, (2, 7))
        out = pred.generate(ids, max_new_tokens=5)
        assert out.shape == (2, 5)
        # deterministic
        out2 = pred.generate(ids, max_new_tokens=5)
        np.testing.assert_array_equal(out, out2)

    def test_sampling_seed_reproducible(self, predictor):
        pred, cfg = predictor
        ids = np.random.default_rng(1).integers(1, cfg.vocab_size, (2, 7))
        a = pred.generate(ids, max_new_tokens=6,
                          decode_strategy="sampling", top_k=8,
                          temperature=1.3, seed=7)
        b = pred.generate(ids, max_new_tokens=6,
                          decode_strategy="sampling", top_k=8,
                          temperature=1.3, seed=7)
        c = pred.generate(ids, max_new_tokens=6,
                          decode_strategy="sampling", top_k=8,
                          temperature=1.3, seed=8)
        np.testing.assert_array_equal(a, b)
        assert a.shape == (2, 6)
        assert not np.array_equal(a, c)  # different seed differs (w.h.p.)

    def test_repetition_penalty_reduces_repeats(self, predictor):
        pred, cfg = predictor
        ids = np.random.default_rng(2).integers(1, cfg.vocab_size, (1, 7))
        plain = pred.generate(ids, max_new_tokens=12)
        pen = pred.generate(ids, max_new_tokens=12,
                            repetition_penalty=5.0)

        def repeats(x):
            _, counts = np.unique(x, return_counts=True)
            return (counts - 1).sum()

        assert repeats(pen) <= repeats(plain)

    def test_beam_search_runs_and_beats_greedy(self, predictor):
        """beam sum-logprob ≥ greedy sum-logprob on the same model."""
        pred, cfg = predictor
        ids = np.random.default_rng(3).integers(1, cfg.vocab_size, (2, 7))
        beam = pred.generate(ids, max_new_tokens=5,
                             decode_strategy="beam_search", num_beams=3)
        assert beam.shape == (2, 5)
        greedy = pred.generate(ids, max_new_tokens=5)

        def score(seq_batch):
            import jax.numpy as jnp

            from paddle_tpu.core.functional import functional_call

            total = []
            for b in range(seq_batch.shape[0]):
                full = np.concatenate([ids[b], seq_batch[b]])
                logits = functional_call(
                    pred.model, pred.params, jnp.asarray(full[None]))
                lp = jax.nn.log_softmax(
                    logits[0].astype(jnp.float32), -1)
                s = sum(float(lp[len(ids[b]) - 1 + i, tok])
                        for i, tok in enumerate(seq_batch[b]))
                total.append(s)
            return np.array(total)

        assert (score(beam) >= score(greedy) - 1e-4).all()


class TestGPTPredictor:
    """GPT now speaks the decode-cache protocol → the AOT Predictor
    serves it exactly like Llama."""

    @pytest.fixture(scope="class")
    def gpt_pred(self):
        from paddle_tpu.inference import Config, Predictor
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        pt.seed(0)
        cfg = GPTConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, max_position_embeddings=64,
            use_flash_attention=False, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0)
        model = GPTForCausalLM(cfg)
        c = Config()
        c.max_seq_len = 64
        c.seq_buckets = (16,)
        c.decode_dtype = jnp.float32
        return Predictor(model, c), cfg

    def test_cached_equals_full_recompute(self, gpt_pred):
        """AOT cached decode token-for-token == argmax over full
        forward recomputes."""
        pred, cfg = gpt_pred
        ids = np.random.default_rng(0).integers(1, cfg.vocab_size, (2, 6))
        out = pred.generate(ids, max_new_tokens=5)
        # oracle: grow the sequence, full forward each step
        from paddle_tpu.core.functional import functional_call

        cur = np.asarray(ids)
        for t in range(5):
            logits = functional_call(
                pred.model, pred.params, jnp.asarray(cur))
            nxt = np.argmax(np.asarray(logits[:, -1]), -1)
            np.testing.assert_array_equal(nxt, out[:, t])
            cur = np.concatenate([cur, nxt[:, None]], 1)

    def test_sampling_and_beam_run(self, gpt_pred):
        pred, cfg = gpt_pred
        ids = np.random.default_rng(1).integers(1, cfg.vocab_size, (2, 6))
        s = pred.generate(ids, max_new_tokens=4,
                          decode_strategy="sampling", top_k=8, seed=1)
        b = pred.generate(ids, max_new_tokens=4,
                          decode_strategy="beam_search", num_beams=3)
        assert s.shape == b.shape == (2, 4)

    def test_default_bf16_cache_dtype(self):
        """the default Config decode_dtype (bf16) works with fp32 params
        — cache writes cast to the cache dtype."""
        from paddle_tpu.inference import Config, Predictor
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        pt.seed(0)
        cfg = GPTConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=1,
            num_attention_heads=2, max_position_embeddings=32,
            use_flash_attention=False, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0)
        c = Config()
        c.max_seq_len = 32
        c.seq_buckets = (16,)      # decode_dtype stays the bf16 default
        pred = Predictor(GPTForCausalLM(cfg), c)
        out = pred.generate(np.arange(1, 7)[None], max_new_tokens=3)
        assert out.shape == (1, 3)

    def test_chunked_prefill_and_vector_guard(self, gpt_pred):
        """s>1 prefill at cache_index>0 (chunked) matches one-shot
        prefill; vector cache_index raises clearly."""
        import jax

        pred, cfg = gpt_pred
        model, params = pred.model, pred.params
        from paddle_tpu.core.functional import functional_call

        ids = np.random.default_rng(3).integers(1, cfg.vocab_size, (1, 8))
        caches = model.init_kv_caches(1, 16, dtype=jnp.float32)
        pos = jnp.arange(8)[None]
        full_logits, _ = functional_call(
            model, params, jnp.asarray(ids), position_ids=pos,
            kv_caches=caches, cache_index=0)
        # two chunks of 4
        caches2 = model.init_kv_caches(1, 16, dtype=jnp.float32)
        l1, caches2 = functional_call(
            model, params, jnp.asarray(ids[:, :4]),
            position_ids=pos[:, :4], kv_caches=caches2, cache_index=0)
        l2, caches2 = functional_call(
            model, params, jnp.asarray(ids[:, 4:]),
            position_ids=pos[:, 4:], kv_caches=caches2, cache_index=4)
        np.testing.assert_allclose(
            np.asarray(l2), np.asarray(full_logits[:, 4:]), rtol=2e-4,
            atol=2e-4)
        with pytest.raises(ValueError, match="scalar cache_index"):
            functional_call(
                model, params, jnp.asarray(ids[:, :1]),
                position_ids=pos[:, :1], kv_caches=caches2,
                cache_index=jnp.asarray([4]))
