"""Round-5 migration-surface sweep: the top-level and nn names a
paddle user types most, present AND behaviorally checked against
torch/numpy references where one exists (parity: python/paddle/tensor/
math.py, base/param_attr.py, nn/layer/{norm,conv,common}.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

# core-engine fast lane (see README "Tests")
pytestmark = pytest.mark.fast

torch = pytest.importorskip("torch")
tF = torch.nn.functional


def test_top_level_tensor_ops():
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(pt.mm(x, x), np.asarray(x) @ np.asarray(x))
    assert float(pt.prod(x)) == 24.0
    np.testing.assert_allclose(pt.tan(x), np.tan(np.asarray(x)), rtol=1e-6)
    np.testing.assert_allclose(
        pt.erf(x), torch.erf(torch.tensor(np.asarray(x))).numpy(),
        rtol=1e-6)
    assert float(pt.floor_divide(jnp.asarray(7), jnp.asarray(2))) == 3
    assert float(pt.mod(jnp.asarray(7.0), jnp.asarray(3.0))) == 1.0
    assert float(pt.remainder(jnp.asarray(-7.0), jnp.asarray(3.0))) == 2.0

    c = pt.as_complex(jnp.asarray([[3.0, 4.0]]))
    assert complex(c[0]) == 3 + 4j
    np.testing.assert_allclose(pt.as_real(c), [[3.0, 4.0]])
    assert float(pt.real(c)[0]) == 3.0 and float(pt.imag(c)[0]) == 4.0
    np.testing.assert_allclose(float(pt.angle(c)[0]), np.angle(3 + 4j),
                               rtol=1e-6)
    assert pt.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]


def test_take_index_add_cov():
    x = jnp.arange(12.0).reshape(3, 4)
    assert float(pt.take(x, jnp.asarray([5]))[0]) == 5.0
    # mode='wrap' wraps; clamp mode clips out-of-range
    assert float(pt.take(x, jnp.asarray([13]), mode="wrap")[0]) == 1.0
    y = pt.index_add(jnp.zeros((3, 2)), jnp.asarray([0, 0, 2]), 0,
                     jnp.ones((3, 2)))
    np.testing.assert_allclose(y, [[2, 2], [0, 0], [1, 1]])

    d = np.random.default_rng(0).standard_normal((4, 20)).astype("f")
    np.testing.assert_allclose(np.asarray(pt.cov(jnp.asarray(d))),
                               np.cov(d), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(pt.corrcoef(jnp.asarray(d))),
                               np.corrcoef(d), rtol=1e-4)
    withnan = np.array([1.0, np.nan, 3.0, 4.0])
    np.testing.assert_allclose(
        float(pt.nanquantile(jnp.asarray(withnan), 0.5)),
        np.nanquantile(withnan, 0.5))


def test_places_grad_flag_dataparallel():
    assert pt.Tensor is jax.Array
    assert pt.CPUPlace() == pt.CPUPlace()
    assert pt.CUDAPlace(1) != pt.CUDAPlace(0)
    # Places are hashable (sets / dict keys), consistent with __eq__
    assert len({pt.CPUPlace(), pt.CPUPlace()}) == 1
    assert len({pt.CUDAPlace(0), pt.CUDAPlace(0), pt.CUDAPlace(1)}) == 2
    assert {pt.CUDAPlace(0): "a"}[pt.CUDAPlace(0)] == "a"
    g = pt.grad(lambda x: (x ** 3).sum(), (jnp.asarray([2.0]),))
    assert float(g[0][0]) == 12.0  # one gradient per input (tuple)
    gx, gy = pt.grad(lambda x, y: (x * y).sum(),
                     (jnp.asarray(2.0), jnp.asarray(5.0)))
    assert float(gx) == 5.0 and float(gy) == 2.0
    with pytest.raises(TypeError, match="functional"):
        pt.grad(jnp.asarray([1.0]), jnp.asarray([1.0]))
    with pytest.raises(TypeError, match="inputs"):
        pt.grad(lambda x: x)
    with pt.set_grad_enabled(False):
        assert not pt.is_grad_enabled()
    assert pt.is_grad_enabled()

    m = nn.Linear(2, 2)
    dp = pt.DataParallel(m)
    assert dp(jnp.ones((1, 2))).shape == (1, 2)
    # upstream delegation: checkpoint keys match the UNWRAPPED model, so
    # a DataParallel-trained state_dict loads into a bare model
    sd = dp.state_dict()
    assert set(sd) == {"weight", "bias"}
    bare = nn.Linear(2, 2)
    missing, unexpected = bare.set_state_dict(sd)
    assert not missing and not unexpected
    np.testing.assert_array_equal(np.asarray(bare.weight.value),
                                  np.asarray(m.weight.value))
    # and the wrapper loads a bare model's checkpoint
    missing, unexpected = dp.set_state_dict(bare.state_dict())
    assert not missing and not unexpected


def test_param_attr():
    pa = pt.ParamAttr(initializer=nn.initializer.Constant(3.0),
                      learning_rate=0.1, trainable=False, name="pw")
    lin = nn.Linear(2, 3, weight_attr=pa)
    assert float(lin.weight.value[0, 0]) == 3.0
    assert lin.weight.trainable is False
    assert lin.weight.optimize_attr["learning_rate"] == 0.1
    assert lin.weight.name == "pw"


def test_pool3d_and_adaptive_parity():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 6, 8, 10)).astype("f")
    np.testing.assert_allclose(
        np.asarray(F.max_pool3d(jnp.asarray(x), 2, 2)),
        tF.max_pool3d(torch.tensor(x), 2, 2).numpy(), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(F.avg_pool3d(jnp.asarray(x), 2, 2)),
        tF.avg_pool3d(torch.tensor(x), 2, 2).numpy(), rtol=1e-5)
    x2 = rng.standard_normal((2, 3, 9, 11)).astype("f")
    np.testing.assert_allclose(
        np.asarray(F.adaptive_max_pool2d(jnp.asarray(x2), (4, 5))),
        tF.adaptive_max_pool2d(torch.tensor(x2), (4, 5)).numpy(),
        rtol=1e-6)
    x1 = rng.standard_normal((2, 3, 13)).astype("f")
    np.testing.assert_allclose(
        np.asarray(F.adaptive_avg_pool1d(jnp.asarray(x1), 5)),
        tF.adaptive_avg_pool1d(torch.tensor(x1), 5).numpy(),
        rtol=1e-5, atol=1e-6)
    x3 = rng.standard_normal((1, 2, 5, 7, 9)).astype("f")
    np.testing.assert_allclose(
        np.asarray(F.adaptive_avg_pool3d(jnp.asarray(x3), (2, 3, 4))),
        tF.adaptive_avg_pool3d(torch.tensor(x3), (2, 3, 4)).numpy(),
        rtol=1e-5, atol=1e-6)


def test_conv_transpose_1d_3d_parity():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, 4, 9)).astype("f")
    w = rng.standard_normal((4, 3, 3)).astype("f")
    b = rng.standard_normal((3,)).astype("f")
    got = F.conv1d_transpose(jnp.asarray(x), jnp.asarray(w),
                             jnp.asarray(b), stride=2, padding=1,
                             output_padding=1)
    ref = tF.conv_transpose1d(torch.tensor(x), torch.tensor(w),
                              torch.tensor(b), stride=2, padding=1,
                              output_padding=1).numpy()
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4,
                               atol=1e-5)
    x = rng.standard_normal((1, 4, 5, 6, 7)).astype("f")
    w = rng.standard_normal((4, 2, 3, 3, 3)).astype("f")
    got = F.conv3d_transpose(jnp.asarray(x), jnp.asarray(w), None,
                             stride=2, padding=1)
    ref = tF.conv_transpose3d(torch.tensor(x), torch.tensor(w), None,
                              stride=2, padding=1).numpy()
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4,
                               atol=1e-5)
    # grouped, via the layer class
    ct = nn.Conv1DTranspose(4, 6, 3, stride=2, groups=2)
    assert ct(jnp.ones((2, 4, 5))).shape == (2, 6, 11)


def test_norm_pad_shuffle_layers():
    pt.seed(0)
    bn1 = nn.BatchNorm1D(8)
    bn1.eval()
    assert bn1(jnp.ones((2, 8, 5))).shape == (2, 8, 5)
    with pytest.raises(ValueError, match="2-D/3-D"):
        bn1(jnp.ones((2, 8, 5, 5)))
    bn3 = nn.BatchNorm3D(4)
    bn3.eval()
    assert bn3(jnp.ones((1, 4, 2, 2, 2))).shape == (1, 4, 2, 2, 2)

    sn = nn.SpectralNorm((6, 4), power_iters=30)
    w = jnp.asarray(np.random.default_rng(0).standard_normal((6, 4)),
                    jnp.float32)
    s = np.linalg.svd(np.asarray(sn(w)), compute_uv=False)[0]
    assert abs(s - 1.0) < 1e-3

    x = jnp.asarray(np.random.default_rng(1)
                    .standard_normal((1, 4, 4, 6)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(nn.PixelUnshuffle(2)(x)),
        tF.pixel_unshuffle(torch.tensor(np.asarray(x)), 2).numpy(),
        rtol=1e-6)
    # unshuffle inverts shuffle
    np.testing.assert_allclose(
        np.asarray(nn.PixelShuffle(2)(nn.PixelUnshuffle(2)(x))),
        np.asarray(x), rtol=1e-6)

    assert nn.Pad1D([1, 2])(jnp.ones((1, 2, 3))).shape == (1, 2, 6)
    assert nn.Pad3D([1, 0, 2, 0, 0, 1])(
        jnp.ones((1, 1, 2, 2, 2))).shape == (1, 1, 3, 4, 3)

    d3 = nn.Dropout3D(0.99)
    d3.train()
    y = d3(jnp.ones((1, 8, 2, 2, 2)))
    # whole channels drop together
    per_channel = np.asarray(y).reshape(8, -1)
    assert all(len(set(row.tolist())) == 1 for row in per_channel)


def test_layer_dict():
    ld = nn.LayerDict({"fc": nn.Linear(2, 3), "act": nn.ReLU()})
    assert "fc" in ld and len(ld) == 2
    assert list(ld.keys()) == ["fc", "act"]
    ld["extra"] = nn.Identity()
    assert len(ld) == 3
    popped = ld.pop("extra")
    assert isinstance(popped, nn.Identity) and len(ld) == 2
    # registered as real sublayers: parameters traverse
    names = {n for n, _ in ld.named_parameters()}
    assert names == {"fc.weight", "fc.bias"}


def test_rrelu_hardtanh():
    x = jnp.asarray([-2.0, -0.5, 0.5, 2.0])
    np.testing.assert_allclose(nn.Hardtanh()(x), [-1, -0.5, 0.5, 1])
    r = nn.RReLU()
    r.eval()
    mid = (1 / 8 + 1 / 3) / 2
    np.testing.assert_allclose(r(x), [-2 * mid, -0.5 * mid, 0.5, 2.0],
                               rtol=1e-6)


def test_adaptive_max_pool_mask_and_bn_formats():
    rng = np.random.default_rng(5)
    x2 = rng.standard_normal((2, 3, 9, 11)).astype("f")
    out, mask = F.adaptive_max_pool2d(jnp.asarray(x2), (4, 5),
                                      return_mask=True)
    tout, tmask = tF.adaptive_max_pool2d(torch.tensor(x2), (4, 5),
                                         return_indices=True)
    np.testing.assert_allclose(np.asarray(out), tout.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(mask), tmask.numpy())

    pt.seed(0)
    bn = nn.BatchNorm1D(4, data_format="NCL")
    bn.train()
    x = jnp.asarray(rng.standard_normal((2, 4, 7)), jnp.float32)
    m = np.asarray(bn(x)).mean(axis=(0, 2))
    assert np.abs(m).max() < 1e-5  # per-CHANNEL normalization

    p1 = nn.Pad1D(2, mode="replicate")
    np.testing.assert_allclose(
        np.asarray(p1(jnp.asarray([[[1.0, 2.0, 3.0]]])))[0, 0],
        [1, 1, 1, 2, 3, 3, 3])
    assert nn.Pad2D(1, mode="circular")(
        jnp.ones((1, 1, 2, 2))).shape == (1, 1, 4, 4)
    with pytest.raises(ValueError, match="pad mode"):
        nn.Pad1D(1, mode="bogus")(jnp.ones((1, 1, 2)))


def test_distributed_namespace_shims():
    d = pt.distributed
    d.fleet.init(is_collective=True)
    assert d.fleet.worker_num() >= 1 and d.fleet.is_first_worker()
    m = nn.Linear(2, 2)
    assert d.fleet.distributed_model(m) is m
    opt_obj = pt.optimizer.SGD(0.1)
    assert d.fleet.distributed_optimizer(opt_obj) is opt_obj
    env = d.ParallelEnv()
    assert env.rank == 0 and env.nranks >= 1 and env.device_id >= 0
    assert d.all_to_all is d.alltoall
    # stream variants accept sync_op/use_calc_stream and delegate; on
    # the 8-device test mesh each shard holds 1 element -> sum is 8
    n = jax.device_count()
    np.testing.assert_allclose(
        d.stream.all_reduce(jnp.ones((n,)), sync_op=True),
        np.full((n,), float(n)))
    x = jnp.ones((4, 2))
    assert d.unshard_dtensor(x).shape == (4, 2)
    assert d.parallelize(m) is m


def test_incubate_segment_and_graph_ops():
    inc = pt.incubate
    np.testing.assert_allclose(
        inc.segment_sum(jnp.asarray([1.0, 2.0, 3.0, 4.0]),
                        jnp.asarray([0, 0, 1, 1])), [3.0, 7.0])
    np.testing.assert_allclose(
        inc.segment_mean(jnp.asarray([1.0, 3.0, 5.0]),
                         jnp.asarray([0, 0, 1])), [2.0, 5.0])
    np.testing.assert_allclose(
        inc.segment_max(jnp.asarray([1.0, 3.0, 5.0]),
                        jnp.asarray([0, 0, 1])), [3.0, 5.0])
    x = jnp.asarray(np.random.default_rng(0).standard_normal((5, 3)),
                    jnp.float32)
    out = inc.graph_send_recv(x, jnp.asarray([0, 1, 2]),
                              jnp.asarray([1, 1, 0]), "sum")
    np.testing.assert_allclose(np.asarray(out[1]),
                               np.asarray(x[0] + x[1]), rtol=1e-6)
    # fused masked softmax == softmax(x + mask)
    ut = inc.softmax_mask_fuse_upper_triangle(jnp.zeros((1, 1, 2, 2)))
    np.testing.assert_allclose(np.asarray(ut[0, 0]),
                               [[1.0, 0.0], [0.5, 0.5]])
    assert float(inc.identity_loss(jnp.asarray([1.0, 3.0]), "mean")) == 2.0


def test_jit_static_vision_shims():
    @pt.jit.not_to_static
    def f(x):
        return x

    assert f._paddle_tpu_not_to_static
    assert pt.jit.TranslatedLayer is not None
    with pt.static.name_scope("blk"):
        pass
    with pt.static.program_guard():
        pass
    assert pt.static.default_main_program().global_block() is not None
    prev = pt.vision.get_image_backend()
    pt.vision.set_image_backend("cv2")
    assert pt.vision.get_image_backend() == "cv2"
    pt.vision.set_image_backend(prev)
    with pytest.raises(ValueError):
        pt.vision.set_image_backend("bogus")


def test_incubate_fix_details():
    inc = pt.incubate
    # paddle's int reduction codes: 0=sum, 1=mean, 2=none
    x = jnp.asarray([1.0, 3.0])
    assert float(inc.identity_loss(x, 0)) == 4.0
    assert float(inc.identity_loss(x, 1)) == 2.0
    np.testing.assert_allclose(inc.identity_loss(x, 2), x)
    # mean pooling with 1-D x keeps rank (regression: count broadcast)
    out = inc.graph_send_recv(jnp.asarray([2.0, 4.0, 6.0]),
                              jnp.asarray([0, 1]), jnp.asarray([0, 0]),
                              "mean")
    assert out.shape == (1,)
    assert float(out[0]) == 3.0


def _spawn_child(out_dir):
    import os
    import pathlib

    rank = os.environ["PADDLE_TRAINER_ID"]
    master = os.environ.get("PADDLE_MASTER", "")
    pathlib.Path(out_dir, f"r{rank}").write_text(master)


def test_spawn_sets_rank_env(tmp_path):
    from paddle_tpu.distributed import spawn

    spawn(_spawn_child, args=(str(tmp_path),), nprocs=2)
    files = sorted(p.name for p in tmp_path.iterdir())
    assert files == ["r0", "r1"]
    masters = {p.read_text() for p in tmp_path.iterdir()}
    # one shared coordinator address, set before fork
    assert len(masters) == 1 and ":" in masters.pop()


def test_tensor_method_surface():
    """paddle.Tensor methods installed on jax.Array — additive only."""
    x = jnp.asarray([[1.0, -2.0], [3.0, 4.0]])
    np.testing.assert_allclose(x.numpy(), np.asarray(x))
    assert x.cast("float16").dtype == jnp.float16
    assert x.cast(pt.bfloat16).dtype == jnp.bfloat16
    assert x.unsqueeze(0).shape == (1, 2, 2)
    assert x.numel() == 4 and x.dim() == 2
    np.testing.assert_allclose(x.t(), np.asarray(x).T)
    np.testing.assert_allclose(x.abs(), np.abs(np.asarray(x)))
    np.testing.assert_allclose(x.scale(2.0, 1.0),
                               np.asarray(x) * 2 + 1)
    v, i = x.topk(1)
    np.testing.assert_allclose(np.asarray(v)[:, 0], [1.0, 4.0])
    np.testing.assert_allclose(
        x.masked_fill(x < 0, 0.0), [[1.0, 0.0], [3.0, 4.0]])
    assert x.expand([3, 2, 2]).shape == (3, 2, 2)
    parts = x.split(2, axis=1)
    assert len(parts) == 2 and parts[0].shape == (2, 1)
    assert bool(x.equal_all(x)) and not bool(x.equal_all(x + 1))
    np.testing.assert_allclose(x.add(x), np.asarray(x) * 2)
    np.testing.assert_allclose(x.matmul(x), np.asarray(x) @ np.asarray(x))
    assert bool(x.greater_than(jnp.zeros_like(x))[0, 0])
    assert x.detach().shape == x.shape
    assert x.cpu().shape == x.shape
    # stop_gradient: readable (paddle default True); assigning True is
    # the common migration idiom and a semantic no-op; only False (tape
    # trainability) raises with the migration hint
    assert x.stop_gradient is True
    x.stop_gradient = True  # no-op, must not raise
    assert x.stop_gradient is True
    with pytest.raises(AttributeError, match="Parameter.trainable"):
        x.stop_gradient = False
    # jax's own names were NOT overridden
    assert x.sum() == jnp.sum(x)
    assert x.reshape(4).shape == (4,)


def test_tensor_methods_under_tracing():
    """Method calls survive jit/grad: tracers resolve them through the
    aval registration (jax's own .sum mechanism)."""

    @jax.jit
    def f(x):
        return x.unsqueeze(0).abs().scale(2.0).squeeze(0) + x.detach()

    np.testing.assert_allclose(f(jnp.asarray([-1.0, 2.0])), [1.0, 6.0])
    g = jax.grad(lambda x: x.abs().sum())(jnp.asarray([-3.0, 4.0]))
    np.testing.assert_allclose(g, [-1.0, 1.0])


def test_review_fix_details():
    x = jnp.arange(10.0)
    # split with -1 = remaining
    a, b, c = x.split([2, -1, 3])
    assert (a.shape[0], b.shape[0], c.shape[0]) == (2, 5, 3)
    with pytest.raises(ValueError, match="-1"):
        x.split([2, -1, -1])
    # expand: -1 only inherits existing dims
    m = jnp.ones((2, 3))
    with pytest.raises(ValueError, match="new"):
        m.expand([-1, 2, 3])
    assert m.expand([4, -1, 3]).shape == (4, 2, 3)
    # equal_all works under jit (returns a traced scalar)
    eq = jax.jit(lambda a, b: a.equal_all(b))(m, m)
    assert bool(eq)
    # segment ops: num_segments makes them jit-able
    ids = jnp.asarray([0, 0, 1])
    f = jax.jit(lambda d: pt.incubate.segment_sum(d, ids,
                                                  num_segments=2))
    np.testing.assert_allclose(f(jnp.asarray([1.0, 2.0, 3.0])),
                               [3.0, 3.0])
    with pytest.raises(ValueError, match="num_segments"):
        jax.jit(lambda d, i: pt.incubate.segment_sum(d, i))(
            jnp.ones((3,)), ids)
    # async stream collective returns a waitable task
    pt.distributed.fleet.init(is_collective=True)
    n = jax.device_count()
    task = pt.distributed.stream.all_reduce(jnp.ones((n,)),
                                            sync_op=False)
    out = task.wait()
    np.testing.assert_allclose(out, np.full((n,), float(n)))
    # Program is a class
    prog = pt.static.default_main_program()
    assert isinstance(prog, pt.static.Program)


def test_dot_and_allclose_paddle_semantics():
    # paddle.dot: per-ROW inner product on 2-D (not matmul)
    a = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    b = jnp.asarray([[5.0, 6.0], [7.0, 8.0]])
    np.testing.assert_allclose(pt.dot(a, b), [17.0, 53.0])
    assert float(pt.dot(jnp.asarray([1.0, 2.0]),
                        jnp.asarray([3.0, 4.0]))) == 11.0
    with pytest.raises(ValueError, match="1-D/2-D"):
        pt.dot(jnp.ones((2, 2, 2)), jnp.ones((2, 2, 2)))
    # method allclose forwards tolerances
    assert bool(a.allclose(a + 1e-7, rtol=1e-3))
    assert not bool(a.allclose(a + 1.0, rtol=1e-6))


def test_model_callbacks_utils_hub(tmp_path):
    import warnings

    assert pt.Model is not None and pt.callbacks.EarlyStopping
    with pytest.raises(NotImplementedError, match="StableHLO"):
        pt.onnx.export(None, "x")

    (tmp_path / "hubconf.py").write_text(
        "def tiny(scale=1):\n    'doc'\n    return scale * 2\n")
    assert pt.hub.list(str(tmp_path)) == ["tiny"]
    assert pt.hub.load(str(tmp_path), "tiny", scale=3) == 6
    with pytest.raises(NotImplementedError, match="zero-egress"):
        pt.hub.load("github.com/x/y", "m", source="github")

    @pt.utils.deprecated(update_to="new_fn", since="2.0")
    def old_fn():
        return 42

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert old_fn() == 42
        assert any("deprecated" in str(x.message) for x in w)
    assert pt.utils.try_import("math").sqrt(4) == 2.0
    with pytest.raises(ImportError, match="custom msg"):
        pt.utils.try_import("no_such_module_xyz", "custom msg")
    g = pt.utils.unique_name
    a, b = g.generate("w"), g.generate("w")
    assert a != b
    with g.guard():
        assert g.generate("w").endswith("_0")


def test_deprecated_levels_and_hub_cache(tmp_path):
    import warnings

    @pt.utils.deprecated(level=0)
    def f0():
        return 0

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert f0() == 0
    # paddle level semantics: 0 = suppressed
    assert not w

    @pt.utils.deprecated(since="2.0")
    def f1():
        return 1

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert f1() == 1
    # default level 1 = warn
    assert any("deprecated" in str(x.message) for x in w)

    @pt.utils.deprecated(level=2, reason="gone")
    def f2():
        pass

    with pytest.raises(RuntimeError, match="gone"):
        f2()

    # hub executes hubconf once per dir; force_reload re-executes
    (tmp_path / "hubconf.py").write_text(
        "import pathlib\n"
        "_p = pathlib.Path(__file__).parent / 'count'\n"
        "_p.write_text(str(int(_p.read_text()) + 1) "
        "if _p.exists() else '1')\n"
        "def m():\n    return 1\n")
    pt.hub.list(str(tmp_path))
    pt.hub.load(str(tmp_path), "m")
    assert (tmp_path / "count").read_text() == "1"
    pt.hub.list(str(tmp_path), force_reload=True)
    assert (tmp_path / "count").read_text() == "2"


def test_functional_additions_parity():
    rng = np.random.default_rng(0)
    x1 = rng.standard_normal((3, 4)).astype("f")
    x2 = rng.standard_normal((3, 5)).astype("f")
    w = rng.standard_normal((6, 4, 5)).astype("f")
    b = rng.standard_normal((6,)).astype("f")
    np.testing.assert_allclose(
        np.asarray(F.bilinear(jnp.asarray(x1), jnp.asarray(x2),
                              jnp.asarray(w), jnp.asarray(b))),
        tF.bilinear(torch.tensor(x1), torch.tensor(x2), torch.tensor(w),
                    torch.tensor(b)).numpy(), rtol=1e-4, atol=1e-5)

    p = np.clip(rng.random((4, 3)).astype("f"), 1e-3, 1 - 1e-3)
    y = rng.integers(0, 2, (4, 3)).astype("f")
    np.testing.assert_allclose(
        float(F.binary_cross_entropy(jnp.asarray(p), jnp.asarray(y))),
        float(tF.binary_cross_entropy(torch.tensor(p),
                                      torch.tensor(y))), rtol=1e-5)

    xl = rng.standard_normal((2, 3, 9)).astype("f")
    np.testing.assert_allclose(
        np.asarray(F.max_pool1d(jnp.asarray(xl), 3, 3)),
        tF.max_pool1d(torch.tensor(xl), 3, 3).numpy(), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(F.avg_pool1d(jnp.asarray(xl), 3, 3)),
        tF.avg_pool1d(torch.tensor(xl), 3, 3).numpy(), rtol=1e-5)
    o, m = F.adaptive_max_pool1d(jnp.asarray(xl), 4, return_mask=True)
    to, tm = tF.adaptive_max_pool1d(torch.tensor(xl), 4,
                                    return_indices=True)
    np.testing.assert_allclose(np.asarray(o), to.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(m), tm.numpy())

    th = rng.standard_normal((2, 2, 3)).astype("f")
    for ac in (True, False):
        np.testing.assert_allclose(
            np.asarray(F.affine_grid(jnp.asarray(th), (2, 1, 4, 5),
                                     align_corners=ac)),
            tF.affine_grid(torch.tensor(th), (2, 1, 4, 5),
                           align_corners=ac).numpy(),
            rtol=1e-4, atol=1e-5)

    xc = rng.standard_normal((2, 6, 4, 4)).astype("f")
    np.testing.assert_allclose(
        np.asarray(F.channel_shuffle(jnp.asarray(xc), 3)),
        tF.channel_shuffle(torch.tensor(xc), 3).numpy())

    np.testing.assert_array_equal(
        np.asarray(F.sequence_mask(jnp.asarray([2, 4]), maxlen=5)),
        [[1, 1, 0, 0, 0], [1, 1, 1, 1, 0]])

    lbl = jnp.asarray(np.eye(4, dtype="f")[[0, 2]])
    np.testing.assert_allclose(
        np.asarray(F.label_smooth(lbl, epsilon=0.1)).sum(-1),
        [1.0, 1.0], rtol=1e-6)

    pt.seed(0)
    g = F.gumbel_softmax(
        jnp.asarray(rng.standard_normal((5, 8)).astype("f")), hard=True)
    assert np.allclose(np.asarray(g).sum(-1), 1.0)
    assert set(np.unique(np.asarray(g))) <= {0.0, 1.0}
    # straight-through gradients flow
    gr = jax.grad(lambda z: F.gumbel_softmax(z, hard=True).sum())(
        jnp.ones((2, 3)))
    assert gr.shape == (2, 3)

    # temporal shift: zero-padded ends, shifted channel blocks
    ts_in = jnp.asarray(np.arange(8 * 4, dtype="f").reshape(8, 4, 1, 1))
    out = F.temporal_shift(ts_in, seg_num=4, shift_ratio=0.25)
    ref5 = np.asarray(ts_in).reshape(2, 4, 4, 1, 1)
    got5 = np.asarray(out).reshape(2, 4, 4, 1, 1)
    np.testing.assert_allclose(got5[:, :-1, 0], ref5[:, 1:, 0])  # back
    np.testing.assert_allclose(got5[:, -1, 0], 0.0)
    np.testing.assert_allclose(got5[:, 1:, 1], ref5[:, :-1, 1])  # fwd
    np.testing.assert_allclose(got5[:, 0, 1], 0.0)
    np.testing.assert_allclose(got5[:, :, 2:], ref5[:, :, 2:])  # rest


def test_voc2012_and_flowers_local(tmp_path):
    """Synthetic devkit tarball: VOC2012 stores compressed bytes and
    decodes lazily; member lookup is root-prefix exact (not a scan)."""
    import io
    import tarfile

    from PIL import Image

    def _png(arr):
        b = io.BytesIO()
        Image.fromarray(arr).save(b, format="PNG")
        return b.getvalue()

    def _jpg(arr):
        b = io.BytesIO()
        Image.fromarray(arr).save(b, format="JPEG")
        return b.getvalue()

    tar_path = tmp_path / "voc.tar"
    root = "VOCdevkit/VOC2012/"
    with tarfile.open(tar_path, "w") as tf:
        def add(name, data):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))

        add(root + "ImageSets/Segmentation/train.txt", b"a\nb\n")
        rng = np.random.default_rng(0)
        for n in ("a", "b"):
            add(root + f"JPEGImages/{n}.jpg",
                _jpg(rng.integers(0, 255, (8, 8, 3)).astype("uint8")))
            add(root + f"SegmentationClass/{n}.png",
                _png(rng.integers(0, 20, (8, 8)).astype("uint8")))

    from paddle_tpu.vision.datasets import VOC2012

    ds = VOC2012(data_file=str(tar_path), mode="train")
    assert len(ds) == 2
    img, seg = ds[0]
    assert img.shape == (8, 8, 3) and seg.shape == (8, 8)
    # records hold compressed BYTES, not decoded arrays
    assert isinstance(ds._records[0][0], bytes)


def test_pairwise_distance_inf_norm():
    x = jnp.asarray([[1.0, 5.0]])
    y = jnp.zeros((1, 2))
    assert abs(float(F.pairwise_distance(x, y, p=float("inf"))[0])
               - 5.0) < 1e-4
    # sequence_mask defaults to paddle's int64 (which the framework's
    # dtype convention maps to jax's default int width)
    out = F.sequence_mask(jnp.asarray([2]))
    assert jnp.issubdtype(out.dtype, jnp.integer)
    assert out.dtype != jnp.bool_
