"""Multi-process tests: REAL ``jax.distributed`` worlds (2 processes,
Gloo CPU collectives), the reference's launcher/worker test pattern
(upstream: test/collective/*). Every barrier and the async metadata
quorum in distributed/checkpoint.py silently no-ops at
process_count()==1 — these are the only tests where they actually run.
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

# these tests need Gloo CPU collectives in the CPU backend; on the
# 0.4.x line every cross-process collective raises "Multiprocess
# computations aren't implemented on the CPU backend"
pytestmark = pytest.mark.skipif(
    jax.__version_info__ < (0, 5),
    reason="multi-process CPU (Gloo) collectives need jax >= 0.5; this "
           "jax's CPU backend rejects multiprocess computations")

HERE = os.path.dirname(__file__)
REPO = os.path.join(HERE, "..")
WORKER = os.path.join(HERE, "mp_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _base_env():
    """CPU world plumbing shared by every spawn style: force the cpu
    platform, scrub the TPU-tunnel plugin, 2 local devices/process."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2")
    return env


def _worker_env(rank, world, port):
    env = _base_env()
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_MASTER": f"127.0.0.1:{port}",
    })
    return env


def _spawn_world(mode, tmp_path, world=2, timeout=240,
                 expect_rc={0: 0, 1: 0}):
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, mode, str(tmp_path)],
            env=_worker_env(r, world, port), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(world)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == expect_rc.get(r, 0), (
            f"rank {r} rc={p.returncode}\n{out[-3000:]}")
    return outs


def test_eager_collectives_two_processes(tmp_path):
    outs = _spawn_world("collective", tmp_path)
    for r, out in enumerate(outs):
        assert f"MP_OK collective rank={r}" in out, out[-2000:]


def test_checkpoint_save_load_two_processes(tmp_path):
    """Sync save: real cross-process barriers, one-writer-per-chunk, and
    reshard-on-load of the other rank's shards."""
    outs = _spawn_world("ckpt_roundtrip", tmp_path)
    for r, out in enumerate(outs):
        assert f"MP_OK ckpt_roundtrip rank={r}" in out, out[-2000:]
    # both ranks' unique chunks landed in ONE committed directory
    ckpt_dir = tmp_path / "ckpt"
    assert (ckpt_dir / "COMMITTED").exists()


def test_async_checkpoint_kill_one_rank_mid_save(tmp_path):
    """Rank 1 dies after the tmpdir barrier but before writing its
    metadata: rank 0's quorum poll must time out without committing and
    the previous checkpoint must stay loadable."""
    outs = _spawn_world("ckpt_kill_rank", tmp_path, timeout=300)
    assert "MP_OK ckpt_kill_rank rank=0" in outs[0], outs[0][-2000:]
    assert (tmp_path / "ckpt_async" / "COMMITTED").exists()
    tmp_dir = tmp_path / "ckpt_async.tmp"
    if tmp_dir.exists():
        assert not (tmp_dir / "COMMITTED").exists()


def test_launch_cli_rendezvous(tmp_path):
    """python -m paddle_tpu.distributed.launch --nproc_per_node 2:
    workers rendezvous via the injected PADDLE_MASTER and run a real
    cross-process allreduce."""
    port = _free_port()
    env = _base_env()
    log_dir = str(tmp_path / "logs")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--log_dir", log_dir,
         os.path.abspath(WORKER), "launch_hello", str(tmp_path)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    logs = ""
    for n in range(2):
        with open(os.path.join(log_dir, f"workerlog.{n}")) as f:
            logs += f.read()
    # 4 global devices (2/process) holding rank+1 → allreduce = 1+1+2+2
    assert "MP_OK launch_hello rank=0 world=2 sum=6.0" in logs, logs[-2000:]
    assert "MP_OK launch_hello rank=1 world=2 sum=6.0" in logs, logs[-2000:]
