"""Concurrency/soak tests for the host-threaded subsystems: the async
checkpoint writer, the process DataLoader, and the serving scheduler.

Parity intent: the reference runs sanitizer CI builds and worker-kill
tests (test/collective/, DataLoader worker-exit tests); functional purity
covers device races here, so the host-side threads are what need stress
coverage (VERDICT r4 §aux: the one 'partial' row).
"""

import gc
import os
import queue
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import checkpoint as dck


# ---------------------------------------------------------------------------
# async checkpoint writer
# ---------------------------------------------------------------------------

def test_async_overlapping_saves_serialize(tmp_path):
    """Back-to-back async saves: the second must wait for the first (one
    in-flight writer), and both checkpoints must be committed + correct."""
    saver = dck.AsyncCheckpointer()
    arrays = {f"w{i}": jnp.full((64, 64), float(i)) for i in range(4)}
    paths = []
    for step in range(4):
        p = str(tmp_path / f"ck{step}")
        sd = {k: v + step for k, v in arrays.items()}
        saver.save(sd, p)
        paths.append((p, step))
    saver.wait_until_finished()
    for p, step in paths:
        assert dck.is_committed(p)
        got = dck.load_state_dict(p)
        np.testing.assert_array_equal(
            np.asarray(got["w3"]), np.full((64, 64), 3.0 + step))


def test_async_rotation_same_path(tmp_path):
    """Repeated async saves to the SAME path (checkpoint rotation): the
    final committed state is the last save, never a torn mix."""
    saver = dck.AsyncCheckpointer()
    p = str(tmp_path / "latest")
    for step in range(5):
        sd = {"w": jnp.full((32, 32), float(step)),
              "step": jnp.asarray(step)}
        saver.save(sd, p)
    saver.wait_until_finished()
    got = dck.load_state_dict(p)
    assert int(got["step"]) == 4
    np.testing.assert_array_equal(np.asarray(got["w"]),
                                  np.full((32, 32), 4.0))


def test_crash_mid_save_keeps_previous(tmp_path):
    """A save that died before the COMMITTED marker must not damage the
    previous checkpoint; recovery serves the old state."""
    p = str(tmp_path / "c")
    dck.save_state_dict({"w": jnp.zeros((8,))}, p)
    # simulate a writer that crashed mid-write: partial tmp, no marker
    os.makedirs(p + ".tmp", exist_ok=True)
    with open(os.path.join(p + ".tmp", "w.part0.npy"), "wb") as f:
        f.write(b"garbage")
    got = dck.load_state_dict(p)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.zeros((8,)))
    # a fresh save over the wreckage must succeed and win
    dck.save_state_dict({"w": jnp.ones((8,))}, p)
    got = dck.load_state_dict(p)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.ones((8,)))


def test_crash_between_commit_renames_promotes_new(tmp_path):
    """Crash between _commit's two renames (path gone, marked tmp
    present): recovery must finish the commit and serve the NEW state."""
    import shutil

    p = str(tmp_path / "c")
    dck.save_state_dict({"w": jnp.zeros((8,))}, p)
    dck.save_state_dict({"w": jnp.ones((8,))}, str(tmp_path / "v2"))
    # recreate the mid-commit wreckage: old ckpt at .old, new (marked)
    # at .tmp, nothing at path
    open(os.path.join(str(tmp_path / "v2"), "COMMITTED"), "a").close()
    os.rename(p, p + ".old")
    shutil.rmtree(p + ".tmp", ignore_errors=True)
    os.rename(str(tmp_path / "v2"), p + ".tmp")
    got = dck.load_state_dict(p)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.ones((8,)))


def test_crash_before_swap_keeps_acknowledged_state(tmp_path):
    """Crash after the marker write but BEFORE the swap (path intact):
    the unacknowledged save is dropped and the last acknowledged
    checkpoint keeps serving — never a torn state."""
    p = str(tmp_path / "c")
    dck.save_state_dict({"w": jnp.zeros((8,))}, p)
    dck.save_state_dict({"w": jnp.ones((8,))}, str(tmp_path / "v2"))
    open(os.path.join(str(tmp_path / "v2"), "COMMITTED"), "a").close()
    os.rename(str(tmp_path / "v2"), p + ".tmp")
    got = dck.load_state_dict(p)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.zeros((8,)))


def test_async_writer_error_propagates(tmp_path):
    """A failing background write surfaces on wait_until_finished (or the
    next save), not silently."""
    saver = dck.AsyncCheckpointer()
    target = tmp_path / "blocked"
    saver.save({"w": jnp.ones((4,))}, str(target))
    saver.wait_until_finished()
    # now make the path unwritable-over: a FILE where the dir must go
    bad = tmp_path / "f" / "nested"  # parent doesn't exist and can't
    with open(tmp_path / "f", "w") as f:
        f.write("x")
    with pytest.raises(Exception):
        saver.save({"w": jnp.ones((4,))}, str(bad))
        saver.wait_until_finished()


def test_async_save_under_training_mutation(tmp_path):
    """Soak: snapshot isolation — the training loop keeps mutating (and
    re-binding) arrays while the writer flushes; every committed ckpt
    must equal the state at ITS save point."""
    saver = dck.AsyncCheckpointer()
    w = jnp.zeros((128, 128))
    expect = {}
    for step in range(6):
        p = str(tmp_path / f"s{step}")
        saver.save({"w": w, "step": jnp.asarray(step)}, p)
        expect[p] = float(w[0, 0])
        w = w + 1.0  # training continues immediately
    saver.wait_until_finished()
    for p, v in expect.items():
        got = dck.load_state_dict(p)
        assert float(np.asarray(got["w"])[0, 0]) == v


# ---------------------------------------------------------------------------
# DataLoader process workers
# ---------------------------------------------------------------------------

class _CrashAt:
    """Dataset whose worker hard-exits on one index (simulates an OOM-
    killed / segfaulted worker)."""

    def __init__(self, n=64, crash_at=37):
        self.n, self.crash_at = n, crash_at

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if i == self.crash_at:
            os._exit(13)
        return np.full((4,), i, np.float32)


@pytest.mark.timeout(120)
def test_process_worker_crash_raises_not_hangs():
    """A worker killed mid-batch must surface as an exception on the
    training loop promptly — never a silent hang (reference parity:
    DataLoader worker-exit detection)."""
    from paddle_tpu import io

    dl = io.DataLoader(_CrashAt(), batch_size=8, num_workers=2,
                       use_process_workers=True, shuffle=False)
    with pytest.raises(Exception):
        for _ in dl:
            pass


@pytest.mark.timeout(120)
def test_process_loader_abandoned_mid_epoch_shuts_down():
    """Dropping the iterator mid-epoch must tear the pool down without
    leaking live worker processes."""
    import multiprocessing as mp

    from paddle_tpu import io

    class _Slow:
        def __len__(self):
            return 256

        def __getitem__(self, i):
            time.sleep(0.01)
            return np.full((4,), i, np.float32)

    dl = io.DataLoader(_Slow(), batch_size=4, num_workers=2,
                       use_process_workers=True, shuffle=False)
    it = iter(dl)
    next(it)
    next(it)
    before = {p.pid for p in mp.active_children()}
    assert before  # workers exist mid-epoch
    it.close()  # abandon the epoch
    gc.collect()
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [p for p in mp.active_children() if p.is_alive()]
        if not alive:
            break
        time.sleep(0.25)
    assert not [p for p in mp.active_children() if p.is_alive()]


@pytest.mark.timeout(120)
def test_thread_loader_epoch_soak():
    """Threaded loader: several full epochs back-to-back with shuffle —
    every element delivered exactly once per epoch, no dropped/duplicated
    futures under prefetch pressure."""
    from paddle_tpu import io

    class _Ds:
        def __len__(self):
            return 101  # prime: exercises ragged last batch

        def __getitem__(self, i):
            return np.asarray([i], np.int64)

    dl = io.DataLoader(_Ds(), batch_size=7, num_workers=4, shuffle=True,
                       drop_last=False)
    for _ in range(3):
        seen = sorted(int(x) for b in dl for x in np.asarray(b).ravel())
        assert seen == list(range(101))


# ---------------------------------------------------------------------------
# serving scheduler
# ---------------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_serving_scheduler_threaded_arrivals():
    """Requests land from a producer thread while the engine loop runs:
    every request must finish with the requested token count — no lost,
    duplicated, or starved slots (soak for the admission bookkeeping)."""
    from paddle_tpu.inference.serving import (
        ContinuousBatchingEngine,
        EngineConfig,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=128, use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    eng = ContinuousBatchingEngine(model, EngineConfig(
        max_slots=3, max_len=96, seq_buckets=(32,),
        cache_dtype=jnp.float32))

    n_requests, new_tokens = 14, 6
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (int(rng.integers(4, 28)),))
               for _ in range(n_requests)]
    ids = []
    errs = []

    def producer():
        try:
            for p in prompts:
                ids.append(eng.add_request(p, new_tokens))
                time.sleep(float(rng.uniform(0.0, 0.02)))
        except BaseException as e:  # surfaces in the main thread assert
            errs.append(e)

    t = threading.Thread(target=producer)
    t.start()
    deadline = time.time() + 240
    while time.time() < deadline:
        busy = eng.step_chunk(4)
        if not t.is_alive() and not busy and not eng.active.any() \
                and len(eng._finished) >= n_requests:
            break
    t.join(timeout=10)
    assert not errs, errs
    assert sorted(eng._finished) == sorted(ids)
    for rid in ids:
        out = eng._finished[rid].output
        assert len(out) == new_tokens, (rid, len(out))


@pytest.mark.timeout(300)
def test_cancel_soak_no_leaks():
    """Soak for ``engine.cancel``: requests land from a producer thread
    while the scheduler loop cancels every third one at staggered
    points (queued, mid-prefill-wave boundaries, mid-decode). After the
    storm: every rid is accounted for, survivors got their full token
    count, and the paged pool + prefix-cache refcounts recover to the
    initial state — the leak-free primitive the SLO-aware scheduler's
    timeout path builds on (ROADMAP item 5)."""
    from paddle_tpu.inference.serving import (
        ContinuousBatchingEngine,
        EngineConfig,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=128, use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    eng = ContinuousBatchingEngine(model, EngineConfig(
        max_slots=3, max_len=96, seq_buckets=(32,),
        cache_dtype=jnp.float32, paged=True, page_size=8))
    free0 = eng.pool.free_pages

    n_requests, new_tokens = 18, 6
    rng = np.random.default_rng(3)
    shared = rng.integers(0, cfg.vocab_size, (16,))  # 2 prefix blocks
    prompts = [np.concatenate(
        [shared, rng.integers(0, cfg.vocab_size,
                              (int(rng.integers(2, 10)),))])
        for _ in range(n_requests)]
    ids = []
    errs = []
    prng = np.random.default_rng(7)

    def producer():
        try:
            for p in prompts:
                ids.append(eng.add_request(p, new_tokens))
                time.sleep(float(prng.uniform(0.0, 0.01)))
        except BaseException as e:
            errs.append(e)

    t = threading.Thread(target=producer)
    t.start()
    cancelled = set()
    deadline = time.time() + 240
    while time.time() < deadline:
        busy = eng.step_chunk(4)
        # cancel every 3rd rid exactly once, whatever state it is in
        for rid in list(ids):
            if rid % 3 == 0 and rid not in cancelled \
                    and eng.cancel(rid):
                cancelled.add(rid)
        if not t.is_alive() and not busy and not eng.active.any() \
                and len(eng._finished) >= n_requests:
            break
    t.join(timeout=10)
    assert not errs, errs
    assert sorted(eng._finished) == sorted(ids)
    for rid in ids:
        req = eng._finished[rid]
        if rid in cancelled:
            assert req.cancelled and req.finish_reason == "cancel"
        else:
            assert len(req.output) == new_tokens, (rid, len(req.output))
    assert cancelled  # the storm actually cancelled something
    # leak check: beyond store-retained prefix pages (all evictable),
    # the pool must fully recover — no page stranded by a cancel
    assert not eng.active.any()
    assert sorted(eng._free_heap) == [0, 1, 2]
    eng._evict_pages(10 ** 9)
    assert eng.pool.free_pages == free0
    assert not eng.pool.ref
    # and the engine still serves after the churn
    out = eng.run([prompts[0]], max_new_tokens=4)
    assert len(out[0].output) == 4


@pytest.mark.timeout(300)
@pytest.mark.chaos
def test_chaos_storm_no_leaks():
    """Fault-injection storm over the paged engine: step faults + NaN
    storms + latency spikes + simulated pool exhaustion from a seeded
    injector, INTERLEAVED with producer-thread arrivals, a cancel
    storm and per-request deadlines. After the storm: every rid is
    accounted for exactly once, survivors carry their exact token
    counts, zero slots / KV pages / prefix refs leak, and the engine
    still serves — the chaos coverage ROADMAP item 5 queued."""
    from paddle_tpu.inference.resilience import FaultInjector
    from paddle_tpu.inference.serving import (
        ContinuousBatchingEngine,
        EngineConfig,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=128, use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    inj = FaultInjector(
        "step:0.08,nan:0.04,latency:0.25,pool:0.05,seed:13",
        latency_ms=2.0)
    eng = ContinuousBatchingEngine(model, EngineConfig(
        max_slots=3, max_len=96, seq_buckets=(32,),
        cache_dtype=jnp.float32, paged=True, page_size=8),
        fault_injector=inj)
    free0 = eng.pool.free_pages

    n_requests, new_tokens = 18, 6
    rng = np.random.default_rng(3)
    shared = rng.integers(0, cfg.vocab_size, (16,))  # 2 prefix blocks
    prompts = [np.concatenate(
        [shared, rng.integers(0, cfg.vocab_size,
                              (int(rng.integers(2, 10)),))])
        for _ in range(n_requests)]
    ids = []
    errs = []
    prng = np.random.default_rng(7)

    def producer():
        try:
            for i, p in enumerate(prompts):
                # every 5th rides a deadline it may or may not make
                kw = {"deadline_ms": 400.0} if i % 5 == 4 else {}
                ids.append(eng.add_request(p, new_tokens, **kw))
                time.sleep(float(prng.uniform(0.0, 0.01)))
        except BaseException as e:
            errs.append(e)

    t = threading.Thread(target=producer)
    t.start()
    cancelled = set()
    deadline = time.time() + 240
    while time.time() < deadline:
        busy = eng.step_chunk(4)
        # cancel every 4th rid exactly once, whatever state it is in
        for rid in list(ids):
            if rid % 4 == 0 and rid not in cancelled \
                    and eng.cancel(rid):
                cancelled.add(rid)
        if not t.is_alive() and not busy and not eng.active.any() \
                and len(eng._finished) >= n_requests:
            break
    t.join(timeout=10)
    assert not errs, errs
    assert sorted(eng._finished) == sorted(ids)
    rs = eng.resilience_stats
    assert rs["recoveries"] > 0, "storm fired no faults — vacuous"
    assert inj.fires["pool"] > 0 and inj.fires["latency"] > 0
    for rid in ids:
        req = eng._finished[rid]
        if rid in cancelled:
            assert req.cancelled and req.finish_reason == "cancel"
        elif req.finish_reason in ("timeout", "failed"):
            # deadline victims / retry-exhausted: released cleanly,
            # partial output only
            assert len(req.output) <= new_tokens
        else:
            # survivors: EXACT token count despite replays
            assert req.finish_reason == "max_new_tokens"
            assert len(req.output) == new_tokens, (rid, len(req.output))
    assert cancelled
    # leak check: beyond store-retained prefix pages (all evictable),
    # the pool must fully recover — no page stranded by any of the
    # cancel/timeout/quarantine paths
    assert not eng.active.any()
    assert sorted(eng._free_heap) == [0, 1, 2]
    eng._evict_pages(10 ** 9)
    assert eng.pool.free_pages == free0
    assert not eng.pool.ref
    # and the engine still serves after the storm (injector off)
    eng._injector = None
    out = eng.run([prompts[0]], max_new_tokens=4)
    assert len(out[0].output) == 4


# ---------------------------------------------------------------------------
# nested-checkpoint structure edge cases (review findings r5)
# ---------------------------------------------------------------------------

def test_nested_roundtrip_preserves_empty_subtrees(tmp_path):
    """SGD slot dicts and an fp32 model's master dict are EMPTY dicts —
    the nested flatten must round-trip them, or restoring a
    TrainStep.state_dict() fails on pytree-structure mismatch."""
    sd = {
        "params": {"w": jnp.ones((4,))},
        "opt_state": {
            "step": jnp.asarray(3),
            "slots": {"w": {}},
            "master": {},
        },
    }
    p = str(tmp_path / "c")
    dck.save_state_dict(sd, p)
    got = dck.load_state_dict(p)
    assert got["opt_state"]["slots"] == {"w": {}}
    assert got["opt_state"]["master"] == {}
    np.testing.assert_array_equal(np.asarray(got["params"]["w"]),
                                  np.ones((4,)))
