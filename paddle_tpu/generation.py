"""Text-generation utilities (parity: PaddleNLP `GenerationMixin` —
paddlenlp/generation/utils.py: decode_strategy greedy_search / sampling /
beam_search with top_k, top_p, temperature, repetition_penalty).

TPU-native design: every logits processor is a pure [batch, vocab] →
[batch, vocab] jnp function (jit/vmap-friendly, no Python branching on
data); sampling uses explicit jax PRNG keys; beam search keeps the KV
cache batch-major ([batch·num_beams, ...]) so a beam reorder is one
``jnp.take`` over the cache pytree — the TPU analog of the reference's
`cache.index_select(beam_idx)`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# logits processors
# ---------------------------------------------------------------------------
def apply_temperature(logits, temperature: float):
    if temperature == 1.0:
        return logits
    return logits / jnp.maximum(temperature, 1e-6)


def top_k_filter(logits, k: int):
    """Keep the k highest logits per row; the rest → -inf."""
    if k <= 0:
        return logits
    k = min(k, logits.shape[-1])  # reference clamps (TopKProcess)
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def top_p_filter(logits, p: float):
    """Nucleus filter: keep the smallest prefix of the sorted
    distribution with cumulative probability ≥ p (the top token always
    survives)."""
    if p >= 1.0:
        return logits
    sort_idx = jnp.argsort(-logits, axis=-1)
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # drop tokens where the cumulative mass BEFORE them already ≥ p
    drop_sorted = (cum - probs) >= p
    drop = jnp.zeros_like(drop_sorted).at[
        jnp.arange(logits.shape[0])[:, None], sort_idx
    ].set(drop_sorted)
    return jnp.where(drop, NEG_INF, logits)


def repetition_penalty_(logits, generated_ids, penalty: float,
                        mask=None):
    """CTRL-style penalty on already-generated tokens (paddle semantics:
    positive logits divided by, negative multiplied by ``penalty``).
    ``generated_ids`` [batch, n]; ``mask`` [batch, n] marks valid ids."""
    if penalty == 1.0:
        return logits
    b, v = logits.shape
    seen = jnp.zeros((b, v), bool)
    valid = jnp.ones(generated_ids.shape, bool) if mask is None else \
        mask.astype(bool)
    seen = seen.at[
        jnp.arange(b)[:, None], generated_ids
    ].max(valid)
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def process_logits(logits, temperature=1.0, top_k=0, top_p=1.0,
                   generated_ids=None, repetition_penalty=1.0,
                   generated_mask=None, min_length_active=False,
                   eos_token_id=None):
    """Composition in the reference's order: repetition penalty →
    temperature → top-k → top-p (+ optional eos ban for min_length)."""
    if generated_ids is not None and repetition_penalty != 1.0:
        logits = repetition_penalty_(logits, generated_ids,
                                     repetition_penalty, generated_mask)
    logits = apply_temperature(logits, temperature)
    if min_length_active and eos_token_id is not None:
        logits = logits.at[:, eos_token_id].set(NEG_INF)
    logits = top_k_filter(logits, top_k)
    logits = top_p_filter(logits, top_p)
    return logits


def process_logits_batch(logits, temperature, top_k, top_p):
    """Vectorized per-ROW processor stack (temperature → top-k → top-p)
    for the serving engine's per-request sampling params: every param is
    a ``[batch]`` array traced into the compiled decode/prefill/verify
    programs, so one program serves any mix of per-slot settings.
    Per-row disables mirror the scalar stack: ``top_k <= 0`` and
    ``top_p >= 1`` are no-ops for that row. Two deliberate deviations
    from the scalar functions (which take static Python ints): top-k
    cuts by sorted RANK, so ties at the k-th logit keep exactly k
    entries rather than all tied ones, and the top-1 token always
    survives both filters (the scalar top-p assumes p > 0; the vector
    form must not emit an all -inf row for a degenerate per-slot p)."""
    logits = logits / jnp.maximum(temperature, 1e-6)[:, None]
    b, v = logits.shape
    sort_idx = jnp.argsort(-logits, axis=-1)
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    rank = jnp.arange(v)[None, :]
    drop_k = (top_k[:, None] > 0) & (rank >= top_k[:, None])
    # scalar composition order: top-p's nucleus mass is computed over
    # the TOP-K SURVIVORS' renormalized distribution, not the full one
    probs = jax.nn.softmax(
        jnp.where(drop_k, NEG_INF, sorted_logits), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    drop_p = (cum - probs) >= top_p[:, None]
    drop_sorted = (drop_k | drop_p) & (rank > 0)
    drop = jnp.zeros_like(drop_sorted).at[
        jnp.arange(b)[:, None], sort_idx
    ].set(drop_sorted)
    return jnp.where(drop, NEG_INF, logits)


def sample_token(logits, rng_key, temperature=1.0, top_k=0, top_p=1.0,
                 **kw):
    """One sampled token per row after the processor stack."""
    logits = process_logits(logits, temperature, top_k, top_p, **kw)
    return jax.random.categorical(rng_key, logits, axis=-1)


# ---------------------------------------------------------------------------
# beam search
# ---------------------------------------------------------------------------
class BeamState:
    """Flat [batch·num_beams]-major beam bookkeeping."""

    def __init__(self, batch, num_beams, max_len, dtype=jnp.int32):
        self.batch = batch
        self.num_beams = num_beams
        # log-prob scores: beam 0 starts at 0, others -inf (standard
        # first-step degeneracy fix)
        self.scores = jnp.where(
            jnp.arange(num_beams)[None, :] == 0, 0.0, NEG_INF
        ) * jnp.ones((batch, 1))
        self.tokens = jnp.zeros((batch, num_beams, max_len), dtype)
        self.lengths = jnp.zeros((batch, num_beams), jnp.int32)
        self.finished = jnp.zeros((batch, num_beams), bool)


def beam_step(state: BeamState, logprobs, t: int,
              eos_token_id: Optional[int] = None):
    """One beam-search step. ``logprobs``: [batch·num_beams, vocab]
    log-softmaxed model output for the beams' last tokens. Returns
    (new_state, beam_idx [batch, num_beams] reorder indices into the
    flat batch·beams axis, next_tokens [batch, num_beams])."""
    b, nb = state.batch, state.num_beams
    v = logprobs.shape[-1]
    lp = logprobs.reshape(b, nb, v)
    if eos_token_id is not None:
        # finished beams may only extend with eos at no cost, so they
        # keep competing under their final score
        frozen = jnp.full((v,), NEG_INF).at[eos_token_id].set(0.0)
        lp = jnp.where(state.finished[..., None], frozen, lp)
    cand = state.scores[..., None] + lp               # [b, nb, v]
    flat = cand.reshape(b, nb * v)
    top_scores, top_idx = jax.lax.top_k(flat, nb)     # [b, nb]
    src_beam = top_idx // v
    next_tok = (top_idx % v).astype(state.tokens.dtype)

    gather = lambda x: jnp.take_along_axis(  # noqa: E731
        x, src_beam.reshape(x.shape[0], nb, *([1] * (x.ndim - 2))),
        axis=1)
    tokens = gather(state.tokens)
    tokens = tokens.at[:, :, t].set(next_tok)
    finished = jnp.take_along_axis(state.finished, src_beam, axis=1)
    lengths = jnp.take_along_axis(state.lengths, src_beam, axis=1)
    lengths = jnp.where(finished, lengths, lengths + 1)
    if eos_token_id is not None:
        finished = finished | (next_tok == eos_token_id)

    new = BeamState.__new__(BeamState)
    new.batch, new.num_beams = b, nb
    new.scores = top_scores
    new.tokens = tokens
    new.lengths = lengths
    new.finished = finished
    # flat reorder indices for the KV cache: batch-major
    beam_idx = (jnp.arange(b)[:, None] * nb + src_beam).reshape(-1)
    return new, beam_idx, next_tok


def beam_finalize(state: BeamState, length_penalty: float = 0.0):
    """Pick each batch row's best beam under the GNMT length penalty
    ((5+len)/6)**alpha (the reference's default scorer)."""
    lens = jnp.maximum(state.lengths, 1).astype(jnp.float32)
    denom = jnp.power((5.0 + lens) / 6.0, length_penalty)
    final = state.scores / denom
    best = jnp.argmax(final, axis=1)                  # [batch]
    tokens = jnp.take_along_axis(
        state.tokens, best[:, None, None], axis=1)[:, 0]
    return tokens, jnp.take_along_axis(final, best[:, None], 1)[:, 0]


def reorder_cache(caches, beam_idx):
    """Gather every cache leaf along its batch (leading) axis — the
    reference's beam cache index_select."""
    return jax.tree_util.tree_map(
        lambda x: jnp.take(x, beam_idx, axis=0), caches)
