"""paddle error taxonomy + enforce helpers.

Parity: ``paddle/common/errors.h`` + the PADDLE_ENFORCE_* macro family
(paddle/common/enforce.h) — typed error categories so callers can catch
classes of failure, and enforce helpers that produce uniform, actionable
messages at user-facing raise sites.

TPU-native design: each category multiple-inherits the closest Python
builtin (InvalidArgumentError is-a ValueError, UnimplementedError is-a
NotImplementedError, ...), so adopting the taxonomy never breaks callers
already catching builtins — the reference's C++ error-code enum becomes
an exception hierarchy idiomatic to a Python-first framework.
"""

from __future__ import annotations

__all__ = [
    "Error", "InvalidArgumentError", "NotFoundError", "OutOfRangeError",
    "AlreadyExistsError", "ResourceExhaustedError",
    "PreconditionNotMetError", "PermissionDeniedError",
    "ExecutionTimeoutError", "UnimplementedError", "UnavailableError",
    "FatalError", "ExternalError",
    "enforce", "enforce_eq", "enforce_gt", "enforce_ge", "enforce_in",
    "enforce_shape_match",
]


class Error(Exception):
    """Base of the paddle error taxonomy (errors.h `ErrorType`)."""

    code = "UNKNOWN"


class InvalidArgumentError(Error, ValueError):
    code = "INVALID_ARGUMENT"


class NotFoundError(Error, FileNotFoundError):
    code = "NOT_FOUND"


class OutOfRangeError(Error, IndexError):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(Error):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(Error, MemoryError):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(Error, RuntimeError):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(Error, PermissionError):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(Error, TimeoutError):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(Error, NotImplementedError):
    code = "UNIMPLEMENTED"


class UnavailableError(Error, RuntimeError):
    code = "UNAVAILABLE"


class FatalError(Error, RuntimeError):
    code = "FATAL"


class ExternalError(Error, RuntimeError):
    code = "EXTERNAL"


def _fmt(msg, cls):
    return f"({cls.code}) {msg}"


def enforce(cond, msg, error_cls=InvalidArgumentError):
    """PADDLE_ENFORCE: raise ``error_cls`` with a coded message unless
    ``cond``. Use only on host-side (non-traced) conditions — inside jit
    use ``checkify``/static checks instead."""
    if not cond:
        raise error_cls(_fmt(msg, error_cls))


def enforce_eq(a, b, what="value", error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(_fmt(
            f"{what} mismatch: expected {b!r}, got {a!r}", error_cls))


def enforce_gt(a, b, what="value", error_cls=InvalidArgumentError):
    if not a > b:
        raise error_cls(_fmt(
            f"{what} must be > {b!r}, got {a!r}", error_cls))


def enforce_ge(a, b, what="value", error_cls=InvalidArgumentError):
    if not a >= b:
        raise error_cls(_fmt(
            f"{what} must be >= {b!r}, got {a!r}", error_cls))


def enforce_in(value, allowed, what="value",
               error_cls=InvalidArgumentError):
    if value not in allowed:
        raise error_cls(_fmt(
            f"{what} must be one of {list(allowed)!r}, got {value!r}",
            error_cls))


def enforce_shape_match(shape, expected, what="tensor",
                        error_cls=InvalidArgumentError):
    """Compare shapes; ``None`` entries in ``expected`` are wildcards."""
    shape, expected = tuple(shape), tuple(expected)
    ok = len(shape) == len(expected) and all(
        e is None or s == e for s, e in zip(shape, expected))
    if not ok:
        raise error_cls(_fmt(
            f"{what} shape mismatch: expected {expected}, got {shape}",
            error_cls))
